"""Golden verdict parity against the reference's published Table V.

GC-4/Age is the reference's fully-determined row: 100% coverage, 201
partitions, 2 SAT / 199 UNSAT / 0 UNKNOWN (BASELINE.md, Appendix Table V).
The full sweep reproduces those counts exactly — partitioning, pruning,
certificates and attacks included — which pins the whole pipeline against
the published artifact (SURVEY.md §4's "golden verdict tests").
"""
import pytest

from fairify_tpu.verify import presets, sweep


def test_gc4_age_matches_table_v(tmp_path, reference_assets_available):
    if not reference_assets_available:
        pytest.skip("reference assets not mounted")
    from fairify_tpu.models import zoo

    net = zoo.load("german", "GC-4")
    # Keep the preset's generous 100 s soft timeout: the assertion includes
    # unknown == 0, which must not hinge on a loaded CI machine's wall clock.
    cfg = presets.get("GC").with_(result_dir=str(tmp_path))
    report = sweep.verify_model(net, cfg, model_name="GC-4", resume=False)
    assert report.partitions_total == 201
    assert report.counts == {"sat": 2, "unsat": 199, "unknown": 0}
    # Every SAT partition carries an exactly-validated counterexample pair.
    ces = [o for o in report.outcomes if o.verdict == "sat"]
    assert all(o.counterexample is not None and o.v_accurate for o in ces)

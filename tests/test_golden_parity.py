"""Golden verdict parity against the reference's published Table V.

GC-4/Age is the reference's fully-determined row: 100% coverage, 201
partitions, 2 SAT / 199 UNSAT / 0 UNKNOWN (BASELINE.md, Appendix Table V).
The full sweep reproduces those counts exactly — partitioning, pruning,
certificates and attacks included — which pins the whole pipeline against
the published artifact (SURVEY.md §4's "golden verdict tests").
"""
import pytest

from fairify_tpu.verify import presets, sweep


@pytest.mark.slow
def test_bm6_age_matches_table_v(tmp_path, reference_assets_available):
    """BM-6/Age — the reference's richest 100%-coverage row (510 partitions,
    156 SAT / 354 UNSAT / 0 UNKNOWN, BASELINE.md Table V).  Slow-marked:
    the bank grid is 2.5× the german one (VERDICT r4 weak #6 asked for
    exactly this pin so a regression cannot hide behind a stale PARITY
    render)."""
    if not reference_assets_available:
        pytest.skip("reference assets not mounted")
    from fairify_tpu.models import zoo

    net = zoo.load("bank", "BM-6")
    cfg = presets.get("BM").with_(result_dir=str(tmp_path))
    report = sweep.verify_model(net, cfg, model_name="BM-6", resume=False)
    assert report.partitions_total == 510
    assert report.counts == {"sat": 156, "unsat": 354, "unknown": 0}
    ces = [o for o in report.outcomes if o.verdict == "sat"]
    assert all(o.counterexample is not None and o.v_accurate for o in ces)


@pytest.mark.slow
def test_gc5_age_improves_reference_unknowns(tmp_path,
                                             reference_assets_available):
    """GC-5/Age — a row the reference could NOT determine (13 attempted,
    0 SAT / 4 UNSAT / 9 UNKNOWN in its 30-minute budget) that this engine
    closes completely: 201 partitions, 1 SAT / 200 UNSAT / 0 UNKNOWN
    (PARITY.md 'improved' class, reproduced since round 3).  Pinning it
    guards the deep-certificate path (sign-BaB + LP + lattice), not just
    the stage-0 fast path the exact-parity rows exercise."""
    if not reference_assets_available:
        pytest.skip("reference assets not mounted")
    from fairify_tpu.models import zoo

    net = zoo.load("german", "GC-5")
    cfg = presets.get("GC").with_(result_dir=str(tmp_path))
    report = sweep.verify_model(net, cfg, model_name="GC-5", resume=False)
    assert report.partitions_total == 201
    assert report.counts == {"sat": 1, "unsat": 200, "unknown": 0}
    ces = [o for o in report.outcomes if o.verdict == "sat"]
    assert all(o.counterexample is not None and o.v_accurate for o in ces)


def test_gc4_age_matches_table_v(tmp_path, reference_assets_available):
    if not reference_assets_available:
        pytest.skip("reference assets not mounted")
    from fairify_tpu.models import zoo

    net = zoo.load("german", "GC-4")
    # Keep the preset's generous 100 s soft timeout: the assertion includes
    # unknown == 0, which must not hinge on a loaded CI machine's wall clock.
    cfg = presets.get("GC").with_(result_dir=str(tmp_path))
    report = sweep.verify_model(net, cfg, model_name="GC-4", resume=False)
    assert report.partitions_total == 201
    assert report.counts == {"sat": 2, "unsat": 199, "unknown": 0}
    # Every SAT partition carries an exactly-validated counterexample pair.
    ces = [o for o in report.outcomes if o.verdict == "sat"]
    assert all(o.counterexample is not None and o.v_accurate for o in ces)

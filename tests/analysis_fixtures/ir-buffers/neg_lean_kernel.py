"""NEGATIVE: every argument consumed, outputs computed, intermediates
proportional to the interface — nothing for the buffer audit."""
import numpy as np


def make():
    from fairify_tpu.analysis.ir import KernelIR
    from fairify_tpu.utils.num import matmul

    def lean_kernel(w, x):
        h = matmul(x, w)
        return h.max(axis=-1), h.min(axis=-1)

    return KernelIR.from_fn(
        lean_kernel,
        (np.ones((8, 8), np.float32), np.ones((4, 8), np.float32)))

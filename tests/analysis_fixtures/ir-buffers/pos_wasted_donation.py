"""POSITIVE: a donated (16, 16) buffer no output can absorb — XLA aliases
donated inputs only into shape/dtype-matching outputs, so the buffer is
lost to the caller AND stays live in the executable."""
import numpy as np


def make():
    from fairify_tpu.analysis.ir import KernelIR

    def shrinking_kernel(buf, x):
        return (buf * x).sum(axis=0) + x  # outputs (16,), never (16, 16)

    return KernelIR.from_fn(
        shrinking_kernel,
        (np.ones((16, 16), np.float32), np.ones(16, np.float32)),
        donate_argnums=(0,))

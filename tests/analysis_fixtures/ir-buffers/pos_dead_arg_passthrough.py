"""POSITIVE: a dead (32, 32) argument uploaded per launch for nothing,
and an input returned verbatim — both flagged by the buffer audit."""
import numpy as np


def make():
    from fairify_tpu.analysis.ir import KernelIR

    def wasteful_kernel(x, stale_cache):
        return x + 1.0, x  # second output is the input, verbatim

    return KernelIR.from_fn(
        wasteful_kernel,
        (np.ones(8, np.float32), np.ones((32, 32), np.float32)))

"""NEGATIVE: in-place-style update — the donated buffer has a
shape/dtype-matching output to alias into; nothing else to flag."""
import numpy as np


def make():
    from fairify_tpu.analysis.ir import KernelIR

    def accumulate_kernel(buf, delta):
        return buf + delta

    return KernelIR.from_fn(
        accumulate_kernel,
        (np.ones((16, 16), np.float32), np.ones((16, 16), np.float32)),
        donate_argnums=(0,))

"""POSITIVE: two production call sites pass the same scalar as a Python
float and a numpy scalar — weak vs strong typing means two executables
for one kernel, predicted from the ground-truth cache key."""
import numpy as np


def make():
    from fairify_tpu.analysis.avals import KernelSpec, Variant
    from fairify_tpu.analysis.ir import KernelIR

    def scale_kernel(x, s):
        return x * s

    spec = KernelSpec(
        "fixture.scale_kernel", lambda w: ((), {}),
        variants=(Variant(
            "second call site passes np.float32",
            lambda w: ((np.ones(8, np.float32), np.float32(2.0)), {}),
            same_exec=True),))
    return KernelIR.from_fn(scale_kernel, (np.ones(8, np.float32), 2.0),
                            spec=spec)

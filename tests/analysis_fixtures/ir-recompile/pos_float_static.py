"""POSITIVE: a float-valued static argument — every distinct value is a
fresh trace+compile; thresholds must be traced scalars or quantized
statics."""
import numpy as np


def make():
    from fairify_tpu.analysis.ir import KernelIR

    def threshold_kernel(x, cut: float):
        return (x > cut).sum()

    return KernelIR.from_fn(threshold_kernel,
                            (np.ones((8, 8), np.float32), 0.75),
                            static_argnames=("cut",))

"""NEGATIVE: a later chunk of the same sweep — different values, same
shapes/dtypes/statics — keys to the same executable, matching its
declared signature budget of 1."""
import numpy as np


def make():
    from fairify_tpu.analysis.avals import KernelSpec, Variant
    from fairify_tpu.analysis.ir import KernelIR

    def window_kernel(x, k: int):
        return x[:, :k].sum(axis=1)

    spec = KernelSpec(
        "fixture.window_kernel", lambda w: ((), {}),
        variants=(Variant(
            "later chunk, same shapes",
            lambda w: ((np.full((4, 8), 7.0, np.float32),), {"k": 4}),
            same_exec=True),),
        expected_signatures=1)
    return KernelIR.from_fn(window_kernel, (np.zeros((4, 8), np.float32),),
                            kwargs={"k": 4}, static_argnames=("k",),
                            spec=spec)

"""NEGATIVE: sound interval arithmetic — sign-split affine image through
the pinned-precision matmul, outward-widened; every primitive is inside
the sound-ops allowlist."""
import numpy as np


def make():
    import jax.numpy as jnp

    from fairify_tpu.analysis.avals import KernelSpec
    from fairify_tpu.analysis.ir import KernelIR
    from fairify_tpu.ops.interval import SOUND_SLACK_ABS, SOUND_SLACK_REL
    from fairify_tpu.utils.num import matmul

    def sound_bounds(w, b, lo, hi):
        wp = jnp.maximum(w, 0.0)
        wn = jnp.minimum(w, 0.0)
        zlo = matmul(lo, wp) + matmul(hi, wn) + b
        zhi = matmul(hi, wp) + matmul(lo, wn) + b
        slack = SOUND_SLACK_REL * jnp.maximum(jnp.abs(zlo),
                                              jnp.abs(zhi)) + SOUND_SLACK_ABS
        return zlo - slack, zhi + slack

    spec = KernelSpec("fixture.sound_bounds", lambda w: ((), {}),
                      sound=True)
    args = (np.ones((8, 8), np.float32), np.zeros(8, np.float32),
            np.zeros((4, 8), np.float32), np.ones((4, 8), np.float32))
    return KernelIR.from_fn(sound_bounds, args, spec=spec)

"""POSITIVE: a 'certify-path' kernel with all three unsound patterns —
default-precision contraction (bf16-rewritable on the MXU), a float
downcast inside the bound computation, and a transcendental outside the
sound-ops allowlist."""
import numpy as np


def make():
    import jax.numpy as jnp

    from fairify_tpu.analysis.avals import KernelSpec
    from fairify_tpu.analysis.ir import KernelIR

    def sloppy_bounds(w, lo, hi):
        mid = 0.5 * (lo + hi)
        y = jnp.matmul(mid, w)  # default precision: NOT utils.num.matmul
        soft = jnp.exp(y)  # transcendental in a bound computation
        return soft.astype(jnp.bfloat16)  # mantissa loss on the verdict

    spec = KernelSpec("fixture.sloppy_bounds", lambda w: ((), {}),
                      sound=True)
    args = (np.ones((8, 8), np.float32), np.zeros((4, 8), np.float32),
            np.ones((4, 8), np.float32))
    return KernelIR.from_fn(sloppy_bounds, args, spec=spec)

"""POSITIVE: jax.debug.print inside a hot kernel lowers to debug_callback
— a device->host round trip per executed print."""
import numpy as np


def make():
    import jax

    from fairify_tpu.analysis.ir import KernelIR

    def noisy_kernel(x):
        jax.debug.print("sum={s}", s=x.sum())
        return x * 2.0

    return KernelIR.from_fn(noisy_kernel, (np.ones((8, 8), np.float32),))

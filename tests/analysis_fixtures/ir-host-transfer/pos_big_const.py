"""POSITIVE: a closed-over 256 KiB host array becomes an executable
constant, re-uploaded per compile instead of managed as a device buffer."""
import numpy as np

_BIG = np.ones((256, 256), np.float32)  # 256 KiB captured constant


def make():
    import jax.numpy as jnp

    from fairify_tpu.analysis.ir import KernelIR

    big = jnp.asarray(_BIG)

    def capturing_kernel(x):
        return x @ big

    return KernelIR.from_fn(capturing_kernel,
                            (np.ones((4, 256), np.float32),))

"""NEGATIVE: pure device math — no callbacks, no captured constants."""
import numpy as np


def make():
    from fairify_tpu.analysis.ir import KernelIR
    from fairify_tpu.utils.num import matmul

    def clean_kernel(w, x):
        return matmul(x, w).sum(axis=-1)

    return KernelIR.from_fn(
        clean_kernel,
        (np.ones((8, 8), np.float32), np.ones((4, 8), np.float32)))

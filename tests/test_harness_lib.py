"""Shared harness scaffolding (scripts/_sweeplib): ledger resume + sorting."""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import _sweeplib  # noqa: E402


def test_done_set_includes_skipped_records(tmp_path):
    path = str(tmp_path / "results.jsonl")
    with open(path, "w") as fp:
        fp.write(json.dumps({"run_id": "r", "model": "CP-2", "sat": 1}) + "\n")
        fp.write(json.dumps({"run_id": "r", "model": "CP-1",
                             "skipped": "input-width mismatch with domain"}) + "\n")
    done = _sweeplib.done_set(path)
    # both verified and skipped models count as done → resume converges;
    # keys carry the binding config (pre-round-2 rows get a legacy sentinel)
    assert ("r", "CP-2", ("legacy", None, None)) in done
    assert ("r", "CP-1", "skipped") in done
    assert _sweeplib.done_set(str(tmp_path / "missing.jsonl")) == set()


def test_model_natkey_orders_families_and_odd_names():
    names = ["CP-10", "CP-2", "aCP-1-Old", "CP-1"]
    ordered = sorted(names, key=_sweeplib.model_natkey)
    assert ordered.index("CP-1") < ordered.index("CP-2") < ordered.index("CP-10")
    assert "aCP-1-Old" in ordered  # non-standard name sorts without crashing


def test_merge_span_ledgers_decided_wins(tmp_path):
    """r4 review: overlapping span ledgers from crashed runs must merge
    decided-wins — a later file's budget-cut 'unknown' can never demote a
    pid another file decided, regardless of file order."""
    from _sweeplib import merge_span_ledgers
    from fairify_tpu.verify import presets

    cfg = presets.get("GC").with_(result_dir=str(tmp_path))

    def write(name, recs):
        with open(tmp_path / name, "w") as fp:
            for pid, verdict in recs:
                fp.write(json.dumps({"partition_id": pid, "verdict": verdict,
                                     "ce": None, "time_s": 0.0}) + "\n")

    # Earlier span decides 3000 SAT; a later overlapping span (sorts after)
    # recorded the same pid unknown (hard budget cut it mid-batch).
    write("GC-m@0-2048.ledger.jsonl", [(3000, "sat"), (1, "unsat")])
    write("GC-m@2048-34816.ledger.jsonl",
          [(3000, "unknown"), (2, "unknown"), (4, "unsat")])
    paths, decided, unknown = merge_span_ledgers(cfg, "m")
    assert len(paths) == 2
    assert decided[3000]["verdict"] == "sat"     # decided-wins
    assert decided[1]["verdict"] == "unsat"
    assert decided[4]["verdict"] == "unsat"
    assert unknown == {2}                        # only the genuinely open pid
    # Reverse arrival order: unknown first, decided later — still decided.
    write("GC-m2@0-9999.ledger.jsonl", [(7, "unknown")])
    write("GC-m2@5000-9999.ledger.jsonl", [(7, "sat")])
    _, decided2, unknown2 = merge_span_ledgers(cfg, "m2")
    assert decided2[7]["verdict"] == "sat" and unknown2 == set()

"""Shared harness scaffolding (scripts/_sweeplib): ledger resume + sorting."""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import _sweeplib  # noqa: E402


def test_done_set_includes_skipped_records(tmp_path):
    path = str(tmp_path / "results.jsonl")
    with open(path, "w") as fp:
        fp.write(json.dumps({"run_id": "r", "model": "CP-2", "sat": 1}) + "\n")
        fp.write(json.dumps({"run_id": "r", "model": "CP-1",
                             "skipped": "input-width mismatch with domain"}) + "\n")
    done = _sweeplib.done_set(path)
    # both verified and skipped models count as done → resume converges;
    # keys carry the binding config (pre-round-2 rows get a legacy sentinel)
    assert ("r", "CP-2", ("legacy", None, None)) in done
    assert ("r", "CP-1", "skipped") in done
    assert _sweeplib.done_set(str(tmp_path / "missing.jsonl")) == set()


def test_model_natkey_orders_families_and_odd_names():
    names = ["CP-10", "CP-2", "aCP-1-Old", "CP-1"]
    ordered = sorted(names, key=_sweeplib.model_natkey)
    assert ordered.index("CP-1") < ordered.index("CP-2") < ordered.index("CP-10")
    assert "aCP-1-Old" in ordered  # non-standard name sorts without crashing

"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Real multi-chip TPU hardware is not available in CI; sharding correctness is
validated on virtual CPU devices (``xla_force_host_platform_device_count``),
and the driver separately dry-run-compiles the multi-chip path via
``__graft_entry__.dryrun_multichip``.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The host image may pre-register an accelerator PJRT plugin (e.g. the
# tunnelled TPU backend) via sitecustomize; if its relay is unreachable,
# *any* backend initialization — even with JAX_PLATFORMS=cpu — blocks
# forever.  Tests are CPU-only by design, so drop every non-CPU backend
# factory before the first jax use.
import jax._src.xla_bridge as _xb  # noqa: E402

_BUILTIN = {"cpu", "tpu", "gpu", "cuda", "rocm", "metal"}
for _name in [n for n in _xb._backend_factories if n not in _BUILTIN]:
    _xb._backend_factories.pop(_name, None)

import jax  # noqa: E402

# The plugin's registration may have pinned jax_platforms to itself via
# jax.config, which overrides the env var — pin it back to CPU.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def reference_assets_available():
    return os.path.isdir("/root/reference/models")


def pytest_configure(config):
    np.random.seed(0)
    config.addinivalue_line(
        "markers", "slow: multi-minute tests (subprocess sweeps, end-to-end)")

"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Real multi-chip TPU hardware is not available in CI; sharding correctness is
validated on virtual CPU devices (``xla_force_host_platform_device_count``),
and the driver separately dry-run-compiles the multi-chip path via
``__graft_entry__.dryrun_multichip``.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def reference_assets_available():
    return os.path.isdir("/root/reference/models")


def pytest_configure(config):
    np.random.seed(0)

"""End-to-end experiment pipeline on a tiny synthetic dataset."""
import numpy as np
import pytest

from fairify_tpu.data import domains as dom_mod
from fairify_tpu.data.domains import DomainSpec
from fairify_tpu.data.loaders import LoadedDataset
from fairify_tpu.analysis import experiment
from fairify_tpu.verify import engine
from fairify_tpu.verify.config import SweepConfig
from tests.test_analysis import _net_with_pa_neuron


@pytest.fixture()
def tiny_setup(monkeypatch, tmp_path):
    dom = DomainSpec(name="tinyexp", label="y",
                     ranges={"a": (0, 3), "pa": (0, 1), "b": (0, 3), "c": (0, 3)})
    monkeypatch.setitem(dom_mod.DOMAINS, "tinyexp", dom)
    rng = np.random.default_rng(0)
    X = rng.integers(0, 4, size=(200, 4)).astype(np.float64)
    y = (X[:, 0] > 1).astype(int)
    import pandas as pd

    df = pd.DataFrame(X, columns=["a", "pa", "b", "c"])
    df["y"] = y
    ds = LoadedDataset("tinyexp", df, X[:150], y[:150], X[150:], y[150:], "y")
    cfg = SweepConfig(
        name="tinyexp", dataset="tinyexp", protected=("pa",),
        partition_threshold=4, sim_size=64, soft_timeout_s=20.0,
        hard_timeout_s=300.0, result_dir=str(tmp_path),
        engine=engine.EngineConfig(frontier_size=64, attack_samples=32,
                                   bab_attack_samples=8, soft_timeout_s=20.0),
    )
    return ds, cfg


def test_experiment_pipeline_biased_model(tiny_setup):
    ds, cfg = tiny_setup
    net = _net_with_pa_neuron(d=4, h=6, pa=1, carrier=3)
    res = experiment.run_experiment(net, cfg, "tiny-biased", dataset=ds,
                                    repair_mode="masked", causal_samples=600)
    # The PA-carrier net discriminates: sweep must find counterexamples.
    assert res.report.counts["sat"] >= 1
    assert res.ce_pairs
    assert res.localization is not None and res.localization.ranked
    # The carrier neuron should top the localization ranking.
    assert res.localization.ranked[0][:2] == (0, 3)
    assert set(res.metrics) == {"original", "fairer", "hybrid"}
    assert 0.0 <= res.causal_rates["original"] <= 1.0
    # Hybrid must never be *more* causally discriminatory than the original
    # on SAT-routed regions when the fairer model actually changed.
    assert set(res.causal_rates) == {"original", "fairer", "hybrid"}


def test_experiment_pipeline_fair_model(tiny_setup):
    ds, cfg = tiny_setup
    from tests.test_analysis import _net_fair

    net = _net_fair(4)
    res = experiment.run_experiment(net, cfg, "tiny-fair", dataset=ds,
                                    repair_mode="masked", causal_samples=400)
    assert res.report.counts["sat"] == 0
    assert res.report.counts["unsat"] == res.report.partitions_total
    assert res.causal_rates["original"] == 0.0
    assert res.fairer_net is net  # nothing to repair

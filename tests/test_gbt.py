"""Gradient-boosted stumps — the from-scratch task3 strong teacher (models/gbt.py)."""
import numpy as np

from fairify_tpu.models.gbt import GradientBoostedTrees, feature_importances


def _toy(n=600, seed=0):
    """Nonlinear binary task a linear model cannot solve: XOR of two
    thresholded features plus noise dims."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, size=(n, 5))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0.2)).astype(np.int64)
    flip = rng.random(n) < 0.05
    y[flip] = 1 - y[flip]
    return X, y


def test_gbt_beats_linear_on_xor():
    X, y = _toy()
    Xtr, ytr, Xte, yte = X[:400], y[:400], X[400:], y[400:]
    gbt = GradientBoostedTrees(n_rounds=200).fit(Xtr, ytr)
    acc = float((gbt.predict(Xte) == yte).mean())
    from sklearn.linear_model import LogisticRegression

    lin = LogisticRegression(max_iter=500).fit(Xtr, ytr)
    lin_acc = float((lin.predict(Xte) == yte).mean())
    assert acc > 0.85, acc           # strong on the nonlinear task
    assert acc > lin_acc + 0.15      # clearly beyond a linear teacher
    # Split importances favor the two signal features (uniform would
    # give them 0.4; late rounds legitimately split noise dims).
    imp = feature_importances(gbt, 5)
    assert imp[0] + imp[1] > 0.5


def test_gbt_deterministic_and_serializes_prediction():
    X, y = _toy(seed=3)
    a = GradientBoostedTrees(n_rounds=50).fit(X, y)
    b = GradientBoostedTrees(n_rounds=50).fit(X, y)
    assert np.array_equal(a.decision_function(X), b.decision_function(X))
    p = a.predict_proba(X)
    assert ((p >= 0) & (p <= 1)).all()
    assert np.array_equal(a.predict(X), (p > 0.5).astype(np.int64))


def test_gbt_degenerate_labels():
    """All-one labels: no split has positive gain; predicts the prior."""
    X = np.random.default_rng(0).uniform(size=(50, 3))
    y = np.ones(50, dtype=np.int64)
    gbt = GradientBoostedTrees(n_rounds=10).fit(X, y)
    assert (gbt.predict(X) == 1).all()

"""IBP soundness and exact certification tests (oracle: brute force on tiny domains)."""
import itertools

import jax.numpy as jnp
import numpy as np

from fairify_tpu.models import mlp as M
from fairify_tpu.ops import exact, interval
from tests.test_mlp import numpy_forward, random_mlp


def brute_force_preacts(ws, bs, lo, hi):
    """All pre-activations over every integer point of the box."""
    points = list(itertools.product(*[range(l, h + 1) for l, h in zip(lo, hi)]))
    X = np.array(points, dtype=np.float64)
    pres = []
    h = X
    for i, (w, b) in enumerate(zip(ws, bs)):
        z = h @ w + b
        pres.append(z)
        h = z if i == len(ws) - 1 else np.maximum(z, 0.0)
    return pres


def test_ibp_contains_all_reachable_values():
    rng = np.random.default_rng(7)
    params = random_mlp(rng, [3, 8, 5, 1])
    ws = [np.asarray(w, dtype=np.float64) for w in params.weights]
    bs = [np.asarray(b, dtype=np.float64) for b in params.biases]
    lo, hi = [0, 0, 1], [2, 3, 4]
    pres = brute_force_preacts(ws, bs, lo, hi)
    bounds = interval.network_bounds(
        params, jnp.asarray(lo, jnp.float32), jnp.asarray(hi, jnp.float32)
    )
    for l in range(len(ws)):
        np.testing.assert_array_less(
            np.asarray(bounds.ws_lb[l]) - 1e-4, pres[l].min(axis=0) + 1e-9
        )
        np.testing.assert_array_less(
            pres[l].max(axis=0) - 1e-9, np.asarray(bounds.ws_ub[l]) + 1e-4
        )


def test_ibp_batched_over_boxes():
    rng = np.random.default_rng(8)
    params = random_mlp(rng, [4, 6, 1])
    lo = jnp.asarray([[0, 0, 0, 0], [1, 1, 1, 1]], jnp.float32)
    hi = jnp.asarray([[2, 2, 2, 2], [3, 3, 3, 3]], jnp.float32)
    bounds = interval.network_bounds(params, lo, hi)
    assert bounds.ws_lb[0].shape == (2, 6)
    # batch row 0 must equal the unbatched computation
    single = interval.network_bounds(params, lo[0], hi[0])
    np.testing.assert_allclose(
        np.asarray(bounds.ws_ub[0][0]), np.asarray(single.ws_ub[0]), rtol=1e-6
    )


def test_dead_from_ws_ub_skips_output_layer():
    rng = np.random.default_rng(9)
    params = random_mlp(rng, [3, 5, 1])
    bounds = interval.network_bounds(
        params, jnp.zeros(3, jnp.float32), jnp.ones(3, jnp.float32)
    )
    deads = interval.dead_from_ws_ub(bounds)
    assert float(deads[-1].sum()) == 0.0


def test_exact_certification_agrees_with_brute_force():
    rng = np.random.default_rng(10)
    params = random_mlp(rng, [3, 10, 4, 1])
    ws = [np.asarray(w) for w in params.weights]
    bs = [np.asarray(b) for b in params.biases]
    lo, hi = [0, 0, 0], [3, 3, 3]
    pres = brute_force_preacts(
        [w.astype(np.float64) for w in ws], [b.astype(np.float64) for b in bs], lo, hi
    )
    # propose everything dead; certification must keep only truly-dead neurons
    proposed = [np.ones_like(b) for b in bs]
    certified = exact.certify_dead_masks(ws, bs, lo, hi, proposed)
    for l in range(len(ws) - 1):
        true_dead = pres[l].max(axis=0) <= 0
        got_dead = certified[l] > 0.5
        # certified ⇒ truly dead (soundness, must hold exactly)
        assert not np.any(got_dead & ~true_dead)
        # on these tiny nets the exact IBP bound is tight enough to find all
        # first-layer dead neurons (affine over the input box ⇒ exact)
        if l == 0:
            np.testing.assert_array_equal(got_dead, true_dead)


def test_exact_bounds_match_float_ibp_closely():
    rng = np.random.default_rng(11)
    params = random_mlp(rng, [4, 7, 1])
    ws = [np.asarray(w) for w in params.weights]
    bs = [np.asarray(b) for b in params.biases]
    lo, hi = [0, 1, 0, 2], [5, 4, 3, 6]
    ws_lb, ws_ub, _, _ = exact.exact_network_bounds(ws, bs, lo, hi)
    bounds = interval.network_bounds(
        params, jnp.asarray(lo, jnp.float32), jnp.asarray(hi, jnp.float32), widen=False
    )
    for l in range(len(ws)):
        np.testing.assert_allclose(
            np.asarray(bounds.ws_ub[l]),
            np.array([float(v) for v in ws_ub[l]]),
            rtol=1e-4, atol=1e-4,
        )

"""Independent exact UNSAT checker (verify.exact_check).

Oracle style follows tests/test_bab2.py: tiny domains where exhaustive
lattice enumeration is feasible; the checker must agree exactly with brute
force — no float tolerance anywhere in the assertions.
"""
from fractions import Fraction

import numpy as np
import pytest

from fairify_tpu.models import mlp
from fairify_tpu.verify import exact_check as ec
from fairify_tpu.verify import property as prop
from fairify_tpu.data.domains import DomainSpec

from test_bab2 import brute_force_flip, tiny_domain  # noqa: F401 (oracle reuse)


# ---------------------------------------------------------------------------
# Exact simplex
# ---------------------------------------------------------------------------


def F(x):
    return Fraction(x)


def test_simplex_feasible_point_satisfies_system():
    # x0 + x1 >= 3 (as -x0 - x1 <= -3), x0 - x1 <= 1, box [0, 10]^2
    A = [[F(-1), F(-1)], [F(1), F(-1)]]
    b = [F(-3), F(1)]
    st, pt = ec._feasible(A, b, [F(0), F(0)], [F(10), F(10)])
    assert st == "feasible"
    assert pt[0] + pt[1] >= 3
    assert pt[0] - pt[1] <= 1
    assert all(F(0) <= v <= F(10) for v in pt)


def test_simplex_infeasible():
    # x0 >= 5 and x0 <= 2 simultaneously.
    A = [[F(-1), F(0)], [F(1), F(0)]]
    b = [F(-5), F(2)]
    st, pt = ec._feasible(A, b, [F(0), F(0)], [F(10), F(10)])
    assert st == "infeasible" and pt is None


def test_simplex_equality_pinned_dims():
    # Width-0 dim: x1 fixed at 4 by its bounds; require x0 + x1 >= 6.
    A = [[F(-1), F(-1)]]
    b = [F(-6)]
    st, pt = ec._feasible(A, b, [F(0), F(4)], [F(10), F(4)])
    assert st == "feasible" and pt[1] == 4 and pt[0] >= 2


def test_exact_dual_bound_matches_lp_optimum():
    from scipy.optimize import linprog

    c = [F(1), F(1)]
    A_ub = [[F(-1), F(-1)]]
    b_ub = [F(-3)]
    A_eq = [[F(1), F(-1)]]
    b_eq = [F(1)]
    lb = [F(0), F(0)]
    ub = [F(10), F(10)]
    res = linprog([1.0, 1.0], A_ub=[[-1.0, -1.0]], b_ub=[-3.0],
                  A_eq=[[1.0, -1.0]], b_eq=[1.0],
                  bounds=[(0, 10), (0, 10)], method="highs")
    y_ub = [F(max(float(-m), 0.0)) for m in np.atleast_1d(res.ineqlin.marginals)]
    y_eq = [F(float(-m)) for m in np.atleast_1d(res.eqlin.marginals)]
    bound = ec._exact_dual_bound(c, A_ub, b_ub, A_eq, b_eq, lb, ub, y_ub, y_eq)
    assert bound == 3  # exact: optimum of min x0+x1 is 3
    # Garbage duals must still give a VALID (just weaker) bound:
    bound2 = ec._exact_dual_bound(c, A_ub, b_ub, A_eq, b_eq, lb, ub,
                                  [F(0)], [F(7)])
    assert bound2 <= 3


# ---------------------------------------------------------------------------
# Pair-property checker vs brute force
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(10))
def test_pair_checker_agrees_with_brute_force(seed):
    rng = np.random.default_rng(seed)
    dom = tiny_domain({"a": (0, 4), "pa": (0, 2), "b": (0, 4)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",))
    enc = prop.encode(query)
    ws = [rng.normal(size=(3, 6)).astype(np.float32) * 0.6,
          rng.normal(size=(6, 1)).astype(np.float32)]
    bs = [(rng.normal(size=(6,)) * 0.3).astype(np.float32),
          np.array([float(rng.normal()) * 0.5], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    lo, hi = dom.lo_hi()
    lo = lo.astype(np.int64)
    hi = hi.astype(np.int64)
    res = ec.decide_pair_box_exact(W, B, enc, lo, hi, max_nodes=20000)
    truth = brute_force_flip(net, enc, lo, hi)
    assert res["verdict"] in ("unsat_confirmed", "refuted")
    assert (res["verdict"] == "refuted") == truth
    if truth:
        from fairify_tpu.verify.engine import validate_pair

        x, xp = res["witness"]
        assert validate_pair(W, B, np.asarray(x), np.asarray(xp))


def test_pair_checker_relaxed_attribute():
    """RA δ handling: flips only reachable via the ε shift are found."""
    # f = a + 3*pa - 4.5 won't flip with pa alone on a ∈ [0,1] ... build a
    # net where the RA dim decides: f = ra + 2*pa - 2.5 over ra ∈ [0, 4].
    ws = [np.array([[0.0], [2.0], [1.0]], dtype=np.float32),
          np.array([[1.0]], dtype=np.float32)]
    bs = [np.array([0.0], dtype=np.float32), np.array([-2.5], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    dom = tiny_domain({"a": (0, 1), "pa": (0, 1), "ra": (1, 1)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",),
                               relaxed=("ra",), relax_eps=1)
    enc = prop.encode(query)
    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    lo, hi = dom.lo_hi()
    res = ec.decide_pair_box_exact(W, B, enc, lo.astype(np.int64),
                                  hi.astype(np.int64))
    # pa=1, ra=1: z = 1+2-2.5 = +0.5 ... pa=0, ra'=2: 2-2.5 = -0.5: flip.
    assert res["verdict"] == "refuted"
    assert brute_force_flip(net, enc, lo.astype(np.int64), hi.astype(np.int64))


def test_pair_checker_ra_direction_asymmetry():
    """Review repro: a flip reachable ONLY via the RA shift leaving the box
    in the direction the role-swap symmetry does not cover.

    f = ra − 4.5 over ra ∈ [0, 4], ε = 1: x = (·, ra=4) gives −0.5 and
    x' = (·, ra=5, shifted out of the box) gives +0.5 — direction
    f_x < 0 ∧ f_x' > 0 only.  A one-direction sweep confirms UNSAT here;
    the checker must refute."""
    ws = [np.array([[0.0], [0.0], [1.0]], dtype=np.float32),
          np.array([[1.0]], dtype=np.float32)]
    bs = [np.array([0.0], dtype=np.float32), np.array([-4.5], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    dom = tiny_domain({"a": (0, 1), "pa": (0, 1), "ra": (0, 4)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",),
                               relaxed=("ra",), relax_eps=1)
    enc = prop.encode(query)
    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    lo, hi = dom.lo_hi()
    res = ec.decide_pair_box_exact(W, B, enc, lo.astype(np.int64),
                                  hi.astype(np.int64))
    assert res["verdict"] == "refuted"
    assert brute_force_flip(net, enc, lo.astype(np.int64), hi.astype(np.int64))


def test_pair_checker_multi_pa_validity():
    """Review repro: with two protected attributes, a legal pair must differ
    in EVERY PA coordinate (property.encode's conjunction of neq).  Here
    f = 4·|p − q| − 2 flips only across pairs differing in exactly one of
    p/q — which are NOT valid pairs — so the box is UNSAT and the checker
    must not refute with an invalid witness."""
    # |p − q| via relu(p − q) + relu(q − p).
    ws = [np.array([[0.0, 0.0], [1.0, -1.0], [-1.0, 1.0]], dtype=np.float32),
          np.array([[4.0], [4.0]], dtype=np.float32)]
    bs = [np.zeros(2, dtype=np.float32), np.array([-2.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    dom = tiny_domain({"a": (0, 1), "p": (0, 1), "q": (0, 1)})
    query = prop.FairnessQuery(domain=dom, protected=("p", "q"))
    enc = prop.encode(query)
    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    lo, hi = dom.lo_hi()
    res = ec.decide_pair_box_exact(W, B, enc, lo.astype(np.int64),
                                  hi.astype(np.int64))
    assert not brute_force_flip(net, enc, lo.astype(np.int64), hi.astype(np.int64))
    assert res["verdict"] == "unsat_confirmed"


# ---------------------------------------------------------------------------
# Sign-certificate confirmation
# ---------------------------------------------------------------------------


def test_sign_certificate_positive_net_confirmed():
    ws = [np.array([[1.0, -1.0]], dtype=np.float32),
          np.array([[1.0], [1.0]], dtype=np.float32)]
    bs = [np.zeros(2, dtype=np.float32), np.array([0.5], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    r = ec.confirm_sign_certificate(W, B, np.array([-4]), np.array([4]),
                                    want_positive=True)
    assert r["verdict"] == "confirmed"


def test_sign_certificate_needs_splits_confirmed():
    """The f ≡ 1 cancellation net (test_bab2): root LP dips below zero, the
    exact confirmation must still close via phase splits."""
    ws = [np.array([[1.0, -1.0, 1.0]], dtype=np.float32),
          np.array([[-1.0], [1.0], [1.0]], dtype=np.float32)]
    bs = [np.array([0.0, 0.0, 8.0], dtype=np.float32),
          np.array([-7.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    r = ec.confirm_sign_certificate(W, B, np.array([-4]), np.array([4]),
                                    want_positive=True)
    assert r["verdict"] == "confirmed"
    assert r["nodes"] > 1


def test_sign_certificate_mixed_net_not_confirmed():
    ws = [np.array([[1.0]], dtype=np.float32), np.array([[1.0]], dtype=np.float32)]
    bs = [np.zeros(1, dtype=np.float32), np.array([-2.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    r = ec.confirm_sign_certificate(W, B, np.array([0]), np.array([6]),
                                    want_positive=True)
    assert r["verdict"] == "not_confirmed"


def test_negative_sign_certificate():
    ws = [np.array([[1.0]], dtype=np.float32), np.array([[-1.0]], dtype=np.float32)]
    bs = [np.zeros(1, dtype=np.float32), np.array([-1.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    r = ec.confirm_sign_certificate(W, B, np.array([0]), np.array([6]),
                                    want_positive=False)
    assert r["verdict"] == "confirmed"


def test_exact_logit_sign_frac_matches_float():
    rng = np.random.default_rng(3)
    ws = [rng.normal(size=(2, 4)).astype(np.float32),
          rng.normal(size=(4, 1)).astype(np.float32)]
    bs = [rng.normal(size=(4,)).astype(np.float32),
          rng.normal(size=(1,)).astype(np.float32)]
    W, B = ec._frac_weights(ws, bs)
    from fairify_tpu.verify.engine import exact_logit_sign

    for _ in range(20):
        x = rng.integers(-5, 6, size=2)
        assert ec._exact_logit_sign_frac(W, B, x) == exact_logit_sign(ws, bs, x)

"""CROWN bounds: soundness vs brute-force enumeration, tightness vs IBP."""
import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from fairify_tpu.models import mlp
from fairify_tpu.ops import crown, interval


def random_net(rng, sizes):
    ws, bs = [], []
    for i in range(len(sizes) - 1):
        ws.append(rng.normal(size=(sizes[i], sizes[i + 1])).astype(np.float32))
        bs.append(rng.normal(size=(sizes[i + 1],)).astype(np.float32))
    return mlp.from_numpy(ws, bs)


def grid_points(lo, hi):
    axes = [np.arange(l, h + 1) for l, h in zip(lo, hi)]
    return np.array(list(itertools.product(*axes)), dtype=np.float32)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("sizes", [(3, 8, 1), (3, 6, 6, 1), (4, 10, 5, 1)])
def test_crown_sound_and_tighter_than_ibp(seed, sizes):
    rng = np.random.default_rng(seed)
    net = random_net(rng, sizes)
    lo = np.zeros(sizes[0], dtype=np.float32)
    hi = np.full(sizes[0], 2.0, dtype=np.float32)

    pts = grid_points(lo, hi)
    logits = np.asarray(mlp.forward(net, jnp.asarray(pts)))
    true_min, true_max = logits.min(), logits.max()

    ilb, iub = interval.output_bounds(net, jnp.asarray(lo), jnp.asarray(hi))
    clb, cub = crown.crown_output_bounds(net, jnp.asarray(lo), jnp.asarray(hi))

    # Soundness: both bound the true extrema (grid points are a subset of the box).
    assert float(ilb) <= true_min + 1e-4 and float(iub) >= true_max - 1e-4
    assert float(clb) <= true_min + 1e-4 and float(cub) >= true_max - 1e-4
    # CROWN is never looser than IBP (intersected by construction).
    assert float(clb) >= float(ilb) - 1e-4
    assert float(cub) <= float(iub) + 1e-4


def test_crown_batched_matches_single():
    rng = np.random.default_rng(3)
    net = random_net(rng, (3, 7, 5, 1))
    los = np.array([[0, 0, 0], [1, 0, 2], [0, 2, 1]], dtype=np.float32)
    his = np.array([[2, 2, 2], [3, 1, 4], [2, 5, 2]], dtype=np.float32)
    blb, bub = crown.crown_output_bounds(net, jnp.asarray(los), jnp.asarray(his))
    for i in range(3):
        slb, sub = crown.crown_output_bounds(net, jnp.asarray(los[i]), jnp.asarray(his[i]))
        np.testing.assert_allclose(np.asarray(blb)[i], np.asarray(slb), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(bub)[i], np.asarray(sub), rtol=1e-5, atol=1e-5)


def test_crown_respects_masks():
    rng = np.random.default_rng(4)
    net = random_net(rng, (3, 8, 1))
    # Kill half the hidden layer; bounds must equal those of the excised net.
    dead = np.zeros(8, dtype=np.float32)
    dead[:4] = 1.0
    masked = net.with_masks((jnp.asarray(1.0 - dead), net.masks[1]))
    excised = mlp.excise(masked)
    lo = jnp.zeros(3)
    hi = jnp.full((3,), 3.0)
    mlb, mub = crown.crown_output_bounds(masked, lo, hi)
    elb, eub = crown.crown_output_bounds(excised, lo, hi)
    np.testing.assert_allclose(float(mlb), float(elb), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(float(mub), float(eub), rtol=1e-4, atol=1e-4)


def test_crown_stable_layers_exact_for_linear_region():
    # With inputs confined where all hidden neurons are provably active,
    # CROWN should be (near-)exact: the net is affine there.
    ws = [np.array([[1.0, -1.0], [1.0, 1.0]], dtype=np.float32),
          np.array([[1.0], [2.0]], dtype=np.float32)]
    bs = [np.array([5.0, 5.0], dtype=np.float32), np.array([-1.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    lo = jnp.asarray(np.array([0.0, 0.0], dtype=np.float32))
    hi = jnp.asarray(np.array([1.0, 1.0], dtype=np.float32))
    clb, cub = crown.crown_output_bounds(net, lo, hi)
    pts = grid_points([0, 0], [1, 1])
    logits = np.asarray(mlp.forward(net, jnp.asarray(pts)))
    assert abs(float(clb) - logits.min()) < 1e-3
    assert abs(float(cub) - logits.max()) < 1e-3


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("sizes", [(3, 8, 1), (3, 6, 6, 1), (4, 10, 5, 5, 1)])
def test_alpha_crown_sound_and_no_looser(seed, sizes):
    """α-CROWN output bounds remain sound and never meaningfully loosen
    plain CROWN (they are intersected with it; slack tolerance only)."""
    rng = np.random.default_rng(100 + seed)
    net = random_net(rng, sizes)
    lo = np.zeros(sizes[0], dtype=np.float32)
    hi = np.full(sizes[0], 2.0, dtype=np.float32)

    pts = grid_points(lo, hi)
    logits = np.asarray(mlp.forward(net, jnp.asarray(pts)))

    clb, cub = crown.crown_output_bounds(net, jnp.asarray(lo), jnp.asarray(hi))
    alb, aub = crown.alpha_crown_output_bounds(
        net, jnp.asarray(lo), jnp.asarray(hi), iters=8)

    assert float(alb) <= logits.min() + 1e-5
    assert float(aub) >= logits.max() - 1e-5
    # Intersected with plain CROWN after widening: never looser, exactly.
    assert float(alb) >= float(clb) - 1e-7
    assert float(aub) <= float(cub) + 1e-7


def test_alpha_crown_tightens_deep_net():
    """On deeper nets (where CROWN's heuristic slope is weakest) the
    α-optimized bounds should be strictly tighter for most random boxes."""
    rng = np.random.default_rng(7)
    net = random_net(rng, (4, 10, 10, 10, 1))
    lo = np.zeros((16, 4), dtype=np.float32)
    hi = np.full((16, 4), 3.0, dtype=np.float32)
    clb, cub = crown.crown_output_bounds(net, jnp.asarray(lo), jnp.asarray(hi))
    alb, aub = crown.alpha_crown_output_bounds(net, jnp.asarray(lo), jnp.asarray(hi), iters=8)
    cw = np.asarray(cub) - np.asarray(clb)
    aw = np.asarray(aub) - np.asarray(alb)
    assert (aw <= cw + 1e-4).all()
    assert aw.mean() < cw.mean()  # strictly tighter on average

"""Two REAL processes sweep host slices of one grid into a shared result
dir; their merged ledgers must reproduce the single-process verdict map.

The in-process span test (tests/test_parallel.py) exercises the slicing
logic; this one exercises the actual multi-host deployment shape — separate
interpreters, concurrent execution, shared filesystem sinks — via the CLI's
``--host-index/--host-count`` flags (SURVEY.md §5.8).
"""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    # /root/.axon_site/sitecustomize.py would register the axon PJRT plugin
    # into the child interpreter; an empty PYTHONPATH keeps the CPU backend
    # clean (same reason tests/conftest.py pins the platform in-process).
    env["PYTHONPATH"] = ""
    return env


@pytest.mark.slow
def test_two_process_sweep_matches_single(tmp_path):
    shared = tmp_path / "shared"
    single = tmp_path / "single"
    base = [sys.executable, "-m", "fairify_tpu", "run", "GC",
            "--models", "GC-4", "--soft-timeout", "5",
            "--hard-timeout", "600"]

    procs = [
        subprocess.Popen(
            base + ["--result-dir", str(shared),
                    "--host-index", str(i), "--host-count", "2"],
            cwd=ROOT, env=_worker_env(),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)
    ]
    try:
        outs = [p.communicate(timeout=900)[0].decode() for p in procs]
    finally:
        for p in procs:  # never leave orphan sweeps running on failure
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]

    # Reference: one process, whole grid.
    ref = subprocess.run(
        base + ["--result-dir", str(single)],
        cwd=ROOT, env=_worker_env(), timeout=900,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    assert ref.returncode == 0, ref.stdout.decode()[-2000:]

    from fairify_tpu.parallel import multihost

    span_ledgers = sorted(str(p) for p in shared.glob("GC-GC-4@*.ledger.jsonl"))
    assert len(span_ledgers) == 2, list(shared.iterdir())
    merged = multihost.merge_ledgers(span_ledgers)

    ref_ledger = single / "GC-GC-4.ledger.jsonl"
    ref_map = {}
    with open(ref_ledger) as fp:
        for line in fp:
            rec = json.loads(line)
            ref_map[rec["partition_id"]] = rec["verdict"]

    got_map = {pid: rec["verdict"] for pid, rec in merged.items()}
    assert set(got_map) == set(ref_map)
    # Decided verdicts are host-count invariant (global partition ids and
    # PRNG keys); only budget-frontier UNKNOWNs may legitimately shift on a
    # slow host, so the strict comparison excludes them rather than baking
    # a machine-speed assumption into a correctness test.
    diff = {k for k in ref_map
            if ref_map[k] != got_map[k]
            and "unknown" not in (ref_map[k], got_map[k])}
    assert not diff, diff
    decided = [k for k in ref_map
               if "unknown" not in (ref_map[k], got_map[k])]
    # The GC-4 grid decides in stage-0 well under the soft budget; if more
    # than a sliver ever times out the test machine is the story, not the
    # invariant.
    assert len(decided) >= 0.9 * len(ref_map)
    assert sorted(got_map[k] for k in decided) == \
        sorted(ref_map[k] for k in decided)

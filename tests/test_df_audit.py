"""DF capped-partitioning audit: our capped grid vs the REFERENCE's.

VERDICT round-1 item 6 asked for evidence that the 8-partition DF grid is
the faithful product of the reference's cap logic, not an accident.  These
tests import the reference's ``partition_df`` / ``partitioned_ranges_df``
(``/root/reference/utils/input_partition.py:78-182``) and compare outputs
on random domains and on the real default-credit domain.
"""
import importlib.util
import os

import numpy as np
import pytest

from fairify_tpu.partition import grid as grid_mod

REF = "/root/reference/utils/input_partition.py"


def _ref_module():
    """Exec the reference partitioner with its heavyweight star-import
    stripped (``from utils.verif_utils import *`` drags in tf/aif360, not
    present here; it also happens to be where ``random`` reaches the module
    namespace, so inject it explicitly)."""
    import random
    import types

    src = open(REF).read().replace("from utils.verif_utils import *", "")
    mod = types.ModuleType("ref_input_partition")
    mod.random = random
    exec(compile(src, REF, "exec"), mod.__dict__)
    return mod


pytestmark = pytest.mark.skipif(not os.path.isfile(REF),
                                reason="reference checkout not present")


def _norm(boxes):
    """Normalize a partition list for comparison (tuples, sorted keys)."""
    return [tuple(sorted((k, (int(v[0]), int(v[1]))) for k, v in b.items()))
            for b in boxes]


@pytest.mark.parametrize("seed", range(8))
def test_chunking_matches_reference(seed):
    rng = np.random.default_rng(seed)
    ranges = {}
    for i in range(rng.integers(2, 7)):
        lo = int(rng.integers(0, 20))
        ranges[f"a{i}"] = (lo, lo + int(rng.integers(0, 40)))
    size = int(rng.integers(2, 12))
    ref = _ref_module().partition_df({k: list(v) for k, v in ranges.items()}, size)
    got = grid_mod.partition_attributes_capped(ranges, size)
    assert set(got) == set(ref)
    for k in got:
        assert [list(p) for p in got[k]] == [list(p) for p in ref[k]]


@pytest.mark.parametrize("seed", range(8))
def test_capped_expansion_matches_reference_deterministic(seed):
    """Below the cap both sides enumerate the identical box list in order."""
    rng = np.random.default_rng(100 + seed)
    attrs, ranges = [], {}
    for i in range(rng.integers(3, 6)):
        lo = int(rng.integers(0, 5))
        attrs.append(f"a{i}")
        ranges[f"a{i}"] = (lo, lo + int(rng.integers(0, 25)))
    pa = [attrs[0]]
    size = int(rng.integers(3, 10))
    cap = 200  # big enough that the sampling branch never triggers here
    mod = _ref_module()
    p_ref = mod.partition_df({k: list(v) for k, v in ranges.items()}, size)
    ref = mod.partitioned_ranges_df(attrs, pa, p_ref,
                                    {k: list(v) for k, v in ranges.items()},
                                    max_partitions=cap)
    if len(ref) > cap:  # pragma: no cover - cap chosen to avoid this
        pytest.skip("sampling branch")
    p_got = grid_mod.partition_attributes_capped(ranges, size)
    got = grid_mod.partitioned_ranges_capped(attrs, pa, p_got, ranges,
                                             max_partitions=cap)
    assert _norm(got) == _norm(ref)


def test_capped_sampling_branch_properties():
    """Above the cap: exactly max_partitions boxes, each a member of the
    full product.  Only protected attributes are included *unconditionally*
    (non-PA attrs that would overflow are dropped to full range instead),
    so the sampling branch needs a wide PA."""
    attrs = ["p", "b"]
    ranges = {"p": (0, 59), "b": (0, 3)}
    size = 10  # p chunks into 6; PA is always chosen -> 6 combos > cap 4
    p_got = grid_mod.partition_attributes_capped(ranges, size)
    cap = 4
    got = grid_mod.partitioned_ranges_capped(attrs, ["p"], p_got, ranges,
                                             max_partitions=cap,
                                             rng=np.random.default_rng(7))
    assert len(got) == cap
    full = grid_mod.partitioned_ranges_capped(attrs, ["p"], p_got, ranges,
                                              max_partitions=1000)
    full_set = set(_norm(full))
    assert set(_norm(got)) <= full_set
    assert len(set(_norm(got))) == cap  # sampled without replacement


def test_df_domain_grid_is_the_reference_grid():
    """The real default-credit domain: our capped grid == the reference's,
    box for box — documenting that the 8-partition DF grid is the faithful
    output of the cap logic (``src/DF/Verify-DF.py:93``)."""
    from fairify_tpu.data.domains import get_domain

    dom = get_domain("default")
    ranges = {k: tuple(v) for k, v in dom.ranges.items()}
    attrs = list(dom.columns)
    pa = ["SEX_2"]
    mod = _ref_module()
    p_ref = mod.partition_df({k: list(v) for k, v in ranges.items()}, 8)
    ref = mod.partitioned_ranges_df(attrs, pa, p_ref,
                                    {k: list(v) for k, v in ranges.items()},
                                    max_partitions=100)
    p_got = grid_mod.partition_attributes_capped(ranges, 8)
    got = grid_mod.partitioned_ranges_capped(attrs, pa, p_got, ranges,
                                             max_partitions=100)
    assert len(ref) <= 100 and _norm(got) == _norm(ref)
    # The published DF runs verify 8 partitions/model; pin that here.
    assert len(got) == 8

"""Chaos suite: fault injection, launch supervision, graceful degradation.

Pins the resilience contract (DESIGN.md §10): for every injected-fault
schedule, partitions decided before/around the fault match the fault-free
run's verdicts exactly; faulted partitions are UNKNOWN with a machine-
readable ``failure`` record; and a subsequent ``resume=True`` pass
converges to the fault-free verdict map.  A transient fault absorbed by a
retry must leave the verdict map bit-identical and cost at most
``max_launch_retries`` extra launches.
"""
import json
import os

import numpy as np
import pytest

from fairify_tpu import obs
from fairify_tpu.obs import metrics as metrics_mod
from fairify_tpu.obs import trace as trace_mod
from fairify_tpu.parallel.pipeline import LaunchPipeline
from fairify_tpu.resilience import faults
from fairify_tpu.resilience.journal import JournalWriter
from fairify_tpu.resilience.supervisor import (
    ChunkDegraded,
    ChunkFailure,
    Supervisor,
    classify,
)
from fairify_tpu.models.train import init_mlp
from fairify_tpu.verify import presets, sweep


@pytest.fixture(autouse=True)
def _clean_state():
    """Quiescent registry, no tracer, no armed fault plan, per test."""
    trace_mod.deactivate()
    metrics_mod.registry().reset()
    faults.disarm()
    yield
    trace_mod.deactivate()
    metrics_mod.registry().reset()
    faults.disarm()


def _fast_sup(**kw):
    kw.setdefault("backoff_s", 1e-4)
    return Supervisor(**kw)


# ---------------------------------------------------------------------------
# faults: spec parsing + deterministic schedules
# ---------------------------------------------------------------------------


def test_parse_spec_forms():
    s = faults.parse_spec("launch.submit:transient:3")
    assert (s.site, s.kind, s.start, s.every) == \
        ("launch.submit", "transient", 3, False)
    s = faults.parse_spec("launch.decode:fatal:2+")
    assert s.every and s.start == 2
    s = faults.parse_spec("compile:crash:2-4")
    assert (s.start, s.stop) == (2, 4)
    s = faults.parse_spec("smt.query:transient:p0.25")
    assert s.rate == pytest.approx(0.25)


@pytest.mark.parametrize("bad", [
    "nope:transient:1",            # unknown site
    "launch.submit:flaky:1",       # unknown kind
    "launch.submit:transient:x",   # unparseable nth
    "launch.submit",               # missing fields
    "launch.submit:transient:0",   # arrivals are 1-based; 0 never fires
    "launch.submit:transient:3-5+",  # range and every-from are exclusive
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_spec(bad)


def test_plan_fires_exact_arrivals():
    plan = faults.FaultPlan(["ledger.append:transient:2",
                             "ledger.append:fatal:4+"])
    fired = []
    for i in range(1, 7):
        try:
            plan.check("ledger.append")
            fired.append(None)
        except faults.InjectedFault as exc:
            fired.append(exc.kind)
    assert fired == [None, "transient", None, "fatal", "fatal", "fatal"]
    # other sites are unaffected
    plan.check("launch.submit")


def test_probabilistic_schedule_is_seed_deterministic():
    def schedule(seed):
        plan = faults.FaultPlan(["compile:transient:p0.5"], seed=seed)
        out = []
        for _ in range(32):
            try:
                plan.check("compile")
                out.append(0)
            except faults.InjectedFault:
                out.append(1)
        return out

    assert schedule(7) == schedule(7)
    assert any(schedule(7)) and not all(schedule(7))


def test_armed_scope_stacks_and_counts():
    with faults.armed(["compile:transient:1"]):
        with pytest.raises(faults.InjectedFault):
            faults.check("compile")
        assert metrics_mod.registry().counter("fault_injected").value(
            site="compile", kind="transient") == 1
        with faults.armed(["compile:fatal:1"]):  # inner schedule wins
            with pytest.raises(faults.InjectedFault) as ei:
                faults.check("compile")
            assert ei.value.kind == "fatal"
        faults.check("compile")  # outer plan restored; arrival 2 is clean
    faults.check("compile")  # disarmed: never raises


# ---------------------------------------------------------------------------
# supervisor: classification, retries, exhaustion, deadline
# ---------------------------------------------------------------------------


def test_classify_taxonomy():
    assert classify(faults.InjectedFault("x", "transient", 1)) == "transient"
    assert classify(faults.InjectedFault("x", "fatal", 1)) == "fatal"
    assert classify(faults.InjectedFault("x", "crash", 1)) == "propagate"
    assert classify(OSError("disk")) == "transient"
    assert classify(TimeoutError()) == "transient"
    assert classify(KeyboardInterrupt()) == "propagate"
    assert classify(MemoryError()) == "propagate"
    assert classify(ValueError("shape")) == "fatal"

    class XlaRuntimeError(Exception):  # name-matched, module-independent
        pass

    assert classify(XlaRuntimeError()) == "transient"


def test_supervisor_retries_transient_then_succeeds():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert _fast_sup(max_retries=2).run(flaky, site="launch.submit") == "ok"
    assert calls["n"] == 3
    assert metrics_mod.registry().counter("launch_retries").value(
        site="launch.submit") == 2


def test_supervisor_exhaustion_carries_failure_record():
    def always():
        raise OSError("still down")

    with pytest.raises(ChunkDegraded) as ei:
        _fast_sup(max_retries=2).run(always, site="launch.decode")
    f = ei.value.failure
    assert (f.site, f.kind, f.error, f.retries) == \
        ("launch.decode", "transient-exhausted", "OSError", 2)
    rec = f.to_record()
    assert rec["reason"] == "launch.decode:transient-exhausted"
    assert "still down" in rec["detail"]


def test_supervisor_fatal_never_retries():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("shape mismatch")

    with pytest.raises(ChunkDegraded) as ei:
        _fast_sup(max_retries=5).run(bad, site="launch.submit")
    assert calls["n"] == 1
    assert ei.value.failure.kind == "fatal"


def test_supervisor_propagates_control_flow():
    with pytest.raises(KeyboardInterrupt):
        _fast_sup().run(lambda: (_ for _ in ()).throw(KeyboardInterrupt()),
                        site="launch.submit")


def test_supervisor_deadline_stops_retries():
    calls = {"n": 0}

    def failing():
        calls["n"] += 1
        import time as _t

        _t.sleep(0.005)
        raise OSError("x")

    sup = Supervisor(max_retries=100, backoff_s=0.0, deadline_s=0.01,
                     sleep=lambda s: None)
    with pytest.raises(ChunkDegraded) as ei:
        sup.run(failing, site="launch.submit")
    assert ei.value.failure.kind == "deadline"
    assert calls["n"] < 100


def test_supervisor_on_retry_refreshes_state():
    seen = []
    state = {"v": "poisoned"}

    def fetch():
        seen.append(state["v"])
        if state["v"] == "poisoned":
            raise OSError("bad payload")
        return state["v"]

    out = _fast_sup(max_retries=2).run(
        fetch, site="launch.decode",
        on_retry=lambda: state.__setitem__("v", "fresh"))
    assert out == "fresh"
    assert seen == ["poisoned", "fresh"]


# ---------------------------------------------------------------------------
# journal: atomic append, fault site, best-effort exhaustion
# ---------------------------------------------------------------------------


def test_journal_appends_valid_jsonl(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with JournalWriter(path) as jw:
        assert jw.append({"a": 1})
        assert jw.append({"b": [1, 2]})
    with open(path) as fp:
        recs = [json.loads(line) for line in fp]
    assert recs == [{"a": 1}, {"b": [1, 2]}]


def test_journal_transient_fault_retried(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with faults.armed(["ledger.append:transient:1"]):
        jw = JournalWriter(path, fault_site="ledger.append",
                           supervisor=_fast_sup(max_retries=2))
        assert jw.append({"pid": 1})
        jw.close()
    with open(path) as fp:
        assert json.loads(fp.read()) == {"pid": 1}


def test_journal_exhaustion_is_best_effort(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with faults.armed(["ledger.append:transient:1+"]):
        jw = JournalWriter(path, fault_site="ledger.append",
                           supervisor=_fast_sup(max_retries=1))
        assert jw.append({"pid": 1}) is False  # recorded, not raised
        jw.close()
    assert os.path.getsize(path) == 0
    assert metrics_mod.registry().counter("ledger_append_failures").total() == 1


# ---------------------------------------------------------------------------
# pipeline: fault sites + ChunkFailure FIFO slotting
# ---------------------------------------------------------------------------


def test_pipeline_degraded_dispatch_keeps_fifo_order():
    sup = _fast_sup(max_retries=1)
    with faults.armed(["launch.submit:fatal:2"]):
        pipe = LaunchPipeline(depth=2, supervisor=sup)
        out = []
        for i in range(3):
            for meta, _ctx, host in pipe.submit(
                    lambda i=i: ({"v": np.array([i])}, None), meta=i):
                out.append((meta, host))
        for meta, _ctx, host in pipe.drain():
            out.append((meta, host))
    assert [m for m, _ in out] == [0, 1, 2]
    assert isinstance(out[1][1], ChunkFailure)  # the 2nd dispatch degraded
    assert int(out[0][1]["v"][0]) == 0 and int(out[2][1]["v"][0]) == 2


def test_pipeline_decode_retry_redispatches():
    sup = _fast_sup(max_retries=2)
    dispatches = {"n": 0}

    def launch():
        dispatches["n"] += 1
        return {"v": np.array([7])}, "ctx"

    with faults.armed(["launch.decode:transient:1"]):
        pipe = LaunchPipeline(depth=1, supervisor=sup)
        pipe.submit(launch, meta=0)
        (meta, ctx, host), = list(pipe.drain())
    assert int(host["v"][0]) == 7 and ctx == "ctx"
    assert dispatches["n"] == 2  # original + one re-dispatch on retry


# ---------------------------------------------------------------------------
# chaos matrix over the sweep (integration)
# ---------------------------------------------------------------------------

SPAN = (0, 48)


def _cfg(tmp_path, name, **kw):
    kw.setdefault("grid_chunk", 16)
    # One-chunk segments: the module's chaos schedules are arrival-count
    # based (nth launch.submit/launch.decode arrivals), written for one
    # launch per chunk — mega_chunks=1 keeps segment arrivals identical to
    # chunk arrivals while still exercising the mega-loop launch path.
    # (Multi-chunk segment blast radii are pinned in test_mega.py.)
    kw.setdefault("mega_chunks", 1)
    return presets.get("GC").with_(
        result_dir=str(tmp_path / name), soft_timeout_s=30.0,
        hard_timeout_s=600.0, sim_size=64, exact_certify_masks=False,
        launch_backoff_s=1e-4, **kw)


def _net():
    return init_mlp((20, 8, 1), seed=3)


def _vmap(report):
    return {o.partition_id: o.verdict for o in report.outcomes}


def _ledger_records(cfg, model="m"):
    path = os.path.join(cfg.result_dir, f"{cfg.name}-{model}@{SPAN[0]}-{SPAN[1]}.ledger.jsonl")
    with open(path) as fp:
        return [json.loads(line) for line in fp if line.strip()]


@pytest.fixture(scope="module")
def fault_free(tmp_path_factory):
    td = tmp_path_factory.mktemp("fault_free")
    cfg = presets.get("GC").with_(
        result_dir=str(td), soft_timeout_s=30.0, hard_timeout_s=600.0,
        sim_size=64, exact_certify_masks=False, grid_chunk=16)
    rep = sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                             partition_span=SPAN)
    return {o.partition_id: o.verdict for o in rep.outcomes}


def test_transient_fault_is_absorbed_and_bounded(tmp_path, fault_free):
    launches = metrics_mod.registry().counter("device_launches")
    base0 = launches.total()
    base = sweep.verify_model(_net(), _cfg(tmp_path, "base"), model_name="m",
                              resume=False, partition_span=SPAN)
    base_launches = launches.total() - base0
    assert _vmap(base) == fault_free

    t0 = launches.total()
    rep = sweep.verify_model(
        _net(), _cfg(tmp_path, "t", inject_faults=("launch.submit:transient:2",)),
        model_name="m", resume=False, partition_span=SPAN)
    fault_launches = launches.total() - t0
    # Bit-identical verdicts, no degradation, and the transient fault cost
    # at most max_launch_retries extra launches (acceptance criterion).
    assert _vmap(rep) == fault_free
    assert rep.degraded == 0
    retries = metrics_mod.registry().counter("launch_retries").total()
    assert 1 <= retries <= rep.partitions_total
    assert fault_launches - base_launches <= _cfg(tmp_path, "x").max_launch_retries


@pytest.mark.parametrize("spec,site", [
    ("launch.submit:transient:2+", "launch.submit"),
    ("launch.submit:fatal:2", "launch.submit"),
    ("launch.decode:transient:2+", "launch.decode"),
    ("launch.decode:fatal:3", "launch.decode"),
])
def test_exhausted_or_fatal_fault_degrades_then_resume_converges(
        tmp_path, fault_free, spec, site):
    cfg = _cfg(tmp_path, "c", inject_faults=(spec,))
    rep = sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                             partition_span=SPAN)
    got = _vmap(rep)
    # Clause 1: no crash.  Clause 2: decided verdicts match the fault-free
    # run exactly; faulted partitions are UNKNOWN with a machine-readable
    # reason in the ledger.
    assert rep.degraded > 0
    assert all(got[k] == fault_free[k] for k in got if got[k] != "unknown")
    failures = [r["failure"] for r in _ledger_records(cfg)
                if r.get("failure")]
    assert len(failures) == rep.degraded
    assert all(f["site"] == site and ":" in f["reason"] for f in failures)
    assert metrics_mod.registry().counter("chunks_degraded").total() >= 1
    # Clause 3: resume (faults disarmed) converges to the fault-free map,
    # and the degraded records do NOT satisfy resume (they re-run).
    resumed = sweep.verify_model(
        _net(), cfg.with_(inject_faults=()), model_name="m", resume=True,
        partition_span=SPAN)
    assert _vmap(resumed) == fault_free
    assert resumed.degraded == 0


def test_crash_mid_drain_then_resume_converges(tmp_path, fault_free):
    cfg = _cfg(tmp_path, "crash", inject_faults=("launch.decode:crash:2",))
    with pytest.raises(faults.InjectedFault):
        sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                           partition_span=SPAN)
    resumed = sweep.verify_model(
        _net(), cfg.with_(inject_faults=()), model_name="m", resume=True,
        partition_span=SPAN)
    assert _vmap(resumed) == fault_free


def test_compile_fault_falls_back_verdicts_unchanged(tmp_path):
    # Fresh architecture + chunk size => this test owns its compile cache
    # misses, so the armed compile faults actually fire.
    net = init_mlp((20, 7, 1), seed=5)
    span = (0, 24)
    fallbacks = metrics_mod.registry().counter("xla_compile_fallbacks")
    f0 = fallbacks.total()
    faulted = sweep.verify_model(
        net, _cfg(tmp_path, "cf", grid_chunk=12,
                  inject_faults=("compile:transient:1+",)),
        model_name="m", resume=False, partition_span=span)
    assert fallbacks.total() > f0  # the AOT path degraded to plain jit...
    clean = sweep.verify_model(
        net, _cfg(tmp_path, "cc", grid_chunk=12), model_name="m",
        resume=False, partition_span=span)
    # ...and results never changed: same verdict map, nothing degraded.
    assert {o.partition_id: o.verdict for o in faulted.outcomes} == \
        {o.partition_id: o.verdict for o in clean.outcomes}
    assert faulted.degraded == 0


def test_ledger_append_exhaustion_keeps_run_alive(tmp_path, fault_free):
    cfg = _cfg(tmp_path, "led", inject_faults=("ledger.append:transient:1+",))
    rep = sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                             partition_span=SPAN)
    # Every verdict is still reported (the in-memory report is complete and
    # correct); only persistence was lost, and that is counted.
    assert _vmap(rep) == fault_free
    assert metrics_mod.registry().counter("ledger_append_failures").total() > 0
    assert len(_ledger_records(cfg)) < rep.partitions_total
    # Resume re-decides the unpersisted partitions and converges.
    resumed = sweep.verify_model(
        _net(), cfg.with_(inject_faults=()), model_name="m", resume=True,
        partition_span=SPAN)
    assert _vmap(resumed) == fault_free


# ---------------------------------------------------------------------------
# ledger loading: torn lines counted, decided-wins merge, degraded not settled
# ---------------------------------------------------------------------------


def test_load_ledger_counts_torn_lines_and_reports(tmp_path, fault_free):
    cfg = _cfg(tmp_path, "torn")
    rep = sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                             partition_span=SPAN)
    path = os.path.join(cfg.result_dir,
                        f"{cfg.name}-m@{SPAN[0]}-{SPAN[1]}.ledger.jsonl")
    with open(path, "a") as fp:
        fp.write('{"partition_id": 999, "verd')  # the torn tail of a crash
    resumed = sweep.verify_model(_net(), cfg, model_name="m", resume=True,
                                 partition_span=SPAN)
    assert resumed.ledger_skipped_lines == 1
    assert _vmap(resumed) == _vmap(rep)


def test_merge_ledgers_decided_wins_and_degraded_not_settled(tmp_path):
    p1 = str(tmp_path / "a.ledger.jsonl")
    p2 = str(tmp_path / "b.ledger.jsonl")
    fail = {"reason": "launch.submit:fatal", "site": "launch.submit",
            "kind": "fatal", "error": "X", "detail": "", "retries": 0}
    with open(p1, "w") as fp:
        fp.write(json.dumps({"partition_id": 1, "verdict": "unsat"}) + "\n")
        fp.write(json.dumps({"partition_id": 2, "verdict": "unknown"}) + "\n")
        fp.write(json.dumps({"partition_id": 3, "verdict": "unknown",
                             "failure": fail}) + "\n")
    with open(p2, "w") as fp:
        # a later budget-cut unknown must never demote the decided pid 1
        fp.write(json.dumps({"partition_id": 1, "verdict": "unknown"}) + "\n")
        # a decided record settles a previously-degraded pid
        fp.write(json.dumps({"partition_id": 3, "verdict": "sat",
                             "ce": None}) + "\n")
        fp.write('{"partition_id": 9, "verd\n')  # torn mid-append
    done, degraded, skipped = sweep.merge_ledgers([p1, p2])
    assert done[1]["verdict"] == "unsat"
    assert done[2]["verdict"] == "unknown"  # plain budget UNKNOWN is settled
    assert done[3]["verdict"] == "sat" and 3 not in degraded
    assert skipped == 1
    # degraded-only pid: not settled
    with open(p2, "a") as fp:
        fp.write(json.dumps({"partition_id": 4, "verdict": "unknown",
                             "failure": fail}) + "\n")
    done, degraded, _ = sweep.merge_ledgers([p1, p2])
    assert 4 in degraded and 4 not in done


# ---------------------------------------------------------------------------
# surfacing: heartbeat counters, report degradation table, smt reasons
# ---------------------------------------------------------------------------


def test_heartbeat_line_carries_retry_and_degraded_counters():
    import io

    from fairify_tpu.obs.heartbeat import Heartbeat

    out = io.StringIO()
    hb = Heartbeat(1000.0, total=10, label="X", stream=out)
    hb.beat(decided=1, attempted=1, unknown=0, force=True)
    assert "retries=" not in out.getvalue()  # healthy: zero-noise
    metrics_mod.registry().counter("launch_retries").inc(site="launch.submit")
    metrics_mod.registry().counter("chunks_degraded").inc(n=2, site="bab")
    hb.beat(decided=2, attempted=2, unknown=0, force=True)
    assert "| retries=1 degraded=2" in out.getvalue()
    hb.close()


def test_report_renders_degradation_table_from_ledger(tmp_path, capsys):
    from fairify_tpu.obs import report as report_mod

    path = str(tmp_path / "GC-m.ledger.jsonl")
    fail = {"reason": "launch.decode:transient-exhausted",
            "site": "launch.decode", "kind": "transient-exhausted",
            "error": "OSError", "detail": "", "retries": 2}
    with open(path, "w") as fp:
        fp.write(json.dumps({"partition_id": 1, "verdict": "unsat"}) + "\n")
        for pid in (2, 3):
            fp.write(json.dumps({"partition_id": pid, "verdict": "unknown",
                                 "failure": fail}) + "\n")
    agg = report_mod.aggregate([path])
    assert agg["degraded"] == {"launch.decode:transient-exhausted": 2}
    assert agg["verdicts"] == {"sat": 0, "unsat": 1, "unknown": 2}
    assert report_mod.main([path]) == 0
    text = capsys.readouterr().out
    assert "degradation reason" in text
    assert "launch.decode:transient-exhausted" in text


def test_smt_retry_ladder_wired_into_unknown_retry(tmp_path, monkeypatch):
    """cfg.smt_retry_timeouts_s reaches the worker pool's dispatch from the
    sweep's UNKNOWN-retry path (stubbed pool fan-out — the wiring is what's
    pinned; the pool itself is covered by tests/test_smt_pool.py)."""
    from concurrent.futures import Future

    from fairify_tpu.smt import pool as pool_mod
    from fairify_tpu.verify import engine as engine_mod

    span = (0, 16)

    def dull_decode(host, ctx, stats=None):  # stage 0 decides nothing
        n = ctx["n"]
        return np.zeros(n, bool), np.zeros(n, bool), {}

    def unknown_many(net, enc, rlo, rhi, cfg, **kw):
        return [engine_mod.Decision("unknown") for _ in range(rlo.shape[0])]

    calls = []

    def fake_submit(pool, net, enc, lo, hi, soft_timeout_s=100.0,
                    retry_timeouts_s=()):
        calls.append((soft_timeout_s, tuple(retry_timeouts_s)))
        fut = Future()
        fut.set_result(pool_mod.SmtResult("unsat", None, None))
        return fut

    monkeypatch.setattr(sweep, "_stage0_block_decode", dull_decode)
    monkeypatch.setattr(engine_mod, "decide_many", unknown_many)
    monkeypatch.setattr(engine_mod, "decide_box",
                        lambda *a, **k: engine_mod.Decision("unknown"))
    monkeypatch.setattr(pool_mod, "submit_box", fake_submit)
    # mega_chunks=0: the dull-stage-0 stub patches the chunk loop's decode.
    cfg = _cfg(tmp_path, "smt", smt_retry_timeouts_s=(7.0, 21.0),
               mega_chunks=0,
               engine=engine_mod.EngineConfig(pgd_phase=False))
    rep = sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                             partition_span=span)
    assert calls and all(c == (cfg.soft_timeout_s, (7.0, 21.0))
                         for c in calls)
    assert len(calls) == rep.partitions_total  # parallel fan-out: one
    # query per still-unknown root, submitted up front
    assert rep.counts["unsat"] == rep.partitions_total  # SMT tier decided


def _smt_toy_cfg(tmp_path, name, **kw):
    """GC preset shrunk to a tiny grid of brute-solvable boxes with the
    SMT worker pool enabled (mirrors tests/test_smt_pool.py; workers=1 so
    dispatch arrival order — and therefore nth-based chaos schedules —
    is deterministic)."""
    from fairify_tpu.data.domains import get_domain
    from fairify_tpu.verify.engine import EngineConfig

    ov = {c: (0, 0) for c in get_domain("german").columns}
    ov["age"] = (0, 1)
    ov["month"] = (0, 5)
    ov["purpose"] = (0, 5)
    ov["credit_amount"] = (0, 2)
    kw.setdefault("smt_retry_timeouts_s", (10.0,))
    kw.setdefault("engine", EngineConfig(pgd_phase=False))
    return presets.get("GC").with_(
        result_dir=str(tmp_path / name), soft_timeout_s=10.0,
        hard_timeout_s=600.0, sim_size=16, exact_certify_masks=False,
        grid_chunk=8, launch_backoff_s=1e-4, max_launch_retries=1,
        domain_overrides=ov, partition_threshold=2, smt_workers=1, **kw)


def _all_unknown_engine(monkeypatch):
    """Stage 0 + BaB decide nothing, so every partition reaches the pool
    (the real stage 0 certifies tiny boxes outright)."""
    from fairify_tpu.verify import engine as engine_mod

    def dull_decode(host, ctx, stats=None):
        n = ctx["n"]
        return np.zeros(n, bool), np.zeros(n, bool), {}

    monkeypatch.setattr(sweep, "_stage0_block_decode", dull_decode)
    monkeypatch.setattr(
        engine_mod, "decide_many",
        lambda net, enc, rlo, rhi, cfg, **kw: [
            engine_mod.Decision("unknown") for _ in range(rlo.shape[0])])
    monkeypatch.setattr(engine_mod, "decide_box",
                        lambda *a, **k: engine_mod.Decision("unknown"))


SMT_SPAN = (0, 8)


def test_smt_worker_crash_degrades_not_crashes_and_resumes(
        tmp_path, monkeypatch):
    """The §14 chaos invariant at the sweep level: with every SMT dispatch
    killing its worker, verify_model never crashes — exactly the affected
    partitions degrade to UNKNOWN with a machine-readable
    ``smt.worker:crash`` failure record, and a disarmed resume=True pass
    re-attempts exactly those and converges to the fault-free map.
    (Pool-vs-in-process verdict parity is pinned in tests/test_smt_pool.py,
    z3-gated where the in-process backend needs the solver.)"""
    _all_unknown_engine(monkeypatch)
    net = init_mlp((20, 4, 1), seed=3)
    base = sweep.verify_model(
        net, _smt_toy_cfg(tmp_path, "b"), model_name="m", resume=False,
        partition_span=SMT_SPAN)
    want = {o.partition_id: o.verdict for o in base.outcomes}
    assert set(want.values()) <= {"sat", "unsat"}  # the pool decided all

    cfg = _smt_toy_cfg(
        tmp_path, "f", inject_faults=("smt.worker.crash:transient:2+",))
    rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                             partition_span=SMT_SPAN)
    got = {o.partition_id: o.verdict for o in rep.outcomes}
    assert rep.degraded > 0
    assert all(want[k] == v for k, v in got.items() if v != "unknown")
    led = sweep._ledger_path(cfg, rep.sink_name)
    with open(led) as fp:
        recs = [json.loads(line) for line in fp if line.strip()]
    reasons = {r["failure"]["reason"] for r in recs if r.get("failure")}
    assert reasons == {"smt.worker:crash"}

    resumed = sweep.verify_model(
        net, cfg.with_(inject_faults=()), model_name="m", resume=True,
        partition_span=SMT_SPAN)
    assert {o.partition_id: o.verdict for o in resumed.outcomes} == want


def test_smt_worker_transient_fault_absorbed(tmp_path, monkeypatch):
    """One worker death (crash:transient:2, a single arrival) is absorbed
    by the fresh-worker retry: the verdict map is IDENTICAL to the
    fault-free run and nothing degrades."""
    _all_unknown_engine(monkeypatch)
    net = init_mlp((20, 4, 1), seed=3)
    base = sweep.verify_model(
        net, _smt_toy_cfg(tmp_path, "b"), model_name="m", resume=False,
        partition_span=SMT_SPAN)
    want = {o.partition_id: o.verdict for o in base.outcomes}
    rep = sweep.verify_model(
        net, _smt_toy_cfg(tmp_path, "t",
                          inject_faults=("smt.worker.crash:transient:2",)),
        model_name="m", resume=False, partition_span=SMT_SPAN)
    assert rep.degraded == 0
    assert {o.partition_id: o.verdict for o in rep.outcomes} == want
    assert metrics_mod.registry().counter("smt_worker_crashes").total() >= 1


def test_parity_fault_never_demotes_stage0_verdicts(tmp_path, fault_free):
    """A fault confined to the parity pass (a metrics-only kernel) keeps
    every stage-0-decided verdict; only still-undecided partitions degrade."""
    # Arrivals on this config: 3 stage-0 chunk launches, then parity — so
    # 4+ faults every launch from the first parity block onward.
    cfg = _cfg(tmp_path, "par", inject_faults=("launch.submit:transient:4+",))
    rep = sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                             partition_span=SPAN)
    got = _vmap(rep)
    decided = {k: v for k, v in got.items() if v != "unknown"}
    assert decided  # stage 0's verdicts survived the parity-phase fault
    assert all(fault_free[k] == v for k, v in decided.items())


def test_smt_unknown_reason_codes():
    from fairify_tpu.verify import smt

    assert smt._unknown_reason("timeout") == "timeout"
    assert smt._unknown_reason("canceled") == "timeout"
    # Memory/resource exhaustion is NOT a timeout: the escalating-timeout
    # ladder must skip it (a bigger time budget only OOMs harder) — the
    # pool's higher-RSS-cap retry is the sanctioned second attempt.
    assert smt._unknown_reason("max. resource limit exceeded") == "memout"
    assert smt._unknown_reason("memout") == "memout"
    assert smt._unknown_reason("out of memory") == "memout"
    assert smt._unknown_reason("(incomplete (theory arithmetic))") == \
        "solver-error"
    assert smt._unknown_reason("") == "solver-error"


def test_smt_injected_fault_maps_to_unknown_reason():
    from fairify_tpu.verify import smt

    if not smt.HAVE_Z3:
        pytest.skip("z3-solver not installed")
    from fairify_tpu.data.domains import DomainSpec
    from fairify_tpu.verify import property as prop
    from fairify_tpu.models import mlp

    rng = np.random.default_rng(0)
    dom = DomainSpec(name="toy", label="y",
                     ranges={"pa": (0, 1), "a": (0, 3), "b": (0, 3)})
    q = prop.FairnessQuery(domain=dom, protected=("pa",))
    enc = prop.encode(q)
    lo, hi = q.domain.lo_hi()
    net = mlp.from_numpy(
        [rng.normal(size=(3, 4)).astype(np.float32),
         rng.normal(size=(4, 1)).astype(np.float32)],
        [np.zeros(4, np.float32), np.zeros(1, np.float32)])
    with faults.armed(["smt.query:transient:1+"]):
        verdict, ce, reason = smt.decide_box_smt(
            net, enc, lo.astype(np.int64), hi.astype(np.int64),
            soft_timeout_s=5.0, retry_timeouts_s=(5.0,))
    assert (verdict, ce, reason) == ("unknown", None, "injected")


# ---------------------------------------------------------------------------
# sharded sweeps: per-shard fault domains + elastic re-sharding
# ---------------------------------------------------------------------------


def _sharded(tmp_path, name, spec=None, resume=False, **kw):
    from fairify_tpu.parallel import shards as shards_mod

    cfg = _cfg(tmp_path, name,
               **({"inject_faults": (spec,)} if spec else {}))
    return cfg, shards_mod.sweep_sharded(
        _net(), cfg, model_name="m", n_shards=3, partition_span=SPAN,
        resume=resume, **kw)


def test_sharded_fault_free_matches_plain(tmp_path, fault_free):
    """Cross-path pin: the sharded runtime (submeshed stage 0, per-shard
    journals) reproduces the single-chip verdict map bit-equal."""
    _cfg_, rep = _sharded(tmp_path, "sh_ff")
    assert _vmap(rep) == fault_free
    assert rep.degraded == 0


def test_device_lost_fatal_reshards_and_converges(tmp_path, fault_free):
    """Killing shard 1's device group mid-sweep: the group is quarantined,
    its span elastically re-shards onto the 5 survivors, and the FULL
    verdict map still equals fault-free — no resume pass needed."""
    import jax

    cfg, rep = _sharded(tmp_path, "sh_dl", spec="device.lost:fatal:2")
    assert _vmap(rep) == fault_free
    assert rep.degraded == 0
    assert metrics_mod.registry().counter("shard_failures").value(
        site="device.lost", kind="fatal") == 1
    # The mesh_size gauge tracks the surviving fleet: 8 minus the lost
    # 3-device group of shard index 1 (groups split 3/3/2).
    assert metrics_mod.registry().gauge("mesh_size").value() \
        == len(jax.devices()) - 3


def test_device_lost_transient_absorbed(tmp_path, fault_free):
    """A transient device.lost (link blip) is absorbed by the shard
    supervisor's retry: identical map, nothing degraded, no quarantine —
    and sweep_sharded never raises (acceptance clause)."""
    _cfg_, rep = _sharded(tmp_path, "sh_tr", spec="device.lost:transient:2")
    assert _vmap(rep) == fault_free
    assert rep.degraded == 0
    assert metrics_mod.registry().counter("shard_failures").total() == 0


def test_all_devices_lost_degrades_then_resume_converges(tmp_path, fault_free):
    """Every dispatch loses its device group: all partitions are ledgered
    UNKNOWN with a machine-readable device.lost failure (carrying the shard
    index), and resume=True on a healthy fleet re-attempts exactly those."""
    cfg, rep = _sharded(tmp_path, "sh_all", spec="device.lost:fatal:1+")
    got = _vmap(rep)
    assert set(got.values()) == {"unknown"}
    assert rep.degraded == rep.partitions_total == SPAN[1] - SPAN[0]
    n_failures = 0
    for k, (s, e) in enumerate(((0, 16), (16, 32), (32, 48))):
        path = os.path.join(cfg.result_dir,
                            f"{cfg.name}-m@{s}-{e}.ledger.jsonl")
        with open(path) as fp:
            failures = [json.loads(l)["failure"] for l in fp if l.strip()]
        n_failures += len(failures)
        assert all(f["reason"] == "device.lost:fatal" for f in failures)
        # Failure attribution is per lineage, not whichever shard failed
        # LAST: all three initial dispatches fail in round 0 (indices
        # 0/1/2 in span order), so span k's records carry shard=k.
        assert {f.get("shard") for f in failures} == {k}
    assert n_failures == rep.partitions_total

    from fairify_tpu.parallel import shards as shards_mod

    resumed = shards_mod.sweep_sharded(
        _net(), cfg.with_(inject_faults=()), model_name="m", n_shards=3,
        partition_span=SPAN, resume=True)
    assert _vmap(resumed) == fault_free
    assert resumed.degraded == 0


def test_device_lost_mega_segments_blast_radius_then_mega_resume(
        tmp_path, fault_free):
    """device.lost × mega-loop segments (ISSUE 19 coverage gap: PR 7's
    shard chaos predates PR 14's mega-loop).  A multi-chunk-segment mega
    config dispatched through the shard runtime runs the per-chunk loop
    (meshes disable ``_use_mega``) and must still match the plain mega
    map bit-equal; a device lost mid-sweep — with every re-shard landing
    on hardware that dies too — degrades EXACTLY the lost shard's span
    (the other shard's decided verdicts survive untouched), and a plain
    ``resume=True`` over that span's journal rides the MEGA path to
    convergence."""
    import jax

    from fairify_tpu.parallel import shards as shards_mod

    cfg = _cfg(tmp_path, "mega_dl", mega_chunks=2,
               inject_faults=("device.lost:fatal:2+",))
    rep = shards_mod.sweep_sharded(
        _net(), cfg, model_name="m", n_shards=2,
        devices=list(jax.devices())[:2], partition_span=SPAN, resume=False)
    got = _vmap(rep)
    # Spans split (0, 32) / (32, 48) at chunk boundaries: arrival 1
    # (shard 0) succeeds, arrival 2 kills shard 1's device, and the
    # re-shard onto the survivor dies at arrival 3 — no survivors, so
    # exactly shard 1's 16 partitions degrade.
    assert rep.degraded == 16
    assert all(got[p] == fault_free[p] for p in range(1, 33))
    assert all(got[p] == "unknown" for p in range(33, 49))
    assert metrics_mod.registry().counter("shard_failures").value(
        site="device.lost", kind="fatal") >= 1
    path = os.path.join(cfg.result_dir, f"{cfg.name}-m@32-48.ledger.jsonl")
    with open(path) as fp:
        failures = [json.loads(l)["failure"] for l in fp
                    if l.strip() and json.loads(l).get("failure")]
    assert failures and all(f["reason"] == "device.lost:fatal"
                            for f in failures)
    # Disarmed plain resume over the lost span: mesh=None + mega_chunks=2
    # takes the mega segment loop over the SHARD's journal (same
    # ``m@32-48`` sink) and re-attempts exactly the degraded partitions.
    resumed = sweep.verify_model(
        _net(), cfg.with_(inject_faults=()), model_name="m", resume=True,
        partition_span=(32, 48))
    rmap = _vmap(resumed)
    assert resumed.degraded == 0
    assert rmap == {p: fault_free[p] for p in range(33, 49)}


@pytest.mark.parametrize("spec", [
    "shard.dispatch:fatal:1",
    "shard.gather:transient:1",
])
def test_shard_site_faults_never_lose_verdicts(tmp_path, fault_free, spec):
    """A fatal dispatch fault re-shards (same map); a transient gather
    fault retries the shard with resume=True, replaying — not recomputing —
    its already-ledgered verdicts."""
    _cfg_, rep = _sharded(tmp_path, "sh_site", spec=spec)
    assert _vmap(rep) == fault_free
    assert rep.degraded == 0


def test_merge_ledgers_across_interleaved_shard_journals(tmp_path):
    """Cross-shard decided-wins: interleaved per-shard journals (a failed
    attempt's partial records + the re-shard's re-decisions) merge to one
    settled map, and torn lines are counted across ALL shard files."""
    fail = {"reason": "device.lost:fatal", "site": "device.lost",
            "kind": "fatal", "error": "DeviceLostError", "detail": "",
            "retries": 0, "shard": 1}
    paths = []
    for k, recs in enumerate((
            [{"partition_id": 1, "verdict": "unsat"},
             {"partition_id": 2, "verdict": "unknown", "failure": fail}],
            [{"partition_id": 17, "verdict": "unknown", "failure": fail},
             {"partition_id": 17, "verdict": "sat", "ce": None}],
            [{"partition_id": 33, "verdict": "unknown"}])):
        p = str(tmp_path / f"GC-m@{k * 16}-{(k + 1) * 16}.ledger.jsonl")
        with open(p, "w") as fp:
            for rec in recs:
                fp.write(json.dumps(rec) + "\n")
        paths.append(p)
    with open(paths[0], "a") as fp:
        fp.write('{"partition_id": 3, "verd')  # torn mid-append
    with open(paths[2], "a") as fp:
        fp.write('{"partition_id": 34, "ver')  # torn in another shard
    done, degraded, skipped = sweep.merge_ledgers(paths)
    assert done[1]["verdict"] == "unsat"
    assert 2 in degraded and 2 not in done      # loss: not settled
    assert done[17]["verdict"] == "sat"         # re-shard re-decision wins
    assert 17 not in degraded
    assert done[33]["verdict"] == "unknown"     # budget UNKNOWN: settled
    assert skipped == 2                         # torn lines sum across files


def test_report_renders_per_shard_table(tmp_path, capsys):
    """Shard journals passed to `fairify_tpu report` produce the per-shard
    degradation table: span-labelled rows with verdict + degraded counts."""
    from fairify_tpu.obs import report as report_mod

    fail = {"reason": "device.lost:fatal", "site": "device.lost",
            "kind": "fatal", "error": "DeviceLostError", "detail": "",
            "retries": 0, "shard": 2}
    p1 = str(tmp_path / "GC-m@0-16.ledger.jsonl")
    p2 = str(tmp_path / "GC-m@16-32.ledger.jsonl")
    with open(p1, "w") as fp:
        fp.write(json.dumps({"partition_id": 1, "verdict": "unsat"}) + "\n")
        fp.write(json.dumps({"partition_id": 2, "verdict": "sat"}) + "\n")
    with open(p2, "w") as fp:
        for pid in (17, 18):
            fp.write(json.dumps({"partition_id": pid, "verdict": "unknown",
                                 "failure": fail}) + "\n")
    agg = report_mod.aggregate([p1, p2])
    assert agg["shards"] == {
        "GC-m@0-16": {"sat": 1, "unsat": 1, "unknown": 0, "degraded": 0},
        "GC-m@16-32": {"sat": 0, "unsat": 0, "unknown": 2, "degraded": 2}}
    assert agg["degraded"] == {"device.lost:fatal": 2}
    assert report_mod.main([p1, p2]) == 0
    text = capsys.readouterr().out
    assert "shard" in text and "GC-m@16-32" in text


# ---------------------------------------------------------------------------
# lint: bare-except / swallowed-BaseException rule
# ---------------------------------------------------------------------------


def _broad_except_findings(tmp_path, src):
    from fairify_tpu.lint import core as lint_core
    from fairify_tpu.lint.rules_obs import BroadExceptRule

    p = tmp_path / "bad.py"
    p.write_text(src)
    result = lint_core.run_lint(rules=[BroadExceptRule()],
                                files=[(str(p), "fairify_tpu/bad.py")])
    return result.findings


def test_lint_flags_silent_broad_excepts(tmp_path):
    findings = _broad_except_findings(tmp_path, (
        "def a():\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"            # bare: flagged
        "        pass\n"
        "def b():\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException:\n"  # swallowed BaseException: flagged
        "        x = 1\n"
        "def c():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"      # re-raises: fine
        "        raise\n"
        "def d():\n"
        "    try:\n"
        "        pass\n"
        "    except ValueError:\n"     # narrow: fine
        "        pass\n"))
    assert sorted(f.line for f in findings) == [4, 9]
    assert all("except" in f.message for f in findings)


def test_lint_base_exception_needs_propagate_reraise(tmp_path):
    """The strict tier: a BaseException handler with SOME raise still
    fails unless the propagate class specifically escapes — either an
    unconditional re-raise or the `classify(exc) == "propagate"` guard
    (KeyboardInterrupt/SystemExit/ReplicaKilled must never be converted
    into a degradation)."""
    findings = _broad_except_findings(tmp_path, (
        "from fairify_tpu.resilience.supervisor import classify\n"
        "def bad_converts_everything():\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException as exc:\n"      # line 5: flagged
        "        raise RuntimeError('wrapped') from exc\n"
        "def good_guard():\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException as exc:\n"
        "        if classify(exc) == 'propagate':\n"
        "            raise\n"
        "        x = 1\n"
        "def good_guard_via_variable():\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException as exc:\n"
        "        cls = classify(exc)\n"
        "        if cls == 'propagate':\n"
        "            raise\n"
        "def good_isinstance():\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException as exc:\n"
        "        if isinstance(exc, (KeyboardInterrupt, SystemExit)):\n"
        "            raise\n"
        "def good_unconditional():\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException:\n"
        "        x = 2\n"
        "        raise\n"))
    assert [f.line for f in findings] == [5]
    assert "propagate" in findings[0].message


def test_classify_replica_killed_is_propagate():
    """The fleet's cooperative kill is the thread analog of SIGKILL: no
    supervisor/handler may convert it into a retry or degradation."""
    from fairify_tpu.resilience.supervisor import classify
    from fairify_tpu.serve.server import ReplicaKilled

    assert classify(ReplicaKilled()) == "propagate"


def test_lint_clean_on_current_tree():
    from fairify_tpu.lint import core as lint_core
    from fairify_tpu.lint.rules_obs import BroadExceptRule

    result = lint_core.run_lint(rules=[BroadExceptRule()])
    assert not result.findings and not result.parse_errors


def test_lint_base_exception_guard_polarity_and_bare_raise(tmp_path):
    """Review hardening: the guard must be POSITIVE and the raise BARE —
    an inverted guard falls through on kills, and `raise Other(...) from
    exc` converts them."""
    findings = _broad_except_findings(tmp_path, (
        "from fairify_tpu.resilience.supervisor import classify\n"
        "def bad_inverted_guard():\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException as exc:\n"      # line 5: flagged
        "        if classify(exc) != 'propagate':\n"
        "            raise RuntimeError('x') from exc\n"
        "def bad_converted_reraise():\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException as exc:\n"      # line 11: flagged
        "        if classify(exc) == 'propagate':\n"
        "            raise RuntimeError('x') from exc\n"
        "def bad_not_isinstance():\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException as exc:\n"      # line 17: flagged
        "        if not isinstance(exc, KeyboardInterrupt):\n"
        "            raise ValueError('x')\n"))
    assert [f.line for f in findings] == [5, 11, 17]


def test_classify_replica_killed_subclass_is_propagate():
    """isinstance semantics survive the import-light name matching: a
    ReplicaKilled SUBCLASS raised at a yield point is still a kill."""
    from fairify_tpu.resilience.supervisor import classify
    from fairify_tpu.serve.server import ReplicaKilled

    class ReplicaPreempted(ReplicaKilled):
        pass

    assert classify(ReplicaPreempted()) == "propagate"

"""Async launch pipeline: depth semantics, verdict-map invariance, crash
safety.

The pipeline (``parallel/pipeline.py``) changes only WHEN chunk results are
fetched — never which kernels run or with which seeds — so the decided/
UNSAT/SAT sets and every witness triple must be bit-identical across
``pipeline_depth``.  And because the ledger is written only after stage-0
results are drained, a run killed with chunks still in flight must never
have ledgered an undrained chunk as decided.
"""
import os

import numpy as np
import pytest

from fairify_tpu.models.train import init_mlp
from fairify_tpu.parallel.pipeline import FlightStats, LaunchPipeline
from fairify_tpu.verify import presets, sweep


# ---------------------------------------------------------------------------
# LaunchPipeline unit semantics (no jax needed beyond device_get on numpy)
# ---------------------------------------------------------------------------


def test_pipeline_fifo_and_depth_bound():
    pipe = LaunchPipeline(depth=2)
    out = []
    at_dispatch = []

    def launch(i):
        # Invariant at dispatch time: room was made BEFORE fn() ran, so at
        # most depth-1 older launches are still in flight.
        at_dispatch.append(len(pipe))
        return {"v": np.array([i])}, {"i": i}

    for i in range(5):
        for meta, ctx, host in pipe.submit(lambda i=i: launch(i), meta=i):
            out.append((meta, ctx["i"], int(host["v"][0])))
        assert len(pipe) <= 2
    for meta, ctx, host in pipe.drain():
        out.append((meta, ctx["i"], int(host["v"][0])))
    # FIFO: drained in submission order, payload/ctx/meta stay aligned.
    assert [m for m, _, _ in out] == list(range(5))
    assert all(m == c == v for m, c, v in out)
    assert at_dispatch == [0, 1, 1, 1, 1]  # 2 in flight after each dispatch
    assert pipe.stats.max == 2


def test_pipeline_depth1_is_synchronous():
    pipe = LaunchPipeline(depth=1)
    at_dispatch = []

    def launch(i):
        at_dispatch.append(len(pipe))
        return np.array([i]), None

    drained = []
    for i in range(3):
        drained += [meta for meta, _, _ in
                    pipe.submit(lambda i=i: launch(i), meta=i)]
    drained += [meta for meta, _, _ in pipe.drain()]
    # Strict alternation: the queue is empty at every dispatch — each
    # launch was fetched before the next one went out (the pre-pipeline
    # execution order), and at most one launch ever existed at a time.
    assert at_dispatch == [0, 0, 0]
    assert drained == [0, 1, 2]
    assert pipe.stats.max == 1


def test_flight_stats_time_weighted_mean():
    t = {"now": 0.0}
    st = FlightStats(clock=lambda: t["now"])
    st.update(1)          # depth 1 for 2s
    t["now"] = 2.0
    st.update(2)          # depth 2 for 2s
    t["now"] = 4.0
    st.update(0)
    assert st.max == 2
    assert st.summary()["mean"] == pytest.approx((1 * 2 + 2 * 2) / 4.0)


# ---------------------------------------------------------------------------
# Verdict-map invariance across pipeline_depth
# ---------------------------------------------------------------------------


def _outcome_map(report):
    out = {}
    for o in report.outcomes:
        ce = None
        if o.counterexample is not None:
            ce = (tuple(int(v) for v in o.counterexample[0]),
                  tuple(int(v) for v in o.counterexample[1]))
        out[o.partition_id] = (o.verdict, ce)
    return out


def test_sweep_verdicts_pipeline_depth_invariant(tmp_path):
    cfg = presets.get("GC").with_(
        soft_timeout_s=30.0, hard_timeout_s=300.0, sim_size=64,
        exact_certify_masks=False, grid_chunk=16)
    net = init_mlp((20, 8, 1), seed=3)
    span = (0, 48)  # 3 chunks of 16 — enough to overlap, cheap enough for CI
    maps = {}
    for depth in (1, 2, 4):
        rep = sweep.verify_model(
            net, cfg.with_(result_dir=str(tmp_path / f"d{depth}"),
                           pipeline_depth=depth),
            model_name="m", resume=False, partition_span=span)
        maps[depth] = _outcome_map(rep)
    assert maps[1], "span produced no outcomes"
    # Bit-identical decided/UNSAT/SAT sets AND witness triples at any depth.
    assert maps[1] == maps[2] == maps[4]


def test_stage0_families_matches_per_family(tmp_path):
    from fairify_tpu.parallel.mesh import stack_models
    from fairify_tpu.verify.property import encode

    cfg = presets.get("GC").with_(grid_chunk=16)
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)
    lo, hi = lo[:32], hi[:32]
    stacks = [stack_models([init_mlp((20, 8, 1), seed=s)
                            for s in (0, 1)]),
              stack_models([init_mlp((20, 6, 1), seed=s)
                            for s in (2, 3, 4)])]
    # One shared pipeline across both architecture groups...
    shared = sweep.stage0_families(stacks, enc, lo, hi, cfg)
    # ...must equal each family swept alone.
    for st, got in zip(stacks, shared):
        want = sweep._stage0_family(st, enc, lo, hi, cfg)
        assert len(got) == len(want)
        for (u_g, s_g, w_g), (u_w, s_w, w_w) in zip(got, want):
            np.testing.assert_array_equal(u_g, u_w)
            np.testing.assert_array_equal(s_g, s_w)
            assert set(w_g) == set(w_w)
            for k in w_g:
                np.testing.assert_array_equal(w_g[k][0], w_w[k][0])
                np.testing.assert_array_equal(w_g[k][1], w_w[k][1])


# ---------------------------------------------------------------------------
# Crash safety: in-flight chunks never reach the ledger
# ---------------------------------------------------------------------------


def test_crash_with_inflight_chunks_never_ledgers_undrained(tmp_path, monkeypatch):
    # mega_chunks=0 pins the per-chunk launch loop: this test monkeypatches
    # its decode (the mega path's crash-safety twin lives in test_mega.py).
    cfg = presets.get("GC").with_(
        result_dir=str(tmp_path / "crash"), soft_timeout_s=30.0,
        hard_timeout_s=300.0, sim_size=64, exact_certify_masks=False,
        grid_chunk=16, pipeline_depth=2, mega_chunks=0)
    net = init_mlp((20, 8, 1), seed=3)
    span = (0, 48)

    real_decode = sweep._stage0_block_decode
    calls = {"n": 0}

    def dying_decode(host, ctx, stats=None):
        calls["n"] += 1
        if calls["n"] >= 2:  # die at the second drain — one chunk in flight
            raise RuntimeError("simulated crash mid-drain")
        return real_decode(host, ctx, stats)

    monkeypatch.setattr(sweep, "_stage0_block_decode", dying_decode)
    with pytest.raises(RuntimeError, match="mid-drain"):
        sweep.verify_model(net, cfg, model_name="m", resume=False,
                           partition_span=span)
    monkeypatch.setattr(sweep, "_stage0_block_decode", real_decode)

    # The crash hit while stage-0 chunks were still in flight: nothing may
    # have been ledgered as decided (the reporting loop runs only after the
    # full drain), so resume re-decides everything from scratch...
    ledger = tmp_path / "crash" / "GC-m@0-48.ledger.jsonl"
    assert not ledger.exists() or os.path.getsize(ledger) == 0

    # ...and the resumed run's verdict map equals an uninterrupted one.
    crashed = sweep.verify_model(net, cfg, model_name="m", resume=True,
                                 partition_span=span)
    clean = sweep.verify_model(
        net, cfg.with_(result_dir=str(tmp_path / "clean")),
        model_name="m", resume=False, partition_span=span)
    assert _outcome_map(crashed) == _outcome_map(clean)


# ---------------------------------------------------------------------------
# Throughput record carries the overlap gauge
# ---------------------------------------------------------------------------


def test_throughput_json_records_pipeline_gauge(tmp_path):
    import json

    # mega_chunks=0: the overlap pin needs ≥2 per-chunk launches in flight;
    # under the mega-loop this span is a single segment per phase.
    cfg = presets.get("GC").with_(
        result_dir=str(tmp_path), soft_timeout_s=30.0, hard_timeout_s=300.0,
        sim_size=64, exact_certify_masks=False, grid_chunk=16,
        pipeline_depth=2, mega_chunks=0)
    net = init_mlp((20, 8, 1), seed=3)
    sweep.verify_model(net, cfg, model_name="m", resume=False,
                       partition_span=(0, 48))
    with open(tmp_path / "GC-m@0-48.throughput.json") as fp:
        thr = json.load(fp)
    assert thr["pipeline_depth"] == 2
    # 3 chunks at depth 2 → the queue genuinely held 2 launches at once.
    assert thr["launches_in_flight_max"] >= 2
    assert 0.0 < thr["launches_in_flight_mean"] <= thr["launches_in_flight_max"]

"""Verification-funnel telemetry (DESIGN.md §20).

Tier-1 pins for the funnel contract:

* every partition lands in EXACTLY one terminal state — the state counts
  sum to the grid size and ``decided_fraction`` is their decided share;
* counts AND the stage-0 margin/gap histograms are bit-invariant across
  ``mega_chunks`` ∈ {0, 1, 4} × ``pipeline_depth`` ∈ {1, 2} (the mega
  loop carries the histograms in its ``lax.scan`` carry; the chunk loop
  buckets host-side under the same rule);
* a chaos-injected ``launch.submit`` exhaustion surfaces as
  ``unknown:failure:launch.submit`` — degradations are never folded into
  the generic unknown buckets;
* the device bucket rule is bit-identical to an independent NumPy
  recomputation (searchsorted semantics), edge values and padded rows
  included, and the non-negative margin mass equals the run's
  stage-0-certified population cross-checked against the ledger;
* ``fairify_tpu report --funnel`` renders the table from an event log;
* the budgeted ladder's unattempted tail is ``unknown:budget`` and its
  ``decided_fraction`` is measured over the FULL grid.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from fairify_tpu import obs
from fairify_tpu.models.train import init_mlp
from fairify_tpu.obs import funnel
from fairify_tpu.verify import presets, sweep


def _cfg(tmp_path, sub, **kw):
    return presets.get("GC").with_(
        result_dir=str(tmp_path / sub), soft_timeout_s=30.0,
        hard_timeout_s=300.0, sim_size=64, exact_certify_masks=False,
        grid_chunk=16, **kw)


def test_funnel_counts_sum_and_bit_invariant(tmp_path):
    """States sum to the grid size; states AND histograms are bit-equal
    across mega_chunks {0, 1, 4} x pipeline_depth {1, 2}."""
    net = init_mlp((20, 8, 1), seed=3)
    span = (0, 48)
    payloads = {}
    for mc in (0, 1, 4):
        for depth in (1, 2):
            cfg = _cfg(tmp_path, f"f_{mc}_{depth}", mega_chunks=mc,
                       pipeline_depth=depth)
            rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                                     partition_span=span)
            fun = rep.funnel
            assert fun is not None
            assert sum(fun["states"].values()) == fun["total"] == 48
            decided = sum(n for s, n in fun["states"].items()
                          if funnel.is_decided(s))
            assert fun["decided"] == decided
            assert fun["decided_fraction"] == pytest.approx(decided / 48.0)
            for state in fun["states"]:
                assert state.startswith("unknown:failure:") \
                    or state in funnel.STATES, state
            payloads[(mc, depth)] = fun
    ref = payloads[(0, 1)]
    for key, fun in payloads.items():
        assert fun["states"] == ref["states"], f"funnel drift at {key}"
        assert fun["margin_hist"] == ref["margin_hist"], f"hist drift at {key}"


def test_funnel_launch_submit_exhaustion(tmp_path):
    """Exhausting launch.submit on exactly one mega segment classifies
    that segment's 16 partitions as unknown:failure:launch.submit."""
    net = init_mlp((20, 8, 1), seed=3)
    span = (0, 48)
    # mega_chunks=1 -> 3 one-chunk segments per phase; max_launch_retries=2
    # means arrivals {2, 3, 4} are segment 2's attempt + both retries.
    cfg = _cfg(tmp_path, "chaos", mega_chunks=1, max_launch_retries=2,
               launch_backoff_s=0.001,
               inject_faults=("launch.submit:transient:2-4",))
    rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                             partition_span=span)
    fun = rep.funnel
    assert rep.degraded == 16
    assert fun["states"].get("unknown:failure:launch.submit") == 16
    assert sum(fun["states"].values()) == 48
    # Degraded partitions never produced margins: the histograms count
    # only the two healthy segments' boxes.
    assert sum(fun["margin_hist"]["margin"]) == 32


def test_bucket_rule_device_matches_numpy():
    """The device one-hot comparison-count rule == NumPy searchsorted
    (an independent implementation), on every edge value, +-eps around
    each edge, the extremes, and with padded rows masked out."""
    import jax.numpy as jnp

    vals = np.concatenate([
        funnel.EDGES,
        funnel.EDGES - np.float32(1e-3),
        funnel.EDGES + np.float32(1e-3),
        np.array([-1e6, 0.0, 1e6], np.float32),
    ]).astype(np.float32)
    gaps = (-vals).astype(np.float32)
    n = vals.size - 3  # the last 3 rows are padding: they must not count
    dev = np.asarray(sweep._chunk_stats_dev(
        jnp.asarray(vals), jnp.asarray(gaps), n))

    def np_hist(v):
        idx = np.searchsorted(funnel.EDGES, v, side="right")
        return np.bincount(idx, minlength=funnel.N_BUCKETS)

    np.testing.assert_array_equal(dev[funnel.MARGIN_ROW], np_hist(vals[:n]))
    np.testing.assert_array_equal(dev[funnel.GAP_ROW], np_hist(gaps[:n]))
    # The host mirror (chunk-loop path) follows the same rule bit-for-bit.
    ok = np.arange(vals.size) < n
    np.testing.assert_array_equal(funnel.hist(vals, ok), np_hist(vals[:n]))
    np.testing.assert_array_equal(funnel.hist(gaps, ok), np_hist(gaps[:n]))


def test_mega_hist_matches_numpy_recompute(tmp_path, monkeypatch):
    """Tiny grid: the mega loop's device-carried histograms equal a NumPy
    searchsorted recomputation from the raw chunk-loop margins/gaps."""
    from fairify_tpu.verify.property import encode

    net = init_mlp((20, 8, 1), seed=3)
    cfg0 = _cfg(tmp_path, "np0", mega_chunks=0)
    enc = encode(cfg0.query())
    _, lo, hi = sweep.build_partitions(cfg0)
    lo, hi = lo[:32], hi[:32]

    captured = []
    orig_add = funnel.StageStats.add_values

    def capture(self, margin, gap, ok=None):
        captured.append((np.array(margin, np.float32),
                         np.array(gap, np.float32)))
        return orig_add(self, margin, gap, ok)

    monkeypatch.setattr(funnel.StageStats, "add_values", capture)
    chunk_stats = funnel.StageStats()
    sweep._stage0_certify_and_attack(net, enc, lo, hi, cfg0,
                                     stats=chunk_stats)
    monkeypatch.undo()
    assert captured and sum(m.size for m, _ in captured) == 32

    mega_stats = funnel.StageStats()
    sweep._stage0_certify_and_attack(net, enc, lo, hi,
                                     _cfg(tmp_path, "np2", mega_chunks=2),
                                     stats=mega_stats)
    assert mega_stats.boxes == 32

    margins = np.concatenate([m for m, _ in captured])
    gaps = np.concatenate([g for _, g in captured])

    def np_hist(v):
        idx = np.searchsorted(funnel.EDGES, v, side="right")
        return np.bincount(idx, minlength=funnel.N_BUCKETS)

    np.testing.assert_array_equal(mega_stats.margin_hist, np_hist(margins))
    np.testing.assert_array_equal(mega_stats.gap_hist, np_hist(gaps))
    np.testing.assert_array_equal(mega_stats.hist, chunk_stats.hist)


def test_funnel_hist_ledger_consistency(tmp_path):
    """margin >= 0 <=> certified at stage 0: the non-negative margin mass
    equals the certified:stage0 state count, and the funnel's sat/unsat/
    unknown split equals the ledger's verdict counts."""
    net = init_mlp((20, 8, 1), seed=3)
    span = (0, 32)
    cfg = _cfg(tmp_path, "led", mega_chunks=2)
    rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                             partition_span=span)
    fun = rep.funnel
    mh = fun["margin_hist"]["margin"]
    assert sum(mh) == 32
    assert sum(mh[funnel.NEG_BUCKETS:]) == \
        fun["states"].get("certified:stage0", 0)

    recs, skipped = sweep._read_ledger(
        str(tmp_path / "led" / "GC-m@0-32.ledger.jsonl"))
    assert skipped == 0 and len(recs) == 32
    by_verdict = {"sat": 0, "unsat": 0, "unknown": 0}
    for rec in recs:
        by_verdict[rec["verdict"]] += 1
    states = fun["states"]
    assert by_verdict["unsat"] == sum(
        states.get(s, 0) for s in ("certified:stage0", "certified:bab",
                                   "smt:unsat"))
    assert by_verdict["sat"] == sum(
        states.get(s, 0) for s in ("attacked:stage0", "attacked:bab",
                                   "smt:sat"))
    assert by_verdict["unknown"] == sum(
        n for s, n in states.items() if s.startswith("unknown"))


def test_report_funnel_renders(tmp_path):
    """`fairify_tpu report --funnel` renders the state table, the decided
    fraction, and the stage-0 bucket table from a traced run's log."""
    from fairify_tpu.obs import report

    net = init_mlp((20, 8, 1), seed=3)
    cfg = _cfg(tmp_path, "rpt", mega_chunks=2)
    log = str(tmp_path / "events.jsonl")
    with obs.tracing(log, run_id="funnel-test"):
        sweep.verify_model(net, cfg, model_name="m", resume=False,
                           partition_span=(0, 32))
    agg = report.aggregate([log])
    fun = agg["funnel"]
    assert sum(fun["states"].values()) == 32
    assert fun["margin_hist"] is not None
    text = report.render_funnel(agg)
    assert "funnel state" in text
    assert "decided fraction:" in text
    assert "stage-0 bucket" in text


def test_budgeted_tail_is_unknown_budget(tmp_path):
    """A zero hard budget attempts nothing: decided_fraction 0.0 over the
    FULL grid, and the whole tail mirrors into unknown:budget."""
    import _sweeplib

    cfg = presets.get("GC").with_(
        soft_timeout_s=2.0, hard_timeout_s=0.0,
        result_dir=str(tmp_path / "out"), grid_chunk=64)
    net = init_mlp((20, 6, 1), seed=1)
    before = funnel.live_decided()
    c = obs.registry().counter("funnel_states")
    budget0 = c.value(state="unknown:budget") or 0
    rec = _sweeplib.budgeted_model_sweep(cfg, net, "m")
    assert rec["attempted"] == 0 and rec["partitions"] == 201
    assert rec["decided_fraction"] == 0.0
    assert (c.value(state="unknown:budget") or 0) - budget0 == 201
    assert funnel.live_decided() == before

"""Driver contract: entry() jit-compiles; dryrun_multichip runs on 8 devices."""
import jax
import numpy as np


def test_entry_compiles_and_runs():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    lb_x, ub_x, lb_p, ub_p = out
    assert lb_x.shape == (8, 2)  # 8 boxes × 2 PA assignments
    assert bool(np.all(np.asarray(lb_x) <= np.asarray(ub_x) + 1e-5))


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    assert len(jax.devices()) == 8
    ge.dryrun_multichip(8)

"""Grid-chunked stage 0 must be equivalent to the whole-grid pass.

Chunking exists so huge grids (the adult domain is 16k partitions) never
exceed HBM.  Per-partition PRNG keys are derived from global indices, so
sound-pruning masks (and simulation samples) are exactly chunk-size
invariant.  Verdicts are only guaranteed equal when every partition is
*decided*: the stage-0 attack/PGD random streams are chunk-dependent, so a
partition may be settled by attack in one run and by branch-and-bound in
the other — the sweep test below therefore gives BaB enough budget to
decide every leftover of this tiny net.
"""
import numpy as np
import pytest

from fairify_tpu.models.train import init_mlp
from fairify_tpu.verify import presets, pruning, sweep


@pytest.fixture(scope="module")
def gc_grid():
    cfg = presets.get("GC")
    _, lo, hi = sweep.build_partitions(cfg)
    return cfg, lo, hi


def test_sound_prune_grid_chunk_invariant(gc_grid):
    cfg, lo, hi = gc_grid
    net = init_mlp((20, 8, 1), seed=3)
    lo, hi = lo[:40], hi[:40]
    whole = pruning.sound_prune_grid(net, lo, hi, 64, cfg.seed, exact_certify=False)
    # 17 does not divide 40 — exercises the padded final chunk.
    chunked = pruning.sound_prune_grid(
        net, lo, hi, 64, cfg.seed, exact_certify=False, chunk=17)
    for a, b in zip(whole.st_deads, chunked.st_deads):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(whole.ws_ub, chunked.ws_ub):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(whole.sim, chunked.sim)


def test_sound_prune_grid_pipeline_depth_invariant(gc_grid):
    """The chunk loop now submits through LaunchPipeline; depth changes
    only *when* results are fetched, so masks, bounds, and samples must be
    bit-equal to the synchronous order (depth 1) at every depth."""
    cfg, lo, hi = gc_grid
    net = init_mlp((20, 8, 1), seed=3)
    lo, hi = lo[:40], hi[:40]
    sync = pruning.sound_prune_grid(
        net, lo, hi, 64, cfg.seed, exact_certify=False, chunk=17,
        pipeline_depth=1)
    for depth in (2, 4):
        piped = pruning.sound_prune_grid(
            net, lo, hi, 64, cfg.seed, exact_certify=False, chunk=17,
            pipeline_depth=depth)
        for a, b in zip(sync.st_deads, piped.st_deads):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(sync.ws_lb, piped.ws_lb):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(sync.ws_ub, piped.ws_ub):
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(sync.sim, piped.sim)


def test_sweep_verdicts_chunk_invariant(tmp_path, gc_grid):
    cfg, _, _ = gc_grid
    net = init_mlp((20, 8, 1), seed=3)
    base = cfg.with_(result_dir=str(tmp_path / "whole"), soft_timeout_s=30.0,
                     hard_timeout_s=300.0, sim_size=64, exact_certify_masks=False)
    whole = sweep.verify_model(net, base, model_name="m", resume=False)
    chunked = sweep.verify_model(
        net, base.with_(result_dir=str(tmp_path / "chunked"), grid_chunk=37),
        model_name="m", resume=False)
    assert whole.counts["unknown"] == 0  # budget suffices → strict comparison
    assert whole.counts == chunked.counts

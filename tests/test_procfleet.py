"""Out-of-process replica fleet (``serve/procfleet.py``, DESIGN.md §18).

Every test here drives REAL subprocesses — no mocks: replicas are
``python -m fairify_tpu.serve.replica`` workers, deaths are literal
``kill -9`` / ``SIGSTOP`` / allocation past ``RLIMIT_AS``, and recovery
is the router's actual waitpid/lease/failover machinery.  The contracts:

* **loss-free hard-kill failover** — a replica SIGKILLed mid-batch loses
  nothing: its requests re-home to a survivor, the survivor's
  ``resume=True`` run replays the crash-safe ledger, and the final
  verdict map (verdict AND counterexample bytes per partition) is
  bit-equal to an undisturbed run;
* **lease-based hang detection** — a SIGSTOPped replica stops beating
  its file lease while staying alive to ``waitpid``; the router must
  declare it wedged, escalate SIGTERM → SIGKILL, and fail over;
* **bounded restart backoff** — repeated deaths restart the slot at most
  ``max_restarts`` times, then abandon it (no flap loop);
* **cross-process exec-cache sharing** — a replica restarted against the
  shared persistent executable cache compiles nothing;
* **client exit codes survive a replica death** — ``fairify_tpu submit
  --wait`` returns 0 (done) across a mid-request kill.
"""
import json
import os
import signal
import sys
import time

import pytest

from fairify_tpu import obs
from fairify_tpu.serve import ProcessFleet, ProcFleetConfig, ServeConfig
from fairify_tpu.serve import client as client_mod
from fairify_tpu.verify import presets, sweep

SPAN = (0, 48)
SIZES = [20, 8, 1]

OVERRIDES = {
    "soft_timeout_s": 30.0, "hard_timeout_s": 600.0, "sim_size": 64,
    "exact_certify_masks": False, "grid_chunk": 16,
    "launch_backoff_s": 1e-4,
}


def _fleet(spool, n=2, **kw):
    kw.setdefault("poll_s", 0.03)
    kw.setdefault("pulse_s", 0.0)
    kw.setdefault("backoff_s", 0.05)
    kw.setdefault("replica", ServeConfig(batch_window_s=0.1, max_batch=4,
                                         poll_s=0.05, span_chunks=1))
    return ProcessFleet(ProcFleetConfig(n_replicas=n, spool=str(spool), **kw))


def _payload(seed=3, span=SPAN, **extra):
    return client_mod.build_payload(
        "GC", init={"sizes": SIZES, "seed": seed},
        overrides=dict(OVERRIDES), span=span, **extra)


def _ledger_map(spool, rid):
    """partition -> (verdict, ce-bytes) from the request's ledger: the
    bit-equality key (counterexamples included)."""
    paths = client_mod.ledger_paths(str(spool), rid)
    assert paths, f"no ledger for {rid}"
    out = {}
    for path in paths:
        for pid, rec in sweep._load_ledger(path).items():
            ce = rec.get("ce")
            out[pid] = (rec["verdict"],
                        None if ce is None else json.dumps(ce))
    return out


def _solo_map(tmp_path, seed=3, span=SPAN):
    """The undisturbed reference: a plain in-process run of the same net."""
    from fairify_tpu.models.train import init_mlp

    cfg = presets.get("GC").with_(result_dir=str(tmp_path / f"solo{seed}"),
                                  **OVERRIDES)
    rep = sweep.verify_model(init_mlp(tuple(SIZES), seed=seed), cfg,
                             model_name="solo", resume=False,
                             partition_span=span)
    out = {}
    for o in rep.outcomes:
        ce = None
        if o.counterexample is not None:
            ce = json.dumps([[int(v) for v in x]
                             for x in o.counterexample])
        out[o.partition_id] = (o.verdict, ce)
    return out


def _wait_running(fl, rid, timeout=90.0):
    """Block until the replica reports the request RUNNING; returns the
    owning slot index."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if fl.status_of(rid) == "running":
            owner = fl.owner_of(rid)
            if owner is not None:
                return owner
        time.sleep(0.01)
    raise AssertionError(
        f"request {rid} never reached running (status="
        f"{fl.status_of(rid)!r})")


# ---------------------------------------------------------------------------
# kill -9 mid-batch: loss-free failover, bit-equal verdicts
# ---------------------------------------------------------------------------


def test_sigkill_mid_batch_failover_bit_equal(tmp_path):
    """A literal ``kill -9`` of the owning replica mid-request loses no
    decided verdict: the survivor's resume replay converges to a verdict
    map (incl. counterexample bytes) bit-equal to the undisturbed run."""
    want = _solo_map(tmp_path)
    spool = tmp_path / "spool"
    with _fleet(spool) as fl:
        assert fl.wait_ready(timeout=180) == 2
        rid = client_mod.submit(str(spool), _payload())
        owner = _wait_running(fl, rid)
        pid = fl.pids()[owner]
        os.kill(pid, signal.SIGKILL)
        rec = fl.wait(rid, timeout=300)
        assert rec is not None and rec["status"] == "done", rec
        got = _ledger_map(spool, rid)
        assert got == want
        # The death was classified and the work re-homed — real failover,
        # not a lucky completion before the kill landed.
        assert fl.restarts()[owner] >= 1
        # Terminal requests are EVICTED from the router's tracking tables
        # (status.json stays the durable answer): a long-lived router must
        # not grow one entry per request ever served.
        t0 = time.monotonic()
        while fl.status_of(rid) is not None and time.monotonic() - t0 < 15:
            time.sleep(0.02)
        assert fl.status_of(rid) is None and fl.owner_of(rid) is None
    deaths = obs.registry().counter("replica_deaths")
    assert deaths.value(kind="crash") >= 1


def test_submit_wait_exit_codes_across_replica_death(tmp_path):
    """``fairify_tpu submit --wait`` exit semantics are pinned across a
    replica kill: 0 for a request that failed over to done, 2 for a
    client-side payload error, 1 for a terminal non-done state."""
    from fairify_tpu import cli

    spool = tmp_path / "spool"
    with _fleet(spool) as fl:
        assert fl.wait_ready(timeout=180) == 2
        # Corrupt payload -> terminal rejected -> --wait exits 1.
        bad = os.path.join(str(spool), "inbox", "badjson.json")
        with open(bad, "w") as fp:
            fp.write("{nope")
        t0 = time.monotonic()
        while client_mod.status(str(spool), "badjson") is None \
                and time.monotonic() - t0 < 30:
            time.sleep(0.02)
        st = client_mod.status(str(spool), "badjson")
        assert st is not None and st["status"] == "rejected"
        # Payload-level validation error -> exit 2 before any submit.
        rc = cli.main(["submit", "GC", "--spool", str(spool), "--wait", "5"])
        assert rc == 2  # neither --model nor --init-sizes
        # A healthy request killed mid-run still exits 0 once failover
        # finishes it (same spool CLI a real client uses).
        rid = client_mod.submit(str(spool), _payload(seed=5))
        owner = _wait_running(fl, rid)
        os.kill(fl.pids()[owner], signal.SIGKILL)
        rec = fl.wait(rid, timeout=300)
        assert rec is not None and rec["status"] == "done"
        # client.wait + the CLI's status mapping: done -> 0.
        assert client_mod.status(str(spool), rid)["status"] == "done"


# ---------------------------------------------------------------------------
# SIGSTOP wedge: lease expiry -> SIGTERM/SIGKILL escalation -> failover
# ---------------------------------------------------------------------------


def test_sigstop_wedge_lease_hang_failover_bit_equal(tmp_path):
    """A SIGSTOPped replica is alive to waitpid but beats no lease: the
    router must detect the hang, hard-kill it (SIGTERM is ignored by a
    stopped process — only the SIGKILL escalation lands), and fail over
    with the verdict map still bit-equal to the undisturbed run."""
    want = _solo_map(tmp_path, seed=7)
    spool = tmp_path / "spool"
    # The lease must clear the worst-case HEALTHY inter-beat gap (one
    # whole granule on a loaded single-core host) or the router would
    # kill the survivor too; 5 s is comfortable, and the SIGSTOPped
    # replica's frozen mtime blows past it just the same.
    with _fleet(spool, lease_s=5.0, term_grace_s=0.5) as fl:
        assert fl.wait_ready(timeout=180) == 2
        rid = client_mod.submit(str(spool), _payload(seed=7))
        owner = _wait_running(fl, rid)
        pid = fl.pids()[owner]
        os.kill(pid, signal.SIGSTOP)
        rec = fl.wait(rid, timeout=300)
        assert rec is not None and rec["status"] == "done", rec
        assert _ledger_map(spool, rid) == want
    deaths = obs.registry().counter("replica_deaths")
    assert deaths.value(kind="hang") >= 1


# ---------------------------------------------------------------------------
# bounded restart backoff
# ---------------------------------------------------------------------------


def test_bounded_restart_backoff(tmp_path):
    """Each death restarts the slot at most ``max_restarts`` times with
    growing jittered backoff; exhaustion abandons the slot instead of
    flap-looping, and the other slot keeps serving."""
    spool = tmp_path / "spool"
    fl = _fleet(spool, n=2, max_restarts=2, backoff_s=0.05)
    with fl:
        assert fl.wait_ready(timeout=180) == 2
        victim_pids = []
        for _round in range(3):  # max_restarts=2 -> third kill is final
            pids = fl.pids()
            if 0 not in pids:
                break
            victim_pids.append(pids[0])
            os.kill(pids[0], signal.SIGKILL)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 120:
                cur = fl.pids().get(0)
                if cur is not None and cur not in victim_pids:
                    break  # restarted under a fresh pid
                if fl.restarts()[0] >= 2 and 0 not in fl.pids():
                    break  # budget spent, slot down
                time.sleep(0.02)
        assert fl.restarts()[0] == 2  # bounded: never more than the cap
        # The slot is abandoned (no live replica 0), slot 1 still serves.
        t0 = time.monotonic()
        while 0 in fl.pids() and time.monotonic() - t0 < 120:
            time.sleep(0.05)
        assert 0 not in fl.pids()
        assert 1 in fl.pids()
        rid = client_mod.submit(str(spool), _payload(seed=9, span=(0, 16)))
        rec = fl.wait(rid, timeout=300)
        assert rec is not None and rec["status"] == "done"


# ---------------------------------------------------------------------------
# shared persistent exec cache: cold replica restart compiles nothing
# ---------------------------------------------------------------------------


def test_exec_cache_shared_across_replica_processes(tmp_path):
    """A replica process restarted against the shared on-disk executable
    cache compiles nothing: the first replica's compiles populated it,
    and the fresh process (empty in-memory caches) loads every kernel."""
    spool = tmp_path / "spool"
    with _fleet(spool, n=1) as fl:
        assert fl.wait_ready(timeout=180) == 1
        rid = client_mod.submit(str(spool), _payload(seed=11, span=(0, 32)))
        rec = fl.wait(rid, timeout=300)
        assert rec is not None and rec["status"] == "done"
        # Kill the only replica: the restart is a genuinely fresh process.
        os.kill(fl.pids()[0], signal.SIGKILL)
        # Restart-backoff window: zero replicas live, respawn pending —
        # the fleet must still report alive() (an operator loop draining
        # here would turn every recoverable crash into a shutdown).
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            if fl.replicas_alive() == 0:
                assert fl.alive()
                break
            time.sleep(0.005)
        rid2 = client_mod.submit(str(spool), _payload(seed=11, span=(0, 32),
                                                     request_id="cold-run"))
        rec2 = fl.wait(rid2, timeout=300)
        assert rec2 is not None and rec2["status"] == "done"
        assert fl.restarts()[0] >= 1
        stats = {}
        fl.drain()
        stats = fl.drain_stats()
    cache_dir = os.path.join(str(spool), "exec-cache")
    assert os.path.isdir(cache_dir) and os.listdir(cache_dir)
    # The restarted replica reports its PROCESS-lifetime compile
    # accounting in its drained control message: warmed from the shared
    # on-disk cache, the fresh process compiled nothing and loaded every
    # kernel from disk.
    assert 0 in stats, stats
    assert stats[0].get("n_compiles") == 0, stats
    assert stats[0].get("exec_cache_hits", 0) > 0, stats


# ---------------------------------------------------------------------------
# memout containment: RLIMIT_AS kills one replica, not the fleet
# ---------------------------------------------------------------------------


def test_memout_is_classified_and_contained(tmp_path):
    """A replica allocating past its RSS cap dies with the distinct
    memout exit code; the router classifies it (not ``crash``), restarts
    the slot, and the fleet keeps serving."""
    spool = tmp_path / "spool"
    deaths = obs.registry().counter("replica_deaths")
    m0 = deaths.value(kind="memout")
    # The cap must clear a sweep's ~1.4 GB VA peak (jax CPU arenas) while
    # still bounding the chaos allocation — 2 GB does both.
    with _fleet(spool, n=2, memory_cap_mb=2048) as fl:
        assert fl.wait_ready(timeout=240) == 2
        assert fl.inject_memout(0)
        t0 = time.monotonic()
        while deaths.value(kind="memout") == m0 \
                and time.monotonic() - t0 < 60:
            time.sleep(0.02)
        assert deaths.value(kind="memout") == m0 + 1
        rid = client_mod.submit(str(spool), _payload(seed=13, span=(0, 16)))
        rec = fl.wait(rid, timeout=300)
        assert rec is not None and rec["status"] == "done"


# ---------------------------------------------------------------------------
# machinery units (no subprocesses)
# ---------------------------------------------------------------------------


def test_config_validation(tmp_path):
    with pytest.raises(ValueError):
        ProcessFleet(ProcFleetConfig(n_replicas=2, spool=""))
    with pytest.raises(ValueError):
        ProcessFleet(ProcFleetConfig(n_replicas=0, spool=str(tmp_path)))


def test_fleet_pulse_throttles_and_reports_changes():
    from fairify_tpu.obs.heartbeat import FleetPulse

    class _Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = _Clock()
    out = []

    class _Stream:
        @staticmethod
        def write(s):
            out.append(s)

        @staticmethod
        def flush():
            pass

    p = FleetPulse(interval_s=5.0, stream=_Stream(), clock=clock)
    assert not p.pulse(2, 2)            # healthy, unchanged: silent
    assert p.pulse(1, 2)                # a death prints immediately
    clock.t += 1.0
    assert not p.pulse(1, 2)            # degraded but throttled
    clock.t += 5.0
    assert p.pulse(1, 2, restarting=1)  # degraded + interval elapsed
    assert p.pulse(2, 2)                # recovery (change) prints
    clock.t += 10.0
    assert not p.pulse(2, 2)            # healthy again: silent
    text = "".join(out)
    assert "replicas alive 1/2" in text and "1 restarting" in text
    assert "replicas alive 2/2" in text


def test_report_renders_replica_table(tmp_path):
    """`fairify_tpu report` folds the router's `replica` events into one
    row per slot: last pid, restart count, deaths by kind, re-homed
    requests, last lease age, abandoned marker."""
    from fairify_tpu.obs import report as report_mod

    recs = [
        {"type": "event", "name": "replica",
         "attrs": {"replica": 0, "event": "spawn", "pid": 100}},
        {"type": "event", "name": "replica",
         "attrs": {"replica": 0, "event": "death", "kind": "crash",
                   "pid": 100}},
        {"type": "event", "name": "replica",
         "attrs": {"replica": 0, "event": "rehome", "requests": 2}},
        {"type": "event", "name": "replica",
         "attrs": {"replica": 0, "event": "metrics", "exec_cache_hits": 9,
                   "n_compiles": 1, "exec_cache_hit_rate": 0.9,
                   "launches_per_model": 4.5}},
        {"type": "event", "name": "replica",
         "attrs": {"replica": 0, "event": "restart", "pid": 101,
                   "restarts": 1}},
        {"type": "event", "name": "replica",
         "attrs": {"replica": 1, "event": "lease_expired",
                   "lease_age": 3.25, "pid": 102}},
        {"type": "event", "name": "replica",
         "attrs": {"replica": 1, "event": "death", "kind": "hang",
                   "pid": 102}},
        {"type": "event", "name": "replica",
         "attrs": {"replica": 1, "event": "abandoned", "restarts": 3}},
    ]
    log = tmp_path / "events.jsonl"
    log.write_text("".join(json.dumps(r) + "\n" for r in recs))
    agg = report_mod.aggregate([str(log)])
    assert agg["replicas"]["0"] == {
        "pid": 101, "restarts": 1, "deaths": {"crash": 1}, "rehomed": 2,
        "last_lease_age_s": None, "abandoned": False,
        "exec_cache_hit_rate": 0.9, "launches_per_model": 4.5}
    assert agg["replicas"]["1"]["deaths"] == {"hang": 1}
    assert agg["replicas"]["1"]["last_lease_age_s"] == 3.25
    assert agg["replicas"]["1"]["abandoned"] is True
    text = report_mod.render(agg)
    assert "replica" in text and "hang=1" in text and "1*" in text
    # Live fleet telemetry columns (satellite of DESIGN.md §19): the
    # metrics beats' derived gauges render per slot.
    assert "90%" in text and "4.5" in text


def test_replica_cmd_carries_template_knobs(tmp_path):
    fl = _fleet(tmp_path / "s", n=1, memory_cap_mb=256,
                replica=ServeConfig(batch_window_s=0.1, max_batch=4,
                                    poll_s=0.05, span_chunks=1,
                                    preempt_factor=2.0, max_preemptions=0,
                                    fair_share_factor=4.0,
                                    fair_share_min_s=10.0))
    cmd = fl._replica_cmd(0)
    joined = " ".join(cmd)
    assert "-m fairify_tpu.serve.replica" in joined
    assert "--span-chunks 1" in joined
    assert "--memory-cap-mb 256" in joined
    assert "--exec-cache" in joined  # auto -> <spool>/exec-cache
    # EVERY overload knob of the template crosses the process boundary —
    # a dropped flag silently reverts the replica to defaults.
    assert "--preempt-factor 2.0" in joined
    assert "--max-preemptions 0" in joined
    assert "--fair-share 4.0" in joined
    assert "--fair-share-min 10.0" in joined
    fl._journal_writer.close()

"""Tier-1 surface of the whole-program concurrency auditor
(``fairify_tpu/analysis/locks.py`` + ``lint/rules_concurrency.py``).

Three layers:

* **repo facts** — the lock catalog covers EVERY ``threading.Lock`` /
  ``RLock`` / ``Condition`` construction in ``fairify_tpu/`` (the
  acceptance bar of the auditor: a lock the graph cannot see is a lock
  the deadlock analysis silently ignores), the canonical aliasing of
  Conditions onto their wrapped locks holds, the cross-object edges the
  runtime actually exercises are modeled, and the graph is acyclic.
* **machinery** — cycle detection with witnesses on a toy two-way
  nesting, call-site lifting of blocking operations, Condition aliasing.
* **rule wiring** — the four rules share one analysis per ``all_rules()``
  invocation and their findings ride the engine (suppressions work).

No jax import: the analysis layer is plain-AST like the rest of lint.
"""
import ast
import pathlib

from fairify_tpu.analysis import locks as locks_mod
from fairify_tpu.lint import core as lint_core

REPO_ROOT = pathlib.Path(lint_core.repo_root())


def _repo_analysis():
    return locks_mod.build_repo_analysis(str(REPO_ROOT))


def _all_constructions():
    """(rel, line) of every threading.Lock/RLock/Condition call in
    fairify_tpu/ — found independently of the analysis, by raw AST scan."""
    out = set()
    for path, rel in lint_core.iter_py_files(str(REPO_ROOT)):
        tree = ast.parse(pathlib.Path(path).read_text(), filename=path)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("Lock", "RLock", "Condition") \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "threading":
                out.add((rel, node.lineno))
    return out


# ---------------------------------------------------------------------------
# Repo facts
# ---------------------------------------------------------------------------


def test_catalog_covers_every_lock_construction():
    an = _repo_analysis()
    catalog = an.catalog()
    missing = _all_constructions() - set(catalog)
    assert not missing, (
        f"lock constructions invisible to the concurrency analysis: "
        f"{sorted(missing)} — extend analysis/locks.py discovery")


def test_condition_aliases_wrapped_lock():
    """server._cv wraps server._lock: both catalog entries share one
    canonical node (with self._cv acquires self._lock)."""
    an = _repo_analysis()
    rel = "fairify_tpu/serve/server.py"
    cv = an.locks[f"{rel}::VerificationServer._cv"]
    lk = an.locks[f"{rel}::VerificationServer._lock"]
    assert cv.canonical == lk.canonical == lk.id


def test_repo_graph_models_cross_object_edges():
    """The edges the fleet/server runtime actually exercises must be in
    the static graph (the dynamic lockprof subset check depends on it):
    router-holds-fleet-lock -> replica load(), and metrics instruments
    bumped under the server condition."""
    an = _repo_analysis()
    short = {(a.split("::")[-1], b.split("::")[-1]) for a, b in an.edges}
    assert ("ServerFleet._lock", "VerificationServer._lock") in short
    assert ("VerificationServer._lock", "MetricsRegistry._lock") in short
    assert ("VerificationServer._lock", "Gauge._lock") in short


def test_repo_graph_is_acyclic():
    an = _repo_analysis()
    assert an.cycles() == [], [
        [(s.split("::")[-1], d.split("::")[-1]) for s, d, _ in c]
        for c in an.cycles()]


def test_repo_has_no_unallowlisted_findings():
    """Raw findings minus the reviewed allowlist == 0 (the lint gate
    enforces the same; this pins it at the analysis layer with names)."""
    from fairify_tpu.lint.rules_concurrency import ALLOW_BLOCKING_UNDER_LOCK

    an = _repo_analysis()
    live = [f for f in an.blocking
            if f"{f.rel}::{f.function}" not in ALLOW_BLOCKING_UNDER_LOCK]
    assert not live, [(f.rel, f.line, f.message) for f in live]
    assert not an.kill, [(f.rel, f.line) for f in an.kill]
    assert not an.cv, [(f.rel, f.line) for f in an.cv]


# ---------------------------------------------------------------------------
# Machinery on toy trees
# ---------------------------------------------------------------------------


def _analyze_src(tmp_path, named_srcs):
    an = locks_mod.ConcurrencyAnalysis()
    for rel, src in named_srcs.items():
        an.add_file(rel, ast.parse(src))
    an.finalize()
    return an


def test_cycle_detection_with_witnesses(tmp_path):
    an = _analyze_src(tmp_path, {"fairify_tpu/x.py": (
        "import threading\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def ba(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n")})
    cycles = an.cycles()
    assert len(cycles) == 1
    steps = cycles[0]
    assert {s.split("::")[-1] for s, _d, _w in steps} == {"P._a", "P._b"}
    # Witnesses carry real locations.
    assert all(w.rel == "fairify_tpu/x.py" and w.line for _s, _d, w in steps)


def test_cross_function_edge_and_blocking_lift(tmp_path):
    """Holding a lock while calling a method that acquires another lock
    (edge) or that reaches a blocking op (finding at the call site)."""
    an = _analyze_src(tmp_path, {"fairify_tpu/y.py": (
        "import threading\n"
        "import time\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._la = threading.Lock()\n"
        "        self.b = B()\n"
        "    def outer(self):\n"
        "        with self._la:\n"
        "            self.b.inner()\n"
        "            self._slow()\n"
        "    def _slow(self):\n"
        "        time.sleep(1)\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._lb = threading.Lock()\n"
        "    def inner(self):\n"
        "        with self._lb:\n"
        "            pass\n")})
    short = {(a.split("::")[-1], b.split("::")[-1]) for a, b in an.edges}
    assert ("A._la", "B._lb") in short
    # One blocking finding, attributed at the _slow() CALL site (line 10),
    # not inside _slow (where no lock is held).
    assert [(f.line, f.function) for f in an.blocking] == [(10, "outer")]


def test_condition_wait_while_holding_second_lock_is_blocking(tmp_path):
    an = _analyze_src(tmp_path, {"fairify_tpu/z.py": (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._m = threading.Lock()\n"
        "        self._cv = threading.Condition()\n"
        "    def bad(self):\n"
        "        with self._m:\n"
        "            with self._cv:\n"
        "                while True:\n"
        "                    self._cv.wait(0.1)\n")})
    assert any("releases only its own lock" in f.message
               for f in an.blocking)


def test_rules_share_one_analysis_per_run():
    from fairify_tpu.lint.rules_concurrency import concurrency_rules

    rules = concurrency_rules()
    assert len({id(r._shared) for r in rules}) == 1
    # And a fresh batch gets a fresh analysis (engine runs are stateful).
    assert id(concurrency_rules()[0]._shared) != id(rules[0]._shared)


def test_findings_ride_engine_suppressions(tmp_path):
    from fairify_tpu.lint.rules_concurrency import concurrency_rules

    p = tmp_path / "fx.py"
    p.write_text(
        "import threading\n"
        "import time\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            time.sleep(1)  # lint: disable=blocking-under-lock\n")
    result = lint_core.run_lint(rules=concurrency_rules(),
                                files=[(str(p), "fairify_tpu/serve/fx.py")])
    assert not result.findings
    assert result.suppressed_by_rule == {"blocking-under-lock": 1}


# ---------------------------------------------------------------------------
# Review hardening regressions
# ---------------------------------------------------------------------------


def test_manual_acquire_finally_must_release_same_lock(tmp_path):
    """A finally releasing a DIFFERENT lock must not mask the leak, and
    blocking ops inside the try's except handlers are still under the
    manually-held lock."""
    an = _analyze_src(tmp_path, {"fairify_tpu/m.py": (
        "import threading\n"
        "import time\n"
        "class M:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def bad(self):\n"
        "        self._a.acquire()\n"
        "        try:\n"
        "            pass\n"
        "        except Exception:\n"
        "            time.sleep(5)\n"
        "        finally:\n"
        "            self._b.release()\n"
        "    def good(self):\n"
        "        self._a.acquire()\n"
        "        try:\n"
        "            pass\n"
        "        except Exception:\n"
        "            time.sleep(5)\n"
        "        finally:\n"
        "            self._a.release()\n")})
    # bad(): wrong-lock finally -> kill-safety finding at the acquire.
    assert [(f.line, f.function) for f in an.kill] == [(8, "bad")]
    # Both handler sleeps run with _a held -> blocking findings in each.
    assert sorted((f.line, f.function) for f in an.blocking) == \
        [(12, "bad"), (20, "good")]


def test_kill_scan_prunes_nested_defs(tmp_path):
    """Mutations inside callbacks defined under the lock run at CALL
    time, not inside the region — they must not trip the torn-state scan."""
    an = _analyze_src(tmp_path, {"fairify_tpu/n.py": (
        "import threading\n"
        "from fairify_tpu.resilience import faults as faults_mod\n"
        "class N:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def safe(self):\n"
        "        with self._lock:\n"
        "            self._x = 1\n"
        "            faults_mod.check('replica.lost')\n"
        "            def cb():\n"
        "                self._b = 2\n"
        "                self._c = 3\n"
        "            self._callback = cb\n")})
    # direct events: mutation(_x), yield, mutation(_callback) — wait,
    # _callback IS a second direct mutation after the yield: that torn
    # pair is real.  Only the nested-def mutations must be invisible.
    assert len(an.kill) == 1  # _x / _callback straddle, cb's body doesn't
    assert "2 mutations" in an.kill[0].message


def test_lock_construction_line_is_the_call_line(tmp_path):
    """Multi-line constructions: the catalog keys on the threading CALL's
    line, which is what the dynamic profiler's frame reports."""
    an = _analyze_src(tmp_path, {"fairify_tpu/w.py": (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = (\n"
        "            threading.Lock())\n")})
    assert ("fairify_tpu/w.py", 5) in an.catalog()


def test_deep_call_chain_edges_still_propagate(tmp_path):
    """Reachability is not capped by the witness-chain length: a lock
    acquired 6 call frames below a lock-holding site is still an edge
    (only the stored witness chain is truncated)."""
    hops = "".join(
        f"    def g{i}(self):\n        self.g{i + 1}()\n" for i in range(6))
    an = _analyze_src(tmp_path, {"fairify_tpu/deep.py": (
        "import threading\n"
        "class D:\n"
        "    def __init__(self):\n"
        "        self._top = threading.Lock()\n"
        "        self._deep = threading.Lock()\n"
        "    def outer(self):\n"
        "        with self._top:\n"
        "            self.g0()\n"
        + hops +
        "    def g6(self):\n"
        "        with self._deep:\n"
        "            pass\n")})
    short = {(a.split("::")[-1], b.split("::")[-1]) for a, b in an.edges}
    assert ("D._top", "D._deep") in short


def test_manual_release_ends_the_held_region(tmp_path):
    """An explicit .release() stops the held-set: statements after it
    are not lock-held (no cascading false blocking findings); the
    kill-safety finding at the unprotected acquire remains."""
    an = _analyze_src(tmp_path, {"fairify_tpu/r.py": (
        "import threading\n"
        "import time\n"
        "class R:\n"
        "    def __init__(self):\n"
        "        self._l = threading.Lock()\n"
        "    def f(self):\n"
        "        self._l.acquire()\n"
        "        self._x = 1\n"
        "        self._l.release()\n"
        "        time.sleep(1)\n")})
    assert [(f.line) for f in an.kill] == [7]  # acquire without try/finally
    assert not an.blocking  # the sleep runs after the release


def test_class_body_and_annassign_locks_discovered(tmp_path):
    """Class-body locks and annotated module locks are nodes: nesting
    through them produces edges, and the catalog covers them."""
    an = _analyze_src(tmp_path, {"fairify_tpu/cb.py": (
        "import threading\n"
        "_GLOBAL: threading.Lock = threading.Lock()\n"
        "class C:\n"
        "    _lock = threading.Lock()\n"
        "    _cv = threading.Condition(_lock)\n"
        "    def f(self):\n"
        "        with C._lock:\n"
        "            with _GLOBAL:\n"
        "                pass\n"
        "    def g(self):\n"
        "        with self._cv:\n"
        "            pass\n")})
    cat = an.catalog()
    assert ("fairify_tpu/cb.py", 2) in cat   # AnnAssign module lock
    assert ("fairify_tpu/cb.py", 4) in cat   # class-body lock
    # The class-body Condition aliases the class-body lock.
    assert an.locks["fairify_tpu/cb.py::C._cv"].canonical == \
        "fairify_tpu/cb.py::C._lock"
    short = {(a.split("::")[-1], b.split("::")[-1]) for a, b in an.edges}
    assert ("C._lock", "_GLOBAL") in short


def test_ambiguous_callee_blocking_does_not_hide_edges(tmp_path):
    """A call site whose receiver is ambiguous between a blocking callee
    and a lock-acquiring callee yields BOTH the blocking finding and the
    edge — one must not suppress the other."""
    an = _analyze_src(tmp_path, {"fairify_tpu/amb.py": (
        "import threading\n"
        "import time\n"
        "class A:\n"
        "    def run(self):\n"
        "        time.sleep(1)\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._inner = threading.Lock()\n"
        "    def run(self):\n"
        "        with self._inner:\n"
        "            pass\n"
        "class H:\n"
        "    def __init__(self, flag):\n"
        "        self._lock = threading.Lock()\n"
        "        self.w = A() if flag else B()\n"
        "    def go(self):\n"
        "        with self._lock:\n"
        "            self.w.run()\n")})
    short = {(a.split("::")[-1], b.split("::")[-1]) for a, b in an.edges}
    assert ("H._lock", "B._inner") in short
    assert len([f for f in an.blocking if f.function == "go"]) == 1


def test_condition_alias_respects_custom_self_name(tmp_path):
    """The aliasing pass uses the method's actual instance-parameter
    name, not a hardcoded 'self'."""
    an = _analyze_src(tmp_path, {"fairify_tpu/sn.py": (
        "import threading\n"
        "class S:\n"
        "    def __init__(this):\n"
        "        this._lock = threading.Lock()\n"
        "        this._cv = threading.Condition(this._lock)\n")})
    assert an.locks["fairify_tpu/sn.py::S._cv"].canonical == \
        "fairify_tpu/sn.py::S._lock"

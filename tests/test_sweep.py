"""End-to-end sweep: verdicts vs oracle, CSV/ledger output, resume, mesh."""
import csv
import os

import numpy as np
import pytest

from fairify_tpu.data import domains as dom_mod
from fairify_tpu.data.domains import DomainSpec
from fairify_tpu.models import mlp
from fairify_tpu.verify import engine, presets, property as prop, sweep
from fairify_tpu.verify.config import SweepConfig
from fairify_tpu.verify.oracle import brute_force_verdict as oracle, random_net


@pytest.fixture()
def tiny_registered(monkeypatch):
    dom = DomainSpec(name="tinysweep", label="y",
                     ranges={"a": (0, 9), "pa": (0, 1), "b": (0, 4)})
    monkeypatch.setitem(dom_mod.DOMAINS, "tinysweep", dom)
    return dom


def make_cfg(tmp_path, **kw):
    base = dict(
        name="tiny", dataset="tinysweep", protected=("pa",),
        partition_threshold=5, sim_size=64, soft_timeout_s=30.0,
        hard_timeout_s=600.0, result_dir=str(tmp_path),
        engine=engine.EngineConfig(frontier_size=64, attack_samples=32,
                                   bab_attack_samples=8, soft_timeout_s=30.0),
    )
    base.update(kw)
    return SweepConfig(**base)


def test_sweep_matches_oracle_and_writes_outputs(tmp_path, tiny_registered):
    rng = np.random.default_rng(7)
    net = random_net(rng, (3, 6, 1))
    cfg = make_cfg(tmp_path)
    report = sweep.verify_model(net, cfg, model_name="tiny-1")

    p_list, lo, hi = sweep.build_partitions(cfg)
    assert report.partitions_total == len(p_list) == 2  # 'a' chunked in two
    query = cfg.query()
    for out, l, h in zip(report.outcomes, lo, hi):
        assert out.verdict == oracle(net, query, l, h)
        if out.verdict == "sat":
            assert out.v_accurate == 1

    csv_path = os.path.join(str(tmp_path), "tiny-1.csv")
    with open(csv_path) as fp:
        rows = list(csv.reader(fp))
    assert rows[0] == sweep.csvio.RES_COLS
    assert len(rows) == 1 + len(report.outcomes)

    # Resume: a second run replays the ledger, adds no CSV rows.
    report2 = sweep.verify_model(net, cfg, model_name="tiny-1")
    assert [o.verdict for o in report2.outcomes] == [o.verdict for o in report.outcomes]
    with open(csv_path) as fp:
        assert len(list(csv.reader(fp))) == len(rows)


def test_sweep_verdicts_mesh_invariant(tmp_path, tiny_registered):
    import jax

    rng = np.random.default_rng(11)
    net = random_net(rng, (3, 5, 1))
    cfg = make_cfg(tmp_path, result_dir=str(tmp_path / "single"))
    rep1 = sweep.verify_model(net, cfg, model_name="m")

    from fairify_tpu.parallel import mesh as mesh_mod

    assert len(jax.devices()) == 8  # conftest forces the virtual CPU mesh
    mesh = mesh_mod.make_mesh(n_parts=8, n_models=1)
    cfg2 = make_cfg(tmp_path, result_dir=str(tmp_path / "mesh"))
    rep2 = sweep.verify_model(net, cfg2, model_name="m", mesh=mesh)
    assert sorted(o.verdict for o in rep1.outcomes) == sorted(o.verdict for o in rep2.outcomes)


def test_presets_cover_all_drivers():
    names = presets.names()
    # 5 base + CP12 (task4's 12-input family) + LSAC + 3 stress + 3 relaxed
    # + relaxed2-BM / relaxed3-BM (framework-native two-/three-RA variants)
    # + 3+3 targeted + targeted-DF (framework-native certificate-path DF)
    assert len(names) == 22
    for n in names:
        cfg = presets.get(n)
        q = cfg.query()  # builds without error, drops phantom attributes
        assert len(q.protected) >= 1
        enc = prop.encode(q)
        assert enc.valid_pair.any()


def test_partition_counts_match_reference_shapes():
    # German base config: credit_amount (0..20000) is the only attribute wider
    # than 100 → ceil(20001/100) = 201 partitions (src/GC/Verify-GC.py:63).
    cfg = presets.get("GC")
    p_list, lo, hi = sweep.build_partitions(cfg)
    assert len(p_list) == 201
    # Compas: Number_of_Priors 0..38 at threshold 5 → 8 chunks.
    cfg = presets.get("CP")
    p_list, _, _ = sweep.build_partitions(cfg)
    assert len(p_list) == 8
    # DF capped: at most max_partitions boxes.
    cfg = presets.get("DF")
    p_list, _, _ = sweep.build_partitions(cfg)
    assert len(p_list) <= 100


def test_cli_metrics_subcommand(capsys, reference_assets_available):
    """`fairify_tpu metrics` prints one group-report JSON line per model."""
    if not reference_assets_available:
        pytest.skip("reference assets not mounted")
    import json

    from fairify_tpu import cli

    rc = cli.main(["metrics", "GC", "--models", "GC-4"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rep = json.loads(line)
    assert rep["model"] == "GC-4" and rep["protected"] == "age"
    for key in ("accuracy", "disparate_impact", "statistical_parity_difference",
                "equal_opportunity_difference", "average_odds_difference",
                "error_rate_difference", "consistency", "theil_index"):
        assert key in rep


def test_cli_host_pair_validation(capsys):
    from fairify_tpu import cli

    assert cli.main(["run", "GC", "--host-index", "0"]) == 2


def test_retry_unknown_reattempts_only_unknowns(tmp_path):
    """resume keeps decided verdicts; retry_unknown re-decides UNKNOWN rows."""
    import json

    from fairify_tpu.models.train import init_mlp

    net = init_mlp((20, 8, 1), seed=3)
    cfg = presets.get("GC").with_(
        result_dir=str(tmp_path), soft_timeout_s=30.0, hard_timeout_s=300.0,
        sim_size=64, exact_certify_masks=False)
    ledger = os.path.join(str(tmp_path), "GC-m.ledger.jsonl")
    # Fabricate a ledger: partition 1 budget-exhausted, 2..201 decided.
    with open(ledger, "w") as fp:
        fp.write(json.dumps({"partition_id": 1, "verdict": "unknown",
                             "ce": None, "time_s": 0.0}) + "\n")
        for pid in range(2, 202):
            fp.write(json.dumps({"partition_id": pid, "verdict": "unsat",
                                 "ce": None, "time_s": 0.0}) + "\n")

    plain = sweep.verify_model(net, cfg, model_name="m", resume=True)
    assert plain.counts["unknown"] == 1  # resume keeps the recorded verdicts

    retried = sweep.verify_model(net, cfg, model_name="m", resume=True,
                                 retry_unknown=True)
    by_pid = {o.partition_id: o.verdict for o in retried.outcomes}
    assert by_pid[1] in ("sat", "unsat")  # re-decided with the real budget
    assert sum(v == "unsat" for pid, v in by_pid.items() if pid > 1) == 200


def test_retry_unknown_csv_stays_one_row_per_partition(tmp_path):
    import csv as _csv
    import json

    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.verify import csvio

    net = init_mlp((20, 8, 1), seed=3)
    cfg = presets.get("GC").with_(
        result_dir=str(tmp_path), soft_timeout_s=30.0, hard_timeout_s=300.0,
        sim_size=64, exact_certify_masks=False)
    first = sweep.verify_model(net, cfg, model_name="m", resume=False)
    # Force partition 5 back to unknown in the ledger, then retry.
    ledger = os.path.join(str(tmp_path), "GC-m.ledger.jsonl")
    with open(ledger, "a") as fp:
        fp.write(json.dumps({"partition_id": 5, "verdict": "unknown",
                             "ce": None, "time_s": 0.0}) + "\n")
    sweep.verify_model(net, cfg, model_name="m", resume=True, retry_unknown=True)
    with open(os.path.join(str(tmp_path), "m.csv"), newline="") as fp:
        rows = list(_csv.reader(fp))[1:]
    pids = [int(r[0]) for r in rows]
    assert pids == sorted(pids) and len(pids) == len(set(pids)) == 201


def test_retry_unknown_csv_counters_recomputed(tmp_path):
    import csv as _csv
    import json

    from fairify_tpu.models.train import init_mlp

    net = init_mlp((20, 8, 1), seed=3)
    cfg = presets.get("GC").with_(
        result_dir=str(tmp_path), soft_timeout_s=30.0, hard_timeout_s=300.0,
        sim_size=64, exact_certify_masks=False)
    sweep.verify_model(net, cfg, model_name="m", resume=False)
    ledger = os.path.join(str(tmp_path), "GC-m.ledger.jsonl")
    with open(ledger, "a") as fp:
        fp.write(json.dumps({"partition_id": 5, "verdict": "unknown",
                             "ce": None, "time_s": 0.0}) + "\n")
    rep = sweep.verify_model(net, cfg, model_name="m", resume=True,
                             retry_unknown=True)
    with open(os.path.join(str(tmp_path), "m.csv"), newline="") as fp:
        rows = list(_csv.reader(fp))[1:]
    # Counters must be cumulative and consistent with the final verdicts.
    counts = {"sat": 0, "unsat": 0, "unknown": 0}
    for row in rows:
        counts[row[1]] += 1
        assert [int(row[2]), int(row[3]), int(row[4])] == [
            counts["sat"], counts["unsat"], counts["unknown"]]
    assert counts == rep.counts


def test_partition_metrics_csv_schema(tmp_path, tiny_registered):
    """VERDICT r3 #4: the flag-gated per-partition group-metric CSV must
    appear next to the 24-col CSV with the reference CP driver's columns
    (``src/CP/Verify-CP.py:448-458``), one row per newly-decided
    partition, with finite metric values."""
    import pandas as pd

    from fairify_tpu.data.loaders import LoadedDataset

    rng = np.random.default_rng(7)
    net = random_net(rng, (3, 6, 1))
    X = rng.integers(0, 5, size=(60, 3)).astype(np.float64)
    X[:, 1] = rng.integers(0, 2, size=60)  # pa column
    y = rng.integers(0, 2, size=60)
    ds = LoadedDataset(name="tinysweep", df=pd.DataFrame(X),
                       X_train=X, y_train=y, X_test=X, y_test=y, label="y")
    cfg = make_cfg(tmp_path, partition_metrics=True)
    report = sweep.verify_model(net, cfg, model_name="tiny-1", dataset=ds)

    path = os.path.join(str(tmp_path), "tiny-1-metrics.csv")
    with open(path) as fp:
        rows = list(csv.reader(fp))
    assert rows[0] == ["Partition ID", "Original Accuracy",
                       "Original F1 Score", "Pruned Accuracy", "Pruned F1",
                       "DI", "SPD", "EOD", "AOD", "ERD", "CNT", "TI"]
    assert len(rows) == 1 + report.partitions_total
    ids = sorted(int(r[0]) for r in rows[1:])
    assert ids == [o.partition_id for o in sorted(
        report.outcomes, key=lambda o: o.partition_id)]
    for r in rows[1:]:
        vals = [float(v) for v in r[1:]]
        # DI is legitimately inf when the privileged base rate is 0
        # (AIF360 semantics); everything else must be finite.
        assert all(np.isfinite(vals[:4]))
        assert all(np.isfinite(vals[5:]))
    # Resume adds no duplicate rows (append-once like the CE CSV).
    sweep.verify_model(net, cfg, model_name="tiny-1", dataset=ds)
    with open(path) as fp:
        assert len(list(csv.reader(fp))) == len(rows)

"""SMT encoding tests: the emitted SMT-LIB2 formula is evaluated with an
exact Fraction-arithmetic interpreter against known witnesses — so the
encoder is exercised (and its semantics pinned) without any solver in the
environment.  Where z3-solver IS importable, the live backend is
agreement-tested against the native engine too.
"""
from fractions import Fraction

import numpy as np
import pytest

from fairify_tpu.data.domains import DomainSpec
from fairify_tpu.models import mlp
from fairify_tpu.verify import property as prop
from fairify_tpu.verify import smt


# ---------------------------------------------------------------------------
# Minimal exact SMT-LIB interpreter (the subset to_smtlib emits)
# ---------------------------------------------------------------------------


def _tokenize(text):
    for line in text.splitlines():
        line = line.split(";", 1)[0]
        for tok in line.replace("(", " ( ").replace(")", " ) ").split():
            yield tok


def _parse_all(text):
    toks = list(_tokenize(text))
    pos = 0

    def parse():
        nonlocal pos
        tok = toks[pos]
        pos += 1
        if tok == "(":
            items = []
            while toks[pos] != ")":
                items.append(parse())
            pos += 1
            return items
        return tok

    forms = []
    while pos < len(toks):
        forms.append(parse())
    return forms


def _ev(e, env):
    if isinstance(e, str):
        if e in env:
            return env[e]
        if e == "true":
            return True
        if e == "false":
            return False
        return Fraction(e.replace(".0", "")) if "." in e else Fraction(int(e))
    op = e[0]
    if op == "+":
        return sum((_ev(a, env) for a in e[1:]), Fraction(0))
    if op == "*":
        r = Fraction(1)
        for a in e[1:]:
            r *= _ev(a, env)
        return r
    if op == "-":
        if len(e) == 2:
            return -_ev(e[1], env)
        return _ev(e[1], env) - _ev(e[2], env)
    if op == "/":
        return _ev(e[1], env) / _ev(e[2], env)
    if op == "to_real":
        return _ev(e[1], env)
    if op == "ite":
        return _ev(e[2], env) if _ev(e[1], env) else _ev(e[3], env)
    if op == ">=":
        return _ev(e[1], env) >= _ev(e[2], env)
    if op == "<=":
        return _ev(e[1], env) <= _ev(e[2], env)
    if op == ">":
        return _ev(e[1], env) > _ev(e[2], env)
    if op == "<":
        return _ev(e[1], env) < _ev(e[2], env)
    if op == "=":
        return _ev(e[1], env) == _ev(e[2], env)
    if op == "distinct":
        return _ev(e[1], env) != _ev(e[2], env)
    if op == "and":
        return all(_ev(a, env) for a in e[1:])
    if op == "or":
        return any(_ev(a, env) for a in e[1:])
    if op == "not":
        return not _ev(e[1], env)
    if op == "let":
        inner = dict(env)
        for name, expr in e[1]:
            inner[name] = _ev(expr, env)
        return _ev(e[2], inner)
    raise ValueError(f"unhandled op {op}")


def holds(text, assignment):
    """True iff every (assert ...) in the script holds under the assignment."""
    env = {k: Fraction(v) for k, v in assignment.items()}
    for form in _parse_all(text):
        if form[0] == "define-fun":
            env[form[1]] = _ev(form[4], env)
        elif form[0] == "assert":
            if not _ev(form[1], env):
                return False
    return True


# ---------------------------------------------------------------------------
# Encoder semantics
# ---------------------------------------------------------------------------


def _toy(ranges):
    cols = tuple(ranges)
    return DomainSpec(name="toy", columns=cols,
                      ranges={k: tuple(v) for k, v in ranges.items()}, label="y")


def _flip_net():
    # logit = relu(2·pa) − 1: pa=0 → −1, pa=1 → +1 (guaranteed flip pair).
    ws = [np.array([[0.0], [2.0]], dtype=np.float32),
          np.array([[1.0]], dtype=np.float32)]
    bs = [np.array([0.0], dtype=np.float32),
          np.array([-1.0], dtype=np.float32)]
    return mlp.from_numpy(ws, bs)


def _setup(relaxed=False):
    ranges = {"a": (0, 3), "pa": (0, 1)}
    q = prop.FairnessQuery(domain=_toy(ranges), protected=("pa",),
                           relaxed=("a",) if relaxed else (),
                           relax_eps=1 if relaxed else 0)
    enc = prop.encode(q)
    lo, hi = q.domain.lo_hi()
    return enc, lo.astype(np.int64), hi.astype(np.int64)


def test_smtlib_witness_satisfies():
    enc, lo, hi = _setup()
    text = smt.to_smtlib(_flip_net(), enc, lo, hi)
    assert "(check-sat)" in text and "QF_LIRA" in text
    # (a=1, pa=0) vs (a=1, pa=1): logits −1 / +1 — a genuine flip pair.
    assert holds(text, {"x0": 1, "x1": 0, "xp0": 1, "xp1": 1})


def test_smtlib_rejects_equal_pa():
    enc, lo, hi = _setup()
    text = smt.to_smtlib(_flip_net(), enc, lo, hi)
    assert not holds(text, {"x0": 1, "x1": 1, "xp0": 1, "xp1": 1})


def test_smtlib_rejects_shared_dim_mismatch():
    enc, lo, hi = _setup()
    text = smt.to_smtlib(_flip_net(), enc, lo, hi)
    # non-PA dim differs (0 vs 2) with no RA declared → equality violated.
    assert not holds(text, {"x0": 0, "x1": 0, "xp0": 2, "xp1": 1})


def test_smtlib_rejects_out_of_box():
    enc, lo, hi = _setup()
    text = smt.to_smtlib(_flip_net(), enc, lo, hi)
    assert not holds(text, {"x0": 9, "x1": 0, "xp0": 9, "xp1": 1})


def test_smtlib_rejects_no_flip():
    enc, lo, hi = _setup()
    # Constant-positive logit: no pair can satisfy the flip disjunction.
    ws = [np.zeros((2, 1), dtype=np.float32)]
    bs = [np.array([1.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    text = smt.to_smtlib(net, enc, lo, hi)
    assert not holds(text, {"x0": 1, "x1": 0, "xp0": 1, "xp1": 1})


def test_smtlib_relaxed_attribute_ball():
    enc, lo, hi = _setup(relaxed=True)
    text = smt.to_smtlib(_flip_net(), enc, lo, hi)
    # |Δa| = 1 ≤ ε: allowed (and x' may even leave the box by ε).
    assert holds(text, {"x0": 1, "x1": 0, "xp0": 2, "xp1": 1})
    # |Δa| = 3 > ε: rejected.
    assert not holds(text, {"x0": 0, "x1": 0, "xp0": 3, "xp1": 1})


def test_smtlib_exact_rational_weights():
    # 0.1f32 is not 1/10; the literal must be its exact dyadic value.
    ws = [np.array([[np.float32(0.1)], [0.0]], dtype=np.float32)]
    bs = [np.array([0.0], dtype=np.float32)]
    enc, lo, hi = _setup()
    text = smt.to_smtlib(mlp.from_numpy(ws, bs), enc, lo, hi)
    assert "(/ 13421773 134217728)" in text


def test_smtlib_masked_neurons_excised():
    net = _flip_net()
    net = mlp.MLP(net.weights, net.biases,
                  (np.array([0.0], dtype=np.float32),  # kill the hidden unit
                   np.ones(1, dtype=np.float32)))
    enc, lo, hi = _setup()
    text = smt.to_smtlib(net, enc, lo, hi)
    # Pruned hidden layer has no neurons: logit ≡ −1 for both roles.
    assert not holds(text, {"x0": 1, "x1": 0, "xp0": 1, "xp1": 1})


# ---------------------------------------------------------------------------
# Live Z3 agreement (runs wherever z3-solver is installed)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not smt.HAVE_Z3, reason="z3-solver not installed")
@pytest.mark.parametrize("seed", range(5))
def test_z3_agrees_with_native_engine(seed):
    from fairify_tpu.verify import engine

    rng = np.random.default_rng(seed)
    ranges = {"a": (0, 3), "pa": (0, 1), "b": (0, 3)}
    q = prop.FairnessQuery(domain=_toy(ranges), protected=("pa",))
    enc = prop.encode(q)
    lo, hi = q.domain.lo_hi()
    ws = [rng.normal(size=(3, 6)).astype(np.float32),
          rng.normal(size=(6, 1)).astype(np.float32)]
    bs = [rng.normal(size=(6,)).astype(np.float32) * 0.5,
          rng.normal(size=(1,)).astype(np.float32)]
    net = mlp.from_numpy(ws, bs)
    native = engine.decide_box(net, enc, lo.astype(np.int64), hi.astype(np.int64),
                               engine.EngineConfig(soft_timeout_s=30.0))
    smt_verdict, _, _reason = smt.decide_box_smt(net, enc, lo.astype(np.int64),
                                                 hi.astype(np.int64))
    if "unknown" not in (native.verdict, smt_verdict):
        assert native.verdict == smt_verdict

"""The persistent verification server (``fairify_tpu/serve``, DESIGN.md §13).

Four contracts:

* **cross-request isolation** — concurrent requests coalesced into shared
  arch-bucketed family launches produce ledgers bit-equal to their solo
  runs (same pinning style as the pipeline depth-invariance tests: the
  family kernels are the solo kernels under vmap with globally-keyed RNG);
* **SLA admission** — the budgeted-sweep predicate at request granularity:
  infeasible deadlines are rejected at submit, queue-expired deadlines
  fail fast without executing, and ``scripts/_sweeplib.py`` delegates its
  span predicate here so harness and service cannot drift;
* **graceful drain** — queued requests requeue to the spool inbox for the
  next server, a drain mid-request (span-granular mode) preempts at a
  chunk-aligned boundary, and ``resume=True`` pickup converges to the
  solo verdict map;
* **warm-cache economics** — after one warmup request, a batch of
  concurrent same-bucket requests compiles nothing and launches strictly
  less than the same requests run sequentially (the coalescing headline).
"""
import json
import os
import time

import numpy as np
import pytest

from fairify_tpu import obs
from fairify_tpu.models.train import init_mlp
from fairify_tpu.obs import compile as compile_obs
from fairify_tpu.serve import (
    AdmissionController,
    AdmissionRejected,
    FleetConfig,
    ServeConfig,
    ServerFleet,
    VerificationServer,
    span_admissible,
)
from fairify_tpu.serve import batcher
from fairify_tpu.serve import client as client_mod
from fairify_tpu.serve import request as request_mod
from fairify_tpu.verify import presets, sweep

SPAN = (0, 48)


def _cfg(tmp_path, name, **kw):
    kw.setdefault("grid_chunk", 16)
    return presets.get("GC").with_(
        result_dir=str(tmp_path / name), soft_timeout_s=30.0,
        hard_timeout_s=600.0, sim_size=64, exact_certify_masks=False,
        launch_backoff_s=1e-4, **kw)


def _net(seed=3):
    return init_mlp((20, 8, 1), seed=seed)


def _omap(rep):
    """partition -> (verdict, counterexample bytes): the bit-equality key."""
    out = {}
    for o in rep.outcomes:
        ce = None if o.counterexample is None else tuple(
            np.asarray(x).tobytes() for x in o.counterexample)
        out[o.partition_id] = (o.verdict, ce)
    return out


# ---------------------------------------------------------------------------
# Admission: the budgeted-sweep predicate at request granularity
# ---------------------------------------------------------------------------


def test_span_admissible_is_the_sweeplib_predicate():
    # No measured rate: the span doubles as the throughput probe.
    assert span_admissible(None, depth=2, chunk=2048, left_s=0.1)
    # Committed in-flight backlog is depth x chunk, not one chunk.
    assert span_admissible(100.0, depth=1, chunk=100, left_s=10.0)
    assert not span_admissible(100.0, depth=8, chunk=100, left_s=10.0)
    # The harness's 0.4 safety factor is the default.
    assert not span_admissible(100.0, depth=1, chunk=100, left_s=2.0)
    assert span_admissible(100.0, depth=1, chunk=100, left_s=2.6)


class _Stub:
    def __init__(self, rid, partitions, deadline_s=None):
        self.id = rid
        self.partitions = partitions
        self.deadline_s = deadline_s


def test_admission_rejects_infeasible_deadline_once_rate_measured():
    ctl = AdmissionController()
    # First request always admits (it IS the throughput probe)...
    ctl.admit(_Stub("a", partitions=1000, deadline_s=0.5))
    # ...and its completion measures the service rate.
    ctl.finished(_Stub("a", 1000), partitions=1000, elapsed_s=10.0)
    assert ctl.rate() == pytest.approx(100.0)
    # 10k partitions at 100/s = 100s >> 0.8 * 2s deadline: reject.
    with pytest.raises(AdmissionRejected):
        ctl.admit(_Stub("b", partitions=10_000, deadline_s=2.0))
    # Best effort (no deadline) always admits.
    ctl.admit(_Stub("c", partitions=10_000))
    # Backlog accounting: c committed 100s of work; a feasible-alone
    # request must now see the queue ahead of it.
    with pytest.raises(AdmissionRejected):
        ctl.admit(_Stub("d", partitions=1000, deadline_s=12.0))
    ctl.release(_Stub("c", 10_000))
    ctl.admit(_Stub("d2", partitions=1000, deadline_s=15.0))


def test_admission_backlog_frees_on_finish():
    ctl = AdmissionController()
    ctl.admit(_Stub("a", 100))
    ctl.finished(_Stub("a", 100), partitions=100, elapsed_s=1.0)
    ctl.admit(_Stub("b", 500, deadline_s=60.0))
    assert ctl.backlog_s() == pytest.approx(5.0)
    ctl.finished(_Stub("b", 500), partitions=500, elapsed_s=5.0)
    assert ctl.backlog_s() == 0.0
    assert ctl.estimate_s(100) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# Overload control: priority queue, bounded-queue shedding, preemption
# ---------------------------------------------------------------------------


class _PStub:
    def __init__(self, rid, partitions, deadline_s=None, priority=1):
        self.id = rid
        self.partitions = partitions
        self.deadline_s = deadline_s
        self.priority = priority


def test_priority_queue_pops_high_first(tmp_path):
    """Higher tiers pop first; FIFO within a tier."""
    srv = VerificationServer(ServeConfig())  # never started: queue holds
    cfg = _cfg(tmp_path, "p")
    lo = srv.submit(cfg, _net(1), "lo", partition_span=SPAN, priority=0)
    n1 = srv.submit(cfg, _net(2), "n1", partition_span=SPAN, priority=1)
    hi = srv.submit(cfg, _net(3), "hi", partition_span=SPAN, priority=2)
    n2 = srv.submit(cfg, _net(4), "n2", partition_span=SPAN, priority=1)
    with srv._cv:
        batch = srv._pop_batch(3)
    assert [r.id for r in batch] == [hi.id, n1.id, n2.id]
    with srv._cv:
        rest = srv._pop_batch(3)
    assert [r.id for r in rest] == [lo.id]


def test_bounded_queue_sheds_with_priority_headroom():
    """max_queue sheds at depth x PRIORITY_HEADROOM: low sheds earliest,
    high rides into the safety margin; the reason is machine-readable."""
    ctl = AdmissionController(max_queue=2)
    ctl.admit(_PStub("a", 10), queue_depth=1)          # under the bound
    with pytest.raises(AdmissionRejected) as exc:
        ctl.admit(_PStub("b", 10), queue_depth=2)      # normal: sheds at 2
    assert exc.value.kind == "shed"
    assert str(exc.value).startswith("shed: queue full")
    with pytest.raises(AdmissionRejected):
        ctl.admit(_PStub("c", 10, priority=0), queue_depth=2)  # low: earlier
    ctl.admit(_PStub("d", 10, priority=2), queue_depth=2)  # high: headroom
    with pytest.raises(AdmissionRejected):
        ctl.admit(_PStub("e", 10, priority=2), queue_depth=3)


def test_feasibility_shed_reason_and_readmit():
    """Deadline-infeasible submits shed with kind='shed'; the failover
    readmit path accounts backlog but never sheds."""
    ctl = AdmissionController()
    ctl.admit(_PStub("probe", 1000, deadline_s=None))
    ctl.finished(_PStub("probe", 1000), partitions=1000, elapsed_s=10.0)
    with pytest.raises(AdmissionRejected) as exc:
        ctl.admit(_PStub("b", 10_000, deadline_s=2.0))
    assert exc.value.kind == "shed"
    assert "deadline-infeasible" in str(exc.value)
    # readmit: same request would shed, but an already-admitted request
    # re-homed by failover must land — and still commit backlog.
    before = ctl.backlog_s()
    ctl.readmit(_PStub("b", 10_000, deadline_s=2.0))
    assert ctl.backlog_s() > before


def test_shed_is_terminal_and_client_visible(tmp_path):
    srv = VerificationServer(ServeConfig(max_queue=1))
    cfg = _cfg(tmp_path, "s0")
    os.makedirs(cfg.result_dir, exist_ok=True)
    srv.submit(cfg, _net(1), "a", partition_span=SPAN)
    cfg2 = _cfg(tmp_path, "s1")
    os.makedirs(cfg2.result_dir, exist_ok=True)
    shed = srv.submit(cfg2, _net(2), "b", partition_span=SPAN)
    assert shed.status == "rejected"
    assert shed.reason.startswith("shed:")
    with open(os.path.join(cfg2.result_dir, "status.json")) as fp:
        rec = json.load(fp)
    assert rec["status"] == "rejected" and rec["reason"].startswith("shed:")


def test_preemption_requeues_and_converges(tmp_path, solo_maps):
    """A running over-budget low-priority request yields at its next
    span granule to a queued higher tier, requeues with its partial
    ledger, and still converges bit-equal to its solo run."""
    srv = VerificationServer(ServeConfig(
        batch_window_s=0.05, span_chunks=1, preempt_factor=1.0))
    # Pre-measure an (optimistic) service rate so the estimate exists and
    # any real elapsed time reads as over-budget.
    srv.admission.finished(_PStub("warm", 10_000_000),
                          partitions=10_000_000, elapsed_s=1.0)
    low = srv.submit(_cfg(tmp_path, "low"), _net(3), "m3",
                     partition_span=SPAN, priority=0)
    srv.start()
    t0 = time.monotonic()
    while time.monotonic() - t0 < 60.0:
        if low.status == "running":
            break
        time.sleep(0.002)
    hi = srv.submit(_cfg(tmp_path, "hi"), _net(5), "m5",
                    partition_span=SPAN, priority=2)
    f_lo = srv.wait(low.id, timeout=600.0)
    f_hi = srv.wait(hi.id, timeout=600.0)
    srv.drain()
    assert f_hi.status == "done", f_hi.reason
    assert f_lo.status == "done", f_lo.reason
    assert f_lo.preemptions >= 1, "the low-priority request never yielded"
    assert _omap(f_lo.report) == solo_maps[3]
    assert _omap(f_hi.report) == solo_maps[5]


# ---------------------------------------------------------------------------
# Fleet: warm replicas, routing, failover
# ---------------------------------------------------------------------------


def test_warm_fleet_zero_compiles_and_bit_equal(tmp_path, solo_maps):
    """ISSUE 11 satellite pin: a warmed fleet serving a same-bucket mix
    compiles NOTHING (xla_compiles == 0 across the wave) and every
    request's verdicts stay bit-equal to its solo run."""
    fl = ServerFleet(FleetConfig(
        n_replicas=2, poll_s=0.02,
        replica=ServeConfig(batch_window_s=0.2, max_batch=4)))
    fl.start()
    # Warm both buckets (two architectures) until quiescent.
    for name, net, n in (("w8", _net(99), (20, 8, 1)),
                         ("w6", init_mlp((20, 6, 1), seed=42), (20, 6, 1))):
        r = fl.submit(_cfg(tmp_path, name), net, name, partition_span=SPAN)
        assert fl.wait(r.id, timeout=600.0).status == "done"
    wave = [fl.submit(_cfg(tmp_path, f"wv{i}"), _net(60 + i), f"wv{i}",
                      partition_span=SPAN) for i in range(2)]
    for r in wave:
        assert fl.wait(r.id, timeout=600.0).status == "done"
    compiles0 = compile_obs.snapshot_totals()["n_compiles"]
    reqs = [
        fl.submit(_cfg(tmp_path, "fa"), _net(3), "m3", partition_span=SPAN),
        fl.submit(_cfg(tmp_path, "fb"), _net(5), "m5", partition_span=SPAN),
        fl.submit(_cfg(tmp_path, "fc"), init_mlp((20, 6, 1), seed=9),
                  "modd", partition_span=SPAN),
    ]
    finals = [fl.wait(r.id, timeout=600.0) for r in reqs]
    fl.drain()
    assert all(f.status == "done" for f in finals), \
        [f.reason for f in finals]
    assert compile_obs.snapshot_totals()["n_compiles"] == compiles0, \
        "a warm fleet recompiled on same-bucket traffic"
    assert _omap(finals[0].report) == solo_maps[3]
    assert _omap(finals[1].report) == solo_maps[5]
    assert _omap(finals[2].report) == solo_maps["odd"]


def test_fleet_failover_mid_request_loses_nothing(tmp_path, solo_maps):
    """Kill the replica that owns a RUNNING request: the router re-homes
    it to the survivor, resume=True replays the partial ledger, and the
    final verdict map is bit-equal to the fault-free solo run."""
    fl = ServerFleet(FleetConfig(
        n_replicas=2, poll_s=0.02,
        replica=ServeConfig(batch_window_s=0.05, span_chunks=1)))
    fl.start()
    req = fl.submit(_cfg(tmp_path, "fo"), _net(3), "m3", partition_span=SPAN)
    owner = fl.owner_of(req.id)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 60.0:
        cur = fl.get(req.id)
        if cur is not None and cur.status == "running":
            break
        time.sleep(0.002)
    fl._replicas[owner].kill()
    final = fl.wait(req.id, timeout=600.0)
    assert final is not None and final.status == "done", \
        (final and final.reason)
    assert fl.owner_of(req.id) != owner, "request was not re-homed"
    assert fl.replicas_alive() == 1
    assert {p: v for p, (v, _) in _omap(final.report).items()} \
        == {p: v for p, (v, _) in solo_maps[3].items()}
    fl.drain()


def test_fleet_routing_sticky_then_spills(tmp_path):
    """Same bucket pins to one replica; once that replica's committed
    load passes spill_load, new requests go to the least-loaded."""
    fl = ServerFleet(FleetConfig(
        n_replicas=2, spill_load=2,
        replica=ServeConfig(batch_window_s=0.2)))
    cfg = _cfg(tmp_path, "rt")
    # Not started: requests pile up on the pinned replica's queue.
    first = [fl.submit(cfg, _net(1), f"m{i}", partition_span=SPAN)
             for i in range(2)]
    owners = {fl.owner_of(r.id) for r in first}
    assert len(owners) == 1, "bucket must pin to one replica"
    spilled = fl.submit(cfg, _net(1), "spill", partition_span=SPAN)
    assert fl.owner_of(spilled.id) not in owners, \
        "saturated bucket must spill to the other replica"
    fl.drain()


# ---------------------------------------------------------------------------
# Batcher: bucketing rules
# ---------------------------------------------------------------------------


def test_plan_buckets_same_signature_and_arch_only(tmp_path):
    cfg = _cfg(tmp_path, "a")

    def req(rid, net, cfg=cfg, span=SPAN):
        r = request_mod.VerifyRequest(
            id=rid, cfg=cfg, net=net, model_name=rid, partition_span=span)
        return r

    a, b = req("a", _net(1)), req("b", _net(2))
    c = req("c", init_mlp((20, 6, 1), seed=3))      # different arch
    d = req("d", _net(4), cfg=cfg.with_(seed=7))    # different grid seed
    e = req("e", _net(5), span=(16, 48))            # different span
    buckets = batcher.plan_buckets([a, b, c, d, e])
    assert [sorted(r.id for r in bk) for bk in buckets] == [["a", "b"]]


def test_stage0_signature_excludes_budgets(tmp_path):
    cfg = _cfg(tmp_path, "a")
    sig1 = batcher.stage0_signature(cfg, None)
    sig2 = batcher.stage0_signature(
        cfg.with_(soft_timeout_s=1.0, hard_timeout_s=2.0,
                  result_dir=str(tmp_path / "elsewhere")), None)
    assert sig1 == sig2
    assert batcher.stage0_signature(cfg.with_(grid_chunk=8), None) != sig1


# ---------------------------------------------------------------------------
# Cross-request verdict isolation (the tentpole contract)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def solo_maps(tmp_path_factory):
    """Solo-run verdict maps for the nets the coalescing tests share."""
    td = tmp_path_factory.mktemp("serve_solo")
    out = {}
    for seed in (3, 5):
        rep = sweep.verify_model(
            _net(seed), _cfg(td, f"solo-{seed}"), model_name=f"m{seed}",
            resume=False, partition_span=SPAN)
        out[seed] = _omap(rep)
    rep = sweep.verify_model(
        init_mlp((20, 6, 1), seed=9), _cfg(td, "solo-odd"),
        model_name="modd", resume=False, partition_span=SPAN)
    out["odd"] = _omap(rep)
    assert out[3], "solo span produced no outcomes"
    return out


def test_concurrent_requests_coalesced_bit_equal_solo(tmp_path, solo_maps):
    """Two same-arch requests coalesce into shared family launches; a
    third odd-arch request rides the same batch on the solo path.  All
    three ledgers must be bit-equal to their solo runs."""
    srv = VerificationServer(ServeConfig(batch_window_s=0.5, max_batch=8))
    # Queue BEFORE starting the worker: all three are guaranteed to land
    # in one batch, so the coalesced path (not timing luck) is under test.
    ra = srv.submit(_cfg(tmp_path, "ra"), _net(3), "m3", partition_span=SPAN)
    rb = srv.submit(_cfg(tmp_path, "rb"), _net(5), "m5", partition_span=SPAN)
    rc = srv.submit(_cfg(tmp_path, "rc"), init_mlp((20, 6, 1), seed=9),
                    "modd", partition_span=SPAN)
    h = obs.registry().histogram("serve_batch_occupancy")
    occ0 = h.count()
    srv.start()
    fa = srv.wait(ra.id, timeout=600.0)
    fb = srv.wait(rb.id, timeout=600.0)
    fc = srv.wait(rc.id, timeout=600.0)
    srv.drain()
    assert (fa.status, fb.status, fc.status) == ("done",) * 3, \
        (fa.reason, fb.reason, fc.reason)
    assert h.count() > occ0, "batch never coalesced: test proved nothing"
    assert _omap(fa.report) == solo_maps[3]
    assert _omap(fb.report) == solo_maps[5]
    assert _omap(fc.report) == solo_maps["odd"]
    # The streamed ledger is the client-visible result: same verdicts.
    led = os.path.join(str(tmp_path / "ra"),
                       f"GC-{fa.report.sink_name}.ledger.jsonl")
    with open(led) as fp:
        recs = {r["partition_id"]: r["verdict"]
                for r in map(json.loads, fp) if "partition_id" in r}
    assert recs == {pid: v for pid, (v, _) in solo_maps[3].items()}


def test_warm_server_no_recompile_and_fewer_launches(tmp_path):
    """ISSUE 8 acceptance shape (CI scale): after warmup (one solo
    request + one coalesced wave that compiles the fixed-width family
    executable) a 4-request concurrent batch compiles nothing and
    launches strictly less than the same 4 spans run sequentially."""
    launches = obs.registry().counter("device_launches")
    # Sequential baseline, measured warm in this same process.
    seq0 = launches.total()
    seq_maps = {}
    for i, seed in enumerate((11, 12, 13, 14)):
        rep = sweep.verify_model(
            _net(seed), _cfg(tmp_path, f"seq-{i}"), model_name=f"s{seed}",
            resume=False, partition_span=SPAN)
        seq_maps[seed] = _omap(rep)
    sequential = launches.total() - seq0
    srv = VerificationServer(ServeConfig(batch_window_s=0.5, max_batch=4))
    # Warmup: solo kernels (one request) + the 4-wide family executable
    # (a coalesced pair — pad_models stretches it to the full max_batch
    # width, so ANY later occupancy hits the same compiled shape).
    w = srv.submit(_cfg(tmp_path, "w"), _net(99), "w", partition_span=SPAN)
    w1 = srv.submit(_cfg(tmp_path, "w1"), _net(21), "w1", partition_span=SPAN)
    w2 = srv.submit(_cfg(tmp_path, "w2"), _net(22), "w2", partition_span=SPAN)
    srv.start()
    for req in (w, w1, w2):
        assert srv.wait(req.id, timeout=600.0).status == "done"
    compiles0 = compile_obs.snapshot_totals()["n_compiles"]
    served0 = launches.total()
    reqs = [srv.submit(_cfg(tmp_path, f"c-{i}"), _net(seed), f"s{seed}",
                       partition_span=SPAN)
            for i, seed in enumerate((11, 12, 13, 14))]
    finals = [srv.wait(r.id, timeout=600.0) for r in reqs]
    served = launches.total() - served0
    srv.drain()
    assert all(f.status == "done" for f in finals)
    assert compile_obs.snapshot_totals()["n_compiles"] == compiles0, \
        "a warm server recompiled on a same-bucket batch"
    assert served < sequential, \
        f"coalescing not working: {served} served vs {sequential} sequential"
    for f, seed in zip(finals, (11, 12, 13, 14)):
        assert _omap(f.report) == seq_maps[seed], f"request s{seed} diverged"


def test_sharded_server_routes_through_fleet_bit_equal(tmp_path, solo_maps):
    """``--shards N`` routes requests through the PR 7 shard fleet (per-
    request fault domains over the virtual 8-device mesh); verdicts stay
    bit-equal to the single-chip solo run."""
    srv = VerificationServer(ServeConfig(n_shards=2))
    req = srv.submit(_cfg(tmp_path, "sh"), _net(3), "m3", partition_span=SPAN)
    srv.start()
    final = srv.wait(req.id, timeout=600.0)
    srv.drain()
    assert final.status == "done", final.reason
    assert {p: v for p, (v, _) in _omap(final.report).items()} \
        == {p: v for p, (v, _) in solo_maps[3].items()}


# ---------------------------------------------------------------------------
# SLA enforcement inside the server loop
# ---------------------------------------------------------------------------


def test_queue_expired_deadline_fails_fast_without_executing(tmp_path):
    srv = VerificationServer(ServeConfig(batch_window_s=0.05))
    launches = obs.registry().counter("device_launches")
    l0 = launches.total()
    req = srv.submit(_cfg(tmp_path, "r"), _net(3), "m",
                     deadline_s=1e-4, partition_span=SPAN)
    time.sleep(0.01)  # guarantee the SLA is already blown in queue
    srv.start()
    final = srv.wait(req.id, timeout=60.0)
    srv.drain()
    assert final.status == "failed"
    assert final.deadline_missed
    assert "deadline expired in queue" in final.reason
    assert launches.total() == l0, "an expired request reached the device"


def test_submit_after_drain_rejected(tmp_path):
    srv = VerificationServer(ServeConfig())
    srv.start()
    srv.drain()
    cfg = _cfg(tmp_path, "r")
    os.makedirs(cfg.result_dir, exist_ok=True)
    req = srv.submit(cfg, _net(3), "m", partition_span=SPAN)
    assert req.status == "rejected"
    assert "draining" in req.reason
    # Rejection is terminal: the client-visible status.json must land so
    # a polling client unblocks instead of waiting out its timeout.
    with open(os.path.join(cfg.result_dir, "status.json")) as fp:
        assert json.load(fp)["status"] == "rejected"


# ---------------------------------------------------------------------------
# Graceful drain + spool resume
# ---------------------------------------------------------------------------


def test_drain_requeues_queued_to_inbox_and_next_server_finishes(
        tmp_path, solo_maps):
    spool = str(tmp_path / "spool")
    payload = client_mod.build_payload(
        "GC", init={"sizes": [20, 8, 1], "seed": 3},
        overrides={"soft_timeout_s": 30.0, "hard_timeout_s": 600.0,
                   "sim_size": 64, "exact_certify_masks": False,
                   "grid_chunk": 16, "launch_backoff_s": 1e-4},
        span=SPAN)
    rid = client_mod.submit(spool, payload)
    # Server 1 ingests the inbox but drains before the worker runs it.
    srv1 = VerificationServer(ServeConfig(spool=spool))
    srv1._scan_inbox()
    requeued = srv1.drain()
    assert [r.id for r in requeued] == [rid]
    assert os.path.exists(os.path.join(spool, "inbox", f"{rid}.json")), \
        "drain must write the queued request back to the inbox"
    # Server 2 picks it up and converges to the solo verdict map.
    srv2 = VerificationServer(ServeConfig(spool=spool, poll_s=0.02))
    srv2.start()
    final = srv2.wait(rid, timeout=600.0)
    srv2.drain()
    assert final is not None and final.status == "done", \
        (final and final.reason)
    assert {p: v for p, (v, _) in _omap(final.report).items()} \
        == {p: v for p, (v, _) in solo_maps[3].items()}
    # The lifecycle journal recorded the requeue then the completion.
    with open(os.path.join(spool, "serve.journal.jsonl")) as fp:
        statuses = [r["status"] for r in map(json.loads, fp)
                    if r.get("request") == rid]
    assert "requeued" in statuses and statuses[-1] == "done"


def test_drain_mid_request_preempts_at_span_boundary_then_resumes(
        tmp_path, solo_maps):
    """Span-granular mode: a drain lands between chunk-aligned granules;
    the requeued request's next server replays the ledger and converges."""
    spool = str(tmp_path / "spool")
    payload = client_mod.build_payload(
        "GC", init={"sizes": [20, 8, 1], "seed": 3},
        overrides={"soft_timeout_s": 30.0, "hard_timeout_s": 600.0,
                   "sim_size": 64, "exact_certify_masks": False,
                   "grid_chunk": 16, "launch_backoff_s": 1e-4},
        span=SPAN)
    rid = client_mod.submit(spool, payload)
    srv1 = VerificationServer(
        ServeConfig(spool=spool, span_chunks=1, poll_s=0.02))
    srv1.start()
    ledger = os.path.join(spool, "requests", rid,
                          f"GC-init20x8x1-s3@{SPAN[0]}-{SPAN[1]}.ledger.jsonl")
    t0 = time.monotonic()
    while time.monotonic() - t0 < 300.0:  # first granule decided?
        if os.path.exists(ledger) and os.path.getsize(ledger) > 0:
            break
        time.sleep(0.02)
    srv1.drain()
    mid = srv1.get(rid)
    assert mid is not None
    # Deterministically preempted mid-request unless the whole request
    # outran the poll (tiny span): either way the next server converges.
    assert mid.status in ("requeued", "done"), mid.reason
    if mid.status == "requeued":
        assert "drained mid-request" in mid.reason
        srv2 = VerificationServer(
            ServeConfig(spool=spool, span_chunks=1, poll_s=0.02))
        srv2.start()
        final = srv2.wait(rid, timeout=600.0)
        srv2.drain()
        assert final is not None and final.status == "done", \
            (final and final.reason)
        got = final
    else:
        got = mid
    assert {p: v for p, (v, _) in _omap(got.report).items()} \
        == {p: v for p, (v, _) in solo_maps[3].items()}


# ---------------------------------------------------------------------------
# Client protocol + report table
# ---------------------------------------------------------------------------


def test_build_payload_validates():
    with pytest.raises(ValueError):
        client_mod.build_payload("GC")  # neither model nor init
    with pytest.raises(ValueError):
        client_mod.build_payload("GC", model="GC-1",
                                 init={"sizes": [4, 1]})  # both
    with pytest.raises(ValueError):
        client_mod.build_payload("GC", init={"sizes": [4]})  # no layers


def test_resolve_payload_rejects_mismatched_input_dim(tmp_path):
    # A 16-input net against GC's 20-attribute domain would fatally
    # degrade every launch — the resolve gate mirrors run_sweep's.
    payload = client_mod.build_payload(
        "GC", init={"sizes": [16, 6, 1], "seed": 0})
    with pytest.raises(ValueError, match="domain dim"):
        client_mod.resolve_payload(payload, str(tmp_path / "rdir"))


def test_unresolvable_payload_writes_rejected_status(tmp_path):
    """A bad spool payload must unblock the waiting client with a terminal
    ``rejected`` status.json before any device launch, not hang it."""
    spool = str(tmp_path / "spool")
    rid = client_mod.submit(spool, client_mod.build_payload(
        "GC", init={"sizes": [16, 6, 1], "seed": 0}))
    launches = obs.registry().counter("device_launches")
    l0 = launches.total()
    srv = VerificationServer(ServeConfig(spool=spool))
    srv._scan_inbox()
    srv.drain()
    rec = client_mod.status(spool, rid)
    assert rec is not None and rec["status"] == "rejected"
    assert "domain dim" in rec["reason"]
    assert launches.total() == l0, "a rejected payload reached the device"
    with open(os.path.join(spool, "serve.journal.jsonl")) as fp:
        statuses = [r["status"] for r in map(json.loads, fp)
                    if r.get("request") == rid]
    assert statuses and statuses[-1] == "rejected"


def test_requeued_pickup_preserves_sla_clock(tmp_path):
    """The deadline is wall-clock from the ORIGINAL submit: a payload that
    sat through a drain/requeue handoff must not get a fresh SLA clock at
    the next server."""
    spool = str(tmp_path / "spool")
    payload = client_mod.build_payload(
        "GC", init={"sizes": [20, 8, 1], "seed": 3},
        overrides={"grid_chunk": 16}, deadline_s=60.0, span=SPAN)
    rid = client_mod.submit(spool, payload)
    path = os.path.join(spool, "inbox", f"{rid}.json")
    with open(path) as fp:
        rec = json.load(fp)
    assert "submitted_ts" in rec
    rec["submitted_ts"] -= 100.0    # original submit was 100 s ago
    with open(path, "w") as fp:
        json.dump(rec, fp)
    srv = VerificationServer(ServeConfig(spool=spool, poll_s=0.02))
    srv.start()
    final = srv.wait(rid, timeout=120.0)
    srv.drain()
    assert final is not None and final.status == "failed", \
        (final and final.status)
    assert final.deadline_missed
    assert "deadline expired in queue" in final.reason


def test_grid_cache_builds_once_per_signature(tmp_path, monkeypatch):
    from fairify_tpu.verify import sweep as sweep_mod

    calls = {"n": 0}
    real = sweep_mod.build_partitions

    def counting(cfg):
        calls["n"] += 1
        return real(cfg)

    monkeypatch.setattr(sweep_mod, "build_partitions", counting)
    srv = VerificationServer(ServeConfig())
    cfg = _cfg(tmp_path, "a")
    assert srv._span_size(cfg, None) > 0
    # Same signature (budgets/sinks excluded): admission sizing and the
    # batcher's grid_fn both hit the memo.
    srv._span_size(cfg.with_(result_dir=str(tmp_path / "b")), None)
    srv._grid(cfg.with_(soft_timeout_s=1.0))
    assert calls["n"] == 1


def test_corrupt_inbox_payload_quarantined_and_rejected(tmp_path):
    """A torn .json can't be a mid-write (the client commit is
    rename-atomic): quarantine it — never re-parse it every poll — and
    reject terminally so the submitting client unblocks."""
    spool = str(tmp_path / "spool")
    srv = VerificationServer(ServeConfig(spool=spool))
    path = os.path.join(spool, "inbox", "rbad.json")
    with open(path, "w") as fp:
        fp.write("{not json")
    srv._scan_inbox()
    srv.drain()
    assert not os.path.exists(path)
    assert os.path.exists(path + ".corrupt")
    rec = client_mod.status(spool, "rbad")
    assert rec is not None and rec["status"] == "rejected"
    assert "corrupt payload" in rec["reason"]


def test_resolve_payload_pins_result_dir(tmp_path):
    payload = client_mod.build_payload(
        "GC", init={"sizes": [20, 8, 1], "seed": 3},
        overrides={"result_dir": "/somewhere/evil", "grid_chunk": 16})
    cfg, net, model_name, dataset = client_mod.resolve_payload(
        payload, str(tmp_path / "rdir"))
    assert cfg.result_dir == str(tmp_path / "rdir")
    assert cfg.grid_chunk == 16
    assert net.in_dim == 20 and net.layer_sizes == (8, 1)
    assert model_name == "init20x8x1-s3"
    assert dataset is None


def test_report_renders_request_table(tmp_path, capsys):
    from fairify_tpu.obs import report as report_mod

    log = tmp_path / "serve.events.jsonl"
    rows = [
        {"type": "event", "name": "request", "ts": 1.0, "tid": 1,
         "attrs": {"request": "r1", "status": "queued", "model": "m3",
                   "queue_wait_s": 0.0, "run_s": 0.0,
                   "deadline_missed": False}},
        {"type": "event", "name": "request", "ts": 2.0, "tid": 1,
         "attrs": {"request": "r1", "status": "done", "model": "m3",
                   "queue_wait_s": 0.2, "run_s": 4.5, "sat": 1, "unsat": 47,
                   "unknown": 0, "deadline_missed": False}},
        {"type": "event", "name": "request", "ts": 2.0, "tid": 1,
         "attrs": {"request": "r2", "status": "failed", "model": "m5",
                   "queue_wait_s": 3.0, "run_s": 0.0,
                   "deadline_missed": True, "reason": "deadline expired"}},
    ]
    log.write_text("".join(json.dumps(r) + "\n" for r in rows))
    agg = report_mod.aggregate([str(log)])
    assert agg["requests"]["r1"]["status"] == "done"  # last wins
    assert agg["requests"]["r1"]["decided"] == 48
    assert agg["requests"]["r2"]["deadline_missed"]
    assert report_mod.main([str(log)]) == 0
    out = capsys.readouterr().out
    assert "deadline misses: 1" in out
    assert "r1" in out and "done" in out

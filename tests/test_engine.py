"""Decision engine vs a brute-force oracle on small integer domains.

The oracle enumerates every legal (x, x') pair of the property — the ground
truth the reference would obtain from Z3 (``src/GC/Verify-GC.py:134-154``) —
and the engine's verdict must match, with SAT counterexamples validated
exactly.
"""
import numpy as np
import pytest

from fairify_tpu.models import mlp
from fairify_tpu.verify import engine, property as prop
from fairify_tpu.verify.oracle import (
    brute_force_verdict as oracle,
    random_net,
    tiny_domain,
)


CFG = engine.EngineConfig(frontier_size=64, attack_samples=32, bab_attack_samples=8,
                          soft_timeout_s=60.0, max_nodes=50_000)


@pytest.mark.parametrize("seed", range(8))
def test_engine_matches_oracle_basic(seed):
    rng = np.random.default_rng(seed)
    dom = tiny_domain({"a": (0, 3), "b": (0, 2), "pa": (0, 1), "c": (0, 2)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",))
    net = random_net(rng, (4, 6, 1))
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    want = oracle(net, query, lo.astype(np.int64), hi.astype(np.int64))
    got = engine.decide_box(net, enc, lo.astype(np.int64), hi.astype(np.int64), CFG)
    assert got.verdict == want
    if got.verdict == "sat":
        x, xp = got.counterexample
        ws = [np.asarray(w) for w in net.weights]
        bs = [np.asarray(b) for b in net.biases]
        assert engine.validate_pair(ws, bs, x, xp)
        # Pair is legal: equal off-PA, differing on PA, inside box on x.
        pa = set(enc.pa_idx.tolist())
        for i in range(len(x)):
            if i in pa:
                assert x[i] != xp[i]
            else:
                assert x[i] == xp[i]
        assert (x >= lo.astype(np.int64)).all() and (x <= hi.astype(np.int64)).all()


@pytest.mark.parametrize("seed", range(4))
def test_engine_matches_oracle_relaxed(seed):
    rng = np.random.default_rng(100 + seed)
    dom = tiny_domain({"a": (0, 3), "pa": (0, 1), "r": (0, 4)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",), relaxed=("r",), relax_eps=2)
    net = random_net(rng, (3, 5, 1))
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    want = oracle(net, query, lo.astype(np.int64), hi.astype(np.int64))
    got = engine.decide_box(net, enc, lo.astype(np.int64), hi.astype(np.int64), CFG)
    assert got.verdict == want


@pytest.mark.parametrize("seed", range(3))
def test_engine_matches_oracle_multi_pa(seed):
    rng = np.random.default_rng(200 + seed)
    dom = tiny_domain({"a": (0, 2), "pa1": (0, 1), "b": (0, 2), "pa2": (0, 2)})
    query = prop.FairnessQuery(domain=dom, protected=("pa1", "pa2"))
    net = random_net(rng, (4, 5, 1))
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    want = oracle(net, query, lo.astype(np.int64), hi.astype(np.int64))
    got = engine.decide_box(net, enc, lo.astype(np.int64), hi.astype(np.int64), CFG)
    assert got.verdict == want


def test_engine_constant_positive_net_unsat():
    # Output weight 0, bias +1: logit ≡ 1 > 0 everywhere → provably fair.
    ws = [np.zeros((3, 4), dtype=np.float32), np.zeros((4, 1), dtype=np.float32)]
    bs = [np.zeros(4, dtype=np.float32), np.ones(1, dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    dom = tiny_domain({"a": (0, 50), "pa": (0, 1), "b": (0, 50)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",))
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    got = engine.decide_box(net, enc, lo.astype(np.int64), hi.astype(np.int64), CFG)
    assert got.verdict == "unsat"
    # Certified at the root without input splitting: either the sign-BaB
    # pre-phase (nodes 0) or the first pair-frontier pass (nodes 1).
    assert got.nodes <= 1


def test_engine_pa_direct_dependence_sat():
    # Logit = +1 if pa=1 else -1 → every shared point is a counterexample.
    ws = [np.array([[0.0], [2.0], [0.0]], dtype=np.float32)]
    bs = [np.array([-1.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    dom = tiny_domain({"a": (0, 10), "pa": (0, 1), "b": (0, 10)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",))
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    got = engine.decide_box(net, enc, lo.astype(np.int64), hi.astype(np.int64), CFG)
    assert got.verdict == "sat"
    x, xp = got.counterexample
    assert x[1] != xp[1]


@pytest.mark.parametrize("seed", range(4))
def test_pgd_attack_witnesses_are_legal(seed):
    """PGD witnesses must be exact strict flips, in-box, legal pairs."""
    rng = np.random.default_rng(300 + seed)
    dom = tiny_domain({"a": (0, 9), "pa": (0, 1), "b": (0, 9), "c": (0, 5)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",))
    net = random_net(rng, (4, 8, 1))
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    los = np.stack([lo.astype(np.int64)] * 3)
    his = np.stack([hi.astype(np.int64)] * 3)
    wit = engine.pgd_attack(net, enc, los, his, np.random.default_rng(seed))
    ws = [np.asarray(w) for w in net.weights]
    bs = [np.asarray(b) for b in net.biases]
    pa = set(enc.pa_idx.tolist())
    for i, (x, xp) in wit.items():
        assert 0 <= i < 3  # padded rows never leak out
        assert engine.validate_pair(ws, bs, x, xp)
        for k in range(len(x)):
            if k in pa:
                assert x[k] != xp[k]
            else:
                assert x[k] == xp[k]
        assert (x >= los[i]).all() and (x <= his[i]).all()


def test_pgd_attack_finds_thin_slab_flip():
    """A flip confined to one shared point — random sampling odds ~1e-4 per
    draw, but the logit gradient points straight at it."""
    # logit = 40*pa - |a - 377|ish: positive only at a=377 (pa=1).
    ws = [np.array([[1.0, -1.0, 0.0], [0.0, 0.0, 1.0]], dtype=np.float32),
          np.array([[-1.0], [-1.0], [40.0]], dtype=np.float32)]
    bs = [np.array([-377.0, 377.0, 0.0], dtype=np.float32),
          np.array([-20.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    dom = tiny_domain({"a": (0, 1000), "pa": (0, 1)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",))
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    wit = engine.pgd_attack(
        net, enc, lo[None].astype(np.int64), hi[None].astype(np.int64),
        np.random.default_rng(0),
    )
    assert 0 in wit
    x, xp = wit[0]
    assert x[0] == 377 and xp[0] == 377


def test_slab_search_finds_hairline_flip():
    """A flip slab thinner than f32 resolution at the box's logit scale is
    found by the exact f64 Newton line search and validated exactly."""
    import numpy as np

    from fairify_tpu.data.domains import DomainSpec
    from fairify_tpu.models import mlp as mlp_mod
    from fairify_tpu.verify import engine
    from fairify_tpu.verify import property as prop

    # f(x) = 7e-4·x0 + 1e-3·pa − 350 over x0 ∈ [0, 1e6]: logits span ±350
    # while the protected offset is 1e-3 — the flip slab is ~1e-9 of the
    # shared range, far below f32 resolution at |f| ~ 350.
    w = np.array([[7e-4], [1e-3], [0.0]], dtype=np.float32)
    b = np.array([-350.0], dtype=np.float32)
    net = mlp_mod.from_numpy([w], [b])
    dom = DomainSpec(name="t", label="y",
                     ranges={"x0": (0, 1_000_000), "pa": (0, 1), "z": (0, 3)})
    enc = prop.encode(prop.FairnessQuery(domain=dom, protected=("pa",)))
    lo = np.array([0, 0, 0], dtype=np.int64)
    hi = np.array([1_000_000, 1, 3], dtype=np.int64)
    weights = [np.asarray(x) for x in net.weights]
    biases = [np.asarray(x) for x in net.biases]

    ce = engine.slab_search(weights, biases, enc, lo, hi,
                            shared0=(lo + hi) / 2.0)
    assert ce is not None
    x, xp = ce
    assert engine.validate_pair(weights, biases, x, xp)
    diff = np.where(x != xp)[0]
    assert list(diff) == [1]  # only the protected attribute differs

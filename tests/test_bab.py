"""Device-resident BaB (DESIGN.md §22) — the kernelised frontier.

Four layers of pins:

* unit — the f64 domain-clip mirror (``ops.lp.clip_box_with_form``) keeps
  every integer point the linear form can make positive, and the static
  set-stack (``ops.crown.output_form_stack``) pads by repetition without
  changing bounds;
* engine — the device queue's verdicts agree with the host-frontier loop
  and the exhaustive oracle, decided verdicts (and counterexamples) are
  frontier-capacity-invariant, a queue that runs out of slots reports
  ``frontier:overflow`` (the SMT tier's feedstock) rather than lying, and
  K branching rounds cost O(segments) launches — not O(rounds);
* sweep — verdict maps, resume ledgers and the funnel are bit-equal
  across frontier capacity {small, large} x mega_chunks {0, 1, 4} and
  against the host-frontier path, and a zero-budget run's UNKNOWN tail
  sums to the grid size;
* integrity — the fold checksum and the trailing canary slot catch a
  corrupted frontier payload (resilience.integrity.verify_bab_segment).

Oracle: brute-force enumeration of every (shared point, PA pair) with f64
forward + exact sign at ties, as in tests/test_lattice.py.
"""
import itertools
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from fairify_tpu.data.domains import DomainSpec, get_domain
from fairify_tpu.models.mlp import from_numpy
from fairify_tpu.models.train import init_mlp
from fairify_tpu.obs import funnel as funnel_mod
from fairify_tpu.ops import crown as crown_ops
from fairify_tpu.ops import lattice as lattice_ops
from fairify_tpu.ops import lp as lp_ops
from fairify_tpu.resilience import integrity
from fairify_tpu.utils import profiling
from fairify_tpu.verify import engine, presets, sweep
from fairify_tpu.verify.engine import EngineConfig
from fairify_tpu.verify.property import FairnessQuery, encode


def _query(span=2, d=4, pa=("p",)):
    names = tuple([f"a{i}" for i in range(d - 1)] + ["p"])
    ranges = {n: (0, span) for n in names}
    ranges["p"] = (0, 1)
    dom = DomainSpec(name="toy", columns=names, ranges=ranges, label="y")
    return FairnessQuery(domain=dom, protected=pa)


def _net(seed, sizes):
    rng = np.random.default_rng(seed)
    ws = [rng.normal(scale=0.6, size=(sizes[i], sizes[i + 1])).astype(np.float32)
          for i in range(len(sizes) - 1)]
    bs = [rng.normal(scale=0.2, size=(sizes[i + 1],)).astype(np.float32)
          for i in range(len(sizes) - 1)]
    return from_numpy(ws, bs)


def _oracle(net, enc, lo, hi):
    """Exhaustive f64/exact enumeration — independent of the BaB."""
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    dims = lattice_ops.shared_dims(enc, len(lo))
    valid = [a for a in range(enc.n_assign)
             if all(lo[enc.pa_idx[k]] <= enc.assignments[a, k] <= hi[enc.pa_idx[k]]
                    for k in range(len(enc.pa_idx)))]
    spaces = [range(int(lo[d]), int(hi[d]) + 1) for d in dims]
    for coord in itertools.product(*spaces):
        signs = {}
        for a in valid:
            x = np.array(lo, dtype=np.int64)
            x[dims] = coord
            x[enc.pa_idx] = enc.assignments[a]
            signs[a] = engine.exact_logit_sign(weights, biases, x)
        for a in valid:
            for b in valid:
                if enc.valid_pair[a, b] and signs[a] > 0 and signs[b] < 0:
                    return "sat"
    return "unsat"


def _eng(**kw):
    """Engine config with every pre-BaB phase off, so roots reach the BaB."""
    base = dict(pgd_phase=False, sign_bab=False, lp_sign=False, lp_pair=False,
                lattice_exhaustive=False, attack_samples=1,
                bab_attack_samples=1, alpha_iters=2, device_bab=True,
                bab_frontier_cap=8, bab_rounds_per_segment=4,
                soft_timeout_s=120.0)
    base.update(kw)
    return EngineConfig(**base)


def _decide1(net, enc, lo, hi, cfg):
    lo = np.asarray([lo], dtype=np.int64)
    hi = np.asarray([hi], dtype=np.int64)
    return engine.decide_many(net, enc, lo, hi, cfg, deadline_s=240.0)[0]


def _ce_key(dec):
    if dec.counterexample is None:
        return None
    x, xp = dec.counterexample
    return (tuple(np.asarray(x).tolist()), tuple(np.asarray(xp).tolist()))


# --------------------------------------------------------------------------
# unit: domain clip (f64 mirror) and the static CROWN set-stack


def test_clip_box_keeps_every_positive_point():
    lo = np.array([0, 0, 0], dtype=np.int64)
    hi = np.array([3, 4, 2], dtype=np.int64)
    pts = np.array(list(itertools.product(*(range(int(l), int(h) + 1)
                                            for l, h in zip(lo, hi)))),
                   dtype=np.int64)
    rng = np.random.default_rng(0)
    saw_clip = saw_empty = False
    for trial in range(200):
        D = rng.normal(size=3)
        if trial % 5 == 0:
            D[int(rng.integers(3))] = 0.0
        c = float(rng.normal(scale=2.0))
        keep = pts[pts @ D + c > 0.0]
        new_lo, new_hi, empty = lp_ops.clip_box_with_form(D, c, lo, hi)
        if empty:
            # Soundness of the EMPTY verdict: no integer point is positive.
            assert keep.shape[0] == 0
            saw_empty = True
            continue
        # Clip only shrinks, and never drops a positive point.
        assert (new_lo >= lo).all() and (new_hi <= hi).all()
        assert (new_lo <= new_hi).all()
        assert ((keep >= new_lo).all(axis=1) & (keep <= new_hi).all(axis=1)).all()
        saw_clip |= bool((new_lo > lo).any() or (new_hi < hi).any())
    assert saw_clip and saw_empty  # the trial set exercised both branches


def test_clip_box_degenerate_forms():
    lo = np.array([0, 0], dtype=np.int64)
    hi = np.array([2, 2], dtype=np.int64)
    # Zero form, positive constant: everything stays.
    new_lo, new_hi, empty = lp_ops.clip_box_with_form(
        np.zeros(2), 1.0, lo, hi)
    assert not empty and (new_lo == lo).all() and (new_hi == hi).all()
    # Zero form, non-positive constant: nothing can be positive.
    _, _, empty = lp_ops.clip_box_with_form(np.zeros(2), 0.0, lo, hi)
    assert empty


def test_output_form_stack_pads_by_repetition():
    import jax.numpy as jnp

    net = _net(0, (4, 6, 1))
    lb = jnp.zeros(4, dtype=jnp.float32)
    ub = jnp.full(4, 2.0, dtype=jnp.float32)
    stk, lo, hi = crown_ops.output_form_stack(net, lb, ub, alpha_iters=0)
    assert all(np.asarray(a).shape[0] == 1 for a in stk)
    stk3, lo3, hi3 = crown_ops.output_form_stack(net, lb, ub, alpha_iters=0,
                                                 n_sets=3)
    assert all(np.asarray(a).shape[0] == 3 for a in stk3)
    for a1, a3 in zip(stk, stk3):
        for i in range(3):  # padding repeats the (only) sound set verbatim
            np.testing.assert_array_equal(np.asarray(a3)[i], np.asarray(a1)[0])
    np.testing.assert_array_equal(np.asarray(lo3), np.asarray(lo))
    np.testing.assert_array_equal(np.asarray(hi3), np.asarray(hi))
    stk_a, _, _ = crown_ops.output_form_stack(net, lb, ub, alpha_iters=4)
    assert all(np.asarray(a).shape[0] == 2 for a in stk_a)
    with pytest.raises(ValueError):
        crown_ops.output_form_stack(net, lb, ub, alpha_iters=4, n_sets=1)


# --------------------------------------------------------------------------
# engine: device queue vs host loop vs oracle; capacity invariance;
# overflow attribution; launch economy


@pytest.mark.parametrize("seed", range(6))
def test_device_bab_matches_host_and_oracle(seed):
    q = _query()
    enc = encode(q)
    net = _net(seed, (4, 8, 1))
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([2, 2, 2, 1], dtype=np.int64)
    want = _oracle(net, enc, lo, hi)
    dev = _decide1(net, enc, lo, hi, _eng())
    host = _decide1(net, enc, lo, hi, _eng(device_bab=False))
    assert dev.verdict == want
    assert host.verdict == want
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    for d in (dev, host):
        if d.verdict == "sat":
            x, xp = d.counterexample
            assert engine.validate_pair(weights, biases, x, xp)
            assert (lo <= np.asarray(x)).all() and (np.asarray(x) <= hi).all()
            assert (lo <= np.asarray(xp)).all() and (np.asarray(xp) <= hi).all()


@pytest.mark.parametrize("seed", (0, 2, 6))
def test_device_bab_capacity_invariant(seed):
    # Span-6 world: wide enough that the BaB genuinely branches (these
    # seeds decide even at the floor capacity; 3, 5 and 7 overflow — see
    # test_frontier_overflow_reason).
    q = _query(span=6)
    enc = encode(q)
    net = _net(seed, (4, 6, 1))
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([6, 6, 6, 1], dtype=np.int64)
    got = {}
    for cap in (4, 64):
        d = _decide1(net, enc, lo, hi,
                     _eng(bab_frontier_cap=cap, alpha_iters=0,
                          bab_rounds_per_segment=1, max_nodes=100000))
        got[cap] = (d.verdict, d.reason, _ce_key(d))
    assert got[4] == got[64], got
    assert got[4][0] in ("sat", "unsat")


def test_frontier_overflow_reason_and_funnel_split():
    # Seed 3 at the floor capacity stalls with splittable boxes it cannot
    # place: the root must fall to the SMT tier as 'frontier:overflow'
    # (capacity, retunable) — not 'frontier:hard' (genuinely hard).  The
    # same root DECIDES once the queue is big enough.
    q = _query(span=6)
    enc = encode(q)
    net = _net(3, (4, 6, 1))
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([6, 6, 6, 1], dtype=np.int64)
    small = _decide1(net, enc, lo, hi,
                     _eng(bab_frontier_cap=4, alpha_iters=0,
                          bab_rounds_per_segment=1, max_nodes=100000))
    assert (small.verdict, small.reason) == ("unknown", "frontier:overflow")
    big = _decide1(net, enc, lo, hi,
                   _eng(bab_frontier_cap=64, alpha_iters=0,
                        bab_rounds_per_segment=1, max_nodes=100000))
    assert big.verdict == "sat"
    # The funnel splits the old catch-all into overflow vs hard; anything
    # unrecognised still lands in the legacy bucket.
    assert funnel_mod.classify(
        "unknown", "bab",
        engine_reason=small.reason) == "unknown:frontier:overflow"
    assert funnel_mod.classify(
        "unknown", "bab",
        engine_reason="frontier:hard") == "unknown:frontier:hard"
    assert funnel_mod.classify(
        "unknown", "bab", engine_reason="???") == "unknown:frontier"
    assert "unknown:frontier:overflow" in funnel_mod.STATES
    assert "unknown:frontier:hard" in funnel_mod.STATES


def test_launches_scale_with_segments_not_rounds():
    # The point of the device queue: K branching rounds per launch.  The
    # same root decided with 8-round segments must cost strictly fewer
    # launches than with 1-round segments, and far fewer than its node
    # count — launches are O(segments), not O(rounds x batches).
    q = _query(span=4)
    enc = encode(q)
    net = _net(3, (4, 6, 1))
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([4, 4, 4, 1], dtype=np.int64)
    launches = {}
    decs = {}
    for rounds in (1, 8):
        before = profiling.launch_count()
        decs[rounds] = _decide1(net, enc, lo, hi,
                                _eng(bab_frontier_cap=64, alpha_iters=0,
                                     bab_rounds_per_segment=rounds,
                                     max_nodes=100000))
        launches[rounds] = profiling.launch_count() - before
    assert decs[1].verdict == decs[8].verdict == "unsat"
    assert launches[8] < launches[1]
    assert launches[8] < decs[8].nodes


# --------------------------------------------------------------------------
# sweep: bit-equality across capacity x mega_chunks; zero-budget tail


_GC_ENGINE = dict(pgd_phase=False, sign_bab=False, lp_sign=False,
                  lp_pair=False, lattice_exhaustive=False, attack_samples=4,
                  bab_attack_samples=4, bab_rounds_per_segment=4)


def _german_world():
    """A grid whose every partition flows through the engine BaB."""
    ov = {c: (0, 0) for c in get_domain("german").columns}
    ov.update(age=(0, 1), month=(0, 5), purpose=(0, 5), credit_amount=(0, 2))
    return ov


def _run_sweep(tmp_path, tag, mega_chunks, cap, device_bab=True,
               hard_timeout_s=600.0, pipeline_depth=2):
    eng = EngineConfig(bab_frontier_cap=cap, **_GC_ENGINE)
    cfg = presets.get("GC").with_(
        result_dir=str(tmp_path / tag), soft_timeout_s=20.0,
        hard_timeout_s=hard_timeout_s, sim_size=16,
        exact_certify_masks=False, grid_chunk=8, mega_chunks=mega_chunks,
        domain_overrides=_german_world(), partition_threshold=2,
        device_bab=device_bab, engine=eng, pipeline_depth=pipeline_depth)
    net = init_mlp((len(cfg.query().columns), 4, 1), seed=3)
    rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                             partition_span=(0, 8))
    ledger = []
    for path in sorted((tmp_path / tag).glob("*.ledger.jsonl")):
        for line in path.read_text().splitlines():
            row = json.loads(line)
            ledger.append((row["partition_id"], row["verdict"], row["ce"]))
    outcomes = tuple((o.partition_id, o.verdict, o.counterexample)
                     for o in rep.outcomes)
    return {"outcomes": outcomes, "ledger": tuple(sorted(ledger)),
            "states": dict(rep.funnel["states"]),
            "margin_hist": rep.funnel["margin_hist"],
            "total": rep.funnel["total"], "decided": rep.funnel["decided"]}


def test_sweep_bit_equal_across_capacity_and_mega_chunks(tmp_path,
                                                         monkeypatch):
    calls = []
    orig = engine._device_bab_phase

    def spy(*args, **kwargs):
        calls.append(1)
        return orig(*args, **kwargs)

    monkeypatch.setattr(engine, "_device_bab_phase", spy)
    ref = _run_sweep(tmp_path, "ref", mega_chunks=0, cap=8)
    assert calls, "device BaB never engaged — the world went vacuous"
    assert ref["states"] == {"certified:bab": 8}
    assert ref["decided"] == ref["total"] == 8
    for mc in (0, 1, 4):
        for cap in (8, 512):
            if (mc, cap) == (0, 8):
                continue
            got = _run_sweep(tmp_path, f"mc{mc}-cap{cap}", mega_chunks=mc,
                             cap=cap)
            assert got == ref, f"drift at mega_chunks={mc} cap={cap}"
    # A deeper async launch pipeline must not perturb anything either
    # (acceptance matrix: capacity x mega_chunks x pipeline_depth).
    deep = _run_sweep(tmp_path, "depth4", mega_chunks=4, cap=8,
                      pipeline_depth=4)
    assert deep == ref
    # The host-frontier path must agree bit-for-bit too (same verdict map,
    # ledger rows and funnel) — the device queue changes the COST, never
    # the answer.
    host = _run_sweep(tmp_path, "host", mega_chunks=0, cap=8,
                      device_bab=False)
    assert host == ref


def test_zero_budget_tail_sums_to_grid(tmp_path):
    # The budgeted ladder with a zero hard budget attempts nothing even
    # with the device BaB armed: the WHOLE grid mirrors into
    # unknown:budget — no partition silently vanishes.
    import _sweeplib

    from fairify_tpu import obs

    eng = EngineConfig(bab_frontier_cap=8, **_GC_ENGINE)
    cfg = presets.get("GC").with_(
        result_dir=str(tmp_path / "zb"), soft_timeout_s=2.0,
        hard_timeout_s=0.0, sim_size=16, exact_certify_masks=False,
        grid_chunk=8, domain_overrides=_german_world(),
        partition_threshold=2, device_bab=True, engine=eng)
    net = init_mlp((len(cfg.query().columns), 4, 1), seed=3)
    c = obs.registry().counter("funnel_states")
    budget0 = c.value(state="unknown:budget") or 0
    rec = _sweeplib.budgeted_model_sweep(cfg, net, "m")
    assert rec["attempted"] == 0 and rec["decided_fraction"] == 0.0
    assert rec["partitions"] > 0
    assert (c.value(state="unknown:budget") or 0) - budget0 \
        == rec["partitions"]


# --------------------------------------------------------------------------
# integrity: fold checksum + canary over the packed frontier buffers


def _clean_bab_payload(qs=5, d=4, g=1):
    rng = np.random.default_rng(7)
    payload = {
        "q_lo": rng.integers(0, 5, size=(qs, d)).astype(np.float32),
        "q_hi": rng.integers(5, 9, size=(qs, d)).astype(np.float32),
        "q_root": rng.integers(0, g, size=qs).astype(np.int32),
        "q_live": np.ones(qs, dtype=bool),
        "found": np.zeros(qs, dtype=bool),
        "wit_a": np.zeros(qs, dtype=np.int32),
        "wit_b": np.zeros(qs, dtype=np.int32),
        "wit_pt": np.zeros((qs, d), dtype=np.float32),
        "nodes": rng.integers(0, 9, size=g).astype(np.int64),
        "splits": rng.integers(0, 9, size=g).astype(np.int64),
        "overflow": np.zeros(g, dtype=np.int64),
    }
    # Trailing canary slot: never allocated, must come back all-zero.
    for key in ("q_lo", "q_hi", "q_root", "q_live", "found",
                "wit_a", "wit_b", "wit_pt"):
        payload[key][-1] = 0
    payload["csum"] = np.int64(
        integrity.fold_host(payload, keys=integrity.BAB_FOLD_KEYS))
    return payload


def test_bab_segment_integrity_detectors():
    clean = _clean_bab_payload()
    assert integrity.verify_bab_segment(clean) is None
    # A flipped bit anywhere in the folded buffers trips the checksum.
    bad = dict(clean)
    bad["q_lo"] = integrity.flip_bit(clean["q_lo"], 3)
    assert integrity.verify_bab_segment(bad) == "checksum"
    # A corruption that lands on the canary slot — with a checksum forged
    # to match — still trips the canary detector.
    forged = {k: np.array(v) for k, v in clean.items() if k != "csum"}
    forged["q_live"][-1] = True
    forged["csum"] = np.int64(
        integrity.fold_host(forged, keys=integrity.BAB_FOLD_KEYS))
    assert integrity.verify_bab_segment(forged) == "canary"

"""L4 analysis suite: metrics, causal tester, localization, repair, hybrid."""
import numpy as np
import pytest

from fairify_tpu.analysis import causal, hybrid, localize, metrics, repair
from fairify_tpu.models import mlp


# ---------------------------------------------------------------------------
# Group metrics (hand-computed oracle values)
# ---------------------------------------------------------------------------


def test_group_metrics_hand_example():
    #          priv (pa=1): preds 1,1,0,0   unpriv (pa=0): preds 1,0,0,0
    prot = np.array([1, 1, 1, 1, 0, 0, 0, 0])
    y_pred = np.array([1, 1, 0, 0, 1, 0, 0, 0])
    y_true = np.array([1, 0, 1, 0, 1, 0, 1, 0])
    assert metrics.statistical_parity_difference(y_pred, prot) == pytest.approx(0.25 - 0.5)
    assert metrics.disparate_impact(y_pred, prot) == pytest.approx(0.5)
    # TPR priv: y=1 at idx 0,2 → preds 1,0 → 0.5 ; unpriv: idx 4,6 → 1,0 → 0.5
    assert metrics.equal_opportunity_difference(y_true, y_pred, prot) == pytest.approx(0.0)
    # FPR priv: y=0 at idx 1,3 → preds 1,0 → 0.5; unpriv idx 5,7 → 0,0 → 0.0
    assert metrics.average_odds_difference(y_true, y_pred, prot) == pytest.approx(
        0.5 * ((0.0 - 0.5) + 0.0))
    err_p = np.mean(y_pred[:4] != y_true[:4])
    err_u = np.mean(y_pred[4:] != y_true[4:])
    assert metrics.error_rate_difference(y_true, y_pred, prot) == pytest.approx(err_u - err_p)


def test_theil_index_zero_for_perfect():
    y = np.array([1, 0, 1, 0])
    assert metrics.theil_index(y, y) == pytest.approx(0.0)


def test_consistency_identical_neighbors():
    X = np.array([[0.0], [0.01], [10.0], [10.01]])
    y_pred = np.array([1, 1, 0, 0])
    assert metrics.consistency(X, y_pred, n_neighbors=2) == pytest.approx(1.0)


def test_group_report_runs():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(50, 4))
    rep = metrics.group_report(X, rng.integers(0, 2, 50), rng.integers(0, 2, 50),
                               rng.integers(0, 2, 50))
    assert 0.0 <= rep.accuracy <= 1.0
    assert np.isfinite(rep.theil_index)


# ---------------------------------------------------------------------------
# Causal discrimination
# ---------------------------------------------------------------------------


def _net_pa_biased(d, pa):
    """Logit = 2*pa - 1: flips with the protected attribute everywhere."""
    w = np.zeros((d, 1), dtype=np.float32)
    w[pa, 0] = 2.0
    return mlp.from_numpy([w], [np.array([-1.0], dtype=np.float32)])


def _net_fair(d):
    w = np.zeros((d, 1), dtype=np.float32)
    return mlp.from_numpy([w], [np.array([1.0], dtype=np.float32)])


def _predictor(net):
    import jax.numpy as jnp

    return lambda X: np.asarray(mlp.predict(net, jnp.asarray(X, jnp.float32)))


def test_causal_rate_biased_net_is_one():
    net = _net_pa_biased(4, 2)
    res = causal.causal_discrimination(_predictor(net), [0, 0, 0, 0], [5, 5, 1, 5], 2,
                                       min_samples=200, max_samples=2000)
    assert res.rate == pytest.approx(1.0)
    assert res.examples


def test_causal_rate_fair_net_is_zero():
    net = _net_fair(4)
    res = causal.causal_discrimination(_predictor(net), [0, 0, 0, 0], [5, 5, 1, 5], 2,
                                       min_samples=200, max_samples=2000)
    assert res.rate == pytest.approx(0.0)
    assert res.interval[1] <= 0.05


def test_causal_joint_pair_sweep_oracle():
    """Joint (i, j) sweep rate matches the brute-force oracle and differs
    from the singleton rate (regression: the pair case used to silently
    re-run the singleton sweep for i, ``VERDICT.md`` round 1 item 3).

    f = +1 iff pa1 + pa2 ≥ 2 on pa ∈ {0,1}²: sweeping pa1 alone flips only
    when the sampled pa2 is 1 (exact rate 0.5); sweeping the pair jointly
    always flips (rate 1.0).
    """
    def predict(X):
        return (X[:, 1] + X[:, 2] >= 2.0)

    lo, hi = [0, 0, 0], [5, 1, 1]
    single = causal.causal_discrimination(predict, lo, hi, 1,
                                          min_samples=3000, max_samples=3000)
    pair = causal.causal_discrimination(predict, lo, hi, (1, 2),
                                        min_samples=3000, max_samples=3000)
    assert single.rate == pytest.approx(0.5, abs=0.05)
    assert pair.rate == pytest.approx(1.0)
    assert pair.rate > single.rate


def test_discrimination_search_superset_pruning():
    """Flagged singletons prune their supersets; clean singletons don't."""
    # Always-flip on pa index 1 → singleton flags → no pair tested.
    biased = lambda X: X[:, 1] > 0.0
    res = causal.discrimination_search(biased, [0, 0, 0], [5, 1, 1], (1, 2),
                                       min_samples=500, max_samples=500)
    assert (1,) in res and (1, 2) not in res
    # Constant prediction → nothing flags → the joint pair runs.
    fair = lambda X: np.ones(len(X), dtype=bool)
    res = causal.discrimination_search(fair, [0, 0, 0], [5, 1, 1], (1, 2),
                                       min_samples=500, max_samples=500)
    assert (1, 2) in res and res[(1, 2)].rate == 0.0


def test_causal_joint_combo_guard():
    with pytest.raises(ValueError):
        causal.causal_discrimination(lambda X: np.ones(len(X), dtype=bool),
                                     [0, 0, 0], [5, 4095, 4095], (1, 2))


# ---------------------------------------------------------------------------
# Localization + masked repair
# ---------------------------------------------------------------------------


def _net_with_pa_neuron(d=4, h=6, pa=1, carrier=3):
    """Hidden neuron `carrier` reads only the PA; others ignore it."""
    rng = np.random.default_rng(0)
    w0 = rng.normal(scale=0.2, size=(d, h)).astype(np.float32)
    w0[pa, :] = 0.0
    w0[pa, carrier] = 5.0
    b0 = np.zeros(h, dtype=np.float32)
    w1 = rng.normal(scale=0.2, size=(h, 1)).astype(np.float32)
    w1[carrier, 0] = 5.0
    return mlp.from_numpy([w0, w1], [b0, np.zeros(1, dtype=np.float32)])


def test_localize_finds_carrier_neuron():
    net = _net_with_pa_neuron()
    rng = np.random.default_rng(1)
    pairs = []
    for _ in range(20):
        x = rng.integers(0, 4, size=4)
        xp = x.copy()
        x[1], xp[1] = 0, 1
        pairs.append((x, xp))
    loc = localize.localize(net, pairs, pa_idx=[1], top_k=3)
    assert loc.skipped_pairs == 0
    layer, neuron, score = loc.ranked[0]
    assert (layer, neuron) == (0, 3)
    assert score > 0


def test_localize_skips_malformed_pairs():
    net = _net_with_pa_neuron()
    bad = (np.array([0, 0, 0, 0]), np.array([1, 1, 0, 0]))  # differs off-PA too
    loc = localize.localize(net, [bad], pa_idx=[1])
    assert loc.skipped_pairs == 1


def test_masked_repair_touches_only_target_columns():
    net = _net_with_pa_neuron()
    rng = np.random.default_rng(2)
    X = rng.integers(0, 4, size=(64, 4)).astype(np.float32)
    y = rng.integers(0, 2, size=64)
    res = repair.masked_repair(net, [(0, 3)], X, y, epochs=2, lr=1e-2)
    w0_old, w0_new = np.asarray(net.weights[0]), np.asarray(res.net.weights[0])
    w1_old, w1_new = np.asarray(net.weights[1]), np.asarray(res.net.weights[1])
    changed = np.abs(w0_new - w0_old) > 1e-7
    assert changed[:, 3].any()  # target column moved
    assert not changed[:, [0, 1, 2, 4, 5]].any()  # others frozen
    assert np.allclose(w1_old, w1_new)  # output layer frozen


def test_same_label_relabel_retrain_matches_reference_semantics():
    """The faithful baseline arm (src/AC/detect_bias.py:412-433): every pair
    point relabeled to the MAX of the model's two predictions (a flip pair
    always relabels to 1) and retrained on exactly that set — after training,
    the mean sigmoid over the pair points must move TOWARD 1 (the relabel
    direction), and an empty pair list is a no-op returning the input net."""
    import jax
    import jax.numpy as jnp

    net = _net_with_pa_neuron()
    rng = np.random.default_rng(5)
    pairs = []
    for _ in range(16):
        x = rng.integers(0, 4, size=4)
        xp = x.copy()
        x[1], xp[1] = 0, 1
        pairs.append((x.astype(np.float32), xp.astype(np.float32)))
    xs = np.stack([p[0] for p in pairs])
    before = float(jax.nn.sigmoid(mlp.forward(net, jnp.asarray(xs))).mean())
    res = repair.same_label_relabel_retrain(net, pairs, epochs=4, lr=5e-2)
    after = float(jax.nn.sigmoid(mlp.forward(res.net, jnp.asarray(xs))).mean())
    assert res.net.layer_sizes == net.layer_sizes
    assert after > before  # trained toward the max-relabel (label 1)
    assert repair.same_label_relabel_retrain(net, []).net is net


def test_counterexample_retrain_respects_floor():
    net = _net_with_pa_neuron()
    rng = np.random.default_rng(3)
    X = rng.integers(0, 4, size=(128, 4)).astype(np.float32)
    import jax.numpy as jnp

    y = np.asarray(mlp.predict(net, jnp.asarray(X))).astype(int)  # learnable labels
    pairs = []
    for _ in range(8):
        x = rng.integers(0, 4, size=4)
        xp = x.copy()
        x[1], xp[1] = 0, 1
        pairs.append((x.astype(np.float32), xp.astype(np.float32)))
    res = repair.counterexample_retrain(net, X, y, pairs, X, y,
                                        stage1_epochs=1, stage2_epochs=2)
    assert res.net.layer_sizes == net.layer_sizes
    assert any(str(h["epoch"]).startswith("stage2") for h in res.history)


def test_counterexample_retrain_meets_success_criteria():
    """VERDICT r2 ask #3: the repair must *improve* fairness by the
    reference's own bar (causal rate down, DI toward 1, |SPD|/|EOD|/|AOD|
    not worse, accuracy ≥ floor) — asserted end-to-end on a small model
    whose bias is genuinely repairable.

    Construction: logit = x0 + 2.5·pa − 3.5 (per-group thresholds 1 vs 3.5)
    over x0 ∈ [0,8]; true labels y = (x0 ≥ 4), so the *fair* classifier
    x0 − 3.5 is also the most accurate one — repair can reach both."""
    import jax.numpy as jnp

    from fairify_tpu.analysis import causal, experiment
    from fairify_tpu.analysis import metrics as gm

    ws = [np.array([[1.0, 0.0], [0.0, 1.0], [0.0, 0.0]], dtype=np.float32),
          np.array([[1.0], [2.5]], dtype=np.float32)]
    bs = [np.array([10.0, 10.0], dtype=np.float32),
          np.array([-38.5], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    rng = np.random.default_rng(7)
    X = np.stack([rng.integers(0, 9, 600), rng.integers(0, 2, 600),
                  rng.integers(0, 5, 600)], axis=1).astype(np.float32)
    y = (X[:, 0] >= 4).astype(int)

    # Counterexample pairs: shared coords where the PA flip changes the class.
    pairs = []
    for _ in range(400):
        x = np.array([rng.integers(0, 9), 0, rng.integers(0, 5)], np.float32)
        xp = x.copy()
        xp[1] = 1
        px = float(mlp.predict(net, jnp.asarray(x[None]))[0])
        pp = float(mlp.predict(net, jnp.asarray(xp[None]))[0])
        if px != pp:
            pairs.append((x, xp))
    assert len(pairs) > 50  # the construction really is biased

    res = repair.counterexample_retrain(
        net, X, y, pairs, X, y, stage1_epochs=2, stage2_epochs=8,
        protected_col=1, seed=0)
    fairer = res.net

    prot = X[:, 1]
    metrics_out = {
        "original": gm.group_report(
            X, y, np.asarray(mlp.predict(net, jnp.asarray(X))).astype(int),
            prot).as_dict(),
        "fairer": gm.group_report(
            X, y, np.asarray(mlp.predict(fairer, jnp.asarray(X))).astype(int),
            prot).as_dict(),
    }
    lo = np.array([0, 0, 0], np.int64)
    hi = np.array([8, 1, 4], np.int64)
    rates = {
        name: causal.causal_discrimination(
            lambda Z, n=m: np.asarray(mlp.predict(n, jnp.asarray(Z, jnp.float32))),
            lo, hi, 1, min_samples=200, max_samples=2000).rate
        for name, m in (("original", net), ("fairer", fairer))
    }
    success = experiment.repair_success(metrics_out, rates)
    assert success["passed"], (success, metrics_out, rates)
    # And the improvement is substantive, not within-tolerance noise:
    assert rates["fairer"] < 0.5 * max(rates["original"], 1e-9)


# ---------------------------------------------------------------------------
# Hybrid routing
# ---------------------------------------------------------------------------


def test_hybrid_routes_by_verdict():
    d = 2
    lo = np.array([[0, 0], [5, 0]])
    hi = np.array([[4, 9], [9, 9]])
    verdicts = ["sat", "unsat"]
    original = _net_fair(d)  # always predicts 1
    w = np.zeros((d, 1), dtype=np.float32)
    fairer = mlp.from_numpy([w], [np.array([-1.0], dtype=np.float32)])  # always 0
    X = np.array([[1, 1], [7, 1], [20, 20]])  # sat box, unsat box, miss
    rep = hybrid.hybrid_predict(X, original, fairer, lo, hi, verdicts)
    assert rep.predictions.tolist() == [0, 1, 1]
    assert rep.routed_fair == 1 and rep.routed_original == 1 and rep.routed_miss == 1


def test_evaluate_hybrid_report_keys():
    d = 2
    lo = np.array([[0, 0]])
    hi = np.array([[9, 9]])
    original = _net_fair(d)
    fairer = _net_fair(d)
    rng = np.random.default_rng(5)
    X = rng.integers(0, 10, size=(40, d))
    y = rng.integers(0, 2, size=40)
    out, routing = hybrid.evaluate_hybrid(X, y, 1, original, fairer, lo, hi, ["sat"])
    assert set(out) == {"original", "fairer", "hybrid"}
    for v in out.values():
        assert "consistency" in v and "disparate_impact" in v
    assert routing.routed_fair + routing.routed_original + routing.routed_miss == 40


# ===========================================================================
# IR-level static analysis: fairify_tpu lint --ir (DESIGN.md §11)
# ===========================================================================
#
# Three layers, mirroring tests/test_lint.py:
#
# * repo gate — the committed obs_jit registry is green under all four IR
#   passes with the committed (empty) baseline, in ratchet mode, inside the
#   30 s CPU budget (the sweep must never become the slow tier-1 path).
# * fixture corpus — tests/analysis_fixtures/<pass-id>/ holds tiny-kernel
#   pos_*/neg_* fixtures; a meta-test requires ≥1 of each per shipped pass.
# * machinery — IR findings ride the existing lint engine: inline
#   suppression on the kernel's def line, baseline grandfathering, JSON.

import importlib.util
import json
import pathlib
import sys

from fairify_tpu.lint import core as lint_core

IR_FIXTURE_ROOT = pathlib.Path(__file__).parent / "analysis_fixtures"


def _pass_modules():
    from fairify_tpu.analysis import (
        passes_buffers,
        passes_host,
        passes_recompile,
        passes_sound,
    )

    return {m.PASS_ID: m for m in (passes_host, passes_sound,
                                   passes_recompile, passes_buffers)}


@pytest.fixture(scope="session")
def ir_result():
    """ONE full IR sweep per test session (context is process-cached)."""
    from fairify_tpu.analysis import irlint

    root = lint_core.repo_root()
    baseline = lint_core.load_baseline(
        str(pathlib.Path(root) / lint_core.BASELINE_REL))
    return irlint.run_ir_lint(baseline=baseline, ratchet=True)


def test_ir_repo_gate_green_with_empty_baseline(ir_result):
    from fairify_tpu.analysis.irlint import IR_RULE_IDS

    assert tuple(ir_result.rules) == IR_RULE_IDS
    assert not ir_result.parse_errors
    assert not ir_result.findings, "\n" + "\n".join(
        f.render() for f in ir_result.findings)
    assert not ir_result.baselined  # real findings get FIXED, not baselined
    assert not ir_result.ratchet_breaches
    assert ir_result.ok


def test_ir_sweep_runtime_budget(ir_result):
    """The full registry sweep (lower + 4 passes + buffer-pass compiles)
    must stay under 30 s on CPU — reported like the AST sweep's ~1.2 s."""
    assert ir_result.duration_s < 30.0, (
        f"IR sweep took {ir_result.duration_s:.1f}s — the lint gate is "
        f"becoming the slow path")


def test_ir_every_registry_kernel_lowers():
    """Every obs_jit kernel has a spec and lowers under its analysis
    avals; no spec is stale (naming an unregistered kernel)."""
    from fairify_tpu.analysis import ir as ir_mod

    ctx = ir_mod.shared_context()
    assert len(ctx.kernels) >= 19
    assert not ctx.missing_specs, [k.name for k in ctx.missing_specs]
    assert ctx.unlowered_specs == []
    for kir in ctx.kernels:
        assert kir.lower_error is None, f"{kir.name}: {kir.lower_error}"
        assert kir.closed_jaxpr is not None
        assert kir.signature_key is not None
        assert kir.path.startswith("fairify_tpu/"), kir.path
        assert len(kir.leaves) == len(kir.closed_jaxpr.jaxpr.invars)


def test_ir_sound_kernel_registry_names_verdict_kernels():
    from fairify_tpu.analysis.avals import sound_kernels

    sk = sound_kernels()
    # The certify path: role bounds, combined certificates, sign/inter
    # bounds, family stacks, and the lattice scan — NOT the attack/PGD/
    # sampling kernels (exact-validated on host before verdict weight).
    assert "engine.role_certify" in sk and "engine.certify_attack" in sk
    assert "lattice.lattice_scan_kernel" in sk
    assert "engine.pgd_attack_kernel" not in sk
    assert "engine.attack_logits" not in sk


def _load_fixture(path: pathlib.Path):
    name = "irfx_" + path.stem
    spec = importlib.util.spec_from_file_location(name, str(path))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(name, None)
    return mod.make()


@pytest.mark.parametrize("pass_id", ["ir-host-transfer", "ir-soundness",
                                     "ir-recompile", "ir-buffers"])
def test_ir_fixture_corpus_golden(pass_id):
    """pos_* fixtures draw ≥1 finding from THEIR pass, neg_* draw none."""
    mod = _pass_modules()[pass_id]
    d = IR_FIXTURE_ROOT / pass_id
    for p in sorted(d.glob("pos_*.py")):
        kir = _load_fixture(p)
        assert kir.lower_error is None, f"{p.name}: {kir.lower_error}"
        findings = mod.check_kernel(kir)
        assert findings, f"{p.name}: positive fixture drew no finding"
    for p in sorted(d.glob("neg_*.py")):
        kir = _load_fixture(p)
        assert kir.lower_error is None, f"{p.name}: {kir.lower_error}"
        findings = mod.check_kernel(kir)
        assert not findings, f"{p.name}: negative fixture drew {findings}"


def test_ir_every_pass_has_positive_and_negative_fixtures():
    """Meta-test: a shipped IR pass without a corpus cannot regress."""
    pass_ids = set(_pass_modules())
    for pass_id in pass_ids:
        d = IR_FIXTURE_ROOT / pass_id
        assert d.is_dir(), f"missing fixture dir for pass {pass_id!r}"
        assert sorted(d.glob("pos_*.py")), f"{pass_id}: no positive fixture"
        assert sorted(d.glob("neg_*.py")), f"{pass_id}: no negative fixture"
    extra = {d.name for d in IR_FIXTURE_ROOT.iterdir() if d.is_dir()} \
        - pass_ids
    assert not extra, f"fixture dirs without a shipped pass: {sorted(extra)}"


def test_ir_findings_ride_lint_machinery(tmp_path):
    """IR findings attribute to real source lines, so inline suppression
    and baseline grandfathering apply unchanged."""
    fx = IR_FIXTURE_ROOT / "ir-buffers" / "pos_dead_arg_passthrough.py"
    from fairify_tpu.analysis import passes_buffers
    from fairify_tpu.analysis.irlint import IRRule

    class _Ctx:
        missing_specs = ()

        def __init__(self, kernels):
            self.kernels = kernels

    def run(src_line_suppressed, baseline=None):
        kir = _load_fixture(fx)
        rel = "fairify_tpu/verify/fx.py"
        body = "def wasteful_kernel(x, stale_cache):\n    return x\n"
        if src_line_suppressed:
            body = ("def wasteful_kernel(x, stale_cache):"
                    "  # lint: disable=ir-buffers\n    return x\n")
        p = tmp_path / "fx.py"
        p.write_text(body)
        kir.path, kir.line, kir.function = rel, 1, "wasteful_kernel"
        rule = IRRule(passes_buffers, ctx=_Ctx([kir]))
        return lint_core.run_lint(rules=[rule], files=[(str(p), rel)],
                                  baseline=baseline)

    live = run(False)
    assert len(live.findings) == 2  # dead arg + passthrough
    assert all(f.rule == "ir-buffers" for f in live.findings)
    assert live.findings[0].key == \
        "ir-buffers::fairify_tpu/verify/fx.py::wasteful_kernel"

    muted = run(True)
    assert not muted.findings and muted.suppressed == 2
    assert muted.suppressed_by_rule == {"ir-buffers": 2}

    key = "ir-buffers::fairify_tpu/verify/fx.py::wasteful_kernel"
    grand = run(False, baseline={key: {"count": 2, "reason": "test"}})
    assert not grand.findings and len(grand.baselined) == 2 and grand.ok


def test_ir_recompile_reports_unspecced_kernel():
    """A kernel registered in obs_jit without an aval spec is itself a
    finding — nothing dodges IR analysis silently."""
    from fairify_tpu.analysis import passes_recompile
    from fairify_tpu.analysis.irlint import IRRule
    from fairify_tpu.obs.compile import ObsJit

    ghost = ObsJit(lambda x: x + 1.0, name="t.ghost_unspecced",
                   register=False)

    class _Ctx:
        kernels = ()
        missing_specs = (ghost,)

    rule = IRRule(passes_recompile, ctx=_Ctx())
    found = list(rule.finalize({}))
    assert len(found) == 1
    assert "no aval spec" in found[0].message


def test_ir_cli_mode_runs_selected_pass(capsys):
    """`fairify_tpu lint --ir` shares the engine CLI: JSON, --rules."""
    rc = lint_core.main(["--ir", "--rules", "ir-host-transfer",
                         "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["ok"] is True
    assert doc["rules"] == ["ir-host-transfer"]
    assert doc["counts"] == {"ir-host-transfer": 0}
    assert "suppressed_by_rule" in doc


def test_ir_context_scope_excludes_test_registered_kernels():
    """A kernel registered from outside fairify_tpu/ (tests, scratch
    scripts) is out of the IR suite's scope — the repo gate must not
    depend on which tests ran first in the process."""
    from fairify_tpu.analysis.ir import kernel_in_scope
    from fairify_tpu.obs.compile import ObsJit, kernels

    probe = ObsJit(lambda x: x + 1.0, name="t.scope_probe", register=False)
    assert not kernel_in_scope(probe)  # defined in tests/, not the package
    real = kernels()["engine.role_certify"]
    assert kernel_in_scope(real)


def test_ir_dead_arg_distinct_from_passthrough():
    """An argument returned verbatim is the passthrough finding, never a
    dead argument ('drop it' would be wrong advice for a value the caller
    reads back)."""
    from fairify_tpu.analysis import passes_buffers
    from fairify_tpu.analysis.ir import KernelIR

    def echo_kernel(x, y):
        return x + 1.0, y

    kir = KernelIR.from_fn(
        echo_kernel, (np.ones(4, np.float32), np.ones(4, np.float32)))
    findings = passes_buffers.check_kernel(kir)
    assert len(findings) == 1 and "verbatim" in findings[0]


def test_ir_context_build_leaves_compile_accounting_untouched():
    """Analysis tracing re-enters nested obs_jit kernels through the
    tracer branch; that must NOT bump trace-inline/fallback accounting —
    the IR sweep promises zero effect on gated metrics."""
    from fairify_tpu.analysis import ir as ir_mod
    from fairify_tpu.obs import compile as compile_mod
    from fairify_tpu.obs import metrics as metrics_mod

    before_ti = {n: k.stats.trace_inlines
                 for n, k in compile_mod.kernels().items()}
    before_fb = metrics_mod.registry().counter(
        "xla_compile_fallbacks").total()
    ctx = ir_mod.IRContext()  # fresh build, not the session-shared one
    assert len(ctx.kernels) >= 19
    for n, k in compile_mod.kernels().items():
        assert k.stats.trace_inlines == before_ti.get(n, 0), n
    assert metrics_mod.registry().counter(
        "xla_compile_fallbacks").total() == before_fb


def test_ir_recompile_stats_branch_is_opt_in():
    """The fallback-only warning reads LIVE stats only when a context is
    built with include_stats=True — the lint gate's input is the repo,
    never process history (chaos tests poison stats with compile faults)."""
    from fairify_tpu.analysis import passes_recompile
    from fairify_tpu.analysis.ir import KernelIR

    def ok_kernel(x):
        return x + 1.0

    kir = KernelIR.from_fn(ok_kernel, (np.ones(4, np.float32),))
    assert passes_recompile.check_kernel(kir) == []

    class _PoisonedStats:
        n_compiles = 0
        fallbacks = 3

    kir.stats = _PoisonedStats()  # what include_stats=True would attach
    msgs = passes_recompile.check_kernel(kir)
    assert len(msgs) == 1 and "plain-jit fallback" in msgs[0]

"""Persistent executable cache (``obs.compile.enable_exec_cache``, §15).

The zero-cold-start leg of the overload-survival layer: compiled
executables serialize to disk keyed by the real ``signature_key`` plus
jax/jaxlib versions, backend, and device kind, and a fresh process warms
from the cache instead of recompiling.  Four contracts:

* **never trusted** — truncated, corrupted, or wrong-identity entries are
  quarantined to ``.corrupt`` and recompiled; a bad cache costs time,
  never correctness;
* **bit-equal** — a cache hit produces byte-identical outputs (and, at the
  sweep level, byte-identical verdict maps) to a fresh compile;
* **race-safe** — replicas racing the same key publish whole entries via
  atomic rename; readers can never observe a torn file;
* **opt-in** — with the cache disabled nothing is written or read, so
  per-process compile accounting elsewhere in the suite is untouched.
"""
import hashlib
import json
import os
import pickle
import subprocess
import sys
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from fairify_tpu import obs
from fairify_tpu.obs import compile as compile_obs

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def cache_dir(tmp_path):
    d = str(tmp_path / "exec")
    compile_obs.enable_exec_cache(d)
    yield d
    compile_obs.disable_exec_cache()


def _kern(name="t.kern"):
    def fn(x, k):
        return x * 2.0 if k else x + 1.0

    return compile_obs.obs_jit(fn, name=name, static_argnames=("k",),
                               register=False)


def _entry_paths(cache_dir):
    return [os.path.join(cache_dir, n) for n in sorted(os.listdir(cache_dir))
            if n.endswith(".exec")]


def test_fresh_instance_loads_from_cache_bit_equal(cache_dir):
    k1 = _kern()
    out1 = np.asarray(k1(jnp.arange(4.0), k=True))
    assert k1.stats.n_compiles == 1 and k1.stats.cache_stores == 1
    assert len(_entry_paths(cache_dir)) == 1
    # A fresh instance (empty in-memory executable cache — the process-
    # restart analog) must load, not compile, and match byte for byte.
    k2 = _kern()
    out2 = np.asarray(k2(jnp.arange(4.0), k=True))
    assert k2.stats.n_compiles == 0
    assert k2.stats.cache_hits == 1
    assert out1.tobytes() == out2.tobytes()


def test_truncated_entry_quarantined_and_recompiled(cache_dir):
    k1 = _kern()
    out1 = np.asarray(k1(jnp.arange(4.0), k=True))
    path = _entry_paths(cache_dir)[0]
    with open(path, "r+b") as fp:
        fp.truncate(40)
    errs = obs.registry().counter("exec_cache_errors")
    e0 = errs.total()
    k2 = _kern()
    out2 = np.asarray(k2(jnp.arange(4.0), k=True))
    assert k2.stats.cache_hits == 0
    assert k2.stats.n_compiles == 1, "a truncated entry must recompile"
    assert errs.total() == e0 + 1
    assert os.path.exists(path + ".corrupt"), "quarantined, never re-parsed"
    assert out2.tobytes() == out1.tobytes()
    # The recompile re-published a good entry: next instance hits again.
    k3 = _kern()
    k3(jnp.arange(4.0), k=True)
    assert k3.stats.cache_hits == 1


def test_corrupt_payload_quarantined(cache_dir):
    k1 = _kern()
    k1(jnp.arange(4.0), k=True)
    path = _entry_paths(cache_dir)[0]
    raw = bytearray(open(path, "rb").read())
    raw[-1] ^= 0xFF  # flip a payload byte: checksum must catch it
    with open(path, "wb") as fp:
        fp.write(raw)
    k2 = _kern()
    out = np.asarray(k2(jnp.arange(4.0), k=True))
    assert k2.stats.cache_hits == 0 and k2.stats.n_compiles == 1
    assert os.path.exists(path + ".corrupt")
    assert out.tobytes() == np.asarray(k1(jnp.arange(4.0), k=True)).tobytes()


def test_wrong_version_entry_rejected_not_loaded(cache_dir):
    """An entry whose embedded identity disagrees (stale jax version, other
    backend) must be quarantined even when its checksum is intact."""
    k1 = _kern()
    k1(jnp.arange(4.0), k=True)
    path = _entry_paths(cache_dir)[0]
    raw = open(path, "rb").read()
    body = raw[len(compile_obs._EXEC_MAGIC):]
    _digest, _, payload = body.partition(b"\n")
    meta = pickle.loads(payload)
    meta["ident"] = meta["ident"].replace(
        compile_obs.jax.__version__, "0.0.1-stale", 1)
    forged = pickle.dumps(meta)
    with open(path, "wb") as fp:
        fp.write(compile_obs._EXEC_MAGIC
                 + hashlib.sha256(forged).hexdigest().encode()
                 + b"\n" + forged)
    k2 = _kern()
    k2(jnp.arange(4.0), k=True)
    assert k2.stats.cache_hits == 0 and k2.stats.n_compiles == 1
    assert os.path.exists(path + ".corrupt")


def test_concurrent_racers_same_key_never_tear(cache_dir):
    """N replicas racing one key: every store publishes a complete entry
    (write-tmp -> fsync -> rename), so the last writer wins a byte-valid
    file and every racer computes the right answer."""
    outs = [None] * 8
    errs = []

    def race(i):
        try:
            k = _kern()
            outs[i] = np.asarray(k(jnp.arange(4.0), k=True)).tobytes()
        except BaseException as exc:  # noqa: BLE001 - surface in main thread
            errs.append(exc)

    threads = [threading.Thread(target=race, args=(i,)) for i in range(8)]
    e0 = obs.registry().counter("exec_cache_errors").total()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(set(outs)) == 1
    assert obs.registry().counter("exec_cache_errors").total() == e0, \
        "a racer observed a torn entry"
    # Whatever the interleaving, the surviving entry is loadable.
    k = _kern()
    k(jnp.arange(4.0), k=True)
    assert k.stats.cache_hits == 1 and k.stats.n_compiles == 0
    assert not [n for n in os.listdir(cache_dir) if n.endswith(".tmp")], \
        "a racer leaked its tmp file"


def test_disabled_cache_writes_and_reads_nothing(tmp_path):
    assert compile_obs.exec_cache_dir() is None
    k = _kern()
    k(jnp.arange(4.0), k=True)
    assert k.stats.cache_stores == 0 and k.stats.cache_hits == 0


def _run_sweep_child(cache_dir, result_dir):
    code = """
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from fairify_tpu.obs import compile as compile_obs
compile_obs.enable_exec_cache(sys.argv[1])
from fairify_tpu.models.train import init_mlp
from fairify_tpu.verify import presets, sweep
cfg = presets.get("GC").with_(
    soft_timeout_s=30.0, hard_timeout_s=600.0, sim_size=32,
    exact_certify_masks=False, grid_chunk=8, launch_backoff_s=1e-4,
    result_dir=sys.argv[2])
net = init_mlp((20, 6, 1), seed=7)
rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                         partition_span=(0, 16))
tot = compile_obs.snapshot_totals()
hits = sum(k.stats.cache_hits for k in compile_obs.kernels().values())
print(json.dumps({
    "map": {str(o.partition_id): o.verdict for o in rep.outcomes},
    "n_compiles": tot["n_compiles"], "hits": hits}))
"""
    out = subprocess.run(
        [sys.executable, "-c", code, cache_dir, result_dir],
        cwd=ROOT, capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_cold_restart_verdicts_bit_equal_and_compile_free(tmp_path):
    """The full-stack zero-cold-start contract: process 1 compiles and
    populates the cache; process 2 (a restarted server / fresh replica)
    compiles NOTHING and produces the identical verdict map."""
    cache = str(tmp_path / "exec")
    first = _run_sweep_child(cache, str(tmp_path / "r1"))
    second = _run_sweep_child(cache, str(tmp_path / "r2"))
    assert first["n_compiles"] > 0, "first process should have compiled"
    assert second["n_compiles"] == 0, \
        f"restart recompiled {second['n_compiles']} kernels"
    assert second["hits"] > 0
    assert second["map"] == first["map"], "cache hit changed verdicts"

"""Phase E — exhaustive lattice decision (ops/lattice.py).

Oracle: brute-force enumeration of every (shared point, PA pair) with f64
forward + exact sign at ties — the semantics of ``engine.decide_leaf``
applied to the whole box.
"""
import itertools

import numpy as np
import pytest

from fairify_tpu.data.domains import DomainSpec
from fairify_tpu.models.mlp import from_numpy
from fairify_tpu.ops import lattice as lattice_ops
from fairify_tpu.verify import engine
from fairify_tpu.verify.property import FairnessQuery, encode


def _query(d=4, pa=("p",)):
    names = tuple([f"a{i}" for i in range(d - 1)] + ["p"])
    ranges = {n: (0, 2) for n in names}
    ranges["p"] = (0, 1)
    dom = DomainSpec(name="toy", columns=names, ranges=ranges, label="y")
    return FairnessQuery(domain=dom, protected=pa)


def _net(seed, sizes):
    rng = np.random.default_rng(seed)
    ws = [rng.normal(scale=0.6, size=(sizes[i], sizes[i + 1])).astype(np.float32)
          for i in range(len(sizes) - 1)]
    bs = [rng.normal(scale=0.2, size=(sizes[i + 1],)).astype(np.float32)
          for i in range(len(sizes) - 1)]
    return from_numpy(ws, bs)


def _oracle(net, enc, lo, hi):
    """Exhaustive f64/exact enumeration — independent of ops.lattice."""
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    dims = lattice_ops.shared_dims(enc, len(lo))
    valid = [a for a in range(enc.n_assign)
             if all(lo[enc.pa_idx[k]] <= enc.assignments[a, k] <= hi[enc.pa_idx[k]]
                    for k in range(len(enc.pa_idx)))]
    spaces = [range(int(lo[d]), int(hi[d]) + 1) for d in dims]
    for coord in itertools.product(*spaces):
        signs = {}
        for a in valid:
            x = np.array(lo, dtype=np.int64)
            x[dims] = coord
            x[enc.pa_idx] = enc.assignments[a]
            signs[a] = engine.exact_logit_sign(weights, biases, x)
        for a in valid:
            for b in valid:
                if enc.valid_pair[a, b] and signs[a] > 0 and signs[b] < 0:
                    return "sat"
    return "unsat"


@pytest.mark.parametrize("seed", range(6))
def test_oracle_agreement(seed):
    q = _query()
    enc = encode(q)
    net = _net(seed, (4, 8, 1))
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([2, 2, 2, 1], dtype=np.int64)
    verdict, ce = lattice_ops.decide_box_exhaustive(net, enc, lo, hi,
                                                    chunk=8)
    assert verdict == _oracle(net, enc, lo, hi)
    if verdict == "sat":
        x, xp = ce
        weights = [np.asarray(w) for w in net.weights]
        biases = [np.asarray(b) for b in net.biases]
        assert engine.validate_pair(weights, biases, x, xp)
        # Pair differs only on the PA dim and both lie in the box.
        assert (x[:-1] == xp[:-1]).all() and x[-1] != xp[-1]
        assert (lo <= x).all() and (x <= hi).all()
        assert (lo <= xp).all() and (xp <= hi).all()


@pytest.mark.parametrize("seed", range(4))
def test_prefix_peeling_matches_oracle(seed):
    """Forcing the int32 guard low makes the scan peel leading dims to the
    host; verdicts must not change."""
    q = _query()
    enc = encode(q)
    net = _net(seed, (4, 8, 1))
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([2, 2, 2, 1], dtype=np.int64)
    verdict, ce = lattice_ops.decide_box_exhaustive(
        net, enc, lo, hi, chunk=4, int32_limit=8, pipeline_depth=3)
    assert verdict == _oracle(net, enc, lo, hi)
    if verdict == "sat":
        x, xp = ce
        weights = [np.asarray(w) for w in net.weights]
        biases = [np.asarray(b) for b in net.biases]
        assert engine.validate_pair(weights, biases, x, xp)
        assert (lo <= x).all() and (x <= hi).all()
        assert (lo <= xp).all() and (xp <= hi).all()


def test_exact_tie_is_not_a_flip():
    """A network whose logit is identically zero has sign 0 everywhere:
    the strict-flip property is UNSAT, and the margin path must settle it
    via exact signs rather than mis-classifying ±0 float noise."""
    q = _query()
    enc = encode(q)
    ws = [np.zeros((4, 4), np.float32), np.zeros((4, 1), np.float32)]
    bs = [np.zeros(4, np.float32), np.zeros(1, np.float32)]
    net = from_numpy(ws, bs)
    lo = np.zeros(4, dtype=np.int64)
    hi = np.array([2, 2, 2, 1], dtype=np.int64)
    verdict, ce = lattice_ops.decide_box_exhaustive(net, enc, lo, hi, chunk=8)
    assert verdict == "unsat" and ce is None


def test_no_legal_pair_is_unsat():
    """PA collapsed to one value in the box — no pair, trivially fair."""
    q = _query()
    enc = encode(q)
    net = _net(0, (4, 8, 1))
    lo = np.array([0, 0, 0, 1], dtype=np.int64)
    hi = np.array([2, 2, 2, 1], dtype=np.int64)
    verdict, _ = lattice_ops.decide_box_exhaustive(net, enc, lo, hi, chunk=8)
    assert verdict == "unsat"


def test_decide_many_lattice_fallthrough():
    """Roots the BaB cannot close (max_nodes=1, all other phases off) are
    settled by Phase E, matching the oracle."""
    q = _query()
    enc = encode(q)
    cfg = engine.EngineConfig(
        soft_timeout_s=60.0, max_nodes=1, sign_bab=False, lp_sign=False,
        lp_pair=False, attack_samples=2, bab_attack_samples=2)
    lo = np.array([[0, 0, 0, 0]], dtype=np.int64)
    hi = np.array([[2, 2, 2, 1]], dtype=np.int64)
    for seed in range(4):
        net = _net(seed, (4, 6, 1))
        dec = engine.decide_many(net, enc, lo, hi, cfg, deadline_s=60.0)
        assert dec[0].verdict == _oracle(net, enc, lo[0], hi[0])


def _ra_query(eps):
    names = ("ra", "a1", "a2", "p")
    ranges = {"ra": (0, 4), "a1": (0, 2), "a2": (0, 2), "p": (0, 1)}
    dom = DomainSpec(name="toy", columns=names, ranges=ranges, label="y")
    return FairnessQuery(domain=dom, protected=("p",), relaxed=("ra",),
                         relax_eps=eps)


def _ra_oracle(net, enc, lo, hi):
    """Per-point exact decision over every core point via decide_leaf —
    the trusted single-point semantics applied to the whole box."""
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    dims = [k for k in range(len(lo)) if k not in enc.pa_idx]
    spaces = [range(int(lo[k]), int(hi[k]) + 1) for k in dims]
    for coord in itertools.product(*spaces):
        pt = np.array(lo, dtype=np.int64)
        pt[dims] = coord
        verdict, _ = engine.decide_leaf(enc, weights, biases, pt, lo, hi)
        if verdict == "sat":
            return "sat"
    return "unsat"


@pytest.mark.parametrize("seed,eps", [(s, e) for s in range(4)
                                      for e in (1, 2)])
def test_ra_window_matches_per_point_oracle(seed, eps):
    """Single-RA boxes are decided by the ε-dilated scan; verdicts must
    match decide_leaf applied to every core point, and SAT witnesses must
    satisfy the RA pair constraints exactly."""
    q = _ra_query(eps)
    enc = encode(q)
    net = _net(seed, (4, 8, 1))
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([4, 2, 2, 1], dtype=np.int64)
    verdict, ce = lattice_ops.decide_box_exhaustive(net, enc, lo, hi,
                                                    chunk=16)
    assert verdict == _ra_oracle(net, enc, lo, hi)
    if verdict == "sat":
        x, xp = ce
        weights = [np.asarray(w) for w in net.weights]
        biases = [np.asarray(b) for b in net.biases]
        assert engine.validate_pair(weights, biases, x, xp)
        assert x[3] != xp[3]                      # PA differs
        assert abs(int(x[0]) - int(xp[0])) <= eps  # RA within ε
        assert (x[1:3] == xp[1:3]).all()          # other dims equal
        # x is in-box; x' may leave the box on the RA axis only
        assert (lo <= x).all() and (x <= hi).all()
        assert (lo[1:] <= xp[1:]).all() and (xp[1:] <= hi[1:]).all()


def test_ra_flip_with_positive_only_in_expanded_ring():
    """Directed soundness regression: f(x) = ra − 4.5 makes every core
    point certainly negative and only expanded-ring cells (ra = 5, 6)
    positive.  decide_leaf accepts the (x negative, x′ positive) direction,
    so the box is SAT — a scan that only dilates negatives returns a wrong
    UNSAT."""
    q = _ra_query(2)
    enc = encode(q)
    # logit = 1.0·ra − 4.5, ignoring every other input.
    w1 = np.zeros((4, 2), np.float32)
    w1[0, 0] = 1.0
    net = from_numpy(
        [w1, np.array([[1.0], [0.0]], np.float32)],
        [np.zeros(2, np.float32), np.array([-4.5], np.float32)])
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([4, 2, 2, 1], dtype=np.int64)
    assert _ra_oracle(net, enc, lo, hi) == "sat"
    verdict, ce = lattice_ops.decide_box_exhaustive(net, enc, lo, hi,
                                                    chunk=16)
    assert verdict == "sat"
    x, xp = ce
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    assert engine.validate_pair(weights, biases, x, xp)
    assert int(xp[0]) > 4  # the positive endpoint lies outside the box


def test_ra_window_peeled_matches_oracle():
    """RA mode composes with prefix peeling (RA axis never peeled)."""
    q = _ra_query(1)
    enc = encode(q)
    net = _net(2, (4, 8, 1))
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([4, 2, 2, 1], dtype=np.int64)
    verdict, _ = lattice_ops.decide_box_exhaustive(
        net, enc, lo, hi, chunk=8, int32_limit=32, pipeline_depth=2)
    assert verdict == _ra_oracle(net, enc, lo, hi)


def test_three_ra_matches_oracle():
    """k = 3 RA dilation agrees with the exact per-point oracle (round 5:
    the separable L∞ window generalizes past the round-4 two-RA gate)."""
    names = ("a0", "a1", "a2", "p")
    dom = DomainSpec(name="toy3", columns=names,
                     ranges={"a0": (0, 2), "a1": (0, 2), "a2": (0, 2),
                             "p": (0, 1)},
                     label="y")
    q = FairnessQuery(domain=dom, protected=("p",),
                      relaxed=("a0", "a1", "a2"), relax_eps=2)
    enc = encode(q)
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([2, 2, 2, 1], dtype=np.int64)
    for seed in (3, 7, 11):
        net = _net(seed, (4, 6, 1))
        verdict, ce = lattice_ops.decide_box_exhaustive(
            net, enc, lo, hi, chunk=1024)
        assert verdict == _ra_oracle(net, enc, lo, hi)
        if verdict == "sat":
            ws = [np.asarray(w) for w in net.weights]
            bs = [np.asarray(b) for b in net.biases]
            assert engine.validate_pair(ws, bs, *ce)


def test_lattice_gates():
    """Over-large delta windows and lattices are left unknown (honest);
    k-RA roots within the (2ε+1)^k ≤ 1e5 window cap are eligible and
    settle — including k = 3 since round 5 (VERDICT r4 #8)."""
    import time

    names = ("a0", "a1", "a2", "p")
    dom = DomainSpec(name="toy", columns=names,
                     ranges={"a0": (0, 2), "a1": (0, 2), "a2": (0, 2),
                             "p": (0, 1)},
                     label="y")
    q_3ra = FairnessQuery(domain=dom, protected=("p",),
                          relaxed=("a0", "a1", "a2"), relax_eps=2)
    enc_3ra = encode(q_3ra)
    q_2ra = FairnessQuery(domain=dom, protected=("p",),
                          relaxed=("a0", "a1"), relax_eps=2)
    enc_2ra = encode(q_2ra)
    q_1ra = FairnessQuery(domain=dom, protected=("p",), relaxed=("a0",),
                          relax_eps=2)
    enc_1ra = encode(q_1ra)
    net = _net(1, (4, 6, 1))
    lo = np.array([[0, 0, 0, 0]], dtype=np.int64)
    hi = np.array([[2, 2, 2, 1]], dtype=np.int64)

    def run(enc, cfg):
        verdicts, ces = ["unknown"], [None]
        engine._lattice_phase(net, enc, lo, hi, verdicts, ces,
                              np.zeros(1), cfg, time.perf_counter(), 30.0)
        return verdicts[0]

    # Window-cap gate: (2ε+1)^k > 1e5 (k=3, ε=24 → 49³ ≈ 1.18e5) is past
    # the decide_leaf margin resolver — honest unknown, not a stall.
    q_cap = FairnessQuery(domain=dom, protected=("p",),
                          relaxed=("a0", "a1", "a2"), relax_eps=24)
    enc_cap = encode(q_cap)
    assert run(enc_cap, engine.EngineConfig()) == "unknown"
    assert lattice_ops.enumerable_size(enc_cap, lo[0], hi[0]) is None
    # Size gate: shared lattice is 27 > lattice_max=4.
    enc = encode(_query(d=4))
    assert run(enc, engine.EngineConfig(lattice_max=4)) == "unknown"
    # Controls: with the gates open, RA-free and 1/2/3-RA roots settle.
    assert run(enc, engine.EngineConfig()) in ("sat", "unsat")
    assert run(enc_1ra, engine.EngineConfig()) in ("sat", "unsat")
    assert run(enc_2ra, engine.EngineConfig()) in ("sat", "unsat")
    assert lattice_ops.enumerable_size(enc_3ra, lo[0], hi[0]) is not None
    got_3ra = run(enc_3ra, engine.EngineConfig())
    assert got_3ra in ("sat", "unsat")


def test_coord_magnitude_gate():
    """ADVICE r3: coordinates at/past 2^24 are not exactly representable in
    f32, so the roundoff recurrence's e0 = 0 base case breaks — such boxes
    must be ineligible (enumerable_size None, decide unknown), including
    when only the ε expansion crosses the line."""
    names = ("a0", "p")
    big = 1 << 24
    dom = DomainSpec(name="wide", columns=names,
                     ranges={"a0": (0, big), "p": (0, 1)}, label="y")
    enc = encode(FairnessQuery(domain=dom, protected=("p",)))
    net = _net(0, (2, 4, 1))
    lo = np.array([0, 0], dtype=np.int64)
    hi = np.array([big, 1], dtype=np.int64)
    assert lattice_ops.enumerable_size(enc, lo, hi) is None
    assert lattice_ops.decide_box_exhaustive(net, enc, lo, hi)[0] == "unknown"
    # One below the line (and a tiny lattice): eligible again.
    hi_ok = np.array([3, 1], dtype=np.int64)
    assert lattice_ops.enumerable_size(enc, lo, hi_ok) == 4
    # ε expansion alone crossing 2^24 also trips the gate.
    dom2 = DomainSpec(name="edge", columns=names,
                      ranges={"a0": (0, big - 1), "p": (0, 1)}, label="y")
    enc_ra = encode(FairnessQuery(domain=dom2, protected=("p",),
                                  relaxed=("a0",), relax_eps=2))
    assert lattice_ops.enumerable_size(
        enc_ra, np.array([0, 0], np.int64),
        np.array([big - 1, 1], np.int64)) is None


@pytest.mark.parametrize("seed", range(4))
def test_roundoff_bound_margin_dominates_f32_evaluation(seed):
    """ADVICE r3: the device roundoff bound is itself evaluated in f32 and
    uses computed |h| rather than true |h|; the claim is that the 4x margin
    on the γ constants dominates both second-order effects.  Checked two
    ways on random nets/points:

    1. soundness: |f32 logit − f64 logit| ≤ f32-computed bound, every point;
    2. headroom: the f32-computed bound stays ≥ 2× a *tightened* f64
       recurrence using the standard first-order constant γ = (n+1)u —
       i.e. even after paying f32 evaluation error and the |h|-proxy, at
       least half the 4× inflation survives as margin.
    """
    import jax.numpy as jnp

    rng = np.random.default_rng(100 + seed)
    sizes = (5, 16, 8, 1)
    net = _net(200 + seed, sizes)
    weights = [np.asarray(w, np.float64) for w in net.weights]
    biases = [np.asarray(b, np.float64) for b in net.biases]
    pts = rng.integers(-50, 1000, size=(64, sizes[0])).astype(np.float64)

    f32_logit, e32 = (np.asarray(v) for v in
                      lattice_ops._signed_forward(net, jnp.asarray(pts, jnp.float32)))

    # f64 forward (true value to ~1e-16 — far finer than the ~1e-5 bound).
    h = pts.copy()
    e64_tight = np.zeros_like(pts)
    u = 2.0 ** -24
    for i, (w, b) in enumerate(zip(weights, biases)):
        gamma_tight = (w.shape[0] + 1) * u  # standard constant, no 4x
        abs_acc = np.abs(h) @ np.abs(w) + np.abs(b)
        e64_tight = e64_tight @ np.abs(w) + gamma_tight * abs_acc
        z = h @ w + b
        if i < len(weights) - 1:
            h = np.maximum(z, 0.0)
            e64_tight = e64_tight  # ReLU is 1-Lipschitz; mask is all-ones here
        else:
            h = z
    f64_logit = h[:, 0]
    e64_tight = e64_tight[:, 0]

    true_err = np.abs(f32_logit - f64_logit)
    assert (true_err <= e32).all(), \
        f"bound violated: max err {true_err.max()} vs bound {e32.min()}"
    assert (e32 >= 2.0 * e64_tight).all(), \
        "4x margin eroded below 2x by f32 evaluation of the recurrence"


def _ra2_query(eps):
    names = ("r1", "r2", "a1", "p")
    ranges = {"r1": (0, 3), "r2": (0, 3), "a1": (0, 2), "p": (0, 1)}
    dom = DomainSpec(name="toy2", columns=names, ranges=ranges, label="y")
    return FairnessQuery(domain=dom, protected=("p",), relaxed=("r1", "r2"),
                         relax_eps=eps)


@pytest.mark.parametrize("seed,eps", [(s, e) for s in range(4)
                                      for e in (1, 2)])
def test_ra2_window_matches_per_point_oracle(seed, eps):
    """Two-RA boxes (VERDICT r3 #6): the separable (2ε+1)² dilation must
    match decide_leaf applied to every core point, and SAT witnesses must
    satisfy the pair constraints on BOTH relaxed dims exactly."""
    q = _ra2_query(eps)
    enc = encode(q)
    net = _net(seed, (4, 8, 1))
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([3, 3, 2, 1], dtype=np.int64)
    verdict, ce = lattice_ops.decide_box_exhaustive(net, enc, lo, hi,
                                                    chunk=32)
    assert verdict == _ra_oracle(net, enc, lo, hi)
    if verdict == "sat":
        x, xp = ce
        weights = [np.asarray(w) for w in net.weights]
        biases = [np.asarray(b) for b in net.biases]
        assert engine.validate_pair(weights, biases, x, xp)
        assert x[3] != xp[3]                        # PA differs
        assert abs(int(x[0]) - int(xp[0])) <= eps   # RA 1 within ε
        assert abs(int(x[1]) - int(xp[1])) <= eps   # RA 2 within ε
        assert x[2] == xp[2]                        # shared dim equal
        assert (lo <= x).all() and (x <= hi).all()  # x core-ranged


def test_ra2_positive_only_in_expanded_corner():
    """Directed 2-RA soundness analog of the single-RA ring regression:
    f = r1 + r2 − 7.5 is negative at every core point (max 6) and positive
    only where BOTH expanded coordinates exceed their core range
    (r1 + r2 ≥ 8, e.g. (5, 4))."""
    q = _ra2_query(2)
    enc = encode(q)
    w1 = np.zeros((4, 2), np.float32)
    w1[0, 0] = 1.0
    w1[1, 0] = 1.0
    net = from_numpy(
        [w1, np.array([[1.0], [0.0]], np.float32)],
        [np.zeros(2, np.float32), np.array([-7.5], np.float32)])
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([3, 3, 2, 1], dtype=np.int64)
    assert _ra_oracle(net, enc, lo, hi) == "sat"
    verdict, ce = lattice_ops.decide_box_exhaustive(net, enc, lo, hi,
                                                    chunk=64)
    assert verdict == "sat"
    x, xp = ce
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    assert engine.validate_pair(weights, biases, x, xp)
    assert int(xp[0]) + int(xp[1]) >= 8  # witness partner in the corner


def test_ra2_peeled_matches_oracle():
    """2-RA mode composes with prefix peeling (RA axes never peeled)."""
    q = _ra2_query(1)
    enc = encode(q)
    net = _net(5, (4, 8, 1))
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([3, 3, 2, 1], dtype=np.int64)
    verdict, _ = lattice_ops.decide_box_exhaustive(
        net, enc, lo, hi, chunk=36, int32_limit=128, pipeline_depth=2)
    assert verdict == _ra_oracle(net, enc, lo, hi)


def test_decide_leaf_delta_lattice_guard():
    """VERDICT r3 #6: the decide_leaf (2ε+1)^|RA| > 100k guard is a tested
    boundary — a window just under the cap enumerates, just over returns an
    honest unknown instead of stalling."""
    names = ("r1", "r2", "r3", "p")
    dom = DomainSpec(name="toy3", columns=names,
                     ranges={"r1": (0, 3), "r2": (0, 3), "r3": (0, 3),
                             "p": (0, 1)}, label="y")
    net = _net(3, (4, 6, 1))
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    pt = np.array([1, 1, 1, 0], dtype=np.int64)
    lo = np.array([0, 0, 0, 0], dtype=np.int64)
    hi = np.array([3, 3, 3, 1], dtype=np.int64)
    # (2·23+1)^3 = 103,823 > 100k → unknown.
    q_over = FairnessQuery(domain=dom, protected=("p",),
                           relaxed=("r1", "r2", "r3"), relax_eps=23)
    v, _ = engine.decide_leaf(encode(q_over), weights, biases, pt, lo, hi)
    assert v == "unknown"
    # (2·22+1)^3 = 91,125 ≤ 100k → enumerates to a real verdict.
    q_under = FairnessQuery(domain=dom, protected=("p",),
                            relaxed=("r1", "r2", "r3"), relax_eps=22)
    v, _ = engine.decide_leaf(encode(q_under), weights, biases, pt, lo, hi)
    assert v in ("sat", "unsat")

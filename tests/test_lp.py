"""Triangle-relaxation LP sign BaB (ops.lp) — the AC-7-residue closer.

Oracle style follows tests/test_engine.py: tiny nets/domains where exact
enumeration is feasible, constructions chosen so each BaB outcome path
('certified' at root, 'certified' only after splits, 'refuted') is hit.
"""
import itertools as it

import numpy as np
import jax.numpy as jnp
import pytest

from fairify_tpu.models import mlp
from fairify_tpu.ops import crown as crown_ops
from fairify_tpu.ops import lp as lp_ops
from fairify_tpu.verify import property as prop

from test_bab2 import tiny_domain  # noqa: F401 (oracle reuse)


def crown_pre_bounds(net, lo, hi):
    b = crown_ops.crown_bounds(
        net, jnp.asarray(lo, jnp.float32)[None], jnp.asarray(hi, jnp.float32)[None])
    return ([np.asarray(x)[0] for x in b.ws_lb],
            [np.asarray(x)[0] for x in b.ws_ub])


def run_bab(net, lo, hi, want_positive=True, **kw):
    ws = [np.asarray(w) for w in net.weights]
    bs = [np.asarray(b) for b in net.biases]
    ms = [np.asarray(m) for m in net.masks]
    pre_lb, pre_ub = crown_pre_bounds(net, lo, hi)
    return lp_ops.sign_bab_lp(ws, bs, ms, lo, hi, pre_lb[:-1], pre_ub[:-1],
                              want_positive, **kw)


def test_certified_at_root():
    """f = relu(a) + relu(-a) + 0.5 ≥ 0.5: triangle lower side is exact."""
    ws = [np.array([[1.0, -1.0]], dtype=np.float32),
          np.array([[1.0], [1.0]], dtype=np.float32)]
    bs = [np.zeros(2, dtype=np.float32), np.array([0.5], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    outcome, nodes = run_bab(net, np.array([-4.0]), np.array([4.0]))
    assert outcome == "certified"
    assert nodes == 1


def test_certified_needs_splits():
    """f ≡ 1 but written as 1 + a − relu(a) + relu(−a) (a carried by an
    always-active neuron h3 = a + 8): the root triangle LP dips to −1, and
    only the activation split on the unstable pair recovers the identity
    relu(a) − relu(−a) = a."""
    ws = [np.array([[1.0, -1.0, 1.0]], dtype=np.float32),
          np.array([[-1.0], [1.0], [1.0]], dtype=np.float32)]
    bs = [np.array([0.0, 0.0, 8.0], dtype=np.float32),
          np.array([-7.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    # Sanity: the function really is ≡ 1 on the lattice.
    for a in range(-4, 5):
        h = np.maximum(0.0, np.array([a, -a, a + 8.0]))
        f = h @ np.array([-1.0, 1.0, 1.0]) - 7.0
        assert abs(f - 1.0) < 1e-9
    outcome, nodes = run_bab(net, np.array([-4.0]), np.array([4.0]))
    assert outcome == "certified"
    assert nodes > 1  # root alone must NOT suffice


def test_refuted_mixed_sign():
    """f = relu(a) − 2 over a ∈ [0, 6]: genuinely mixed sign, no unstable
    neurons — the root LP optimum is the true minimum and the BaB refutes."""
    ws = [np.array([[1.0]], dtype=np.float32), np.array([[1.0]], dtype=np.float32)]
    bs = [np.zeros(1, dtype=np.float32), np.array([-2.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    outcome, nodes = run_bab(net, np.array([0.0]), np.array([6.0]))
    assert outcome == "refuted"


def test_budget_exhaustion_reported():
    ws = [np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32),
          np.random.default_rng(1).normal(size=(8, 1)).astype(np.float32)]
    bs = [np.zeros(8, dtype=np.float32), np.array([0.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    outcome, nodes = run_bab(net, np.array([-8.0, -8.0]), np.array([8.0, 8.0]),
                             max_nodes=1)
    assert outcome in ("budget", "refuted", "certified")
    if outcome == "budget":
        assert nodes <= 1


@pytest.mark.parametrize("seed", range(8))
def test_certified_implies_lattice_positive(seed):
    """Soundness vs brute force: a 'certified' positive sign means every
    integer lattice point in the box has f > 0 (the LP proves the stronger
    continuous-box statement)."""
    rng = np.random.default_rng(seed)
    ws = [rng.normal(size=(2, 6)).astype(np.float32) * 0.7,
          rng.normal(size=(6, 4)).astype(np.float32) * 0.7,
          rng.normal(size=(4, 1)).astype(np.float32)]
    bs = [rng.normal(size=(6,)).astype(np.float32) * 0.3,
          rng.normal(size=(4,)).astype(np.float32) * 0.3,
          np.array([float(rng.normal()) + 1.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    lo = np.array([-3.0, -3.0])
    hi = np.array([3.0, 3.0])
    outcome, _ = run_bab(net, lo, hi, want_positive=True)
    Wn = [np.asarray(w, np.float64) for w in ws]
    Bn = [np.asarray(b, np.float64) for b in bs]

    def f(x):
        h = np.asarray(x, np.float64)
        for i, (w, b) in enumerate(zip(Wn, Bn)):
            h = h @ w + b
            if i < len(Wn) - 1:
                h = np.maximum(h, 0.0)
        return float(h[0])

    vals = [f(p) for p in it.product(range(-3, 4), repeat=2)]
    if outcome == "certified":
        assert min(vals) > 0.0
    # And conversely, if the true continuous min is clearly positive the BaB
    # (complete, generous budget) must not refute:
    if outcome == "refuted":
        assert min(vals) < 0.5  # refutation only plausible near/below zero


def test_negative_sign_path():
    """want_positive=False negates the net: f = −relu(a) − 1 < 0 certifies."""
    ws = [np.array([[1.0]], dtype=np.float32), np.array([[-1.0]], dtype=np.float32)]
    bs = [np.zeros(1, dtype=np.float32), np.array([-1.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    outcome, _ = run_bab(net, np.array([0.0]), np.array([6.0]),
                         want_positive=False)
    assert outcome == "certified"


def test_pair_bab_lp_flip_direction_with_ra_shift():
    """Review repro (same class as the exact-checker's): with an RA shift
    the mirrored flip lives in the out-of-box ε band only tower b reaches,
    so direction 1 is killed and ONLY flip=True finds the witness.

    f = ra − 4.5 over ra ∈ [0, 4], ε = 1: x = (·, ra=4) < 0 and
    x' = (·, ra=5) > 0."""
    import jax.numpy as jnp

    from fairify_tpu.ops import crown as crown_ops

    ws = [np.array([[0.0], [0.0], [1.0]], dtype=np.float32),
          np.array([[1.0]], dtype=np.float32)]
    bs = [np.array([0.0], dtype=np.float32), np.array([-4.5], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    dom = tiny_domain({"a": (0, 1), "pa": (0, 1), "ra": (0, 4)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",),
                               relaxed=("ra",), relax_eps=1)
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    lo, hi = lo.astype(np.int64), hi.astype(np.int64)
    x_lo, x_hi, xp_lo, xp_hi, valid = prop.role_boxes(
        enc, lo[None].astype(np.float32), hi[None].astype(np.float32))

    def pre_bounds(blo, bhi):
        b = crown_ops.crown_bounds(net, jnp.asarray(blo), jnp.asarray(bhi))
        return ([np.asarray(x)[0] for x in b.ws_lb[:-1]],
                [np.asarray(x)[0] for x in b.ws_ub[:-1]])

    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    M = [np.asarray(m) for m in net.masks]
    ba = pre_bounds(x_lo[0, 0][None], x_hi[0, 0][None])
    bb = pre_bounds(xp_lo[0, 1][None], xp_hi[0, 1][None])
    st1, _, _ = lp_ops.pair_bab_lp(W, B, M, enc, lo, hi,
                                   enc.assignments[0], enc.assignments[1],
                                   ba, bb, flip=False)
    assert st1 == "killed"  # f ≥ 0 impossible inside the box
    st2, _, wit = lp_ops.pair_bab_lp(W, B, M, enc, lo, hi,
                                     enc.assignments[0], enc.assignments[1],
                                     ba, bb, flip=True)
    assert st2 == "sat" and wit is not None
    x, xp = wit
    assert xp[2] == 5  # the witness uses the out-of-box ε band


def test_forced_inactive_infeasible_region():
    """Forcing z ≤ 0 where z ≥ 2 over the box must yield an empty region
    (exercised via the BaB's infeasible-branch discharge on a crafted net)."""
    # h1 = relu(a + 10) with a ∈ [0, 4]: z ∈ [10, 14], never inactive.
    ws = [np.array([[1.0]], dtype=np.float32), np.array([[1.0]], dtype=np.float32)]
    bs = [np.array([10.0], dtype=np.float32), np.array([0.5], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    wsn = [np.asarray(w) for w in net.weights]
    bsn = [np.asarray(b) for b in net.biases]
    msn = [np.asarray(m) for m in net.masks]
    pre_lb, pre_ub = crown_pre_bounds(net, np.array([0.0]), np.array([4.0]))
    tlp = lp_ops.TriangleLP(wsn, bsn, msn, np.array([0.0]), np.array([4.0]),
                            pre_lb[:-1], pre_ub[:-1])
    st, _, _ = tlp.solve_min([np.array([-1], dtype=np.int8)])
    assert st == "infeasible"

"""Tier-1 surface of the dynamic lock profiler (``obs/lockprof.py``).

Pins the three contracts the chaos matrix's ``--lockprof`` cell relies
on: the recorder captures real multi-thread acquisition interleaves, the
event-log schema round-trips through ``fairify_tpu report``'s reader,
and observed edges are a subset of the static graph — on a toy module
via an explicit analysis, and on the REAL serve/fleet stack against the
whole-repo graph (the CI gate: an unmodeled edge here is a bug in
``analysis/locks.py``, not in the runtime).
"""
import ast
import threading

import pytest

from fairify_tpu.analysis import locks as locks_mod
from fairify_tpu.obs import lockprof


@pytest.fixture
def profiler():
    """Installed lockprof for the test body; ALWAYS restored (the patch
    is process-global)."""
    lockprof.install()
    lockprof.reset()
    try:
        yield lockprof
    finally:
        lockprof.uninstall()


def test_multithread_interleave_records_edges(profiler, tmp_path):
    """Two threads nesting a -> b concurrently: the edge is recorded
    once per acquisition, never inverted, and the held stack survives a
    Condition wait/notify handoff between the threads."""
    a = threading.Lock(); a_site = a.site          # noqa: E702
    b = threading.Lock(); b_site = b.site          # noqa: E702
    cv = threading.Condition()
    state = {"ready": 0}

    def worker():
        with a:
            with b:
                with cv:
                    state["ready"] += 1
                    cv.notify_all()

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    with cv:
        while state["ready"] < 4:
            cv.wait(1.0)
    for t in threads:
        t.join()
    edges = lockprof.observed_edges()
    assert edges.get((a_site, b_site), 0) >= 4
    assert (b_site, a_site) not in edges


def test_observed_subset_of_static_on_toy_module(profiler, tmp_path):
    """Exercise a toy class dynamically AND analyze the same source
    statically: observed ⊆ static holds, and an artificial extra edge
    (not in the source) is reported as unmodeled."""
    src = (
        "import threading\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n")
    mod_path = tmp_path / "toy_locks.py"
    mod_path.write_text(src)
    rel = str(mod_path)  # dynamic sites use the abs path; rel must match
    an = locks_mod.ConcurrencyAnalysis()
    an.add_file(rel, ast.parse(src))
    an.finalize()

    ns: dict = {}
    exec(compile(src, str(mod_path), "exec"), ns)
    p = ns["P"]()
    p.ab()
    rep = lockprof.check_against_static(analysis=an)
    assert rep.in_scope >= 1 and not rep.unmodeled and rep.ok

    # An edge the source never takes (b held, then a) must be flagged.
    bad = dict(lockprof.observed_edges())
    bad[(p._b.site, p._a.site)] = 1
    rep2 = lockprof.check_against_static(analysis=an, edges=bad)
    assert len(rep2.unmodeled) == 1 and not rep2.ok
    assert "P._b" in rep2.unmodeled[0] and "P._a" in rep2.unmodeled[0]


def test_confirmed_static_cycle_escalates(profiler, tmp_path):
    """A static lock-order cycle whose every edge manifests dynamically
    is reported as confirmed (the callers fail hard on it)."""
    src = (
        "import threading\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def ba(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n")
    mod_path = tmp_path / "toy_cycle.py"
    mod_path.write_text(src)
    an = locks_mod.ConcurrencyAnalysis()
    an.add_file(str(mod_path), ast.parse(src))
    an.finalize()
    assert len(an.cycles()) == 1

    ns: dict = {}
    exec(compile(src, str(mod_path), "exec"), ns)
    p = ns["P"]()
    p.ab()
    rep = lockprof.check_against_static(analysis=an)
    assert not rep.confirmed_cycles  # only one arm manifested
    p.ba()  # deadlock-shaped in a single thread is safe; both edges now real
    rep = lockprof.check_against_static(analysis=an)
    assert len(rep.confirmed_cycles) == 1 and not rep.ok


def test_flush_emits_event_log_schema(profiler, tmp_path):
    """lock_edge events land in the obs event log with src/dst/count and
    aggregate into the report's lock-edge table."""
    from fairify_tpu import obs
    from fairify_tpu.obs import report as report_mod

    log = tmp_path / "events.jsonl"
    with obs.tracing(str(log), run_id="lockprof-test"):
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        n = lockprof.flush_events()
        assert n >= 1
        assert lockprof.flush_events() == 0  # flush is incremental
    records = obs.load_events(str(log))
    edges = [r for r in records
             if r.get("type") == "event" and r.get("name") == "lock_edge"]
    assert edges and all(
        {"src", "dst", "count"} <= set(e["attrs"]) for e in edges)
    agg = report_mod.aggregate([str(log)])
    assert agg["lock_edges"] and agg["lock_edges"][0]["count"] >= 1
    text = report_mod.render(agg)
    assert "observed lock-order edges" in text


def test_real_serve_fleet_edges_modeled(profiler):
    """Drive the REAL fleet router + server submit path under lockprof
    and check observed ⊆ the whole-repo static graph.  This is the CI
    gate for analysis drift: new runtime lock nesting must be modeled."""
    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.serve import FleetConfig, ServeConfig, ServerFleet
    from fairify_tpu.verify import presets

    cfg = presets.get("GC").with_(sim_size=16, grid_chunk=8)
    net = init_mlp((len(cfg.query().columns), 4, 1), seed=0)
    fl = ServerFleet(FleetConfig(n_replicas=2,
                                 replica=ServeConfig(batch_window_s=0.01)))
    # Never started: _route pins a bucket (fleet lock -> replica load()),
    # submit queues (server cv -> admission/metrics locks) — the lock
    # nesting runs without any device work.
    req = fl.submit(cfg, net, "m", partition_span=(0, 8))
    assert req.status == "queued"
    fl.drain()
    edges = lockprof.observed_edges()
    fleet_edges = [(s, d) for (s, d) in edges
                   if s[0].endswith("serve/fleet.py")]
    assert fleet_edges, "fleet router recorded no edges — probe broken?"
    rep = lockprof.check_against_static()
    assert rep.in_scope >= 2
    assert not rep.unmodeled, rep.unmodeled
    assert not rep.confirmed_cycles, rep.confirmed_cycles


def test_flush_is_incremental_by_count(profiler, tmp_path):
    """Periodic flushers get delta events, so report sums stay exact
    across flushes instead of freezing at the first count."""
    from fairify_tpu import obs
    from fairify_tpu.obs import report as report_mod

    log = tmp_path / "events.jsonl"
    a = threading.Lock()
    b = threading.Lock()
    with obs.tracing(str(log), run_id="lockprof-delta"):
        with a:
            with b:
                pass
        assert lockprof.flush_events() == 1
        for _ in range(3):
            with a:
                with b:
                    pass
        assert lockprof.flush_events() == 1  # same edge, new delta
        assert lockprof.flush_events() == 0  # nothing new
    agg = report_mod.aggregate([str(log)])
    (row,) = [r for r in agg["lock_edges"]]
    assert row["count"] == 4

"""Obs layer: spans (threads, nesting, Chrome export), metrics, heartbeat,
report CLI round-trip, launch-counter shims, lint, traced sweep end-to-end."""
import json
import os
import sys
import threading

import numpy as np
import pytest

from fairify_tpu import obs
from fairify_tpu.obs import heartbeat as hb_mod
from fairify_tpu.obs import metrics as metrics_mod
from fairify_tpu.obs import report as report_mod
from fairify_tpu.obs import trace as trace_mod
from fairify_tpu.utils import profiling
from fairify_tpu.utils.timing import PhaseTimer


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Each test gets a quiescent registry and no active tracer."""
    trace_mod.deactivate()
    metrics_mod.registry().reset()
    yield
    trace_mod.deactivate()
    metrics_mod.registry().reset()


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


def test_span_nesting_and_attributes(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tr = trace_mod.Tracer(path, run_id="r1")
    with tr.span("outer", model="m") as outer:
        with tr.span("inner") as inner:
            inner.set(verdict="unsat", n=3)
    tr.close()

    events = trace_mod.load_events(path)
    assert events[0]["type"] == "meta" and events[0]["run_id"] == "r1"
    spans = {e["name"]: e for e in events if e["type"] == "span"}
    assert set(spans) == {"outer", "inner"}
    # Inner closes first (JSONL order), nests under outer, keeps attrs.
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["outer"]["parent_id"] is None
    assert spans["inner"]["attrs"] == {"verdict": "unsat", "n": 3}
    assert spans["outer"]["attrs"] == {"model": "m"}
    assert spans["inner"]["dur_s"] <= spans["outer"]["dur_s"]
    # Closing record is a registry snapshot.
    assert events[-1]["type"] == "metrics"


def test_span_thread_safety(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tr = trace_mod.Tracer(path)

    def work(i):
        with tr.span("worker", idx=i):
            with tr.span("child", idx=i):
                pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tr.close()

    spans = [e for e in trace_mod.load_events(path) if e["type"] == "span"]
    workers = {e["attrs"]["idx"]: e for e in spans if e["name"] == "worker"}
    children = {e["attrs"]["idx"]: e for e in spans if e["name"] == "child"}
    assert len(workers) == len(children) == 8
    for i in range(8):
        # Parentage never crosses threads: each child nests under ITS
        # thread's worker span and shares its tid.
        assert children[i]["parent_id"] == workers[i]["span_id"]
        assert children[i]["tid"] == workers[i]["tid"]


def test_span_launch_delta_attribute(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with trace_mod.tracing(path):
        with obs.span("devwork"):
            profiling.bump_launch(3)
        with obs.span("hostwork"):
            pass
    spans = {e["name"]: e for e in trace_mod.load_events(path)
             if e["type"] == "span"}
    assert spans["devwork"]["attrs"]["launches"] == 3
    assert "launches" not in spans["hostwork"]["attrs"]


def test_disabled_spans_are_noops():
    assert trace_mod.current() is None
    with obs.span("nothing", a=1) as sp:
        sp.set(b=2)  # must not raise, must not record anywhere
    obs.event("verdict", verdict="sat")


def test_chrome_trace_valid(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with trace_mod.tracing(path):
        with obs.span("phase_a"):
            with obs.span("phase_b"):
                pass
        obs.event("verdict", verdict="sat")
    chrome = trace_mod.chrome_trace_path(path)
    assert chrome == str(tmp_path / "t.chrome.json")
    with open(chrome) as fp:
        doc = json.load(fp)
    events = doc["traceEvents"]
    complete = [e for e in events if e.get("ph") == "X"]
    assert {e["name"] for e in complete} == {"phase_a", "phase_b"}
    for e in complete:
        assert e["ts"] >= 0 and e["dur"] >= 0 and isinstance(e["tid"], int)
    assert any(e.get("ph") == "i" and e["name"] == "verdict" for e in events)


def test_tracing_scope_nesting(tmp_path):
    """An inner maybe_tracing must defer to the outer scope's tracer."""
    outer_path = str(tmp_path / "outer.jsonl")
    inner_path = str(tmp_path / "inner.jsonl")
    with trace_mod.tracing(outer_path) as outer:
        with trace_mod.maybe_tracing(inner_path) as inner:
            assert inner is outer
            with obs.span("nested"):
                pass
    assert not os.path.exists(inner_path)
    assert any(e.get("name") == "nested"
               for e in trace_mod.load_events(outer_path))


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------


def test_counter_labels_and_reset():
    reg = metrics_mod.MetricsRegistry()
    c = reg.counter("decisions")
    c.inc(verdict="sat")
    c.inc(2, verdict="unsat")
    assert c.value(verdict="sat") == 1
    assert c.value(verdict="unsat") == 2
    assert c.total() == 3
    reg.reset()
    assert c.total() == 0
    # Registration survives reset: same object comes back.
    assert reg.counter("decisions") is c


def test_histogram_bucket_counts():
    reg = metrics_mod.MetricsRegistry()
    h = reg.histogram("lat", buckets=(1.0, 2.0, 5.0))
    for v in (0.5, 1.5, 1.9, 4.0, 100.0):
        h.observe(v)
    assert h.counts() == [1, 2, 1, 1]  # ≤1, ≤2, ≤5, overflow
    assert h.count() == 5
    assert h.sum() == pytest.approx(107.9)
    snap = h.snapshot()[0]
    assert snap["buckets"] == [1.0, 2.0, 5.0]
    assert snap["counts"] == [1, 2, 1, 1]


def test_kind_collision_raises():
    reg = metrics_mod.MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_launch_counter_shims_resettable():
    profiling.reset_launches()
    assert profiling.launch_count() == 0
    profiling.bump_launch()
    profiling.bump_launch(4)
    assert profiling.launch_count() == 5
    # The same count is visible through the registry instrument...
    assert obs.registry().counter("device_launches").total() == 5
    # ...and a per-run reset zeroes absolute reads (the old module-global
    # accumulated forever).
    profiling.reset_launches()
    assert profiling.launch_count() == 0


def test_throughput_counter_mirrors_registry():
    from fairify_tpu.utils.profiling import ThroughputCounter

    c = ThroughputCounter()
    c.record("sat", via_stage0=True)
    c.record("unsat", via_stage0=False)
    c.record("unknown", via_stage0=False)
    dec = obs.registry().counter("decisions")
    assert dec.value(verdict="sat", via="stage0") == 1
    assert dec.value(verdict="unsat", via="bab") == 1
    assert dec.value(verdict="unknown", via="bab") == 1


def test_phase_timer_get_returns_raw_float():
    t = PhaseTimer()
    t.phases["x"] = 0.123456789
    assert t.get("x") == 0.123456789  # no 2-decimal rounding (serialization rounds)


# ---------------------------------------------------------------------------
# Heartbeat
# ---------------------------------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_heartbeat_throttles(capsys):
    import io

    clock = _FakeClock()
    out = io.StringIO()
    hb = hb_mod.Heartbeat(10.0, total=100, label="m", stream=out, clock=clock)
    clock.t += 1.0
    assert hb.beat(decided=1, attempted=1) is True  # first beat emits
    clock.t += 5.0
    assert hb.beat(decided=2, attempted=2) is False  # interval not elapsed
    assert out.getvalue().count("\n") == 1
    clock.t += 6.0
    assert hb.beat(decided=3, attempted=3) is True
    lines = out.getvalue().strip().splitlines()
    assert len(lines) == 2
    assert "3/100 attempted" in lines[1] and "eta" in lines[1]
    # force=True bypasses the throttle (the sweep's final line).
    assert hb.beat(decided=3, attempted=3, force=True) is True


def test_heartbeat_disabled_interval():
    import io

    out = io.StringIO()
    hb = hb_mod.Heartbeat(0.0, stream=out)
    assert hb.beat(decided=1, attempted=1) is False
    assert out.getvalue() == ""


def test_heartbeat_launch_delta():
    import io

    clock = _FakeClock()
    out = io.StringIO()
    hb = hb_mod.Heartbeat(1.0, stream=out, clock=clock)
    profiling.bump_launch(7)
    clock.t += 2.0
    hb.beat(decided=0, attempted=1)
    assert "+7 launches" in out.getvalue()


def test_heartbeat_eta_uses_recent_rate_not_run_mean():
    """Budgeted sweeps: a stage-0 burst (hundreds of partitions per second)
    followed by the BaB tail (seconds per partition).  The whole-run mean
    would promise ~1 minute; the recent-rate ETA must reflect the tail."""
    import io
    import re

    clock = _FakeClock()
    out = io.StringIO()
    hb = hb_mod.Heartbeat(10.0, total=1000, stream=out, clock=clock)
    clock.t += 1.0
    hb.beat(decided=500, attempted=500)  # stage-0 burst: 500 parts in 1s
    clock.t += 60.0
    hb.beat(decided=510, attempted=510)  # BaB tail: 10 parts in 60s
    lines = out.getvalue().strip().splitlines()
    eta = int(re.search(r"eta (\d+)s", lines[1]).group(1))
    # Whole-run mean (510/61 ≈ 8.4 pps) would claim eta ≈ 59 s; the recent
    # window runs at 1/6 pps, so an honest ETA is in the thousands.
    assert eta > 1000, eta


# ---------------------------------------------------------------------------
# Report CLI
# ---------------------------------------------------------------------------


def _synthetic_log(path):
    tr = trace_mod.Tracer(str(path), run_id="synth")
    with tr.span("stage0_decide", partitions=4):
        profiling.bump_launch(2)
    for pid, v in ((1, "sat"), (2, "unsat"), (3, "unsat"), (4, "unknown")):
        tr.event("verdict", model="m-1", partition_id=pid, verdict=v,
                 via="stage0" if pid < 4 else "bab")
    tr.close()


def test_report_cli_roundtrip(tmp_path, capsys):
    from fairify_tpu import cli

    log = tmp_path / "run.jsonl"
    _synthetic_log(log)
    json_out = tmp_path / "agg.json"
    rc = cli.main(["report", str(log), "--json-out", str(json_out)])
    assert rc == 0
    table = capsys.readouterr().out
    assert "m-1" in table and "stage0_decide" in table
    agg = json.loads(json_out.read_text())
    assert agg["verdicts"] == {"sat": 1, "unsat": 2, "unknown": 1}
    assert agg["decided"] == 3 and agg["attempted"] == 4
    assert agg["models"]["m-1"]["unsat"] == 2
    assert agg["phases"]["stage0_decide"]["launches"] == 2
    assert agg["device_launches"] == 2  # from the closing metrics snapshot


def test_report_cli_missing_file(tmp_path, capsys):
    from fairify_tpu import cli

    rc = cli.main(["report", str(tmp_path / "nope.jsonl")])
    assert rc == 2


def test_report_tolerates_truncated_line(tmp_path):
    log = tmp_path / "run.jsonl"
    _synthetic_log(log)
    with open(log, "a") as fp:
        fp.write('{"type": "event", "name": "verdi')  # crash mid-write
    agg = report_mod.aggregate([str(log)])
    assert agg["attempted"] == 4


def test_torn_lines_counted_not_raised(tmp_path, capsys):
    """Crash-mid-sweep leaves torn lines (the final line, or mid-file on a
    network fs): load_events and report must skip them WITH a counted
    warning, never raise or silently under-report."""
    log = tmp_path / "run.jsonl"
    _synthetic_log(log)
    # Tear a mid-file line and append a torn final line.
    lines = log.read_text().splitlines(keepends=True)
    lines[1] = lines[1][: len(lines[1]) // 2].rstrip() + "\n"
    log.write_text("".join(lines) + '{"type": "event", "na')
    records, skipped = trace_mod.load_events(str(log), count_skipped=True)
    assert skipped == 2
    assert all(isinstance(r, dict) for r in records)
    agg = report_mod.aggregate([str(log)])
    assert agg["skipped_lines"] == 2
    assert "torn/truncated" in report_mod.render(agg)
    rc = report_mod.main([str(log)])
    assert rc == 0
    assert "skipped 2 torn/truncated" in capsys.readouterr().err
    # Default signature unchanged for existing callers.
    assert isinstance(trace_mod.load_events(str(log)), list)


def test_report_dedupes_resumed_and_retried_partitions(tmp_path):
    """A resumed run appends ledger replays (and a retry re-decides an
    unknown) to the same log; each partition must count exactly once, with
    the LAST record winning."""
    log = tmp_path / "run.jsonl"
    tr = trace_mod.Tracer(str(log))
    tr.event("verdict", model="m", partition_id=1, verdict="sat", via="stage0")
    tr.event("verdict", model="m", partition_id=2, verdict="unknown", via="bab")
    tr.close()
    tr2 = trace_mod.Tracer(str(log))  # resumed run, same file (append)
    tr2.event("verdict", model="m", partition_id=1, verdict="sat", via="ledger")
    tr2.event("verdict", model="m", partition_id=2, verdict="unsat", via="bab")
    tr2.close()
    agg = report_mod.aggregate([str(log)])
    assert agg["attempted"] == 2
    assert agg["verdicts"] == {"sat": 1, "unsat": 1, "unknown": 0}
    # The 'via' breakdown covers decided partitions only and reflects the
    # winning records.
    assert agg["via"] == {"ledger": 1, "bab": 1}


def test_report_via_excludes_unknowns(tmp_path):
    log = tmp_path / "run.jsonl"
    tr = trace_mod.Tracer(str(log))
    tr.event("verdict", model="m", partition_id=1, verdict="unsat", via="bab")
    tr.event("verdict", model="m", partition_id=2, verdict="unknown", via="bab")
    tr.close()
    agg = report_mod.aggregate([str(log)])
    assert agg["via"] == {"bab": 1}  # unknowns are not "decided via" anything


def test_metrics_snapshot_is_per_run_delta(tmp_path):
    """Launches bumped BEFORE the tracer opens (warm-up pass, earlier runs)
    must not appear in the closing metrics record."""
    profiling.bump_launch(50)  # pre-run noise
    log = tmp_path / "run.jsonl"
    with trace_mod.tracing(str(log)):
        profiling.bump_launch(4)
    agg = report_mod.aggregate([str(log)])
    assert agg["device_launches"] == 4
    # Two runs appended to one file: their per-run deltas sum.
    with trace_mod.tracing(str(log)):
        profiling.bump_launch(3)
    agg = report_mod.aggregate([str(log)])
    assert agg["device_launches"] == 7


def test_snapshot_delta_histograms_and_gauges():
    reg = metrics_mod.MetricsRegistry()
    h = reg.histogram("lat", buckets=(1.0,))
    g = reg.gauge("g")
    h.observe(0.5)
    g.set(10)
    before = reg.snapshot()
    h.observe(2.0)
    g.set(20)
    delta = metrics_mod.snapshot_delta(before, reg.snapshot())
    s = delta["lat"]["series"][0]
    assert s["counts"] == [0, 1] and s["count"] == 1
    assert s["sum"] == pytest.approx(2.0)
    assert delta["g"]["series"][0]["value"] == 20  # gauges: last write wins


# ---------------------------------------------------------------------------
# Lint + end-to-end traced sweep
# ---------------------------------------------------------------------------


def test_obs_rules_clean_on_tree():
    """The five obs rules (tier-1-wired) pass on the current tree —
    through the rule engine; the old ``scripts/lint_obs.py`` shim is gone."""
    from fairify_tpu.lint import core as lint_core
    from fairify_tpu.lint.rules import legacy_rules

    result = lint_core.run_lint(rules=legacy_rules())
    assert not result.findings and not result.parse_errors


def test_lint_bans_raw_jit_in_verify_and_ops(tmp_path):
    """Every spelling of a bare jax.jit in verify/ or ops/ is flagged;
    obs_jit passes; files outside the scope are untouched."""
    from fairify_tpu.lint import core as lint_core
    from fairify_tpu.lint.rules_obs import RawJitRule

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "from functools import partial\n"
        "@jax.jit\n"
        "def a(x):\n    return x\n"
        "b = jax.jit(lambda x: x)\n"
        "@partial(jax.jit, static_argnames=('k',))\n"
        "def c(x, k):\n    return x\n")
    for scope_rel in ("fairify_tpu/verify/bad.py", "fairify_tpu/ops/bad.py"):
        result = lint_core.run_lint(rules=[RawJitRule()],
                                    files=[(str(bad), scope_rel)])
        assert len(result.findings) == 3, scope_rel
    # Out of scope (models/ trains ad-hoc nets; the rule protects the
    # verification core): no raw-jit errors.
    result = lint_core.run_lint(rules=[RawJitRule()],
                                files=[(str(bad), "fairify_tpu/models/bad.py")])
    assert not result.findings
    good = tmp_path / "good.py"
    good.write_text(
        "from fairify_tpu.obs import obs_jit\n"
        "@obs_jit(static_argnames=('k',))\n"
        "def a(x, k):\n    return x\n")
    result = lint_core.run_lint(rules=[RawJitRule()],
                                files=[(str(good),
                                        "fairify_tpu/verify/good.py")])
    assert not result.findings


def test_traced_sweep_matches_report(tmp_path, monkeypatch):
    """Acceptance: a traced sweep writes JSONL + Chrome trace whose spans
    cover the stage-0 phases and whose report reproduces the ModelReport."""
    from fairify_tpu.data import domains as dom_mod
    from fairify_tpu.data.domains import DomainSpec
    from fairify_tpu.verify import engine, sweep
    from fairify_tpu.verify.config import SweepConfig
    from fairify_tpu.verify.oracle import random_net

    dom = DomainSpec(name="tinyobs", label="y",
                     ranges={"a": (0, 9), "pa": (0, 1), "b": (0, 4)})
    monkeypatch.setitem(dom_mod.DOMAINS, "tinyobs", dom)
    trace_path = str(tmp_path / "run.jsonl")
    cfg = SweepConfig(
        name="tinyobs", dataset="tinyobs", protected=("pa",),
        partition_threshold=5, sim_size=64, soft_timeout_s=30.0,
        hard_timeout_s=600.0, result_dir=str(tmp_path),
        trace_out=trace_path,
        engine=engine.EngineConfig(frontier_size=64, attack_samples=32,
                                   bab_attack_samples=8, soft_timeout_s=30.0))
    net = random_net(np.random.default_rng(7), (3, 6, 1))
    report = sweep.verify_model(net, cfg, model_name="tiny-1")

    events = trace_mod.load_events(trace_path)
    names = {e["name"] for e in events if e["type"] == "span"}
    assert {"verify_model", "stage0_prune", "stage0_decide",
            "stage0_parity"} <= names
    model_span = next(e for e in events if e["type"] == "span"
                      and e["name"] == "verify_model")
    assert model_span["attrs"]["partitions"] == report.partitions_total
    # Device work is attributed: some span carries a launches attr.
    assert any(e["attrs"].get("launches", 0) > 0
               for e in events if e["type"] == "span")

    # Chrome trace loads and covers the same spans.
    with open(trace_mod.chrome_trace_path(trace_path)) as fp:
        doc = json.load(fp)
    assert {"verify_model", "stage0_decide"} <= {
        e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}

    # `report` over the log reproduces the run's verdict counts.
    agg = report_mod.aggregate([trace_path])
    assert agg["verdicts"] == report.counts
    assert agg["attempted"] == len(report.outcomes)

"""Crash-resume fuzz (VERDICT r3 #8): SIGKILL a sweep mid-ledger, resume,
and require the merged ledger to equal an uninterrupted run's verdict map.

The JSONL ledger exists precisely for this scenario — a host dying with no
chance to flush or finalize — but round 3 only ever exercised clean
interrupts (completed processes replaying their own ledgers).  Here the
sweep subprocess is killed with SIGKILL the moment its ledger starts
filling (mid-reporting-loop, so the tail may be a truncated JSON line,
which ``sweep._load_ledger`` must tolerate), then a second process resumes
into the same result dir.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = ""  # keep the axon PJRT plugin out of the child
    return env


def _ledger_map(path):
    out = {}
    with open(path) as fp:
        for line in fp:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # the truncated tail a SIGKILL leaves behind
            out[rec["partition_id"]] = rec["verdict"]
    return out


@pytest.mark.slow
def test_sigkill_mid_sweep_resume_matches_uninterrupted(tmp_path):
    crashed = tmp_path / "crashed"
    clean = tmp_path / "clean"
    base = [sys.executable, "-m", "fairify_tpu", "run", "GC",
            "--models", "GC-4", "--soft-timeout", "5",
            "--hard-timeout", "600"]
    ledger = crashed / "GC-GC-4.ledger.jsonl"

    # Up to 3 attempts to land the SIGKILL while the ledger is partially
    # written (the reporting loop is fast; a very fast machine could finish
    # before the poll sees the first line — then the ledger is complete and
    # the kill proves nothing, so retry from scratch).
    partial = False
    for _ in range(3):
        if ledger.exists():
            ledger.unlink()
        proc = subprocess.Popen(
            base + ["--result-dir", str(crashed)], cwd=ROOT,
            env=_worker_env(), stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        try:
            deadline = time.time() + 600
            while time.time() < deadline and proc.poll() is None:
                if ledger.exists() and os.path.getsize(ledger) > 0:
                    break
                time.sleep(0.02)
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        if ledger.exists() and 0 < len(_ledger_map(ledger)) < 201:
            partial = True
            break
    assert ledger.exists(), "sweep never started writing its ledger"
    pre_resume = _ledger_map(ledger)

    # Resume into the same result dir (fresh process, same config key).
    res = subprocess.run(
        base + ["--result-dir", str(crashed)], cwd=ROOT, env=_worker_env(),
        timeout=900, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    assert res.returncode == 0, res.stdout.decode()[-2000:]

    # Uninterrupted reference run.
    ref = subprocess.run(
        base + ["--result-dir", str(clean)], cwd=ROOT, env=_worker_env(),
        timeout=900, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    assert ref.returncode == 0, ref.stdout.decode()[-2000:]

    got = _ledger_map(ledger)
    want = _ledger_map(clean / "GC-GC-4.ledger.jsonl")
    assert set(got) == set(want)
    # Verdicts are deterministic on this grid (stage-0 + keyed PRNG), so
    # the merged map must equal the uninterrupted one exactly; budget
    # UNKNOWNs are excluded on principle (machine speed, not correctness).
    diff = {k for k in want if want[k] != got[k]
            and "unknown" not in (want[k], got[k])}
    assert not diff, diff
    # The resume must have preserved (not re-decided differently) every
    # verdict the crashed run already recorded.
    for pid, v in pre_resume.items():
        if v != "unknown":
            assert got[pid] == v, (pid, v, got[pid])
    if partial:
        # The crash genuinely interrupted the loop: the resumed run had
        # real work left, so this exercised merge-not-recompute.
        assert len(pre_resume) < len(got)

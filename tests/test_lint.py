"""Tier-1 surface of the ``fairify_tpu.lint`` rule engine (DESIGN.md §11).

Three layers:

* **repo gate** — the committed tree is clean under all fifteen rules
  with the committed baseline, including ratchet mode, inside the 5 s
  runtime budget.  This is the CI wiring: a PR that introduces a finding
  (or grows a baselined rule's count) fails here.
* **fixture corpus** — ``tests/lint_fixtures/<rule-id>/`` holds small
  positive/negative snippets per rule.  Each fixture's first line declares
  the virtual repo-relative path it is linted as (``# rel: …``), and every
  line that must be flagged carries an ``# EXPECT`` marker; the golden test
  pins the exact ``(path, line)`` set per rule.  A meta-test asserts every
  shipped rule keeps ≥1 positive and ≥1 negative fixture.
* **engine behavior** — inline suppressions, baseline grandfathering,
  ratchet breaches, JSON output.

No jax import anywhere on these paths: the lint layer is plain-AST only.
"""
import json
import pathlib
import subprocess
import sys

import pytest

from fairify_tpu.lint import core
from fairify_tpu.lint.rules import LEGACY_RULE_IDS, all_rules, legacy_rules

REPO_ROOT = pathlib.Path(core.repo_root())
FIXTURE_ROOT = pathlib.Path(__file__).parent / "lint_fixtures"

RULE_IDS = [r.id for r in all_rules()]


def _rule(rule_id):
    """A fresh instance (rules are stateful across one engine run)."""
    return {r.id: r for r in all_rules()}[rule_id]


def _fixture_files(rule_id):
    """[(abs path, declared repo-relative path)] for one rule's corpus."""
    out = []
    for p in sorted((FIXTURE_ROOT / rule_id).glob("*.py")):
        first = p.read_text().splitlines()[0]
        assert first.startswith("# rel: "), \
            f"{p} must declare its virtual path in line 1 as '# rel: …'"
        out.append((str(p), first[len("# rel: "):].strip()))
    return out


def _expected_lines(path, rel):
    """{(rel, lineno)} of every ``# EXPECT``-marked line in one fixture."""
    return {(rel, i)
            for i, line in enumerate(
                pathlib.Path(path).read_text().splitlines(), start=1)
            if "# EXPECT" in line}


# ---------------------------------------------------------------------------
# Repo gate (the actual CI check)
# ---------------------------------------------------------------------------


def test_repo_clean_under_all_rules_with_ratchet():
    baseline = core.load_baseline(str(REPO_ROOT / core.BASELINE_REL))
    result = core.run_lint(baseline=baseline, ratchet=True)
    assert result.rules == list(RULE_IDS) and len(result.rules) == 15
    assert not result.parse_errors, [f.render() for f in result.parse_errors]
    assert not result.findings, "\n" + "\n".join(
        f.render() for f in result.findings)
    assert not result.ratchet_breaches, result.ratchet_breaches
    assert result.ok
    assert result.n_files > 50  # whole-repo sweep, not a partial walk
    # Runtime budget: the full sweep (incl. the whole-program concurrency
    # analysis) must stay cheap enough to run on every commit.
    assert result.duration_s < 5.0, result.duration_s


def test_repo_walk_includes_scripts():
    """The default walk covers scripts/ (chaos-coverage reads the chaos
    driver there); fairify_tpu-scoped rules must still skip those files."""
    files = dict(core.default_files(str(REPO_ROOT)))
    rels = set(files.values())
    assert "scripts/chaos_matrix.py" in rels
    assert any(r.startswith("fairify_tpu/") for r in rels)


def test_legacy_rules_clean():
    """The five original observability rules find nothing on the tree."""
    result = core.run_lint(rules=legacy_rules())
    assert tuple(result.rules) == LEGACY_RULE_IDS
    assert not result.findings and not result.parse_errors


# ---------------------------------------------------------------------------
# Fixture corpus: golden (path, line) sets per rule
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_fixture_corpus_golden(rule_id):
    """Linting one rule's full fixture dir (as one virtual tree, so the
    cross-file fault-site analysis sees registry + call sites together)
    flags exactly the ``# EXPECT`` lines."""
    files = _fixture_files(rule_id)
    expected = set()
    for path, rel in files:
        expected |= _expected_lines(path, rel)
    result = core.run_lint(rules=[_rule(rule_id)], files=files)
    assert not result.parse_errors, [f.render() for f in result.parse_errors]
    got = {(f.path, f.line) for f in result.findings}
    assert got == expected, (
        f"{rule_id}: findings {sorted(got - expected)} unexpected, "
        f"{sorted(expected - got)} missing")


def test_every_rule_has_positive_and_negative_fixtures():
    """Meta-test: a shipped rule without a corpus cannot regress safely."""
    for rule_id in RULE_IDS:
        d = FIXTURE_ROOT / rule_id
        assert d.is_dir(), f"missing fixture dir for rule {rule_id!r}"
        pos = sorted(d.glob("pos_*.py"))
        neg = sorted(d.glob("neg_*.py"))
        assert pos, f"{rule_id}: no positive fixture (pos_*.py)"
        assert neg, f"{rule_id}: no negative fixture (neg_*.py)"
        for p in pos:
            assert "# EXPECT" in p.read_text(), \
                f"{p} is a positive fixture but marks no # EXPECT line"
        for p in neg:
            assert "# EXPECT" not in p.read_text(), \
                f"{p} is a negative fixture but marks an # EXPECT line"
    extra = {d.name for d in FIXTURE_ROOT.iterdir() if d.is_dir()} \
        - set(RULE_IDS)
    assert not extra, f"fixture dirs without a shipped rule: {sorted(extra)}"


# ---------------------------------------------------------------------------
# Suppressions, baseline, ratchet
# ---------------------------------------------------------------------------


def _lint_src(tmp_path, src, rel="fairify_tpu/verify/fx.py", **kw):
    p = tmp_path / "fx.py"
    p.write_text(src)
    return core.run_lint(rules=kw.pop("rules", None) or [_rule("obs-print")],
                         files=[(str(p), rel)], **kw)


def test_inline_suppression_silences_exactly_that_line(tmp_path):
    result = _lint_src(tmp_path, (
        "def f(i):\n"
        "    print(i)  # lint: disable=obs-print\n"
        "    print(i)  # lint: disable=obs-time-time  (wrong id: no effect)\n"
        "    print(i)\n"))
    assert [f.line for f in result.findings] == [3, 4]
    assert result.suppressed == 1


def test_inline_suppression_disable_all(tmp_path):
    result = _lint_src(tmp_path,
                       "print(1)  # lint: disable=all\n")
    assert not result.findings and result.suppressed == 1


def test_baseline_grandfathers_by_key_and_count(tmp_path):
    src = "def f(i):\n    print(i)\n    print(i)\n"
    key = "obs-print::fairify_tpu/verify/fx.py::f"
    baseline = {key: {"count": 1, "reason": "test"}}
    result = _lint_src(tmp_path, src, baseline=baseline)
    assert len(result.findings) == 1 and len(result.baselined) == 1
    assert result.findings[0].key == key  # overflow past the budget is live
    # Full budget: everything grandfathered, run is ok (without ratchet).
    result = _lint_src(tmp_path, src,
                       baseline={key: {"count": 2, "reason": "test"}})
    assert not result.findings and len(result.baselined) == 2 and result.ok


def test_ratchet_breaches_when_count_exceeds_baseline(tmp_path):
    src = "def f(i):\n    print(i)\n    print(i)\n"
    key = "obs-print::fairify_tpu/verify/fx.py::f"
    ok = _lint_src(tmp_path, src, ratchet=True,
                   baseline={key: {"count": 2, "reason": "test"}})
    assert ok.ok and not ok.ratchet_breaches
    bad = _lint_src(tmp_path, src, ratchet=True,
                    baseline={key: {"count": 1, "reason": "test"}})
    assert bad.ratchet_breaches == ["obs-print: 2 finding(s) > baseline 1"]
    assert not bad.ok


def test_malformed_baseline_is_loud(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"findings": {"obs-print::x.py::f": {"count": 0}}}))
    with pytest.raises(ValueError):
        core.load_baseline(str(p))
    # The reason is mandatory: grandfathering without a recorded why fails.
    p.write_text(json.dumps({"findings": {"obs-print::x.py::f": {"count": 1}}}))
    with pytest.raises(ValueError):
        core.load_baseline(str(p))
    assert core.load_baseline(str(tmp_path / "missing.json")) == {}


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    result = _lint_src(tmp_path, "def broken(:\n")
    assert not result.findings
    assert [f.rule for f in result.parse_errors] == ["parse"]
    assert not result.ok


# ---------------------------------------------------------------------------
# CLI: scripts/lint.py (JSON + ratchet)
# ---------------------------------------------------------------------------


def test_scripts_lint_json_and_ratchet_exit_zero():
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "scripts" / "lint.py"),
         "--format", "json", "--ratchet"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True
    assert sorted(doc["counts"]) == sorted(RULE_IDS)
    assert all(n == 0 for n in doc["counts"].values())
    assert doc["ratchet_breaches"] == []


def test_cli_rejects_unknown_rule_id(capsys):
    assert core.main(["--rules", "no-such-rule"]) == 2


def test_cli_rule_subset(capsys):
    assert core.main(["--rules", "obs-print,jit-purity",
                      "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert sorted(doc["rules"]) == ["jit-purity", "obs-print"]


def test_lint_obs_shim_removed():
    """The PR 6 migration shim is gone; the rule engine is the only lint
    entry point (``fairify_tpu lint`` / ``scripts/lint.py``)."""
    assert not (REPO_ROOT / "scripts" / "lint_obs.py").exists()


def test_json_and_text_emit_per_rule_suppression_counts(tmp_path):
    """--format json must carry the per-rule suppression breakdown the
    text renderer prints (suppressions are counted, never silent)."""
    from fairify_tpu.lint.rules import all_rules

    p = tmp_path / "fx.py"
    p.write_text(
        "import time\n"
        "def f(i):\n"
        "    print(i)  # lint: disable=obs-print\n"
        "    print(i)  # lint: disable=obs-print\n"
        "    t = time.time()  # lint: disable=obs-time-time\n")
    result = core.run_lint(rules=all_rules(),
                           files=[(str(p), "fairify_tpu/verify/fx.py")])
    assert result.suppressed == 3
    assert result.suppressed_by_rule == {"obs-print": 2, "obs-time-time": 1}
    doc = result.as_dict()
    assert doc["suppressed_by_rule"] == {"obs-print": 2,
                                         "obs-time-time": 1}
    text = core.render_text(result)
    assert "suppressed by rule: obs-print=2, obs-time-time=1" in text


def test_baseline_rejects_whitespace_only_reason(tmp_path):
    """A grandfathered entry with a whitespace-only reason is as useless
    as a missing one — the ratchet gate must refuse to load it."""
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps(
        {"findings": {"obs-print::x.py::f": {"count": 1, "reason": "   "}}}))
    with pytest.raises(ValueError, match="reason"):
        core.load_baseline(str(p))
    # And through the CLI ratchet path: exit 2, loud on stderr.
    import subprocess

    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "scripts" / "lint.py"),
         "--ratchet", "--baseline", str(p)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "reason" in proc.stderr

"""Pruning-variant and loader-variant parity (harsh/global/mismatch/adf)."""
import numpy as np
import pytest

from fairify_tpu.models import mlp, train
from fairify_tpu.verify import pruning


def _net():
    return train.init_mlp([6, 12, 8, 1], seed=5)


def test_harsh_prune_equals_candidates():
    net = _net()
    lo = np.zeros((3, 6), dtype=np.int64)
    hi = np.full((3, 6), 9, dtype=np.int64)
    harsh = pruning.harsh_prune_grid(net, lo, hi, sim_size=128, seed=0)
    sound = pruning.sound_prune_grid(net, lo, hi, sim_size=128, seed=0, exact_certify=False)
    for h, c in zip(harsh, sound.candidates):
        np.testing.assert_array_equal(h, c)


def test_sound_prune_global_is_single_box_grid():
    net = _net()
    lo = np.zeros(6, dtype=np.int64)
    hi = np.full(6, 9, dtype=np.int64)
    glob = pruning.sound_prune_global(net, lo, hi, sim_size=128, seed=0)
    grid = pruning.sound_prune_grid(net, lo[None], hi[None], 128, 0)
    for a, b in zip(glob.st_deads, grid.st_deads):
        np.testing.assert_array_equal(a, b)
    assert glob.st_deads[0].shape[0] == 1
    # Sound deads are always a subset of simulation candidates.
    for d, c in zip(glob.st_deads, glob.candidates):
        assert np.all(d <= c + 1e-6)


def test_prediction_mismatch_finds_flips():
    rng = np.random.default_rng(2)
    net = _net()
    ws = [np.asarray(w) for w in net.weights]
    bs = [np.asarray(b) for b in net.biases]
    X = rng.integers(0, 10, size=(64, 6)).astype(np.float64)
    none_dead = [np.zeros(12), np.zeros(8), np.zeros(1)]
    assert pruning and mlp.prediction_mismatch(ws, bs, X, dead=none_dead).size == 0
    # Killing every hidden neuron forces the constant-bias prediction;
    # mismatches must be exactly the points the original classifies otherwise.
    all_dead = [np.ones(12), np.ones(8), np.zeros(1)]
    mm = mlp.prediction_mismatch(ws, bs, X, dead=all_dead)
    orig = mlp.predict_np(ws, bs, X)
    pruned = mlp.predict_np(ws, bs, X, dead=all_dead)
    np.testing.assert_array_equal(mm, np.where(orig != pruned)[0])


def test_load_adult_adf_one_hot(reference_assets_available):
    if not reference_assets_available:
        pytest.skip("reference assets not mounted")
    from fairify_tpu.data import loaders

    base = loaders.load("adult")
    adf = loaders.load("adult_adf")
    assert adf.y_train.shape == (base.y_train.shape[0], 2)
    np.testing.assert_array_equal(adf.y_train.sum(axis=1), np.ones(len(adf.y_train)))
    np.testing.assert_array_equal(adf.y_train[:, 1], base.y_train)
    np.testing.assert_array_equal(adf.X_train, base.X_train)

"""The 12-feature compas encoding (task4's CP family): domain, loader, sweep wiring."""
import numpy as np
import pytest

from fairify_tpu.data import domains, loaders
from fairify_tpu.models import zoo
from fairify_tpu.verify import presets, sweep

pytestmark = pytest.mark.usefixtures("skip_without_reference_assets")


@pytest.fixture
def skip_without_reference_assets(reference_assets_available):
    if not reference_assets_available:
        pytest.skip("reference assets not mounted")


def test_domain_matches_data():
    ds = loaders.load("compass12")
    dom = domains.get_domain("compass12")
    assert tuple(ds.feature_columns) == dom.columns
    X = np.asarray(ds.X)
    lo, hi = dom.lo_hi()
    assert (X >= lo[None, :]).all() and (X <= hi[None, :]).all()


def test_zoo_filter_selects_12_input_models():
    cfg = presets.get("CP12")
    nets, skipped = zoo.load_matching("compass12", 12)
    # the 12-input family: CP-2..10 + aCP-1-Old; 6-input CP-1/CP-11 skipped
    assert len(nets) >= 9 and all(n.in_dim == 12 for n in nets.values())
    assert "CP-11" in skipped and "CP-1" in skipped
    assert cfg.query().protected == ("race",)


def test_cp12_partition_grid_builds():
    cfg = presets.get("CP12")
    parts = sweep.build_partitions(cfg)
    lo, hi = parts[1], parts[2]
    assert lo.shape[1] == 12
    # PA column stays full-range in every partition box
    race = cfg.query().domain.columns.index("race")
    assert (lo[:, race] == 0).all() and (hi[:, race] == 1).all()

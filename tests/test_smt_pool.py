"""Out-of-process SMT worker pool: containment, parity, fan-out, chaos.

Pins the DESIGN.md §14 isolation contract against REAL subprocesses — the
brute backend gives ground-truth verdicts on tiny boxes without
``z3-solver``, so every test here exercises genuine out-of-process
solving, not mocks:

* verdict parity — the pool agrees with the native engine on decided
  verdicts (and, where z3 is installed, with in-process
  ``decide_box_smt``), portfolio on or off, any worker count;
* hard wall-clock bound — a wedged worker (chaos ``hang``) is SIGKILLed
  within grace of its tier deadline, pinned with a stopwatch;
* crash containment — a worker SIGKILLed mid-query (a real ``kill -9`` on
  the live subprocess, not a simulation) is retried on a fresh worker and
  the query still decides; exhaustion degrades to a machine-readable
  ``smt.worker:*`` reason, never an exception;
* memout policy — an RSS-capped worker that allocates past its cap dies
  alone; the retry runs ONCE on a doubled cap, never at a bigger time
  budget;
* sweep integration — a crippled-engine sweep whose UNKNOWNs the pool
  decides is bit-equal to the healthy-engine sweep, and the serve-mode
  deferred drain converges to the same map.
"""
import os
import signal
import time

import numpy as np
import pytest

from fairify_tpu.data.domains import DomainSpec
from fairify_tpu.models import mlp
from fairify_tpu.obs import metrics as metrics_mod
from fairify_tpu.resilience import faults
from fairify_tpu.smt import protocol
from fairify_tpu.smt.pool import PoolConfig, SmtPool, solve_box, submit_box
from fairify_tpu.verify import property as prop
from fairify_tpu.verify import smt as smt_mod


@pytest.fixture(autouse=True)
def _clean_state():
    metrics_mod.registry().reset()
    faults.disarm()
    yield
    metrics_mod.registry().reset()
    faults.disarm()


def _toy(ranges):
    return DomainSpec(name="toy", columns=tuple(ranges),
                      ranges={k: tuple(v) for k, v in ranges.items()},
                      label="y")


def _setup(ranges=None, protected=("pa",)):
    ranges = ranges or {"a": (0, 3), "pa": (0, 1)}
    q = prop.FairnessQuery(domain=_toy(ranges), protected=protected)
    enc = prop.encode(q)
    lo, hi = q.domain.lo_hi()
    return enc, lo.astype(np.int64), hi.astype(np.int64)


def _flip_net():
    ws = [np.array([[0.0], [2.0]], dtype=np.float32),
          np.array([[1.0]], dtype=np.float32)]
    bs = [np.array([0.0], dtype=np.float32),
          np.array([-1.0], dtype=np.float32)]
    return mlp.from_numpy(ws, bs)


def _const_net():
    return mlp.from_numpy([np.zeros((2, 1), np.float32)],
                          [np.array([1.0], np.float32)])


def _pool(**kw):
    kw.setdefault("workers", 1)
    kw.setdefault("backend", "brute")
    kw.setdefault("grace_s", 0.5)
    kw.setdefault("backoff_s", 1e-3)
    return SmtPool(PoolConfig(**kw))


# ---------------------------------------------------------------------------
# Verdicts and parity
# ---------------------------------------------------------------------------


def test_pool_decides_sat_and_unsat():
    enc, lo, hi = _setup()
    with _pool() as pool:
        v, ce, reason = solve_box(pool, _flip_net(), enc, lo, hi,
                                  soft_timeout_s=10.0)
        assert (v, reason) == ("sat", None)
        assert ce is not None and len(ce[0]) == 2
        v, ce, reason = solve_box(pool, _const_net(), enc, lo, hi,
                                  soft_timeout_s=10.0)
        assert (v, ce, reason) == ("unsat", None, None)


@pytest.mark.parametrize("workers,portfolio", [(1, 0), (2, 0), (2, 2)])
def test_pool_parity_with_native_engine(workers, portfolio):
    """Decided pool verdicts equal the native engine's on random tiny
    nets — any worker count, portfolio on or off (§14 determinism rule:
    the VERDICT is deterministic; the witness need not be)."""
    from fairify_tpu.verify import engine

    enc, lo, hi = _setup({"a": (0, 2), "pa": (0, 1), "b": (0, 2)})
    with _pool(workers=workers, portfolio=portfolio) as pool:
        for seed in range(4):
            rng = np.random.default_rng(seed)
            net = mlp.from_numpy(
                [rng.normal(size=(3, 4)).astype(np.float32),
                 rng.normal(size=(4, 1)).astype(np.float32)],
                [rng.normal(size=(4,)).astype(np.float32) * 0.5,
                 rng.normal(size=(1,)).astype(np.float32)])
            native = engine.decide_box(
                net, enc, lo, hi, engine.EngineConfig(soft_timeout_s=30.0))
            got, ce, _reason = solve_box(pool, net, enc, lo, hi,
                                         soft_timeout_s=30.0)
            assert got in ("sat", "unsat")  # brute is complete on tiny boxes
            if native.verdict != "unknown":
                assert got == native.verdict
            if got == "sat":
                assert engine.validate_pair(
                    [np.asarray(w) for w in net.weights],
                    [np.asarray(b) for b in net.biases], *ce)


@pytest.mark.skipif(not smt_mod.HAVE_Z3, reason="z3-solver not installed")
def test_pool_parity_with_in_process_z3():
    """Pool-backed solving produces the same verdicts as the in-process
    ``decide_box_smt`` it replaced (pool backend resolves to z3 here)."""
    enc, lo, hi = _setup({"a": (0, 3), "pa": (0, 1), "b": (0, 3)})
    with SmtPool(PoolConfig(workers=2, backend="z3")) as pool:
        for seed in range(4):
            rng = np.random.default_rng(seed)
            net = mlp.from_numpy(
                [rng.normal(size=(3, 6)).astype(np.float32),
                 rng.normal(size=(6, 1)).astype(np.float32)],
                [rng.normal(size=(6,)).astype(np.float32) * 0.5,
                 rng.normal(size=(1,)).astype(np.float32)])
            inproc, _, _ = smt_mod.decide_box_smt(net, enc, lo, hi,
                                                  soft_timeout_s=30.0)
            pooled, _, _ = solve_box(pool, net, enc, lo, hi,
                                     soft_timeout_s=30.0)
            assert pooled == inproc


def test_fan_out_resolves_every_query_and_zeroes_gauges():
    enc, lo, hi = _setup()
    with _pool(workers=2) as pool:
        futs = [submit_box(pool, _flip_net(), enc, lo, hi,
                           soft_timeout_s=10.0) for _ in range(8)]
        verdicts = [f.result(timeout=60.0).verdict for f in futs]
    assert verdicts == ["sat"] * 8
    reg = metrics_mod.registry()
    assert reg.gauge("smt_pool_queue_depth").value() == 0
    assert reg.gauge("smt_pool_active").value() == 0


# ---------------------------------------------------------------------------
# Containment: crash / hang / memout / spawn
# ---------------------------------------------------------------------------


def test_real_sigkill_mid_query_is_retried_and_still_decides():
    """kill -9 of the live worker subprocess WHILE it solves: the pool
    classifies the death transient, respawns, and the query still comes
    back decided — the acceptance criterion's literal scenario."""
    # A box big enough that the brute enumeration takes a while.
    enc, lo, hi = _setup({"a": (0, 30), "b": (0, 30), "pa": (0, 1)})
    with _pool(workers=1, max_retries=2) as pool:
        fut = submit_box(pool, _const_net(), enc, lo, hi,
                         soft_timeout_s=120.0)
        deadline = time.monotonic() + 10.0
        while not pool.live_workers() and time.monotonic() < deadline:
            time.sleep(0.01)
        procs = pool.live_workers()
        assert procs, "worker never spawned"
        time.sleep(0.2)  # let the solve actually start
        os.kill(procs[0].pid, signal.SIGKILL)
        res = fut.result(timeout=120.0)
    assert res.verdict == "unsat"
    assert res.attempts >= 2  # the kill cost one attempt
    assert metrics_mod.registry().counter("smt_worker_crashes").value(
        kind="crash") >= 1


def test_hang_is_killed_within_grace_of_deadline():
    """A wedged solver (chaos hang: ignores its soft timeout entirely) is
    SIGKILLed within grace of each tier deadline — the query is provably
    wall-clock bounded however pathological."""
    enc, lo, hi = _setup()
    soft, grace, retries = 0.3, 0.4, 1
    with _pool(workers=1, grace_s=grace, max_retries=retries) as pool:
        with faults.armed(["smt.worker.hang:transient:1+"]):
            t0 = time.monotonic()
            v, ce, reason = solve_box(pool, _flip_net(), enc, lo, hi,
                                      soft_timeout_s=soft)
            elapsed = time.monotonic() - t0
    assert (v, ce, reason) == ("unknown", None, protocol.REASON_HANG)
    # (retries + 1) attempts, each bounded by soft + grace, plus respawn
    # and backoff slack — far below a single wedged z3 call.
    assert elapsed < (retries + 1) * (soft + grace) + 5.0


def test_portfolio_returns_on_first_decisive_answer():
    """The winner's answer comes back IMMEDIATELY — a losing variant
    wedged past its deadline must not hold the caller hostage (the
    'losers are simply abandoned' rule, pinned with a stopwatch)."""
    enc, lo, hi = _setup()
    soft = 2.0
    with _pool(workers=2, portfolio=2, grace_s=1.0, max_retries=2) as pool:
        # Exactly ONE dispatch arrival hangs: one variant wedges (worth
        # ~3 attempts x 3 s to exhaust), the other solves in millis.
        with faults.armed(["smt.worker.hang:transient:1"]):
            t0 = time.monotonic()
            v, _, reason = solve_box(pool, _flip_net(), enc, lo, hi,
                                     soft_timeout_s=soft)
            elapsed = time.monotonic() - t0
    assert (v, reason) == ("sat", None)
    assert elapsed < soft + 1.0  # decisively below the loser's ladder


def test_crash_transient_absorbed_fatal_degrades():
    enc, lo, hi = _setup()
    with _pool(workers=1, max_retries=2) as pool:
        with faults.armed(["smt.worker.crash:transient:1"]):
            v, _, reason = solve_box(pool, _flip_net(), enc, lo, hi,
                                     soft_timeout_s=10.0)
        assert (v, reason) == ("sat", None)  # one retry absorbed it
        with faults.armed(["smt.worker.crash:fatal:1"]):
            v, _, reason = solve_box(pool, _flip_net(), enc, lo, hi,
                                     soft_timeout_s=10.0)
        assert (v, reason) == ("unknown", protocol.REASON_CRASH)
        with faults.armed(["smt.worker.crash:transient:1+"]):
            v, _, reason = solve_box(pool, _flip_net(), enc, lo, hi,
                                     soft_timeout_s=10.0)
        assert (v, reason) == ("unknown", protocol.REASON_CRASH)


def test_memout_retries_once_on_doubled_cap_then_degrades():
    enc, lo, hi = _setup()
    with _pool(workers=1, memory_cap_mb=192) as pool:
        # One injected memout: the doubled-cap retry decides the query.
        with faults.armed(["smt.worker.memout:transient:1"]):
            v, _, reason = solve_box(pool, _flip_net(), enc, lo, hi,
                                     soft_timeout_s=10.0)
        assert (v, reason) == ("sat", None)
        # Every dispatch memouts: one higher-cap retry, then degrade —
        # NEVER a bigger time budget (the ladder is skipped).
        with faults.armed(["smt.worker.memout:transient:1+"]):
            v, _, reason = solve_box(pool, _flip_net(), enc, lo, hi,
                                     soft_timeout_s=10.0,
                                     retry_timeouts_s=(10.0, 10.0))
        assert (v, reason) == ("unknown", protocol.REASON_MEMOUT)
    assert metrics_mod.registry().counter("smt_memouts").total() >= 2


def test_spawn_fault_degrades_query_not_run():
    enc, lo, hi = _setup()
    with _pool(workers=1) as pool:
        with faults.armed(["smt.worker.spawn:fatal:1"]):
            v, _, reason = solve_box(pool, _flip_net(), enc, lo, hi,
                                     soft_timeout_s=10.0)
        assert (v, reason) == ("unknown", protocol.REASON_SPAWN)
        # The pool recovers: the next query spawns a healthy worker.
        v, _, reason = solve_box(pool, _flip_net(), enc, lo, hi,
                                 soft_timeout_s=10.0)
        assert (v, reason) == ("sat", None)


def test_crash_kind_fault_propagates():
    """kind=crash keeps its global meaning: never handled, not even by
    the pool — it models the HOST dying, not a worker."""
    from fairify_tpu.resilience.faults import InjectedFault

    enc, lo, hi = _setup()
    with _pool(workers=1) as pool:
        with faults.armed(["smt.worker.crash:crash:1"]):
            with pytest.raises(InjectedFault):
                pool._dispatch(smt_mod.build_query(_flip_net(), enc, lo, hi),
                               5.0, seed=0)


# ---------------------------------------------------------------------------
# Sweep integration (the tier + deferred drain)
# ---------------------------------------------------------------------------


def _toy_cfg(tmp_path, name, **kw):
    """GC preset shrunk to a tiny 18-partition grid of brute-solvable
    boxes (8-16 integer pairs each), so the pool's workers return REAL
    verdicts in milliseconds."""
    from fairify_tpu.data.domains import get_domain
    from fairify_tpu.verify import presets

    ov = {c: (0, 0) for c in get_domain("german").columns}
    ov["age"] = (0, 1)            # the PA
    ov["month"] = (0, 5)          # partitioned (threshold 2 → 3 spans)
    ov["purpose"] = (0, 5)        # partitioned
    ov["credit_amount"] = (0, 2)  # rides along whole
    from fairify_tpu.verify.engine import EngineConfig

    kw.setdefault("smt_retry_timeouts_s", (10.0,))
    kw.setdefault("engine", EngineConfig(pgd_phase=False))
    return presets.get("GC").with_(
        result_dir=str(tmp_path / name), soft_timeout_s=10.0,
        hard_timeout_s=600.0, sim_size=16, exact_certify_masks=False,
        grid_chunk=8, launch_backoff_s=1e-4,
        domain_overrides=ov, partition_threshold=2,
        smt_workers=2, **kw)


def _unknown_engine(monkeypatch):
    """Stage 0 and BaB decide NOTHING: every partition deterministically
    reaches the SMT tier (the real stage 0 certifies tiny boxes outright,
    which would leave the tier vacuously untested)."""
    from fairify_tpu.verify import engine as engine_mod
    from fairify_tpu.verify import sweep as sweep_mod

    def dull_decode(host, ctx):
        n = ctx["n"]
        return np.zeros(n, bool), np.zeros(n, bool), {}

    monkeypatch.setattr(sweep_mod, "_stage0_block_decode", dull_decode)
    monkeypatch.setattr(
        engine_mod, "decide_many",
        lambda net, enc, rlo, rhi, cfg, **kw: [
            engine_mod.Decision("unknown", reason="deadline")
            for _ in range(rlo.shape[0])])
    monkeypatch.setattr(engine_mod, "decide_box",
                        lambda *a, **k: engine_mod.Decision("unknown"))
    return sweep_mod


SPAN = (0, 12)


def _vmap(rep):
    return {o.partition_id: o.verdict for o in rep.outcomes}


def test_sweep_smt_tier_decides_what_engine_would(tmp_path, monkeypatch):
    """Healthy-engine sweep vs crippled-engine sweep whose UNKNOWNs the
    pool decides: bit-equal verdict maps (the §14 parity contract at the
    sweep level, real worker subprocesses underneath)."""
    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.verify import sweep as sweep_mod

    cfg_h = _toy_cfg(tmp_path, "healthy", smt_retry_timeouts_s=())
    net = init_mlp((len(cfg_h.query().columns), 4, 1), seed=3)
    healthy = sweep_mod.verify_model(net, cfg_h, model_name="m",
                                     resume=False, partition_span=SPAN)
    assert set(_vmap(healthy).values()) <= {"sat", "unsat"}

    sweep_mod = _unknown_engine(monkeypatch)
    # GC partitions are big boxes: give the brute backend enough headroom
    # via a per-test pool config (pair cap covers the partition size).
    pooled = sweep_mod.verify_model(
        net, _toy_cfg(tmp_path, "pooled"), model_name="m",
        resume=False, partition_span=SPAN)
    got = _vmap(pooled)
    want = _vmap(healthy)
    decided = {k: v for k, v in got.items() if v != "unknown"}
    assert decided  # the tier actually decided partitions
    assert metrics_mod.registry().counter("smt_queries").total() > 0
    assert all(want[k] == v for k, v in decided.items())


def test_sweep_deferred_drain_converges(tmp_path, monkeypatch):
    """smt_defer mode: the report comes back with provisional UNKNOWNs +
    an SmtDrain; draining patches outcomes AND the ledger so a resume
    sees the decided verdicts (the serve worker's non-blocking phase)."""
    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.smt.pool import PoolConfig as PC
    from fairify_tpu.smt.pool import SmtPool as SP

    sweep_mod = _unknown_engine(monkeypatch)
    cfg = _toy_cfg(tmp_path, "defer")
    net = init_mlp((len(cfg.query().columns), 4, 1), seed=3)
    with SP(PC(workers=2, backend="brute")) as pool:
        rep = sweep_mod.verify_model(
            net, cfg, model_name="m", resume=False, partition_span=SPAN,
            smt_pool=pool, smt_defer=True)
        blocking = sweep_mod.verify_model(
            net, _toy_cfg(tmp_path, "block"), model_name="m",
            resume=False, partition_span=SPAN)
        if rep.smt_pending is not None:
            stats = rep.smt_pending.drain()
            assert stats["decided"] >= 0
        assert _vmap(rep) == _vmap(blocking)
        # The drained ledger is the record of truth: a resume pass replays
        # every decided verdict without re-solving.
        resumed = sweep_mod.verify_model(
            net, cfg, model_name="m", resume=True, partition_span=SPAN)
    assert _vmap(resumed) == _vmap(blocking)


def test_serve_nonblocking_smt_phase_completes_requests(
        tmp_path, monkeypatch):
    """Two SMT-enabled requests through the persistent server: the
    server-wide pool solves them, the deferred drain finishes both off
    the worker thread, and each request's final map matches a solo run
    (the §14 serve contract end to end, inside tier-1)."""
    from fairify_tpu.serve import ServeConfig, VerificationServer

    sweep_mod = _unknown_engine(monkeypatch)
    cfg_a = _toy_cfg(tmp_path, "sa")
    cfg_b = _toy_cfg(tmp_path, "sb")
    net = __import__("fairify_tpu.models.train",
                     fromlist=["init_mlp"]).init_mlp((20, 4, 1), seed=3)
    solo = sweep_mod.verify_model(
        net, _toy_cfg(tmp_path, "solo"), model_name="solo", resume=False,
        partition_span=SPAN)
    want = _vmap(solo)
    srv = VerificationServer(ServeConfig(batch_window_s=0.05,
                                         smt_workers=2)).start()
    try:
        ra = srv.submit(cfg_a, net, "ma", partition_span=SPAN)
        rb = srv.submit(cfg_b, net, "mb", partition_span=SPAN)
        fa = srv.wait(ra.id, timeout=300.0)
        fb = srv.wait(rb.id, timeout=300.0)
        assert fa.status == fb.status == "done"
        assert _vmap(fa.report) == want
        assert _vmap(fb.report) == want
        assert fa.report.smt_pending is None  # drained, not dangling
    finally:
        srv.drain()
    assert metrics_mod.registry().counter("smt_queries").total() > 0


def test_heartbeat_renders_smt_pool_line():
    import io

    from fairify_tpu.obs.heartbeat import Heartbeat

    out = io.StringIO()
    hb = Heartbeat(1000.0, total=4, label="X", stream=out)
    hb.beat(decided=1, attempted=1, force=True)
    assert "smt:" not in out.getvalue()  # no pool: zero-noise
    reg = metrics_mod.registry()
    reg.gauge("smt_pool_workers").set(3)
    reg.gauge("smt_pool_active").set(2)
    reg.gauge("smt_pool_queue_depth").set(5)
    hb.beat(decided=2, attempted=2, force=True)
    assert "| smt: 2/5 workers=3" in out.getvalue()
    hb.close()


def test_report_renders_smt_outcome_table(tmp_path, capsys):
    from fairify_tpu.obs import report as report_mod

    path = str(tmp_path / "ev.jsonl")
    metrics = {"smt_queries": {"kind": "counter", "series": [
        {"labels": {"verdict": "sat"}, "value": 3},
        {"labels": {"verdict": "unsat"}, "value": 4},
        {"labels": {"verdict": "unknown", "reason": "timeout"}, "value": 2},
        {"labels": {"verdict": "unknown", "reason": "memout"}, "value": 1},
        {"labels": {"verdict": "unknown",
                    "reason": "smt.worker:crash"}, "value": 1},
    ]}}
    import json as _json

    with open(path, "w") as fp:
        fp.write(_json.dumps({"type": "metrics", "metrics": metrics}) + "\n")
    agg = report_mod.aggregate([path])
    assert agg["smt"] == {"decided": 7, "timeout": 2, "memout": 1,
                          "smt.worker:crash": 1}
    assert report_mod.main([path]) == 0
    text = capsys.readouterr().out
    assert "smt outcome" in text and "smt.worker:crash" in text

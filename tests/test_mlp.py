"""MLP representation: forward parity, masking ≡ excision, h5 ingest."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fairify_tpu.models import mlp as M


def random_mlp(rng, sizes):
    ws, bs = [], []
    for i in range(len(sizes) - 1):
        ws.append(rng.normal(size=(sizes[i], sizes[i + 1])).astype(np.float32))
        bs.append(rng.normal(size=(sizes[i + 1],)).astype(np.float32))
    return M.from_numpy(ws, bs)


def numpy_forward(ws, bs, x):
    h = np.asarray(x, dtype=np.float32)
    for i, (w, b) in enumerate(zip(ws, bs)):
        z = h @ w + b
        h = z if i == len(ws) - 1 else np.maximum(z, 0.0)
    return h[..., 0]


def test_forward_matches_numpy():
    rng = np.random.default_rng(1)
    params = random_mlp(rng, [7, 11, 5, 1])
    x = rng.normal(size=(13, 7)).astype(np.float32)
    got = np.asarray(M.forward(params, jnp.asarray(x)))
    want = numpy_forward([np.asarray(w) for w in params.weights],
                         [np.asarray(b) for b in params.biases], x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_mask_equals_excision():
    rng = np.random.default_rng(2)
    params = random_mlp(rng, [6, 10, 8, 1])
    masks = [
        jnp.asarray((rng.uniform(size=10) > 0.3).astype(np.float32)),
        jnp.asarray((rng.uniform(size=8) > 0.3).astype(np.float32)),
        jnp.ones((1,), jnp.float32),
    ]
    masked = params.with_masks(masks)
    dense = M.excise(masked)
    x = rng.normal(size=(17, 6)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(M.forward(masked, jnp.asarray(x))),
        np.asarray(M.forward(dense, jnp.asarray(x))),
        rtol=1e-5, atol=1e-5,
    )


def test_layer_outputs_shapes():
    rng = np.random.default_rng(3)
    params = random_mlp(rng, [4, 9, 3, 1])
    outs = M.layer_outputs(params, jnp.ones((4,)))
    assert [o.shape for o in outs] == [(9,), (3,), (1,)]


def test_predict_is_sign_test():
    rng = np.random.default_rng(4)
    params = random_mlp(rng, [5, 6, 1])
    x = rng.normal(size=(50, 5)).astype(np.float32)
    logits = M.forward(params, jnp.asarray(x))
    np.testing.assert_array_equal(
        np.asarray(M.predict(params, jnp.asarray(x))), np.asarray(logits) > 0.0
    )


@pytest.mark.usefixtures("reference_assets_available")
def test_ingest_gc1(reference_assets_available):
    if not reference_assets_available:
        pytest.skip("reference assets unavailable")
    from fairify_tpu.models import zoo

    params = zoo.load("german", "GC-1")
    assert params.in_dim == 20
    assert params.layer_sizes == (50, 1)
    # logit should be finite on an arbitrary integer input
    x = jnp.zeros((20,))
    assert np.isfinite(float(M.forward(params, x)))


@pytest.mark.usefixtures("reference_assets_available")
def test_ingest_matches_tf_forward(reference_assets_available):
    if not reference_assets_available:
        pytest.skip("reference assets unavailable")
    tf = pytest.importorskip("tensorflow")
    from fairify_tpu.models import zoo

    # Keras 3 cannot load the legacy h5 files directly; rebuild the same
    # architecture and install the ingested weights, then compare outputs.
    params = zoo.load("german", "GC-1")
    keras_model = tf.keras.Sequential([
        tf.keras.layers.Input(shape=(20,)),
        tf.keras.layers.Dense(50, activation="relu"),
        tf.keras.layers.Dense(1, activation="sigmoid"),
    ])
    keras_model.set_weights(
        [np.asarray(a) for pair in zip(params.weights, params.biases) for a in pair]
    )
    rng = np.random.default_rng(5)
    x = rng.integers(0, 3, size=(8, 20)).astype(np.float32)
    keras_logit_sigmoid = keras_model.predict(x, verbose=0)[:, 0]
    ours = np.asarray(M.forward(params, jnp.asarray(x)))
    ours_sigmoid = 1.0 / (1.0 + np.exp(-ours))
    np.testing.assert_allclose(ours_sigmoid, keras_logit_sigmoid, rtol=1e-4, atol=1e-5)

"""Compile observability: obs_jit registry semantics (cache hit vs recompile
under shape/static churn, analysis fallback, nested-trace fallback,
span/metrics agreement), the ragged-chunk pad (pinned compile counts +
verdict invariance), and the heartbeat compile flag."""
import io
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fairify_tpu import obs
from fairify_tpu.obs import compile as compile_mod
from fairify_tpu.obs import heartbeat as hb_mod
from fairify_tpu.obs import metrics as metrics_mod
from fairify_tpu.obs import report as report_mod
from fairify_tpu.obs import trace as trace_mod


@pytest.fixture(autouse=True)
def _clean_obs_state():
    trace_mod.deactivate()
    metrics_mod.registry().reset()
    hb_mod._ACTIVE = None
    yield
    trace_mod.deactivate()
    metrics_mod.registry().reset()
    hb_mod._ACTIVE = None


def _fresh_kernel(name, static=()):
    """A uniquely-named obs_jit kernel (executable caches are process-wide,
    so shared shapes across tests would hide compiles)."""

    def fn(x, k=2):
        for _ in range(int(k) if not isinstance(k, jnp.ndarray) else 2):
            x = jnp.tanh(x @ jnp.eye(x.shape[-1], dtype=x.dtype))
        return x

    return compile_mod.obs_jit(fn, name=name, static_argnames=static)


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------


def test_registration_and_default_name():
    k = _fresh_kernel("t.reg_default")
    assert compile_mod.kernels()["t.reg_default"] is k
    # Default naming strips the underscore and qualifies by module basename.

    @compile_mod.obs_jit
    def _my_probe_kernel(x):
        return x + 1

    assert "test_compile_obs.my_probe_kernel" in compile_mod.kernels()
    assert np.asarray(_my_probe_kernel(np.float32(1.0))) == 2.0


def test_cache_hit_vs_shape_and_static_recompile():
    k = _fresh_kernel("t.churn", static=("k",))
    c = obs.registry().counter("xla_compiles")
    x = np.ones((7, 5), np.float32)
    y1 = k(x, k=2)
    assert c.value(kernel="t.churn") == 1
    y2 = k(x, k=2)  # identical signature: cache hit, no recompile
    assert c.value(kernel="t.churn") == 1
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    k(x, k=3)  # static-arg churn
    assert c.value(kernel="t.churn") == 2
    k(np.ones((9, 5), np.float32), k=2)  # shape churn
    assert c.value(kernel="t.churn") == 3
    assert k.stats.n_compiles == 3
    assert len(k.stats.signatures) == 3
    assert obs.registry().gauge(
        "xla_kernel_signatures").value(kernel="t.churn") == 3
    # Dtype-churn is a distinct signature too (a retrace in jax terms) —
    # while f64 input canonicalizes to the f32 signature under x64-off,
    # exactly as jax's own dispatch cache would treat it.
    k(np.ones((7, 5), np.int32), k=2)
    assert c.value(kernel="t.churn") == 4
    k(np.ones((7, 5), np.float64), k=2)  # canonicalizes to f32: cache hit
    assert c.value(kernel="t.churn") == 4


def test_positional_static_args_and_results_match_plain_jit():
    def fn(x, n):
        return x * n

    k = compile_mod.obs_jit(fn, name="t.pos_static", static_argnames=("n",))
    x = np.arange(6, dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(k(x, 3)), x * 3)  # positional
    np.testing.assert_array_equal(np.asarray(k(x, n=3)), x * 3)  # keyword
    # Positional and keyword static spellings share one static key: the
    # second call's (pos vs kw) must not have recompiled a third time.
    assert k.stats.n_compiles == 1


def test_first_compile_records_cost_and_memory_analysis():
    k = _fresh_kernel("t.analysis")
    k(np.ones((16, 8), np.float32))
    st = k.stats
    # CPU backend supports both analyses in this jax version; the contract
    # is "recorded when available".
    assert st.n_compiles == 1
    if st.flops is not None:
        assert st.flops > 0
        assert obs.registry().gauge(
            "xla_kernel_flops").value(kernel="t.analysis") == st.flops
    if st.temp_bytes is not None:
        assert obs.registry().gauge(
            "xla_kernel_temp_bytes").value(kernel="t.analysis") == st.temp_bytes


def test_graceful_fallback_when_analyses_unavailable(monkeypatch):
    """Backends without cost/memory analysis must not break compilation."""
    import jax._src.stages as stages

    def boom(self):
        raise NotImplementedError("no analysis on this backend")

    monkeypatch.setattr(stages.Compiled, "cost_analysis", boom)
    monkeypatch.setattr(stages.Compiled, "memory_analysis", boom)
    k = _fresh_kernel("t.no_analysis")
    out = k(np.ones((4, 3), np.float32))
    assert np.asarray(out).shape == (4, 3)
    assert k.stats.n_compiles == 1
    assert k.stats.flops is None and k.stats.temp_bytes is None
    assert k.stats.fallbacks == 0  # analysis absence is not a call fallback


def test_aot_failure_falls_back_to_plain_jit(monkeypatch):
    k = _fresh_kernel("t.aot_fail")

    class _NoLower:
        def __init__(self, jitted):
            self._jitted = jitted

        def __call__(self, *a, **kw):
            return self._jitted(*a, **kw)

        def lower(self, *a, **kw):
            raise RuntimeError("AOT path unavailable")

    monkeypatch.setattr(k, "_jitted", _NoLower(k._jitted))
    out = k(np.ones((3, 3), np.float32))
    assert np.asarray(out).shape == (3, 3)
    assert k.stats.n_compiles == 0
    assert k.stats.fallbacks >= 1
    assert obs.registry().counter(
        "xla_compile_fallbacks").value(kernel="t.aot_fail") >= 1
    # Subsequent calls keep working through the fallback sentinel.
    assert np.asarray(k(np.ones((3, 3), np.float32))).shape == (3, 3)


def test_nested_trace_calls_do_not_count_as_compiles():
    inner = _fresh_kernel("t.nested_inner")

    @jax.jit
    def outer(x):
        return inner(x) + 1.0

    out = outer(jnp.ones((4, 4)))
    assert np.asarray(out).shape == (4, 4)
    # The outer jit owns the (untracked) compile; the inner kernel saw only
    # tracers and must not have taken the AOT path.
    assert inner.stats.n_compiles == 0


def test_compile_span_and_metrics_agree(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with trace_mod.tracing(path):
        k = _fresh_kernel("t.span_agree", static=("k",))
        k(np.ones((5, 5), np.float32), k=2)
        k(np.ones((5, 5), np.float32), k=2)  # hit — no span
        k(np.ones((5, 5), np.float32), k=3)  # recompile — second span
    events = trace_mod.load_events(path)
    spans = [e for e in events if e["type"] == "span"
             and e["name"] == "compile.t.span_agree"]
    assert len(spans) == 2
    for sp in spans:
        assert sp["attrs"]["kernel"] == "t.span_agree"
        assert "float32[5,5]" in sp["attrs"]["signature"]
        assert sp["attrs"]["static"] in ("k=2", "k=3")
        assert sp["attrs"]["compile_s"] >= 0
        assert sp["dur_s"] >= sp["attrs"]["compile_s"]
    # The closing metrics snapshot carries the same count.
    metrics = next(e for e in reversed(events) if e["type"] == "metrics")
    series = metrics["metrics"]["xla_compiles"]["series"]
    mine = [s for s in series
            if dict(s["labels"]).get("kernel") == "t.span_agree"]
    assert mine and mine[0]["value"] == 2
    # report builds the per-kernel table from the same log.
    agg = report_mod.aggregate([path])
    row = agg["compiles"]["t.span_agree"]
    assert row["count"] == 2 and row["signatures"] == 2
    assert "t.span_agree" in report_mod.render(agg)


def test_totals_delta_per_run_view():
    before = compile_mod.snapshot_totals()
    k = _fresh_kernel("t.totals")
    k(np.ones((6, 2), np.float32))
    delta = compile_mod.totals_delta(before)
    assert delta["n_compiles"] == 1
    assert delta["compile_s"] > 0
    # peak_temp_bytes is attributed to kernels compiled WITHIN the window.
    if k.stats.temp_bytes:
        assert delta["peak_temp_bytes"] == k.stats.temp_bytes
    # A warm window (no compiles) attributes nothing — an earlier run's
    # big executables never leak into a later run's record.
    warm0 = compile_mod.snapshot_totals()
    k(np.ones((6, 2), np.float32))  # cache hit
    warm = compile_mod.totals_delta(warm0)
    assert warm["n_compiles"] == 0
    assert warm["peak_temp_bytes"] == 0


# ---------------------------------------------------------------------------
# Ragged-chunk pad: pinned compile counts + verdict invariance
# ---------------------------------------------------------------------------


@pytest.fixture()
def tiny_domain(monkeypatch):
    from fairify_tpu.data import domains as dom_mod
    from fairify_tpu.data.domains import DomainSpec

    dom = DomainSpec(name="tinycmp", label="y",
                     ranges={"a": (0, 34), "pa": (0, 1), "b": (0, 3)})
    monkeypatch.setitem(dom_mod.DOMAINS, "tinycmp", dom)
    return dom


def _tiny_cfg(tmp_path, **kw):
    from fairify_tpu.verify import engine
    from fairify_tpu.verify.config import SweepConfig

    return SweepConfig(
        name="tinycmp", dataset="tinycmp", protected=("pa",),
        partition_threshold=5, sim_size=48, soft_timeout_s=30.0,
        hard_timeout_s=600.0, result_dir=str(tmp_path),
        engine=engine.EngineConfig(frontier_size=64, attack_samples=23,
                                   bab_attack_samples=8, soft_timeout_s=30.0),
        **kw)


def test_ragged_chunk_single_compile_and_verdict_invariance(
        tmp_path, tiny_domain):
    """A grid whose last chunk is ragged (7 partitions, chunk 4) must pad
    up to the chunk bucket inside the submit helpers: ONE stage-0 compile
    per kernel for the whole sweep, and verdicts equal to the unchunked
    run's."""
    from fairify_tpu.verify import sweep
    from fairify_tpu.verify.oracle import random_net

    net = random_net(np.random.default_rng(11), (3, 5, 1))
    # mega_chunks=0 pins the per-chunk loop's ragged-pad contract; the
    # mega path's twin (scan kernels, same pad inside the segment stack)
    # is asserted below.
    cfg = _tiny_cfg(tmp_path / "ragged", grid_chunk=4, mega_chunks=0)
    c = obs.registry().counter("xla_compiles")
    ragged = sweep.verify_model(net, cfg, model_name="m", resume=False)
    # 7 partitions / chunk 4 → spans of 4,3: the ragged last block must
    # reuse the 4-row executables, pinning ONE compile per stage-0 kernel
    # (certify+attack fused, sim+bounds, parity).
    assert ragged.partitions_total == 7
    for kern in ("engine.certify_attack", "pruning.sim_and_bounds",
                 "sweep.parity_grid_from_keys"):
        assert c.value(kernel=kern) == 1, kern
    thr = json.load(open(tmp_path / "ragged" / "tinycmp-m.throughput.json"))
    assert thr["n_compiles"] == int(sum(
        s["value"] for s in c.snapshot()))
    assert thr["compile_s"] > 0

    # Mega-loop ragged twin: both chunks (one ragged, padded) ride ONE
    # scan launch per phase and each mega kernel compiles exactly once.
    mega = sweep.verify_model(
        net, _tiny_cfg(tmp_path / "mega", grid_chunk=4),
        model_name="m", resume=False)
    for kern in ("sweep.mega_stage0_kernel", "pruning.mega_sim_and_bounds",
                 "sweep.mega_parity_kernel"):
        assert c.value(kernel=kern) == 1, kern
    assert [o.verdict for o in mega.outcomes] == \
        [o.verdict for o in ragged.outcomes]

    whole = sweep.verify_model(
        net, _tiny_cfg(tmp_path / "whole", grid_chunk=0),
        model_name="m", resume=False)
    assert whole.counts["unknown"] == 0  # strict comparison is meaningful
    assert ragged.counts == whole.counts
    assert [o.verdict for o in ragged.outcomes] == \
        [o.verdict for o in whole.outcomes]


def test_family_ragged_chunk_single_compile(tmp_path, tiny_domain):
    """Stacked-family stage 0 with a ragged final chunk: one compile for
    the family kernel, per-model results identical to the unchunked pass."""
    from fairify_tpu.parallel.mesh import stack_models
    from fairify_tpu.verify import sweep
    from fairify_tpu.verify.oracle import random_net
    from fairify_tpu.verify.property import encode

    nets = [random_net(np.random.default_rng(s), (3, 5, 1)) for s in (1, 2)]
    stacked = stack_models(nets)
    cfg = _tiny_cfg(tmp_path, grid_chunk=4)
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)
    assert lo.shape[0] % 4 != 0  # the point: a ragged last chunk
    c = obs.registry().counter("xla_compiles")
    chunked = sweep._stage0_family(stacked, enc, lo, hi,
                                   cfg.with_(mega_chunks=0))
    assert c.value(kernel="sweep.family_stage0_kernel") == 1
    # Mega twin: the ragged chunk pads inside the segment stack and the
    # whole family×segment pass is one compiled scan kernel.
    mega = sweep._stage0_family(stacked, enc, lo, hi, cfg)
    assert c.value(kernel="sweep.mega_family_stage0_kernel") == 1
    whole = sweep._stage0_family(stacked, enc, lo, hi,
                                 cfg.with_(grid_chunk=0))
    for (cu, cs, cw), (mu, ms, mw), (wu, ws, ww) in zip(chunked, mega, whole):
        np.testing.assert_array_equal(cu, wu)
        np.testing.assert_array_equal(cs, ws)
        assert set(cw) == set(ww)
        np.testing.assert_array_equal(mu, cu)
        np.testing.assert_array_equal(ms, cs)
        assert set(mw) == set(cw)


# ---------------------------------------------------------------------------
# Heartbeat compile flag
# ---------------------------------------------------------------------------


def test_heartbeat_flags_compiles():
    out = io.StringIO()
    hb = obs.Heartbeat(10.0, total=10, label="m", stream=out)
    assert hb_mod.active() is hb
    hb_mod.notify_compile("engine.certify_attack")
    assert "[hb m] compiling engine.certify_attack…" in out.getvalue()
    hb.close()
    assert hb_mod.active() is None
    hb_mod.notify_compile("engine.certify_attack")  # no active hb: no-op
    assert out.getvalue().count("compiling") == 1


def test_heartbeat_compile_flag_fires_during_real_compile():
    out = io.StringIO()
    hb = obs.Heartbeat(10.0, stream=out)
    k = _fresh_kernel("t.hb_compile")
    k(np.ones((2, 2), np.float32))
    hb.close()
    assert "compiling t.hb_compile…" in out.getvalue()


def test_disabled_heartbeat_does_not_register():
    hb = obs.Heartbeat(0.0)
    assert hb_mod.active() is None
    hb.close()


def test_heartbeat_closed_when_sweep_raises(monkeypatch, tmp_path,
                                            tiny_domain):
    """A sweep that crashes mid-run must not leak its heartbeat as the live
    one — later runs' compile flags would print against the dead label."""
    from fairify_tpu.verify import sweep

    def boom(*a, **kw):
        obs.Heartbeat(1.0, label="doomed", stream=io.StringIO())
        raise RuntimeError("mid-sweep crash")

    monkeypatch.setattr(sweep, "_verify_model_impl", boom)
    cfg = _tiny_cfg(tmp_path, heartbeat_s=1.0)
    with pytest.raises(RuntimeError, match="mid-sweep crash"):
        sweep.verify_model(object(), cfg, model_name="m")
    assert hb_mod.active() is None


def test_compile_flag_survives_closed_stream():
    """A stale registration over a closed stream must never fail the kernel
    call that triggered the flag; it deregisters itself instead."""
    class _Closed:
        def write(self, *a):
            raise ValueError("I/O operation on closed file")

        def flush(self):
            raise ValueError("I/O operation on closed file")

    hb = obs.Heartbeat(1.0, stream=_Closed())
    assert hb_mod.active() is hb
    hb_mod.notify_compile("engine.certify_attack")  # must not raise
    assert hb_mod.active() is None


def test_nested_trace_fallbacks_are_counted_distinctly():
    """The plain-jit inline path a nested trace takes must not be silent:
    it registers no signatures, so it is counted per kernel under the
    xla_compile_fallbacks metric's kind="trace" series and in
    stats.trace_inlines (the ir-recompile pass reads exactly these)."""
    inner = _fresh_kernel("t.trace_inline")

    @jax.jit
    def outer(x):
        return inner(x) * 2.0

    outer(jnp.ones((3, 3)))
    assert inner.stats.n_compiles == 0
    assert inner.stats.trace_inlines >= 1
    assert obs.registry().counter("xla_compile_fallbacks").value(
        kernel="t.trace_inline", kind="trace") >= 1
    d = inner.stats.as_dict()
    assert d["trace_inlines"] >= 1 and d["n_fallback_signatures"] == 0


def test_aot_fallback_registers_signature(monkeypatch):
    """An AOT-failure fallback still records WHICH signature it served —
    a kernel that only ever falls back stays attributable."""
    k = _fresh_kernel("t.aot_sig")

    class _NoLower:
        def __init__(self, jitted):
            self._jitted = jitted

        def __call__(self, *a, **kw):
            return self._jitted(*a, **kw)

        def lower(self, *a, **kw):
            raise RuntimeError("AOT path unavailable")

        def trace(self, *a, **kw):
            raise RuntimeError("AOT path unavailable")

    monkeypatch.setattr(k, "_jitted", _NoLower(k._jitted))
    k(np.ones((3, 3), np.float32))
    assert k.stats.n_compiles == 0
    assert len(k.stats.fallback_signatures) == 1
    assert len(k.stats.signatures) == 0


def test_lowered_for_analysis_and_signature_key_have_no_side_effects():
    """The IR-analysis hooks reuse the AOT path without touching the
    executable cache, stats, or metrics."""
    k = _fresh_kernel("t.analysis_hook")
    x = np.ones((4, 4), np.float32)
    traced = k.lowered_for_analysis(x)
    assert traced.jaxpr is not None
    key1 = k.signature_key(x)
    key2 = k.signature_key(np.zeros((4, 4), np.float32))
    assert key1 == key2  # same aval, same executable
    assert key1 != k.signature_key(np.ones((5, 4), np.float32))
    assert k.stats.n_compiles == 0 and k.stats.fallbacks == 0
    assert not k._execs
    assert obs.registry().counter("xla_compiles").value(
        kernel="t.analysis_hook") == 0

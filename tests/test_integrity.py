"""Result-integrity layer: SDC injection, detection, containment (§21).

Pins the integrity contract (DESIGN.md §21) at both grains:

* unit — the deterministic corruption helpers, the host-side fold/canary
  detectors, the per-row ledger CRC, and the hash-keyed recheck sampler
  (``resilience/integrity.py``), plus the ``corrupt`` fault-kind plumbing
  in ``resilience/faults.py`` (own arrival stream, data-plane-only sites);
* end-to-end — a corrupted device fetch degrades (never decides) exactly
  its blast radius and a disarmed resume converges; a corrupted ledger
  row is dropped by CRC on resume and re-decided; a full-rate sampled
  recheck of a clean run is bit-quiet (no violations, same verdict map).

The chaos matrix (``scripts/chaos_matrix.py --integrity``) runs the same
scenarios at full span and across --serve/--procfleet topologies; these
tests are the fast always-on subset.
"""
import json
import os

import numpy as np
import pytest

from fairify_tpu import obs
from fairify_tpu.obs import metrics as metrics_mod
from fairify_tpu.obs import trace as trace_mod
from fairify_tpu.resilience import faults, integrity
from fairify_tpu.resilience.journal import JournalWriter
from fairify_tpu.models.train import init_mlp
from fairify_tpu.verify import presets, sweep


@pytest.fixture(autouse=True)
def _clean_state():
    """Quiescent registry, no tracer, no armed fault plan, per test."""
    trace_mod.deactivate()
    metrics_mod.registry().reset()
    faults.disarm()
    yield
    trace_mod.deactivate()
    metrics_mod.registry().reset()
    faults.disarm()


# ---------------------------------------------------------------------------
# corruption helpers: deterministic, dtype-aware bit flips
# ---------------------------------------------------------------------------


def test_flip_bit_is_deterministic_and_changes_exactly_one_element():
    for arr in (np.arange(12, dtype=np.int32),
                np.linspace(-1, 1, 7, dtype=np.float32),
                np.zeros(5, dtype=bool)):
        a1 = integrity.flip_bit(arr, 3)
        a2 = integrity.flip_bit(arr, 3)
        assert np.array_equal(a1, a2)          # same n -> same flip
        assert a1.dtype == arr.dtype and a1.shape == arr.shape
        diff = a1.reshape(-1) != arr.reshape(-1)
        assert int(diff.sum()) == 1
        assert not np.shares_memory(a1, arr)   # input is never mutated
    # different arrivals move the flip around
    arr = np.arange(8, dtype=np.int64)
    assert not np.array_equal(integrity.flip_bit(arr, 1),
                              integrity.flip_bit(arr, 2))


def test_flip_bit_float_flip_is_magnitude_scale():
    # Exponent-MSB flips are the classic SDC signature: the value must
    # change by orders of magnitude, not an absorbable ULP.
    arr = np.full(4, 1.5, dtype=np.float32)
    out = integrity.flip_bit(arr, 0)
    changed = out[out != arr]
    assert changed.size == 1
    v = float(changed[0])
    # exponent-MSB flip of 1.5 lands on NaN/inf or a value orders of
    # magnitude away — never an absorbable ULP
    assert (not np.isfinite(v)) or not (1e-3 < abs(v) / 1.5 < 1e3)


def test_flip_bit_empty_array_is_noop():
    arr = np.empty((0,), dtype=np.float32)
    assert integrity.flip_bit(arr, 5).size == 0


def test_corrupt_host_never_touches_the_checksum():
    payload = {"cert": np.ones((2, 4), dtype=bool),
               "wit": np.zeros((2, 4), dtype=np.float32),
               "csum": np.int32(123)}
    for n in range(6):
        out = integrity.corrupt_host(payload, n)
        assert int(out["csum"]) == 123
        assert any(not np.array_equal(out[k], payload[k])
                   for k in ("cert", "wit"))


def test_corrupt_record_inverts_decided_verdicts():
    assert integrity.corrupt_record({"verdict": "unsat"}, 1)["verdict"] == "sat"
    assert integrity.corrupt_record({"verdict": "sat"}, 1)["verdict"] == "unsat"
    out = integrity.corrupt_record({"verdict": "unknown",
                                    "partition_id": 7}, 1)
    assert out["partition_id"] != 7
    # stays valid JSON — a corrupt row, not a torn line
    json.dumps(out)


def test_corrupt_witness_flips_one_side_per_arrival():
    ce = (np.ones(4), np.ones(4))
    x0, xp0 = integrity.corrupt_witness(ce, 0)
    assert not np.array_equal(x0, ce[0]) and np.array_equal(xp0, ce[1])
    x1, xp1 = integrity.corrupt_witness(ce, 1)
    assert np.array_equal(x1, ce[0]) and not np.array_equal(xp1, ce[1])


# ---------------------------------------------------------------------------
# detectors: fold checksum + canary, ledger CRC, recheck sampler
# ---------------------------------------------------------------------------


def _segment_payload():
    """A synthetic mega-segment payload whose last row is a clean canary."""
    payload = {
        "cert": np.ones((3, 4), dtype=bool),
        "wit": np.zeros((3, 4), dtype=np.float32),
        "reason": np.ones((3, 4), dtype=np.int32),
        "stats": np.arange(12, dtype=np.int32).reshape(3, 4),
    }
    payload["csum"] = np.int32(integrity.fold_host(payload))
    return payload


def test_verify_segment_clean_payload_passes():
    assert integrity.verify_segment(_segment_payload()) is None


def test_verify_segment_checksum_catches_any_buffer_flip():
    for key in integrity.FOLD_KEYS:
        payload = _segment_payload()
        payload[key] = integrity.flip_bit(payload[key], 1)
        assert integrity.verify_segment(payload) == "checksum"


def test_verify_segment_canary_catches_consistent_corruption():
    # A stuck line that corrupts data AND fold identically slips past the
    # checksum; the known-answer canary row is the second net.
    payload = _segment_payload()
    payload["cert"][-1, 0] = False
    payload["csum"] = np.int32(integrity.fold_host(payload))
    assert integrity.verify_segment(payload) == "canary"


def test_fold_host_wraps_around_without_error():
    payload = {k: np.full((2, 2), 2**30, dtype=np.int32)
               for k in integrity.FOLD_KEYS}
    v = integrity.fold_host(payload)
    assert np.iinfo(np.int32).min <= v <= np.iinfo(np.int32).max


def test_record_crc_is_key_order_independent():
    a = {"partition_id": 3, "verdict": "unsat", "via": "stage0"}
    b = {"via": "stage0", "verdict": "unsat", "partition_id": 3}
    assert integrity.record_crc(a) == integrity.record_crc(b)


def test_verify_records_drops_corrupt_keeps_legacy_strips_crc():
    good = {"partition_id": 1, "verdict": "unsat"}
    sealed = dict(good, _crc=integrity.record_crc(good))
    corrupt = dict(integrity.corrupt_record(good, 1),
                   _crc=integrity.record_crc(good))
    legacy = {"partition_id": 2, "verdict": "sat"}  # pre-§21 ledger row
    trusted, bad = integrity.verify_records([sealed, corrupt, legacy])
    assert bad == 1
    assert trusted == [good, legacy]
    assert all("_crc" not in r for r in trusted)


def test_sampled_is_deterministic_and_rate_shaped():
    keys = [f"chunk:{i}" for i in range(2000)]
    picks = [integrity.sampled(11, k, 0.05) for k in keys]
    assert picks == [integrity.sampled(11, k, 0.05) for k in keys]
    share = sum(picks) / len(picks)
    assert 0.02 < share < 0.10                  # ~rate, hash-keyed
    assert not any(integrity.sampled(11, k, 0.0) for k in keys[:50])
    assert all(integrity.sampled(11, k, 1.0) for k in keys[:50])
    # a different seed selects a different subset
    assert picks != [integrity.sampled(12, k, 0.05) for k in keys]


# ---------------------------------------------------------------------------
# faults: the corrupt kind rides its own arrival stream
# ---------------------------------------------------------------------------


def test_parse_spec_corrupt_only_at_data_plane_sites():
    s = faults.parse_spec("launch.decode:corrupt:2")
    assert (s.site, s.kind, s.start) == ("launch.decode", "corrupt", 2)
    for site in sorted(faults.CORRUPT_SITES):
        faults.parse_spec(f"{site}:corrupt:1+")
    with pytest.raises(ValueError, match="data-plane"):
        faults.parse_spec("compile:corrupt:1")
    with pytest.raises(ValueError):
        faults.parse_spec("launch.submit:corrupt:1")


def test_corruption_schedule_one_shot_and_every():
    plan = faults.FaultPlan(["ledger.append:corrupt:2",
                             "smt.query:corrupt:1+"])
    hits = [plan.corruption("ledger.append") for _ in range(4)]
    assert hits == [None, 2, None, None]        # :N fires once, at N
    assert [plan.corruption("smt.query") for _ in range(3)] == [1, 2, 3]


def test_corrupt_specs_are_invisible_to_check():
    # Arming a corrupt spec must never shift (or fire on) the
    # control-plane arrival stream chaos schedules depend on.
    plan = faults.FaultPlan(["launch.decode:corrupt:1+",
                             "launch.decode:fatal:3"])
    plan.check("launch.decode")                  # arrivals 1, 2 clean
    plan.check("launch.decode")
    assert plan.corruption("launch.decode") == 1  # own stream starts at 1
    with pytest.raises(faults.InjectedFault) as ei:
        plan.check("launch.decode")              # control arrival 3
    assert ei.value.kind == "fatal"


def test_journal_crc_roundtrip_and_injected_row_corruption(tmp_path):
    path = str(tmp_path / "m.ledger.jsonl")
    with faults.armed(("ledger.append:corrupt:2",)):
        w = JournalWriter(path, fsync=False, crc=True)
        w.append({"partition_id": 1, "verdict": "unsat"})
        w.append({"partition_id": 2, "verdict": "unsat"})  # mutates post-CRC
        w.append({"partition_id": 3, "verdict": "sat"})
        w.close()
    rows = [json.loads(l) for l in open(path) if l.strip()]
    assert all("_crc" in r for r in rows)
    trusted, bad = integrity.verify_records(rows)
    assert bad == 1
    assert [r["partition_id"] for r in trusted] == [1, 3]
    # the corrupted row is on disk with an inverted verdict, valid JSON
    assert rows[1]["verdict"] == "sat"


# ---------------------------------------------------------------------------
# end-to-end: detect, contain, converge on resume
# ---------------------------------------------------------------------------

SPAN = (0, 16)


def _cfg(tmp_path, name, **kw):
    kw.setdefault("grid_chunk", 16)
    kw.setdefault("mega_chunks", 1)
    return presets.get("GC").with_(
        result_dir=str(tmp_path / name), soft_timeout_s=30.0,
        hard_timeout_s=600.0, sim_size=64, exact_certify_masks=False,
        launch_backoff_s=1e-4, **kw)


def _net():
    return init_mlp((20, 8, 1), seed=3)


def _vmap(report):
    return {o.partition_id: o.verdict for o in report.outcomes}


@pytest.fixture(scope="module")
def fault_free(tmp_path_factory):
    td = tmp_path_factory.mktemp("int_fault_free")
    cfg = presets.get("GC").with_(
        result_dir=str(td), soft_timeout_s=30.0, hard_timeout_s=600.0,
        sim_size=64, exact_certify_masks=False, grid_chunk=16)
    rep = sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                             partition_span=SPAN)
    return {o.partition_id: o.verdict for o in rep.outcomes}


def test_decode_corruption_detected_contained_and_resumed(tmp_path,
                                                          fault_free):
    viol = metrics_mod.registry().counter("integrity_violations")
    cfg = _cfg(tmp_path, "dec",
               inject_faults=("launch.decode:corrupt:1",))
    rep = sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                             partition_span=SPAN)
    assert viol.value(site="launch.decode") >= 1
    got = _vmap(rep)
    # soundness: nothing DECIDED may disagree with the fault-free map —
    # a corrupted fetch degrades, it never decides.
    assert all(fault_free[p] == v for p, v in got.items() if v != "unknown")
    assert rep.degraded >= 1, "corrupted segment must demote its partitions"
    path = os.path.join(
        cfg.result_dir, f"{cfg.name}-m@{SPAN[0]}-{SPAN[1]}.ledger.jsonl")
    reasons = {r["failure"]["reason"] for r in
               (json.loads(l) for l in open(path) if l.strip())
               if r.get("failure")}
    assert reasons and all(r.startswith("integrity.launch.decode")
                           for r in reasons)
    # disarmed resume: decided-wins keeps the good verdicts, re-runs the
    # demoted span, and converges bit-equal to fault-free.
    resumed = sweep.verify_model(_net(), cfg.with_(inject_faults=()),
                                 model_name="m", resume=True,
                                 partition_span=SPAN)
    assert _vmap(resumed) == fault_free
    assert resumed.degraded == 0


def test_ledger_row_corrupted_on_disk_is_dropped_and_redecided(
        tmp_path, fault_free):
    crc_ctr = metrics_mod.registry().counter("ledger_crc_mismatch")
    cfg = _cfg(tmp_path, "led")
    sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                       partition_span=SPAN)
    path = os.path.join(
        cfg.result_dir, f"{cfg.name}-m@{SPAN[0]}-{SPAN[1]}.ledger.jsonl")
    rows = [json.loads(l) for l in open(path) if l.strip()]
    victim = next(r for r in rows if r.get("verdict") in ("sat", "unsat"))
    flipped = dict(victim)
    flipped["verdict"] = "sat" if victim["verdict"] == "unsat" else "unsat"
    with open(path, "w") as fp:  # rot the row in place, CRC untouched
        for r in rows:
            fp.write(json.dumps(flipped if r is victim else r) + "\n")
    c0 = crc_ctr.total()
    resumed = sweep.verify_model(_net(), cfg, model_name="m", resume=True,
                                 partition_span=SPAN)
    assert crc_ctr.total() - c0 >= 1
    # the rotted pid was re-DECIDED, not replayed: final map is fault-free
    assert _vmap(resumed) == fault_free


def test_full_rate_recheck_is_bit_quiet_on_a_clean_run(tmp_path, fault_free):
    viol = metrics_mod.registry().counter("integrity_violations")
    rechecks = metrics_mod.registry().counter("integrity_rechecks")
    v0, r0 = viol.total(), rechecks.total()
    cfg = _cfg(tmp_path, "rck", integrity_recheck=1.0)
    rep = sweep.verify_model(_net(), cfg, model_name="m", resume=False,
                             partition_span=SPAN)
    assert rechecks.value(kind="chunk") >= 1
    assert rechecks.value(kind="exact") >= 1    # escalation ran too
    assert viol.total() - v0 == 0               # clean run: zero violations
    assert _vmap(rep) == fault_free
    assert rep.degraded == 0

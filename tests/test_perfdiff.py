"""scripts/perfdiff.py — the perf regression gate: noise-band rule, record
loading (bench JSONL + throughput JSON), CLI exit codes, self-test wiring."""
import json
import os
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "scripts"))
import perfdiff  # noqa: E402

sys.path.pop(0)


def _bench_line(value, lo, hi, launches=100, n_compiles=0):
    return {"metric": f"verified_partitions_per_sec_per_chip (GC-1, sat=1 "
                      f"unsat=2; median of 3 repeats)",
            "value": value, "unit": "partitions/sec", "min": lo, "max": hi,
            "device_launches": launches, "n_compiles": n_compiles}


def test_self_test_passes():
    """The built-in contract checks (CI wiring for the gate itself)."""
    assert perfdiff.self_test() == 0


def test_identical_bench_records_pass(tmp_path):
    rec = json.dumps(_bench_line(50.0, 46.0, 53.0))
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(rec + "\n")
    b.write_text(rec + "\n")
    assert perfdiff.main([str(a), str(b)]) == 0


def test_injected_2x_slowdown_flagged(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_bench_line(50.0, 46.0, 53.0)) + "\n")
    b.write_text(json.dumps(_bench_line(25.0, 23.0, 26.5)) + "\n")
    assert perfdiff.main([str(a), str(b)]) == 1


def test_overlapping_noise_bands_pass(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_bench_line(50.0, 46.0, 53.0)) + "\n")
    b.write_text(json.dumps(_bench_line(47.0, 44.0, 49.0)) + "\n")
    assert perfdiff.main([str(a), str(b)]) == 0


def test_metric_key_ignores_run_detail():
    """Bench metric strings embed per-run counts; the join key must not."""
    k1 = perfdiff._metric_key(
        "verified_partitions_per_sec_per_chip (GC-1, sat=186; median of 3)")
    k2 = perfdiff._metric_key(
        "verified_partitions_per_sec_per_chip (GC-1, sat=99; median of 5)")
    assert k1 == k2 == "verified_partitions_per_sec_per_chip"


def test_throughput_json_comparison(tmp_path):
    base = {"partitions_per_sec": 10.0, "device_launches": 40,
            "n_compiles": 3, "compile_s": 4.0}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(base))
    # Within tolerance: passes.
    b.write_text(json.dumps({**base, "partitions_per_sec": 9.0}))
    assert perfdiff.main([str(a), str(b)]) == 0
    # Halved rate: band-less record, rel-tol guard flags it.
    b.write_text(json.dumps({**base, "partitions_per_sec": 5.0}))
    assert perfdiff.main([str(a), str(b)]) == 1
    # Recompile churn (the ragged-chunk gate): n_compiles doubled.
    b.write_text(json.dumps({**base, "n_compiles": 6}))
    assert perfdiff.main([str(a), str(b)]) == 1


def test_both_throughput_rates_gated(tmp_path):
    """A device-count change can hold total partitions_per_sec steady while
    per-chip throughput halves — both rates must load and gate."""
    base = {"partitions_per_sec": 10.0, "partitions_per_sec_per_chip": 10.0}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(base))
    assert set(perfdiff.load_records(str(a))) == {
        "partitions_per_sec", "partitions_per_sec_per_chip"}
    b.write_text(json.dumps({"partitions_per_sec": 10.0,
                             "partitions_per_sec_per_chip": 5.0}))
    assert perfdiff.main([str(a), str(b)]) == 1


def test_zero_baseline_compile_growth_flagged(tmp_path):
    """The headline warm-run case: baseline n_compiles=0/compile_s=0 is the
    healthy state, and ANY real growth from it must gate (a relative-only
    rule would skip a zero baseline entirely)."""
    warm = {"partitions_per_sec": 10.0, "device_launches": 40,
            "n_compiles": 0, "compile_s": 0.0}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(warm))
    b.write_text(json.dumps({**warm, "n_compiles": 6, "compile_s": 14.0}))
    assert perfdiff.main([str(a), str(b)]) == 1
    # Persistent-cache reload jitter under the absolute floor still passes.
    b.write_text(json.dumps({**warm, "compile_s": 0.3}))
    assert perfdiff.main([str(a), str(b)]) == 0
    # A candidate that silently DROPS the counter fields warns (not a
    # silent pass pretending the gate ran).
    b.write_text(json.dumps({"partitions_per_sec": 10.0}))
    assert perfdiff.main([str(a), str(b)]) == 0  # warning, not regression
    recs = perfdiff.compare(perfdiff.load_records(str(a)),
                            perfdiff.load_records(str(b)))
    assert any(f["kind"] == "missing" and "n_compiles" in f["metric"]
               for f in recs)


def test_bench_jsonl_multiple_lines_and_noise_lines(tmp_path):
    lines = [
        json.dumps(_bench_line(50.0, 46.0, 53.0)),
        json.dumps({"metric": "ac_suite_vmap (12 models)", "value": 900.0,
                    "min": 850.0, "max": 930.0}),
        "some stray stderr noise",
    ]
    a = tmp_path / "a.json"
    a.write_text("\n".join(lines))
    recs = perfdiff.load_records(str(a))
    assert set(recs) == {"verified_partitions_per_sec_per_chip",
                        "ac_suite_vmap"}
    # One metric regresses, the other holds: still a failure overall.
    b = tmp_path / "b.json"
    b.write_text("\n".join([
        json.dumps(_bench_line(50.0, 46.0, 53.0)),
        json.dumps({"metric": "ac_suite_vmap (12 models)", "value": 300.0,
                    "min": 280.0, "max": 320.0}),
    ]))
    assert perfdiff.main([str(a), str(b)]) == 1


def test_missing_metric_warns_but_passes(tmp_path, capsys):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(_bench_line(50.0, 46.0, 53.0)) + "\n")
    b.write_text("{}")
    assert perfdiff.main([str(a), str(b)]) == 0
    assert "absent from candidate" in capsys.readouterr().out


def test_unreadable_baseline_is_an_error(tmp_path):
    a = tmp_path / "a.json"
    a.write_text("not json at all")
    b = tmp_path / "b.json"
    b.write_text(json.dumps(_bench_line(50.0, 46.0, 53.0)))
    assert perfdiff.main([str(a), str(b)]) == 2


def test_multichip_record_gating(tmp_path):
    """MULTICHIP records (n_devices/ok + optional per-mesh throughput and
    scaling factor) load as gated metrics: an ok flip, a shrunken fleet,
    or a lost scaling factor each fail; identical records pass."""
    base = {"n_devices": 8, "rc": 0, "ok": True,
            "model_partitions_per_sec": {"1": 100.0, "8": 450.0},
            "scaling_x": 4.5}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(base))
    assert set(perfdiff.load_records(str(a))) == {
        "multichip.ok", "multichip.n_devices", "multichip.pps@1dev",
        "multichip.pps@8dev", "multichip.scaling_x"}
    b.write_text(json.dumps(base))
    assert perfdiff.main([str(a), str(b)]) == 0
    b.write_text(json.dumps({**base, "ok": False}))
    assert perfdiff.main([str(a), str(b)]) == 1
    b.write_text(json.dumps({**base, "scaling_x": 1.1,
                             "model_partitions_per_sec": {"1": 100.0,
                                                          "8": 110.0}}))
    assert perfdiff.main([str(a), str(b)]) == 1
    # ok and n_devices are deterministic, so they gate strictly: losing
    # even ONE device of the fleet fails (no 20% noise tolerance).
    b.write_text(json.dumps({**base, "n_devices": 7}))
    assert perfdiff.main([str(a), str(b)]) == 1
    # The minimal driver record shape ({n_devices, rc, ok, ...}) still
    # gates on the ok flag and the fleet size.
    a.write_text(json.dumps({"n_devices": 8, "rc": 0, "ok": True,
                             "skipped": False, "tail": ""}))
    b.write_text(json.dumps({"n_devices": 4, "rc": 0, "ok": True}))
    assert perfdiff.main([str(a), str(b)]) == 1
    # Cross-shape: a minimal driver baseline gates a rich scaling-harness
    # candidate (the README recipe) — ok means run-health in both shapes,
    # throughput metrics join the gate only once both sides carry them.
    b.write_text(json.dumps(base))
    assert perfdiff.main([str(a), str(b)]) == 0
    b.write_text(json.dumps({**base, "ok": False}))
    assert perfdiff.main([str(a), str(b)]) == 1


def test_serve_record_gating(tmp_path):
    """SERVE records (scripts/serve_bench.py) load as gated metrics:
    p95 latency and deadline-miss growth fail (lower-is-better with the
    miss rate's 2-point absolute floor), a warm server that starts
    recompiling fails, in-tolerance jitter passes."""
    base = {"kind": "SERVE", "warm_xla_compiles": 0,
            "clients": {"1": {"p95_ms": 400.0, "deadline_miss_rate": 0.0,
                              "requests_per_s": 2.0,
                              "batch_occupancy_mean": 1.0},
                        "4": {"p95_ms": 900.0, "deadline_miss_rate": 0.0,
                              "requests_per_s": 4.0,
                              "batch_occupancy_mean": 3.5}}}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(base))
    assert set(perfdiff.load_records(str(a))) == {
        "serve.warm_xla_compiles",
        "serve.p95_ms@1c", "serve.deadline_miss_rate@1c",
        "serve.requests_per_s@1c", "serve.batch_occupancy@1c",
        "serve.p95_ms@4c", "serve.deadline_miss_rate@4c",
        "serve.requests_per_s@4c", "serve.batch_occupancy@4c"}
    b.write_text(json.dumps(base))
    assert perfdiff.main([str(a), str(b)]) == 0
    cand = json.loads(json.dumps(base))
    cand["clients"]["4"]["p95_ms"] = 2400.0
    b.write_text(json.dumps(cand))
    assert perfdiff.main([str(a), str(b)]) == 1
    cand = json.loads(json.dumps(base))
    cand["clients"]["4"]["deadline_miss_rate"] = 0.3
    b.write_text(json.dumps(cand))
    assert perfdiff.main([str(a), str(b)]) == 1
    cand = json.loads(json.dumps(base))
    cand["warm_xla_compiles"] = 4
    b.write_text(json.dumps(cand))
    assert perfdiff.main([str(a), str(b)]) == 1
    # One unlucky miss over a clean baseline stays inside the floor, and
    # latency jitter inside --rel-tol passes.
    cand = json.loads(json.dumps(base))
    cand["clients"]["4"]["deadline_miss_rate"] = 0.015
    cand["clients"]["4"]["p95_ms"] = 1000.0
    b.write_text(json.dumps(cand))
    assert perfdiff.main([str(a), str(b)]) == 0


def test_procfleet_block_gating(tmp_path):
    """A SERVE record's `procfleet` block (serve_bench --replica-procs)
    gates replica deaths/restarts/re-homes lower-is-better with a 2-count
    floor: a flapping fleet fails even when the latency columns survive
    failover; a single blip within the floor passes."""
    base = {"kind": "SERVE", "replica_procs": 2,
            "clients": {"4": {"p95_ms": 900.0, "deadline_miss_rate": 0.0,
                              "requests_per_s": 4.0}},
            "procfleet": {"replica_deaths": 0, "replica_restarts": 0,
                          "rehomed": 0, "fleet_n_compiles": 9,
                          "fleet_exec_cache_hits": 30}}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(base))
    assert {"serve.replica_deaths", "serve.replica_restarts",
            "serve.replica_rehomed"} <= set(perfdiff.load_records(str(a)))
    b.write_text(json.dumps(base))
    assert perfdiff.main([str(a), str(b)]) == 0
    # Flapping fleet: kills, restarts, and re-homes all step up.
    cand = json.loads(json.dumps(base))
    cand["procfleet"].update(replica_deaths=6, replica_restarts=6,
                             rehomed=5)
    b.write_text(json.dumps(cand))
    assert perfdiff.main([str(a), str(b)]) == 1
    # One death + restart over a clean baseline is inside the 2-count
    # floor (a single chaos-style blip, not a flap loop).
    cand = json.loads(json.dumps(base))
    cand["procfleet"].update(replica_deaths=1, replica_restarts=1,
                             rehomed=1)
    b.write_text(json.dumps(cand))
    assert perfdiff.main([str(a), str(b)]) == 0
    # Fleet compile totals are informational (warmup compiles are
    # legitimate on a cold cache), never gated.
    cand = json.loads(json.dumps(base))
    cand["procfleet"]["fleet_n_compiles"] = 40
    b.write_text(json.dumps(cand))
    assert perfdiff.main([str(a), str(b)]) == 0


def test_integrity_counter_zero_growth_gate(tmp_path):
    """Integrity detections gate at zero growth (ISSUE 19): a bench line
    whose integrity_violations grows from a clean 0 baseline fails; the
    throughput record's counters (nested under `resilience`) hoist into
    the same gate."""
    clean = {**_bench_line(50.0, 46.0, 53.0), "integrity_violations": 0,
             "ledger_crc_mismatch": 0}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(clean) + "\n")
    b.write_text(json.dumps(clean) + "\n")
    assert perfdiff.main([str(a), str(b)]) == 0
    b.write_text(json.dumps({**clean, "integrity_violations": 3}) + "\n")
    assert perfdiff.main([str(a), str(b)]) == 1
    thr = {"partitions_per_sec": 10.0,
           "resilience": {"degraded": 0, "integrity_violations": 0,
                          "ledger_crc_mismatch": 0}}
    a.write_text(json.dumps(thr))
    recs = perfdiff.load_records(str(a))
    assert recs["partitions_per_sec"]["integrity_violations"] == 0
    b.write_text(json.dumps(
        {**thr, "resilience": {"degraded": 1, "integrity_violations": 2,
                               "ledger_crc_mismatch": 0}}))
    assert perfdiff.main([str(a), str(b)]) == 1


def test_integrity_recheck_overhead_gate(tmp_path):
    """The bench headline's integrity_ab block gates the sampled-recheck
    cost lower-is-better with a 5-point floor: within-noise overhead
    passes, a step change fails."""
    base = {**_bench_line(50.0, 46.0, 53.0),
            "integrity_ab": {"recheck_rate": 0.05, "pps_on": 49.0,
                             "pps_off": 50.0, "overhead_rel": 0.02}}
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(base) + "\n")
    assert "integrity_recheck_overhead_rel" in perfdiff.load_records(str(a))
    cand = json.loads(json.dumps(base))
    cand["integrity_ab"]["overhead_rel"] = 0.06
    b.write_text(json.dumps(cand) + "\n")
    assert perfdiff.main([str(a), str(b)]) == 0
    cand["integrity_ab"]["overhead_rel"] = 0.40
    b.write_text(json.dumps(cand) + "\n")
    assert perfdiff.main([str(a), str(b)]) == 1


def test_chaos_archive_sdc_gate(tmp_path):
    """A chaos-matrix JSONL archive aggregates into chaos.sdc_escaped /
    chaos.failed_cells: any decided-wrong verdict that escaped containment
    (or a newly failing cell) fails the gate."""
    clean = [{"cell": "integrity/launch.decode/run", "ok": True,
              "sdc_escaped": 0},
             {"cell": "launch.decode/transient", "ok": True}]
    leaky = [{"cell": "integrity/launch.decode/run", "ok": False,
              "sdc_escaped": 1},
             {"cell": "launch.decode/transient", "ok": True}]
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    a.write_text("\n".join(json.dumps(r) for r in clean) + "\n")
    b.write_text("\n".join(json.dumps(r) for r in leaky) + "\n")
    recs = perfdiff.load_records(str(a))
    assert recs["chaos.sdc_escaped"]["value"] == 0.0
    assert recs["chaos.failed_cells"]["value"] == 0.0
    assert perfdiff.main([str(a), str(a)]) == 0
    assert perfdiff.main([str(a), str(b)]) == 1


def test_self_test_cli_flag():
    assert perfdiff.main(["--self-test"]) == 0

"""Device-resident stage-0 mega-loop (DESIGN.md §17).

The contracts ISSUE 14 pins in tier-1:

* verdict maps, counterexamples, and ledgers are BIT-EQUAL between the
  mega-loop and the per-chunk launch loop across segment sizes
  {1 chunk, several, whole grid} and pipeline depths {1, 2} — on the real
  GC-1 zoo net and on a stacked adult (AC) family;
* launches per model drop from O(chunks) to O(segments), recorded as
  ``launches_per_model`` in the throughput JSON;
* a ``launch.submit``/``launch.decode`` fault exhausted mid-segment
  degrades EXACTLY that segment's partitions and ``resume=True``
  converges to the fault-free map (a transient is absorbed outright);
* a crash while a segment is in flight never ledgers undrained work
  (the mega twin of test_pipeline's chunk-loop crash test);
* segment progress is observable: ``segment`` events land in the trace
  log (rendered by ``fairify_tpu report``) and the heartbeat prints a
  throttled ``segments done/total`` line.
"""
import io
import json
import os

import numpy as np
import pytest

from fairify_tpu.models.train import init_mlp
from fairify_tpu.verify import presets, sweep


def _cfg(tmp_path, sub, **kw):
    return presets.get("GC").with_(
        result_dir=str(tmp_path / sub), soft_timeout_s=30.0,
        hard_timeout_s=300.0, sim_size=64, exact_certify_masks=False,
        grid_chunk=16, **kw)


def _outcome_map(report):
    out = {}
    for o in report.outcomes:
        ce = None
        if o.counterexample is not None:
            ce = (tuple(int(v) for v in o.counterexample[0]),
                  tuple(int(v) for v in o.counterexample[1]))
        out[o.partition_id] = (o.verdict, ce, round(float(o.pruned_acc), 6))
    return out


def _ledger_map(path):
    """pid → (verdict, ce) from a ledger file (time fields excluded)."""
    recs, skipped = sweep._read_ledger(str(path))
    assert skipped == 0
    out = {}
    for rec in recs:
        ce = rec.get("ce")
        out[rec["partition_id"]] = (
            rec["verdict"],
            tuple(tuple(c) for c in ce) if ce else None)
    return out


def test_mega_bit_equal_gc1_across_segments_and_depths(tmp_path):
    """GC-1 (the headline net): chunk loop vs mega at {1, 2, whole}.

    ``_flagship_net`` is bench.py's GC-1 — the reference zoo h5 when the
    assets are present, its synthetic architecture twin otherwise.
    """
    from __graft_entry__ import _flagship_net

    net = _flagship_net()
    span = (0, 64)  # 4 chunks of 16
    maps, ledgers = {}, {}
    for mc in (0, 1, 2, 8):
        for depth in (1, 2):
            cfg = _cfg(tmp_path, f"gc_{mc}_{depth}", mega_chunks=mc,
                       pipeline_depth=depth)
            rep = sweep.verify_model(net, cfg, model_name="GC-1",
                                     resume=False, partition_span=span)
            maps[(mc, depth)] = _outcome_map(rep)
            ledgers[(mc, depth)] = _ledger_map(
                tmp_path / f"gc_{mc}_{depth}" / "GC-GC-1@0-64.ledger.jsonl")
    ref, led_ref = maps[(0, 1)], ledgers[(0, 1)]
    assert ref and led_ref
    for key in maps:
        assert maps[key] == ref, f"outcome drift at {key}"
        assert ledgers[key] == led_ref, f"ledger drift at {key}"


def test_mega_family_bit_equal_ac(tmp_path):
    """One adult (AC) architecture family through stage0_families."""
    from fairify_tpu.parallel.mesh import stack_models
    from fairify_tpu.verify.property import encode

    cfg = presets.get("AC").with_(grid_chunk=16)
    d = len(cfg.query().columns)
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)
    lo, hi = lo[:48], hi[:48]
    stacked = stack_models([init_mlp((d, 8, 1), seed=s) for s in (0, 1, 2)])
    want = sweep.stage0_families([stacked], enc, lo, hi,
                                 cfg.with_(mega_chunks=0))[0]
    for mc in (1, 2, 8):
        got = sweep.stage0_families([stacked], enc, lo, hi,
                                    cfg.with_(mega_chunks=mc))[0]
        assert len(got) == len(want)
        for (u_g, s_g, w_g), (u_w, s_w, w_w) in zip(got, want):
            np.testing.assert_array_equal(u_g, u_w)
            np.testing.assert_array_equal(s_g, s_w)
            assert set(w_g) == set(w_w)
            for k in w_g:
                np.testing.assert_array_equal(w_g[k][0], w_w[k][0])
                np.testing.assert_array_equal(w_g[k][1], w_w[k][1])


def test_mega_launch_economy(tmp_path):
    """Launches per model are O(segments), not O(chunks), and recorded."""
    net = init_mlp((20, 8, 1), seed=3)
    span = (0, 48)  # 3 chunks
    thr = {}
    for mc in (0, 8):
        cfg = _cfg(tmp_path, f"econ_{mc}", mega_chunks=mc)
        sweep.verify_model(net, cfg, model_name="m", resume=False,
                           partition_span=span)
        with open(tmp_path / f"econ_{mc}" / "GC-m@0-48.throughput.json") as fp:
            thr[mc] = json.load(fp)
    # Chunk loop: one launch per chunk per phase (prune/certify/parity).
    # Whole-grid segments: one launch per phase.
    assert thr[8]["device_launches"] < thr[0]["device_launches"]
    assert thr[8]["launches_per_model"] == thr[8]["device_launches"]
    assert thr[8]["launches_per_model"] <= 3 + 1  # 3 phases (+ PGD slack)


def test_mega_ragged_final_segment_single_compile(tmp_path):
    """5 chunks at mega_chunks=4 → segments of 4 and 1: the ragged final
    segment must pad its CHUNK axis to the segment bucket and reuse the
    full-segment executables — one compile per mega kernel, results
    bit-equal to the chunk loop."""
    from fairify_tpu import obs

    net = init_mlp((20, 8, 1), seed=7)  # fresh arch: owns its compiles
    span = (0, 80)  # 5 chunks of 16
    c = obs.registry().counter("xla_compiles")
    kernels = ("sweep.mega_stage0_kernel", "pruning.mega_sim_and_bounds",
               "sweep.mega_parity_kernel")
    before = {k: c.value(kernel=k) or 0 for k in kernels}
    rep = sweep.verify_model(
        net, _cfg(tmp_path, "ragged", mega_chunks=4), model_name="m",
        resume=False, partition_span=span)
    for k in kernels:
        assert (c.value(kernel=k) or 0) - before[k] == 1, k
    chunked = sweep.verify_model(
        net, _cfg(tmp_path, "ragged0", mega_chunks=0), model_name="m",
        resume=False, partition_span=span)
    assert _outcome_map(rep) == _outcome_map(chunked)


def _fault_cfg(tmp_path, sub, specs):
    # mega_chunks=1 → 3 one-chunk segments per phase; max_launch_retries=2
    # means arrivals {2, 3, 4} are segment 2's attempt + both retries.
    return _cfg(tmp_path, sub, mega_chunks=1, max_launch_retries=2,
                launch_backoff_s=0.001, inject_faults=specs)


@pytest.mark.parametrize("site", ["launch.submit", "launch.decode"])
def test_mega_fault_exhausted_degrades_one_segment(tmp_path, site):
    net = init_mlp((20, 8, 1), seed=3)
    span = (0, 48)
    clean = sweep.verify_model(
        net, _cfg(tmp_path, f"{site}-clean"), model_name="m", resume=False,
        partition_span=span)
    want = _outcome_map(clean)

    spec = f"{site}:transient:2-4"  # exhaust exactly segment 2's attempts
    cfg = _fault_cfg(tmp_path, f"{site}-exh", (spec,))
    rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                             partition_span=span)
    got = _outcome_map(rep)
    seg2 = set(range(17, 33))  # partitions of the second 16-chunk segment
    assert rep.degraded == 16
    for pid, (verdict, ce, _pa) in got.items():
        if pid in seg2:
            assert verdict == "unknown", f"pid {pid} should have degraded"
        else:
            assert (verdict, ce) == want[pid][:2], f"pid {pid} drifted"
    # The ledger carries machine-readable failure records for exactly seg2.
    recs, _ = sweep._read_ledger(
        str(tmp_path / f"{site}-exh" / "GC-m@0-48.ledger.jsonl"))
    failed_pids = {r["partition_id"] for r in recs if r.get("failure")}
    assert failed_pids == seg2

    # resume=True re-attempts only the degraded segment and converges.
    resumed = sweep.verify_model(net, cfg.with_(inject_faults=()),
                                 model_name="m", resume=True,
                                 partition_span=span)
    res_map = {pid: v[:2] for pid, v in _outcome_map(resumed).items()}
    assert res_map == {pid: v[:2] for pid, v in want.items()}


def test_mega_fault_transient_absorbed(tmp_path):
    net = init_mlp((20, 8, 1), seed=3)
    span = (0, 48)
    clean = sweep.verify_model(
        net, _cfg(tmp_path, "trans-clean"), model_name="m", resume=False,
        partition_span=span)
    cfg = _fault_cfg(tmp_path, "trans", ("launch.submit:transient:2",))
    rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                             partition_span=span)
    assert rep.degraded == 0
    assert _outcome_map(rep) == _outcome_map(clean)


def test_mega_crash_mid_segment_never_ledgers_undrained(tmp_path, monkeypatch):
    """The chunk-loop crash-safety pin, on the mega decode path."""
    cfg = _cfg(tmp_path, "crash", mega_chunks=1, pipeline_depth=2)
    net = init_mlp((20, 8, 1), seed=3)
    span = (0, 48)

    real_decode = sweep._mega_segment_decode
    calls = {"n": 0}

    def dying_decode(host, ctx):
        calls["n"] += 1
        if calls["n"] >= 2:  # die at the second drain — one seg in flight
            raise RuntimeError("simulated crash mid-drain")
        return real_decode(host, ctx)

    monkeypatch.setattr(sweep, "_mega_segment_decode", dying_decode)
    with pytest.raises(RuntimeError, match="mid-drain"):
        sweep.verify_model(net, cfg, model_name="m", resume=False,
                           partition_span=span)
    monkeypatch.setattr(sweep, "_mega_segment_decode", real_decode)

    ledger = tmp_path / "crash" / "GC-m@0-48.ledger.jsonl"
    assert not ledger.exists() or os.path.getsize(ledger) == 0

    crashed = sweep.verify_model(net, cfg, model_name="m", resume=True,
                                 partition_span=span)
    clean = sweep.verify_model(
        net, _cfg(tmp_path, "crash-clean", mega_chunks=1), model_name="m",
        resume=False, partition_span=span)
    assert _outcome_map(crashed) == _outcome_map(clean)


def test_segment_events_and_report_table(tmp_path):
    from fairify_tpu.obs import report as report_mod

    trace = tmp_path / "trace.jsonl"
    cfg = _cfg(tmp_path, "events", mega_chunks=1, trace_out=str(trace))
    net = init_mlp((20, 8, 1), seed=3)
    sweep.verify_model(net, cfg, model_name="m", resume=False,
                       partition_span=(0, 48))
    agg = report_mod.aggregate([str(trace)])
    segs = agg["segments"]
    assert segs["stage0_decide"]["done"] == segs["stage0_decide"]["total"] == 3
    assert segs["stage0_decide"]["partitions"] == 48
    assert "mega segments" in report_mod.render(agg)


def test_heartbeat_segment_line():
    from fairify_tpu.obs.heartbeat import Heartbeat

    out = io.StringIO()
    hb = Heartbeat(0.001, total=48, label="GC-1", stream=out,
                   clock=iter(np.arange(0.0, 100.0, 1.0)).__next__)
    try:
        assert hb.segment("stage0_decide", 1, 3, in_flight=2)
        # Mid-phase beats throttle on the interval clock; the final
        # segment always prints.
        assert hb.segment("stage0_decide", 3, 3)
    finally:
        hb.close()
    text = out.getvalue()
    assert "stage0_decide segments 1/3 (2 in flight)" in text
    assert "stage0_decide segments 3/3" in text

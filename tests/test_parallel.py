"""Mesh helpers, multihost slicing, throughput counters."""
import numpy as np
import pytest

from fairify_tpu.parallel import mesh as mesh_mod
from fairify_tpu.parallel import multihost
from fairify_tpu.utils.profiling import ThroughputCounter, xla_trace


def test_host_slice_partitions_balanced():
    n = 23
    slices = [multihost.host_slice(n, pi, 4) for pi in range(4)]
    assert slices[0][0] == 0 and slices[-1][1] == n
    covered = []
    for s, e in slices:
        covered.extend(range(s, e))
    assert covered == list(range(n))
    widths = [e - s for s, e in slices]
    assert max(widths) - min(widths) <= 1


def test_allgather_single_process_identity():
    codes = np.array([0, 1, 2, 1], dtype=np.int8)
    out = multihost.allgather_verdicts(codes)
    np.testing.assert_array_equal(out, codes)


def test_pad_to_multiple():
    a = np.arange(10).reshape(5, 2)
    padded, n = mesh_mod.pad_to_multiple(a, 4)
    assert n == 5 and padded.shape == (8, 2)
    np.testing.assert_array_equal(padded[5:], np.tile(a[-1:], (3, 1)))
    same, n2 = mesh_mod.pad_to_multiple(a, 5)
    assert n2 == 5 and same.shape == (5, 2)


def test_stack_models_rejects_mixed_archs():
    from fairify_tpu.models import train

    a = train.init_mlp([4, 8, 1], seed=0)
    b = train.init_mlp([4, 6, 1], seed=1)
    with pytest.raises(ValueError):
        mesh_mod.stack_models([a, b])


def test_throughput_counter():
    c = ThroughputCounter(n_devices=2)
    for v, s0 in [("sat", True), ("unsat", True), ("sat", False), ("unknown", False)]:
        c.record(v, via_stage0=s0)
    s = c.summary()
    assert s["decided"] == 3 and s["stage0_decided"] == 2
    assert s["unknown"] == 1
    assert s["partitions_per_sec_per_chip"] == pytest.approx(s["partitions_per_sec"] / 2)


def test_xla_trace_noop():
    with xla_trace(None):
        pass


def test_sweep_host_spans_cover_grid(tmp_path):
    """Two simulated hosts sweep disjoint spans; merged ledgers equal the
    single-host run's verdict map (global PRNG keys + partition ids)."""
    import os

    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.verify import presets, sweep

    net = init_mlp((20, 8, 1), seed=3)
    base = presets.get("GC").with_(
        soft_timeout_s=30.0, hard_timeout_s=300.0, sim_size=64,
        exact_certify_masks=False)

    whole = sweep.verify_model(
        net, base.with_(result_dir=str(tmp_path / "whole")),
        model_name="m", resume=False)
    assert whole.counts["unknown"] == 0  # fully decidable → strict equality

    # Hosts share one result_dir: sinks are span-qualified so appends never
    # interleave on a network filesystem.
    shared = base.with_(result_dir=str(tmp_path / "shared"))
    spans = [multihost.host_slice(201, pi, 2) for pi in range(2)]
    ledgers = []
    reports = []
    for hi_, pc in ((0, 2), (1, 2)):
        rep, codes = multihost.sweep_host(
            net, shared, model_name="m", process_index=hi_, process_count=pc)
        reports.append(rep)
        s, e = spans[hi_]
        ledgers.append(os.path.join(shared.result_dir,
                                    f"GC-m@{s}-{e}.ledger.jsonl"))
    assert all(os.path.isfile(p) for p in ledgers)
    assert sum(len(r.outcomes) for r in reports) == whole.partitions_total

    merged = multihost.merge_ledgers(ledgers)
    assert len(merged) == whole.partitions_total
    whole_map = {o.partition_id: o.verdict for o in whole.outcomes}
    assert {k: v["verdict"] for k, v in merged.items()} == whole_map


def test_decide_many_mesh_invariant():
    """BaB over a sharded frontier returns the same verdicts as unsharded."""
    import numpy as np

    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.parallel.mesh import make_mesh
    from fairify_tpu.verify import engine, presets, sweep
    from fairify_tpu.verify.property import encode

    net = init_mlp((20, 8, 1), seed=5)
    cfg = presets.get("GC")
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)
    lo, hi = lo[:24], hi[:24]
    ecfg = engine.EngineConfig(soft_timeout_s=30.0, frontier_size=64)

    plain = engine.decide_many(net, enc, lo, hi, ecfg)
    mesh = make_mesh()
    sharded = engine.decide_many(net, enc, lo, hi, ecfg, mesh=mesh)
    pv = [d.verdict for d in plain]
    sv = [d.verdict for d in sharded]
    assert "unknown" not in pv  # fully decidable -> strict comparison
    assert pv == sv

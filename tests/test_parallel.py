"""Mesh helpers, multihost slicing, shard planning, throughput counters."""
import numpy as np
import pytest

from fairify_tpu.parallel import mesh as mesh_mod
from fairify_tpu.parallel import multihost
from fairify_tpu.parallel import shards as shards_mod
from fairify_tpu.utils.profiling import ThroughputCounter, xla_trace


def test_host_slice_partitions_balanced():
    n = 23
    slices = [multihost.host_slice(n, pi, 4) for pi in range(4)]
    assert slices[0][0] == 0 and slices[-1][1] == n
    covered = []
    for s, e in slices:
        covered.extend(range(s, e))
    assert covered == list(range(n))
    widths = [e - s for s, e in slices]
    assert max(widths) - min(widths) <= 1


def test_allgather_single_process_identity():
    codes = np.array([0, 1, 2, 1], dtype=np.int8)
    out = multihost.allgather_verdicts(codes)
    np.testing.assert_array_equal(out, codes)


def test_pad_to_multiple():
    a = np.arange(10).reshape(5, 2)
    padded, n = mesh_mod.pad_to_multiple(a, 4)
    assert n == 5 and padded.shape == (8, 2)
    np.testing.assert_array_equal(padded[5:], np.tile(a[-1:], (3, 1)))
    same, n2 = mesh_mod.pad_to_multiple(a, 5)
    assert n2 == 5 and same.shape == (5, 2)
    # The docstring now matches the signature: any axis pads.
    padded1, n1 = mesh_mod.pad_to_multiple(a, 4, axis=1)
    assert n1 == 2 and padded1.shape == (5, 4)


def test_make_mesh_warns_once_on_truncation_and_records_gauge():
    import jax

    from fairify_tpu.obs import metrics as metrics_mod

    assert len(jax.devices()) == 8
    mesh_mod._TRUNCATION_WARNED = False
    with pytest.warns(RuntimeWarning, match="uses 3 of 8"):
        mesh = mesh_mod.make_mesh(n_parts=3, n_models=1)
    assert mesh.shape == {"parts": 3, "models": 1}
    assert metrics_mod.registry().gauge("mesh_devices").value() == 3
    # Warn-once: the second truncating build is silent.
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        mesh_mod.make_mesh(n_parts=3, n_models=1)
    mesh_mod._TRUNCATION_WARNED = False


def test_make_mesh_rejects_oversubscription():
    with pytest.raises(ValueError, match="needs 9 devices"):
        mesh_mod.make_mesh(n_parts=9, n_models=1)


def test_submesh_over_explicit_devices():
    import jax

    devs = jax.devices()[2:5]
    mesh = mesh_mod.submesh(devs)
    assert mesh.shape == {"parts": 3, "models": 1}
    assert list(mesh.devices.flat) == list(devs)
    with pytest.raises(ValueError, match="do not factor"):
        mesh_mod.submesh(devs, n_models=2)
    with pytest.raises(ValueError):
        mesh_mod.submesh([])


def test_shard_spans_alignment_balance_and_caps():
    spans = shards_mod.shard_spans(0, 48, 3, align=16)
    assert spans == [(0, 16), (16, 32), (32, 48)]
    # Coverage + chunk-aligned interior boundaries on a ragged grid.
    spans = shards_mod.shard_spans(0, 201, 4, align=16)
    assert spans[0][0] == 0 and spans[-1][1] == 201
    for (_, e1), (s2, _) in zip(spans, spans[1:]):
        assert e1 == s2 and e1 % 16 == 0
    # n_shards capped at whole-chunk count; empty span yields nothing.
    assert len(shards_mod.shard_spans(0, 48, 99, align=16)) == 3
    assert shards_mod.shard_spans(5, 5, 3) == []
    # Offset spans keep global alignment semantics (re-split of a shard).
    assert shards_mod.shard_spans(16, 48, 2, align=16) == [(16, 32), (32, 48)]


def test_device_groups_balanced():
    groups = shards_mod.device_groups(list(range(8)), 3)
    assert [len(g) for g in groups] == [3, 3, 2]
    assert [d for g in groups for d in g] == list(range(8))
    assert shards_mod.device_groups([1, 2], 5) == [(1,), (2,)]


def test_stack_models_rejects_mixed_archs():
    from fairify_tpu.models import train

    a = train.init_mlp([4, 8, 1], seed=0)
    b = train.init_mlp([4, 6, 1], seed=1)
    with pytest.raises(ValueError):
        mesh_mod.stack_models([a, b])


def test_throughput_counter():
    c = ThroughputCounter(n_devices=2)
    for v, s0 in [("sat", True), ("unsat", True), ("sat", False), ("unknown", False)]:
        c.record(v, via_stage0=s0)
    s = c.summary()
    assert s["decided"] == 3 and s["stage0_decided"] == 2
    assert s["unknown"] == 1
    assert s["partitions_per_sec_per_chip"] == pytest.approx(s["partitions_per_sec"] / 2)


def test_xla_trace_noop():
    with xla_trace(None):
        pass


def test_sweep_host_spans_cover_grid(tmp_path):
    """Two simulated hosts sweep disjoint spans; merged ledgers equal the
    single-host run's verdict map (global PRNG keys + partition ids)."""
    import os

    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.verify import presets, sweep

    net = init_mlp((20, 8, 1), seed=3)
    base = presets.get("GC").with_(
        soft_timeout_s=30.0, hard_timeout_s=300.0, sim_size=64,
        exact_certify_masks=False)

    whole = sweep.verify_model(
        net, base.with_(result_dir=str(tmp_path / "whole")),
        model_name="m", resume=False)
    assert whole.counts["unknown"] == 0  # fully decidable → strict equality

    # Hosts share one result_dir: sinks are span-qualified so appends never
    # interleave on a network filesystem.
    shared = base.with_(result_dir=str(tmp_path / "shared"))
    spans = [multihost.host_slice(201, pi, 2) for pi in range(2)]
    ledgers = []
    reports = []
    for hi_, pc in ((0, 2), (1, 2)):
        rep, codes = multihost.sweep_host(
            net, shared, model_name="m", process_index=hi_, process_count=pc)
        reports.append(rep)
        s, e = spans[hi_]
        ledgers.append(os.path.join(shared.result_dir,
                                    f"GC-m@{s}-{e}.ledger.jsonl"))
    assert all(os.path.isfile(p) for p in ledgers)
    assert sum(len(r.outcomes) for r in reports) == whole.partitions_total

    merged = multihost.merge_ledgers(ledgers)
    assert len(merged) == whole.partitions_total
    whole_map = {o.partition_id: o.verdict for o in whole.outcomes}
    assert {k: v["verdict"] for k, v in merged.items()} == whole_map


def test_sweep_sharded_matches_single_chip(tmp_path):
    """Fault-free sharded sweep (3 fault domains over the 8-device virtual
    mesh) is verdict-map bit-equal to the plain single-chip sweep, and each
    initial shard span keeps its own journal."""
    import os

    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.verify import presets, sweep

    net = init_mlp((20, 8, 1), seed=3)
    span = (0, 48)
    base = presets.get("GC").with_(
        soft_timeout_s=30.0, hard_timeout_s=600.0, sim_size=64,
        exact_certify_masks=False, grid_chunk=16)
    plain = sweep.verify_model(
        net, base.with_(result_dir=str(tmp_path / "plain")), model_name="m",
        resume=False, partition_span=span)
    want = {o.partition_id: o.verdict for o in plain.outcomes}

    cfg = base.with_(result_dir=str(tmp_path / "sharded"))
    rep = shards_mod.sweep_sharded(net, cfg, model_name="m", n_shards=3,
                                   partition_span=span, resume=False)
    assert {o.partition_id: o.verdict for o in rep.outcomes} == want
    assert rep.partitions_total == 48 and rep.degraded == 0
    for s, e in ((0, 16), (16, 32), (32, 48)):
        assert os.path.isfile(os.path.join(
            cfg.result_dir, f"GC-m@{s}-{e}.ledger.jsonl"))
    # run_sweep-level validation: sharding composes with neither the
    # multi-host split nor retry_unknown (yet) — fail fast, not mid-fleet.
    with pytest.raises(ValueError, match="mutually exclusive"):
        sweep.run_sweep(cfg, host_index=0, host_count=2, n_shards=2)
    with pytest.raises(ValueError, match="retry_unknown"):
        sweep.run_sweep(cfg, retry_unknown=True, n_shards=2)


def test_decide_many_mesh_invariant():
    """BaB over a sharded frontier returns the same verdicts as unsharded."""
    import numpy as np

    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.parallel.mesh import make_mesh
    from fairify_tpu.verify import engine, presets, sweep
    from fairify_tpu.verify.property import encode

    net = init_mlp((20, 8, 1), seed=5)
    cfg = presets.get("GC")
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)
    lo, hi = lo[:24], hi[:24]
    ecfg = engine.EngineConfig(soft_timeout_s=30.0, frontier_size=64)

    plain = engine.decide_many(net, enc, lo, hi, ecfg)
    mesh = make_mesh()
    sharded = engine.decide_many(net, enc, lo, hi, ecfg, mesh=mesh)
    pv = [d.verdict for d in plain]
    sv = [d.verdict for d in sharded]
    assert "unknown" not in pv  # fully decidable -> strict comparison
    assert pv == sv

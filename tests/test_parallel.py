"""Mesh helpers, multihost slicing, throughput counters."""
import numpy as np
import pytest

from fairify_tpu.parallel import mesh as mesh_mod
from fairify_tpu.parallel import multihost
from fairify_tpu.utils.profiling import ThroughputCounter, xla_trace


def test_host_slice_partitions_balanced():
    n = 23
    slices = [multihost.host_slice(n, pi, 4) for pi in range(4)]
    assert slices[0][0] == 0 and slices[-1][1] == n
    covered = []
    for s, e in slices:
        covered.extend(range(s, e))
    assert covered == list(range(n))
    widths = [e - s for s, e in slices]
    assert max(widths) - min(widths) <= 1


def test_allgather_single_process_identity():
    codes = np.array([0, 1, 2, 1], dtype=np.int8)
    out = multihost.allgather_verdicts(codes)
    np.testing.assert_array_equal(out, codes)


def test_pad_to_multiple():
    a = np.arange(10).reshape(5, 2)
    padded, n = mesh_mod.pad_to_multiple(a, 4)
    assert n == 5 and padded.shape == (8, 2)
    np.testing.assert_array_equal(padded[5:], np.tile(a[-1:], (3, 1)))
    same, n2 = mesh_mod.pad_to_multiple(a, 5)
    assert n2 == 5 and same.shape == (5, 2)


def test_stack_models_rejects_mixed_archs():
    from fairify_tpu.models import train

    a = train.init_mlp([4, 8, 1], seed=0)
    b = train.init_mlp([4, 6, 1], seed=1)
    with pytest.raises(ValueError):
        mesh_mod.stack_models([a, b])


def test_throughput_counter():
    c = ThroughputCounter(n_devices=2)
    for v, s0 in [("sat", True), ("unsat", True), ("sat", False), ("unknown", False)]:
        c.record(v, via_stage0=s0)
    s = c.summary()
    assert s["decided"] == 3 and s["stage0_decided"] == 2
    assert s["unknown"] == 1
    assert s["partitions_per_sec_per_chip"] == pytest.approx(s["partitions_per_sec"] / 2)


def test_xla_trace_noop():
    with xla_trace(None):
        pass

# rel: fairify_tpu/serve/fx_queue_ok.py
import threading


class Queue:
    def __init__(self):
        self._cv = threading.Condition(threading.Lock())
        self._items = []

    def put(self, item):
        with self._cv:
            self._items.append(item)
            self._items = list(self._items)
            self._cv.notify_all()

    def peek(self):
        with self._cv:
            return self._items[-1]

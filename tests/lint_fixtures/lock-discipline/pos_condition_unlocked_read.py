# rel: fairify_tpu/serve/fx_queue.py
import threading


class Queue:
    """A Condition wraps a lock; `with self._cv:` acquires it — state
    assigned inside that block is lock-protected like any Lock's."""

    def __init__(self):
        self._cv = threading.Condition(threading.Lock())
        self._items = []
        self._draining = False

    def put(self, item):
        with self._cv:
            self._items.append(item)
            self._items = list(self._items)
            self._cv.notify_all()

    def drain(self):
        with self._cv:
            self._draining = True

    def unsafe_peek(self):
        return self._items[-1]  # EXPECT

    def unsafe_is_draining(self):
        return self._draining  # EXPECT

# rel: fairify_tpu/obs/metrics.py
import threading


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}  # __init__ writes precede sharing: exempt

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def get(self, k):
        return self._items.get(k)  # EXPECT

# rel: fairify_tpu/parallel/pipeline.py
import threading


class SafeBox:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def get(self, k):
        with self._lock:
            return self._items.get(k)


class NoLocks:
    # No lock attributes: the rule has nothing to protect here.
    def __init__(self):
        self.items = {}

    def put(self, k, v):
        self.items[k] = v

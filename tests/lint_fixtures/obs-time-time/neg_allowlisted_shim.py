# rel: fairify_tpu/obs/trace.py
import time


def wall_clock():
    # This rel is the allowlisted obs clock shim (ALLOW_TIME_TIME).
    return time.time()


def monotonic():
    return time.perf_counter()

# rel: fairify_tpu/verify/fx_time.py
import time


def slow_phase():
    t0 = time.time()  # EXPECT
    return t0

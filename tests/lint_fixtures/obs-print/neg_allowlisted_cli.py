# rel: fairify_tpu/cli.py
def render(rows):
    # The CLI renders user-facing output: allowlisted (ALLOW_PRINT).
    for r in rows:
        print(r)

# rel: fairify_tpu/verify/fx_print.py
def progress(i):
    print(f"partition {i}")  # EXPECT

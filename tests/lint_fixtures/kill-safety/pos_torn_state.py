# rel: fairify_tpu/serve/fx_torn.py
import threading

from fairify_tpu.resilience import faults as faults_mod


class Router:
    """Kill hazards: a chaos yield point between two guarded mutations
    (the `with` releases on ReplicaKilled with the invariant torn), and
    a manual acquire that leaks the lock on any exception."""

    def __init__(self):
        self._lock = threading.Lock()
        self._owner = None
        self._count = 0
        self._x = 0

    def rehome(self, req):
        with self._lock:
            self._owner = req.id
            faults_mod.check("replica.lost")  # EXPECT
            self._count = self._count + 1

    def manual(self):
        self._lock.acquire()  # EXPECT
        self._x = 1
        self._lock.release()

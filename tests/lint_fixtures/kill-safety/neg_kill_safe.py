# rel: fairify_tpu/serve/fx_killsafe.py
import threading

from fairify_tpu.resilience import faults as faults_mod


class Router:
    """Kill-safe shapes: a single mutation next to the yield point (no
    torn pair), and manual acquire wrapped in try/finally."""

    def __init__(self):
        self._lock = threading.Lock()
        self._owner = None
        self._x = 0

    def rehome(self, req):
        with self._lock:
            faults_mod.check("replica.lost")
            self._owner = req.id

    def manual(self):
        self._lock.acquire()
        try:
            self._x = 1
        finally:
            self._lock.release()

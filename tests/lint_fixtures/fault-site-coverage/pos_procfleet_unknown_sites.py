# rel: fairify_tpu/serve/fx_procfleet_typos.py
from fairify_tpu.resilience import faults as faults_mod


def spawn_and_sweep_typoed(slots):
    # Misspelled process-fleet sites: every --inject-fault spec targeting
    # them is rejected at the CLI while these paths run unprotected.
    for _slot in slots:
        faults_mod.check("replica.spwan")  # EXPECT
    faults_mod.check("replica.leese")  # EXPECT

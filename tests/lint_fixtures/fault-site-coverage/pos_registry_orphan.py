# rel: fairify_tpu/resilience/faults.py
FAULT_SITES = frozenset({"demo.used", "demo.orphan", "shard.dispatch",  # EXPECT
                         "shard.gather", "device.lost", "request.admit",
                         "request.deadline", "serve.drain",
                         "request.preempt", "replica.lost",
                         "replica.spawn", "replica.lease",
                         "smt.worker.spawn", "smt.worker.crash",
                         "smt.worker.hang", "smt.worker.memout"})
FAULT_KINDS = frozenset({"transient", "fatal", "crash"})

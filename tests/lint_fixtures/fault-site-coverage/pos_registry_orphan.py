# rel: fairify_tpu/resilience/faults.py
FAULT_SITES = frozenset({"demo.used", "demo.orphan"})  # EXPECT
FAULT_KINDS = frozenset({"transient", "fatal", "crash"})

# rel: fairify_tpu/resilience/faults.py
FAULT_SITES = frozenset({"demo.used", "demo.orphan", "shard.dispatch",  # EXPECT
                         "shard.gather", "device.lost", "request.admit",
                         "request.deadline", "serve.drain"})
FAULT_KINDS = frozenset({"transient", "fatal", "crash"})

# rel: fairify_tpu/serve/fx_fleet.py
from fairify_tpu.resilience import faults as faults_mod


def health_sweep_and_yield(replicas, running):
    # Literal anchors for the overload-survival sites: the fleet router's
    # per-replica health check and the server's span-granule preemption
    # decision each stay a named chaos-injectable site.
    for _replica in replicas:
        faults_mod.check("replica.lost")
    if running:
        faults_mod.check("request.preempt")

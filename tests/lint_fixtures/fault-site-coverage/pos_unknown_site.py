# rel: fairify_tpu/verify/fx_sites.py
from fairify_tpu.resilience import faults


def instrumented():
    faults.check("demo.used")
    faults.check("demo.bogus")  # EXPECT

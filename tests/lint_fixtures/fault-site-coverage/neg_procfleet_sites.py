# rel: fairify_tpu/serve/fx_procfleet.py
from fairify_tpu.resilience import faults as faults_mod


def spawn_and_sweep(slots, lease_s):
    # Literal anchors for the process-fleet sites: the router's replica
    # fork and its file-lease heartbeat check each stay a named
    # chaos-injectable site (DESIGN.md §18).
    for _slot in slots:
        faults_mod.check("replica.spawn")
    if lease_s > 0:
        faults_mod.check("replica.lease")

# rel: fairify_tpu/smt/fx_pool_typos.py
from fairify_tpu.resilience import faults


def dispatch_typoed(send):
    # Misspelled pool sites: every --inject-fault spec targeting them is
    # rejected at the CLI while these paths run unprotected.
    faults.check("smt.worker.crashed")  # EXPECT
    faults.check("smt.worker.oom")  # EXPECT
    return send()

# rel: fairify_tpu/resilience/fx_journal.py
def open_ledger(journal_cls, path, site=None):
    # fault_site= literals count as coverage (the JournalWriter contract);
    # supervisor.run(..., site=...) labels do not.
    return journal_cls(path, fault_site=site or "demo.used")


def open_shard(journal_cls, path, op):
    # A dynamic (f-string) site is intentionally uncounted: its fragments
    # ("demo.") must not be collected as literal site names.
    return journal_cls(path, fault_site=f"demo.{op}")

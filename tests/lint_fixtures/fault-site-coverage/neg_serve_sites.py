# rel: fairify_tpu/serve/fx_serve.py
from fairify_tpu.resilience import faults as faults_mod


def admit_and_run(request, run):
    # Literal anchors for the service sites: admission decisions, the
    # per-request deadline check, and graceful drain each stay a named
    # chaos-injectable site.
    faults_mod.check("request.admit")
    faults_mod.check("request.deadline")
    rep = run(request)
    faults_mod.check("serve.drain")
    return rep

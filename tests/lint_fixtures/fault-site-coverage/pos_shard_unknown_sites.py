# rel: fairify_tpu/parallel/fx_shard_typos.py
from fairify_tpu.resilience import faults


def dispatch_shard_typoed(journal_cls, path, run):
    # Misspelled shard-runtime sites: every spec targeting them is rejected
    # at the CLI while these paths run unprotected — each must be flagged.
    faults.check("shard.dispach")  # EXPECT
    rep = run()
    faults.check("device.gone")  # EXPECT
    journal_cls(path, fault_site="shard.gathr")  # EXPECT
    return rep

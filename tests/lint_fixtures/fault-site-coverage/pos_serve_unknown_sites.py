# rel: fairify_tpu/serve/fx_serve_typos.py
from fairify_tpu.resilience import faults as faults_mod


def admit_and_run_typoed(request, run):
    # Misspelled service sites: every --inject-fault spec targeting them
    # is rejected at the CLI while these paths run unprotected.
    faults_mod.check("request.admitt")  # EXPECT
    rep = run(request)
    faults_mod.check("serve.drained")  # EXPECT
    return rep

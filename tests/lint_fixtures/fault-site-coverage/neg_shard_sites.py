# rel: fairify_tpu/parallel/fx_shards.py
from fairify_tpu.resilience import faults


def dispatch_shard(run):
    # Literal anchors for the shard-runtime sites: each registered site
    # keeps >=1 literal call site, so chaos coverage never silently drops.
    faults.check("device.lost")
    faults.check("shard.dispatch")
    rep = run()
    faults.check("shard.gather")
    return rep

# rel: fairify_tpu/serve/fx_fleet_typos.py
from fairify_tpu.resilience import faults as faults_mod


def health_sweep_typoed(replicas):
    # Misspelled fleet sites: every --inject-fault spec targeting them is
    # rejected at the CLI while these paths run unprotected.
    for _replica in replicas:
        faults_mod.check("replica.lose")  # EXPECT
    faults_mod.check("request.preemptt")  # EXPECT

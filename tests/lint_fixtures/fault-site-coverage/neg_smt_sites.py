# rel: fairify_tpu/smt/fx_pool.py
from fairify_tpu.resilience import faults as faults_mod


def spawn_and_dispatch(spawn, send):
    # Literal anchors for the SMT worker-pool sites: spawning a solver
    # subprocess, and the three dispatch-path chaos conversions (SIGKILL
    # mid-query / wedge past deadline / allocate past the RSS cap) each
    # stay a named chaos-injectable site.
    faults_mod.check("smt.worker.spawn")
    w = spawn()
    faults_mod.check("smt.worker.crash")
    faults_mod.check("smt.worker.hang")
    faults_mod.check("smt.worker.memout")
    return send(w)

# rel: fairify_tpu/verify/fx_rawjit.py
from functools import partial

import jax


@jax.jit  # EXPECT
def a(x):
    return x


b = jax.jit(lambda x: x)  # EXPECT


@partial(jax.jit, static_argnames=("k",))  # EXPECT
def c(x, k):
    return x

# rel: fairify_tpu/verify/fx_obsjit.py
from fairify_tpu.obs import obs_jit


@obs_jit(static_argnames=("k",))
def registered(x, k):
    return x

# rel: fairify_tpu/models/fx_train.py
import jax


@jax.jit
def train_step(params, batch):
    # models/ trains ad-hoc nets; the rule protects verify/ + ops/ only.
    return params

# rel: fairify_tpu/verify/fx_pure.py
from fairify_tpu import obs
from fairify_tpu.obs import obs_jit
from fairify_tpu.utils import profiling

results = []
totals = {}


@obs_jit
def impure_kernel(x):
    print("tracing", x)  # EXPECT
    obs.event("kernel", n=1)  # EXPECT
    profiling.bump_launch()  # EXPECT
    results.append(x)  # EXPECT
    totals["x"] = x  # EXPECT
    return x


def make_counter():
    acc = 0

    @obs_jit
    def kernel(x):
        nonlocal acc  # EXPECT
        acc = acc + 1
        return x

    return kernel

# rel: fairify_tpu/verify/fx_pure_ok.py
from fairify_tpu.obs import obs_jit


@obs_jit(static_argnames=("n",))
def pure_kernel(optimizer, x, state, n):
    ys = []
    for i in range(n):
        ys.append(x * i)  # kernel-local list: trace-local, fine
    scratch = {}
    scratch["m"] = x  # kernel-local dict: fine
    updates, state = optimizer.update(x, state)  # optax-style: pure
    return sum(ys), updates, state


def host_progress(i):
    print("host", i)  # not a jitted body: obs-print's business, not ours

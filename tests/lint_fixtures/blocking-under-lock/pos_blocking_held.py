# rel: fairify_tpu/serve/fx_blocking.py
import subprocess
import threading
import time


class Worker:
    """Blocking operations while holding the queue lock: direct sleep,
    a subprocess wait, and one reached through a call chain."""

    def __init__(self):
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._flush)
        self.items = []

    def direct_sleep(self):
        with self._lock:
            time.sleep(0.1)  # EXPECT

    def run_tool(self):
        with self._lock:
            subprocess.run(["true"], check=True)  # EXPECT

    def join_worker(self):
        with self._lock:
            self._thread.join()  # EXPECT

    def via_call(self):
        with self._lock:
            self._flush()  # EXPECT

    def _flush(self):
        # No lock held HERE — the finding belongs at the call site above,
        # where the lock is actually held.
        time.sleep(0.05)

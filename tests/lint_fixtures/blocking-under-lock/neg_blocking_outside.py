# rel: fairify_tpu/serve/fx_nonblocking.py
import threading
import time


class Worker:
    """Blocking work staged outside the `with` block is the fix the rule
    asks for: snapshot under the lock, block after releasing."""

    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def drain(self):
        with self._lock:
            batch = list(self.items)
            self.items = []
        time.sleep(0.1)
        return batch

    def pure_bookkeeping(self):
        with self._lock:
            self.items.append(1)
            return len(self.items)

# rel: fairify_tpu/serve/fx_frames.py
"""Frame writers that provably drop the trace context — every flagged
line hands a dict LITERAL without trace fields (and not a reviewed
control frame) to a cross-boundary writer."""
import json
import sys

from fairify_tpu.smt import protocol
from fairify_tpu.serve.client import write_atomic_json


def solve_frame_without_trace(pipe, qid):
    # A per-request pipe frame built inline: 'solve' is NOT a control op.
    pipe.write(protocol.dump_msg({"op": "solve", "qid": qid}))  # EXPECT


def hand_rolled_newline_framing(chan, qid, verdict):
    chan.write(json.dumps({"qid": qid, "verdict": verdict}) + "\n")  # EXPECT


def spool_payload_without_trace(inbox, req_id, cfg):
    write_atomic_json(inbox + "/" + req_id + ".json",
                      {"id": req_id, "cfg": cfg})  # EXPECT


def send_helper_with_literal_result(send, qid, ce):
    send({"qid": qid, "verdict": "sat", "ce": ce})  # EXPECT

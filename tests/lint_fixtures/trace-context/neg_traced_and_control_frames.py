# rel: fairify_tpu/serve/fx_frames_ok.py
"""The compliant shapes: traced frames, reviewed control frames,
pass-through writers, and opaque (undecidable) payloads."""
import json

from fairify_tpu.smt import protocol
from fairify_tpu.serve.client import write_atomic_json


def traced_solve_frame(pipe, qid, ctx_fields):
    pipe.write(protocol.dump_msg(
        {"op": "solve", "qid": qid, "trace": ctx_fields["trace"]}))


def trace_id_variant(chan, qid, tid):
    chan.write(json.dumps({"qid": qid, "trace_id": tid}) + "\n")


def control_frames(send):
    send({"op": "ping"})
    send({"op": "drained", "replica": 0, "requeued": []})
    send({"hello": True, "pid": 1234})
    send({"qid": None, "error": "unknown op"})


def pass_through_writer(pipe, obj):
    # The frame is a parameter: this is plumbing, the constructor is the
    # responsible party.
    pipe.write(protocol.dump_msg(obj))


def opaque_payload(inbox, req_id):
    payload = load_payload(req_id)  # noqa: F821 — fixture-only
    write_atomic_json(inbox + "/" + req_id + ".json", payload)


def spread_may_carry_trace(send, qid, extra):
    send({"qid": qid, **extra})


def status_record_by_name(rdir, rec):
    write_atomic_json(rdir + "/status.json", rec)

# rel: fairify_tpu/verify/fx_hazard.py
import jax

from fairify_tpu.obs import obs_jit


@obs_jit(static_argnames=("size", "flavor"))  # EXPECT
def typo_kernel(x, size):
    return x


@obs_jit(static_argnames=("eps",))
def float_static(x, eps: float = 1e-3):  # EXPECT
    return x


@obs_jit
def traced_branch(x, y):
    if x > 0:  # EXPECT
        return y
    return -y


@obs_jit(static_argnames=("chunk",))
def chunked(x, chunk):
    return x


def sweep_over(xs):
    outs = []
    for n in range(8):
        outs.append(chunked(xs, chunk=n))  # EXPECT
    return outs


def relaunch(fns, x):
    for f in fns:
        g = jax.jit(f)  # EXPECT
        x = g(x)
    return x

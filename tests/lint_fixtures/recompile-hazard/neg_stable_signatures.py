# rel: fairify_tpu/verify/fx_hazard_ok.py
import jax.numpy as jnp

from fairify_tpu.obs import obs_jit


@obs_jit(static_argnames=("n", "with_sim"))
def stable_kernel(net, x, n, with_sim=True):
    ys = x if with_sim else -x  # static conditional: fine
    if x is None:  # identity on the Python object: concrete
        return ys
    if x.ndim == 2:  # shape introspection: concrete under tracing
        ys = ys[None]
    if len(x) > 3:  # len() is concrete
        ys = ys * 2
    return jnp.where(x > 0, ys, -ys)  # traced select belongs in the graph


def drive(xs):
    out = []
    for x in xs:
        # Constant static per call — the loop variable feeds a TRACED slot.
        out.append(stable_kernel(None, x, 4))
    return out

# rel: fairify_tpu/verify/fx_broad_ok.py
def narrow():
    try:
        work()
    except ValueError:
        pass


def reraises():
    try:
        work()
    except Exception:
        raise


def classified(classify):
    try:
        work()
    except Exception as exc:
        if classify(exc) == "propagate":
            raise
        record(exc)


def work():
    pass


def record(exc):
    pass

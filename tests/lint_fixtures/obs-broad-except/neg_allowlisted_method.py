# rel: fairify_tpu/obs/compile.py
class ObsJit:
    def __call__(self):
        # fairify_tpu/obs/compile.py::__call__ is an ALLOW_BROAD_EXCEPT
        # entry (reviewed compile fallback).
        try:
            return run()
        except Exception:
            return None


def run():
    pass

# rel: fairify_tpu/verify/fx_broad.py
def swallow_bare():
    try:
        work()
    except:  # EXPECT
        pass


def swallow_base():
    try:
        work()
    except BaseException:  # EXPECT
        cleanup = 1


class Widget:
    # Class-body handler: attributed to 'Widget', never to the enclosing
    # module/function allowlist key (the old walker got this wrong).
    try:
        import optional_dep
    except Exception:  # EXPECT
        optional_dep = None


def work():
    pass

# rel: scripts/other_tool.py
"""A spec literal OUTSIDE scripts/chaos_matrix.py does not count as
chaos coverage (and is not itself a finding) — only the driver's cells
keep a site honest."""

REPRO = "demo.lost:transient:1"

# rel: scripts/chaos_matrix.py
"""Fixture chaos driver: covers demo.used, references an unknown site.

(`demo.lost` is registered but has no cell here and no exemption — that
finding lands on the registry's FAULT_SITES line; the shard.* sites are
CHAOS_EXEMPT, so their absence is fine.  `smt.query` is covered by a
``corrupt``-kind integrity cell, the ISSUE 19 vocabulary — a corrupt
spec counts as coverage exactly like the older kinds, and a corrupt spec
naming an unknown site is flagged exactly like them too.)
"""

SCHEDULES = [
    ("demo.used", "transient", "demo.used:transient:2"),
    ("nope.site", "transient", "nope.site:transient:1"),  # EXPECT
]

# Result-integrity cells (--integrity, DESIGN.md §21): corrupt-kind specs.
INTEGRITY_SPECS = [
    "smt.query:corrupt:1+",
    "nope.flip:corrupt:1",  # EXPECT
]

# Process-fleet style cells (full spec literals, the shape the real
# --procfleet section uses): these keep replica.spawn / replica.lease
# covered in the fixture registry.
PROCFLEET_SPECS = [
    "replica.spawn:transient:1",
    "replica.lease:fatal:1",
]

# rel: fairify_tpu/resilience/faults.py
FAULT_SITES = frozenset({"demo.used", "demo.lost", "smt.query",  # EXPECT
                         "shard.dispatch", "shard.gather",
                         "replica.spawn", "replica.lease"})
FAULT_KINDS = frozenset({"transient", "fatal", "crash"})

# rel: fairify_tpu/serve/fx_cv_bad.py
import threading


class Box:
    """Condition misuse: wait guarded by `if` (spurious wakeup / ignored
    wait(timeout) return runs the pop on an empty box), and notify
    without holding (RuntimeError at runtime)."""

    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def take_bad(self):
        with self._cv:
            if not self._items:
                self._cv.wait(1.0)  # EXPECT
            return self._items.pop()

    def wake_bad(self, item):
        self._items.append(item)
        self._cv.notify_all()  # EXPECT

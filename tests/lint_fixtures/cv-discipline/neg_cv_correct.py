# rel: fairify_tpu/serve/fx_cv_good.py
import threading


class Box:
    """The correct shapes: while-predicate wait, notify under the cv."""

    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def take(self):
        with self._cv:
            while not self._items:
                self._cv.wait(0.5)
            return self._items.pop()

    def put(self, item):
        with self._cv:
            self._items.append(item)
            self._cv.notify_all()

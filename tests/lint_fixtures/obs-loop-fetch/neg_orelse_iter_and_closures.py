# rel: fairify_tpu/verify/fx_fetch_ok.py
import numpy as np


def cold(chunks, dev):
    for c in chunks:
        pass
    else:
        final = np.asarray(dev)  # for-else runs once, not per iteration
    for row in np.asarray(dev):  # the iterable evaluates once
        pass

    def decode(x):
        # Nested def resets the loop context: this is the pipeline's
        # drain path, handed HOST payloads.
        return np.asarray(x)

    for c in chunks:
        decode(c)
    last = np.asarray(dev)  # not in a loop at all
    return final, last

# rel: fairify_tpu/verify/engine.py
import numpy as np


def decide_many(frontier):
    # engine.py::decide_many is an ALLOW_LOOP_FETCH entry (sequentially
    # dependent BaB iterations).
    while frontier:
        frontier = np.asarray(frontier)
    return frontier

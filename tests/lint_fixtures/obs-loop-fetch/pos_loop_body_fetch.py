# rel: fairify_tpu/verify/fx_fetch.py
import jax
import numpy as np


def hot(chunks, dev):
    out = []
    for c in chunks:
        out.append(np.asarray(c))  # EXPECT
    while dev:
        dev = jax.device_get(dev)  # EXPECT
    for c in chunks:
        c.block_until_ready()  # EXPECT
    return out

# rel: fairify_tpu/serve/fx_cycle.py
import threading


class Pair:
    """Two methods acquire the same two locks in opposite order: thread 1
    in ab() holding _a while thread 2 in ba() holds _b deadlocks."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def ab(self):
        with self._a:
            with self._b:  # EXPECT
                self.n = 1

    def ba(self):
        with self._b:
            with self._a:
                self.n = 2

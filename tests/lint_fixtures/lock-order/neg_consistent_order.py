# rel: fairify_tpu/serve/fx_ordered.py
import threading


class Ordered:
    """Both paths take _a before _b — nesting is fine when the global
    order is consistent."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def fast(self):
        with self._a:
            with self._b:
                self.n = 1

    def slow(self):
        with self._a:
            self.n = 2
            with self._b:
                self.n = 3

"""Round-2 engine upgrades: tied pair-difference certificate, β-CROWN-style
sign-constrained bounds, uniform-sign BaB, and the LP leaf endgame.

Oracle style follows tests/test_engine.py: tiny domains where exact
brute-force enumeration is feasible, deliberately re-deriving the property
semantics independently of the engine code.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from fairify_tpu.models import mlp
from fairify_tpu.ops import crown as crown_ops
from fairify_tpu.verify import engine
from fairify_tpu.verify import property as prop
from fairify_tpu.data.domains import DomainSpec


def tiny_domain(ranges):
    cols = tuple(ranges)
    return DomainSpec(name="toy", columns=cols,
                      ranges={k: tuple(v) for k, v in ranges.items()}, label="y")


def random_net(rng, sizes, pa_scale=1.0):
    ws, bs = [], []
    for a, b in zip(sizes[:-1], sizes[1:]):
        ws.append(rng.normal(size=(a, b)).astype(np.float32))
        bs.append((rng.normal(size=(b,)) * 0.5).astype(np.float32))
    return mlp.from_numpy(ws, bs)


def brute_force_flip(net, enc, lo, hi):
    """Exhaustive exact flip search on the integer lattice (independent oracle)."""
    import itertools as it

    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    free = [d for d in range(len(lo)) if d not in set(int(j) for j in enc.pa_idx)]
    deltas = (list(it.product(range(-enc.eps, enc.eps + 1), repeat=len(enc.ra_idx)))
              if len(enc.ra_idx) and enc.eps else [tuple()])
    for pt in it.product(*(range(int(lo[d]), int(hi[d]) + 1) for d in free)):
        base = np.zeros(len(lo), dtype=np.int64)
        base[free] = pt
        for a in range(enc.n_assign):
            if not ((lo[enc.pa_idx] <= enc.assignments[a]).all()
                    and (enc.assignments[a] <= hi[enc.pa_idx]).all()):
                continue
            x = base.copy()
            x[enc.pa_idx] = enc.assignments[a]
            sx = engine.exact_logit_sign(W, B, x)
            if sx == 0:
                continue
            for b in range(enc.n_assign):
                if not enc.valid_pair[a, b]:
                    continue
                if not ((lo[enc.pa_idx] <= enc.assignments[b]).all()
                        and (enc.assignments[b] <= hi[enc.pa_idx]).all()):
                    continue
                for dl in deltas:
                    xp = base.copy()
                    xp[enc.pa_idx] = enc.assignments[b]
                    for k, dv in enumerate(dl):
                        xp[enc.ra_idx[k]] += dv
                    sp = engine.exact_logit_sign(W, B, xp)
                    if (sx > 0 and sp < 0) or (sx < 0 and sp > 0):
                        return True
    return False


@pytest.mark.parametrize("seed", range(6))
def test_tied_diff_certificate_sound(seed):
    """A box certified by the combined (role-bound + tied-diff) certificate
    must contain no exact flip pair — checked by brute force."""
    rng = np.random.default_rng(seed)
    dom = tiny_domain({"a": (0, 4), "pa": (0, 2), "ra": (0, 4)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",),
                               relaxed=("ra",), relax_eps=1)
    enc = prop.encode(query)
    net = random_net(rng, (3, 8, 5, 1))
    # Damp PA sensitivity so a meaningful fraction of trials certify.
    ws = [np.asarray(w).copy() for w in net.weights]
    ws[0][1, :] *= 0.01
    net = mlp.from_numpy(ws, [np.asarray(b) for b in net.biases])
    lo, hi = dom.lo_hi()
    lo = lo.astype(np.int64)[None, :]
    hi = hi.astype(np.int64)[None, :]
    x_lo, x_hi, xp_lo, xp_hi, valid = prop.role_boxes(
        enc, lo.astype(np.float32), hi.astype(np.float32))
    av, pm, rm = engine._enc_tensors(enc, 3)
    cert, score, _margin = engine._role_certify_kernel(
        net, jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xp_lo),
        jnp.asarray(xp_hi), jnp.asarray(lo, jnp.float32),
        jnp.asarray(hi, jnp.float32), jnp.asarray(av), jnp.asarray(pm),
        jnp.asarray(rm), float(enc.eps), jnp.asarray(valid),
        jnp.asarray(enc.valid_pair), alpha_iters=4)
    assert np.asarray(score).shape == (1, 3)
    if bool(np.asarray(cert)[0]):
        assert not brute_force_flip(net, enc, lo[0], hi[0])


@pytest.mark.parametrize("seed", range(5))
def test_sign_constrained_bounds_sound(seed):
    """Constrained bounds must contain f(x) for every sampled x that
    satisfies the branch sign pattern."""
    rng = np.random.default_rng(50 + seed)
    net = random_net(rng, (4, 10, 6, 1))
    lo = np.zeros((1, 4), dtype=np.float32)
    hi = np.full((1, 4), 4.0, dtype=np.float32)
    sizes = [10, 6]
    signs = [np.zeros((1, n), dtype=np.float32) for n in sizes]
    # Random split pattern on a few neurons.
    for _ in range(3):
        j = rng.integers(2)
        signs[j][0, rng.integers(sizes[j])] = rng.choice([-1.0, 1.0])
    out_lo, out_hi, feas, scores, resolved = crown_ops.sign_constrained_output_bounds(
        net, jnp.asarray(lo), jnp.asarray(hi),
        tuple(jnp.asarray(s) for s in signs), alpha_iters=6)
    out_lo, out_hi = float(np.asarray(out_lo)[0]), float(np.asarray(out_hi)[0])
    # Sample points, keep those satisfying the pattern, check containment.
    X = rng.uniform(0.0, 4.0, size=(4000, 4)).astype(np.float32)
    pre = mlp.preactivations(net, jnp.asarray(X))
    keep = np.ones(len(X), dtype=bool)
    for j in range(2):
        z = np.asarray(pre[j])
        s = signs[j][0]
        keep &= ((s == 0) | (s * z >= 0)).all(axis=1)
    if keep.any():
        f = np.asarray(mlp.forward(net, jnp.asarray(X[keep])))
        assert f.min() >= out_lo - 1e-3
        assert f.max() <= out_hi + 1e-3
    for rv, n in zip(resolved, sizes):
        assert np.asarray(rv).shape == (1, n)


def test_uniform_sign_bab_positive_net():
    """A net whose logit is provably positive everywhere → 'unsat' roots."""
    rng = np.random.default_rng(7)
    ws = [rng.normal(size=(3, 6)).astype(np.float32) * 0.1,
          rng.normal(size=(6, 1)).astype(np.float32) * 0.1]
    bs = [np.zeros(6, dtype=np.float32), np.full(1, 5.0, dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    dom = tiny_domain({"a": (0, 6), "pa": (0, 1), "b": (0, 6)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",))
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    roots_lo = np.stack([lo, lo]).astype(np.int64)
    roots_hi = np.stack([hi, hi]).astype(np.int64)
    from fairify_tpu.verify.engine import EngineConfig, uniform_sign_bab

    verdicts, nodes, cost, _lp = uniform_sign_bab(
        net, enc, roots_lo, roots_hi,
        EngineConfig(alpha_iters=4), deadline_s=60.0)
    assert verdicts == ["unsat", "unsat"]
    # ADVICE r2: sign-phase work must be attributed to the roots it served.
    assert (nodes >= 1).all()
    assert (cost > 0.0).all()


def test_uniform_sign_bab_mixed_net_bails():
    """A net with an obvious sign change must not be certified 'unsat'.

    Needs a hidden layer: depth-1 nets take the n_hidden == 0 early-exit
    and would pass vacuously without exercising the sampling bail.
    """
    # f = relu(a) - 3: mixed sign over a ∈ [0, 6] (f(0) = -3, f(6) = +3).
    ws = [np.array([[1.0], [0.0], [0.0]], dtype=np.float32),
          np.array([[1.0]], dtype=np.float32)]
    bs = [np.array([0.0], dtype=np.float32),
          np.array([-3.0], dtype=np.float32)]
    net = mlp.from_numpy(ws, bs)
    dom = tiny_domain({"a": (0, 6), "pa": (0, 1), "b": (0, 6)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",))
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    from fairify_tpu.verify.engine import EngineConfig, uniform_sign_bab

    verdicts, _, _, _ = uniform_sign_bab(net, enc, lo.astype(np.int64)[None],
                                         hi.astype(np.int64)[None],
                                         EngineConfig(alpha_iters=4), deadline_s=30.0)
    assert verdicts == ["mixed"]


@pytest.mark.parametrize("seed", range(4))
def test_tied_diff_slack_covers_wide_domains(seed):
    """ADVICE r2 (medium): the tied-diff outward slack must scale with the
    concretized term magnitudes, not the cancelled bound value.

    On wide integer domains the f32 per-dim products D·hi are huge while the
    netted bound is near zero; the widened f32 bound must still dominate the
    exact f64 supremum of (pos-form − neg-form) over tied coordinates."""
    from fairify_tpu.ops.interval import SOUND_SLACK_ABS, SOUND_SLACK_REL

    rng = np.random.default_rng(100 + seed)
    B, V, d = 3, 2, 6
    lo = np.zeros((B, d), dtype=np.float64)
    hi = np.full((B, d), 1e6, dtype=np.float64)
    # Nearly-cancelling forms: A_neg = A_pos + tiny perturbation.
    A_pos = rng.normal(size=(B, V, d)).astype(np.float32)
    pert = (rng.normal(size=(B, V, d)) * 1e-7).astype(np.float32)
    A_neg = A_pos + pert
    c_pos = rng.normal(size=(B, V)).astype(np.float32)
    c_neg = c_pos + (rng.normal(size=(B, V)) * 1e-7).astype(np.float32)
    shared = np.ones(d, dtype=np.float32)
    m, _, g = engine._tied_diff_ub(
        jnp.asarray(A_pos), jnp.asarray(c_pos), jnp.asarray(A_neg),
        jnp.asarray(c_neg), jnp.asarray(lo, jnp.float32),
        jnp.asarray(hi, jnp.float32), jnp.asarray(shared))
    widened = np.asarray(m) + SOUND_SLACK_REL * np.asarray(g) + SOUND_SLACK_ABS
    # Exact supremum in f64: per-dim max of D_j·s_j over [lo_j, hi_j].
    D = A_pos.astype(np.float64)[:, :, None, :] - A_neg.astype(np.float64)[:, None, :, :]
    sup = np.where(D > 0, D * hi[:, None, None, :], D * lo[:, None, None, :]).sum(-1) \
        + c_pos.astype(np.float64)[:, :, None] - c_neg.astype(np.float64)[:, None, :]
    assert (widened >= sup - 1e-12).all()
    # The magnitude term must reflect the concretized scale: ≥ the f64
    # recomputation of Σ_j |D_j|·max(|lo_j|,|hi_j|) (up to f32 rounding).
    mag64 = (np.abs(D) * np.maximum(np.abs(lo), np.abs(hi))[:, None, None, :]).sum(-1)
    assert (np.asarray(g, np.float64) >= (1 - 1e-5) * mag64).all()


def test_leaf_sign_lp_exact():
    """LP endgame on a fully-resolved pattern matches brute-force region min."""
    rng = np.random.default_rng(11)
    ws = [rng.normal(size=(2, 3)).astype(np.float32),
          rng.normal(size=(3, 1)).astype(np.float32)]
    bs = [rng.normal(size=(3,)).astype(np.float32),
          np.array([2.0], dtype=np.float32)]
    lo = np.zeros(2)
    hi = np.full(2, 5.0)
    masks = [np.ones(3, dtype=np.float32), np.ones(1, dtype=np.float32)]
    # Brute-force the true pattern-region minimum on a fine grid.
    gx, gy = np.meshgrid(np.linspace(0, 5, 201), np.linspace(0, 5, 201))
    X = np.stack([gx.ravel(), gy.ravel()], axis=1)
    z = X @ ws[0] + bs[0]
    for pattern in ([1, 1, 1], [1, -1, 1], [-1, -1, -1]):
        sat = ((np.array(pattern) * z) >= 0).all(axis=1)
        outcome = engine._leaf_sign_lp(ws, bs, masks, [np.array(pattern)],
                                       lo, hi, want_positive=True)
        if not sat.any():
            assert outcome in ("infeasible", "certified", "mixed")
            continue
        h = np.maximum(z[sat], 0.0) * (np.array(pattern) > 0)
        f = h @ ws[1] + bs[1]
        true_min = f.min()
        if outcome == "certified":
            assert true_min > -1e-4
        elif outcome == "infeasible":
            assert not sat.any()


def test_decide_leaf_ra_lattice_guard():
    """An exponential RA delta lattice degrades to 'unknown', not a stall."""
    dom = tiny_domain({"pa": (0, 1), "r1": (0, 9), "r2": (0, 9), "r3": (0, 9)})
    query = prop.FairnessQuery(domain=dom, protected=("pa",),
                               relaxed=("r1", "r2", "r3"), relax_eps=30)
    enc = prop.encode(query)
    net = random_net(np.random.default_rng(0), (4, 3, 1))
    W = [np.asarray(w) for w in net.weights]
    B = [np.asarray(b) for b in net.biases]
    point = np.array([0, 3, 3, 3], dtype=np.int64)
    verdict, ce = engine.decide_leaf(enc, W, B, point, point, point)
    assert verdict == "unknown" and ce is None

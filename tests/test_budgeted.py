"""Budgeted attempt-until-hard-budget sweep (scripts/_sweeplib.py).

Reference semantics under test: a contiguous attempted prefix of the
shuffled grid, coverage reported instead of UNKNOWN-padding, per-config
ledgers, and resume that continues the prefix rather than restarting.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

import _sweeplib  # noqa: E402

from fairify_tpu.models.train import init_mlp
from fairify_tpu.verify import presets, sweep


def _cfg(tmp_path, hard):
    return presets.get("GC").with_(
        soft_timeout_s=2.0, hard_timeout_s=hard,
        result_dir=str(tmp_path / "out"), grid_chunk=64)


def test_budgeted_full_coverage(tmp_path):
    """A generous budget attempts the whole grid: cov == 1.0."""
    net = init_mlp((20, 6, 1), seed=1)
    rec = _sweeplib.budgeted_model_sweep(_cfg(tmp_path, 600.0), net, "m")
    assert rec["attempted"] == rec["partitions"] == 201
    assert rec["cov"] == 1.0
    assert rec["sat"] + rec["unsat"] + rec["unknown"] == 201


def test_budgeted_prefix_and_ledger_dirs(tmp_path):
    """An exhausted budget attempts a proper prefix (here: nothing) with no
    UNKNOWN-padding of the unattempted tail; per-config ledger dirs keep
    different budgets from resuming into each other.  (A wall-clock-based
    partial prefix would be machine-speed dependent — a warm jit cache can
    legitimately finish the whole 201-box grid inside any nonzero budget —
    so the deterministic zero-budget edge pins the semantics instead.)"""
    net = init_mlp((20, 6, 1), seed=1)
    rec = _sweeplib.budgeted_model_sweep(_cfg(tmp_path, 0.0), net, "m")
    assert rec["attempted"] == 0 and rec["partitions"] == 201
    assert rec["cov"] == 0.0
    # Attempted counts only: the unattempted tail is coverage, not UNKNOWN.
    assert rec["sat"] + rec["unsat"] + rec["unknown"] == 0

    rec2 = _sweeplib.budgeted_model_sweep(_cfg(tmp_path, 600.0), net, "m")
    assert rec2["attempted"] == 201
    # Per-config result_dir suffixes: budgets never share ledgers.
    assert (tmp_path / "out" / "b2-600").is_dir()
    assert not (tmp_path / "out" / "b2-0").glob("*.ledger.jsonl") or \
        not list((tmp_path / "out" / "b2-0").glob("*.ledger.jsonl"))


def test_retry_span_unknowns_merges_and_counts_once(tmp_path):
    """The soft-budget retry pass merges ALL span ledgers decided-wins
    first (a pid any overlapping span decided is never re-counted),
    re-decides exactly the still-unknown pids, and appends the new
    verdicts to ONE span ledger tagged ``retry: soft`` (the glob-sorted
    last, which for this two-span fixture is the 64-128 file)."""
    import json

    net = init_mlp((20, 6, 1), seed=1)
    # Generous per-partition soft budget: the deadline passed to
    # decide_many is soft_timeout_s * n_unknown and includes cold-JIT
    # compile, so the default 2 s would make this assertion machine-speed
    # dependent (see test_budgeted_prefix_and_ledger_dirs's note).
    cfg = _cfg(tmp_path, 600.0).with_(soft_timeout_s=60.0)
    os.makedirs(cfg.result_dir, exist_ok=True)
    led_a = os.path.join(cfg.result_dir, f"{cfg.name}-m@0-64.ledger.jsonl")
    led_b = os.path.join(cfg.result_dir, f"{cfg.name}-m@64-128.ledger.jsonl")
    with open(led_a, "w") as fp:
        fp.write('{"partition_id": 1, "verdict": "sat"}\n')
        fp.write('{"partition_id": 2, "verdict": "unknown"}\n')
        fp.write('{"partition_id": 3, "verdict": "unknown"}\n')
    with open(led_b, "w") as fp:
        # pid 3 was decided by a crashed run's overlapping span: the merge
        # must treat it as settled even though ledger A holds it unknown.
        fp.write('{"partition_id": 3, "verdict": "unsat"}\n')
        fp.write('{"partition_id": 70, "verdict": "unknown"}\n')

    fixed = _sweeplib.retry_span_unknowns(cfg, net, "m", budget_s=60.0)

    # A 6-neuron net decides instantly: both genuine unknowns get verdicts.
    assert sum(fixed.values()) == 2
    retried = {}
    with open(led_b) as fp:
        for line in fp:
            rec = json.loads(line)
            if rec.get("retry") == "soft":
                retried[rec["partition_id"]] = rec["verdict"]
    assert set(retried) == {2, 70}
    assert all(v in ("sat", "unsat") for v in retried.values())
    # Ledger A untouched: the retry appends to one sink only.
    assert sum(1 for _ in open(led_a)) == 3


def test_config_key_distinguishes_budgets(tmp_path):
    results = tmp_path / "results.jsonl"
    with open(results, "w") as fp:
        fp.write('{"run_id": "x", "model": "m", "soft_s": 5.0, "hard_s": 60.0,'
                 ' "cap": null, "attempted": 10}\n')
        fp.write('{"run_id": "x", "model": "legacy", "soft_s": 5.0,'
                 ' "hard_s": 60.0}\n')
        fp.write('{"run_id": "x", "model": "sk", "skipped": "mismatch"}\n')
        fp.write('{"run_id": "x", "model": "tagged", "soft_s": 5.0,'
                 ' "hard_s": 60.0, "cap": null, "attempted": 10,'
                 ' "engine_tag": "r5"}\n')
    done = _sweeplib.done_set(str(results))
    # Untagged rows key with engine_tag None (ADVICE r4 #2: a harness
    # passing a fresh tag re-executes instead of resuming past them).
    assert ("x", "m", (5.0, 60.0, None, None)) in done
    assert ("x", "tagged", (5.0, 60.0, None, "r5")) in done
    assert ("x", "tagged", (5.0, 60.0, None, None)) not in done
    # Legacy rows (pre-cap/attempted fields) get a sentinel key: a new
    # full-grid run with the same budgets must NOT be skipped.
    assert ("x", "legacy", (5.0, 60.0, None, None)) not in done
    assert ("x", "legacy", ("legacy", 5.0, 60.0)) in done
    assert ("x", "sk", "skipped") in done

"""Synthetic-data generators (task1 analog): in-domain samples, learning, determinism."""
import numpy as np
import pytest

from fairify_tpu.models import synth


def _toy(n=400, seed=0):
    """Correlated integer data on a small lattice: x1 ~ x0, x2 independent."""
    rng = np.random.default_rng(seed)
    x0 = rng.integers(0, 4, size=n)
    x1 = np.clip(x0 + rng.integers(-1, 2, size=n), 0, 4)
    x2 = rng.integers(0, 2, size=n)
    return np.stack([x0, x1, x2], axis=1)


def test_copula_samples_in_support():
    X = _toy()
    cop = synth.GaussianCopula.fit(X)
    S = cop.sample(500, seed=1)
    assert S.shape == (500, 3)
    for j in range(3):
        assert set(np.unique(S[:, j])) <= set(np.unique(X[:, j]))


def test_copula_preserves_marginals_and_correlation():
    X = _toy(2000)
    S = synth.GaussianCopula.fit(X).sample(4000, seed=2)
    for j in range(3):
        assert abs(S[:, j].mean() - X[:, j].mean()) < 0.2
    r_real = np.corrcoef(X[:, 0], X[:, 1])[0, 1]
    r_syn = np.corrcoef(S[:, 0], S[:, 1])[0, 1]
    assert abs(r_real - r_syn) < 0.25 and r_syn > 0.3


def test_copula_deterministic():
    X = _toy()
    cop = synth.GaussianCopula.fit(X)
    assert np.array_equal(cop.sample(50, seed=7), cop.sample(50, seed=7))


def test_ar_model_learns_and_samples_in_domain():
    X = _toy(600)
    lo, hi = [0, 0, 0], [4, 4, 1]
    m = synth.ARColumnModel.init(lo, hi, hidden=32, seed=0)
    hist = m.fit(X, epochs=40, lr=5e-3, seed=0)
    assert hist[-1] < hist[0]  # loss decreased
    S = m.sample(400, seed=3)
    assert S.shape == (400, 3)
    assert (S >= np.array(lo)).all() and (S <= np.array(hi)).all()
    # learned the x0→x1 coupling direction
    r = np.corrcoef(S[:, 0], S[:, 1])[0, 1]
    assert r > 0.2


def test_ar_sampling_deterministic():
    m = synth.ARColumnModel.init([0, 0], [3, 3], hidden=16, seed=1)
    assert np.array_equal(m.sample(30, seed=5), m.sample(30, seed=5))


def test_bootstrap_rows_subset():
    X = _toy(100)
    B = synth.bootstrap_rows(X, 50, seed=0)
    rows = {tuple(r) for r in X}
    assert all(tuple(r) in rows for r in B)


def test_quantizer_roundtrip_and_support():
    rng = np.random.default_rng(0)
    wide = rng.integers(0, 20000, size=(500, 1))   # credit_amount-like
    narrow = rng.integers(0, 3, size=(500, 1))
    X = np.concatenate([wide, narrow], axis=1)
    q = synth.ColumnQuantizer.fit(X, max_card=16)
    assert q.card[0] <= 16 and q.card[1] == 3
    B = q.encode(X)
    assert (B >= 0).all() and (B < q.card[None, :]).all()
    # narrow column is identity-coded
    decoded = q.decode(B, seed=1)
    assert np.array_equal(decoded[:, 1], X[:, 1])
    # decoded wide values come from the observed support and the right bin
    support = set(np.unique(wide))
    assert all(v in support for v in decoded[:, 0])


def test_ar_handles_wide_columns_quickly():
    rng = np.random.default_rng(1)
    X = np.stack([rng.integers(0, 20000, size=300),
                  rng.integers(0, 2, size=300)], axis=1)
    S = synth.synthesize("ar", X, [0, 0], [19999, 1], 50, seed=0, ar_epochs=5)
    assert S.shape == (50, 2)
    assert set(np.unique(S[:, 0])) <= set(np.unique(X[:, 0]))


def test_synthesize_dispatch():
    X = _toy(200)
    lo, hi = [0, 0, 0], [4, 4, 1]
    for kind in synth.GENERATORS:
        S = synth.synthesize(kind, X, lo, hi, 40, seed=0, ar_epochs=5)
        assert S.shape == (40, 3)
    with pytest.raises(ValueError):
        synth.synthesize("ctgan", X, lo, hi, 10)

"""Partition-grid semantics vs the reference algorithm."""
import numpy as np

from fairify_tpu.data.domains import GERMAN
from fairify_tpu.partition import (
    boxes_from_partitions,
    coverage_fraction,
    partition_attributes,
    partition_attributes_capped,
    partition_density,
    partitioned_ranges,
    partitioned_ranges_capped,
)


def test_partition_chunks_wide_attributes_only():
    p = partition_attributes({"a": (0, 9), "b": (0, 100)}, 10)
    assert "a" not in p  # width 10 <= threshold
    assert p["b"] == [(0, 9), (10, 19), (20, 29), (30, 39), (40, 49),
                      (50, 59), (60, 69), (70, 79), (80, 89), (90, 99), (100, 100)]


def test_partition_chunks_cover_range_disjointly():
    p = partition_attributes({"x": (3, 47)}, 7)["x"]
    covered = []
    for lo, hi in p:
        covered.extend(range(lo, hi + 1))
    assert covered == list(range(3, 48))


def test_german_partition_count_matches_reference():
    # GC driver: threshold 100 chunks only credit_amount (0..20000 → 201
    # chunks); every other attribute is narrower. src/GC/Verify-GC.py:70-72
    # and Appendix Table V (GC3/GC4: 201 partitions, 100% coverage).
    p_dict = partition_attributes(GERMAN.ranges, 100)
    assert list(p_dict.keys()) == ["credit_amount"]
    p_list = partitioned_ranges(GERMAN.columns, p_dict, GERMAN.ranges)
    assert len(p_list) == 201
    assert abs(coverage_fraction(p_list, GERMAN.ranges) - 1.0) < 1e-12


def test_boxes_tensor_roundtrip():
    p_dict = partition_attributes({"a": (0, 5), "b": (0, 25)}, 10)
    p_list = partitioned_ranges(["a", "b"], p_dict, {"a": (0, 5), "b": (0, 25)})
    lo, hi = boxes_from_partitions(p_list, ["a", "b"])
    assert lo.shape == hi.shape == (len(p_list), 2)
    assert (lo <= hi).all()
    # every point of the domain lands in exactly one box
    for a in range(6):
        for b in range(26):
            inside = ((lo <= [a, b]) & ([a, b] <= hi)).all(axis=1)
            assert inside.sum() == 1


def test_capped_partitioning_caps_product():
    ranges = {"pa": (0, 1), "big": (0, 10_000), "med": (0, 50)}
    p_dict = partition_attributes_capped(ranges, 8)
    p_list = partitioned_ranges_capped(
        ["pa", "big", "med"], ["pa"], p_dict, ranges, max_partitions=100
    )
    assert len(p_list) <= 100
    # 'big' (1251 chunks) cannot fit in the 100-partition budget, so it keeps
    # its full range in every partition; 'med' (7 chunks) gets partitioned.
    assert all(p["big"] == (0, 10_000) for p in p_list)
    assert all(p["med"] != (0, 50) for p in p_list)
    # pa (width 2 <= 8) is never chunked, so the product is just med's 7 chunks
    assert len(p_list) == 7
    assert all(p["pa"] == (0, 1) for p in p_list)


def test_partition_density_matches_manual_count():
    ranges = {"a": (0, 3), "b": (0, 3)}
    p_dict = partition_attributes(ranges, 2)
    p_list = partitioned_ranges(["a", "b"], p_dict, ranges)
    X = np.array([[0, 0], [1, 1], [2, 2], [3, 3], [0, 3]])
    dens = partition_density(p_list, X, ["a", "b"])
    np.testing.assert_allclose(dens.sum(), 1.0)
    for p, d in zip(p_list, dens):
        manual = np.mean([
            (p["a"][0] <= x[0] <= p["a"][1]) and (p["b"][0] <= x[1] <= p["b"][1])
            for x in X
        ])
        assert abs(d - manual) < 1e-12


def test_product_boxes_matches_dict_path():
    """The vectorized grid equals the dict-based cartesian product exactly
    (same box contents AND ordering) on every base domain."""
    import numpy as np

    from fairify_tpu.data import domains
    from fairify_tpu.partition.grid import (
        boxes_from_partitions, partition_attributes, partitioned_ranges,
        product_boxes,
    )

    for name, thr in (("german", 100), ("bank", 100), ("compass", 5),
                      ("german", 10)):
        dom = domains.get_domain(name)
        ranges = {k: list(v) for k, v in dom.ranges.items()}
        p_dict = partition_attributes(ranges, thr)
        p_list = partitioned_ranges(list(dom.columns), p_dict, ranges)
        lo_d, hi_d = boxes_from_partitions(p_list, dom.columns)
        lo_v, hi_v = product_boxes(dom.columns, p_dict, ranges)
        np.testing.assert_array_equal(lo_d.astype(np.int64), lo_v)
        np.testing.assert_array_equal(hi_d.astype(np.int64), hi_v)


def test_boxlist_views():
    import numpy as np

    from fairify_tpu.partition.grid import BoxList

    lo = np.array([[0, 5], [1, 6]]); hi = np.array([[2, 7], [3, 8]])
    bl = BoxList(lo, hi, ("a", "b"))
    assert len(bl) == 2
    assert bl[1] == {"a": (1, 3), "b": (6, 8)}
    assert len(bl[:1]) == 1 and bl[:1][0] == {"a": (0, 2), "b": (5, 7)}
    assert [b["a"] for b in bl] == [(0, 2), (1, 3)]

"""Parity tests: native exact core vs the pure-Python Fraction path.

The native library (``native/exact_core.cc``) must compute *identical* values
to :mod:`fairify_tpu.ops.exact` — both are exact, so any disagreement is a
bug in one of them.  Oracles here are the Fraction implementations and
hand-built nets with known exact zeros.
"""
from __future__ import annotations

from fractions import Fraction

import numpy as np
import pytest

from fairify_tpu.ops import exact as exact_ops
from fairify_tpu.ops import exact_native as en

pytestmark = pytest.mark.skipif(not en.available(), reason="native core unavailable")


def _random_net(rng, sizes):
    ws = [
        rng.normal(scale=0.4, size=(sizes[i], sizes[i + 1])).astype(np.float32)
        for i in range(len(sizes) - 1)
    ]
    bs = [
        rng.normal(scale=0.2, size=(sizes[i + 1],)).astype(np.float32)
        for i in range(len(sizes) - 1)
    ]
    return ws, bs


def _fraction_sign(ws, bs, x):
    h = [Fraction(int(t)) for t in np.asarray(x, dtype=np.int64)]
    for i, (w, b) in enumerate(zip(ws, bs)):
        wf = np.asarray(w, dtype=np.float64)
        bf = np.asarray(b, dtype=np.float64)
        nxt = []
        for j in range(wf.shape[1]):
            acc = Fraction(float(bf[j]))
            for t in range(wf.shape[0]):
                acc += Fraction(float(wf[t, j])) * h[t]
            if i < len(ws) - 1 and acc < 0:
                acc = Fraction(0)
            nxt.append(acc)
        h = nxt
    v = h[0]
    return 0 if v == 0 else (1 if v > 0 else -1)


def test_forward_signs_match_fractions():
    rng = np.random.default_rng(7)
    ws, bs = _random_net(rng, (9, 24, 12, 1))
    pts = rng.integers(-6, 30, size=(64, 9))
    nat = en.forward_signs(ws, bs, pts)
    ref = np.array([_fraction_sign(ws, bs, p) for p in pts], dtype=np.int8)
    assert np.array_equal(nat, ref)


def test_forward_signs_exact_zero():
    # f(x) = x0 - x1: sign is exactly 0 on the diagonal — float prefilters
    # cannot see this; the dyadic core must.
    w = np.array([[1.0], [-1.0]], dtype=np.float32)
    b = np.array([0.0], dtype=np.float32)
    out = en.forward_signs([w], [b], np.array([[5, 5], [6, 5], [4, 5]]))
    assert out.tolist() == [0, 1, -1]


def test_forward_signs_deep_subnormal_scales():
    # Mixed tiny/huge weights exercise wide exponent alignment in dy_add.
    rng = np.random.default_rng(3)
    ws, bs = _random_net(rng, (4, 8, 8, 1))
    ws[0] *= np.float32(1e-20)
    ws[1] *= np.float32(1e18)
    pts = rng.integers(0, 50, size=(16, 4))
    nat = en.forward_signs(ws, bs, pts)
    ref = np.array([_fraction_sign(ws, bs, p) for p in pts], dtype=np.int8)
    assert np.array_equal(nat, ref)


def test_certify_matches_python(monkeypatch):
    rng = np.random.default_rng(11)
    ws, bs = _random_net(rng, (6, 16, 10, 1))
    # Engineer some genuinely dead neurons: large negative bias.
    bs[0][:4] = -100.0
    bs[1][:3] = -100.0
    lo = np.zeros(6, dtype=np.int64)
    hi = np.full(6, 8, dtype=np.int64)
    proposed = [np.ones(16, np.float32), np.ones(10, np.float32), np.zeros(1, np.float32)]
    nat = en.certify_dead(ws, bs, lo, hi, proposed)
    # Force the Fraction path for the oracle.
    monkeypatch.setattr(en, "certify_dead", lambda *a, **k: None)
    ref = exact_ops.certify_dead_masks(ws, bs, lo, hi, proposed)
    assert all(np.array_equal(a, b) for a, b in zip(nat, ref))
    assert nat[0][:4].sum() == 4  # the engineered dead neurons are certified


def test_certify_batch_matches_single():
    rng = np.random.default_rng(13)
    ws, bs = _random_net(rng, (5, 12, 1))
    bs[0][:5] = -50.0
    P = 7
    lo = rng.integers(0, 3, size=(P, 5)).astype(np.int64)
    hi = lo + rng.integers(1, 6, size=(P, 5))
    proposed = [np.ones((P, 12), np.float32), np.zeros((P, 1), np.float32)]
    batched = en.certify_dead_batch(ws, bs, lo, hi, proposed)
    for p in range(P):
        single = en.certify_dead(ws, bs, lo[p], hi[p], [c[p] for c in proposed])
        for l in range(2):
            assert np.array_equal(batched[l][p], single[l])


def test_bound_signs_match_fractions():
    rng = np.random.default_rng(17)
    ws, bs = _random_net(rng, (5, 10, 6, 1))
    lo = np.zeros(5, dtype=np.int64)
    hi = np.full(5, 12, dtype=np.int64)
    ws_lb, ws_ub, _, _ = exact_ops.exact_network_bounds(ws, bs, lo, hi)
    nat_lb, nat_ub = en.bound_signs(ws, bs, lo, hi)
    for l in range(3):
        ref_lb = np.sign([float(v > 0) - float(v < 0) for v in ws_lb[l]]).astype(np.int8)
        ref_ub = np.sign([float(v > 0) - float(v < 0) for v in ws_ub[l]]).astype(np.int8)
        assert np.array_equal(nat_lb[l], ref_lb)
        assert np.array_equal(nat_ub[l], ref_ub)


def test_engine_sign_uses_native_on_ambiguity():
    from fairify_tpu.verify import engine

    w = np.array([[1.0], [-1.0]], dtype=np.float32)
    b = np.array([0.0], dtype=np.float32)
    assert engine.exact_logit_sign([w], [b], np.array([3, 3])) == 0
    assert engine.exact_logit_sign([w], [b], np.array([4, 3])) == 1

"""Fused Pallas IBP kernel: parity with the XLA path and exact-bound soundness."""
import numpy as np
import pytest

import jax.numpy as jnp

from fairify_tpu.models import train
from fairify_tpu.ops import interval, pallas_ibp
from fairify_tpu.ops.masks import apply_dead_masks


def _boxes(rng, B, d, span=10):
    lo = rng.integers(0, 5, size=(B, d)).astype(np.float32)
    hi = lo + rng.integers(0, span, size=(B, d))
    return jnp.asarray(lo), jnp.asarray(hi)


def test_matches_xla_path():
    rng = np.random.default_rng(0)
    net = train.init_mlp([7, 40, 24, 1], seed=1)
    lo, hi = _boxes(rng, 33, 7)  # non-multiple of the batch tile
    ws_lb, ws_ub = pallas_ibp.network_ws_bounds(net, lo, hi)
    ref = interval.network_bounds(net, lo, hi)
    for l in range(3):
        a, b = np.asarray(ws_lb[l]), np.asarray(ref.ws_lb[l])
        tol = 1e-4 * np.maximum(np.abs(a), np.abs(b)).max() + 1e-5
        np.testing.assert_allclose(a, b, atol=tol)
        np.testing.assert_allclose(np.asarray(ws_ub[l]), np.asarray(ref.ws_ub[l]), atol=tol)


def test_contains_exact_bounds():
    from fairify_tpu.ops.exact import exact_network_bounds

    net = train.init_mlp([5, 12, 8, 1], seed=2)
    ws = [np.asarray(w) for w in net.weights]
    bs = [np.asarray(b) for b in net.biases]
    lo = np.zeros(5, dtype=np.int64)
    hi = np.full(5, 7, dtype=np.int64)
    ws_lb, ws_ub = pallas_ibp.network_ws_bounds(
        net, jnp.asarray(lo, jnp.float32)[None], jnp.asarray(hi, jnp.float32)[None]
    )
    ex_lb, ex_ub, _, _ = exact_network_bounds(ws, bs, lo, hi)
    for l in range(3):
        for j in range(len(ex_lb[l])):
            assert float(ws_lb[l][0, j]) <= float(ex_lb[l][j])
            assert float(ws_ub[l][0, j]) >= float(ex_ub[l][j])


def test_respects_dead_masks():
    rng = np.random.default_rng(3)
    net = train.init_mlp([6, 16, 10, 1], seed=4)
    dead = [np.zeros(16, np.float32), np.zeros(10, np.float32), np.zeros(1, np.float32)]
    dead[0][:6] = 1.0
    masked = apply_dead_masks(net, dead)
    lo, hi = _boxes(rng, 8, 6)
    ws_lb, ws_ub = pallas_ibp.network_ws_bounds(masked, lo, hi)
    ref = interval.network_bounds(masked, lo, hi)
    for l in range(3):
        a, b = np.asarray(ws_ub[l]), np.asarray(ref.ws_ub[l])
        tol = 1e-4 * np.maximum(np.abs(a), np.abs(b)).max() + 1e-5
        np.testing.assert_allclose(a, b, atol=tol)


def test_output_bounds_shape():
    net = train.init_mlp([4, 8, 1], seed=5)
    rng = np.random.default_rng(6)
    lo, hi = _boxes(rng, 5, 4)
    lb, ub = pallas_ibp.output_bounds(net, lo, hi)
    assert lb.shape == (5,) and ub.shape == (5,)
    assert bool(jnp.all(lb <= ub))


def test_wide_net_rejected():
    net = train.init_mlp([4, 200, 1], seed=7)
    assert not pallas_ibp.available(net)
    with pytest.raises(ValueError):
        pallas_ibp.network_ws_bounds(
            net, jnp.zeros((1, 4), jnp.float32), jnp.ones((1, 4), jnp.float32)
        )

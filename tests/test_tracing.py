"""End-to-end distributed request tracing (DESIGN.md §19).

The fleet's cross-process hops are each pinned against REAL subprocesses
(same bar as ``tests/test_procfleet.py`` — no mocks):

* **context plumbing** — ``TraceContext`` binds thread-locally, never
  inherits across threads, stamps every span/event written under it, and
  round-trips through the wire ``fields()`` / ``from_fields()`` shape;
* **submit stamps, requeue preserves** — ``serve.client.submit`` gives
  every payload a trace id exactly once (``setdefault``): a re-homed or
  requeued payload keeps its identity across any number of owners;
* **router → replica** — one spool submit against a 2-process fleet
  yields ONE connected trace: the router shard's request events and the
  replica shard's spans join on the payload's trace id, and the merged
  critical path survives a literal mid-request ``kill -9`` + re-home;
* **replica → SMT worker** — a pool query carries the caller's context
  in its solve frame; the worker process opens its own shard and records
  ``smt.worker_solve`` under the caller's trace id (a real worker
  subprocess, brute backend);
* **trace-off = zero cost** — a fleet run without ``--trace-dir`` emits
  zero trace records anywhere in the spool;
* **merged export** — per-process shards merge into one Chrome/Perfetto
  file with pid-namespaced process tracks and integer thread ids.
"""
import glob
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from fairify_tpu import obs
from fairify_tpu.obs import metrics as metrics_mod
from fairify_tpu.obs import trace as trace_mod
from fairify_tpu.smt import protocol


@pytest.fixture(autouse=True)
def _clean_obs_state():
    trace_mod.activate(None)
    trace_mod._ctx_tls.ctx = None
    metrics_mod.registry().reset()
    yield
    trace_mod.activate(None)
    trace_mod._ctx_tls.ctx = None
    metrics_mod.registry().reset()


# ---------------------------------------------------------------------------
# context API units
# ---------------------------------------------------------------------------


def test_trace_context_binding_and_wire_shape():
    tid = trace_mod.new_trace_id()
    assert len(tid) == 16 and int(tid, 16) >= 0
    assert trace_mod.new_trace_id() != tid
    assert trace_mod.current_context() is None
    assert trace_mod.context_fields() == {}
    ctx = trace_mod.TraceContext(tid, None)
    with trace_mod.context(ctx):
        assert trace_mod.current_context() is ctx
        fields = trace_mod.context_fields()
        assert fields == {"trace": {"id": tid}}
        inner = trace_mod.TraceContext("b" * 16, 7)
        with trace_mod.context(inner):
            assert trace_mod.current_context() is inner
            assert trace_mod.context_fields()["trace"] == {
                "id": "b" * 16, "span": 7}
        assert trace_mod.current_context() is ctx
        # A None context defers to the enclosing one (spool payloads
        # without a trace field must not sever an outer scope).
        with trace_mod.context(None):
            assert trace_mod.current_context() is ctx
    assert trace_mod.current_context() is None
    # Wire round-trip.
    back = trace_mod.TraceContext.from_fields(
        {"trace": {"id": tid, "span": 3}})
    assert (back.trace_id, back.parent_span) == (tid, 3)
    assert trace_mod.TraceContext.from_fields({}) is None
    assert trace_mod.TraceContext.from_fields({"trace": {}}) is None
    assert trace_mod.TraceContext.from_fields(None) is None


def test_context_never_inherits_across_threads():
    """Queue handoffs must capture the context at enqueue and re-bind at
    dequeue — implicit inheritance would attribute one request's spans to
    whichever request's thread happened to spawn the worker."""
    seen = []
    with trace_mod.context(trace_mod.TraceContext("c" * 16, None)):
        t = threading.Thread(
            target=lambda: seen.append(trace_mod.current_context()))
        t.start()
        t.join()
    assert seen == [None]


def test_span_and_event_records_carry_trace_id(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tr = trace_mod.Tracer(path, run_id="unit")
    trace_mod.activate(tr)
    try:
        with trace_mod.context(trace_mod.TraceContext("d" * 16, 41)):
            with obs.span("outer"):
                with obs.span("inner"):
                    pass
            obs.event("tick", n=1)
        with obs.span("unbound"):
            pass
    finally:
        trace_mod.activate(None)
        tr.close()
    recs = trace_mod.load_events(path)
    spans = {r["name"]: r for r in recs if r.get("type") == "span"}
    assert spans["outer"]["trace_id"] == "d" * 16
    assert spans["inner"]["trace_id"] == "d" * 16
    # Only the context-root span records the REMOTE parent (the sender's
    # span id); the nested span has a local parent instead.
    assert spans["outer"]["remote_parent"] == 41
    assert "remote_parent" not in spans["inner"]
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert "trace_id" not in spans["unbound"]
    ev = next(r for r in recs if r.get("type") == "event")
    assert ev["trace_id"] == "d" * 16
    meta = next(r for r in recs if r.get("type") == "meta")
    assert meta["pid"] == os.getpid()


def test_submit_stamps_trace_exactly_once(tmp_path):
    from fairify_tpu.serve import client as client_mod

    spool = str(tmp_path / "spool")
    payload = client_mod.build_payload(
        "GC", init={"sizes": [4, 1], "seed": 0})
    rid = client_mod.submit(spool, payload)
    with open(os.path.join(spool, "inbox", f"{rid}.json")) as fp:
        on_disk = json.load(fp)
    tid = on_disk["trace"]["id"]
    assert len(tid) == 16
    # A requeued/re-homed payload keeps its identity: submit never
    # re-stamps an existing trace field.
    rid2 = client_mod.submit(spool, dict(on_disk, id="requeue-1"))
    with open(os.path.join(spool, "inbox", f"{rid2}.json")) as fp:
        assert json.load(fp)["trace"]["id"] == tid
    # Under a bound context the payload joins the caller's trace.
    with trace_mod.context(trace_mod.TraceContext("e" * 16, None)):
        payload3 = client_mod.build_payload(
            "GC", init={"sizes": [4, 1], "seed": 0})
        rid3 = client_mod.submit(spool, payload3)
    with open(os.path.join(spool, "inbox", f"{rid3}.json")) as fp:
        assert json.load(fp)["trace"]["id"] == "e" * 16


def test_solve_request_frame_carries_trace():
    req = protocol.solve_request(3, {"q": 1}, 10.0,
                                 trace={"id": "f" * 16, "span": 2})
    assert req["trace"] == {"id": "f" * 16, "span": 2}
    assert "trace" not in protocol.solve_request(3, {"q": 1}, 10.0)


# ---------------------------------------------------------------------------
# merged export + critical paths (synthetic shards)
# ---------------------------------------------------------------------------


def _shard(tmp_path, pid, run_id, records):
    path = str(tmp_path / f"trace.{pid}.jsonl")
    with open(path, "w") as fp:
        fp.write(json.dumps({"type": "meta", "version": 1, "run_id": run_id,
                             "pid": pid, "wall_time": 100.0}) + "\n")
        for rec in records:
            fp.write(json.dumps(rec) + "\n")
    return path


TID = "a1b2c3d4e5f60718"


def _synthetic_fleet_shards(tmp_path):
    router = _shard(tmp_path, 100, "serve", [
        {"type": "event", "name": "request", "ts": 0.01, "tid": 1,
         "attrs": {"request": "r-1", "status": "done", "replica": 0,
                   "queue_wait_s": 0.2, "run_s": 1.0, "trace_id": TID}},
    ])
    replica = _shard(tmp_path, 200, "replica-0", [
        {"type": "span", "name": "serve.admit", "ts": 0.0, "dur_s": 0.05,
         "span_id": 1, "tid": 1, "trace_id": TID, "attrs": {}},
        {"type": "span", "name": "serve.batch_stage0", "ts": 0.1,
         "dur_s": 0.1, "span_id": 2, "tid": 1,
         "attrs": {"trace_ids": [TID]}},
        {"type": "span", "name": "serve.request", "ts": 0.2, "dur_s": 1.0,
         "span_id": 3, "tid": 1, "trace_id": TID,
         "attrs": {"request": "r-1"}},
        {"type": "span", "name": "compile.stage0", "ts": 0.25,
         "dur_s": 0.3, "span_id": 4, "tid": 1, "trace_id": TID,
         "attrs": {}},
        {"type": "span", "name": "pipeline.drain", "ts": 0.6, "dur_s": 0.1,
         "span_id": 5, "tid": 1, "trace_id": TID, "attrs": {}},
    ])
    worker = _shard(tmp_path, 300, "smt-worker", [
        {"type": "span", "name": "smt.worker_solve", "ts": 0.7,
         "dur_s": 0.2, "span_id": 1, "tid": 1, "trace_id": TID,
         "remote_parent": 3, "attrs": {"qid": 0}},
    ])
    return [router, replica, worker]


def test_merged_chrome_export_namespaces_processes(tmp_path):
    paths = _synthetic_fleet_shards(tmp_path)
    assert trace_mod.shard_paths(str(tmp_path)) == sorted(paths)
    out = str(tmp_path / "merged.chrome.json")
    n = trace_mod.write_chrome_trace_merged(paths, out)
    with open(out) as fp:
        events = json.load(fp)["traceEvents"]
    assert n == sum(1 for e in events if e["ph"] != "M")
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {"serve [pid 100]", "replica-0 [pid 200]",
                     "smt-worker [pid 300]"}
    # One shared timebase: the worker's span lands after the replica's.
    spans = {e["name"]: e for e in events if e["ph"] == "X"}
    assert spans["smt.worker_solve"]["ts"] > spans["serve.admit"]["ts"]
    assert all(isinstance(e["pid"], int) for e in events)
    assert all(isinstance(e["tid"], int)
               for e in events if e["ph"] != "M")
    # Cross-shard join key rides into the viewer.
    assert spans["serve.request"]["args"]["trace_id"] == TID


def test_critical_path_table_joins_shards_on_trace_id(tmp_path):
    from fairify_tpu.obs import report as report_mod

    rows = report_mod.critical_paths(_synthetic_fleet_shards(tmp_path))
    row = rows[TID]
    assert row["request"] == "r-1" and row["complete"]
    assert row["replica"] == 0 and row["replica_pid"] == 200
    assert row["worker_pids"] == [300]
    assert row["admission_s"] == 0.05
    assert row["coalesce_s"] == 0.1
    assert row["compile_s"] == 0.3
    assert row["smt_s"] == 0.2
    assert row["drain_s"] == 0.1
    # device = run residual; stages sum EXACTLY to the measured latency.
    assert row["device_s"] == pytest.approx(1.0 - 0.3 - 0.2 - 0.1)
    assert row["total_s"] == pytest.approx(
        row["queue_wait_s"] + row["run_s"])
    text = report_mod.render_critical_paths(rows)
    assert "r-1" in text and "complete critical paths: 1" in text


# ---------------------------------------------------------------------------
# replica -> SMT worker: a real worker subprocess records the caller's trace
# ---------------------------------------------------------------------------


def test_smt_worker_shard_joins_callers_trace(tmp_path):
    from fairify_tpu.data.domains import DomainSpec
    from fairify_tpu.models import mlp
    from fairify_tpu.smt.pool import PoolConfig, SmtPool, solve_box
    from fairify_tpu.verify import property as prop

    ranges = {"a": (0, 3), "pa": (0, 1)}
    q = prop.FairnessQuery(
        domain=DomainSpec(name="toy", columns=tuple(ranges),
                          ranges={k: tuple(v) for k, v in ranges.items()},
                          label="y"),
        protected=("pa",))
    enc = prop.encode(q)
    lo, hi = q.domain.lo_hi()
    net = mlp.from_numpy(
        [np.array([[0.0], [2.0]], dtype=np.float32),
         np.array([[1.0]], dtype=np.float32)],
        [np.array([0.0], dtype=np.float32),
         np.array([-1.0], dtype=np.float32)])
    trace_dir = str(tmp_path / "tr")
    tid = trace_mod.new_trace_id()
    with SmtPool(PoolConfig(workers=1, backend="brute", grace_s=0.5,
                            backoff_s=1e-3, trace_dir=trace_dir)) as pool:
        with trace_mod.context(trace_mod.TraceContext(tid, 9)):
            v, _ce, reason = solve_box(pool, net, enc,
                                       lo.astype(np.int64),
                                       hi.astype(np.int64),
                                       soft_timeout_s=10.0)
    assert (v, reason) == ("sat", None)
    shards = trace_mod.shard_paths(trace_dir)
    assert shards, "worker opened no trace shard"
    solves = []
    for path in shards:
        recs = trace_mod.load_events(path)
        meta = next(r for r in recs if r.get("type") == "meta")
        assert meta["pid"] != os.getpid()  # a real subprocess's shard
        assert meta["run_id"] == "smt-worker"
        solves += [r for r in recs if r.get("type") == "span"
                   and r["name"] == "smt.worker_solve"]
    assert solves, "worker recorded no solve span"
    assert all(s["trace_id"] == tid for s in solves)
    # The cross-process root remembers the sender-side span id.
    assert all(s.get("remote_parent") == 9 for s in solves)


# ---------------------------------------------------------------------------
# router -> replica across a 2-process fleet, kill -9 mid-request
# ---------------------------------------------------------------------------


def test_procfleet_one_request_one_connected_trace_tree(tmp_path):
    """One spool submit against a 2-replica PROCESS fleet with tracing on:
    the router shard's request events and the replica shards' spans form
    one tree joined on the payload's trace id, the merged critical path
    stays complete across a literal mid-request ``kill -9`` + re-home,
    and the router publishes ``fleet_metrics.json``."""
    from fairify_tpu.obs import report as report_mod
    from fairify_tpu.serve import ProcessFleet, ProcFleetConfig, ServeConfig
    from fairify_tpu.serve import client as client_mod
    from tests.test_procfleet import OVERRIDES, SIZES, _wait_running

    spool = tmp_path / "spool"
    trace_dir = str(spool / "trace")
    fl = ProcessFleet(ProcFleetConfig(
        n_replicas=2, spool=str(spool), poll_s=0.03, pulse_s=0.0,
        backoff_s=0.05, trace_dir=trace_dir,
        replica=ServeConfig(batch_window_s=0.1, max_batch=4, poll_s=0.05,
                            span_chunks=1)))
    payload = client_mod.build_payload(
        "GC", init={"sizes": SIZES, "seed": 3}, overrides=dict(OVERRIDES),
        span=(0, 48))
    # Pre-stamped identity: submit must preserve it (setdefault), and it
    # is the join key asserted across every process's shard below.
    tid = trace_mod.new_trace_id()
    payload["trace"] = {"id": tid}
    with obs.tracing(trace_mod.shard_path(trace_dir), run_id="serve"):
        with fl:
            assert fl.wait_ready(timeout=180) == 2
            rid = client_mod.submit(str(spool), payload)
            owner = _wait_running(fl, rid)
            os.kill(fl.pids()[owner], signal.SIGKILL)
            rec = fl.wait(rid, timeout=300)
            assert rec is not None and rec["status"] == "done", rec
            assert fl.restarts()[owner] >= 1  # the kill landed mid-request
    shards = trace_mod.shard_paths(trace_dir)
    pids = set()
    spans_by_pid = {}
    for path in shards:
        recs = trace_mod.load_events(path)
        meta = next(r for r in recs if r.get("type") == "meta")
        pids.add(meta["pid"])
        spans_by_pid[meta["pid"]] = [
            r for r in recs if r.get("type") == "span"
            and (r.get("trace_id") == tid
                 or tid in r.get("attrs", {}).get("trace_ids", []))]
    assert len(pids) >= 3  # router + 2 replica processes (distinct pids)
    traced_pids = {p for p, s in spans_by_pid.items() if s}
    assert os.getpid() in pids  # the router's own shard
    assert traced_pids - {os.getpid()}, \
        "no replica process recorded spans under the request's trace"
    rows = report_mod.critical_paths(shards)
    row = rows[tid]
    assert row["request"] == rid and row["complete"], row
    assert row["total_s"] == pytest.approx(
        row["queue_wait_s"] + row["run_s"])
    stages = (row["admission_s"] + row["compile_s"] + row["device_s"]
              + row["smt_s"] + row["drain_s"])
    assert stages == pytest.approx(row["run_s"], rel=0.05)
    # Merged Perfetto export spans every process.
    out = str(tmp_path / "merged.chrome.json")
    assert trace_mod.write_chrome_trace_merged(shards, out) > 0
    with open(out) as fp:
        merged = json.load(fp)["traceEvents"]
    assert len({e["pid"] for e in merged}) == len(pids)
    # Fleet-wide metrics aggregation rode the beats/drain summaries.
    with open(os.path.join(str(spool), "fleet_metrics.json")) as fp:
        fm = json.load(fp)
    assert fm["fleet"]["n_replicas"] == 2
    assert fm["drained"], fm
    any_slot = next(iter(fm["drained"].values()))
    assert "exec_cache_hits" in any_slot and "device_launches" in any_slot


def test_procfleet_trace_off_emits_zero_records(tmp_path):
    """Without ``--trace-dir`` the same fleet emits ZERO trace records:
    payloads still carry ids (stamping is O(1)), but no process opens a
    shard and no span is written anywhere in the spool."""
    from fairify_tpu.serve import ProcessFleet, ProcFleetConfig, ServeConfig
    from fairify_tpu.serve import client as client_mod
    from tests.test_procfleet import OVERRIDES, SIZES

    spool = tmp_path / "spool"
    fl = ProcessFleet(ProcFleetConfig(
        n_replicas=1, spool=str(spool), poll_s=0.03, pulse_s=0.0,
        backoff_s=0.05,
        replica=ServeConfig(batch_window_s=0.1, max_batch=4, poll_s=0.05,
                            span_chunks=1)))
    with fl:
        assert fl.wait_ready(timeout=180) == 1
        rid = client_mod.submit(str(spool), client_mod.build_payload(
            "GC", init={"sizes": SIZES, "seed": 3},
            overrides=dict(OVERRIDES), span=(0, 16)))
        rec = fl.wait(rid, timeout=300)
        assert rec is not None and rec["status"] == "done", rec
    stray = [os.path.join(root, f)
             for root, _dirs, files in os.walk(str(spool))
             for f in files
             if f.startswith("trace.") and f.endswith(".jsonl")]
    assert stray == [], stray

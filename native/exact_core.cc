// Native exact-arithmetic core for fairify_tpu (C ABI, loaded via ctypes).
//
// Everything soundness-critical in the framework bottoms out in questions
// about *exact* signs of affine/ReLU expressions whose coefficients are
// float32 (dyadic rationals m * 2^e) and whose inputs are integers:
//
//   * sign of the network logit at an integer point (counterexample
//     validation, branch-and-bound leaf decisions) — the quantity the
//     reference's Z3 encoding reasons about (utils/GC-1-Model-Functions.py,
//     z3_net over ToReal(Int) inputs);
//   * exact interval upper bounds per neuron over an integer box (the
//     closed-form equivalent of the reference's per-neuron "singular
//     verification" Z3 queries, utils/prune.py:276-364).
//
// Python's fractions.Fraction computes these exactly but at ~1e4 ops/s; this
// file computes the same values in dyadic fixed-point big-integer arithmetic
// (no gcd, no division — every quantity is m * 2^e with a big-int m), which
// is exact by construction and ~100-1000x faster.  The Python wrapper
// (fairify_tpu/ops/exact_native.py) falls back to the Fraction path when the
// shared library is unavailable.
//
// Build: g++ -O2 -shared -fPIC -o libfairify_exact.so exact_core.cc

#include <cstdint>
#include <cstring>
#include <cmath>
#include <vector>

namespace {

using u64 = std::uint64_t;
using u128 = unsigned __int128;
using i64 = std::int64_t;

// ---------------------------------------------------------------------------
// Signed big integer: sgn in {-1,0,1}, little-endian 64-bit limbs.
// ---------------------------------------------------------------------------

struct Big {
  int sgn = 0;
  std::vector<u64> m;
};

inline void trim(Big &a) {
  while (!a.m.empty() && a.m.back() == 0) a.m.pop_back();
  if (a.m.empty()) a.sgn = 0;
}

inline int cmp_mag(const std::vector<u64> &a, const std::vector<u64> &b) {
  if (a.size() != b.size()) return a.size() < b.size() ? -1 : 1;
  for (size_t i = a.size(); i-- > 0;) {
    if (a[i] != b[i]) return a[i] < b[i] ? -1 : 1;
  }
  return 0;
}

inline std::vector<u64> add_mag(const std::vector<u64> &a, const std::vector<u64> &b) {
  const std::vector<u64> &x = a.size() >= b.size() ? a : b;
  const std::vector<u64> &y = a.size() >= b.size() ? b : a;
  std::vector<u64> r(x.size() + 1, 0);
  u64 carry = 0;
  for (size_t i = 0; i < x.size(); ++i) {
    u128 s = (u128)x[i] + (i < y.size() ? y[i] : 0) + carry;
    r[i] = (u64)s;
    carry = (u64)(s >> 64);
  }
  r[x.size()] = carry;
  while (!r.empty() && r.back() == 0) r.pop_back();
  return r;
}

// |a| >= |b| required.
inline std::vector<u64> sub_mag(const std::vector<u64> &a, const std::vector<u64> &b) {
  std::vector<u64> r(a.size(), 0);
  u64 borrow = 0;
  for (size_t i = 0; i < a.size(); ++i) {
    u64 bi = (i < b.size() ? b[i] : 0);
    u64 t = a[i] - bi;
    u64 borrow2 = a[i] < bi;
    u64 t2 = t - borrow;
    borrow2 |= (t < borrow);
    r[i] = t2;
    borrow = borrow2;
  }
  while (!r.empty() && r.back() == 0) r.pop_back();
  return r;
}

inline Big big_add(const Big &a, const Big &b) {
  if (a.sgn == 0) return b;
  if (b.sgn == 0) return a;
  Big r;
  if (a.sgn == b.sgn) {
    r.sgn = a.sgn;
    r.m = add_mag(a.m, b.m);
  } else {
    int c = cmp_mag(a.m, b.m);
    if (c == 0) return r;  // zero
    if (c > 0) {
      r.sgn = a.sgn;
      r.m = sub_mag(a.m, b.m);
    } else {
      r.sgn = b.sgn;
      r.m = sub_mag(b.m, a.m);
    }
  }
  trim(r);
  return r;
}

// Shift left by k bits (k >= 0).
inline void shl_bits(Big &a, u64 k) {
  if (a.sgn == 0 || k == 0) return;
  u64 limbs = k / 64, bits = k % 64;
  size_t n = a.m.size();
  a.m.resize(n + limbs + (bits ? 1 : 0), 0);
  if (bits) {
    for (size_t i = n; i-- > 0;) {
      u64 hi = a.m[i] >> (64 - bits);
      a.m[i + limbs + 1] |= hi;
      a.m[i + limbs] = a.m[i] << bits;
      if (i < limbs) a.m[i] = 0;
    }
    // clear low limbs not covered when limbs > 0
    for (size_t i = 0; i < limbs && i < n; ++i) a.m[i] = 0;
    if (limbs == 0) {
      // already shifted in place above
    }
  } else {
    for (size_t i = n; i-- > 0;) a.m[i + limbs] = a.m[i];
    for (size_t i = 0; i < limbs; ++i) a.m[i] = 0;
  }
  trim(a);
}

// a * s where s fits one limb; ssgn is the sign of s.
inline Big mul_small(const Big &a, u64 s, int ssgn) {
  Big r;
  if (a.sgn == 0 || s == 0 || ssgn == 0) return r;
  r.sgn = a.sgn * ssgn;
  r.m.assign(a.m.size() + 1, 0);
  u64 carry = 0;
  for (size_t i = 0; i < a.m.size(); ++i) {
    u128 p = (u128)a.m[i] * s + carry;
    r.m[i] = (u64)p;
    carry = (u64)(p >> 64);
  }
  r.m[a.m.size()] = carry;
  trim(r);
  return r;
}

// ---------------------------------------------------------------------------
// Dyadic rational: v * 2^e.
// ---------------------------------------------------------------------------

struct Dy {
  Big v;
  i64 e = 0;
};

inline Dy dy_from_i64(i64 x) {
  Dy d;
  if (x == 0) return d;
  d.v.sgn = x < 0 ? -1 : 1;
  u64 mag = x < 0 ? (u64)(-(x + 1)) + 1 : (u64)x;
  d.v.m.push_back(mag);
  return d;
}

// Exact conversion of any finite double (covers all float32 values).
inline Dy dy_from_double(double x) {
  Dy d;
  if (x == 0.0) return d;
  int ex;
  double m = std::frexp(x, &ex);        // x = m * 2^ex, |m| in [0.5, 1)
  double scaled = std::ldexp(m, 53);    // integer-valued, |.| < 2^53
  i64 mi = (i64)scaled;                 // exact
  d = dy_from_i64(mi);
  d.e = (i64)ex - 53;
  return d;
}

inline Dy dy_add(const Dy &a, const Dy &b) {
  if (a.v.sgn == 0) return b;
  if (b.v.sgn == 0) return a;
  Dy r;
  if (a.e == b.e) {
    r.v = big_add(a.v, b.v);
    r.e = a.e;
  } else if (a.e > b.e) {
    Big av = a.v;
    shl_bits(av, (u64)(a.e - b.e));
    r.v = big_add(av, b.v);
    r.e = b.e;
  } else {
    Big bv = b.v;
    shl_bits(bv, (u64)(b.e - a.e));
    r.v = big_add(a.v, bv);
    r.e = a.e;
  }
  return r;
}

// a * w where w came from a double (mantissa fits one limb).
inline Dy dy_mul_f(const Dy &a, const Dy &w) {
  Dy r;
  if (a.v.sgn == 0 || w.v.sgn == 0) return r;
  u64 wm = w.v.m.empty() ? 0 : w.v.m[0];
  r.v = mul_small(a.v, wm, w.v.sgn);
  r.e = a.e + w.e;
  return r;
}

inline int dy_sign(const Dy &a) { return a.v.sgn; }

inline int dy_cmp(const Dy &a, const Dy &b) {
  Dy nb = b;
  nb.v.sgn = -nb.v.sgn;
  return dy_sign(dy_add(a, nb));
}

struct LayerW {
  int in, out;
  std::vector<Dy> w;  // in*out, row-major (i * out + j)
  std::vector<Dy> b;  // out
};

static void build_layers(int n_layers, const int *sizes, const float *w_flat,
                         const float *b_flat, std::vector<LayerW> &layers) {
  layers.resize(n_layers);
  size_t wo = 0, bo = 0;
  for (int l = 0; l < n_layers; ++l) {
    LayerW &L = layers[l];
    L.in = sizes[l];
    L.out = sizes[l + 1];
    L.w.resize((size_t)L.in * L.out);
    L.b.resize(L.out);
    for (size_t k = 0; k < (size_t)L.in * L.out; ++k) L.w[k] = dy_from_double((double)w_flat[wo + k]);
    for (int j = 0; j < L.out; ++j) L.b[j] = dy_from_double((double)b_flat[bo + j]);
    wo += (size_t)L.in * L.out;
    bo += L.out;
  }
}

}  // namespace

extern "C" {

// Exact sign of the first output logit at each integer point.
//   sizes:    n_layers+1 ints
//   w_flat:   concatenated row-major (in x out) float32 weights
//   b_flat:   concatenated float32 biases
//   points:   n_points x sizes[0] int64
//   out_sign: n_points int8 in {-1, 0, 1}
void ft_forward_signs(int n_layers, const int *sizes, const float *w_flat,
                      const float *b_flat, int n_points, const i64 *points,
                      signed char *out_sign) {
  std::vector<LayerW> layers;
  build_layers(n_layers, sizes, w_flat, b_flat, layers);
  int d0 = sizes[0];
#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic)
#endif
  for (int p = 0; p < n_points; ++p) {
    std::vector<Dy> h, z;
    h.assign(d0, Dy());
    for (int i = 0; i < d0; ++i) h[i] = dy_from_i64(points[(size_t)p * d0 + i]);
    for (int l = 0; l < n_layers; ++l) {
      const LayerW &L = layers[l];
      z.assign(L.out, Dy());
      for (int j = 0; j < L.out; ++j) z[j] = L.b[j];
      for (int i = 0; i < L.in; ++i) {
        if (h[i].v.sgn == 0) continue;
        const Dy *wr = &L.w[(size_t)i * L.out];
        for (int j = 0; j < L.out; ++j) {
          if (wr[j].v.sgn == 0) continue;
          z[j] = dy_add(z[j], dy_mul_f(h[i], wr[j]));
        }
      }
      if (l < n_layers - 1) {
        for (int j = 0; j < L.out; ++j)
          if (z[j].v.sgn < 0) z[j] = Dy();
      }
      h.swap(z);
    }
    out_sign[p] = (signed char)dy_sign(h[0]);
  }
}

void ft_certify_dead_batch(int n_layers, const int *sizes, const float *w_flat,
                           const float *b_flat, int n_boxes, const i64 *lo,
                           const i64 *hi, const unsigned char *proposed,
                           unsigned char *certified);

// Exact-rational veto of proposed dead masks (the closed-form equivalent of
// the reference's per-neuron Z3 singular verification; see
// fairify_tpu/ops/exact.py:certify_dead_masks for the argument).
//   lo, hi:    sizes[0] int64 integer box
//   proposed:  concatenated uint8 per hidden layer (sizes[1..n_layers-1])
//   certified: same layout, written 0/1
void ft_certify_dead(int n_layers, const int *sizes, const float *w_flat,
                     const float *b_flat, const i64 *lo, const i64 *hi,
                     const unsigned char *proposed, unsigned char *certified) {
  ft_certify_dead_batch(n_layers, sizes, w_flat, b_flat, 1, lo, hi, proposed, certified);
}

// Batched ft_certify_dead: n_boxes independent integer boxes (lo/hi are
// n_boxes x sizes[0]; proposed/certified are n_boxes x sum(hidden sizes)).
// One weight conversion serves every box — this is the per-partition exact
// certification sweep of the sound-pruning pass.
void ft_certify_dead_batch(int n_layers, const int *sizes, const float *w_flat,
                           const float *b_flat, int n_boxes, const i64 *lo,
                           const i64 *hi, const unsigned char *proposed,
                           unsigned char *certified) {
  std::vector<LayerW> layers;
  build_layers(n_layers, sizes, w_flat, b_flat, layers);
  int d0 = sizes[0];
  size_t stride = 0;
  for (int l = 0; l < n_layers - 1; ++l) stride += sizes[l + 1];
#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic)
#endif
  for (int bx = 0; bx < n_boxes; ++bx) {
    const i64 *blo = lo + (size_t)bx * d0;
    const i64 *bhi = hi + (size_t)bx * d0;
    const unsigned char *bprop = proposed + (size_t)bx * stride;
    unsigned char *bcert = certified + (size_t)bx * stride;
    std::vector<Dy> lb(d0), ub(d0);
    for (int i = 0; i < d0; ++i) {
      lb[i] = dy_from_i64(blo[i]);
      ub[i] = dy_from_i64(bhi[i]);
    }
    size_t off = 0;
    for (int l = 0; l < n_layers - 1; ++l) {
      const LayerW &L = layers[l];
      std::vector<Dy> mn(L.out), mx(L.out);
      for (int j = 0; j < L.out; ++j) {
        mn[j] = L.b[j];
        mx[j] = L.b[j];
      }
      for (int i = 0; i < L.in; ++i) {
        const Dy *wr = &L.w[(size_t)i * L.out];
        for (int j = 0; j < L.out; ++j) {
          const Dy &wij = wr[j];
          if (wij.v.sgn == 0) continue;
          if (wij.v.sgn < 0) {
            mn[j] = dy_add(mn[j], dy_mul_f(ub[i], wij));
            mx[j] = dy_add(mx[j], dy_mul_f(lb[i], wij));
          } else {
            mn[j] = dy_add(mn[j], dy_mul_f(lb[i], wij));
            mx[j] = dy_add(mx[j], dy_mul_f(ub[i], wij));
          }
        }
      }
      lb.assign(L.out, Dy());
      ub.assign(L.out, Dy());
      for (int j = 0; j < L.out; ++j) {
        bool dead = bprop[off + j] && dy_sign(mx[j]) <= 0;
        bcert[off + j] = dead ? 1 : 0;
        if (dead) continue;
        if (dy_sign(mn[j]) > 0) lb[j] = mn[j];
        if (dy_sign(mx[j]) > 0) ub[j] = mx[j];
      }
      off += L.out;
    }
  }
}

// Exact pre-activation (ws) and post-ReLU (pl) bound SIGNS per neuron over an
// integer box, with optional alive masks pinning pruned neurons to [0,0].
// Out arrays are concatenated over ALL layers (sizes[1..n_layers]), int8.
void ft_bound_signs(int n_layers, const int *sizes, const float *w_flat,
                    const float *b_flat, const i64 *lo, const i64 *hi,
                    const unsigned char *alive /* may be null */,
                    signed char *ws_lb_sign, signed char *ws_ub_sign) {
  std::vector<LayerW> layers;
  build_layers(n_layers, sizes, w_flat, b_flat, layers);
  int d0 = sizes[0];
  std::vector<Dy> lb(d0), ub(d0);
  for (int i = 0; i < d0; ++i) {
    lb[i] = dy_from_i64(lo[i]);
    ub[i] = dy_from_i64(hi[i]);
  }
  size_t off = 0;
  for (int l = 0; l < n_layers; ++l) {
    const LayerW &L = layers[l];
    std::vector<Dy> mn(L.out), mx(L.out);
    for (int j = 0; j < L.out; ++j) {
      mn[j] = L.b[j];
      mx[j] = L.b[j];
    }
    for (int i = 0; i < L.in; ++i) {
      const Dy *wr = &L.w[(size_t)i * L.out];
      for (int j = 0; j < L.out; ++j) {
        const Dy &wij = wr[j];
        if (wij.v.sgn == 0) continue;
        if (wij.v.sgn < 0) {
          mn[j] = dy_add(mn[j], dy_mul_f(ub[i], wij));
          mx[j] = dy_add(mx[j], dy_mul_f(lb[i], wij));
        } else {
          mn[j] = dy_add(mn[j], dy_mul_f(lb[i], wij));
          mx[j] = dy_add(mx[j], dy_mul_f(ub[i], wij));
        }
      }
    }
    for (int j = 0; j < L.out; ++j) {
      ws_lb_sign[off + j] = (signed char)dy_sign(mn[j]);
      ws_ub_sign[off + j] = (signed char)dy_sign(mx[j]);
    }
    if (l < n_layers - 1) {
      lb.assign(L.out, Dy());
      ub.assign(L.out, Dy());
      for (int j = 0; j < L.out; ++j) {
        bool dead = alive && !alive[off + j];
        if (dead) continue;
        if (dy_sign(mn[j]) > 0) lb[j] = mn[j];
        if (dy_sign(mx[j]) > 0) ub[j] = mx[j];
      }
    }
    off += L.out;
  }
}

int ft_abi_version(void) { return 1; }

}  // extern "C"

#!/bin/bash
# Round-5 hard-tier remainder — reprioritized after wall-clock measurement.
#
# The stage-B full grid ran every exhaustible german preset to cov 100% at
# the reference budget, but targeted-AC measured ~19 min/model (12 adult
# models), which would have starved the named stage-C rows.  This remainder
# puts the round-5 flagship rows first (relaxed3-BM's first-ever record and
# the ADVICE-corrected soft-200 stress-BM BM-4), then breadth over the
# remaining targeted presets at a 600 s tier (still 2.5-5x the r4 120/240 s
# tiers), then the BM-S2 scaled re-run (its first record ran while a zombie
# round-4 queue contended for the chip).
set -u
cd "$(dirname "$0")/.." || exit 1
TAG="r5-$(git rev-parse --short HEAD 2>/dev/null || echo untagged)"
echo "=== hard tier r5b, tag $TAG ($(date -u +%H:%M:%S)) ==="

PYTHONUNBUFFERED=1 python scripts/variants.py run --out variants \
  --hard 3600 --tag "$TAG" --presets relaxed3-BM --models BM-4 \
  || echo "!! relaxed3 exited $?"
PYTHONUNBUFFERED=1 python scripts/variants.py run --out variants \
  --hard 3600 --tag "$TAG" --presets stress-BM --models BM-4 \
  || echo "!! stressbm exited $?"
for p in targeted-BM targeted2-GC targeted2-AC targeted2-BM targeted-DF; do
  echo "--- $p (600s tier) ($(date -u +%H:%M:%S)) ---"
  PYTHONUNBUFFERED=1 python scripts/variants.py run --out variants \
    --hard 600 --tag "$TAG" --presets "$p" || echo "!! $p exited $?"
done
echo "--- BM-S2 scaled clean re-run ($(date -u +%H:%M:%S)) ---"
# make is idempotent; guarantees the zoo exists on a fresh checkout (the
# run stage fails loudly on an empty zoo, and || echo would swallow it).
PYTHONUNBUFFERED=1 python scripts/scaled_stress.py make \
  || echo "!! scaled make exited $?"
FAIRIFY_TPU_MODEL_ROOT="$PWD/models_scaled" PYTHONUNBUFFERED=1 \
  python scripts/scaled_stress.py run --hard 900 --tag "$TAG-clean" \
  || echo "!! scaled rerun exited $?"
echo "=== r5b complete ($(date -u +%H:%M:%S)) ==="

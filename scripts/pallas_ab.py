#!/usr/bin/env python
"""On-chip A/B: device-resident mega-loop vs the per-chunk launch loop.

Lineage — this harness was born as the Pallas fused-IBP A/B and produced
``audits/pallas_ab_r5.json`` (pallas 0.97x on GC-1: on a launch-bound
tunnelled chip a fused-VMEM kernel cannot beat the already-fused XLA jit;
the kernel was removed per VERDICT r4 weak #4, last tree with it at commit
7b248ba).  The round-14 successor A/Bs the NEXT launch-economy lever on
the same stage-0 call sites: the ``lax.scan`` mega-loop (ISSUE 14,
DESIGN.md §17) that certifies a whole segment of grid chunks in ONE
``obs_jit`` launch, against the per-chunk multi-launch loop it replaces.

Per config (GC-1 and an AC prefix), both arms run the identical fused
certify+attack pass over the same grid prefix through
``sweep._stage0_certify_and_attack``:

* **chunked** — ``mega_chunks=0``: one launch per grid chunk (the pre-r14
  loop, kept as the mesh/non-CROWN fallback);
* **mega** — whole-prefix segments: ONE launch for all chunks.

and the harness records wall time, launch counts, speedup, and checks the
two arms' (unsat, sat, witness) maps are bit-identical — the invariant
tests/test_mega.py pins in tier-1.

Usage: python scripts/pallas_ab.py [--iters 5] [--out audits/mega_ab_r14.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
os.chdir(ROOT)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--chunk", type=int, default=256,
                    help="grid chunk for the A/B (small enough that the "
                         "prefix spans several chunks)")
    ap.add_argument("--prefix", type=int, default=2048,
                    help="partition-grid prefix per config")
    ap.add_argument("--out", default="audits/mega_ab_r14.json")
    args = ap.parse_args()

    import jax
    import numpy as np

    from fairify_tpu.models import zoo
    from fairify_tpu.utils import profiling
    from fairify_tpu.utils.cache import enable_persistent_cache
    from fairify_tpu.verify import presets, sweep
    from fairify_tpu.verify.property import encode

    enable_persistent_cache()
    out = {"platform": jax.devices()[0].platform,
           "device": str(jax.devices()[0]),
           "iters": args.iters, "configs": []}

    for preset_name, model in (("GC", "GC-1"), ("AC", "AC-1")):
        cfg0 = presets.get(preset_name).with_(
            result_dir="/tmp/mega_ab", grid_chunk=args.chunk)
        try:
            net = zoo.load(cfg0.dataset, model)
        except (OSError, KeyError):
            # Reference zoo assets absent (bare container): synthetic twin
            # at the domain width — the A/B measures launch economics, not
            # this particular net's verdicts.
            from fairify_tpu.models.train import init_mlp

            net = init_mlp((len(cfg0.query().columns), 50, 1), seed=0)
            model += " (synthetic twin)"
        enc = encode(cfg0.query())
        _, lo, hi = sweep.build_partitions(cfg0)
        P = min(lo.shape[0], args.prefix)
        lo, hi = lo[:P], hi[:P]
        n_chunks = (P + args.chunk - 1) // args.chunk

        arms = {"chunked": cfg0.with_(mega_chunks=0),
                "mega": cfg0.with_(mega_chunks=n_chunks)}
        rows, results, launches = {}, {}, {}
        for name, cfg in arms.items():
            # One untimed pass per arm compiles its kernels at the exact
            # shapes, so the timed medians measure launches, not traces.
            sweep._stage0_certify_and_attack(net, enc, lo, hi, cfg)
            times = []
            for _ in range(args.iters):
                l0 = profiling.launch_count()
                t0 = time.perf_counter()
                res = sweep._stage0_certify_and_attack(net, enc, lo, hi, cfg)
                times.append(time.perf_counter() - t0)
                launches[name] = profiling.launch_count() - l0
            results[name] = res
            rows[name] = sorted(times)[len(times) // 2]

        u_c, s_c, w_c = results["chunked"]
        u_m, s_m, w_m = results["mega"]
        equal = (np.array_equal(u_c, u_m) and np.array_equal(s_c, s_m)
                 and set(w_c) == set(w_m)
                 and all(np.array_equal(w_c[k][0], w_m[k][0])
                         and np.array_equal(w_c[k][1], w_m[k][1])
                         for k in w_c))
        out["configs"].append({
            "preset": preset_name, "model": model, "partitions": int(P),
            "grid_chunk": args.chunk, "chunks": int(n_chunks),
            "stage0_ms": {k: round(v * 1e3, 2) for k, v in rows.items()},
            "launches": {k: int(v) for k, v in launches.items()},
            "speedup_mega": round(rows["chunked"] / rows["mega"], 3),
            "verdicts_bit_equal": bool(equal),
        })
        print(json.dumps(out["configs"][-1]), flush=True)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fp:
        json.dump(out, fp, indent=1)
    print(json.dumps({"wrote": args.out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())

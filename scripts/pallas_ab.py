#!/usr/bin/env python
"""On-chip A/B: Pallas fused-IBP kernel vs the XLA interval path.

HISTORICAL RECORD — this harness produced ``audits/pallas_ab_r5.json``
(GC-1: pallas 0.97x, AC-1: 0.83x isolated / 1.08x e2e, masks identical):
on the tunnelled single chip every stage-0 call is launch-bound (~100 ms
relay round-trip), so a fused-VMEM kernel cannot beat the already-fused
XLA jit.  Per VERDICT r4 weak #4 ("prove it or remove it") the kernel
was removed right after this run; to re-run the A/B, check out the tree
at commit 7b248ba (the last with ``ops/pallas_ibp.py``).

VERDICT r4 weak #4: the flag-gated ``ops/pallas_ibp.py`` kernel was never
benchmarked on the real chip — "prove it or remove it".  This harness times
the exact stage-0 pruning call both paths serve
(:func:`pruning.sound_prune_grid` via ``_sim_and_bounds``'s ``pallas`` flag,
plus the isolated bounds kernels) on the GC and AC grids, checks the two
paths' pruning masks agree, and writes ``audits/pallas_ab_r5.json``.

Usage: python scripts/pallas_ab.py [--iters 5]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
os.chdir(ROOT)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--out", default="audits/pallas_ab_r5.json")
    args = ap.parse_args()

    try:
        from fairify_tpu.ops import pallas_ibp
    except ImportError:
        raise SystemExit(
            "ops/pallas_ibp.py was removed after this A/B concluded the "
            "kernel gives no win on the launch-bound tunnelled chip "
            "(audits/pallas_ab_r5.json holds the recorded numbers).  To "
            "re-run, check out commit 7b248ba — the last tree with the "
            "kernel.")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fairify_tpu.models import zoo
    from fairify_tpu.ops import interval as interval_ops
    from fairify_tpu.utils.cache import enable_persistent_cache
    from fairify_tpu.utils.prng import grid_keys
    from fairify_tpu.verify import presets, pruning, sweep

    enable_persistent_cache()
    out = {"platform": jax.devices()[0].platform,
           "device": str(jax.devices()[0]), "configs": []}

    for preset_name, model in (("GC", "GC-1"), ("AC", "AC-1")):
        cfg = presets.get(preset_name).with_(result_dir="/tmp/pallas_ab")
        net = zoo.load(cfg.dataset, model)
        _, lo, hi = sweep.build_partitions(cfg)
        P = min(lo.shape[0], 2048)
        lo, hi = lo[:P], hi[:P]
        flo = jnp.asarray(lo, jnp.float32)
        fhi = jnp.asarray(hi, jnp.float32)
        if not pallas_ibp.available(net):
            out["configs"].append({"preset": preset_name, "model": model,
                                   "skipped": "net wider than LANE pad"})
            continue

        # (a) isolated bounds kernels — the component the Pallas kernel
        # replaces (jitted wrappers, block_until_ready timing).
        xla_fn = jax.jit(lambda l, h: interval_ops.network_bounds(net, l, h))
        pl_fn = jax.jit(
            lambda l, h: interval_ops.network_bounds_pallas(net, l, h))
        rows = {}
        for name, fn in (("xla", xla_fn), ("pallas", pl_fn)):
            r = fn(flo, fhi)  # compile
            jax.block_until_ready(r)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                jax.block_until_ready(fn(flo, fhi))
            rows[name] = (time.perf_counter() - t0) / args.iters
        # Mask agreement: the consumer of these bounds is the dead-neuron
        # criterion; both paths must prune identically.
        bx = xla_fn(flo, fhi)
        bp = pl_fn(flo, fhi)
        dead_x = [np.asarray(d) for d in interval_ops.dead_from_ws_ub(bx)]
        dead_p = [np.asarray(d) for d in interval_ops.dead_from_ws_ub(bp)]
        masks_equal = all(np.array_equal(a, b)
                          for a, b in zip(dead_x, dead_p))

        # (b) end-to-end stage-0 prune (sim + bounds fused in one jit) with
        # the pallas flag off/on — what the sweep actually pays.
        e2e = {}
        for name, flag in (("xla", False), ("pallas", True)):
            keys = grid_keys(cfg.seed, 0, P)
            r = pruning._sim_and_bounds(net, keys, flo, fhi, cfg.sim_size,
                                        pallas=flag, with_sim=False)
            jax.block_until_ready(r)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                jax.block_until_ready(pruning._sim_and_bounds(
                    net, keys, flo, fhi, cfg.sim_size, pallas=flag,
                    with_sim=False))
            e2e[name] = (time.perf_counter() - t0) / args.iters
        out["configs"].append({
            "preset": preset_name, "model": model, "partitions": int(P),
            "bounds_ms": {k: round(v * 1e3, 2) for k, v in rows.items()},
            "bounds_speedup_pallas": round(rows["xla"] / rows["pallas"], 3),
            "prune_e2e_ms": {k: round(v * 1e3, 2) for k, v in e2e.items()},
            "prune_speedup_pallas": round(e2e["xla"] / e2e["pallas"], 3),
            "dead_masks_equal": bool(masks_equal),
        })
        print(json.dumps(out["configs"][-1]), flush=True)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fp:
        json.dump(out, fp, indent=1)
    print(json.dumps({"wrote": args.out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())

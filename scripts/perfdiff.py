#!/usr/bin/env python
"""Perf regression gate: diff two bench/throughput records within noise bands.

    python scripts/perfdiff.py BASELINE CANDIDATE [--rel-guard G] [--rel-tol T]

Each input is either a bench output (one JSON object per line, as printed
by ``python -m fairify_tpu bench`` and archived in ``BENCH_r*.json``) or a
sweep throughput record (``<preset>-<model>.throughput.json``).  Exit code
1 iff at least one shared metric regressed; 0 otherwise — the CI gate the
bench trajectory runs behind.

**Noise-band rule** (docs/DESIGN.md §8): bench records carry a per-metric
repeat band [min, max] around the quoted median.  A higher-is-better metric
is a regression iff the candidate's band falls *entirely below* the
baseline's (``cand.max < base.min``) AND the gap clears a relative guard
(default 2% of the baseline value) — so identical runs and band-overlapping
noise always pass, while a genuine slowdown (disjoint bands) always fails.
Records without repeats (throughput JSONs) have zero-width bands, where the
guard alone separates noise from signal; their default guard is the wider
``--rel-tol`` (20%) since a single sample carries no variance evidence.

Lower-is-better counters (``device_launches``, ``n_compiles``,
``compile_s``, ``launches_per_model``) regress when the candidate exceeds
baseline by the tolerance: launch/compile counts are deterministic per
config, so growth means a lost fusion, fresh shape churn, or (for
``launches_per_model``) the stage-0 mega-loop silently degrading to the
per-chunk launch loop.

**MULTICHIP records** (``MULTICHIP_r*.json``, and the richer output of
``scripts/multichip_scaling.py``) are a third shape: a single JSON object
with ``n_devices``/``ok`` plus optionally per-mesh-size throughput
(``model_partitions_per_sec: {"1": x, "8": y}``) and the 1→N ``scaling_x``
ratio.  They gate as higher-is-better zero-width-band metrics
(``multichip.ok``, ``multichip.n_devices``, ``multichip.pps@<n>dev``,
``multichip.scaling_x``): an ``ok`` flip or a fleet shrunken by even one
device fails outright (deterministic metrics gate strictly), while the
single-sample throughput/scaling numbers fail past the band-less noise
tolerance (``--rel-tol``).  ``ok`` means run-health in BOTH record
shapes (the driver's dry-run success; the scaling harness's cross-mesh
verdict consistency), so a minimal driver baseline gates a rich scaling
candidate: the throughput metrics simply join the gate once both sides
carry them.

**SERVE records** (``SERVE_r*.json`` from ``scripts/serve_bench.py``;
``"kind": "SERVE"``) gate the verification service: per client level,
``serve.p95_ms@<n>c`` and ``serve.deadline_miss_rate@<n>c`` are
**lower-is-better** single samples (p95 growth past ``--rel-tol`` fails;
miss rate gets a 2-point absolute floor on top so a 0.0 baseline doesn't
fail on one unlucky miss), ``serve.requests_per_s@<n>c`` and
``serve.batch_occupancy@<n>c`` gate higher-is-better, and
``serve.warm_xla_compiles`` is lower-is-better with the same 0.5 absolute
floor as ``n_compiles`` — a warm server that starts recompiling fails
outright.  The overload-survival fields (ISSUE 11): ``serve.shed_rate@<n>c``
gates lower-is-better with a 10-point absolute floor (sheds are honest
triage, but a step change in shed volume at equal load is a capacity
regression) and ``serve.preemptions@<n>c`` lower-is-better with a
2-count floor; ``serve.cold_restart_xla_compiles`` /
``serve.cold_restart_compile_s`` gate the zero-cold-start contract — a
restarted process recompiling anything it should have loaded from the
executable cache fails (0.5 floors match ``n_compiles``/``compile_s``).
A ``procfleet`` block (``--replica-procs`` runs, ISSUE 15) adds
``serve.replica_deaths`` / ``serve.replica_restarts`` /
``serve.replica_rehomed`` — lower-is-better with a 2-count floor: a fleet
that starts dying or flapping at equal load is a containment regression
even when failover keeps the latency columns green.
``serve.batch_occupancy@<n>c`` is emitted only for shed-free levels: under
admission shedding it measures admitted workload shape, not batcher
packing, so a shedding candidate simply drops the metric (a ``missing``
warning, not a regression).  A ``trace_ab`` block (``serve_bench
--trace-ab``, DESIGN.md §19) adds ``serve.trace_pps_on`` (traced goodput,
higher-is-better) and ``serve.trace_overhead_rel`` (tracing's on-vs-off
goodput cost, lower-is-better with a 5-point absolute floor) — tracing
that stops being within-noise fails the gate.

**SMT records** (``audits/SMT_r*.json`` from ``scripts/smt_bench.py``;
``"kind": "SMT"``) gate the out-of-process solver pool: per worker count,
``smt.qps@<n>w`` (queries/s) and the 1→N ``smt.speedup_x`` ratio gate
higher-is-better as band-less single samples, while
``smt.worker_crashes`` and ``smt.memouts`` are **lower-is-better** with a
0.5 absolute floor — a healthy bench run contains ZERO worker deaths, so
any growth from 0 is a containment regression, not noise.

**Decided fraction** (obs.funnel, DESIGN.md §20): bench lines and
throughput records carrying ``decided_fraction`` gate it
**higher-is-better with an absolute floor** (default 0.02): fractions
live in [0, 1], so the relative band-less tolerance (20%) would wave
through a funnel collapse from 0.99 to 0.85 — instead ANY drop past two
absolute points fails.  The metric joins the gate only when both sides
carry it (older baselines simply don't gate it yet).

**Integrity gates** (ISSUE 19, DESIGN.md §21): bench lines and throughput
records carry ``integrity_violations`` / ``ledger_crc_mismatch`` (nested
under a throughput record's ``resilience`` block; hoisted at load) —
lower-is-better with a 0.5 floor, i.e. ZERO growth: a healthy run detects
no corruption and drops no CRC-failed ledger rows.  The bench headline's
``integrity_ab`` block adds ``integrity_recheck_overhead_rel`` — what the
sampled device-recheck costs in decided throughput at the benched
``DEFAULT_RECHECK_RATE`` — lower-is-better with a 5-point absolute floor
(same measurement-grain rule as tracing overhead).  A chaos-matrix JSONL
archive (rows keyed by ``cell``, ``audits/chaos_integrity_r*.jsonl``)
aggregates into ``chaos.sdc_escaped`` (decided-WRONG verdicts that escaped
containment: any growth from 0 fails outright) and ``chaos.failed_cells``.

``--self-test`` runs the built-in contract checks (wired into tier-1 via
``tests/test_perfdiff.py``): identical records pass, a 2x slowdown fails,
overlapping noisy bands pass, doubled launches fail.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

# Higher-is-better rate fields of a throughput record; everything a bench
# line quotes under "value" is also a rate.
_THROUGHPUT_RATES = ("partitions_per_sec", "partitions_per_sec_per_chip")
# Lower-is-better counters shared by bench lines and throughput records,
# with an absolute growth floor so a ZERO baseline still gates: a warm run's
# healthy state is n_compiles=0/compile_s=0.0, and growth from 0 is exactly
# the shape-churn regression this tool exists to catch (a relative-only rule
# would skip it).  The compile_s floor of 0.5s ignores persistent-cache
# reload jitter while catching any real recompile.
_LOWER_BETTER = {"device_launches": 0.5, "n_compiles": 0.5, "compile_s": 0.5,
                 # Launch economy of the stage-0 mega-loop (ISSUE 14):
                 # launches per model is O(segments), and a slide back
                 # toward O(chunks) — a broken mega path silently falling
                 # to the per-chunk loop — is a regression even when the
                 # wall-clock rate hides it behind noise.
                 "launches_per_model": 0.5,
                 # Result-integrity counters (ISSUE 19, DESIGN.md §21): a
                 # healthy run detects ZERO corruption and drops ZERO
                 # CRC-failed ledger rows, so ANY growth from 0 is a trust
                 # regression, not noise (zero-growth gate).
                 "integrity_violations": 0.5,
                 "ledger_crc_mismatch": 0.5}


def _metric_key(metric: str) -> str:
    """Stable join key for a bench metric string: the text before the
    parenthesised run detail (counts/medians vary run to run by design)."""
    return metric.split(" (", 1)[0].strip()


def _bench_record(obj: dict) -> Optional[dict]:
    if "metric" not in obj or "value" not in obj:
        return None
    v = obj["value"]
    rec = {"value": v, "min": obj.get("min", v), "max": obj.get("max", v),
           "banded": "min" in obj and "max" in obj}
    for k in _LOWER_BETTER:
        if obj.get(k) is not None:
            rec[k] = obj[k]
    return rec


def _flat(v: float, strict: bool = False) -> dict:
    """Zero-width-band record for a single-sample metric.

    ``strict`` marks a deterministic metric (a flag, a device count): ANY
    decrease is a regression, no noise tolerance applies.
    """
    v = float(v)
    rec = {"value": v, "min": v, "max": v, "banded": False}
    if strict:
        rec["strict"] = True
    return rec


def _flat_lower(v: float, floor: float = 0.0) -> dict:
    """Zero-width-band record for a LOWER-is-better single sample.

    Regression iff the candidate exceeds baseline by the relative
    tolerance plus ``floor`` absolute slack (the floor lets a 0.0
    baseline — miss rate, warm compiles — gate growth without failing on
    measurement grain).
    """
    v = float(v)
    return {"value": v, "min": v, "max": v, "banded": False,
            "lower": True, "floor": float(floor)}


def _flat_fraction(v: float, floor: float = 0.02) -> dict:
    """Zero-width-band record for a HIGHER-is-better bounded fraction.

    Regression iff the candidate falls more than ``floor`` ABSOLUTE points
    below baseline: a [0, 1] fraction under the relative tolerance would
    let a funnel collapse ride inside 20% "noise"."""
    v = float(v)
    return {"value": v, "min": v, "max": v, "banded": False,
            "fraction": True, "floor": float(floor)}


def _serve_records(obj: dict) -> Dict[str, dict]:
    """Metrics of one SERVE record (``scripts/serve_bench.py``)."""
    if obj.get("kind") != "SERVE":
        return {}
    out: Dict[str, dict] = {}
    if obj.get("warm_xla_compiles") is not None:
        out["serve.warm_xla_compiles"] = _flat_lower(
            obj["warm_xla_compiles"], floor=0.5)
    for n, row in sorted((obj.get("clients") or {}).items(),
                         key=lambda kv: int(kv[0])):
        if not isinstance(row, dict):
            continue
        if row.get("p95_ms") is not None:
            out[f"serve.p95_ms@{n}c"] = _flat_lower(row["p95_ms"])
        if row.get("deadline_miss_rate") is not None:
            out[f"serve.deadline_miss_rate@{n}c"] = _flat_lower(
                row["deadline_miss_rate"], floor=0.02)
        if row.get("shed_rate") is not None:
            out[f"serve.shed_rate@{n}c"] = _flat_lower(
                row["shed_rate"], floor=0.10)
        if row.get("preemptions") is not None:
            out[f"serve.preemptions@{n}c"] = _flat_lower(
                row["preemptions"], floor=2.0)
        if row.get("requests_per_s") is not None:
            out[f"serve.requests_per_s@{n}c"] = _flat(row["requests_per_s"])
        if row.get("batch_occupancy_mean") is not None \
                and not row.get("shed_rate"):
            # Occupancy is a coalescing-health gate only at shed-free
            # levels: under admission shedding it measures how much work
            # was ADMITTED per window (workload shape), not how well the
            # batcher packed it — a level that honestly sheds half its
            # burst must not fail for coalescing "worse" than a level
            # that queued everything.  The coalesced-vs-sequential launch
            # check in serve_bench still guards coalescing itself.
            out[f"serve.batch_occupancy@{n}c"] = _flat(
                row["batch_occupancy_mean"])
    tab = obj.get("trace_ab")
    if isinstance(tab, dict):
        # Tracing-overhead A/B (serve_bench --trace-ab, DESIGN.md §19):
        # traced goodput gates higher-is-better like any pps metric, and
        # the on-vs-off overhead fraction gates lower-is-better with a
        # 5-point absolute floor (single-sample measurement grain).
        if tab.get("pps_on") is not None:
            out["serve.trace_pps_on"] = _flat(tab["pps_on"])
        if tab.get("overhead_rel") is not None:
            out["serve.trace_overhead_rel"] = _flat_lower(
                max(float(tab["overhead_rel"]), 0.0), floor=0.05)
    cold = obj.get("cold_restart")
    if isinstance(cold, dict):
        if cold.get("n_compiles") is not None:
            out["serve.cold_restart_xla_compiles"] = _flat_lower(
                cold["n_compiles"], floor=0.5)
        if cold.get("compile_s") is not None:
            out["serve.cold_restart_compile_s"] = _flat_lower(
                cold["compile_s"], floor=0.5)
    pf = obj.get("procfleet")
    if isinstance(pf, dict):
        # Process-fleet health (ISSUE 15): kill/restart/re-home counters
        # gate lower-is-better with a 2-count floor — a replica fleet
        # that starts dying or flapping at equal load is a containment
        # regression even when the latency columns survive it (that is
        # the point of failover).
        for key, metric in (("replica_deaths", "serve.replica_deaths"),
                            ("replica_restarts", "serve.replica_restarts"),
                            ("rehomed", "serve.replica_rehomed")):
            if pf.get(key) is not None:
                out[metric] = _flat_lower(pf[key], floor=2.0)
    return out


def _smt_records(obj: dict) -> Dict[str, dict]:
    """Metrics of one SMT pool record (``scripts/smt_bench.py``)."""
    if obj.get("kind") != "SMT":
        return {}
    out: Dict[str, dict] = {}
    for n, row in sorted((obj.get("workers") or {}).items(),
                         key=lambda kv: int(kv[0])):
        if isinstance(row, dict) and row.get("queries_per_s") is not None:
            out[f"smt.qps@{n}w"] = _flat(row["queries_per_s"])
    if obj.get("speedup_x") is not None:
        out["smt.speedup_x"] = _flat(obj["speedup_x"])
    if obj.get("worker_crashes") is not None:
        out["smt.worker_crashes"] = _flat_lower(obj["worker_crashes"],
                                                floor=0.5)
    if obj.get("memouts") is not None:
        out["smt.memouts"] = _flat_lower(obj["memouts"], floor=0.5)
    return out


def _multichip_records(obj: dict) -> Dict[str, dict]:
    """Metrics of one MULTICHIP record (``n_devices`` marks the shape).

    The minimal driver records ({n_devices, rc, ok}) gate on the ok flag
    and the fleet size; ``scripts/multichip_scaling.py`` adds per-mesh
    throughput and the 1→N scaling factor, each its own gated metric.
    The ok flag and fleet size are deterministic, so they gate strictly —
    losing ONE chip fails; the throughput/scaling numbers are single
    samples and keep the band-less noise tolerance.
    """
    if "n_devices" not in obj or "metric" in obj:
        return {}
    out: Dict[str, dict] = {}
    if "ok" in obj:
        out["multichip.ok"] = _flat(1.0 if obj["ok"] else 0.0, strict=True)
    out["multichip.n_devices"] = _flat(obj["n_devices"], strict=True)
    pps = obj.get("model_partitions_per_sec")
    if isinstance(pps, dict):
        for n, v in pps.items():
            out[f"multichip.pps@{n}dev"] = _flat(v)
    if obj.get("scaling_x") is not None:
        out["multichip.scaling_x"] = _flat(obj["scaling_x"])
    return out


def load_records(path: str) -> Dict[str, dict]:
    """Metric key → record.  Accepts bench JSONL (one object per line) or a
    single throughput/headline JSON object; unparseable lines are skipped
    (bench output may interleave stderr noise when captured loosely)."""
    with open(path) as fp:
        text = fp.read()
    objs = []
    try:
        parsed = json.loads(text)
        objs = parsed if isinstance(parsed, list) else [parsed]
    except json.JSONDecodeError:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                objs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    out: Dict[str, dict] = {}
    # Driver-wrapper bench archives (BENCH_r*.json: {"cmd", "rc", "tail",
    # "parsed", ...}) carry the bench JSON lines inside the "tail" string
    # and the headline under "parsed" — unwrap both so
    # `perfdiff BENCH_r05.json BENCH_r06.json` gates archived rounds
    # directly.
    unwrapped = []
    for obj in objs:
        unwrapped.append(obj)  # wrappers may ALSO be records themselves
        # (the minimal MULTICHIP driver shape carries n_devices + a tail)
        if isinstance(obj, dict) and "metric" not in obj \
                and ("tail" in obj or "parsed" in obj):
            # "parsed" first: it is the driver's minimal extract of the
            # last tail line, so the richer tail record (repeat bands,
            # launch counters) wins the by-key dedup below.
            if isinstance(obj.get("parsed"), dict):
                unwrapped.append(obj["parsed"])
            for line in str(obj.get("tail", "")).splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        unwrapped.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
    chaos_rows = chaos_sdc = chaos_bad = 0
    for obj in unwrapped:
        if not isinstance(obj, dict):
            continue
        if isinstance(obj.get("resilience"), dict):
            # Throughput records nest the integrity counters under the
            # resilience block — hoist them to the gate's flat keys
            # (explicit top-level values win).
            obj = {**{k: obj["resilience"][k]
                      for k in ("integrity_violations", "ledger_crc_mismatch")
                      if k in obj["resilience"]}, **obj}
        if "cell" in obj and ("ok" in obj or "sdc_escaped" in obj):
            # Chaos-matrix archive row: aggregated below into the
            # file-level SDC-escape and cell-health gates.
            chaos_rows += 1
            chaos_sdc += int(obj.get("sdc_escaped") or 0)
            chaos_bad += 0 if obj.get("ok", True) else 1
            continue
        rec = _bench_record(obj)
        if rec is not None:
            key = _metric_key(obj["metric"])
            out[key] = rec
            if obj.get("decided_fraction") is not None:
                out[f"{key}.decided_fraction"] = _flat_fraction(
                    obj["decided_fraction"])
            iab = obj.get("integrity_ab")
            if isinstance(iab, dict) and iab.get("overhead_rel") is not None:
                # Sampled-recheck cost A/B (bench headline): gate the
                # overhead fraction like the tracing A/B — lower is
                # better, 5-point absolute floor for single-sample grain.
                out["integrity_recheck_overhead_rel"] = _flat_lower(
                    max(float(iab["overhead_rel"]), 0.0), floor=0.05)
            continue
        sv = _serve_records(obj)
        if sv:
            out.update(sv)
            continue
        sm = _smt_records(obj)
        if sm:
            out.update(sm)
            continue
        mc = _multichip_records(obj)
        if mc:
            out.update(mc)
            continue
        # Throughput JSON: every rate present gets its own zero-width-band
        # record (total AND per-chip — a device-count change can hold one
        # steady while the other regresses), counters attached to the first.
        first = True
        for rate in _THROUGHPUT_RATES:
            if obj.get(rate) is not None:
                v = float(obj[rate])
                trec = {"value": v, "min": v, "max": v, "banded": False}
                if first:
                    for k in _LOWER_BETTER:
                        if obj.get(k) is not None:
                            trec[k] = obj[k]
                    first = False
                out[rate] = trec
        if not first and obj.get("decided_fraction") is not None:
            # Only genuine throughput records (a rate matched above) carry
            # the funnel's decided fraction into the gate.
            out["decided_fraction"] = _flat_fraction(obj["decided_fraction"])
    if chaos_rows:
        out["chaos.sdc_escaped"] = _flat_lower(chaos_sdc, floor=0.5)
        out["chaos.failed_cells"] = _flat_lower(chaos_bad, floor=0.5)
    return out


def compare(base: Dict[str, dict], cand: Dict[str, dict],
            rel_guard: float = 0.02, rel_tol: float = 0.2) -> List[dict]:
    """Regression findings over the metrics both sides carry."""
    findings: List[dict] = []
    for key in sorted(base):
        b = base[key]
        c = cand.get(key)
        if c is None:
            findings.append({"metric": key, "kind": "missing",
                             "detail": "metric absent from candidate"})
            continue
        # Higher-is-better bounded fractions (decided_fraction): fail on
        # any drop past the absolute floor — no relative tolerance.
        if b.get("fraction"):
            floor = b.get("floor", 0.02)
            if b["min"] - c["max"] > floor:
                findings.append({
                    "metric": key, "kind": "regression",
                    "detail": (f"fell {b['value']} -> {c['value']} "
                               f"(> {floor} absolute drop; higher is "
                               f"better)")})
            continue
        # Lower-is-better single samples (SERVE latency/miss-rate): grow
        # past the tolerance plus the metric's absolute floor and fail.
        if b.get("lower"):
            floor = b.get("floor", 0.0)
            if c["min"] - b["max"] > floor + rel_tol * abs(b["value"]):
                findings.append({
                    "metric": key, "kind": "regression",
                    "detail": (f"grew {b['value']} -> {c['value']} "
                               f"(> baseline + {rel_tol:.2f}x + {floor} "
                               f"floor; lower is better)")})
            continue
        # Higher-is-better rate with the noise-band rule; strict metrics
        # (deterministic flags/counts) regress on ANY decrease.
        if b.get("strict"):
            guard = 0.0
        else:
            guard = rel_guard if (b["banded"] and c["banded"]) else rel_tol
        gap = b["min"] - c["max"]
        if gap > 0 and gap > guard * max(abs(b["value"]), 1e-12):
            findings.append({
                "metric": key, "kind": "regression",
                "detail": (f"candidate band [{c['min']}, {c['max']}] below "
                           f"baseline band [{b['min']}, {b['max']}] "
                           f"(median {b['value']} -> {c['value']})")})
        # Lower-is-better counters both records carry.
        for lk, floor in _LOWER_BETTER.items():
            bv, cv = b.get(lk), c.get(lk)
            if bv is None:
                continue
            if cv is None:
                findings.append({
                    "metric": f"{key}.{lk}", "kind": "missing",
                    "detail": f"{lk} absent from candidate "
                              f"(baseline has {bv})"})
                continue
            if cv > bv * (1.0 + rel_tol) + floor:
                findings.append({
                    "metric": f"{key}.{lk}", "kind": "regression",
                    "detail": f"{lk} grew {bv} -> {cv} "
                              f"(> {1.0 + rel_tol:.2f}x baseline + {floor})"})
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("baseline", nargs="?")
    ap.add_argument("candidate", nargs="?")
    ap.add_argument("--rel-guard", type=float, default=0.02,
                    help="disjoint-band gap guard for banded metrics "
                         "(fraction of baseline; default 0.02)")
    ap.add_argument("--rel-tol", type=float, default=0.2,
                    help="tolerance for band-less metrics and lower-better "
                         "counters (default 0.2)")
    ap.add_argument("--json", action="store_true",
                    help="print findings as one JSON line")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in contract checks and exit")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if not args.baseline or not args.candidate:
        ap.error("baseline and candidate are required (or --self-test)")
    base = load_records(args.baseline)
    cand = load_records(args.candidate)
    if not base:
        print(f"perfdiff: no recognizable records in {args.baseline}",
              file=sys.stderr)
        return 2
    findings = compare(base, cand, rel_guard=args.rel_guard,
                       rel_tol=args.rel_tol)
    regressions = [f for f in findings if f["kind"] == "regression"]
    if args.json:
        print(json.dumps({"metrics": len(base), "findings": findings,
                          "regressed": len(regressions)}))
    else:
        for f in findings:
            tag = "REGRESSION" if f["kind"] == "regression" else "warning"
            print(f"perfdiff {tag}: {f['metric']}: {f['detail']}")
        verdict = "FAIL" if regressions else "ok"
        print(f"perfdiff {verdict}: {len(base)} metric(s) compared, "
              f"{len(regressions)} regressed")
    return 1 if regressions else 0


def self_test() -> int:
    """Contract checks for the noise-band rule (tier-1, test_perfdiff.py)."""
    base = {"pps": {"value": 50.0, "min": 46.0, "max": 53.0, "banded": True,
                    "device_launches": 120, "n_compiles": 0}}
    same = {"pps": dict(base["pps"])}
    slow = {"pps": {"value": 25.0, "min": 23.0, "max": 26.5, "banded": True,
                    "device_launches": 120, "n_compiles": 0}}
    noisy = {"pps": {"value": 47.0, "min": 44.0, "max": 49.0, "banded": True,
                     "device_launches": 120, "n_compiles": 0}}
    launchy = {"pps": {"value": 50.0, "min": 46.0, "max": 53.0, "banded": True,
                       "device_launches": 240, "n_compiles": 0}}
    lean = {"pps": {"value": 50.0, "min": 46.0, "max": 53.0, "banded": True,
                    "launches_per_model": 3.0}}
    chunky = {"pps": {"value": 50.0, "min": 46.0, "max": 53.0, "banded": True,
                      "launches_per_model": 24.0}}
    warm = {"pps": {"value": 50.0, "min": 46.0, "max": 53.0, "banded": True,
                    "n_compiles": 0, "compile_s": 0.0}}
    churned = {"pps": {"value": 50.0, "min": 46.0, "max": 53.0, "banded": True,
                       "n_compiles": 6, "compile_s": 14.0}}
    jitter = {"pps": {"value": 50.0, "min": 46.0, "max": 53.0, "banded": True,
                      "n_compiles": 0, "compile_s": 0.3}}
    mc_base = _multichip_records(
        {"n_devices": 8, "ok": True,
         "model_partitions_per_sec": {"1": 100.0, "8": 450.0},
         "scaling_x": 4.5})
    mc_same = dict(mc_base)
    mc_broken = _multichip_records(
        {"n_devices": 8, "ok": False,
         "model_partitions_per_sec": {"1": 100.0, "8": 450.0},
         "scaling_x": 4.5})
    mc_flat = _multichip_records(
        {"n_devices": 8, "ok": True,
         "model_partitions_per_sec": {"1": 100.0, "8": 110.0},
         "scaling_x": 1.1})
    mc_shrunk = _multichip_records({"n_devices": 4, "ok": True})
    mc_one_lost = _multichip_records(
        {"n_devices": 7, "ok": True,
         "model_partitions_per_sec": {"1": 100.0, "8": 450.0},
         "scaling_x": 4.5})
    mc_jitter = _multichip_records(
        {"n_devices": 8, "ok": True,
         "model_partitions_per_sec": {"1": 98.0, "8": 430.0},
         "scaling_x": 4.4})
    sv = {"kind": "SERVE", "warm_xla_compiles": 0,
          "clients": {"4": {"p95_ms": 800.0, "deadline_miss_rate": 0.0,
                            "requests_per_s": 5.0,
                            "batch_occupancy_mean": 3.5}}}
    sv_base = _serve_records(sv)
    svo = {"kind": "SERVE", "warm_xla_compiles": 0,
           "clients": {"16": {"p95_ms": 9000.0, "deadline_miss_rate": 0.0,
                              "shed_rate": 0.25, "preemptions": 1,
                              "requests_per_s": 2.0,
                              "batch_occupancy_mean": 6.0}},
           "cold_restart": {"n_compiles": 0, "compile_s": 0.1}}
    svo_base = _serve_records(svo)
    svo_same = _serve_records(json.loads(json.dumps(svo)))
    svo_sheddy = _serve_records(
        {**svo, "clients": {"16": {**svo["clients"]["16"],
                                   "shed_rate": 0.8}}})
    svo_thrashy = _serve_records(
        {**svo, "clients": {"16": {**svo["clients"]["16"],
                                   "preemptions": 14}}})
    svo_jitter = _serve_records(
        {**svo, "clients": {"16": {**svo["clients"]["16"],
                                   "shed_rate": 0.31, "preemptions": 3}}})
    svo_coldly = _serve_records(
        {**svo, "cold_restart": {"n_compiles": 9, "compile_s": 21.0}})
    svp = {"kind": "SERVE", "replica_procs": 2,
           "clients": {"4": {"p95_ms": 900.0, "deadline_miss_rate": 0.0,
                             "requests_per_s": 4.0}},
           "procfleet": {"replica_deaths": 0, "replica_restarts": 0,
                         "rehomed": 0, "fleet_n_compiles": 9}}
    svp_base = _serve_records(svp)
    svp_same = _serve_records(json.loads(json.dumps(svp)))
    svp_flappy = _serve_records(
        {**svp, "procfleet": {**svp["procfleet"], "replica_deaths": 6,
                              "replica_restarts": 6, "rehomed": 5}})
    svp_blip = _serve_records(
        {**svp, "procfleet": {**svp["procfleet"], "replica_deaths": 1,
                              "replica_restarts": 1, "rehomed": 1}})
    svt = {"kind": "SERVE",
           "clients": {"4": {"p95_ms": 800.0, "requests_per_s": 5.0}},
           "trace_ab": {"clients": 4, "pps_on": 4.9, "pps_off": 5.0,
                        "overhead_rel": 0.02, "within_noise": True}}
    svt_base = _serve_records(svt)
    svt_same = _serve_records(json.loads(json.dumps(svt)))
    svt_heavy = _serve_records(
        {**svt, "trace_ab": {"clients": 4, "pps_on": 3.0, "pps_off": 5.0,
                             "overhead_rel": 0.4, "within_noise": False}})
    sv16_melt = _serve_records(       # the r01 shape: no shedding, melted
        {"kind": "SERVE",
         "clients": {"16": {"p95_ms": 126226.2, "deadline_miss_rate": 0.625,
                            "batch_occupancy_mean": 8.0,
                            "requests_per_s": 0.128}}})
    sv16_shedding = _serve_records(   # the r02 shape: honest triage
        {"kind": "SERVE",
         "clients": {"16": {"p95_ms": 9000.0, "deadline_miss_rate": 0.0,
                            "shed_rate": 0.3, "preemptions": 1,
                            "batch_occupancy_mean": 4.0,
                            "requests_per_s": 2.0}}})
    sv_same = _serve_records(json.loads(json.dumps(sv)))
    sv_slow = _serve_records(
        {"kind": "SERVE", "warm_xla_compiles": 0,
         "clients": {"4": {"p95_ms": 1900.0, "deadline_miss_rate": 0.0,
                           "requests_per_s": 5.0,
                           "batch_occupancy_mean": 3.5}}})
    sv_missy = _serve_records(
        {"kind": "SERVE", "warm_xla_compiles": 0,
         "clients": {"4": {"p95_ms": 800.0, "deadline_miss_rate": 0.25,
                           "requests_per_s": 5.0,
                           "batch_occupancy_mean": 3.5}}})
    sv_cold = _serve_records(
        {"kind": "SERVE", "warm_xla_compiles": 5,
         "clients": {"4": {"p95_ms": 800.0, "deadline_miss_rate": 0.0,
                           "requests_per_s": 5.0,
                           "batch_occupancy_mean": 3.5}}})
    sv_jitter = _serve_records(
        {"kind": "SERVE", "warm_xla_compiles": 0,
         "clients": {"4": {"p95_ms": 880.0, "deadline_miss_rate": 0.01,
                           "requests_per_s": 4.6,
                           "batch_occupancy_mean": 3.3}}})
    sm = {"kind": "SMT", "queries": 16,
          "workers": {"1": {"queries_per_s": 3.0},
                      "4": {"queries_per_s": 10.5}},
          "speedup_x": 3.5, "worker_crashes": 0, "memouts": 0}
    sm_base = _smt_records(sm)
    sm_same = _smt_records(json.loads(json.dumps(sm)))
    sm_serial = _smt_records(
        {"kind": "SMT", "queries": 16,
         "workers": {"1": {"queries_per_s": 3.0},
                     "4": {"queries_per_s": 3.2}},
         "speedup_x": 1.07, "worker_crashes": 0, "memouts": 0})
    sm_crashy = _smt_records(dict(sm, worker_crashes=4, memouts=2))
    sm_jitter = _smt_records(
        {"kind": "SMT", "queries": 16,
         "workers": {"1": {"queries_per_s": 2.8},
                     "4": {"queries_per_s": 9.9}},
         "speedup_x": 3.3, "worker_crashes": 0, "memouts": 0})
    df_base = {"df": _flat_fraction(0.98)}
    df_same = {"df": _flat_fraction(0.98)}
    df_jitter = {"df": _flat_fraction(0.965)}
    df_collapsed = {"df": _flat_fraction(0.60)}
    iv_clean = {"pps": {"value": 50.0, "min": 46.0, "max": 53.0,
                        "banded": True, "integrity_violations": 0,
                        "ledger_crc_mismatch": 0}}
    iv_corrupt = {"pps": {"value": 50.0, "min": 46.0, "max": 53.0,
                          "banded": True, "integrity_violations": 3,
                          "ledger_crc_mismatch": 2}}
    ia_base = {"integrity_recheck_overhead_rel": _flat_lower(0.02,
                                                             floor=0.05)}
    ia_heavy = {"integrity_recheck_overhead_rel": _flat_lower(0.40,
                                                              floor=0.05)}
    ia_jitter = {"integrity_recheck_overhead_rel": _flat_lower(0.06,
                                                               floor=0.05)}
    import os
    import tempfile

    chaos_clean = [
        {"cell": "integrity/launch.decode/run", "ok": True,
         "sdc_escaped": 0},
        {"cell": "integrity/smt.query/run", "ok": True, "sdc_escaped": 0},
        {"cell": "launch.decode/transient", "ok": True}]
    chaos_leaky = [
        {"cell": "integrity/launch.decode/run", "ok": False,
         "sdc_escaped": 2},
        {"cell": "integrity/smt.query/run", "ok": True, "sdc_escaped": 0},
        {"cell": "launch.decode/transient", "ok": True}]
    chaos_recs = {}
    for tag, rows in (("clean", chaos_clean), ("leaky", chaos_leaky)):
        with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                         delete=False) as fp:
            fp.write("\n".join(json.dumps(r) for r in rows) + "\n")
            cname = fp.name
        chaos_recs[tag] = load_records(cname)
        os.unlink(cname)

    thr_obj = {"partitions_per_sec": 12.5, "partitions_per_sec_per_chip": 12.5,
               "device_launches": 9, "decided_fraction": 0.9875}
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as fp:
        json.dump(thr_obj, fp)
        tname = fp.name
    trecs = load_records(tname)
    os.unlink(tname)

    wrapper = {"n": 5, "rc": 0, "cmd": "python bench.py",
               "tail": '{"metric": "pps (201 parts)", "value": 67.0, '
                       '"min": 60.0, "max": 70.0}\nnot json noise\n',
               "parsed": {"metric": "pps (201 parts)", "value": 67.0}}
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as fp:
        json.dump(wrapper, fp)
        wname = fp.name
    wrecs = load_records(wname)
    os.unlink(wname)
    checks = [
        ("driver-wrapper bench archive unwraps",
         [] if ("pps" in wrecs and wrecs["pps"]["min"] == 60.0)
         else [{"kind": "regression"}], 0),
        ("identical records pass", compare(base, same), 0),
        ("2x slowdown flagged", compare(base, slow), 1),
        ("overlapping noise bands pass", compare(base, noisy), 0),
        ("doubled launches flagged", compare(base, launchy), 1),
        ("launches_per_model sliding back to O(chunks) flagged",
         compare(lean, chunky), 1),
        ("identical launches_per_model passes", compare(lean, lean), 0),
        ("compiles growing from a warm 0 baseline flagged",
         compare(warm, churned), 2),
        ("cache-reload jitter over a 0 baseline passes",
         compare(warm, jitter), 0),
        ("identical multichip records pass", compare(mc_base, mc_same), 0),
        ("multichip ok flip flagged", compare(mc_base, mc_broken), 1),
        ("lost multichip scaling flagged (pps@8dev + scaling_x)",
         compare(mc_base, mc_flat), 2),
        ("shrunken fleet flagged",
         compare(_multichip_records({"n_devices": 8, "ok": True}), mc_shrunk),
         1),
        ("single lost device flagged (strict n_devices)",
         compare(mc_base, mc_one_lost), 1),
        ("in-tolerance throughput jitter passes",
         compare(mc_base, mc_jitter), 0),
        ("identical serve records pass", compare(sv_base, sv_same), 0),
        ("serve p95 growth flagged", compare(sv_base, sv_slow), 1),
        ("serve deadline misses flagged", compare(sv_base, sv_missy), 1),
        ("warm server recompiling flagged", compare(sv_base, sv_cold), 1),
        ("serve latency/miss jitter passes", compare(sv_base, sv_jitter), 0),
        ("identical overload records pass", compare(svo_base, svo_same), 0),
        ("shed-rate step change flagged", compare(svo_base, svo_sheddy), 1),
        ("preemption thrash flagged", compare(svo_base, svo_thrashy), 1),
        ("shed/preempt jitter passes", compare(svo_base, svo_jitter), 0),
        ("cold restart recompiling flagged (n_compiles + compile_s)",
         compare(svo_base, svo_coldly), 2),
        ("shedding candidate's occupancy not gated vs melted baseline",
         compare(sv16_melt, sv16_shedding), 0),
        ("identical procfleet records pass", compare(svp_base, svp_same), 0),
        ("replica fleet flapping flagged (deaths+restarts+rehomes)",
         compare(svp_base, svp_flappy), 3),
        ("single replica blip within count floor passes",
         compare(svp_base, svp_blip), 0),
        ("identical trace A/B records pass", compare(svt_base, svt_same), 0),
        ("tracing-overhead step change flagged (pps_on + overhead_rel)",
         compare(svt_base, svt_heavy), 2),
        ("throughput JSON carries decided_fraction into the gate",
         [] if (trecs.get("decided_fraction", {}).get("value") == 0.9875
                and trecs["decided_fraction"].get("fraction"))
         else [{"kind": "regression"}], 0),
        ("identical decided fractions pass", compare(df_base, df_same), 0),
        ("decided-fraction jitter within the floor passes",
         compare(df_base, df_jitter), 0),
        ("funnel collapse flagged (decided_fraction)",
         compare(df_base, df_collapsed), 1),
        ("identical integrity counters pass", compare(iv_clean, iv_clean),
         0),
        ("corruption detections from a 0 baseline flagged "
         "(violations + crc)", compare(iv_clean, iv_corrupt), 2),
        ("recheck-overhead step change flagged", compare(ia_base, ia_heavy),
         1),
        ("recheck-overhead jitter within the floor passes",
         compare(ia_base, ia_jitter), 0),
        ("chaos archive loads sdc/cell gates",
         [] if (chaos_recs["clean"].get("chaos.sdc_escaped",
                                        {}).get("value") == 0.0
                and chaos_recs["clean"]["chaos.failed_cells"]["value"]
                == 0.0)
         else [{"kind": "regression"}], 0),
        ("identical chaos archives pass",
         compare(chaos_recs["clean"], chaos_recs["clean"]), 0),
        ("escaped SDC + failed cell flagged",
         compare(chaos_recs["clean"], chaos_recs["leaky"]), 2),
        ("identical smt records pass", compare(sm_base, sm_same), 0),
        ("lost smt scaling flagged (qps@4w + speedup_x)",
         compare(sm_base, sm_serial), 2),
        ("smt worker deaths from a 0 baseline flagged",
         compare(sm_base, sm_crashy), 2),
        ("smt qps jitter passes", compare(sm_base, sm_jitter), 0),
    ]
    failed = 0
    for name, findings, want in checks:
        got = len([f for f in findings if f["kind"] == "regression"])
        ok = got == want
        failed += not ok
        print(f"perfdiff self-test: {name}: "
              f"{'ok' if ok else f'FAIL (got {got}, want {want})'}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

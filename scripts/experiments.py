"""Assemble EXPERIMENTS.md from the model-generation pipeline records.

Collects the JSON summaries written by the three generated-model pipelines
(the reference's experimentData task analogs):

* ``scripts/synthetic_models.py``  → ``<dir>/summary.json``   (task1)
* ``scripts/predicted_labels.py``  → ``<dir>/summary.jsonl``  (task2/3)
* ``python -m fairify_tpu experiment ... --json-out <file>``  (repair/hybrid
  experiment drivers, ``src/*/Verify-*-experiment-new2.py``)

Usage:
    python scripts/experiments.py render --synthetic res/synthetic \
        --predicted res/predicted --experiment res/experiment.json \
        [--platform "TPU v5e (1 chip)"]
"""
from __future__ import annotations

import argparse
import json
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_json(path):
    if path and os.path.isfile(path):
        with open(path) as fp:
            return json.load(fp)
    return None


def _load_jsonl(path):
    recs = []
    if path and os.path.isfile(path):
        with open(path) as fp:
            for line in fp:
                recs.append(json.loads(line))
    return recs


def cmd_render(args):
    lines = [
        "# EXPERIMENTS — generated-model pipelines (task1/task2 analogs + repair)",
        "",
        f"Rendered by `scripts/experiments.py` (runs on {args.platform}).  "
        "These pipelines *create* models rather than verify shipped ones: "
        "synthetic-data students (reference task1, CTGAN/GPT-2 there; "
        "from-scratch copula/autoregressive/bootstrap generators here), "
        "teacher-labelled students (task2, KNN/RF), and the verify→localize→"
        "repair→route→audit experiment drivers.",
        "",
    ]

    synth = _load_json(os.path.join(args.synthetic, "summary.json")) if args.synthetic else None
    if synth:
        lines += [
            "## Synthetic-data students (task1 analog)",
            "",
            "| Generator | Model | Rows | #P | SAT | UNSAT | UNK | Student acc | Time (s) |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for r in synth:
            if r.get("skipped"):
                lines.append(f"| {r['generator']} | {r['model']} | — skipped: {r['skipped']} | | | | | | |")
                continue
            lines.append(
                f"| {r['generator']} | {r['model']} | {r['rows']} | {r['partitions']} | "
                f"{r['sat']} | {r['unsat']} | {r['unknown']} | {r['test_acc']} | "
                f"{r['total_time_s']} |")
        lines.append("")

    pred_all = _load_jsonl(os.path.join(args.predicted, "summary.jsonl")) if args.predicted else []
    # re-runs append; keep the latest record per model
    pred_all = list({r["model"]: r for r in pred_all}.values())
    # task2 = classical teachers; task3 = strong teachers (gbt stands in
    # for TabPFN, whose checkpoint is unfetchable here — models/gbt.py).
    pred = [r for r in pred_all if r["teacher"] in ("knn", "rf")]
    strong = [r for r in pred_all if r["teacher"] not in ("knn", "rf")]

    def teacher_table(rows):
        out = [
            "| Model | Teacher | Teacher acc | #P | SAT | UNSAT | UNK | Student acc | Time (s) |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for r in rows:
            out.append(
                f"| {r['model']} | {r['teacher']} | {r['teacher_acc']} | {r['partitions']} | "
                f"{r['sat']} | {r['unsat']} | {r['unknown']} | {r['student_acc']} | "
                f"{r['total_time_s']} |")
        return out + [""]

    if pred:
        lines += ["## Teacher-labelled students (task2 analog)", ""]
        lines += teacher_table(pred)
    if strong:
        lines += [
            "## Strong-teacher students (task3 analog)",
            "",
            "Reference task3 uses TabPFN (unfetchable checkpoint); the "
            "strong-teacher role is filled by from-scratch gradient-boosted "
            "depth-2 trees (`fairify_tpu/models/gbt.py` — depth 2 so the "
            "teacher captures feature interactions an additive model "
            "cannot).  Same pipeline: fit teacher → relabel → train MLP "
            "student → export `.h5` → verify "
            "(`scripts/predicted_labels.py --teacher gbt`).",
            "",
        ]
        lines += teacher_table(strong)

    t5_path = args.task5 or os.path.join(ROOT, "audits",
                                         "task5_compare_r4.json")
    if os.path.isfile(t5_path):
        t5 = _load_json(t5_path)
        lines += [
            "## Cross-tool counterexample comparison (task5 analog)",
            "",
            "`scripts/task5_compare.py` rebuilds the reference's task5 "
            "artifact family: its committed Fairify/FairQuant CE CSVs are "
            "re-encoded through our loaders and re-judged by exact "
            "rational replay, and our own decoded CE sets are emitted per "
            "model in the same CSV shape.  Each replay self-diagnoses its "
            "encoding lineage by comparing the CSV's recorded output "
            "probability with OUR forward at the re-encoded point "
            "(`output_match_rate`); only lineage-matched rows are a "
            "like-for-like judgement.  " + t5.get("caveat", ""),
            "",
            "| Model | Fairify conf/pairs (lineage match) | "
            "FairQuant conf/refuted/unencodable (lineage match) | Our CE pairs |",
            "|---|---|---|---|",
        ]
        def t5_cell(rec, tool):
            if tool not in rec:
                return "—"
            t = rec[tool]
            m = t.get("output_match_rate")
            mtxt = f", match {m}" if m is not None else ", no output col"
            return (f"{t['confirmed']}/{t['pairs']}"
                    f" ({t['refuted']} ref, {t['unencodable']} unenc{mtxt})")

        for r in t5["records"]:
            lines.append(f"| {r['model']} | {t5_cell(r, 'fairify')} | "
                         f"{t5_cell(r, 'fairquant')} | {r['ours']['ce_pairs']} |")
        lines.append("")

    exps = [_load_json(p) for p in args.experiment.split(",")] if args.experiment else []
    for exp in filter(None, exps):
        lines += _experiment_section(exp)

    out_md = os.path.join(ROOT, "EXPERIMENTS.md")
    with open(out_md, "w") as fp:
        fp.write("\n".join(lines) + "\n")
    print(f"wrote {out_md}")


def _experiment_section(exp, note=""):
    lines = [
        f"## Repair experiment: `{exp['model']}` (verify → localize → repair → route → audit)",
        "",
        (f"Verdicts {exp['verdicts']}, {exp['counterexample_pairs']} "
         f"counterexample pairs, top biased neurons {exp['biased_neurons'][:3]}."
         + (f"  {note}" if note else "")),
        "",
    ]
    if exp.get("fairer_verdicts"):
        lines += [f"Repaired-model verdicts (same grid): {exp['fairer_verdicts']}.", ""]
    if exp.get("routing"):
        r = exp["routing"]
        lines += [(f"Hybrid routing over the test set: {r['fair']} → fairer, "
                   f"{r['original']} → original, {r['miss']} misses."), ""]
    if exp.get("success") is not None:
        s = exp["success"]
        verdict = "PASSED" if s.get("passed") else "FAILED"
        fails = [k for k, v in s.items() if k != "passed" and not v]
        lines += [(f"Success criteria (reference's own bar, "
                   f"`src/AC/new_model.py:248-260`): **{verdict}**"
                   + (f" — failing: {', '.join(fails)}" if fails else "")), ""]
    lines += [
        "| Variant | Acc | DI | SPD | EOD | AOD | ERD | Consistency | Theil | Causal rate |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for variant, m in exp["metrics"].items():
        lines.append(
            f"| {variant} | {m['accuracy']} | {m['disparate_impact']} | "
            f"{m['statistical_parity_difference']} | {m['equal_opportunity_difference']} | "
            f"{m['average_odds_difference']} | {m['error_rate_difference']} | "
            f"{m['consistency']} | {m['theil_index']} | "
            f"{exp['causal_rates'].get(variant, '—')} |")
    lines.append("")
    return lines


def cmd_append(args):
    """Append one experiment section to the existing EXPERIMENTS.md.

    ``render`` regenerates the whole file from its source JSONs; when those
    live in a gitignored results dir from an earlier round, appending keeps
    the committed sections intact while recording the new run.
    """
    exp = _load_json(args.experiment)
    if exp is None:
        raise SystemExit(f"missing experiment JSON: {args.experiment}")
    out_md = os.path.join(ROOT, "EXPERIMENTS.md")
    existing = open(out_md).read() if os.path.isfile(out_md) else ""
    body = "\n".join(_experiment_section(exp, note=args.note))
    header = f"## Repair experiment: `{exp['model']}`"
    if header in existing:
        # Splice the replacement in place (up to the next header or EOF) so
        # re-running an earlier model's experiment never reorders sections.
        start = existing.index(header)
        nxt = existing.find("\n## ", start + 1)
        tail = existing[nxt + 1:] if nxt >= 0 else ""
        out = existing[:start] + body + ("\n" + tail if tail else "\n")
    elif existing:
        out = existing.rstrip("\n") + "\n\n" + body + "\n"
    else:
        out = "# EXPERIMENTS — generated-model pipelines\n\n" + body + "\n"
    with open(out_md, "w") as fp:
        fp.write(out)
    print(f"appended {exp['model']} section to {out_md}")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    rend = sub.add_parser("render")
    rend.add_argument("--synthetic", default=None)
    rend.add_argument("--predicted", default=None)
    rend.add_argument("--experiment", default=None)
    rend.add_argument("--platform", default="CPU (virtual mesh)")
    rend.add_argument("--task5", default=None,
                      help="task5 comparison audit JSON (default: "
                           "audits/task5_compare_r4.json)")
    rend.set_defaults(fn=cmd_render)
    app = sub.add_parser("append")
    app.add_argument("--experiment", required=True)
    app.add_argument("--note", default="")
    app.set_defaults(fn=cmd_append)
    args = ap.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()

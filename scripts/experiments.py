"""Assemble EXPERIMENTS.md from the model-generation pipeline records.

Collects the JSON summaries written by the three generated-model pipelines
(the reference's experimentData task analogs):

* ``scripts/synthetic_models.py``  → ``<dir>/summary.json``   (task1)
* ``scripts/predicted_labels.py``  → ``<dir>/summary.jsonl``  (task2/3)
* ``python -m fairify_tpu experiment ... --json-out <file>``  (repair/hybrid
  experiment drivers, ``src/*/Verify-*-experiment-new2.py``)

Usage:
    python scripts/experiments.py render --synthetic res/synthetic \
        --predicted res/predicted --experiment res/experiment.json \
        [--platform "TPU v5e (1 chip)"]
"""
from __future__ import annotations

import argparse
import json
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_json(path):
    if path and os.path.isfile(path):
        with open(path) as fp:
            return json.load(fp)
    return None


def _load_jsonl(path):
    recs = []
    if path and os.path.isfile(path):
        with open(path) as fp:
            for line in fp:
                recs.append(json.loads(line))
    return recs


def cmd_render(args):
    lines = [
        "# EXPERIMENTS — generated-model pipelines (task1/task2 analogs + repair)",
        "",
        f"Rendered by `scripts/experiments.py` (runs on {args.platform}).  "
        "These pipelines *create* models rather than verify shipped ones: "
        "synthetic-data students (reference task1, CTGAN/GPT-2 there; "
        "from-scratch copula/autoregressive/bootstrap generators here), "
        "teacher-labelled students (task2, KNN/RF), and the verify→localize→"
        "repair→route→audit experiment drivers.",
        "",
    ]

    synth = _load_json(os.path.join(args.synthetic, "summary.json")) if args.synthetic else None
    if synth:
        lines += [
            "## Synthetic-data students (task1 analog)",
            "",
            "| Generator | Model | Rows | #P | SAT | UNSAT | UNK | Student acc | Time (s) |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for r in synth:
            if r.get("skipped"):
                lines.append(f"| {r['generator']} | {r['model']} | — skipped: {r['skipped']} | | | | | | |")
                continue
            lines.append(
                f"| {r['generator']} | {r['model']} | {r['rows']} | {r['partitions']} | "
                f"{r['sat']} | {r['unsat']} | {r['unknown']} | {r['test_acc']} | "
                f"{r['total_time_s']} |")
        lines.append("")

    pred = _load_jsonl(os.path.join(args.predicted, "summary.jsonl")) if args.predicted else []
    # re-runs append; keep the latest record per model
    pred = list({r["model"]: r for r in pred}.values())
    if pred:
        lines += [
            "## Teacher-labelled students (task2 analog)",
            "",
            "| Model | Teacher | Teacher acc | #P | SAT | UNSAT | UNK | Student acc | Time (s) |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for r in pred:
            lines.append(
                f"| {r['model']} | {r['teacher']} | {r['teacher_acc']} | {r['partitions']} | "
                f"{r['sat']} | {r['unsat']} | {r['unknown']} | {r['student_acc']} | "
                f"{r['total_time_s']} |")
        lines.append("")

    exps = [_load_json(p) for p in args.experiment.split(",")] if args.experiment else []
    for exp in filter(None, exps):
        lines += [
            f"## Repair experiment: `{exp['model']}` (verify → localize → repair → route → audit)",
            "",
            f"Verdicts {exp['verdicts']}, {exp['counterexample_pairs']} "
            f"counterexample pairs, top biased neurons {exp['biased_neurons'][:3]}.",
            "",
            "| Variant | Acc | DI | SPD | EOD | AOD | ERD | Consistency | Theil | Causal rate |",
            "|---|---|---|---|---|---|---|---|---|---|",
        ]
        for variant, m in exp["metrics"].items():
            lines.append(
                f"| {variant} | {m['accuracy']} | {m['disparate_impact']} | "
                f"{m['statistical_parity_difference']} | {m['equal_opportunity_difference']} | "
                f"{m['average_odds_difference']} | {m['error_rate_difference']} | "
                f"{m['consistency']} | {m['theil_index']} | "
                f"{exp['causal_rates'].get(variant, '—')} |")
        lines.append("")

    out_md = os.path.join(ROOT, "EXPERIMENTS.md")
    with open(out_md, "w") as fp:
        fp.write("\n".join(lines) + "\n")
    print(f"wrote {out_md}")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    rend = sub.add_parser("render")
    rend.add_argument("--synthetic", default=None)
    rend.add_argument("--predicted", default=None)
    rend.add_argument("--experiment", default=None)
    rend.add_argument("--platform", default="CPU (virtual mesh)")
    rend.set_defaults(fn=cmd_render)
    args = ap.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()

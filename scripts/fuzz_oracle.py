"""Randomized soundness fuzzer: engine verdicts vs the brute-force oracle.

Random tiny MLPs × random integer domains × random queries (plain /
multi-PA / relaxed), decided by the complete engine and cross-checked
against exhaustive pair enumeration (``fairify_tpu/verify/oracle.py``).
Any disagreement is a soundness or completeness bug; SAT witnesses are
additionally replayed in exact arithmetic.  This is the standing
adversarial self-check the reference lacks (its closest analogs are the
C-check / V-accurate replay columns, ``src/GC/Verify-GC.py:225-254``).

Usage:
    python scripts/fuzz_oracle.py [--trials 200] [--seed0 0] [--verbose]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def one_trial(seed: int, cfg) -> dict:
    import numpy as np

    from fairify_tpu.verify import engine, property as prop
    from fairify_tpu.verify.oracle import brute_force_verdict, random_net, tiny_domain

    rng = np.random.default_rng(seed)
    # random domain: 3-5 attrs, small ranges (oracle is exponential)
    d = int(rng.integers(3, 6))
    names = [f"a{i}" for i in range(d)]
    ranges = {}
    for nm in names:
        lo = int(rng.integers(0, 2))
        ranges[nm] = (lo, lo + int(rng.integers(1, 4)))
    n_pa = int(rng.integers(1, 3))
    pa = tuple(rng.choice(names, size=n_pa, replace=False).tolist())
    ra, eps = (), 0
    rest = [nm for nm in names if nm not in pa]
    if rest and rng.random() < 0.3:
        ra, eps = (rest[0],), int(rng.integers(1, 3))
    dom = tiny_domain(ranges)
    query = prop.FairnessQuery(domain=dom, protected=pa, relaxed=ra, relax_eps=eps)

    hidden = [int(rng.integers(2, 7)) for _ in range(int(rng.integers(1, 4)))]
    scale = float(rng.choice([0.3, 1.0, 3.0]))
    net = random_net(rng, (d, *hidden, 1), scale=scale)

    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    lo, hi = lo.astype(np.int64), hi.astype(np.int64)
    want = brute_force_verdict(net, query, lo, hi)
    got = engine.decide_box(net, enc, lo, hi, cfg)
    rec = {"seed": seed, "pa": pa, "ra": ra, "eps": eps, "hidden": hidden,
           "scale": scale, "want": want, "got": got.verdict}
    if got.verdict == "sat":
        x, xp = got.counterexample
        ws = [np.asarray(w) for w in net.weights]
        bs = [np.asarray(b) for b in net.biases]
        rec["witness_valid"] = bool(engine.validate_pair(ws, bs, x, xp))
    return rec


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trials", type=int, default=200)
    ap.add_argument("--seed0", type=int, default=0)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    from fairify_tpu.verify import engine

    cfg = engine.EngineConfig(frontier_size=64, attack_samples=32,
                              bab_attack_samples=8, soft_timeout_s=60.0,
                              max_nodes=50_000)
    import jax

    t0 = time.perf_counter()
    mismatches, bad_witness, unknowns = [], [], 0
    for i in range(args.trials):
        if i and i % 10 == 0:
            # every trial jits fresh shapes; without this the accumulated
            # executables eventually OOM the LLVM JIT on long runs
            jax.clear_caches()
        if i and i % 25 == 0:
            print(json.dumps({"progress": i, "mismatches": len(mismatches),
                              "unknowns": unknowns}), flush=True)
        rec = one_trial(args.seed0 + i, cfg)
        if args.verbose:
            print(json.dumps(rec), flush=True)
        if rec["got"] == "unknown":
            unknowns += 1  # budget exhaustion is not a soundness bug
        elif rec["got"] != rec["want"]:
            mismatches.append(rec)
        if rec.get("witness_valid") is False:
            bad_witness.append(rec)
    print(json.dumps({
        "trials": args.trials, "mismatches": len(mismatches),
        "invalid_witnesses": len(bad_witness), "unknowns": unknowns,
        "elapsed_s": round(time.perf_counter() - t0, 1),
    }))
    failures = {rec["seed"]: rec for rec in mismatches + bad_witness}
    for rec in failures.values():
        print("FAIL " + json.dumps(rec), file=sys.stderr)
    return 1 if (mismatches or bad_witness) else 0


if __name__ == "__main__":
    sys.exit(main())

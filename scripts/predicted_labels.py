"""Predicted-label model variants: train on another classifier's labels, verify.

The reference's ``experimentData/task2`` notebooks study Fairify on MLPs
trained against labels *predicted* by KNN / random-forest models instead of
the ground truth, and ``task3`` repeats it with a strong pretrained tabular
teacher (TabPFN) (SURVEY.md §4.3).  This script is both pipelines as one
first-class command: fit the teacher, relabel the training split, train an
MLP student, export it as Keras-compatible ``.h5``, and run the dataset's
verification preset on it.

Teachers: ``knn`` / ``rf`` (task2), ``gbt`` (task3 analog — TabPFN's
checkpoint is unfetchable here, so the strong-teacher role is filled by
from-scratch gradient-boosted stumps, ``fairify_tpu/models/gbt.py``;
``tabpfn`` stays a gated option for environments that have it).

Usage:
    python scripts/predicted_labels.py [--preset GC] [--teacher knn|rf|gbt]
        [--hidden 50] [--epochs 30] [--out res/predicted]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", default="GC")
    ap.add_argument("--teacher", choices=("knn", "rf", "gbt", "tabpfn"),
                    default="knn")
    ap.add_argument("--hidden", type=int, nargs="*", default=[50])
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--soft", type=float, default=10.0)
    ap.add_argument("--hard", type=float, default=600.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="res/predicted")
    args = ap.parse_args()

    import numpy as np

    from fairify_tpu.data import loaders
    from fairify_tpu.models import export, train
    from fairify_tpu.verify import presets, sweep

    cfg = presets.get(args.preset).with_(
        soft_timeout_s=args.soft, hard_timeout_s=args.hard, result_dir=args.out)
    ds = loaders.load(cfg.dataset)

    if args.teacher == "knn":
        from sklearn.neighbors import KNeighborsClassifier

        teacher = KNeighborsClassifier(n_neighbors=5)
    elif args.teacher == "rf":
        from sklearn.ensemble import RandomForestClassifier

        teacher = RandomForestClassifier(n_estimators=100, random_state=42)
    elif args.teacher == "gbt":
        from fairify_tpu.models.gbt import GradientBoostedTrees

        teacher = GradientBoostedTrees(n_rounds=300, learning_rate=0.1,
                                       max_depth=2)
    else:
        # task3's teacher; the package (and its pretrained prior) is not in
        # this image, so the option is gated rather than stubbed.
        try:
            from tabpfn import TabPFNClassifier
        except ImportError:
            sys.exit("tabpfn is not installed in this environment; "
                     "use --teacher knn or rf (task2 analogs)")
        teacher = TabPFNClassifier()
    teacher.fit(ds.X_train, ds.y_train)
    y_soft = teacher.predict(ds.X_train).astype(np.float32)
    teacher_acc = float((teacher.predict(ds.X_test) == ds.y_test).mean())

    net = train.train_mlp(ds.X_train.astype(np.float32), y_soft,
                          hidden=list(args.hidden), epochs=args.epochs,
                          seed=args.seed)
    os.makedirs(args.out, exist_ok=True)
    name = f"{args.preset}-{args.teacher}"
    if args.seed:  # keep seed sweeps side by side (seed 0 = legacy name)
        name += f"-s{args.seed}"
    h5_path = os.path.join(args.out, f"{name}.h5")
    export.save_keras_h5(net, h5_path)

    report = sweep.verify_model(net, cfg, model_name=name, dataset=ds,
                                resume=False)
    rec = {
        "model": name, "teacher": args.teacher, "teacher_acc": round(teacher_acc, 4),
        "student_h5": h5_path, "partitions": report.partitions_total,
        **report.counts, "student_acc": round(report.original_acc, 4),
        "total_time_s": round(report.total_time_s, 2),
    }
    print(json.dumps(rec))
    with open(os.path.join(args.out, "summary.jsonl"), "a") as fp:
        fp.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()

"""Multi-device weak-scaling record for the stage-0 kernels (VERDICT r2 #6, r3 #5).

Real multi-chip hardware is not reachable from this environment (one
tunnelled chip), so the only honest multi-device *throughput* evidence is
the virtual CPU mesh the sharding tests already use: this script times the
stage-0 certify+attack pass (the sweep's dominant whole-grid kernel) on a
fixed grid across 1/2/4/8 virtual devices and records throughput, parallel
efficiency, per-device work-shrink, and collective-op counts from the
compiled HLO into ``audits/scaling_r4.json``, which
``scripts/perf_table.py`` renders into PERF.md.

Each device count runs in a fresh subprocess: the XLA device count is a
process-level flag (``xla_force_host_platform_device_count``) that must be
set before backend init.  Same-verdict invariance across mesh sizes is
separately asserted by ``tests/test_parallel.py::test_decide_many_mesh_invariant``
and ``tests/test_sweep.py::test_sweep_verdicts_mesh_invariant``; this
script measures speed and sharding structure only.

Usage: python scripts/scaling.py [--parts 4096] [--model GC-1] [--reps 3]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, os, re, sys, time
sys.path.insert(0, {root!r})
import numpy as np
import jax
import jax.numpy as jnp

from fairify_tpu.models import zoo
from fairify_tpu.parallel import mesh as mesh_mod
from fairify_tpu.verify import engine, presets, sweep
from fairify_tpu.verify.property import encode, role_boxes

n_dev = {n_dev}
cfg = presets.get("stress-GC").with_(grid_chunk=0)
net = zoo.load(cfg.dataset, {model!r})
enc = encode(cfg.query())
_, lo, hi = sweep.build_partitions(cfg)
lo, hi = lo[: {parts}], hi[: {parts}]
mesh = mesh_mod.make_mesh(n_parts=n_dev)
# Warmup (compile) then timed reps.
sweep._stage0_certify_and_attack(net, enc, lo, hi, cfg, mesh=mesh)
times = []
for _ in range({reps}):
    t0 = time.perf_counter()
    unsat, sat, wit = sweep._stage0_certify_and_attack(
        net, enc, lo, hi, cfg, mesh=mesh)
    times.append(time.perf_counter() - t0)

# Sharding-structure counters (VERDICT r3 #5): per-device input bytes of the
# sharded role-box tensors (the work-shrink evidence: each device holds and
# processes parts/N boxes), and collective-op counts in the compiled HLO of
# the certify kernel (what XLA actually inserted for this mesh).
flo, fhi = lo.astype(np.float32), hi.astype(np.float32)
x_lo, x_hi, xp_lo, xp_hi, valid = role_boxes(enc, flo, fhi)
sharded = mesh_mod.shard_parts(mesh, x_lo, x_hi, xp_lo, xp_hi, flo, fhi, valid)
net_r = mesh_mod.replicated(mesh, net)
av, pm, rm = engine._enc_tensors(enc, lo.shape[1])
# Measured per-device bytes: sum each sharded array's shards that actually
# live on device 0 (NOT global nbytes / N, which would be 1/N-shrink by
# construction even if shard_parts silently replicated).
dev0 = jax.devices()[0]
dev0_bytes = sum(s.data.nbytes for a in sharded
                 for s in a.addressable_shards if s.device == dev0)
lowered = engine._role_certify_kernel.lower(
    net_r, sharded[0], sharded[1], sharded[2], sharded[3],
    sharded[4], sharded[5], jnp.asarray(av), jnp.asarray(pm),
    jnp.asarray(rm), float(enc.eps), sharded[6],
    jnp.asarray(enc.valid_pair), alpha_iters=0)
hlo = lowered.compile().as_text()
colls = {{op: len(re.findall(op, hlo))
         for op in ("all-reduce", "all-gather", "collective-permute",
                    "reduce-scatter", "all-to-all")}}
out_bytes = int(np.asarray(unsat).nbytes + np.asarray(sat).nbytes)
print(json.dumps({{
    "devices": n_dev,
    "parts": int(lo.shape[0]),
    "best_s": round(min(times), 4),
    "parts_per_sec": round(lo.shape[0] / min(times), 1),
    "decided": int(np.sum(unsat) + np.sum(sat)),
    "input_mb_per_device": round(dev0_bytes / 1e6, 3),
    "verdict_gather_bytes": out_bytes,
    "hlo_collectives": colls,
}}))
"""


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--parts", type=int, default=4096)
    ap.add_argument("--model", default="GC-1")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", default="audits/scaling_r4.json")
    args = ap.parse_args()

    rows = []
    for n_dev in (1, 2, 4, 8):
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": "",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={n_dev}",
        })
        code = _CHILD.format(root=ROOT, n_dev=n_dev, parts=args.parts,
                             model=args.model, reps=args.reps)
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=1800)
        line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
        if not line.startswith("{"):
            print(f"devices={n_dev} FAILED:\n{out.stderr[-2000:]}",
                  file=sys.stderr)
            return 1
        rec = json.loads(line)
        rows.append(rec)
        print(json.dumps(rec), flush=True)
    base = rows[0]
    for r in rows:
        r["parts_per_device"] = r["parts"] // r["devices"]
        r["overhead_vs_1dev"] = round(r["best_s"] / base["best_s"], 3)
        r["input_shrink_vs_1dev"] = round(
            base["input_mb_per_device"] / max(r["input_mb_per_device"], 1e-9), 2)
    verdict_invariant = len({r["decided"] for r in rows}) == 1
    n_coll = sum(sum(r["hlo_collectives"].values()) for r in rows)
    coll_phrase = (
        "the compiled HLO contains ZERO collectives (hlo_collectives — the "
        "certify kernel is embarrassingly data-parallel over the parts "
        "axis, so on real chips no ICI traffic is needed at all until the "
        "final verdict gather)" if n_coll == 0 else
        "the compiled HLO shows the collectives XLA inserted for the mesh "
        "(hlo_collectives)")
    result = {
        "kernel": "stage0 certify+attack (CROWN role bounds + tied-diff + "
                  "sampling attack)",
        "grid": f"stress-GC prefix, {args.parts} partitions, model {args.model}",
        "platform": "virtual CPU mesh (xla_force_host_platform_device_count; "
                    "single host)",
        "caveat": (
            "Virtual devices SHARE one host's physical cores, so wall-clock "
            "speedup is structurally unobservable here — N virtual devices "
            "run N shards on the same silicon, and the measured slowdown is "
            "the cost of smaller per-shard batches plus collective overhead "
            "on shared cores.  What this record demonstrates: the sharded "
            "stage-0 path executes at every mesh size, per-device input "
            "bytes shrink ∝ 1/N (input_mb_per_device / input_shrink rows — "
            "the actual multi-chip scaling mechanism: each real chip gets "
            "parts/N boxes and its own MXU), " + coll_phrase + ", the "
            "host↔device verdict gather is bytes-per-partition tiny "
            "(verdict_gather_bytes), and the decided-verdict set is "
            "mesh-size invariant (also asserted by tests/test_parallel.py::"
            "test_decide_many_mesh_invariant and tests/test_sweep.py::"
            "test_sweep_verdicts_mesh_invariant)."),
        "verdicts_mesh_invariant": verdict_invariant,
        "rows": rows,
    }
    out_path = os.path.join(ROOT, args.out)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as fp:
        json.dump(result, fp, indent=1)
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

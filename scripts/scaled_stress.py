#!/usr/bin/env python
"""Scaled stress zoos: wider/deeper AC + BM families (VERDICT r4 missing #3).

The reference's stress drivers point at scaled-model directories that are
missing from its own artifact (``/root/reference/stress/AC/Verify-AC.py:21``
``model_dir = './AC-Model/'``, likewise ``stress/BM/Verify-BM.py:21``) — the
*intent* is stress-testing on bigger nets than the shipped zoos, but the
models were never published.  This harness honors that intent natively:

* ``make`` — trains scaled MLPs on the real adult/bank datasets
  (:func:`fairify_tpu.models.train.train_mlp`) and exports them as
  Keras-compatible ``.h5`` (:mod:`fairify_tpu.models.export`) into
  ``models_scaled/{adult,bank}``: per family one ≥2× WIDER net than the
  widest shipped model and one DEEPER net (shipped AC tops out at
  64-32-16-8-4, ``PARITY.md``).
* ``run`` — budgeted stress sweeps over the scaled zoo via the standard
  variant pipeline, at the stress presets' reference budgets (soft 200 s).
  Must run as its own process: the zoo root env var is read at import time.

Usage:
    python scripts/scaled_stress.py make
    python scripts/scaled_stress.py run [--hard 3600] [--tag r5]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
SCALED_ROOT = os.path.join(ROOT, "models_scaled")
sys.path.insert(0, ROOT)

# (dataset, name, hidden sizes): widest shipped AC is 64-32-16-8-4 and BM
# 64-32-16-8 (PARITY.md model column) → S1 doubles every hidden width, S2
# adds depth at the doubled width.
SCALED = [
    ("adult", "AC-S1", [128, 64, 32, 16, 8]),
    ("adult", "AC-S2", [128, 64, 64, 32, 16, 8]),
    ("bank", "BM-S1", [128, 64, 32, 16]),
    ("bank", "BM-S2", [128, 64, 32, 32, 16, 8]),
]


def cmd_make(args) -> None:
    from fairify_tpu.data import loaders
    from fairify_tpu.models import export, train
    from fairify_tpu.models.zoo import FAMILIES

    for dataset, name, hidden in SCALED:
        sub, _ = FAMILIES[dataset]
        out_dir = os.path.join(SCALED_ROOT, sub)
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"{name}.h5")
        if os.path.isfile(path) and not args.force:
            print(f"== {name}: exists", flush=True)
            continue
        ds = loaders.load(dataset)
        import zlib

        # crc32, not hash(): PYTHONHASHSEED randomizes str hashes per
        # process, and the scaled zoo must be reproducible across rounds.
        net = train.train_mlp(ds.X_train, ds.y_train, hidden,
                              epochs=args.epochs,
                              seed=zlib.crc32(name.encode()) % 2**31)
        import jax.numpy as jnp
        import numpy as np

        from fairify_tpu.models import mlp as mlp_mod

        pred = np.asarray(mlp_mod.predict(net, jnp.asarray(ds.X_test, jnp.float32)))
        acc = float((pred.astype(int) == ds.y_test).mean())
        export.save_keras_h5(net, path, name=name)
        print(json.dumps({"model": name, "hidden": hidden,
                          "test_acc": round(acc, 4), "path": path}), flush=True)


def cmd_run(args) -> None:
    # The zoo root must be pinned BEFORE fairify_tpu.models.zoo is imported.
    assert os.environ.get("FAIRIFY_TPU_MODEL_ROOT") == SCALED_ROOT or \
        os.path.realpath(os.environ.get("FAIRIFY_TPU_MODEL_ROOT", "")) == \
        os.path.realpath(SCALED_ROOT), (
            "run via: FAIRIFY_TPU_MODEL_ROOT=models_scaled python "
            "scripts/scaled_stress.py run (the root is bound at import time)")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _sweeplib import run_and_record_budgeted
    from fairify_tpu.verify import presets

    from fairify_tpu.models import zoo

    missing = [n for _, n, _ in SCALED
               if not any(p.stem == n for d in ("adult", "bank")
                          for p in zoo.model_paths(d))]
    if missing:
        raise SystemExit(f"scaled zoo incomplete (missing {missing}) — run "
                         "`python scripts/scaled_stress.py make` first")
    out = os.path.join(ROOT, "variants")
    os.makedirs(out, exist_ok=True)
    results_path = os.path.join(out, "results_scaled.jsonl")
    for preset in ("stress-AC", "stress-BM"):
        cfg = presets.get(preset).with_(
            hard_timeout_s=args.hard,
            result_dir=os.path.join(out, preset + "-scaled"))
        run_and_record_budgeted(
            cfg, preset + "-scaled", results_path,
            extra={"engine_tag": args.tag} if args.tag else None)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    mk = sub.add_parser("make")
    mk.add_argument("--epochs", type=int, default=25)
    mk.add_argument("--force", action="store_true")
    mk.set_defaults(fn=cmd_make)
    run = sub.add_parser("run")
    run.add_argument("--hard", type=float, default=3600.0)
    run.add_argument("--tag", default=None)
    run.set_defaults(fn=cmd_run)
    args = ap.parse_args()
    os.chdir(ROOT)
    args.fn(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())

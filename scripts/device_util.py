"""Device-utilization record for the stage-0 kernels (VERDICT r3 #3).

Measures, on the real chip, the stage-0 certify kernel (CROWN role bounds
+ tied-diff) and the attack forward for GC-1 and AC-1 on real grid chunks:

* XLA's own ``compiled.cost_analysis()`` FLOP and logical bytes-accessed
  counts (the compiler's static model; logical bytes count fused
  intermediates, so they are an upper bound on physical HBM traffic);
* measured warm-launch wall time (median over reps of 8 back-to-back
  launches, each synced by a device→host output fetch — on the tunnelled
  chip ``block_until_ready`` returns before remote completion);
* achieved FLOP/s and its fraction of the chip's nominal peak — the
  roofline position.  Also captures a real ``jax.profiler`` trace
  directory for XProf/TensorBoard inspection.

The point (SURVEY.md §5.1's profiling mandate): substantiate with numbers
that stage 0 is HBM-bound at tiny arithmetic intensity — the partitions
axis streams role boxes through small matmuls — so throughput scales with
the partition batch, and `frontier_size`/`grid_chunk` tuning is about
launch amortization, not MXU saturation.

Writes ``audits/device_util_r4.json``.

Usage: python scripts/device_util.py [--chunk 2048] [--reps 5]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# Nominal per-chip peaks by device kind (public spec sheets).  Fallback is
# conservative; the record states which row was used.
PEAKS = {
    # device_kind substring: (peak f32 TFLOP/s, HBM GB/s)
    "v2": (11.5, 300.0),
    "v3": (61.0, 900.0),
    "v4": (137.5, 1200.0),
    "v5 lite": (98.0, 820.0),
    "v5": (197.0, 1600.0),
    "v6 lite": (460.0, 1640.0),
    "v6": (460.0, 1640.0),
}


def measure(kernel_name, lowered, run, reps, inner=8):
    """Time ``inner`` back-to-back launches per rep, each synced by a
    device→host fetch of an output (``run`` must end in np.asarray /
    device_get — on the tunnelled chip ``block_until_ready`` returns
    before remote completion, which round 4 caught as a 5×-over-peak
    'measured' HBM rate).  cost_analysis 'bytes accessed' is XLA's
    LOGICAL per-op traffic (counts fused intermediates), reported as
    such, not as physical HBM bytes."""
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0))
    bytes_acc = float(cost.get("bytes accessed", 0.0))
    run()  # warmup beyond compile (cache effects)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(inner):
            run()
        times.append((time.perf_counter() - t0) / inner)
    wall = statistics.median(times)
    return {
        "kernel": kernel_name,
        "xla_flops": flops,
        "xla_logical_bytes": bytes_acc,
        "arithmetic_intensity_flops_per_logical_byte":
            round(flops / bytes_acc, 3) if bytes_acc else None,
        "warm_launch_s_median": round(wall, 6),
        "achieved_gflops": round(flops / wall / 1e9, 2),
        "logical_gbps": round(bytes_acc / wall / 1e9, 2),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--chunk", type=int, default=2048)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--trace-dir", default="res/xla_trace_r4")
    ap.add_argument("--out", default=os.path.join(ROOT, "audits",
                                                  "device_util_r4.json"))
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fairify_tpu.models import zoo
    from fairify_tpu.verify import engine, presets, sweep
    from fairify_tpu.verify.property import encode, role_boxes

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", str(dev))
    peak = next((v for k, v in PEAKS.items() if k in kind.lower()), None)

    records = []
    for preset_name, model in (("GC", "GC-1"), ("AC", "AC-1")):
        cfg = presets.get(preset_name)
        net = zoo.load(cfg.dataset, model)
        enc = encode(cfg.query())
        _, lo, hi = sweep.build_partitions(cfg)
        lo, hi = lo[: args.chunk], hi[: args.chunk]
        flo, fhi = lo.astype(np.float32), hi.astype(np.float32)
        x_lo, x_hi, xp_lo, xp_hi, valid = role_boxes(enc, flo, fhi)
        av, pm, rm = engine._enc_tensors(enc, lo.shape[1])
        cert_args = (net, jnp.asarray(x_lo), jnp.asarray(x_hi),
                     jnp.asarray(xp_lo), jnp.asarray(xp_hi),
                     jnp.asarray(flo), jnp.asarray(fhi), jnp.asarray(av),
                     jnp.asarray(pm), jnp.asarray(rm), float(enc.eps),
                     jnp.asarray(valid), jnp.asarray(enc.valid_pair))

        lowered = engine._role_certify_kernel.lower(*cert_args, alpha_iters=0)

        def run_cert():
            out = engine._role_certify_kernel(*cert_args, alpha_iters=0)
            np.asarray(out[0])  # device->host fetch = true completion sync

        rec = measure(f"{model} stage0 certify ({lo.shape[0]} boxes)",
                      lowered, run_cert, args.reps)
        rec["parts"] = int(lo.shape[0])
        rec["boxes_per_sec"] = round(lo.shape[0] / rec["warm_launch_s_median"], 1)
        records.append(rec)

        rng = np.random.default_rng(0)
        xr, pr = engine.build_attack_candidates(enc, rng, lo, hi, 32)
        att_args = (net, jnp.asarray(xr), jnp.asarray(pr))
        lowered_a = engine._attack_logits.lower(*att_args)

        def run_att():
            out = engine._attack_logits(*att_args)
            np.asarray(out[0])  # device->host fetch = true completion sync

        rec = measure(f"{model} attack forward ({xr.shape[0]}x{xr.shape[1]}"
                      f"x{xr.shape[2]} candidates)", lowered_a, run_att,
                      args.reps)
        records.append(rec)

    # One real profiler trace around a certify launch (XProf-viewable).
    os.makedirs(args.trace_dir, exist_ok=True)
    with jax.profiler.trace(args.trace_dir):
        run_cert()
    trace_files = sum(len(fs) for _, _, fs in os.walk(args.trace_dir))

    for r in records:
        if peak:
            r["pct_peak_flops"] = round(100.0 * r["achieved_gflops"] / (peak[0] * 1e3), 2)
    out = {
        "what": ("Roofline position of the stage-0 kernels on the real "
                 "chip: XLA cost_analysis FLOPs/logical-bytes + measured "
                 "warm-launch wall time (device-fetch-synced).  "
                 "Arithmetic intensity of a few FLOP/logical-byte puts "
                 "stage 0 deep in the memory/launch-bound region — the "
                 "partitions axis streams small role-box tensors through "
                 "small matmuls — so tuning is launch/batch amortization "
                 "(grid_chunk, frontier_size), not MXU saturation; the "
                 "MXU headroom is what the vmapped model-family kernels "
                 "exploit."),
        "script": "scripts/device_util.py",
        "device_kind": kind,
        "platform": dev.platform,
        "nominal_peaks": ({"tflops_f32": peak[0], "hbm_gbps": peak[1]}
                          if peak else "unknown device kind"),
        "profiler_trace": {"dir": args.trace_dir, "files": trace_files},
        "records": records,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fp:
        json.dump(out, fp, indent=1)
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    main()

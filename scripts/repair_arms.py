#!/usr/bin/env python
"""Three-arm repair comparison: masked vs same-label baseline vs consensus.

VERDICT r4 missing #2: the repo shipped masked repair and a (better)
consensus-label two-stage retrain, but the reference's third variant — the
conservative same-label relabeling retrain (``/root/reference/src/AC/
detect_bias.py:412-433``) — had no analog, so the consensus design's
superiority was asserted, not measured.  This harness runs ONE verification
sweep to collect counterexample pairs, then all three repair arms from the
same starting net, and records per-arm: validation accuracy, the group
metrics (DI/SPD/EOD/AOD), black-box causal discrimination rate, and mean
pair inconsistency on the counterexample pairs.  Writes
``audits/repair_arms_r5.json`` and appends a section to ``EXPERIMENTS.md``.

Usage: python scripts/repair_arms.py [--preset GC --model GC-3]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
os.chdir(ROOT)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", default="GC")
    ap.add_argument("--model", default="GC-3")
    ap.add_argument("--out", default="audits/repair_arms_r5.json")
    ap.add_argument("--no-md", action="store_true")
    args = ap.parse_args()

    import jax.numpy as jnp
    import numpy as np

    from fairify_tpu.analysis import causal as causal_mod
    from fairify_tpu.analysis import repair as repair_mod
    from fairify_tpu.data import loaders
    from fairify_tpu.models import mlp as mlp_mod, zoo
    from fairify_tpu.verify import presets, sweep

    cfg = presets.get(args.preset).with_(
        result_dir=f"/tmp/repair_arms_{args.preset}")
    net = zoo.load(cfg.dataset, args.model)
    ds = loaders.load(cfg.dataset)
    query = cfg.query()
    pa_col = query.columns.index(query.protected[0])

    report = sweep.verify_model(net, cfg, model_name=args.model, dataset=ds,
                                resume=False)
    pairs = [o.counterexample for o in report.outcomes if o.counterexample]
    if not pairs:
        print(json.dumps({"preset": args.preset, "model": args.model,
                          "verdicts": report.counts,
                          "note": "model certified fair - no counterexample "
                                  "pairs, nothing to repair"}))
        return 0
    xs = np.stack([p[0] for p in pairs]).astype(np.float32)
    xps = np.stack([p[1] for p in pairs]).astype(np.float32)

    Xv = jnp.asarray(np.asarray(ds.X_test), jnp.float32)
    yv = np.asarray(ds.y_test)
    prot = np.asarray(ds.X_test)[:, pa_col]
    dlo, dhi = query.domain.lo_hi()

    def snapshot(m):
        snap = repair_mod._group_snapshot(m, Xv, yv, prot)
        from fairify_tpu.models.mlp import forward

        import jax

        probs_x = jax.nn.sigmoid(forward(m, jnp.asarray(xs)))
        probs_p = jax.nn.sigmoid(forward(m, jnp.asarray(xps)))
        snap["pair_inconsistency"] = float(
            jnp.mean(jnp.abs(probs_x - probs_p)))
        pred = lambda X: np.asarray(
            mlp_mod.predict(m, jnp.asarray(X, jnp.float32)))
        snap["causal_rate"] = causal_mod.causal_discrimination(
            pred, dlo.astype(np.int64), dhi.astype(np.int64), pa_col,
            min_samples=200, max_samples=2000).rate
        return {k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in snap.items()}

    from fairify_tpu.analysis import localize as localize_mod

    loc = localize_mod.localize(net, pairs, [pa_col], top_k=5)
    arms = {"original": net}
    arms["masked"] = repair_mod.masked_repair(
        net, [(l, j) for l, j, _ in loc.ranked], ds.X_train, ds.y_train,
        epochs=3).net
    # The reference's faithful baseline: relabel each pair to the max of
    # the model's two predictions, plain BCE retrain, 5 epochs.
    arms["same_label_baseline"] = repair_mod.same_label_relabel_retrain(
        net, pairs).net
    arms["consensus_two_stage"] = repair_mod.counterexample_retrain(
        net, ds.X_train, ds.y_train, pairs, ds.X_test, ds.y_test,
        protected_col=pa_col).net

    out = {
        "preset": args.preset, "model": args.model,
        "verdicts": report.counts, "ce_pairs": len(pairs),
        "arms": {name: snapshot(m) for name, m in arms.items()},
        "reference_baseline_anchor": "src/AC/detect_bias.py:412-433",
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fp:
        json.dump(out, fp, indent=1)
    print(json.dumps(out))

    if not args.no_md:
        a = out["arms"]

        def row(name, label):
            s = a[name]
            return (f"| {label} | {s['acc']:.4f} | {s['di']:.3f} | "
                    f"{s['spd']:.4f} | {s['eod']:.4f} | {s['aod']:.4f} | "
                    f"{s['causal_rate']:.4f} | {s['pair_inconsistency']:.4f} |")

        section = [
            "",
            f"## Repair-arm comparison: `{args.model}` "
            "(same-label baseline vs consensus)",
            "",
            "The reference's conservative same-label relabeling retrain "
            "(`src/AC/detect_bias.py:412-433`: both pair points relabeled "
            "to the max prediction, plain BCE, 5 epochs) run FAITHFULLY as "
            "a baseline arm beside the masked repair and the consensus "
            "two-stage retrain, all from the same starting net and the "
            f"same {out['ce_pairs']} counterexample pairs "
            "(`scripts/repair_arms.py`, record "
            "`audits/repair_arms_r5.json`) — the consensus design's value "
            "is measured, not asserted (VERDICT r4 missing #2).",
            "",
            "| Arm | Acc | DI | SPD | EOD | AOD | causal rate | "
            "pair inconsistency |",
            "|---|---|---|---|---|---|---|---|",
            row("original", "original (no repair)"),
            row("masked", "masked fine-tune"),
            row("same_label_baseline",
                "same-label relabel retrain (reference baseline)"),
            row("consensus_two_stage", "consensus two-stage (this repo)"),
        ]
        with open("EXPERIMENTS.md", "a") as fp:
            fp.write("\n".join(section) + "\n")
        print("appended EXPERIMENTS.md section")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Diagnose the AC-7 UNKNOWN residue (round-3 scoping, VERDICT.md item 1).

For a sample of the 4,433 undecided partitions per PA, report which regime
each box is in:

* one-signed sampled logits (sign-BaB candidate that ran out of budget), vs
* genuinely mixed-sign logits over the box (uniform-sign certificate
  inapplicable — needs the relational pair-difference BaB), and
* how close the PGD attack gets to a flip (best |logit| and the PA logit
  offset |δ| at that point — the flip-slab width).

Usage: env PYTHONPATH= JAX_PLATFORMS=cpu python scripts/diagnose_ac7.py [N]
"""
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np
import jax.numpy as jnp

from fairify_tpu.models import zoo
from fairify_tpu.verify import engine, presets, sweep
from fairify_tpu.verify.property import encode, role_boxes


def main(n_sample=96, pa="sex"):
    cfg = presets.get("AC")
    if pa != "sex":
        cfg = cfg.with_(protected=(pa,))
    p_list, lo, hi = sweep.build_partitions(cfg)
    led_path = os.path.join(ROOT, "parity", f"AC-{pa}", "AC-AC-7.ledger.jsonl")
    led = {}
    for line in open(led_path):
        r = json.loads(line)
        led[r["partition_id"]] = r["verdict"]
    unk = sorted(pid for pid, v in led.items() if v == "unknown")
    print(f"PA={pa}: {len(unk)} unknown of {len(led)}")
    rng = np.random.default_rng(0)
    pick = rng.choice(len(unk), size=min(n_sample, len(unk)), replace=False)
    idx = np.array([unk[i] - 1 for i in sorted(pick)])

    net = zoo.load("adult", "AC-7")
    enc = encode(cfg.query())
    blo, bhi = lo[idx], hi[idx]
    B = len(idx)

    # Sampled role logits (1024 samples per box).
    xr, pr = engine.build_attack_candidates(enc, rng, blo, bhi, 1024)
    lx, lp = engine._attack_logits(net, jnp.asarray(xr), jnp.asarray(pr))
    lx, lp = np.asarray(lx), np.asarray(lp)
    x_lo, x_hi, xp_lo, xp_hi, valid = role_boxes(
        enc, blo.astype(np.float32), bhi.astype(np.float32))
    allv = np.concatenate([
        np.where(valid[:, None, :], lx, np.nan).reshape(B, -1),
        np.where(valid[:, None, :], lp, np.nan).reshape(B, -1)], axis=1)
    smin = np.nanmin(allv, axis=1)
    smax = np.nanmax(allv, axis=1)
    one_signed = (smin > 0) | (smax < 0)

    # PA sensitivity at sampled points: |f(x_a) - f(x_b)| across the two
    # assignments, same shared coords (slab width δ).
    # lx shape (B, S, V); V=2 for sex.
    if lx.shape[-1] == 2:
        delta = np.abs(lx[..., 0] - lx[..., 1])
        dmed = np.median(delta, axis=1)
        dmax = delta.max(axis=1)
    else:
        dmed = dmax = np.zeros(B)

    # CROWN root bounds (alpha 8).
    from fairify_tpu.ops import crown as crown_ops
    lbx, ubx = crown_ops.crown_output_bounds(net, jnp.asarray(x_lo), jnp.asarray(x_hi))
    lbx, ubx = np.asarray(lbx), np.asarray(ubx)
    # reduce over valid assignments
    lb = np.where(valid, lbx, np.inf).min(axis=1)
    ub = np.where(valid, ubx, -np.inf).max(axis=1)

    # PGD best |logit|.
    w, pts, best_abs = engine.pgd_attack(
        net, enc, blo, bhi, np.random.default_rng(1), return_points=True)

    print(f"one-signed-sample boxes: {one_signed.sum()}/{B}")
    print(f"sampled logit min/max percentiles: "
          f"min p10={np.percentile(smin,10):.3f} p50={np.percentile(smin,50):.3f} "
          f"p90={np.percentile(smin,90):.3f}; "
          f"max p10={np.percentile(smax,10):.3f} p50={np.percentile(smax,50):.3f} "
          f"p90={np.percentile(smax,90):.3f}")
    print(f"PA |delta| median-of-medians={np.median(dmed):.5f} "
          f"max-of-max={dmax.max():.5f}")
    print(f"CROWN root lb p50={np.percentile(lb,50):.2f}  ub p50={np.percentile(ub,50):.2f}")
    print(f"PGD witnesses found: {len(w)}/{B}; best|logit| p10={np.percentile(best_abs,10):.4f} "
          f"p50={np.percentile(best_abs,50):.4f} p90={np.percentile(best_abs,90):.4f}")
    # Regime classification
    mixed = ~one_signed
    print(f"mixed-sign boxes: {mixed.sum()} — these need the relational certificate")
    # For mixed boxes: is the PGD objective (min(max f_a, -min f_b)) actually
    # negative (no flip nearby) or positive-but-invalid (f32 flip, exact no)?
    return 0


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 96
    pa = sys.argv[2] if len(sys.argv) > 2 else "sex"
    sys.exit(main(n, pa))

"""Independent exact replay of UNSAT certificates (VERDICT r2 ask #4).

``z3-solver`` cannot be installed here, so the ``audits/smt/`` artifacts had
never been consumed by any decision procedure other than the engine that
produced them.  This harness replays them — and a sample of the hardest
UNSAT certificates (AC-7/AC-11, both protected attributes) — through
``verify.exact_check``: exact rational arithmetic, exact simplex leaves,
float-LP search whose every discharge is re-proved by an exactly-verified
weak-duality bound.  No CROWN f32 kernel, no HiGHS tolerance, no shared
numerics with the engine under audit.

* manifest UNSAT rows  → ``decide_pair_box_exact`` (lattice-complete;
  'unsat_confirmed' expected);
* manifest SAT rows    → the recorded witness replayed in exact arithmetic;
* AC-7 / AC-11 samples → ``confirm_sign_certificate`` (the uniform-sign
  claim behind those certificates), falling back to the pair checker.

Usage:
    python scripts/exact_replay.py [--sample 8] [--max-nodes 60000]
        [--out audits/exact_replay_r3.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sample", type=int, default=8,
                    help="UNSAT partitions sampled per (model, PA)")
    ap.add_argument("--max-nodes", type=int, default=60000)
    ap.add_argument("--out", default="audits/exact_replay_r3.json")
    args = ap.parse_args()

    import numpy as np

    from fairify_tpu.models import zoo
    from fairify_tpu.verify import exact_check, presets, sweep
    from fairify_tpu.verify.engine import validate_pair
    from fairify_tpu.verify.property import encode

    results = {"manifest": [], "hard_certificates": [], "summary": {}}
    grids: dict = {}
    nets: dict = {}

    def get_grid(preset, overrides=None):
        key = (preset, tuple(sorted((overrides or {}).items())))
        if key not in grids:
            cfg = presets.get(preset)
            if overrides:
                cfg = cfg.with_(**overrides)
            _, lo, hi = sweep.build_partitions(cfg)
            grids[key] = (cfg, lo, hi, encode(cfg.query()))
        return grids[key]

    def get_net(dataset, model):
        if (dataset, model) not in nets:
            net = zoo.load(dataset, model)
            nets[(dataset, model)] = (
                [np.asarray(w) for w in net.weights],
                [np.asarray(b) for b in net.biases])
        return nets[(dataset, model)]

    # ---- 1. The SMT-LIB artifact manifest ----------------------------------
    man_path = os.path.join(ROOT, "audits", "smt", "manifest.jsonl")
    with open(man_path) as fp:
        manifest = [json.loads(line) for line in fp]
    for rec in manifest:
        cfg, lo, hi, enc = get_grid(rec["preset"])
        W, B = get_net(cfg.dataset, rec["model"])
        p = rec["partition_id"] - 1
        t0 = time.time()
        if rec["native_verdict"] == "sat":
            x, xp = (np.asarray(v, dtype=np.int64) for v in rec["native_ce"])
            # Well-formedness first (legal pair, in-box), then the exact
            # strict flip — both are what the certificate claims.
            legal = exact_check.pair_is_legal(enc, lo[p], hi[p], x, xp)
            ok = legal and validate_pair(W, B, x, xp)
            out = {"file": rec["file"], "expected": "sat",
                   "result": "witness_confirmed" if ok else "WITNESS_REFUTED",
                   "legal_pair": bool(legal),
                   "time_s": round(time.time() - t0, 2)}
        else:
            r = exact_check.decide_pair_box_exact(
                W, B, enc, lo[p], hi[p], max_nodes=args.max_nodes)
            out = {"file": rec["file"], "expected": "unsat",
                   "result": r["verdict"], "nodes": r.get("nodes"),
                   "time_s": round(time.time() - t0, 2)}
            if r["verdict"] == "refuted":
                out["witness"] = r["witness"]
        results["manifest"].append(out)
        print(json.dumps(out), flush=True)

    # ---- 2. AC-7 / AC-11 hard-certificate samples, both PAs ----------------
    rng = np.random.default_rng(0)
    for model in ("AC-7", "AC-11"):
        for pa, overrides in (("sex", None), ("race", {"protected": ("race",)})):
            ledger = os.path.join(ROOT, "parity", f"AC-{pa}",
                                  f"AC-{model}.ledger.jsonl")
            if not os.path.isfile(ledger):
                continue
            led = {}
            with open(ledger) as fp:
                for line in fp:
                    try:
                        r = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    led[r["partition_id"]] = r["verdict"]
            unsat_pids = sorted(p for p, v in led.items() if v == "unsat")
            if not unsat_pids:
                continue
            pick = sorted(rng.choice(len(unsat_pids),
                                     size=min(args.sample, len(unsat_pids)),
                                     replace=False))
            cfg, lo, hi, enc = get_grid("AC", overrides)
            W, B = get_net("adult", model)
            for i in pick:
                pid = unsat_pids[i]
                p = pid - 1
                t0 = time.time()
                # The uniform-sign claim first (the certificate's shape for
                # these models); sampled logits pick the conjectured sign.
                from fairify_tpu.models.mlp import forward_np

                mid = ((lo[p] + hi[p]) // 2).astype(np.float64)
                want_pos = float(forward_np(W, B, mid)) > 0
                # The uniform-sign shortcut only implies pair-UNSAT when
                # the box itself covers both roles — an RA shift widens the
                # x' role by ±ε beyond it, so relaxed presets must take the
                # pair checker.
                r = {"verdict": "skipped"} if enc.eps else \
                    exact_check.confirm_sign_certificate(
                        W, B, lo[p], hi[p], want_positive=want_pos,
                        max_nodes=4000)
                method = "sign"
                if r["verdict"] != "confirmed":
                    r = exact_check.decide_pair_box_exact(
                        W, B, enc, lo[p], hi[p], max_nodes=args.max_nodes)
                    method = "pair"
                    verdict = r["verdict"]
                else:
                    verdict = "unsat_confirmed"
                out = {"model": model, "pa": pa, "partition_id": pid,
                       "method": method, "result": verdict,
                       "nodes": r.get("nodes"),
                       "time_s": round(time.time() - t0, 2)}
                results["hard_certificates"].append(out)
                print(json.dumps(out), flush=True)

    # ---- summary -----------------------------------------------------------
    man_ok = sum(1 for r in results["manifest"]
                 if r["result"] in ("witness_confirmed", "unsat_confirmed"))
    hard_ok = sum(1 for r in results["hard_certificates"]
                  if r["result"] == "unsat_confirmed")
    refuted = sum(1 for sec in ("manifest", "hard_certificates")
                  for r in results[sec]
                  if r["result"] in ("refuted", "WITNESS_REFUTED"))
    results["summary"] = {
        "manifest_total": len(results["manifest"]),
        "manifest_confirmed": man_ok,
        "hard_total": len(results["hard_certificates"]),
        "hard_confirmed": hard_ok,
        "refuted": refuted,
    }
    out_path = os.path.join(ROOT, args.out)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as fp:
        json.dump(results, fp, indent=1)
    print(json.dumps(results["summary"]))
    return 0 if refuted == 0 else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env bash
# Reproduction harness — the rebuild's analog of the reference's
# reproduce.sh / reproduce-experiment.sh (SURVEY.md §1 L5): run every base
# sweep preset over its model zoo and collect the per-model CSVs + ledgers
# + throughput counters under ./res/.
#
# Usage: scripts/reproduce.sh [results_dir] [soft_timeout_s]
set -euo pipefail
RES="${1:-res}"
SOFT="${2:-100}"

for preset in GC AC BM CP DF; do
  echo "=== preset $preset"
  python -m fairify_tpu run "$preset" \
    --soft-timeout "$SOFT" --result-dir "$RES/$preset"
done

echo "=== stress / relaxed / targeted variants"
for preset in stress-GC stress-AC stress-BM relaxed-GC relaxed-AC relaxed-BM \
              targeted-GC targeted-AC targeted-BM targeted2-GC targeted2-AC targeted2-BM; do
  echo "=== preset $preset"
  python -m fairify_tpu run "$preset" \
    --soft-timeout "$SOFT" --result-dir "$RES/$preset"
done

echo "=== headline benchmark"
python -m fairify_tpu bench | tee "$RES/bench.json"

#!/usr/bin/env python
"""AC-suite scaling harness: model-partitions/s at mesh size 1 vs N.

Produces the MULTICHIP perfdiff record ROADMAP item 2 asks for: one JSON
object with the per-mesh-size stage-0 throughput of a same-architecture
model family and the 1→N scaling factor, gate-able by
``scripts/perfdiff.py`` against a previous round's record::

    python scripts/multichip_scaling.py --devices 8 --out MULTICHIP_scaling.json
    python scripts/perfdiff.py MULTICHIP_r05.json MULTICHIP_scaling.json

The sweep runs through the sharded runtime (``parallel.shards``) with
``n_shards=1`` — the whole device fleet under one ``(parts, models)``
mesh, which is the maximum-launch-width configuration — timing the
stage-0-dominated grid pass of a synthetic family (the AC-suite pattern:
several same-input-width MLPs).  On real multi-chip hardware the wall
clock is the headline; on virtual CPU devices
(``xla_force_host_platform_device_count``) the absolute numbers mean
little, but the RECORD SHAPE and the gate wiring are identical, so CI can
watch the ratio on whatever fleet it has.

Record semantics: ``ok`` is run-health (every mesh size completed and
decided the SAME verdict map) — the meaning the driver's minimal
``MULTICHIP_r*.json`` records already carry, so the two shapes gate
against each other.  ``scaling_ok`` records whether ``scaling_x`` met
``--target-x``; the regression signal for throughput is ``scaling_x`` /
``pps@Ndev`` moving between rounds (perfdiff gates them whenever both
records carry them), not a fixed bar shared-core virtual devices can
never clear.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# Pin the virtual CPU fleet BEFORE jax initializes (same contract as
# tests/conftest.py); harmless when real accelerators are configured via
# JAX_PLATFORMS explicitly.
_N = None
for _i, _a in enumerate(sys.argv):
    if _a == "--devices" and _i + 1 < len(sys.argv):
        _N = int(sys.argv[_i + 1])
    elif _a.startswith("--devices="):
        _N = int(_a.split("=", 1)[1])
_N = _N or 8
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={_N}").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _run_once(net, cfg, devices, span, label):
    """One sharded sweep over ``devices`` (n_shards=1); partitions/sec."""
    from fairify_tpu.parallel import shards

    t0 = time.perf_counter()
    rep = shards.sweep_sharded(net, cfg, model_name=label, devices=devices,
                               n_shards=1, partition_span=span, resume=False)
    dt = time.perf_counter() - t0
    n = len(rep.outcomes)
    return n / max(dt, 1e-9), rep


def _vmap(rep):
    return {o.partition_id: o.verdict for o in rep.outcomes}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=8,
                    help="fleet size for the wide mesh (default 8)")
    ap.add_argument("--models", type=int, default=4,
                    help="synthetic same-architecture family size")
    ap.add_argument("--hidden", type=int, default=64,
                    help="hidden width of the synthetic MLPs")
    ap.add_argument("--span", type=int, default=192,
                    help="partition-grid span per model")
    ap.add_argument("--grid-chunk", type=int, default=64)
    ap.add_argument("--out", default="MULTICHIP_scaling.json")
    ap.add_argument("--target-x", type=float, default=4.0,
                    help="scaling factor the record's ok flag requires")
    args = ap.parse_args()

    import jax

    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.verify import presets

    devs = jax.devices()
    if len(devs) < args.devices:
        print(f"multichip_scaling: only {len(devs)} devices visible "
              f"(wanted {args.devices})", file=sys.stderr)
        return 2
    cfg = presets.get("GC").with_(
        soft_timeout_s=30.0, hard_timeout_s=3600.0, sim_size=64,
        exact_certify_masks=False, grid_chunk=args.grid_chunk,
        result_dir=os.path.join("res", "multichip_scaling"))
    n_in = len(cfg.query().columns)
    span = (0, args.span)
    pps = {}
    verdicts = {}  # mesh size -> per-model verdict maps
    for n_dev in (1, args.devices):
        rates = []
        maps = []
        for m in range(args.models):
            net = init_mlp((n_in, args.hidden, 1), seed=100 + m)
            cfg_m = cfg.with_(result_dir=os.path.join(
                cfg.result_dir, f"d{n_dev}"))
            # Warm the compile caches on the first model only; the timed
            # family rides warm executables like a serving fleet would.
            rate, rep = _run_once(net, cfg_m, list(devs[:n_dev]), span,
                                  label=f"m{m}")
            if m == 0:
                rate, rep = _run_once(net, cfg_m, list(devs[:n_dev]), span,
                                      label=f"m{m}")
            rates.append(rate)
            maps.append(_vmap(rep))
            print(json.dumps({"mesh": n_dev, "model": f"m{m}",
                              "partitions_per_sec": round(rate, 2),
                              **rep.counts}), flush=True)
        pps[str(n_dev)] = round(sum(rates) / len(rates), 3)
        verdicts[n_dev] = maps
    scaling = pps[str(args.devices)] / max(pps["1"], 1e-9)
    # `ok` is run-health — the same meaning the driver's minimal
    # MULTICHIP_r*.json dry-run records carry, so the two shapes gate
    # against each other: every mesh size completed AND decided the same
    # verdict map.  Target attainment is its own field (`scaling_ok`);
    # the gated regression signal for throughput is `scaling_x` /
    # `pps@Ndev` moving between rounds, not a fixed bar a virtual-CPU rig
    # can never clear.
    consistent = verdicts[1] == verdicts[args.devices]
    record = {
        "n_devices": args.devices,
        "rc": 0,
        "ok": consistent,
        "verdicts_consistent": consistent,
        "model_partitions_per_sec": pps,
        "scaling_x": round(scaling, 3),
        "scaling_ok": scaling >= args.target_x,
        "target_x": args.target_x,
        "family": {"models": args.models, "hidden": args.hidden,
                   "span": args.span, "grid_chunk": args.grid_chunk},
    }
    with open(args.out, "w") as fp:
        json.dump(record, fp, indent=2)
    print(json.dumps(record), flush=True)
    # A cross-mesh verdict mismatch is a correctness failure worth a
    # nonzero exit even with no baseline to perfdiff against; a missed
    # throughput target is not (that signal gates round-over-round).
    return 0 if consistent else 1


if __name__ == "__main__":
    sys.exit(main())

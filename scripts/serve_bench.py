#!/usr/bin/env python
"""Service-level benchmark: request latency under concurrent clients.

    python scripts/serve_bench.py --out SERVE_r01.json [--clients 1 4 16]
        [--preset GC] [--span 48] [--grid-chunk 16] [--rounds 2]
        [--priority-mix] [--replicas N]

Runs ONE warm in-process :class:`fairify_tpu.serve.VerificationServer` and,
for each client level C, submits C concurrent same-architecture requests
(distinct synthetic models, so cross-request arch-bucketed coalescing is
exercised, not per-model caching) and measures per-request wall latency
submit → terminal state.  The record a round produces is the ``SERVE``
perfdiff kind::

    {"kind": "SERVE", "clients": {"4": {"p50_ms": ..., "p95_ms": ...,
     "p99_ms": ..., "deadline_miss_rate": ..., "batch_occupancy_mean": ...,
     "requests_per_s": ...}, ...},
     "warm_xla_compiles": 0, "coalesced_device_launches": N,
     "sequential_device_launches": M}

Two service-health headlines ride along (ISSUE 8 acceptance):

* ``warm_xla_compiles`` — XLA compiles during the 4-client level (the
  acceptance cell) after warmup.  A warm server must not recompile
  whatever mix of same-bucket requests arrives: the healthy value is 0.
  Each level row also carries its own ``xla_compiles`` — the 16-client
  stress level may legitimately compile *refinement*-path kernels
  (sign-BaB, pair-LP) the first time a pathological model's UNKNOWNs
  reach them; that is a new code path, not shape churn, and it shows up
  in its level's row instead of silently failing the warm gate.
* ``coalesced_device_launches`` vs ``sequential_device_launches`` — device
  launches for the 4-client concurrent level vs 4 solo ``verify_model``
  runs of the same spans.  Coalescing is measurably working iff
  coalesced < sequential.

**Overload scenario** (``--priority-mix``, ISSUE 11 / SERVE_r02): the
measured levels run with the overload-survival layer live — a bounded
queue (``--max-queue``), priority tiers in a high:normal:normal:low
rotation (high gets a quarter of the SLA, low is best-effort), span-
granular preemption (``--preempt-factor``) — and each level row splits
honest triage from failure: ``shed_rate`` (rejected with a ``shed:``
reason before costing device time) and ``preemptions`` are reported
separately, latencies and ``deadline_miss_rate`` cover ADMITTED requests
only.  A shed is a fast, actionable rejection; counting it as a miss
(as a naive reading of r01 would) rewards servers that bury clients in a
two-minute queue instead of answering.  ``requests_per_s`` is completed-
request goodput (``done / wall``); r01 counted every terminal request, so
across that seam the comparison is conservative — goodput can only
under-claim against a throughput baseline.

``--replicas N`` routes the levels through
:class:`serve.fleet.ServerFleet`.  Every client submits the SAME span and
architecture (one coalescing bucket): it is the router's load
*spill-over* — not workload partitioning — that spreads an overloaded
bucket across replicas, exactly as production traffic would.  The
executable cache is always on (under ``--work-dir``, or a persistent
``--exec-cache-dir`` for steady-state runs), and the record closes with a
``cold_restart`` block: a fresh subprocess re-runs one span against the
populated cache — ``n_compiles == 0`` with ``compile_s ~ 0`` is the
zero-cold-start headline.

``scripts/perfdiff.py`` gates p95 latency, deadline-miss, shed-rate,
preemption-count, and cold-restart compile growth between two SERVE
records (lower-is-better with noise tolerances; see its docstring).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _percentiles(latencies_s):
    import numpy as np

    if not latencies_s:
        return {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
    ms = np.asarray(sorted(latencies_s)) * 1000.0
    return {
        "p50_ms": round(float(np.percentile(ms, 50)), 1),
        "p95_ms": round(float(np.percentile(ms, 95)), 1),
        "p99_ms": round(float(np.percentile(ms, 99)), 1),
    }


def _cold_restart(args, exec_dir: str, in_dim: int) -> dict:
    """Fresh-process probe of the zero-cold-start contract: a subprocess
    with empty in-memory caches re-runs the warmup span against the
    executable cache this bench populated.  ``n_compiles == 0`` with
    ``compile_s ~ 0`` is the headline — every kernel loads from disk."""
    import subprocess

    rdir = os.path.join(os.path.abspath(args.work_dir), "cold-restart")
    code = f"""
import json, os, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
t_import = time.perf_counter()
from fairify_tpu.obs import compile as compile_obs
compile_obs.enable_exec_cache({exec_dir!r})
from fairify_tpu.models.train import init_mlp
from fairify_tpu.verify import presets, sweep
cfg = presets.get({args.preset!r}).with_(
    soft_timeout_s=10.0, hard_timeout_s=600.0, sim_size=64,
    exact_certify_masks=False, grid_chunk={args.grid_chunk},
    launch_backoff_s=1e-4, result_dir={rdir!r})
net = init_mlp(({in_dim}, 8, 1), seed=0)
t0 = time.perf_counter()
sweep.verify_model(net, cfg, model_name="cold", resume=False,
                   partition_span=(0, {args.span}))
tot = compile_obs.snapshot_totals()
hits = sum(k.stats.cache_hits for k in compile_obs.kernels().values())
print(json.dumps({{
    "wall_s": round(time.perf_counter() - t0, 3),
    "import_s": round(t0 - t_import, 3),
    "n_compiles": tot["n_compiles"],
    "compile_s": round(tot["compile_s"], 3),
    "exec_cache_hits": hits,
}}))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], cwd=ROOT, env=env,
                         capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        print(f"serve_bench: cold-restart probe failed:\n{out.stderr[-2000:]}",
              file=sys.stderr)
        return {"error": out.stderr[-400:]}
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="SERVE_r01.json")
    ap.add_argument("--preset", default="GC")
    ap.add_argument("--span", type=int, default=48,
                    help="partitions per request (one contiguous span)")
    ap.add_argument("--grid-chunk", type=int, default=16)
    ap.add_argument("--clients", type=int, nargs="*", default=[1, 4, 16],
                    help="concurrent-client levels to measure")
    ap.add_argument("--rounds", type=int, default=2,
                    help="measurement rounds per level (latency sample size "
                         "= clients x rounds)")
    ap.add_argument("--deadline", type=float, default=120.0,
                    help="per-request SLA in seconds (misses are counted, "
                         "not fatal; also clamps a pathological request's "
                         "refinement tail — FIFO refinement means one hard "
                         "tail delays everything behind it)")
    ap.add_argument("--work-dir", default="serve_bench_work",
                    help="scratch directory for request sinks (wiped)")
    ap.add_argument("--priority-mix", action="store_true",
                    help="overload scenario: priority tiers, bounded-queue "
                         "shedding, and span-granular preemption at every "
                         "level (the SERVE_r02 configuration)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="route levels through a ServerFleet of N replicas "
                         "(clients spread over N span groups)")
    ap.add_argument("--replica-procs", type=int, default=0,
                    help="route levels through a ProcessFleet of N "
                         "OS-process replicas (serve.procfleet): submits "
                         "go through the real spool protocol, latencies "
                         "are the server-side queue_wait+run from each "
                         "request's terminal record, and the record gains "
                         "a `procfleet` block (deaths/restarts/re-homes + "
                         "fleet compile totals) perfdiff gates "
                         "lower-is-better")
    ap.add_argument("--max-queue", type=int, default=8,
                    help="bounded-queue shed depth in --priority-mix mode")
    ap.add_argument("--preempt-factor", type=float, default=2.0,
                    help="over-budget preemption multiple in --priority-mix "
                         "mode (span_chunks=1)")
    ap.add_argument("--fair-share", type=float, default=4.0,
                    help="fair-share hard-budget clamp multiple in "
                         "--priority-mix mode: under contention a request "
                         "gets this multiple of its admission estimate; "
                         "overrun degrades to resumable UNKNOWNs")
    ap.add_argument("--no-cold-restart", action="store_true",
                    help="skip the cold-restart-from-cache subprocess probe")
    ap.add_argument("--trace-dir", default=None,
                    help="run the measured levels with distributed tracing "
                         "on: every process (router, replicas, SMT workers) "
                         "writes a trace.<pid>.jsonl shard here, merged by "
                         "`fairify_tpu report --trace-dir` (DESIGN.md §19)")
    ap.add_argument("--trace-ab", type=int, default=0, metavar="N",
                    help="after the measured levels, A/B one N-client round "
                         "with tracing ON vs OFF on the warm server and "
                         "gate the pps delta through perfdiff.compare "
                         "(within-noise = green).  In-process modes only: "
                         "process replicas fix their tracer at spawn, so "
                         "the arms would not differ (skipped with a note)")
    ap.add_argument("--exec-cache-dir", default=None,
                    help="persistent executable cache directory (default: "
                         "<work-dir>/exec-cache, wiped with it).  Point it "
                         "somewhere persistent to measure the steady state "
                         "a deployed fleet actually runs in: first-touch "
                         "refinement compiles are paid once per deployment, "
                         "not once per load spike")
    args = ap.parse_args()

    from fairify_tpu import obs
    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.obs import compile as compile_obs
    from fairify_tpu.serve import FleetConfig, ServeConfig, ServerFleet, \
        VerificationServer
    from fairify_tpu.verify import presets, sweep

    cfg0 = presets.get(args.preset).with_(
        soft_timeout_s=10.0, hard_timeout_s=600.0, sim_size=64,
        exact_certify_masks=False, grid_chunk=args.grid_chunk,
        launch_backoff_s=1e-4)
    span = (0, args.span)
    in_dim = len(cfg0.query().columns)
    shutil.rmtree(args.work_dir, ignore_errors=True)
    # Executable cache on from the very first compile: the warmup runs
    # populate it, the cold_restart probe proves a fresh process loads it.
    exec_dir = args.exec_cache_dir or os.path.join(
        os.path.abspath(args.work_dir), "exec-cache")
    compile_obs.enable_exec_cache(exec_dir)

    # One coalescing bucket for every client (same span, same arch): the
    # fleet's spill-over routing — not span partitioning — is what spreads
    # an overloaded bucket across replicas, exactly like production load.

    # Priority rotation (25 % high / 50 % normal / 25 % low) + SLA shape:
    # high gets a quarter of the window (interactive), low twice of it
    # (batch — still a deadline: a best-effort request would spend every
    # optional refinement budget and is a different workload, not a tier).
    # Applied only at overload levels (>= 8 clients): the small levels
    # stay bit-comparable with the r01 methodology.
    def _prio_of(c):
        tier = ("high", "normal", "normal", "low")[c % 4]
        prio = {"low": 0, "normal": 1, "high": 2}[tier]
        deadline = {"low": 2.0 * args.deadline, "normal": args.deadline,
                    "high": args.deadline / 4.0}[tier]
        return prio, deadline

    registry = obs.registry()
    launches = registry.counter("device_launches")
    # serve_batch_occupancy counts requests that actually entered a
    # coalesced stage-0 wave (serve_batch_size would also count solo
    # batches — queue pressure, not coalescing, and it would read full
    # even with coalescing broken).
    batch_hist = registry.histogram("serve_batch_occupancy")

    def _net(seed):
        return init_mlp((in_dim, 8, 1), seed=seed)

    procs = args.replica_procs > 0
    # Sequential baseline: 4 solo runs, counted warm (after one throwaway
    # cold run that pays the compiles the server's warmup also pays).
    # Skipped in --replica-procs mode: launches happen in replica
    # processes, so the coalesced side of the comparison is unobservable
    # here (the procfleet block carries the fleet-level health instead).
    sequential_launches = None
    if not procs:
        sweep.verify_model(
            _net(0),
            cfg0.with_(result_dir=os.path.join(args.work_dir, "warm")),
            model_name="warm", resume=False, partition_span=span)
        seq0 = launches.total()
        for i in range(4):
            sweep.verify_model(
                _net(100 + i),
                cfg0.with_(result_dir=os.path.join(args.work_dir,
                                                   f"solo-{i}")),
                model_name=f"solo-{i}", resume=False, partition_span=span)
        sequential_launches = int(launches.total() - seq0)

    mix = args.priority_mix
    scfg = ServeConfig(
        batch_window_s=0.2, max_batch=8, exec_cache=exec_dir,
        max_queue=args.max_queue if mix else 0,
        preempt_factor=args.preempt_factor if mix else 0.0,
        fair_share_factor=args.fair_share if mix else 0.0,
        # Strict fair share: the latency-predictable tier — even an
        # uncontended tail request is clamped to its share, so one
        # refinement-hungry model can't stretch a level's p95 by 10x.
        # Requests run whole-span (span_chunks=0): the BaB phase spends
        # up to its granule budget on hard roots, so splitting a span
        # into G granules multiplies that burn by G — preemption (which
        # needs granules) is exercised by chaos_matrix --fleet and
        # test_serve, not by this latency record.
        fair_share_idle_exempt=not mix,
        # Thread-mode servers hand the SMT pool its worker shard dir
        # directly; process replicas get --trace-dir from the fleet.
        trace_dir=args.trace_dir if not args.replica_procs else None)
    spool = os.path.join(os.path.abspath(args.work_dir), "spool")
    if procs:
        from fairify_tpu.serve import ProcessFleet, ProcFleetConfig

        srv = ProcessFleet(ProcFleetConfig(
            n_replicas=args.replica_procs, spool=spool, poll_s=0.02,
            pulse_s=5.0, exec_cache=exec_dir, trace_dir=args.trace_dir,
            replica=scfg))
    elif args.replicas > 1:
        # Spill AT the shed bound: a burst spreads over the fleet right
        # before replicas would start shedding, while a small (shed-free,
        # sub-max_queue) burst stays on one replica with its full
        # coalescing occupancy.
        srv = ServerFleet(FleetConfig(
            n_replicas=args.replicas, poll_s=0.02,
            spill_load=max(args.max_queue, 2),
            replica=scfg))
    else:
        srv = VerificationServer(scfg)
    srv.start()
    # Router-side trace shard: replica/worker processes write their own
    # (the fleet forwards --trace-dir), so `fairify_tpu report
    # --trace-dir` merges every process of this bench into one tree.
    trace_scope = None
    if args.trace_dir:
        from fairify_tpu.obs import trace as trace_mod

        os.makedirs(args.trace_dir, exist_ok=True)
        trace_scope = obs.tracing(trace_mod.shard_path(args.trace_dir),
                                  run_id="serve-bench")
        trace_scope.__enter__()
    if procs:
        from fairify_tpu.serve import client as spool_client

        ready = srv.wait_ready(timeout=300)
        print(f"serve_bench: {ready}/{args.replica_procs} process replicas "
              f"ready", file=sys.stderr)

        cfg_overrides = {
            "soft_timeout_s": 10.0, "hard_timeout_s": 600.0, "sim_size": 64,
            "exact_certify_masks": False, "grid_chunk": args.grid_chunk,
            "launch_backoff_s": 1e-4}

        def spool_submit(seed, deadline=None, prio=None):
            return spool_client.submit(spool, spool_client.build_payload(
                args.preset, init={"sizes": [in_dim, 8, 1], "seed": seed},
                overrides=dict(cfg_overrides), deadline_s=deadline,
                span=span, priority=prio))

        def spool_wait(rid, timeout=900.0):
            return spool_client.wait(spool, rid, timeout=timeout,
                                     poll_s=0.02)
    # Server warmup: one solo request (solo kernels) plus one coalesced
    # wave (the fixed-width family executable — pad_models means any
    # later occupancy reuses it).  After this, the measured levels must
    # hit the warm executable cache only.  In --replica-procs mode the
    # warmup spreads one request per replica (least-loaded routing), so
    # every process compiles-or-loads its kernels before measurement.
    if procs:
        warm_ids = [spool_submit(900 + i)
                    for i in range(max(args.replica_procs, 2))]
        for rid in warm_ids:
            spool_wait(rid)
        compiles0 = 0
    if not procs:
        w = srv.submit(
            cfg0.with_(result_dir=os.path.join(args.work_dir, "w0")),
            _net(0), "w0", partition_span=span)
        srv.wait(w.id, timeout=900.0)
        wave = [srv.submit(
            cfg0.with_(result_dir=os.path.join(args.work_dir, f"wv{i}")),
            _net(900 + i), f"wv{i}", partition_span=span) for i in range(2)]
        for req in wave:
            srv.wait(req.id, timeout=900.0)
        # Warm-until-quiescent: keep feeding fresh warmup models until a
        # whole round adds zero compiles.  The SERVE_r01 postmortem found
        # the 7 mid-load compiles at 16 clients were FIRST-TOUCH
        # refinement kernels (sign-BaB, pair-LP, PGD slabs) — paths only
        # UNKNOWN-heavy models reach, which the old stage-0-decidable
        # warmup never exercised; the measured levels then paid
        # multi-second compile stalls mid-overload.
        wseed = 950
        for _round in range(6):
            c_before = compile_obs.snapshot_totals()["n_compiles"]
            wave = [srv.submit(
                cfg0.with_(result_dir=os.path.join(args.work_dir,
                                                   f"wq{wseed + i}")),
                _net(wseed + i), f"wq{wseed + i}", partition_span=span)
                for i in range(4)]
            for req in wave:
                srv.wait(req.id, timeout=900.0)
            wseed += 4
            if compile_obs.snapshot_totals()["n_compiles"] == c_before:
                break
        compiles0 = compile_obs.snapshot_totals()["n_compiles"]

    preempt_ctr = registry.counter("serve_preemptions")
    levels = {}
    coalesced_launches = None
    seed = 1000
    for n_clients in args.clients:
        latencies = []
        misses = 0
        sheds = 0
        done_n = 0
        total = 0
        b_sum0, b_cnt0 = batch_hist.sum(), batch_hist.count()
        lvl_l0 = launches.total()
        lvl_c0 = compile_obs.snapshot_totals()["n_compiles"]
        lvl_p0 = preempt_ctr.total()
        t_lvl = time.perf_counter()
        for rnd in range(args.rounds):
            if procs:
                # Spool protocol end-to-end: latency is the server-side
                # queue_wait + run from each terminal record (the r01/r02
                # finished_at - submitted_at quantity, measured where the
                # clocks live).
                rids = []
                for c in range(n_clients):
                    seed += 1
                    if mix and n_clients >= 8:
                        prio, deadline = _prio_of(c)
                    else:
                        prio, deadline = 1, args.deadline
                    rids.append(spool_submit(seed, deadline=deadline,
                                             prio=prio))
                for rid in rids:
                    rec = spool_wait(rid)
                    total += 1
                    if rec is None:
                        misses += 1  # never terminal: worse than a miss
                        continue
                    if rec.get("status") == "rejected" and str(
                            rec.get("reason", "")).startswith("shed"):
                        sheds += 1
                        continue
                    done_n += int(rec.get("status") == "done")
                    latencies.append(float(rec.get("queue_wait_s", 0.0))
                                     + float(rec.get("run_s", 0.0)))
                    misses += int(bool(rec.get("deadline_missed"))
                                  or rec.get("status") != "done")
                continue
            reqs = []
            for c in range(n_clients):
                seed += 1
                rdir = os.path.join(args.work_dir,
                                    f"c{n_clients}-r{rnd}-{c}")
                if mix and n_clients >= 8:
                    prio, deadline = _prio_of(c)
                else:
                    prio, deadline = 1, args.deadline
                reqs.append(srv.submit(
                    cfg0.with_(result_dir=rdir), _net(seed),
                    f"m{seed}", deadline_s=deadline,
                    partition_span=span, priority=prio))
            for req in reqs:
                done = srv.wait(req.id, timeout=900.0)
                total += 1
                if done is not None and done.status == "rejected" \
                        and done.reason.startswith("shed"):
                    # Honest triage: the client got an actionable answer
                    # in milliseconds, before any device time was spent —
                    # a rejection, not a miss.
                    sheds += 1
                    continue
                if done is None or done.finished_at is None:
                    misses += 1  # never finished: worse than a miss
                    continue
                done_n += int(done.status == "done")
                latencies.append(done.finished_at - done.submitted_at)
                misses += int(done.deadline_missed
                              or done.status != "done")
        wall = time.perf_counter() - t_lvl
        admitted = total - sheds
        b_cnt = batch_hist.count() - b_cnt0
        occupancy = ((batch_hist.sum() - b_sum0) / b_cnt) if b_cnt else 0.0
        if n_clients == 4 and not procs:
            # Launches land in replica processes in --replica-procs mode;
            # this process's counter would read a misleading 0.
            coalesced_launches = int((launches.total() - lvl_l0)
                                     / args.rounds)
        row = {
            "requests": total,
            "admitted": admitted,
            **_percentiles(latencies),
            "deadline_miss_rate": round(misses / max(admitted, 1), 4),
            "shed_rate": round(sheds / max(total, 1), 4),
            "requests_per_s": round(done_n / wall, 3),
        }
        if not procs:
            # Compile/occupancy/preemption instruments live in THIS
            # process only for thread-mode servers; replica processes
            # report their compile totals in the procfleet block instead.
            row["preemptions"] = int(preempt_ctr.total() - lvl_p0)
            row["batch_occupancy_mean"] = round(occupancy, 3)
            row["xla_compiles"] = int(
                compile_obs.snapshot_totals()["n_compiles"] - lvl_c0)
        levels[str(n_clients)] = row
        print(f"serve_bench: {n_clients:>2} client(s): "
              f"{levels[str(n_clients)]}", file=sys.stderr)
    # Tracing-overhead A/B (DESIGN.md §19): one N-client round with the
    # tracer ON, one OFF, on the same warm server — gated through the
    # real perfdiff noise model (OFF is the baseline, ON the candidate;
    # a finding means tracing costs more than single-sample noise).
    trace_ab = None
    if args.trace_ab > 0 and not procs:
        if trace_scope is not None:
            trace_scope.__exit__(None, None, None)  # OFF arm must be off
            trace_scope = None
        from fairify_tpu.obs import trace as trace_mod

        def _ab_round(n, seed0):
            t0 = time.perf_counter()
            reqs = [srv.submit(
                cfg0.with_(result_dir=os.path.join(args.work_dir,
                                                   f"ab{seed0 + c}")),
                _net(seed0 + c), f"ab{seed0 + c}",
                deadline_s=args.deadline, partition_span=span,
                priority=1) for c in range(n)]
            done = 0
            for req in reqs:
                rec = srv.wait(req.id, timeout=900.0)
                done += int(rec is not None and rec.status == "done")
            return done / (time.perf_counter() - t0)

        # Own shard dir: reusing --trace-dir would reopen (and truncate)
        # this pid's main shard.
        ab_dir = os.path.join(os.path.abspath(args.work_dir), "trace-ab")
        os.makedirs(ab_dir, exist_ok=True)
        with obs.tracing(trace_mod.shard_path(ab_dir),
                         run_id="serve-bench-ab"):
            pps_on = _ab_round(args.trace_ab, 5000)
        pps_off = _ab_round(args.trace_ab, 6000)
        sys.path.insert(0, os.path.join(ROOT, "scripts"))
        import perfdiff

        findings = perfdiff.compare(
            {"serve.trace_ab_pps": perfdiff._flat(pps_off)},
            {"serve.trace_ab_pps": perfdiff._flat(pps_on)},
            rel_guard=0.02, rel_tol=0.2)
        trace_ab = {
            "clients": args.trace_ab,
            "pps_on": round(pps_on, 3),
            "pps_off": round(pps_off, 3),
            "overhead_rel": round((pps_off - pps_on) / max(pps_off, 1e-9),
                                  4),
            "within_noise": not findings,
        }
        print(f"serve_bench: trace A/B {trace_ab}"
              + (f" findings={findings}" if findings else ""),
              file=sys.stderr)
    elif args.trace_ab > 0:
        print("serve_bench: --trace-ab skipped: process replicas fix "
              "their tracer at spawn, the arms would not differ",
              file=sys.stderr)
    # The warm gate is the acceptance cell: 4 concurrent requests on a
    # warmed server compile nothing (falls back to the total across levels
    # when 4 wasn't measured).
    if procs:
        warm_compiles = None
    elif "4" in levels:
        warm_compiles = levels["4"]["xla_compiles"]
    else:
        warm_compiles = compile_obs.snapshot_totals()["n_compiles"] - compiles0
    procfleet_block = None
    if procs:
        drain_stats = {}
        srv.drain()
        drain_stats = srv.drain_stats()
        reg = registry
        procfleet_block = {
            "replicas": args.replica_procs,
            "replica_deaths": int(reg.counter("replica_deaths").total()),
            "replica_restarts": int(
                reg.counter("replica_restarts").total()),
            "rehomed": int(reg.counter("replica_rehomed").total()),
            "fleet_n_compiles": sum(
                int(s.get("n_compiles", 0)) for s in drain_stats.values()),
            "fleet_exec_cache_hits": sum(
                int(s.get("exec_cache_hits", 0))
                for s in drain_stats.values()),
        }
        print(f"serve_bench: procfleet {procfleet_block}", file=sys.stderr)
    else:
        srv.drain()
    if trace_scope is not None:
        trace_scope.__exit__(None, None, None)  # flush the router shard

    record = {
        "kind": "SERVE",
        "preset": args.preset,
        "span": args.span,
        "grid_chunk": args.grid_chunk,
        "rounds": args.rounds,
        "deadline_s": args.deadline,
        "priority_mix": bool(mix),
        "replicas": args.replicas,
        "replica_procs": args.replica_procs,
        "clients": levels,
        "warm_xla_compiles": None if warm_compiles is None
        else int(warm_compiles),
        "coalesced_device_launches": coalesced_launches,
        "sequential_device_launches": sequential_launches,
    }
    if args.trace_dir:
        record["trace_dir"] = args.trace_dir
    if trace_ab is not None:
        record["trace_ab"] = trace_ab
    if procfleet_block is not None:
        record["procfleet"] = procfleet_block
    if not args.no_cold_restart:
        record["cold_restart"] = _cold_restart(args, exec_dir, in_dim)
        print(f"serve_bench: cold restart from cache: "
              f"{record['cold_restart']}", file=sys.stderr)
    with open(args.out, "w") as fp:
        json.dump(record, fp, indent=1)
    print(json.dumps(record))
    if procs:
        # Process-mode health: every client level completed, and the
        # fleet neither crashed nor flapped (deaths gate lives in
        # perfdiff; here a restart is only fatal if requests were lost).
        ok = all(lvl.get("requests", 0) > 0 for lvl in levels.values())
        print(f"serve_bench: procfleet levels "
              f"{'OK' if ok else 'INCOMPLETE'} "
              f"(deaths={procfleet_block['replica_deaths']} "
              f"restarts={procfleet_block['replica_restarts']})",
              file=sys.stderr)
        return 0 if ok else 1
    ok = warm_compiles == 0 and (
        coalesced_launches is None or coalesced_launches < sequential_launches)
    if trace_ab is not None:
        ok = ok and trace_ab["within_noise"]
    print(f"serve_bench: warm compiles {warm_compiles} "
          f"(healthy: 0), coalesced launches {coalesced_launches} vs "
          f"{sequential_launches} sequential -> "
          f"{'OK' if ok else 'NOT COALESCING'}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Service-level benchmark: request latency under concurrent clients.

    python scripts/serve_bench.py --out SERVE_r01.json [--clients 1 4 16]
        [--preset GC] [--span 48] [--grid-chunk 16] [--rounds 2]

Runs ONE warm in-process :class:`fairify_tpu.serve.VerificationServer` and,
for each client level C, submits C concurrent same-architecture requests
(distinct synthetic models, so cross-request arch-bucketed coalescing is
exercised, not per-model caching) and measures per-request wall latency
submit → terminal state.  The record a round produces is the ``SERVE``
perfdiff kind::

    {"kind": "SERVE", "clients": {"4": {"p50_ms": ..., "p95_ms": ...,
     "p99_ms": ..., "deadline_miss_rate": ..., "batch_occupancy_mean": ...,
     "requests_per_s": ...}, ...},
     "warm_xla_compiles": 0, "coalesced_device_launches": N,
     "sequential_device_launches": M}

Two service-health headlines ride along (ISSUE 8 acceptance):

* ``warm_xla_compiles`` — XLA compiles during the 4-client level (the
  acceptance cell) after warmup.  A warm server must not recompile
  whatever mix of same-bucket requests arrives: the healthy value is 0.
  Each level row also carries its own ``xla_compiles`` — the 16-client
  stress level may legitimately compile *refinement*-path kernels
  (sign-BaB, pair-LP) the first time a pathological model's UNKNOWNs
  reach them; that is a new code path, not shape churn, and it shows up
  in its level's row instead of silently failing the warm gate.
* ``coalesced_device_launches`` vs ``sequential_device_launches`` — device
  launches for the 4-client concurrent level vs 4 solo ``verify_model``
  runs of the same spans.  Coalescing is measurably working iff
  coalesced < sequential.

``scripts/perfdiff.py`` gates p95 latency and deadline-miss growth between
two SERVE records (lower-is-better with noise tolerances; see its
docstring).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _percentiles(latencies_s):
    import numpy as np

    ms = np.asarray(sorted(latencies_s)) * 1000.0
    return {
        "p50_ms": round(float(np.percentile(ms, 50)), 1),
        "p95_ms": round(float(np.percentile(ms, 95)), 1),
        "p99_ms": round(float(np.percentile(ms, 99)), 1),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="SERVE_r01.json")
    ap.add_argument("--preset", default="GC")
    ap.add_argument("--span", type=int, default=48,
                    help="partitions per request (one contiguous span)")
    ap.add_argument("--grid-chunk", type=int, default=16)
    ap.add_argument("--clients", type=int, nargs="*", default=[1, 4, 16],
                    help="concurrent-client levels to measure")
    ap.add_argument("--rounds", type=int, default=2,
                    help="measurement rounds per level (latency sample size "
                         "= clients x rounds)")
    ap.add_argument("--deadline", type=float, default=120.0,
                    help="per-request SLA in seconds (misses are counted, "
                         "not fatal; also clamps a pathological request's "
                         "refinement tail — FIFO refinement means one hard "
                         "tail delays everything behind it)")
    ap.add_argument("--work-dir", default="serve_bench_work",
                    help="scratch directory for request sinks (wiped)")
    args = ap.parse_args()

    from fairify_tpu import obs
    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.obs import compile as compile_obs
    from fairify_tpu.serve import ServeConfig, VerificationServer
    from fairify_tpu.verify import presets, sweep

    cfg0 = presets.get(args.preset).with_(
        soft_timeout_s=10.0, hard_timeout_s=600.0, sim_size=64,
        exact_certify_masks=False, grid_chunk=args.grid_chunk,
        launch_backoff_s=1e-4)
    span = (0, args.span)
    in_dim = len(cfg0.query().columns)
    shutil.rmtree(args.work_dir, ignore_errors=True)

    registry = obs.registry()
    launches = registry.counter("device_launches")
    # serve_batch_occupancy counts requests that actually entered a
    # coalesced stage-0 wave (serve_batch_size would also count solo
    # batches — queue pressure, not coalescing, and it would read full
    # even with coalescing broken).
    batch_hist = registry.histogram("serve_batch_occupancy")

    def _net(seed):
        return init_mlp((in_dim, 8, 1), seed=seed)

    # Sequential baseline: 4 solo runs, counted warm (after one throwaway
    # cold run that pays the compiles the server's warmup also pays).
    sweep.verify_model(
        _net(0), cfg0.with_(result_dir=os.path.join(args.work_dir, "warm")),
        model_name="warm", resume=False, partition_span=span)
    seq0 = launches.total()
    for i in range(4):
        sweep.verify_model(
            _net(100 + i),
            cfg0.with_(result_dir=os.path.join(args.work_dir, f"solo-{i}")),
            model_name=f"solo-{i}", resume=False, partition_span=span)
    sequential_launches = int(launches.total() - seq0)

    srv = VerificationServer(ServeConfig(batch_window_s=0.2, max_batch=8))
    srv.start()
    # Server warmup: one solo request (solo kernels) plus one coalesced
    # wave (the fixed-width family executable — pad_models means any later
    # occupancy reuses it).  After this, the measured levels must hit the
    # warm executable cache only.
    w = srv.submit(cfg0.with_(result_dir=os.path.join(args.work_dir, "w0")),
                   _net(0), "w0", partition_span=span)
    srv.wait(w.id, timeout=900.0)
    wave = [srv.submit(
        cfg0.with_(result_dir=os.path.join(args.work_dir, f"wv{i}")),
        _net(900 + i), f"wv{i}", partition_span=span) for i in range(2)]
    for req in wave:
        srv.wait(req.id, timeout=900.0)
    compiles0 = compile_obs.snapshot_totals()["n_compiles"]

    levels = {}
    coalesced_launches = None
    seed = 1000
    for n_clients in args.clients:
        latencies = []
        misses = 0
        total = 0
        b_sum0, b_cnt0 = batch_hist.sum(), batch_hist.count()
        lvl_l0 = launches.total()
        lvl_c0 = compile_obs.snapshot_totals()["n_compiles"]
        t_lvl = time.perf_counter()
        for rnd in range(args.rounds):
            reqs = []
            for c in range(n_clients):
                seed += 1
                rdir = os.path.join(args.work_dir,
                                    f"c{n_clients}-r{rnd}-{c}")
                reqs.append(srv.submit(
                    cfg0.with_(result_dir=rdir), _net(seed),
                    f"m{seed}", deadline_s=args.deadline,
                    partition_span=span))
            for req in reqs:
                done = srv.wait(req.id, timeout=900.0)
                total += 1
                if done is None or done.finished_at is None:
                    misses += 1  # never finished: worse than a miss
                    continue
                latencies.append(done.finished_at - done.submitted_at)
                misses += int(done.deadline_missed
                              or done.status != "done")
        wall = time.perf_counter() - t_lvl
        b_cnt = batch_hist.count() - b_cnt0
        occupancy = ((batch_hist.sum() - b_sum0) / b_cnt) if b_cnt else 0.0
        if n_clients == 4:
            coalesced_launches = int((launches.total() - lvl_l0)
                                     / args.rounds)
        levels[str(n_clients)] = {
            "requests": total,
            **_percentiles(latencies),
            "deadline_miss_rate": round(misses / max(total, 1), 4),
            "batch_occupancy_mean": round(occupancy, 3),
            "requests_per_s": round(total / wall, 3),
            "xla_compiles": int(compile_obs.snapshot_totals()["n_compiles"]
                                - lvl_c0),
        }
        print(f"serve_bench: {n_clients:>2} client(s): "
              f"{levels[str(n_clients)]}", file=sys.stderr)
    # The warm gate is the acceptance cell: 4 concurrent requests on a
    # warmed server compile nothing (falls back to the total across levels
    # when 4 wasn't measured).
    if "4" in levels:
        warm_compiles = levels["4"]["xla_compiles"]
    else:
        warm_compiles = compile_obs.snapshot_totals()["n_compiles"] - compiles0
    srv.drain()

    record = {
        "kind": "SERVE",
        "preset": args.preset,
        "span": args.span,
        "grid_chunk": args.grid_chunk,
        "rounds": args.rounds,
        "deadline_s": args.deadline,
        "clients": levels,
        "warm_xla_compiles": int(warm_compiles),
        "coalesced_device_launches": coalesced_launches,
        "sequential_device_launches": sequential_launches,
    }
    with open(args.out, "w") as fp:
        json.dump(record, fp, indent=1)
    print(json.dumps(record))
    ok = warm_compiles == 0 and (
        coalesced_launches is None or coalesced_launches < sequential_launches)
    print(f"serve_bench: warm compiles {warm_compiles} "
          f"(healthy: 0), coalesced launches {coalesced_launches} vs "
          f"{sequential_launches} sequential -> "
          f"{'OK' if ok else 'NOT COALESCING'}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

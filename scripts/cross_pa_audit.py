"""Cross-PA verdict coincidence audit (VERDICT r2 ask #5).

Round 2 observed that per-partition verdicts for AC models agree
bit-for-bit between the PA=sex and PA=race runs (and GC between age/sex on
its 100%-decided models) — 192,000 coinciding verdicts deserve a measured
explanation, not silence.  This script quantifies the mechanism:

* **verdict diff** — per-partition agreement counts for every model with
  ledgers under two PA runs;
* **PA-sensitivity vs box spread** — per partition, the sampled logit
  spread over the shared box against the maximum logit shift induced by
  flipping each PA.  When both PAs' shifts are tiny relative to the box
  spread, the flip slab's position — hence the verdict — is set by the
  *shared* geometry, and the two PAs necessarily see the same SAT/UNSAT
  partition of the grid.

Writes ``audits/cross_pa_r3.json``; ``scripts/parity.py render`` folds the
summary into PARITY.md (so the explanation survives re-renders).

Usage: python scripts/cross_pa_audit.py [--samples 256] [--parts 1024]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


PAIRS = [
    # (family, run A, run B, preset, dataset, PA column names)
    ("AC", "AC-sex", "AC-race", "AC", "adult", ("sex", "race")),
    ("GC", "GC-age", "GC-sex", "GC", "german", ("age", "sex")),
]


def load_ledger(path):
    led = {}
    if not os.path.isfile(path):
        return led
    with open(path) as fp:
        for line in fp:
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                continue
            led[r["partition_id"]] = r["verdict"]
    return led


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--samples", type=int, default=256)
    ap.add_argument("--parts", type=int, default=1024,
                    help="partitions sampled per model for the sensitivity stats")
    ap.add_argument("--out", default="audits/cross_pa_r3.json")
    args = ap.parse_args()

    import jax.numpy as jnp
    import numpy as np

    from fairify_tpu.models import zoo
    from fairify_tpu.models.mlp import forward
    from fairify_tpu.verify import presets, sweep

    results = {"models": [], "summary": {}}
    for family, run_a, run_b, preset, dataset, pa_names in PAIRS:
        cfg = presets.get(preset)
        _, lo, hi = sweep.build_partitions(cfg)
        cols = list(cfg.query().columns)
        dom = cfg.query().domain
        # One comprehension keeps name → column → range aligned; a missing
        # PA name raises here instead of silently shifting the zip.
        pa_spec = [(n, cols.index(n), dom.ranges[n]) for n in pa_names]
        dir_a = os.path.join(ROOT, "parity", run_a)
        dir_b = os.path.join(ROOT, "parity", run_b)
        if not (os.path.isdir(dir_a) and os.path.isdir(dir_b)):
            continue
        models = sorted(
            f.split(".")[0].split(f"{preset}-", 1)[1]
            for f in os.listdir(dir_a) if f.endswith(".ledger.jsonl"))
        rng = np.random.default_rng(7)
        for model in models:
            led_a = load_ledger(os.path.join(dir_a, f"{preset}-{model}.ledger.jsonl"))
            led_b = load_ledger(os.path.join(dir_b, f"{preset}-{model}.ledger.jsonl"))
            common = sorted(set(led_a) & set(led_b))
            if not common:
                continue
            agree = sum(1 for p in common if led_a[p] == led_b[p])
            net = zoo.load(dataset, model)
            P = len(common)
            pick = rng.choice(P, size=min(args.parts, P), replace=False)
            idx = np.array([common[i] - 1 for i in sorted(pick)])
            blo, bhi = lo[idx], hi[idx]
            S = args.samples
            shared = rng.integers(blo[:, None, :], bhi[:, None, :] + 1,
                                  size=(len(idx), S, blo.shape[1])).astype(np.float32)
            spread = None
            deltas = {}
            base = np.asarray(forward(net, jnp.asarray(shared)))
            spread = base.max(axis=1) - base.min(axis=1)
            for name, col, (plo, phi) in pa_spec:
                vals = []
                for v in range(int(plo), int(phi) + 1):
                    pts = shared.copy()
                    pts[..., col] = float(v)
                    vals.append(np.asarray(forward(net, jnp.asarray(pts))))
                stack = np.stack(vals)  # (V, P, S)
                delta = (stack.max(axis=0) - stack.min(axis=0)).max(axis=1)
                deltas[name] = delta
            ratios = {name: np.median(d / np.maximum(spread, 1e-9))
                      for name, d in deltas.items()}
            results["models"].append({
                "family": family, "model": model,
                "runs": [run_a, run_b],
                "partitions_common": len(common),
                "verdicts_agree": agree,
                "median_box_logit_spread": round(float(np.median(spread)), 4),
                "median_pa_shift": {n: round(float(np.median(d)), 4)
                                    for n, d in deltas.items()},
                "median_shift_over_spread": {n: round(float(r), 5)
                                             for n, r in ratios.items()},
            })
            print(json.dumps(results["models"][-1]), flush=True)

    total = sum(m["partitions_common"] for m in results["models"])
    agree = sum(m["verdicts_agree"] for m in results["models"])
    ratios = [r for m in results["models"]
              for r in m["median_shift_over_spread"].values()]
    results["summary"] = {
        "partitions_compared": total,
        "verdicts_agree": agree,
        "agreement_pct": round(100.0 * agree / max(total, 1), 3),
        "max_median_shift_over_spread": round(max(ratios), 5) if ratios else None,
    }
    out_path = os.path.join(ROOT, args.out)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as fp:
        json.dump(results, fp, indent=1)
    print(json.dumps(results["summary"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Export per-partition SMT-LIB2 audit files for offline solver replay.

``z3-solver`` is not installable in this environment, so the native-vs-SMT
agreement audit is packaged to run ANYWHERE: for sampled partitions of a
preset (stratified by the native verdict recorded in a sweep ledger) this
writes one ``.smt2`` file each — the exact pair property with dyadic-
rational weights (``fairify_tpu.verify.smt.to_smtlib``) — plus a
``manifest.jsonl`` mapping file → expected answer.  Any sound QF_LIRA
solver (z3, cvc5, yices2) must report ``sat`` for native SAT rows and
``unsat`` for native UNSAT rows; a disagreement would disprove the native
engine.  Where z3 IS importable, ``tests/test_smt.py`` runs the same
audit live via ``decide_box_smt``.

Usage:
    python scripts/smt_export.py <preset> <model> <ledger.jsonl>
        [--per-class 4] [--out audits/smt]
Replay (any machine with a solver):
    for f in audits/smt/*.smt2; do z3 "$f"; done   # compare to manifest
"""
from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("preset")
    ap.add_argument("model")
    ap.add_argument("ledger")
    ap.add_argument("--per-class", type=int, default=4)
    ap.add_argument("--out", default="audits/smt")
    args = ap.parse_args()

    from fairify_tpu.models import zoo
    from fairify_tpu.verify import presets, smt, sweep
    from fairify_tpu.verify.property import encode

    cfg = presets.get(args.preset)
    net = zoo.load(cfg.dataset, args.model)
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)

    # Last-wins per partition (resumed/re-decided ledgers append; the final
    # row is the record of truth — same merge as sweep._load_ledger).
    latest: dict = {}
    with open(args.ledger) as fp:
        for line in fp:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            latest[rec["partition_id"]] = rec
    by_class: dict = {"sat": [], "unsat": [], "unknown": []}
    for pid in sorted(latest):
        rec = latest[pid]
        by_class.setdefault(rec["verdict"], []).append(rec)

    os.makedirs(args.out, exist_ok=True)
    manifest_path = os.path.join(args.out, "manifest.jsonl")
    # Rewrite the manifest for this (preset, model): stale rows must never
    # coexist with regenerated files.
    kept = []
    if os.path.isfile(manifest_path):
        with open(manifest_path) as fp:
            kept = [json.loads(line) for line in fp]
        kept = [r for r in kept
                if not (r["preset"] == args.preset and r["model"] == args.model)]
    rows = list(kept)
    n_out = 0
    for verdict in ("sat", "unsat", "unknown"):
        for rec in by_class[verdict][: args.per_class]:
            pid = rec["partition_id"]
            p = pid - 1  # partition_id is 1-based grid index
            fname = f"{args.preset}-{args.model}-p{pid}.smt2"
            text = smt.to_smtlib(net, enc, lo[p], hi[p],
                                 name=f"{args.preset}/{args.model} "
                                      f"partition {pid}",
                                 get_model=(verdict == "sat"))
            with open(os.path.join(args.out, fname), "w") as fp:
                fp.write(text)
            rows.append({
                "file": fname, "preset": args.preset, "model": args.model,
                "partition_id": pid, "native_verdict": verdict,
                "expected_smt": verdict if verdict != "unknown" else None,
                "native_ce": rec.get("ce"),
            })
            n_out += 1
    with open(manifest_path, "w") as mf:
        for r in rows:
            mf.write(json.dumps(r) + "\n")
    # Only after files and manifest are both written: drop stale .smt2 for
    # this (preset, model) so the glob replay stays 1:1 with the manifest —
    # deleting first would make a mid-export crash orphan the old manifest.
    import glob as _glob

    current = {r["file"] for r in rows}
    for old in _glob.glob(os.path.join(
            args.out, f"{args.preset}-{args.model}-p*.smt2")):
        if os.path.basename(old) not in current:
            os.remove(old)
    print(f"wrote {n_out} .smt2 files to {args.out} (+ manifest.jsonl)")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""A/B the async launch pipeline: pipeline_depth 1 vs 2 on the same tree.

Three arms, each with one untimed warm pass then ≥3 timed repeats (bench
discipline — compiles never land inside a timed region).  Depth-1 and
depth-2 repeats are INTERLEAVED so slow process drift (cache state, cgroup
throttling) hits both arms equally:

* **GC-1 headline** — the bench headline sweep (flagship German net,
  201 partitions) end-to-end at ``grid_chunk 64`` so the grid is 4 stage-0
  chunks the pipeline can overlap (the stock whole-grid chunk gives it one
  launch and nothing to hide).
* **AC family suite** — the adult model family (reference zoo when
  present, else the shipped ``models_scaled`` twins), stacked per
  architecture, swept over a 2048-partition slice at ``grid_chunk 512``
  through ONE shared pipeline (``sweep.stage0_families``).
* **Simulated relay** — the same stage-0 sweep through a
  :class:`RelayPipeline` that delays each launch's host visibility by the
  audited ~110 ms tunnel round-trip (``audits/device_util_r4.json``).
  This container's CPU backend has no tunnel, so the first two arms can
  only show *harmlessness* (overlap achieved, verdicts identical, walls
  within noise); this arm demonstrates the effect the pipeline exists
  for — at depth 1 every chunk pays the round-trip serially, at depth ≥2
  the round-trips hide behind in-flight compute.  Clearly labelled
  synthetic in the record.

Every arm checks verdict-map equality between depths — the pipeline must
change WHEN results are fetched, never WHAT is decided.

Usage: python scripts/pipeline_ab.py [--out audits/pipeline_ab_r6.json]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from fairify_tpu.parallel.pipeline import LaunchPipeline  # noqa: E402

REPEATS = 3
DEPTHS = (1, 2)
RELAY_S = 0.110  # audited flat launch round-trip, audits/device_util_r4.json


class RelayPipeline(LaunchPipeline):
    """LaunchPipeline whose results become host-visible only ``relay_s``
    after the kernel finishes — a synthetic stand-in for the tunnelled
    chip's relay latency.  A watcher thread stamps each launch's true
    finish time (``block_until_ready``), so with depth ≥2 one launch's
    relay window overlaps the next launch's compute, exactly like a real
    pipelined tunnel."""

    def __init__(self, depth: int, relay_s: float = RELAY_S):
        super().__init__(depth)
        self.relay_s = relay_s
        self._ready = {}

    def submit(self, fn, meta=None):
        def wrapped():
            import jax

            payload, ctx = fn()
            key = object()

            def watch():
                jax.block_until_ready(payload)
                self._ready[key] = time.perf_counter() + self.relay_s

            threading.Thread(target=watch, daemon=True).start()
            return payload, {"_key": key, "_ctx": ctx}

        return super().submit(wrapped, meta)

    def _drain_one(self):
        # The relay wait lives INSIDE the drain, i.e. before the pipeline
        # admits the next dispatch — at depth 1 every chunk therefore pays
        # the full round-trip serially (the pre-pipeline order), while at
        # depth ≥2 the already-in-flight launch computes through it.
        meta, wrapped_ctx, host = super()._drain_one()
        key = wrapped_ctx["_key"]
        while key not in self._ready:  # watcher stamp races device_get
            time.sleep(0.001)
        delay = self._ready.pop(key) - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        return meta, wrapped_ctx["_ctx"], host


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2]


def _summarize(runs, key):
    vals = [r[key] for r in runs]
    return {
        f"median_{key}": _median(vals), "min": min(vals), "max": max(vals),
        "in_flight_max": max(r["in_flight_max"] for r in runs),
        "in_flight_mean": _median([r["in_flight_mean"] for r in runs]),
        "runs": runs,
    }


def _gc_cfg():
    from fairify_tpu.verify import engine, presets

    return presets.get("GC").with_(
        soft_timeout_s=10.0, hard_timeout_s=10 * 60.0,
        exact_certify_masks=False, grid_chunk=64,
        engine=engine.EngineConfig(frontier_size=512, attack_samples=128,
                                   bab_attack_samples=16, soft_timeout_s=10.0),
    )


def gc_headline_arm(tmp_root: str) -> dict:
    from __graft_entry__ import _flagship_net
    from fairify_tpu import obs
    from fairify_tpu.verify import sweep

    net = _flagship_net()
    cfgs = {d: _gc_cfg().with_(pipeline_depth=d,
                               result_dir=os.path.join(tmp_root, f"gc-d{d}"))
            for d in DEPTHS}
    for cfg in cfgs.values():  # warm: identical sweep, untimed
        shutil.rmtree(cfg.result_dir, ignore_errors=True)
        sweep.verify_model(net, cfg, model_name="warm", resume=False)
    runs = {d: [] for d in DEPTHS}
    verdict_maps = {}
    for _ in range(REPEATS):
        for d in DEPTHS:  # interleaved
            cfg = cfgs[d]
            shutil.rmtree(cfg.result_dir, ignore_errors=True)
            obs.registry().reset()
            t0 = time.perf_counter()
            rep = sweep.verify_model(net, cfg, model_name="GC-1", resume=False)
            dt = time.perf_counter() - t0
            decided = rep.counts["sat"] + rep.counts["unsat"]
            with open(os.path.join(cfg.result_dir,
                                   "GC-GC-1.throughput.json")) as fp:
                thr = json.load(fp)
            runs[d].append({
                "parts_per_sec": round(decided / dt, 3),
                "elapsed_s": round(dt, 3),
                "device_launches": thr["device_launches"],
                "in_flight_max": thr["launches_in_flight_max"],
                "in_flight_mean": thr["launches_in_flight_mean"],
            })
            verdict_maps[d] = {
                o.partition_id: (o.verdict,
                                 None if o.counterexample is None else
                                 tuple(tuple(c.tolist())
                                       for c in o.counterexample))
                for o in rep.outcomes}
    arm = {"label": "GC-1 headline, end-to-end (201 partitions, "
                    "grid_chunk 64; interleaved repeats)",
           "counts": rep.counts,
           "depths": {d: _summarize(runs[d], "parts_per_sec")
                      for d in DEPTHS}}
    arm["verdict_maps_identical"] = all(
        verdict_maps[d] == verdict_maps[DEPTHS[0]] for d in DEPTHS)
    return arm


def _adult_stacks(cfg):
    from collections import defaultdict

    from fairify_tpu.models import zoo
    from fairify_tpu.parallel.mesh import stack_models

    n_attrs = len(cfg.query().columns)
    nets, _ = zoo.load_matching("adult", n_attrs)
    source = "reference zoo"
    if not nets:  # this container ships only the scaled twins
        nets, _ = zoo.load_matching("adult", n_attrs,
                                    root=os.path.join(ROOT, "models_scaled"))
        source = "models_scaled"
    groups = defaultdict(list)
    for n in sorted(nets):
        groups[(nets[n].in_dim,) + nets[n].layer_sizes].append(n)
    return ([stack_models([nets[n] for n in g]) for g in groups.values()],
            len(nets), source)


def ac_family_arm() -> dict:
    from fairify_tpu import obs
    from fairify_tpu.verify import presets, sweep
    from fairify_tpu.verify.property import encode

    cfg = presets.get("AC").with_(grid_chunk=512)
    stacks, n_models, source = _adult_stacks(cfg)
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)
    lo, hi = lo[:2048], hi[:2048]
    for st in stacks:  # warm/compile per architecture, untimed
        sweep._stage0_family(st, enc, lo[:512], hi[:512], cfg)
    runs = {d: [] for d in DEPTHS}
    sig = {}
    decided = 0
    for _ in range(REPEATS):
        for d in DEPTHS:  # interleaved
            obs.registry().reset()
            pipe = LaunchPipeline(d)
            t0 = time.perf_counter()
            fams = sweep.stage0_families(stacks, enc, lo, hi,
                                         cfg.with_(pipeline_depth=d),
                                         pipe=pipe)
            dt = time.perf_counter() - t0
            decided = int(sum((u | s).sum()
                              for fam in fams for u, s, _ in fam))
            runs[d].append({
                "model_parts_per_sec": round(decided / dt, 1),
                "elapsed_s": round(dt, 3),
                "in_flight_max": pipe.stats.max,
                "in_flight_mean": round(pipe.stats.mean(), 3),
            })
            sig[d] = [(u.tobytes(), s.tobytes(), tuple(sorted(w)))
                      for fam in fams for u, s, w in fam]
    arm = {"label": f"AC family suite ({n_models} adult models from "
                    f"{source}, 2048-partition slice, grid_chunk 512, "
                    f"shared pipeline; interleaved repeats)",
           "decided_model_partitions": decided,
           "depths": {d: _summarize(runs[d], "model_parts_per_sec")
                      for d in DEPTHS}}
    arm["verdict_maps_identical"] = all(
        sig[d] == sig[DEPTHS[0]] for d in DEPTHS)
    return arm


def relay_sim_arm() -> dict:
    from __graft_entry__ import _flagship_net
    from fairify_tpu import obs
    from fairify_tpu.verify import sweep
    from fairify_tpu.verify.property import encode

    cfg = _gc_cfg().with_(grid_chunk=16)  # 13 chunks: room to hide 12 RTs
    net = _flagship_net()
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)
    sweep._stage0_certify_and_attack(net, enc, lo, hi, cfg)  # warm, no relay
    runs = {d: [] for d in DEPTHS}
    maps = {}
    for _ in range(REPEATS):
        for d in DEPTHS:  # interleaved
            obs.registry().reset()
            pipe = RelayPipeline(d, RELAY_S)
            t0 = time.perf_counter()
            unsat, sat, wit = sweep._stage0_certify_and_attack(
                net, enc, lo, hi, cfg.with_(pipeline_depth=d), pipe=pipe)
            dt = time.perf_counter() - t0
            runs[d].append({
                "chunks_per_sec": round(13 / dt, 3),
                "elapsed_s": round(dt, 3),
                "in_flight_max": pipe.stats.max,
                "in_flight_mean": round(pipe.stats.mean(), 3),
            })
            maps[d] = (unsat.tobytes(), sat.tobytes(),
                       {k: tuple(tuple(c.tolist()) for c in v)
                        for k, v in wit.items()})
    arm = {"label": f"SYNTHETIC relay: GC-1 stage-0, 13 chunks of 16, "
                    f"each launch + {RELAY_S * 1000:.0f} ms simulated tunnel "
                    f"round-trip (audits/device_util_r4.json); interleaved "
                    f"repeats",
           "relay_s": RELAY_S,
           "depths": {d: _summarize(runs[d], "chunks_per_sec")
                      for d in DEPTHS}}
    arm["verdict_maps_identical"] = all(
        maps[d] == maps[DEPTHS[0]] for d in DEPTHS)
    return arm


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(ROOT, "audits",
                                                  "pipeline_ab_r6.json"))
    ap.add_argument("--tmp", default="/tmp/fairify_tpu_pipeline_ab")
    args = ap.parse_args()
    import jax

    rec = {
        "platform": jax.devices()[0].platform,
        "repeats": REPEATS,
        "arms": {
            "gc_headline": gc_headline_arm(args.tmp),
            "ac_family": ac_family_arm(),
            "relay_sim": relay_sim_arm(),
        },
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fp:
        json.dump(rec, fp, indent=2)
    print(json.dumps(
        {k: {"identical": v["verdict_maps_identical"],
             **{str(d): {kk: vv for kk, vv in v["depths"][d].items()
                         if kk != "runs"} for d in v["depths"]}}
         for k, v in rec["arms"].items()}, indent=1))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

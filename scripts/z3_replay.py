#!/usr/bin/env python
"""External-solver replay of the exported SMT-LIB2 certificates.

VERDICT r4 "What's missing #1": the 21 ``audits/smt/*.smt2`` exports (the
reference's ground-truth encoding, ``/root/reference/src/GC/Verify-GC.py:
145-214``) had only the in-house exact checker behind them because
``z3-solver`` is not pip-installable here.  The runtime image does however
ship Microsoft's **libz3.so.4** (system library, Z3 4.8.12) — a genuinely
external solver implementation.  This harness drives it through the Z3 C API
via ctypes (no pip), replays every manifest entry, and records the solver's
verdict next to the native engine's.

Per file: the SMT-LIB2 source is evaluated with ``Z3_eval_smtlib2_string``
in a CHILD process (z3 can be killed on wall timeout without taking the
harness down), with ``(get-model)`` / model production stripped — agreement
is about the sat/unsat verdict; model printing on the AC-size nets costs
minutes of pure pretty-printing.  An in-solver ``timeout`` (ms) is set as
well so z3 returns ``unknown`` instead of hanging.

Usage: python scripts/z3_replay.py [--budget-s 900] [--out audits/z3_replay_r5]
"""
from __future__ import annotations

import argparse
import ctypes
import json
import os
import subprocess
import sys
import time

LIBZ3 = "/usr/lib/x86_64-linux-gnu/libz3.so.4"


def _solve_child(path: str, budget_ms: int, pin: str | None = None) -> None:
    """Child-process entry: print one JSON line with z3's verdict.

    With ``pin`` (a JSON ``[x_values, xp_values]``), equality assertions
    fixing every ``x_i``/``xp_i`` to the native counterexample are inserted
    before ``(check-sat)`` — z3 then *checks* the witness against the same
    SMT encoding instead of searching for one.  This is the recorded
    fallback for certificates whose open solve exceeds the budget (the
    exact-dyadic GC encodings defeat z3's rational simplex): a weaker but
    still external validation, kept distinct in the log."""
    lib = ctypes.CDLL(LIBZ3)
    lib.Z3_mk_config.restype = ctypes.c_void_p
    lib.Z3_set_param_value.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_char_p]
    lib.Z3_mk_context.restype = ctypes.c_void_p
    lib.Z3_mk_context.argtypes = [ctypes.c_void_p]
    lib.Z3_eval_smtlib2_string.restype = ctypes.c_char_p
    lib.Z3_eval_smtlib2_string.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.Z3_get_full_version.restype = ctypes.c_char_p

    cfg = lib.Z3_mk_config()
    lib.Z3_set_param_value(cfg, b"timeout", str(budget_ms).encode())
    ctx = lib.Z3_mk_context(cfg)
    src_lines = []
    for line in open(path):
        ls = line.strip()
        if ls == "(get-model)" or ls == "(set-option :produce-models true)":
            continue  # verdict-only replay (see module docstring)
        if ls == "(check-sat)" and pin:
            xs, xps = json.loads(pin)
            for i, v in enumerate(xs):
                src_lines.append(f"(assert (= x{i} {int(v)}))\n")
            for i, v in enumerate(xps):
                src_lines.append(f"(assert (= xp{i} {int(v)}))\n")
        src_lines.append(line)
    t0 = time.time()
    out = lib.Z3_eval_smtlib2_string(ctx, "".join(src_lines).encode())
    verdict = (out or b"").decode().strip().splitlines()
    verdict = verdict[-1] if verdict else "error"
    print(json.dumps({
        "z3_verdict": verdict,
        "z3_wall_s": round(time.time() - t0, 2),
        "z3_version": lib.Z3_get_full_version().decode(),
    }))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget-s", type=float, default=900.0,
                    help="per-certificate wall budget (reference model "
                         "budget is 1 h; most certificates close far faster)")
    ap.add_argument("--smt-dir", default="audits/smt")
    ap.add_argument("--out", default="audits/z3_replay_r5")
    ap.add_argument("--child", help=argparse.SUPPRESS)
    ap.add_argument("--pin", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.child:
        _solve_child(args.child, int(args.budget_s * 1000), pin=args.pin)
        return 0

    os.chdir(os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
    manifest = [json.loads(l) for l in open(
        os.path.join(args.smt_dir, "manifest.jsonl"))]
    # Small files first: every GC/BM verdict lands before the AC heavies.
    manifest.sort(key=lambda m: os.path.getsize(
        os.path.join(args.smt_dir, m["file"])))
    log_path = args.out + ".jsonl"
    manifest_files = {m["file"] for m in manifest}
    done = {}
    foreign = []  # records from other manifests/--smt-dirs: preserved verbatim
    if os.path.isfile(log_path):
        for line in open(log_path):
            rec = json.loads(line)
            if rec["file"] in manifest_files:
                done[rec["file"]] = rec
            else:
                foreign.append(line)
    for m in manifest:
        if m["file"] in done:
            continue
        path = os.path.join(args.smt_dir, m["file"])
        try:
            cp = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child", path,
                 "--budget-s", str(args.budget_s)],
                capture_output=True, text=True, timeout=args.budget_s + 60)
            rec = json.loads(cp.stdout.strip().splitlines()[-1])
        except subprocess.TimeoutExpired:
            rec = {"z3_verdict": "wall-timeout", "z3_wall_s": args.budget_s}
        except Exception as exc:  # child crash: record, keep replaying
            rec = {"z3_verdict": "error", "error": str(exc)[:200]}
        rec = {"file": m["file"], "expected": m["expected_smt"],
               "native_verdict": m["native_verdict"], **rec}
        rec["agree"] = rec["z3_verdict"] == m["expected_smt"]
        done[m["file"]] = rec
        with open(log_path, "a") as fp:
            fp.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)

    # Pinned-witness fallback: SAT certificates the open solve could not
    # close within budget get their native counterexample asserted and a
    # fast z3 check of the pinned query — recorded as ``z3_pinned``, never
    # as an open-solve verdict.
    for m in manifest:
        rec = done[m["file"]]
        if m["expected_smt"] != "sat" or rec["z3_verdict"] == "sat" \
                or rec.get("z3_pinned") or not m.get("native_ce"):
            continue
        path = os.path.join(args.smt_dir, m["file"])
        try:
            cp = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child", path,
                 "--budget-s", "300", "--pin", json.dumps(m["native_ce"])],
                capture_output=True, text=True, timeout=360)
            pinned = json.loads(cp.stdout.strip().splitlines()[-1])
            rec["z3_pinned"] = pinned["z3_verdict"]
            rec["z3_pinned_wall_s"] = pinned["z3_wall_s"]
        except Exception as exc:
            rec["z3_pinned"] = f"error: {str(exc)[:120]}"
        done[m["file"]] = rec
        print(json.dumps(rec), flush=True)
    # Atomic rewrite with pinned fields merged: the jsonl is the resume
    # ledger for solves costing up to 1200 s each — a crash mid-rewrite
    # must not truncate it.  Records for files outside the current
    # manifest (e.g. a different --smt-dir) are preserved verbatim.
    tmp = log_path + ".tmp"
    with open(tmp, "w") as fp:
        for l in foreign:
            fp.write(l)
        for m in manifest:
            if m["file"] in done:
                fp.write(json.dumps(done[m["file"]]) + "\n")
    os.replace(tmp, log_path)

    agree = sum(1 for r in done.values() if r.get("agree"))
    decided = sum(1 for r in done.values()
                  if r.get("z3_verdict") in ("sat", "unsat"))
    summary = {
        "solver": "libz3.so.4 (system) via ctypes C API",
        "certificates": len(manifest),
        "replayed": len(done),
        "z3_decided": decided,
        "agree_with_native": agree,
        "pinned_witness_validated": sum(
            1 for r in done.values() if r.get("z3_pinned") == "sat"),
        # A pinned-witness REFUTATION (z3: unsat for the asserted native
        # counterexample) is the most alarming outcome this audit can
        # produce — surfaced here and in ``disagree`` below, never buried.
        "pinned_witness_refuted": [
            r["file"] for r in done.values() if r.get("z3_pinned") == "unsat"],
        "disagree": [r for r in done.values()
                     if (r.get("z3_verdict") in ("sat", "unsat")
                         and not r["agree"])
                     or r.get("z3_pinned") == "unsat"],
        "undecided": [r["file"] for r in done.values()
                      if r.get("z3_verdict") not in ("sat", "unsat")],
    }
    with open(args.out + ".json", "w") as fp:
        json.dump(summary, fp, indent=2)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""task5 analog: cross-tool counterexample comparison (VERDICT r3 #7).

The reference's ``experimentData/task5`` compares counterexample sets
across verification tools: per model it ships decoded CE CSVs from Fairify
(``counterexamples-fairify-<M>.csv``) and FairQuant
(``counterexamples-fairquant-<M>.csv``) plus comparison notebooks.  This
harness rebuilds that artifact family around our framework:

1. **Replay the reference tools' committed CEs on the shared models.**
   Each decoded row pair is re-encoded through our loaders' fitted
   encoders (the exact mappings of ``utils/standard_data.py:4-65`` /
   ``utils/verif_utils.py``) and the pair is checked by the engine's exact
   rational replay (``engine.validate_pair``) — the strongest possible
   cross-tool statement: *their* witnesses judged by *our* ground-truth
   checker.  Rows whose categories/values fall outside the dataset's
   fitted domain are counted ``unencodable`` (FairQuant's GC rows use
   e.g. ``month=78`` and purpose codes absent from german.data — it
   verifies a wider domain).
2. **Emit our own CE sets in the same decoded shape**
   (``counterexamples-fairify_tpu-<M>.csv``: decoded feature columns +
   ``output`` probability + ``prediction``; two rows per pair) from a
   fresh budgeted sweep of each model.

Writes ``audits/task5_compare_r4.json`` and per-model CSVs under --out.

Usage: python scripts/task5_compare.py [--out res/task5] [--soft 5]
           [--hard 600] [--families GC,AC,BM]
"""
from __future__ import annotations

import argparse
import csv
import json
import os
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
REF = "/root/reference/experimentData/task5"

# (family, model, preset, PA overrides) — the models task5 ships CSVs for.
TARGETS = {
    "GC": [("GC-1", "GC", {}), ("GC-2", "GC", {}), ("GC-3", "GC", {})],
    "AC": [("AC-1", "AC", {}), ("AC-2", "AC", {}), ("AC-3", "AC", {})],
    "BM": [("BM-1", "BM", {}), ("BM-2", "BM", {}), ("BM-3", "BM", {})],
}

# German re-encode maps (duplicating data/loaders._german_preprocess, which
# mirrors utils/standard_data.py:4-65 — the task5 GC CSVs carry raw codes).
_GC_GROUPS = {
    "credit_history": {"A30": "None/Paid", "A31": "None/Paid",
                       "A32": "None/Paid", "A33": "Delay", "A34": "Other"},
    "savings": {"A61": "<500", "A62": "<500", "A63": "500+", "A64": "500+",
                "A65": "Unknown/None"},
    "employment": {"A71": "Unemployed", "A72": "1-4 years",
                   "A73": "1-4 years", "A74": "4+ years", "A75": "4+ years"},
    "status": {"A11": "<200", "A12": "<200", "A13": "200+", "A14": "None"},
}
_GC_SEX = {"A91": 1, "A93": 1, "A94": 1, "A92": 0, "A95": 0}


def _encode_row(ds, family: str, row: dict):
    """Decoded CSV row → encoded int vector in our feature order, or None
    (with a reason) when a value falls outside the fitted domain."""
    out = np.zeros(len(ds.feature_columns), dtype=np.int64)
    for i, col in enumerate(ds.feature_columns):
        if col not in row:
            return None, f"missing column {col}"
        raw = str(row[col]).strip()
        if family == "GC" and col in _GC_GROUPS:
            if raw not in _GC_GROUPS[col]:
                return None, f"{col}={raw} outside german.data codes"
            raw = _GC_GROUPS[col][raw]
        if family == "GC" and col == "sex" and raw in _GC_SEX:
            out[i] = _GC_SEX[raw]
            continue
        enc = ds.encoders.get(col)
        if enc is not None and hasattr(enc, "classes_"):
            classes = list(enc.classes_)
            if raw in classes:
                out[i] = classes.index(raw)
                continue
            # numeric-coded categorical (e.g. "1") stored as number
            try:
                val = float(raw)
            except ValueError:
                return None, f"{col}={raw} not in fitted classes"
            if val in [float(c) if not isinstance(c, str) else None
                       for c in classes]:
                out[i] = [float(c) if not isinstance(c, str) else None
                          for c in classes].index(val)
                continue
            return None, f"{col}={raw} not in fitted classes"
        try:
            out[i] = int(round(float(raw)))
        except ValueError:
            return None, f"{col}={raw} not numeric"
    return out, None


def _pairs_from_csv(path: str, pair_key: str | None):
    """Consecutive-row pairs (fairify shape) or CE_ID-grouped pairs
    (fairquant shape)."""
    with open(path, newline="") as fp:
        rows = list(csv.DictReader(fp))
    pairs = []
    if pair_key and rows and pair_key in rows[0]:
        by_id: dict = {}
        for r in rows:
            by_id.setdefault(r[pair_key], []).append(r)
        for rid, grp in by_id.items():
            if len(grp) == 2:
                pairs.append((grp[0], grp[1]))
    else:
        for k in range(0, len(rows) - 1, 2):
            pairs.append((rows[k], rows[k + 1]))
    return pairs


def replay_tool_csv(ds, family, weights, biases, path, pair_key=None):
    from fairify_tpu.models.mlp import forward_np
    from fairify_tpu.verify import engine

    pairs = _pairs_from_csv(path, pair_key)
    confirmed = refuted = unencodable = 0
    out_match = out_total = 0
    reasons: dict = {}
    for ra, rb in pairs:
        xa, why_a = _encode_row(ds, family, ra)
        xb, why_b = _encode_row(ds, family, rb)
        if xa is None or xb is None:
            unencodable += 1
            why = why_a or why_b
            reasons[why] = reasons.get(why, 0) + 1
            continue
        # Lineage self-diagnosis: when the CSV records the tool's own
        # output probability, compare it with OUR forward at the
        # re-encoded point.  A low match rate means the tool's encoding
        # of these columns differs from ours — then refuted counts
        # measure the encoding mismatch, not the tool's soundness.
        for row, x in ((ra, xa), (rb, xb)):
            if "output" in row and row["output"]:
                try:
                    rec_out = float(row["output"])
                except ValueError:
                    continue
                lg = float(forward_np(weights, biases,
                                      np.asarray(x, dtype=np.float64)))
                ours = 1.0 / (1.0 + np.exp(-lg))
                out_total += 1
                if abs(ours - rec_out) < 1e-3:
                    out_match += 1
        if engine.validate_pair(weights, biases, xa, xb):
            confirmed += 1
        else:
            refuted += 1
    top = sorted(reasons.items(), key=lambda kv: -kv[1])[:3]
    rec = {"pairs": len(pairs), "confirmed": confirmed, "refuted": refuted,
           "unencodable": unencodable,
           "top_unencodable_reasons": [f"{k} (x{v})" for k, v in top]}
    if out_total:
        rec["output_match_rate"] = round(out_match / out_total, 4)
        rec["encoding_lineage"] = ("matched" if out_match / out_total > 0.9
                                   else "MISMATCHED — refuted counts are an "
                                        "encoding-lineage artifact, not a "
                                        "soundness judgement")
    return rec


def our_ce_csv(ds, net, cfg, model, out_dir) -> dict:
    """Budgeted sweep → decoded CE CSV in the task5 fairify shape."""
    from fairify_tpu.analysis.decode import decode_point
    from fairify_tpu.models.mlp import forward_np
    from fairify_tpu.verify import sweep

    rep = sweep.verify_model(net, cfg, model_name=model, dataset=ds,
                             resume=True)
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    path = os.path.join(out_dir, f"counterexamples-fairify_tpu-{model}.csv")
    n_pairs = 0
    with open(path, "w", newline="") as fp:
        wr = csv.writer(fp)
        cols = list(ds.feature_columns) + ["output", "prediction"]
        wr.writerow(cols)
        for o in rep.outcomes:
            if o.verdict != "sat" or o.counterexample is None:
                continue
            for pt in o.counterexample:
                dec = decode_point(ds, np.asarray(pt))
                logit = float(forward_np(weights, biases,
                                         np.asarray(pt, dtype=np.float64)))
                prob = 1.0 / (1.0 + np.exp(-logit))
                wr.writerow([dec[c] for c in ds.feature_columns]
                            + [prob, int(prob > 0.5)])
            n_pairs += 1
    counts = rep.counts
    return {"csv": path, "ce_pairs": n_pairs, **counts}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="res/task5")
    ap.add_argument("--soft", type=float, default=5.0)
    ap.add_argument("--hard", type=float, default=600.0)
    ap.add_argument("--families", default="GC,AC,BM")
    ap.add_argument("--audit-out",
                    default=os.path.join(ROOT, "audits",
                                         "task5_compare_r4.json"))
    args = ap.parse_args()

    from fairify_tpu.data import loaders
    from fairify_tpu.models import zoo
    from fairify_tpu.verify import presets

    os.makedirs(args.out, exist_ok=True)
    records = []
    for family in args.families.split(","):
        for model, preset, overrides in TARGETS[family]:
            cfg = presets.get(preset).with_(
                soft_timeout_s=args.soft, hard_timeout_s=args.hard,
                result_dir=os.path.join(args.out, family), **overrides)
            ds = loaders.load(cfg.dataset)
            net = zoo.load(cfg.dataset, model)
            weights = [np.asarray(w) for w in net.weights]
            biases = [np.asarray(b) for b in net.biases]
            rec = {"model": model, "family": family}
            for tool, pair_key in (("fairify", None), ("fairquant", "CE_ID")):
                path = os.path.join(REF, family,
                                    f"counterexamples-{tool}-{model}.csv")
                if os.path.isfile(path):
                    rec[tool] = replay_tool_csv(ds, family, weights, biases,
                                                path, pair_key)
            rec["ours"] = our_ce_csv(ds, net, cfg, model,
                                     os.path.join(args.out, family))
            print(json.dumps(rec), flush=True)
            records.append(rec)
    out = {
        "what": ("Cross-tool counterexample comparison in the reference's "
                 "task5 shape: the committed Fairify/FairQuant CE CSVs "
                 "re-encoded through our loaders and re-judged by exact "
                 "rational replay, plus our own decoded CE sets per model."),
        "caveat": ("'refuted' means the pair does not strictly flip the "
                   "shared .h5 under OUR loader's encoding — for Fairify "
                   "rows that is a like-for-like judgement (same "
                   "preprocessing lineage, and its rows replay ~100%); "
                   "FairQuant rows carry values outside the dataset's "
                   "fitted domain (e.g. german month=78, purpose=A47), so "
                   "its refuted counts primarily measure an encoding/"
                   "domain mismatch between tools, NOT FairQuant "
                   "unsoundness."),
        "script": "scripts/task5_compare.py",
        "reference": REF,
        "records": records,
    }
    os.makedirs(os.path.dirname(args.audit_out), exist_ok=True)
    with open(args.audit_out, "w") as fp:
        json.dump(out, fp, indent=1)
    print(f"wrote {args.audit_out}")
    return 0


if __name__ == "__main__":
    main()

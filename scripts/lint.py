#!/usr/bin/env python
"""CI entry for the static-analysis rule engine (``fairify_tpu.lint``).

Equivalent to ``python -m fairify_tpu lint`` but importable without the
package installed (inserts the repo root on sys.path).  Typical CI lines:

    python scripts/lint.py                      # text findings, exit 1 on any
    python scripts/lint.py --format json        # machine-readable result
    python scripts/lint.py --ratchet            # also gate per-rule growth
                                                # vs audits/lint_baseline.json
    python scripts/lint.py --ir                 # jaxpr/IR passes over the
                                                # obs_jit kernel registry
                                                # (imports jax; ~15 s CPU)

See DESIGN.md §11 for the rule catalog (AST and IR) and the allowlist /
suppression / baseline workflow.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fairify_tpu.lint import core  # noqa: E402


if __name__ == "__main__":
    sys.exit(core.main())

"""Zoo-wide verdict parity against the reference's published Table V.

Sweeps every (preset × protected-attribute × model) combination the
reference's Appendix Table V reports (BASELINE.md), with identical query
semantics (domains, partition thresholds, PA) but TPU-scale budgets, and
renders ``PARITY.md``.

The reference attempted only as many partitions as fit its 30-minute CPU
budget; this harness attempts the FULL grid for every model.  Parity
criteria per row:

* ref ``SAT``  → we must find at least one validated counterexample pair
  (SAT witnesses are ground truth: every pair is replayed exactly);
* ref ``UNK``  → any outcome is consistent; deciding partitions the
  reference could not is an improvement, reported as such;
* rows with 100% coverage and 0 UNK in the reference (GC-3/GC-4, BM-6)
  must match SAT/UNSAT counts exactly (same grid, deterministic order).

Usage:
    python scripts/parity.py run [--out parity] [--soft 5] [--hard 600]
                                 [--runs GC-age,BM-age,...]
    python scripts/parity.py render [--out parity]

Results accumulate in ``<out>/results.jsonl`` (one line per model, resumable
— completed models are skipped on re-run).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# (run_id, preset, config overrides, Table V "PA" label or None)
RUNS = [
    ("GC-age", "GC", {}, "Age"),
    ("GC-sex", "GC", {"protected": ("sex",)}, "Sex"),
    ("BM-age", "BM", {}, "Age"),
    ("AC-sex", "AC", {}, "Sex"),
    ("AC-race", "AC", {"protected": ("race",)}, "Race"),
    ("CP-race", "CP", {}, None),
    ("CP12-race", "CP12", {}, None),
    ("DF-sex2", "DF", {}, None),
]


def parse_baseline(path=os.path.join(ROOT, "BASELINE.md")):
    """{(pa_label, 'GC-1'): row dict} from the Table V markdown."""
    rows = {}
    pat = re.compile(r"^\| (Age|Sex|Race) \| ([A-Z]{2})(\d+) \|")
    with open(path) as fp:
        for line in fp:
            m = pat.match(line)
            if not m:
                continue
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            pa, fam, num = m.group(1), m.group(2), m.group(3)
            rows[(pa, f"{fam}-{num}")] = {
                "ver": cells[2], "attempted": int(cells[3]),
                "cov_pct": float(cells[4]), "sat": int(cells[5]),
                "unsat": int(cells[6]), "unk": int(cells[7]),
                "hs": int(cells[9]),  # heuristic-prune successes (unsound path)
                "total_s_per_part": float(cells[14]),
            }
    return rows


def cmd_run(args):
    from _sweeplib import run_and_record
    from fairify_tpu.verify import presets

    os.makedirs(args.out, exist_ok=True)
    results_path = os.path.join(args.out, "results.jsonl")
    wanted = set(args.runs.split(",")) if args.runs else None
    for run_id, preset, overrides, pa in RUNS:
        if wanted and run_id not in wanted:
            continue
        cfg = presets.get(preset).with_(
            soft_timeout_s=args.soft, hard_timeout_s=args.hard,
            result_dir=os.path.join(args.out, run_id), **overrides)
        run_and_record(cfg, run_id, results_path, extra={"pa": pa})


def cmd_refresh(args):
    """Recompute verdict counts in results.jsonl from the ledgers.

    After a ``--retry-unknown`` pass rewrites a model's ledger, the cached
    counts in results.jsonl are stale; this re-reads every ledger (last
    record per partition wins) and rewrites the results file in place.
    Timing fields are kept from the original run and marked refreshed.
    """
    import glob

    sys.path.insert(0, ROOT)
    from fairify_tpu.verify.sweep import _load_ledger

    results_path = os.path.join(args.out, "results.jsonl")
    recs = []
    with open(results_path) as fp:
        for line in fp:
            recs.append(json.loads(line))
    by_key = {(r["run_id"], r["model"]): r for r in recs}
    preset_of = {rid: preset for rid, preset, _, _ in RUNS}
    changed = 0
    for (run_id, model), rec in by_key.items():
        if "skipped" in rec:
            continue
        ledger = os.path.join(args.out, run_id,
                              f"{preset_of.get(run_id, run_id)}-{model}.ledger.jsonl")
        if not os.path.isfile(ledger):
            continue
        led = _load_ledger(ledger)
        counts = {"sat": 0, "unsat": 0, "unknown": 0}
        for r in led.values():
            counts[r["verdict"]] += 1
        if (counts["sat"], counts["unsat"], counts["unknown"]) != (
                rec["sat"], rec["unsat"], rec["unknown"]):
            rec.update(counts)
            rec["refreshed"] = True
            changed += 1
    with open(results_path, "w") as fp:
        for r in recs:
            fp.write(json.dumps(r) + "\n")
    print(f"refreshed {changed} of {len(recs)} rows from ledgers")


def cmd_render(args):
    baseline = parse_baseline()
    recs = []
    path = os.path.join(args.out, "results.jsonl")
    if os.path.isfile(path):
        with open(path) as fp:
            for line in fp:
                recs.append(json.loads(line))
    if not recs:
        sys.exit(f"no results in {path} yet — run `python scripts/parity.py run` first")
    order = {rid: i for i, (rid, _, _, _) in enumerate(RUNS)}

    from _sweeplib import model_natkey

    recs = [r for r in recs if "skipped" not in r]
    recs.sort(key=lambda r: (order.get(r["run_id"], 99), model_natkey(r["model"])))
    lines = [
        "# PARITY — full-zoo verdicts vs the reference's Appendix Table V",
        "",
        "Generated by `scripts/parity.py` from `<out>/results.jsonl` "
        "(re-run `python scripts/parity.py render` after new sweeps).",
        "",
        "Reference rows ran a 30-min CPU budget and attempted only a grid "
        "subset; this framework sweeps the **full grid** per model on one "
        "TPU chip (per-row budgets recorded in results.jsonl; typical "
        f"soft {recs[0]['soft_s']}s / hard {recs[0]['hard_s']}s).  "
        "`agree` column: `exact` = SAT/UNSAT counts match the "
        "reference exactly (possible only on its 100%-coverage rows), "
        "`yes` = verdicts consistent (every reference SAT reproduced), "
        "`near*` = counts differ within the reference's unsound "
        "heuristic-prune successes (#HS) — adjudicated by "
        "`scripts/crosscheck.py` (independent attack on our UNSAT "
        "certificates), "
        "`improved` = we decide partitions the reference left UNKNOWN, "
        "`—` = no published row.",
        "",
        "| Run | Model | Ref Ver (#P, SAT/US/UNK) | Ours (#P, SAT/US/UNK) | "
        "Ours s/part | Ref s/part | Speedup | Agree |",
        "|---|---|---|---|---|---|---|---|",
    ]
    agree_fail = []
    for r in recs:
        ref = baseline.get((r["pa"], r["model"])) if r["pa"] else None
        ours_cell = (f"{r['partitions']}, {r['sat']}/{r['unsat']}/{r['unknown']}")
        decided = r["sat"] + r["unsat"]
        ours_spp = r["total_time_s"] / max(decided, 1)
        if ref is None:
            ref_cell, ref_spp_cell, speed_cell, agree = "—", "—", "—", "—"
        else:
            ref_cell = (f"{ref['ver']} ({ref['attempted']}, "
                        f"{ref['sat']}/{ref['unsat']}/{ref['unk']})")
            ref_spp_cell = f"{ref['total_s_per_part']:.2f}"
            speed_cell = f"{ref['total_s_per_part'] / max(ours_spp, 1e-9):,.0f}×"
            if ref["cov_pct"] >= 99.9 and ref["unk"] == 0:
                ok = (r["sat"] == ref["sat"] and r["unsat"] == ref["unsat"]
                      and r["unknown"] == 0)
                # Reference rows that used heuristic pruning are not ground
                # truth (the heuristic path is unsound, utils/prune.py:862-939);
                # counts within that slack + our unknowns are consistent —
                # scripts/crosscheck.py adjudicates by attacking our UNSATs.
                # Direction matters: our SATs are exact-replay-validated, so
                # a SAT *surplus* can only be explained by ref heuristic rows
                # (#HS); a SAT *deficit* additionally by our own unknowns.
                near = ((r["sat"] - ref["sat"] <= ref["hs"])
                        and (ref["sat"] - r["sat"] <= ref["hs"] + r["unknown"]))
                agree = "exact" if ok else ("near*" if near else "MISMATCH")
            elif ref["ver"] == "SAT":
                agree = "yes" if r["sat"] > 0 else "MISMATCH"
                if agree == "yes" and r["unknown"] == 0:
                    agree = "improved"
            else:  # ref UNK
                agree = "improved" if decided > 0 else "yes"
            if agree == "MISMATCH":
                agree_fail.append((r["run_id"], r["model"]))
        lines.append(
            f"| {r['run_id']} | {r['model']} | {ref_cell} | {ours_cell} | "
            f"{ours_spp:.3f} | {ref_spp_cell} | {speed_cell} | {agree} |")
    lines += ["", f"Mismatches: {agree_fail if agree_fail else 'none'}", ""]

    # Cross-PA verdict coincidence: measured explanation (ask r2 #5).
    xpa_path = os.path.join(ROOT, "audits", "cross_pa_r3.json")
    if os.path.isfile(xpa_path):
        with open(xpa_path) as fp:
            xpa = json.load(fp)
        s = xpa["summary"]
        ratios = [r for m in xpa["models"]
                  for r in m["median_shift_over_spread"].values()]
        worst = max(xpa["models"],
                    key=lambda m: max(m["median_shift_over_spread"].values()))
        fams: dict = {}
        for m in xpa["models"]:
            fams.setdefault((m["family"], tuple(m["runs"])), []).append(m)
        fam_desc = ", ".join(
            f"{fam} {ra.split('-', 1)[1]}-vs-{rb.split('-', 1)[1]} "
            f"×{len(ms)} models"
            for (fam, (ra, rb)), ms in sorted(fams.items()))
        lines += [
            "## Cross-PA verdict coincidence (audited)",
            "",
            (f"Per-partition verdicts agree across protected-attribute runs "
             f"on **{s['verdicts_agree']:,} / {s['partitions_compared']:,}** "
             f"compared partitions ({fam_desc}; "
             "`audits/cross_pa_r3.json`, `scripts/cross_pa_audit.py`). "
             "This is a *property of the zoo models*, not an artifact: per "
             "partition, the logit shift induced by flipping the protected "
             "attribute is small against the logit spread over the shared "
             f"box (median shift/spread ratios "
             f"{min(ratios):.3f}–{max(ratios):.3f} across models, worst "
             f"{s['max_median_shift_over_spread']:.3f} on "
             f"{worst['model']}), so the "
             "flip slab's location — and with it each partition's SAT/UNSAT "
             "verdict — is fixed by the shared-coordinate geometry that both "
             "PA runs see identically.  The *witnesses* are genuinely "
             "PA-specific (a sex run flips the sex dim, a race run the race "
             "dim), and the reference's own published GC-3/GC-4 rows show "
             "the same age/sex coincidence (BASELINE.md Table V)."),
            "",
        ]
    out = os.path.join(ROOT, "PARITY.md")
    with open(out, "w") as fp:
        fp.write("\n".join(lines))
    print(f"wrote {out} ({len(recs)} rows); mismatches: {agree_fail or 'none'}")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    run = sub.add_parser("run")
    run.add_argument("--out", default="parity")
    run.add_argument("--soft", type=float, default=5.0)
    run.add_argument("--hard", type=float, default=600.0)
    run.add_argument("--runs", default="")
    run.set_defaults(fn=cmd_run)
    ren = sub.add_parser("render")
    ren.add_argument("--out", default="parity")
    ren.set_defaults(fn=cmd_render)
    rf = sub.add_parser("refresh")
    rf.add_argument("--out", default="parity")
    rf.set_defaults(fn=cmd_refresh)
    args = ap.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()

"""Second-chance pass over every parity run with UNKNOWN partitions.

Reads ``<out>/results.jsonl``, and for each model with unknown > 0 re-runs
the sweep with ``retry_unknown`` and a larger soft timeout (the ledger
makes this incremental: decided partitions are skipped, only the
budget-exhausted ones are re-attempted — now with the α-CROWN escalated
engine).  Finish with ``python scripts/parity.py refresh`` + ``render``.

Usage: python scripts/retry_unknowns.py [--out parity] [--soft 30]
       [--hard 900] [--max-unknown 100000]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "scripts"))

from parity import RUNS  # noqa: E402  (scripts/ sibling)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="parity")
    ap.add_argument("--soft", type=float, default=30.0)
    ap.add_argument("--hard", type=float, default=900.0)
    ap.add_argument("--max-unknown", type=int, default=100000,
                    help="skip rows with more unknowns than this")
    args = ap.parse_args()

    from fairify_tpu.models import zoo
    from fairify_tpu.verify import presets, sweep

    cfg_of = {rid: (preset, overrides) for rid, preset, overrides, _ in RUNS}
    with open(os.path.join(args.out, "results.jsonl")) as fp:
        recs = [json.loads(line) for line in fp]
    todo = [r for r in recs if "skipped" not in r and r["unknown"] > 0
            and r["unknown"] <= args.max_unknown]
    print(f"{len(todo)} models with unknowns to retry", flush=True)
    for r in sorted(todo, key=lambda r: r["unknown"]):
        preset, overrides = cfg_of[r["run_id"]]
        cfg = presets.get(preset).with_(
            soft_timeout_s=args.soft, hard_timeout_s=args.hard,
            result_dir=os.path.join(args.out, r["run_id"]), **overrides)
        net = zoo.load(cfg.dataset, r["model"])
        rep = sweep.verify_model(net, cfg, model_name=r["model"],
                                 resume=True, retry_unknown=True)
        print(json.dumps({"run_id": r["run_id"], "model": r["model"],
                          "was_unknown": r["unknown"], **rep.counts}),
              flush=True)


if __name__ == "__main__":
    main()

"""Deep-soft-budget pass over variant rows with residual UNKNOWNs.

The budgeted variant sweep already gives every in-prefix box a soft-timeout
re-decision (``_sweeplib.retry_span_unknowns``); a box still UNKNOWN after
that resisted the engine at the row's 100 s soft budget.  This driver gives
exactly those boxes a deeper per-partition budget — the escalation the
reference applies by hand when it re-runs a model with a larger argv soft
timeout (``src/GC/Verify-GC.py:146-149``) — and patches the results row in
place with an explicit ``deep_retry`` marker so the rendered Budget column
never passes the deep pass off as the base tier.

Usage: python scripts/deep_retry_variants.py [--out variants]
           [--soft 600] [--budget 1200] [--max-unknown 100000]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "scripts"))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="variants")
    ap.add_argument("--soft", type=float, default=600.0,
                    help="deep per-partition soft budget (s)")
    ap.add_argument("--budget", type=float, default=1200.0,
                    help="wall budget per (preset, model) row (s)")
    ap.add_argument("--max-unknown", type=int, default=100000)
    ap.add_argument("--lattice-max", type=float, default=5.0e10,
                    help="Phase E lattice ceiling for the escalated engine "
                         "(prefix-peeled enumeration makes 10^10-class "
                         "boxes minutes, not hours)")
    ap.add_argument("--presets", default="",
                    help="comma list restricting which presets to deepen")
    args = ap.parse_args()

    from _sweeplib import retry_span_unknowns
    from fairify_tpu.models import zoo
    from fairify_tpu.verify import presets

    results_path = os.path.join(args.out, "results.jsonl")
    with open(results_path) as fp:
        recs = [json.loads(line) for line in fp]

    # Latest record per (run, model, budget/cap config) is the live row.
    latest: dict = {}
    for i, r in enumerate(recs):
        if "skipped" in r or "attempted" not in r:
            continue
        latest[(r["run_id"], r["model"], r["soft_s"], r["hard_s"],
                r.get("cap"), r.get("engine_tag"))] = i
    wanted = set(args.presets.split(",")) if args.presets else None
    todo = [(k, i) for k, i in sorted(latest.items())
            if 0 < recs[i]["unknown"] <= args.max_unknown
            and (wanted is None or k[0] in wanted)]
    print(f"{len(todo)} rows with residual unknowns", flush=True)

    grids: dict = {}
    for k, i in todo:
        r = recs[i]
        cfg = presets.get(r["run_id"]).with_(
            soft_timeout_s=r["soft_s"], hard_timeout_s=r["hard_s"],
            result_dir=os.path.join(args.out, r["run_id"]))
        if r.get("cap") is not None:
            # Rows recorded under --max-partitions used the capped sampled
            # grid; the ledger pids index THAT grid, so it must be rebuilt
            # identically or lo[idx]/hi[idx] would be different boxes.
            cfg = cfg.with_(capped_partitions=True, max_partitions=r["cap"])
        # The span ledgers live under the ORIGINAL config's budget-suffixed
        # dir (budgeted_model_sweep) — engine-tagged rows (round 5+) add the
        # tag to that dir, so the deep pass must follow it or it silently
        # no-ops on exactly the rows it should deepen.  Only the
        # per-partition soft budget is escalated for the re-decision.
        sub = f"b{cfg.soft_timeout_s:g}-{cfg.hard_timeout_s:g}"
        if r.get("engine_tag"):
            sub += f"-{r['engine_tag']}"
        cfg = cfg.with_(result_dir=os.path.join(cfg.result_dir, sub))
        # Escalate the engine's per-root node cap with the soft budget:
        # stress-GC box 624 (GC-5) certifies at ~227k BaB nodes — above the
        # 200k default — so a deeper wall budget without a deeper node cap
        # loops forever on exactly the boxes this driver exists for.
        from dataclasses import replace

        deep = cfg.with_(
            soft_timeout_s=args.soft,
            engine=replace(cfg.engine,
                           max_nodes=max(cfg.engine.max_nodes,
                                         int(2000 * args.soft)),
                           lattice_max=max(cfg.engine.lattice_max,
                                           args.lattice_max)))
        net = zoo.load(deep.dataset, r["model"])
        # One grid per (preset, cap): models of a preset share it, and the
        # stress grids reach 3.3M boxes — rebuild per row would dominate,
        # and its bookkeeping must not skew the row's dec/s.
        gkey = (r["run_id"], r.get("cap"))
        if gkey not in grids:
            from fairify_tpu.verify import sweep as sweep_mod

            _, lo, hi = sweep_mod.build_partitions(deep)
            grids[gkey] = (lo, hi)
        t0 = time.perf_counter()
        fixed, residual = retry_span_unknowns(
            deep, net, r["model"], budget_s=args.budget, grid=grids[gkey],
            return_residual=True)
        dt = time.perf_counter() - t0
        if residual == 0:
            # Nothing left to attempt.  Two sub-cases: (a) no ledgers at
            # all — genuine no-op; (b) the ledgers already hold MORE
            # decided verdicts than the row (e.g. a prior deep pass whose
            # row patch failed) — the decided-wins ledger merge is the
            # record of truth, so recount the row WITHOUT stamping a
            # deep_retry marker (no escalation ran in this invocation).
            from _sweeplib import merge_span_ledgers

            paths_l, led_dec, led_unk = merge_span_ledgers(cfg, r["model"])
            if paths_l and (len(led_unk) < recs[i]["unknown"]):
                # Tier honesty (r5 review): ledger entries record their own
                # per-decision soft budget; any decided entry deeper than
                # the row's base soft means a prior deep pass's verdicts
                # are being recovered — the row MUST carry the deep_retry
                # marker (its wall was lost with the crashed patch; say so)
                # or the Budget column would pass deep work off as base
                # tier.
                deep_entries = [rec_l for rec_l in led_dec.values()
                                if rec_l.get("soft_s", r["soft_s"])
                                > r["soft_s"]]
                deep_soft = max((rec_l["soft_s"] for rec_l in deep_entries),
                                default=0.0)

                def recount(row):
                    _rollup_counts(row, led_dec, led_unk)
                    if deep_entries:
                        dr = row.setdefault(
                            "deep_retry",
                            {"soft_s": deep_soft, "fixed": 0, "wall_s": 0.0})
                        dr["soft_s"] = max(dr["soft_s"], deep_soft)
                        dr["fixed"] = max(dr["fixed"], len(deep_entries))
                        dr["wall_unrecorded"] = True
                    return row

                ok = _patch_results_row(results_path, k, recount)
                print(json.dumps({"run_id": r["run_id"],
                                  "model": r["model"],
                                  "recounted_from_ledgers": ok,
                                  "deep_entries": len(deep_entries),
                                  "unknown": len(led_unk)}), flush=True)
            else:
                print(json.dumps({"run_id": r["run_id"], "model": r["model"],
                                  "warning": "no residual unknowns in "
                                             "ledgers; row not patched"}),
                      flush=True)
            continue
        n_fixed = sum(fixed.values())

        # ADVICE r3 (+ r4 review): the span ledgers are the record of truth
        # for EVERY count, not just unknown — after a crash between a prior
        # deep run's ledger append and its row patch, the row's sat/unsat
        # are stale too (blindly adding `fixed` would silently drop the
        # crash-decided partitions).  Recompute all three counts with the
        # SAME decided-wins merge retry_span_unknowns uses
        # (_sweeplib.merge_span_ledgers) — a file-order last-wins merge
        # could demote a decided pid behind an overlapping span's
        # budget-cut 'unknown'.
        from _sweeplib import merge_span_ledgers

        _, led_decided, led_unknown = merge_span_ledgers(cfg, r["model"])
        led_counts = {"sat": 0, "unsat": 0, "unknown": len(led_unknown)}
        for rec_l in led_decided.values():
            led_counts[rec_l["verdict"]] += 1

        def patch(row):
            _rollup_counts(row, led_decided, led_unknown)
            row["total_time_s"] = round(row["total_time_s"] + dt, 2)
            row["decided_per_sec"] = round(
                (row["sat"] + row["unsat"]) / max(row["total_time_s"], 1e-9),
                3)
            dr = row.setdefault("deep_retry", {"soft_s": args.soft,
                                               "fixed": 0, "wall_s": 0.0})
            # Repeated invocations at different --soft tiers accumulate
            # into one marker labelled with the DEEPEST per-partition
            # budget applied (rendered as "up to N s", scripts/variants.py).
            dr["soft_s"] = max(dr["soft_s"], args.soft)
            dr["fixed"] += n_fixed
            dr["wall_s"] = round(dr["wall_s"] + dt, 2)
            return row

        if _patch_results_row(results_path, k, patch):
            print(json.dumps({"run_id": r["run_id"], "model": r["model"],
                              **fixed,
                              "still_unknown": led_counts["unknown"],
                              "wall_s": round(dt, 2)}), flush=True)
        else:
            # The target row vanished between startup and the patch (a
            # concurrent rewrite) — the decided boxes ARE in the span
            # ledger, only the results-row accounting is lost; say so.
            print(json.dumps({"run_id": r["run_id"], "model": r["model"],
                              **fixed,
                              "warning": "results row disappeared; deep "
                                         "verdicts kept in span ledger "
                                         "but row not patched"}),
                  flush=True)
    return 0


def _rollup_counts(row: dict, led_decided: dict, led_unknown) -> dict:
    """Decided-wins ledger counts -> row (the ONE row-accounting rule,
    shared by the post-escalation patch and the ledger recount so the two
    paths cannot diverge)."""
    cts = {"sat": 0, "unsat": 0}
    for rec_l in led_decided.values():
        cts[rec_l["verdict"]] += 1
    row["sat"] = cts["sat"]
    row["unsat"] = cts["unsat"]
    row["unknown"] = len(led_unknown)
    row["decided_per_sec"] = round(
        (row["sat"] + row["unsat"]) / max(row["total_time_s"], 1e-9), 3)
    return row


def _patch_results_row(results_path: str, row_key, patch_fn) -> bool:
    """Re-read → patch one row by key → atomic replace.

    The driver runs for hours; holding its startup snapshot and rewriting
    the whole file per patch silently dropped every record another process
    (a concurrently appending sweep) added since startup.  Re-reading
    immediately before each patch shrinks that lost-append window from
    hours to milliseconds, and the write-then-rename keeps a kill mid-write
    from truncating the ledger.  (Best effort, not a lock — don't run two
    patching drivers concurrently.)  Returns False when no row matches the
    key (a concurrent rewrite removed it) — the caller must surface that
    rather than report success.
    """
    with open(results_path) as fp:
        rows = [json.loads(line) for line in fp]
    # Latest-wins, like main()'s `latest` dict: duplicate-key rows are an
    # anticipated ledger state, and the LAST one is the live row.
    target = None
    for i, row in enumerate(rows):
        if "skipped" in row or "attempted" not in row:
            continue
        if (row["run_id"], row["model"], row["soft_s"], row["hard_s"],
                row.get("cap"), row.get("engine_tag")) == row_key:
            target = i
    if target is None:
        return False
    patch_fn(rows[target])
    tmp = results_path + ".tmp"
    with open(tmp, "w") as fp:
        for row in rows:
            fp.write(json.dumps(row) + "\n")
    os.replace(tmp, results_path)
    return True


if __name__ == "__main__":
    sys.exit(main())

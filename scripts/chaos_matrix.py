"""Chaos matrix: sweep the injectable fault sites × kinds and check the
degradation contract (smt.query needs z3-solver and is covered by the
z3-gated tests in tests/test_resilience.py instead).

For each (site, kind) cell this driver runs a small deterministic sweep
with an injected fault schedule (``resilience.faults``), then checks the
three-clause contract DESIGN.md §10 pins:

1. the run never crashes (``kind=crash`` cells EXPECT the crash instead);
2. partitions decided around the fault carry the fault-free run's
   verdicts exactly; faulted partitions are UNKNOWN with a machine-
   readable ``failure`` record in the ledger;
3. a subsequent ``resume=True`` pass (faults disarmed) converges to the
   fault-free verdict map.

Every cell's schedule is printed in its JSON row, so any failure is
reproducible with ``fairify_tpu run --inject-fault <spec>``.  Exit 1 if
any cell violates the contract.

Shard-loss cells (``parallel.shards``) extend the matrix to the sharded
runtime: ``device.lost`` at each shard index × {transient, fatal}.  A
transient loss must be absorbed by the shard supervisor (verdict map
IDENTICAL, nothing degraded); a fatal loss must quarantine the shard's
device group, elastically re-shard its span onto the survivors, and still
converge to the fault-free verdict map without a resume pass.

Serve cells (``--serve``) extend the matrix to the persistent server
(``fairify_tpu/serve``): ``launch.*`` and ``request.*`` faults injected
while TWO concurrent clients share coalesced launches.  The contract
inside the server loop mirrors DESIGN.md §13's blast-radius table: the
server never crashes, a faulted *request* degrades or rejects alone while
its neighbor's decided verdicts stay bit-equal to a solo run, and a
resubmit after disarm (``resume=True`` over the same request sink)
converges to the fault-free map.

SMT worker-pool cells (``fairify_tpu/smt``, DESIGN.md §14) extend the
matrix to the out-of-process solver: ``smt.worker.{crash,hang,memout}`` ×
{transient (one arrival — the fresh-worker retry must absorb it: verdict
map IDENTICAL, nothing degraded), exhausted (every arrival — exactly the
faulted queries' partitions degrade to UNKNOWN with a machine-readable
``smt.worker:*`` failure record, and a disarmed resume converges)}.  The
injected faults convert to REAL subprocess events (SIGKILL mid-dispatch,
a wedged worker killed at its hard deadline, an allocation past the RSS
cap), so these cells exercise the true containment machinery.  BaB and
stage 0 are substituted with always-unknown stubs for these cells only:
CROWN certifies any tiny-box world outright, so no real config funnels
work to the solver deterministically — the machinery under test (fan-out,
death classification, degradation, resume) is entirely real.  With
``--serve``, the same faults run inside the persistent server under two
concurrent clients sharing the server-wide pool.

Fleet cells (``--fleet``) extend the matrix to replicated serving
(``serve/fleet.py``, DESIGN.md §15): ``replica.lost`` × {transient, fatal}
× {idle, mid-batch, mid-SMT-drain} and ``request.preempt``.  A transient
loss is a heartbeat blip the router absorbs (nothing dies, verdicts
identical); a fatal loss kills that replica (cooperative SIGKILL analog)
and the router's real failover re-homes its in-flight + queued requests to
survivors — the contract is *zero lost decided verdicts*: every request
reaches a terminal state and the post-failover verdict map is bit-equal to
the fault-free run (``resume=True`` ledger replay).  ``request.preempt``
forces a mid-flight span-granular preemption; the preempted request must
requeue, complete, and stay bit-equal.

Result-integrity cells (``--integrity``, DESIGN.md §21) extend the
matrix to SILENT data corruption: ``corrupt``-kind faults flip a data
bit instead of raising, at ``launch.decode`` (a device->host result
buffer), ``ledger.append`` (a verdict row already on disk) and
``smt.query`` (a solver counterexample).  The contract per cell: the
corruption is DETECTED (``integrity_violations`` or
``ledger_crc_mismatch`` fired), ZERO corrupted verdicts escape as
decided (``sdc_escaped == 0``), affected partitions land in
``unknown:failure:integrity.<site>``, and a disarmed resume converges to
the fault-free map.  With ``--serve`` the same corruptions run inside
the replicated server — a suspect replica must be quarantined — and with
``--procfleet`` inside real OS-process replicas, where the router must
classify the death as ``kind=integrity``.  The procfleet × smt.query
cell is delegated: the solver stubs cannot cross the process boundary
(no real config funnels work to the solver deterministically), and the
in-process run/serve smt.query cells exercise the identical
``_SmtTier.result`` code path the replica runs.

Usage: python scripts/chaos_matrix.py [--out chaos] [--span 48]
           [--grid-chunk 16] [--preset GC] [--shards 3] [--serve]
           [--fleet] [--procfleet] [--integrity] [--no-smt]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# The shard-loss cells need a device fleet; pin the virtual CPU mesh
# BEFORE jax initializes (same contract as tests/conftest.py).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# Transient cells use nth=2 (one retry absorbs it: verdicts must be
# IDENTICAL, not just consistent); exhausting cells use 2+ (every arrival
# from the 2nd: bounded retries cannot absorb it, the chunk must degrade).
SCHEDULES = [
    ("launch.submit", "transient", "launch.submit:transient:2"),
    ("launch.submit", "exhausted", "launch.submit:transient:2+"),
    ("launch.submit", "fatal", "launch.submit:fatal:2"),
    ("launch.decode", "transient", "launch.decode:transient:2"),
    ("launch.decode", "exhausted", "launch.decode:transient:2+"),
    ("launch.decode", "fatal", "launch.decode:fatal:2"),
    ("ledger.append", "transient", "ledger.append:transient:2"),
    ("ledger.append", "exhausted", "ledger.append:transient:2+"),
    ("ledger.append", "fatal", "ledger.append:fatal:2"),
]
# Not in the table above:
# * compile — fires only on an obs_jit cache MISS, so its cell needs its
#   own fresh architecture (below); fatal/crash compile faults are
#   structurally identical to transient there (everything lands in the
#   plain-jit fallback except crash, which propagates like any crash).
# * smt.query — decide_box_smt needs z3-solver (absent from this image);
#   the z3-gated tests in tests/test_resilience.py cover it.


def _vmap(report):
    return {o.partition_id: o.verdict for o in report.outcomes}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="chaos")
    ap.add_argument("--preset", default="GC")
    ap.add_argument("--span", type=int, default=48)
    ap.add_argument("--grid-chunk", type=int, default=16)
    ap.add_argument("--shards", type=int, default=3,
                    help="fault domains for the shard-loss cells "
                         "(0 disables them)")
    ap.add_argument("--serve", action="store_true",
                    help="also run the server-loop cells: launch.*/"
                         "request.* faults under two concurrent clients")
    ap.add_argument("--fleet", action="store_true",
                    help="also run the replicated-serving cells: "
                         "replica.lost x {transient,fatal} x {idle,"
                         "mid-batch,mid-SMT-drain} + request.preempt")
    ap.add_argument("--procfleet", action="store_true",
                    help="also run the OS-process replica-fleet cells "
                         "(serve/procfleet.py, real subprocesses): literal "
                         "SIGKILL mid-batch, SIGSTOP lease-wedge, "
                         "replica.lease fatal, replica.spawn x {transient,"
                         "exhausted}, memout x {transient,exhausted}")
    ap.add_argument("--integrity", action="store_true",
                    help="also run the result-integrity cells: corrupt-"
                         "kind faults (silent bit flips, no exception) at "
                         "launch.decode / ledger.append / smt.query; with "
                         "--serve / --procfleet the corruption runs inside "
                         "the replicated and OS-process serving stacks too")
    ap.add_argument("--no-smt", action="store_true",
                    help="skip the smt.worker.* pool cells")
    ap.add_argument("--lockprof", action="store_true",
                    help="run the whole matrix under the dynamic lock "
                         "profiler (obs.lockprof) and add a final cell "
                         "asserting every observed acquisition-order edge "
                         "exists in the static lock graph (fairify_tpu "
                         "lint's lock-order analysis)")
    args = ap.parse_args()

    if args.lockprof:
        # Install BEFORE any server/pool/plan construction so their locks
        # are profiled; module-level locks predate this and are exempt.
        from fairify_tpu.obs import lockprof

        lockprof.install()

    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.verify import presets, sweep

    cfg0 = presets.get(args.preset).with_(
        soft_timeout_s=30.0, hard_timeout_s=600.0, sim_size=64,
        exact_certify_masks=False, grid_chunk=args.grid_chunk,
        launch_backoff_s=0.001)
    net = init_mlp((len(cfg0.query().columns), 8, 1), seed=3)
    span = (0, args.span)
    shutil.rmtree(args.out, ignore_errors=True)

    base = sweep.verify_model(
        net, cfg0.with_(result_dir=os.path.join(args.out, "base")),
        model_name="m", resume=False, partition_span=span)
    want = _vmap(base)
    print(json.dumps({"cell": "fault-free", **base.counts}), flush=True)

    failures = 0
    for site, label, spec in SCHEDULES:
        rdir = os.path.join(args.out, f"{site}-{label}".replace(".", "_"))
        cfg = cfg0.with_(result_dir=rdir, inject_faults=(spec,))
        row = {"cell": f"{site}/{label}", "spec": spec}
        try:
            rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                                     partition_span=span)
        except BaseException as exc:  # contract clause 1: must not crash
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
            failures += 1
            print(json.dumps(row), flush=True)
            continue
        got = _vmap(rep)
        decided_match = all(got[k] == want[k] for k in got
                            if got[k] != "unknown")
        row.update(degraded=rep.degraded, **rep.counts,
                   decided_match=decided_match)
        resumed = sweep.verify_model(
            net, cfg.with_(inject_faults=()), model_name="m", resume=True,
            partition_span=span)
        row["resume_converged"] = _vmap(resumed) == want
        row["ok"] = decided_match and row["resume_converged"]
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

    # compile cell: needs a fresh architecture so obs_jit actually compiles
    # (a warm cache never reaches the fault site and the cell would pass
    # vacuously) — faulted vs clean compared on that net's own verdicts,
    # and the row asserts the fault really fired.
    from fairify_tpu.obs import metrics as metrics_mod

    fired = metrics_mod.registry().counter("fault_injected")
    f0 = fired.value(site="compile", kind="transient")
    cnet = init_mlp((len(cfg0.query().columns), 7, 1), seed=11)
    row = {"cell": "compile/transient", "spec": "compile:transient:1+"}
    rep_f = sweep.verify_model(
        cnet, cfg0.with_(result_dir=os.path.join(args.out, "compile_f"),
                         inject_faults=("compile:transient:1+",)),
        model_name="m", resume=False, partition_span=span)
    rep_c = sweep.verify_model(
        cnet, cfg0.with_(result_dir=os.path.join(args.out, "compile_c")),
        model_name="m", resume=False, partition_span=span)
    row["fired"] = fired.value(site="compile", kind="transient") > f0
    row["degraded"] = rep_f.degraded
    row["decided_match"] = _vmap(rep_f) == _vmap(rep_c)
    row["ok"] = bool(row["fired"] and row["decided_match"]
                     and rep_f.degraded == 0)
    failures += 0 if row["ok"] else 1
    print(json.dumps(row), flush=True)

    # crash-kind cells: the fault MUST propagate, and resume must converge.
    for spec in ("launch.submit:crash:2", "launch.decode:crash:2"):
        site = spec.split(":")[0]
        rdir = os.path.join(args.out, f"{site}-crash".replace(".", "_"))
        cfg = cfg0.with_(result_dir=rdir, inject_faults=(spec,))
        row = {"cell": f"{site}/crash", "spec": spec}
        try:
            sweep.verify_model(net, cfg, model_name="m", resume=False,
                               partition_span=span)
            row["crashed"] = False
        except Exception:
            row["crashed"] = True
        resumed = sweep.verify_model(
            net, cfg.with_(inject_faults=()), model_name="m", resume=True,
            partition_span=span)
        row["resume_converged"] = _vmap(resumed) == want
        row["ok"] = row["crashed"] and row["resume_converged"]
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

    # Mega-segment blast-radius cells (ISSUE 14 / DESIGN.md §17): with
    # one-chunk segments, exhausting exactly one segment's submit (or
    # decode) attempts — nth 2-4 covers segment 2's attempt plus both
    # retries at the default max_launch_retries=2 — must degrade that
    # segment's partitions ONLY, and resume must converge to the
    # fault-free map.
    for site in ("launch.submit", "launch.decode"):
        spec = f"{site}:transient:2-4"
        rdir = os.path.join(args.out, f"mega_{site.replace('.', '_')}")
        cfg = cfg0.with_(result_dir=rdir, mega_chunks=1,
                         inject_faults=(spec,))
        row = {"cell": f"mega/{site}/exhausted-mid-segment", "spec": spec}
        try:
            rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                                     partition_span=span)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
            failures += 1
            print(json.dumps(row), flush=True)
            continue
        got = _vmap(rep)
        seg = set(range(args.grid_chunk + 1, 2 * args.grid_chunk + 1))
        blast_exact = rep.degraded == args.grid_chunk and all(
            got[pid] == "unknown" for pid in seg) and all(
            got[k] == want[k] for k in got if k not in seg)
        resumed = sweep.verify_model(
            net, cfg.with_(inject_faults=()), model_name="m", resume=True,
            partition_span=span)
        row.update(degraded=rep.degraded, blast_radius_exact=blast_exact,
                   resume_converged=_vmap(resumed) == want)
        row["ok"] = bool(blast_exact and row["resume_converged"])
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

    # Device-BaB segment cells (DESIGN.md §22): faults mid-BaB-segment.
    # The cells drive engine.decide_many directly on a toy world whose
    # roots genuinely branch (the chaos sweep's boxes all certify at the
    # root, so BaB never launches there) — inside decide_many only the
    # device-BaB phase routes launches through LaunchPipeline's fault
    # sites, so launch.* arrival numbers count BaB segments exactly.
    # Contract: a transient fault is absorbed by the supervisor retry
    # (verdict-for-verdict identical, nothing degraded); an exhausted one
    # degrades exactly ONE segment's root group (bab_frontier_cap=4 →
    # one root per group) to UNKNOWN while every other root matches the
    # fault-free run; a decode corruption is caught by the frontier fold
    # checksum + canary slot (integrity_violations fires, zero corrupted
    # verdicts escape); and a disarmed re-run converges — the device
    # queue state never advances on a failed fetch, so re-running from
    # the roots is the engine's (stateless) resume analog.
    from fairify_tpu.data.domains import DomainSpec
    from fairify_tpu.resilience import faults as faults_lib
    from fairify_tpu.verify import engine as engine_mod
    from fairify_tpu.verify.engine import EngineConfig
    from fairify_tpu.verify.property import FairnessQuery, encode

    bab_dom = DomainSpec(name="chaos-bab", columns=("a0", "a1", "a2", "p"),
                         ranges={"a0": (0, 2), "a1": (0, 2), "a2": (0, 2),
                                 "p": (0, 1)}, label="y")
    bab_enc = encode(FairnessQuery(domain=bab_dom, protected=("p",)))
    bab_net = init_mlp((4, 6, 1), seed=0)
    bab_lo = [[0, 0, 0, 0], [0, 0, 0, 0], [1, 0, 0, 0], [0, 1, 0, 0]]
    bab_hi = [[2, 2, 2, 1], [1, 2, 2, 1], [2, 2, 2, 1], [2, 2, 1, 1]]
    bab_cfg = EngineConfig(
        soft_timeout_s=60.0, pgd_phase=False, sign_bab=False, lp_sign=False,
        lp_pair=False, lattice_exhaustive=False, attack_samples=2,
        bab_attack_samples=2, device_bab=True, bab_frontier_cap=4,
        bab_rounds_per_segment=2, max_launch_retries=1,
        launch_backoff_s=1e-3)

    def _bab_run(spec=None):
        import numpy as np

        lo = np.asarray(bab_lo, dtype=np.int64)
        hi = np.asarray(bab_hi, dtype=np.int64)
        specs = () if spec is None else (spec,)
        with faults_lib.armed(specs, seed=bab_cfg.seed):
            decs = engine_mod.decide_many(bab_net, bab_enc, lo, hi, bab_cfg,
                                          deadline_s=120.0)
        return {i: d.verdict for i, d in enumerate(decs)}

    bab_want = _bab_run()
    row = {"cell": "bab/fault-free",
           "all_decided": all(v != "unknown" for v in bab_want.values())}
    failures += 0 if row["all_decided"] else 1
    print(json.dumps(row), flush=True)

    BAB_CELLS = [
        # (cell, spec, absorbed): transient = one mid-BaB arrival, the
        # retry absorbs it; exhausted = the arrival AND its only retry
        # (max_launch_retries=1) fault, the segment's group degrades.
        ("bab/launch.submit/transient", "launch.submit:transient:2", True),
        ("bab/launch.submit/exhausted", "launch.submit:transient:2-3", False),
        ("bab/launch.decode/transient", "launch.decode:transient:2", True),
        ("bab/launch.decode/exhausted", "launch.decode:transient:2-3", False),
    ]
    for cell, spec, absorbed in BAB_CELLS:
        row = {"cell": cell, "spec": spec}
        try:
            got = _bab_run(spec)
        except BaseException as exc:  # clause 1: must not crash
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
            failures += 1
            print(json.dumps(row), flush=True)
            continue
        unknowns = [k for k, v in got.items() if v == "unknown"]
        row["unknowns"] = unknowns
        row["decided_match"] = all(got[k] == bab_want[k] for k in got
                                   if got[k] != "unknown")
        row["rerun_converged"] = _bab_run() == bab_want
        if absorbed:
            row["ok"] = bool(got == bab_want and row["rerun_converged"])
        else:
            # Blast radius: exactly one root group (one root at cap 4).
            row["ok"] = bool(row["decided_match"] and len(unknowns) == 1
                             and row["rerun_converged"])
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

    if args.integrity:
        # launch.decode:corrupt mid-BaB — a bit flips in a fetched
        # frontier buffer; the packed-queue fold checksum / canary slot
        # must catch it at decode and degrade only that group.
        viol_bab = metrics_mod.registry().counter("integrity_violations")
        spec = "launch.decode:corrupt:2"
        row = {"cell": "integrity/bab/launch.decode", "spec": spec}
        v0 = viol_bab.value(site="launch.decode")
        try:
            got = _bab_run(spec)
            row["detected"] = bool(viol_bab.value(site="launch.decode") > v0)
            row["sdc_escaped"] = sum(1 for k in got
                                     if got[k] != "unknown"
                                     and got[k] != bab_want[k])
            unknowns = [k for k, v in got.items() if v == "unknown"]
            row["unknowns"] = unknowns
            row["rerun_converged"] = _bab_run() == bab_want
            row["ok"] = bool(row["detected"] and row["sdc_escaped"] == 0
                             and len(unknowns) == 1
                             and row["rerun_converged"])
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

    # Result-integrity cells (--integrity, DESIGN.md §21): corrupt-kind
    # faults flip DATA bits silently instead of raising.  Contract per
    # cell: detected (integrity_violations / ledger_crc_mismatch fired),
    # zero corrupted verdicts escape as decided, affected partitions land
    # in unknown:failure:integrity.<site>, and a disarmed resume converges
    # to the fault-free map.  The smt.query corruption cells need the
    # stubbed-solver world and live in the SMT section below.
    if args.integrity:
        from fairify_tpu.verify.sweep import _ledger_path, _read_ledger

        viol = metrics_mod.registry().counter("integrity_violations")
        crc_ctr = metrics_mod.registry().counter("ledger_crc_mismatch")

        # launch.decode:corrupt — a bit flips in a fetched result buffer.
        # The mega segment's checksum/canary catches it at decode: exactly
        # that segment degrades, nothing wrong is ever decided.
        spec = "launch.decode:corrupt:2"
        cfg = cfg0.with_(result_dir=os.path.join(args.out, "int_decode"),
                         mega_chunks=1, inject_faults=(spec,))
        row = {"cell": "integrity/launch.decode/run", "spec": spec}
        v0 = viol.value(site="launch.decode")
        try:
            rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                                     partition_span=span)
            got = _vmap(rep)
            row["sdc_escaped"] = sum(
                1 for k in got if got[k] != "unknown" and got[k] != want[k])
            row["detected"] = bool(viol.value(site="launch.decode") > v0)
            recs, _sk = _read_ledger(_ledger_path(cfg, rep.sink_name))
            reasons = {r["failure"]["reason"] for r in recs
                       if r.get("failure")}
            row["reasons"] = sorted(reasons)
            seg = set(range(args.grid_chunk + 1, 2 * args.grid_chunk + 1))
            row["blast_radius_exact"] = bool(
                rep.degraded == args.grid_chunk
                and all(got[pid] == "unknown" for pid in seg)
                and all(got[k] == want[k] for k in got if k not in seg))
            resumed = sweep.verify_model(
                net, cfg.with_(inject_faults=()), model_name="m",
                resume=True, partition_span=span)
            row["resume_converged"] = _vmap(resumed) == want
            row["ok"] = bool(
                row["detected"] and row["sdc_escaped"] == 0
                and reasons == {"integrity.launch.decode:fatal"}
                and row["blast_radius_exact"] and row["resume_converged"])
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # ledger.append:corrupt — a bit flips in a row already written to
        # the verdict ledger.  The live run's in-memory map is unharmed;
        # the hazard is a later RESUME trusting the row.  The per-row CRC
        # makes it unreadable: dropped, counted, and re-decided.
        spec = "ledger.append:corrupt:3"
        cfg = cfg0.with_(result_dir=os.path.join(args.out, "int_ledger"),
                         inject_faults=(spec,))
        row = {"cell": "integrity/ledger.append/run", "spec": spec}
        c0 = crc_ctr.total()
        try:
            rep = sweep.verify_model(net, cfg, model_name="m", resume=False,
                                     partition_span=span)
            row["run_map_ok"] = _vmap(rep) == want
            resumed = sweep.verify_model(
                net, cfg.with_(inject_faults=()), model_name="m",
                resume=True, partition_span=span)
            row["crc_mismatch"] = crc_ctr.total() - c0
            row["resume_converged"] = _vmap(resumed) == want
            row["ok"] = bool(row["run_map_ok"] and row["crc_mismatch"] >= 1
                             and row["resume_converged"])
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        if args.serve:
            import time as time_mod

            from fairify_tpu.resilience import faults as faults_lib
            from fairify_tpu.serve import FleetConfig, ServeConfig, \
                ServerFleet, VerificationServer

            # Corruption detected inside a replica marks it suspect; the
            # router quarantines (kills) it, and a disarmed resubmit over
            # the same sink converges on the survivor.
            row = {"cell": "integrity/launch.decode/serve-quarantine",
                   "spec": "launch.decode:corrupt:1"}
            quar = metrics_mod.registry().counter("replica_quarantined")
            q0 = quar.total()
            try:
                rdir = os.path.join(args.out, "int_serve_decode")
                fl = ServerFleet(FleetConfig(
                    n_replicas=2, poll_s=0.02,
                    replica=ServeConfig(batch_window_s=0.1, max_batch=4)))
                with faults_lib.armed(("launch.decode:corrupt:1",),
                                      seed=cfg0.seed):
                    r1 = fl.submit(
                        cfg0.with_(result_dir=rdir, mega_chunks=1), net,
                        "ma", partition_span=span)
                    fl.start()
                    f1 = fl.wait(r1.id, timeout=900.0)
                t0 = time_mod.monotonic()
                while quar.total() == q0 \
                        and time_mod.monotonic() - t0 < 30.0:
                    time_mod.sleep(0.01)
                row["quarantined"] = quar.total() - q0
                got1 = {} if f1 is None or f1.report is None \
                    else _vmap(f1.report)
                row["sdc_escaped"] = sum(
                    1 for p, v in got1.items()
                    if v != "unknown" and v != want[p])
                r2 = fl.submit(cfg0.with_(result_dir=rdir, mega_chunks=1),
                               net, "ma", partition_span=span)
                f2 = fl.wait(r2.id, timeout=900.0)
                row["replicas_alive"] = fl.replicas_alive()
                fl.drain()
                row["resume_converged"] = bool(
                    f2 is not None and f2.status == "done"
                    and f2.report is not None and _vmap(f2.report) == want)
                row["ok"] = bool(
                    f1 is not None and f1.status == "done"
                    and row["quarantined"] >= 1 and row["sdc_escaped"] == 0
                    and row["replicas_alive"] == 1
                    and row["resume_converged"])
            except BaseException as exc:
                row["crashed"] = f"{type(exc).__name__}: {exc}"
                row["ok"] = False
            failures += 0 if row["ok"] else 1
            print(json.dumps(row), flush=True)

            # Ledger corruption lands on DISK, not in RAM — the serving
            # replica is NOT suspect; the resubmit's resume pass must drop
            # the corrupt row by CRC and re-decide it.
            row = {"cell": "integrity/ledger.append/serve",
                   "spec": "ledger.append:corrupt:2"}
            c0 = crc_ctr.total()
            try:
                rdir = os.path.join(args.out, "int_serve_ledger")
                with faults_lib.armed(("ledger.append:corrupt:2",),
                                      seed=cfg0.seed):
                    srv = VerificationServer(
                        ServeConfig(batch_window_s=0.2, max_batch=2))
                    r1 = srv.submit(cfg0.with_(result_dir=rdir), net, "ma",
                                    partition_span=span)
                    srv.start()
                    f1 = srv.wait(r1.id, timeout=900.0)
                    suspect = srv.suspect()
                    srv.drain()
                srv2 = VerificationServer(
                    ServeConfig(batch_window_s=0.2, max_batch=2))
                r2 = srv2.submit(cfg0.with_(result_dir=rdir), net, "ma",
                                 partition_span=span)
                srv2.start()
                f2 = srv2.wait(r2.id, timeout=900.0)
                srv2.drain()
                row["suspect"] = suspect
                row["crc_mismatch"] = crc_ctr.total() - c0
                row["resume_converged"] = bool(
                    f2.status == "done" and _vmap(f2.report) == want)
                row["ok"] = bool(f1.status == "done" and not suspect
                                 and row["crc_mismatch"] >= 1
                                 and row["resume_converged"])
            except BaseException as exc:
                row["crashed"] = f"{type(exc).__name__}: {exc}"
                row["ok"] = False
            failures += 0 if row["ok"] else 1
            print(json.dumps(row), flush=True)

        if args.procfleet:
            import time as time_mod

            from fairify_tpu.serve import ProcessFleet, ProcFleetConfig, \
                ServeConfig
            from fairify_tpu.serve import client as client_lib

            deaths_ctr = metrics_mod.registry().counter("replica_deaths")
            int_sizes = [len(cfg0.query().columns), 8, 1]
            int_model = "init" + "x".join(str(s) for s in int_sizes) + "-s3"
            # span_chunks stays 0 (whole span per granule) so the request
            # writes ONE ledger and a local resume can replay it directly.
            int_over = {"soft_timeout_s": 30.0, "hard_timeout_s": 600.0,
                        "sim_size": 64, "exact_certify_masks": False,
                        "grid_chunk": args.grid_chunk,
                        "launch_backoff_s": 1e-4, "mega_chunks": 1}

            def _int_pf(tag):
                return ProcessFleet(ProcFleetConfig(
                    n_replicas=2, spool=os.path.join(args.out, tag),
                    poll_s=0.03, pulse_s=0.0, backoff_s=0.05,
                    replica=ServeConfig(batch_window_s=0.1, max_batch=4,
                                        poll_s=0.05)))

            def _int_pf_submit(fl, fault=None):
                over = dict(int_over)
                if fault is not None:
                    over["inject_faults"] = [fault]
                return client_lib.submit(
                    fl.cfg.spool, client_lib.build_payload(
                        args.preset, init={"sizes": int_sizes, "seed": 3},
                        overrides=over, span=span))

            def _int_pf_vmap(fl, rid):
                out = {}
                for path in client_lib.ledger_paths(fl.cfg.spool, rid):
                    for pid, rec in sweep._load_ledger(path).items():
                        out[pid] = rec["verdict"]
                return out

            def _int_pf_resume(fl, rid):
                # Disarmed local resume over the replica's own sink: the
                # cross-process analog of the run cells' resume pass (and
                # the CRC read-path check for the ledger cell).
                rcfg = cfg0.with_(
                    result_dir=os.path.join(fl.cfg.spool, "requests", rid),
                    mega_chunks=1)
                rep = sweep.verify_model(
                    init_mlp(tuple(int_sizes), seed=3), rcfg,
                    model_name=int_model, resume=True, partition_span=span)
                return _vmap(rep)

            # In-replica decode corruption: the replica detects it, beats
            # the violation count over the control pipe, and the router
            # must kill + fail over the slot under kind=integrity.
            row = {"cell": "integrity/launch.decode/procfleet",
                   "spec": "launch.decode:corrupt:2"}
            try:
                d0 = deaths_ctr.value(kind="integrity")
                fl = _int_pf("pf_int_decode").start()
                fl.wait_ready(timeout=180)
                rid = _int_pf_submit(fl, fault="launch.decode:corrupt:2")
                rec = fl.wait(rid, timeout=600)
                row["status"] = None if rec is None else rec.get("status")
                t0 = time_mod.monotonic()
                while deaths_ctr.value(kind="integrity") == d0 \
                        and time_mod.monotonic() - t0 < 60:
                    time_mod.sleep(0.02)
                row["deaths_integrity"] = \
                    deaths_ctr.value(kind="integrity") - d0
                got = _int_pf_vmap(fl, rid)
                row["sdc_escaped"] = sum(
                    1 for p, v in got.items()
                    if v != "unknown" and v != want[p])
                fl.drain()
                row["resume_converged"] = _int_pf_resume(fl, rid) == want
                row["ok"] = bool(row["status"] == "done"
                                 and row["deaths_integrity"] >= 1
                                 and row["sdc_escaped"] == 0
                                 and row["resume_converged"])
            except BaseException as exc:
                row["crashed"] = f"{type(exc).__name__}: {exc}"
                row["ok"] = False
            failures += 0 if row["ok"] else 1
            print(json.dumps(row), flush=True)

            # In-replica ledger corruption: invisible at write time (no
            # integrity death), caught by the CRC when the sink is replayed.
            row = {"cell": "integrity/ledger.append/procfleet",
                   "spec": "ledger.append:corrupt:3"}
            try:
                d0 = deaths_ctr.value(kind="integrity")
                fl = _int_pf("pf_int_ledger").start()
                fl.wait_ready(timeout=180)
                rid = _int_pf_submit(fl, fault="ledger.append:corrupt:3")
                rec = fl.wait(rid, timeout=600)
                row["status"] = None if rec is None else rec.get("status")
                fl.drain()
                c0 = crc_ctr.total()
                row["resume_converged"] = _int_pf_resume(fl, rid) == want
                row["crc_mismatch"] = crc_ctr.total() - c0
                row["no_integrity_death"] = \
                    deaths_ctr.value(kind="integrity") == d0
                row["ok"] = bool(row["status"] == "done"
                                 and row["crc_mismatch"] >= 1
                                 and row["no_integrity_death"]
                                 and row["resume_converged"])
            except BaseException as exc:
                row["crashed"] = f"{type(exc).__name__}: {exc}"
                row["ok"] = False
            failures += 0 if row["ok"] else 1
            print(json.dumps(row), flush=True)

            # smt.query × procfleet is DELEGATED (see module docstring):
            # the always-unknown solver stubs cannot cross the process
            # boundary and no real config funnels work to the solver
            # deterministically.  The run + serve smt.query cells exercise
            # the identical _SmtTier.result code path the replica runs.
            print(json.dumps({
                "cell": "integrity/smt.query/procfleet",
                "delegated": "covered by integrity/smt.query/{run,serve}"
                             " (same in-process code path; stubs cannot"
                             " cross the replica process boundary)",
                "ok": True}), flush=True)

    # Shard-loss cells: device.lost at each shard index × transient/fatal
    # over the sharded runtime.  The fault-free SHARDED run is the pin —
    # it must itself equal the single-chip map (cross-path invariance).
    if args.shards:
        import jax

        from fairify_tpu.obs import metrics as metrics_mod
        from fairify_tpu.parallel import shards as shards_mod

        n_sh = min(args.shards, len(jax.devices()))
        sh_base = shards_mod.sweep_sharded(
            net, cfg0.with_(result_dir=os.path.join(args.out, "shard_base")),
            model_name="m", n_shards=n_sh, partition_span=span, resume=False)
        row = {"cell": "shard/fault-free", "shards": n_sh,
               "matches_single_chip": _vmap(sh_base) == want}
        failures += 0 if row["matches_single_chip"] else 1
        print(json.dumps(row), flush=True)

        for k in range(n_sh):
            for kind in ("transient", "fatal"):
                spec = f"device.lost:{kind}:{k + 1}"
                rdir = os.path.join(args.out, f"shard{k}_{kind}")
                cfg = cfg0.with_(result_dir=rdir, inject_faults=(spec,))
                row = {"cell": f"device.lost/shard{k}/{kind}", "spec": spec}
                fail_ctr = metrics_mod.registry().counter("shard_failures")
                f0 = fail_ctr.total()
                try:
                    rep = shards_mod.sweep_sharded(
                        net, cfg, model_name="m", n_shards=n_sh,
                        partition_span=span, resume=False)
                except BaseException as exc:  # clause 1: must not crash
                    row["crashed"] = f"{type(exc).__name__}: {exc}"
                    row["ok"] = False
                    failures += 1
                    print(json.dumps(row), flush=True)
                    continue
                got = _vmap(rep)
                decided_match = all(got[p] == want[p] for p in got
                                    if got[p] != "unknown")
                row.update(degraded=rep.degraded, **rep.counts,
                           decided_match=decided_match,
                           shard_failures=fail_ctr.total() - f0)
                if kind == "transient":
                    # Absorbed by the shard supervisor's retry: identical
                    # map, nothing degraded, no shard failure recorded.
                    row["ok"] = bool(got == want and rep.degraded == 0)
                else:
                    # Quarantine + elastic re-shard: the lost shard's span
                    # is re-decided on the survivors, so the FULL map must
                    # converge without any resume pass.
                    row["ok"] = bool(got == want and row["shard_failures"] >= 1)
                failures += 0 if row["ok"] else 1
                print(json.dumps(row), flush=True)

    # Serve cells: faults inside the persistent server loop, two
    # concurrent clients coalesced into shared launches.  The schedule is
    # armed GLOBALLY around the server lifetime (requests carry empty
    # inject_faults, so verify_model's own arming scope is a no-op and the
    # worker thread sees the plan).
    if args.serve:
        from fairify_tpu.resilience import faults as faults_lib
        from fairify_tpu.serve import ServeConfig, VerificationServer

        net_b = init_mlp((len(cfg0.query().columns), 8, 1), seed=5)
        base_b = sweep.verify_model(
            net_b, cfg0.with_(result_dir=os.path.join(args.out, "serve_bb")),
            model_name="mb", resume=False, partition_span=span)
        want_b = _vmap(base_b)

        SERVE_CELLS = [
            # (cell, spec, absorbed): absorbed=True means retries must hide
            # the fault entirely (identical maps, both done); False means
            # degradation is allowed but a disarm-resubmit must converge.
            ("serve/launch.submit/transient", "launch.submit:transient:2",
             True),
            ("serve/launch.decode/transient", "launch.decode:transient:2",
             True),
            ("serve/launch.submit/exhausted", "launch.submit:transient:2+",
             False),
            ("serve/request.deadline/transient", "request.deadline:transient:1",
             False),
        ]
        for cell, spec, absorbed in SERVE_CELLS:
            rdir = os.path.join(args.out, cell.replace("/", "_").replace(".", "_"))
            row = {"cell": cell, "spec": spec}
            dirs = {"ma": os.path.join(rdir, "a"), "mb": os.path.join(rdir, "b")}
            try:
                with faults_lib.armed((spec,), seed=cfg0.seed):
                    srv = VerificationServer(
                        ServeConfig(batch_window_s=0.4, max_batch=4))
                    ra = srv.submit(cfg0.with_(result_dir=dirs["ma"]), net,
                                    "ma", partition_span=span)
                    rb = srv.submit(cfg0.with_(result_dir=dirs["mb"]), net_b,
                                    "mb", partition_span=span)
                    srv.start()
                    fa = srv.wait(ra.id, timeout=900.0)
                    fb = srv.wait(rb.id, timeout=900.0)
                    srv.drain()
            except BaseException as exc:  # clause 1: the loop never crashes
                row["crashed"] = f"{type(exc).__name__}: {exc}"
                row["ok"] = False
                failures += 1
                print(json.dumps(row), flush=True)
                continue
            row["status"] = {"ma": fa.status, "mb": fb.status}
            maps = {}
            for req, name in ((fa, "ma"), (fb, "mb")):
                maps[name] = {} if req.report is None else _vmap(req.report)
            wants = {"ma": want, "mb": want_b}
            decided_match = all(
                maps[n].get(p) == wants[n][p]
                for n in maps for p in maps[n] if maps[n][p] != "unknown")
            row["decided_match"] = decided_match
            if absorbed:
                row["ok"] = bool(fa.status == fb.status == "done"
                                 and maps["ma"] == want
                                 and maps["mb"] == want_b)
            else:
                # Per-request blast radius + recovery: disarm, resubmit
                # over the same sinks; resume=True must converge both.
                srv2 = VerificationServer(
                    ServeConfig(batch_window_s=0.4, max_batch=4))
                r2a = srv2.submit(cfg0.with_(result_dir=dirs["ma"]), net,
                                  "ma", partition_span=span)
                r2b = srv2.submit(cfg0.with_(result_dir=dirs["mb"]), net_b,
                                  "mb", partition_span=span)
                srv2.start()
                f2a = srv2.wait(r2a.id, timeout=900.0)
                f2b = srv2.wait(r2b.id, timeout=900.0)
                srv2.drain()
                row["resume_converged"] = bool(
                    f2a.status == f2b.status == "done"
                    and _vmap(f2a.report) == want
                    and _vmap(f2b.report) == want_b)
                row["ok"] = bool(decided_match and row["resume_converged"])
            failures += 0 if row["ok"] else 1
            print(json.dumps(row), flush=True)

        # request.admit: the decision itself faults — the request is
        # rejected, never executed, and the server survives to serve the
        # next client.
        row = {"cell": "serve/request.admit/transient",
               "spec": "request.admit:transient:1"}
        try:
            with faults_lib.armed(("request.admit:transient:1",),
                                  seed=cfg0.seed):
                srv = VerificationServer(ServeConfig(batch_window_s=0.1))
                ra = srv.submit(
                    cfg0.with_(result_dir=os.path.join(args.out, "adm_a")),
                    net, "ma", partition_span=span)
                rb = srv.submit(
                    cfg0.with_(result_dir=os.path.join(args.out, "adm_b")),
                    net_b, "mb", partition_span=span)
                srv.start()
                fb = srv.wait(rb.id, timeout=900.0)
                srv.drain()
            row["status"] = {"ma": ra.status, "mb": fb.status}
            row["ok"] = bool(ra.status == "rejected"
                             and "request.admit" in ra.reason
                             and fb.status == "done"
                             and _vmap(fb.report) == want_b)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # serve.drain: a fault during shutdown must not make the drain
        # deniable — queued requests still requeue, the journal closes.
        row = {"cell": "serve/serve.drain/transient",
               "spec": "serve.drain:transient:1"}
        try:
            with faults_lib.armed(("serve.drain:transient:1",),
                                  seed=cfg0.seed):
                srv = VerificationServer(ServeConfig())  # never started:
                rq = srv.submit(                         # stays queued
                    cfg0.with_(result_dir=os.path.join(args.out, "drn")),
                    net, "ma", partition_span=span)
                requeued = srv.drain()
            row["ok"] = bool([r.id for r in requeued] == [rq.id]
                             and rq.status == "requeued")
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

    # Fleet cells: replica.lost x {transient, fatal} x {idle, mid-batch}
    # + request.preempt over the replicated server (serve/fleet.py).  The
    # mid-SMT-drain flavor needs the stubbed solver world and lives in the
    # SMT section below.  Contract (DESIGN.md §15): a transient loss is a
    # heartbeat blip nothing dies over; a fatal loss kills the replica and
    # failover re-homes its requests loss-free — every request terminal,
    # final verdict maps bit-equal to the fault-free runs.
    if args.fleet:
        import time as time_mod

        from fairify_tpu.resilience import faults as faults_lib
        from fairify_tpu.serve import FleetConfig, ServeConfig, ServerFleet

        net_b = init_mlp((len(cfg0.query().columns), 8, 1), seed=5)
        base_b = sweep.verify_model(
            net_b, cfg0.with_(result_dir=os.path.join(args.out, "fleet_bb")),
            model_name="mb", resume=False, partition_span=span)
        want_b = _vmap(base_b)
        f_wants = {"ma": want, "mb": want_b}
        f_nets = {"ma": net, "mb": net_b}

        def _fleet(tag):
            fl = ServerFleet(FleetConfig(
                n_replicas=2, poll_s=0.02,
                replica=ServeConfig(batch_window_s=0.1, max_batch=4,
                                    span_chunks=1)))
            rdir = os.path.join(args.out, tag)
            reqs = {n_: fl.submit(cfg0.with_(result_dir=os.path.join(rdir,
                                                                     n_)),
                                  f_nets[n_], n_, partition_span=span)
                    for n_ in ("ma", "mb")}
            return fl, reqs

        def _finish(row, fl, reqs, want_alive):
            finals = {n_: fl.wait(r.id, timeout=900.0)
                      for n_, r in reqs.items()}
            row["status"] = {n_: (f.status if f else "?")
                             for n_, f in finals.items()}
            maps = {n_: ({} if f is None or f.report is None
                         else _vmap(f.report)) for n_, f in finals.items()}
            row["replicas_alive"] = fl.replicas_alive()
            fl.drain()
            row["bit_equal"] = all(maps[n_] == f_wants[n_] for n_ in maps)
            row["ok"] = bool(
                all(f is not None and f.status == "done"
                    for f in finals.values())
                and row["bit_equal"]
                and row["replicas_alive"] == want_alive)
            return row

        # replica.lost:transient — a blip during an in-flight batch: the
        # router absorbs it, nothing dies, nothing degrades.
        row = {"cell": "fleet/replica.lost/transient",
               "spec": "replica.lost:transient:1"}
        try:
            fl, reqs = _fleet("fleet_transient")
            with faults_lib.armed(("replica.lost:transient:1",),
                                  seed=cfg0.seed):
                fl.start()
                row = _finish(row, fl, reqs, want_alive=2)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # replica.lost:fatal while IDLE — the loss lands before any work:
        # later submits must route around the quarantined replica.
        row = {"cell": "fleet/replica.lost/fatal/idle",
               "spec": "replica.lost:fatal:1"}
        try:
            fl = ServerFleet(FleetConfig(
                n_replicas=2, poll_s=0.02,
                replica=ServeConfig(batch_window_s=0.1, max_batch=4)))
            fl.start()
            with faults_lib.armed(("replica.lost:fatal:1",), seed=cfg0.seed):
                t0 = time_mod.monotonic()
                while fl.replicas_alive() == 2 \
                        and time_mod.monotonic() - t0 < 30.0:
                    time_mod.sleep(0.01)
            rdir = os.path.join(args.out, "fleet_idle")
            reqs = {n_: fl.submit(cfg0.with_(result_dir=os.path.join(rdir,
                                                                     n_)),
                                  f_nets[n_], n_, partition_span=span)
                    for n_ in ("ma", "mb")}
            row = _finish(row, fl, reqs, want_alive=1)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # replica.lost:fatal MID-BATCH — kill the replica that owns a
        # RUNNING request; failover must re-home its in-flight + queued
        # work to the survivor with zero lost decided verdicts.
        row = {"cell": "fleet/replica.lost/fatal/mid-batch"}
        try:
            fl, reqs = _fleet("fleet_midbatch")
            fl.start()
            t0 = time_mod.monotonic()
            owner = None
            while time_mod.monotonic() - t0 < 60.0:
                running = [n_ for n_, r in reqs.items()
                           if fl.get(r.id) is not None
                           and fl.get(r.id).status == "running"]
                if running:
                    owner = fl.owner_of(reqs[running[0]].id)
                    break
                time_mod.sleep(0.005)
            spec = f"replica.lost:fatal:{(owner or 0) + 1}"
            row["spec"] = spec
            with faults_lib.armed((spec,), seed=cfg0.seed):
                t0 = time_mod.monotonic()
                while fl.replicas_alive() == 2 \
                        and time_mod.monotonic() - t0 < 30.0:
                    time_mod.sleep(0.005)
            row = _finish(row, fl, reqs, want_alive=1)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # request.preempt — the injected fault FORCES a mid-flight
        # span-granular preemption; the preempted request requeues,
        # completes, and stays bit-equal.
        row = {"cell": "fleet/request.preempt",
               "spec": "request.preempt:transient:1"}
        try:
            from fairify_tpu.obs import metrics as metrics_mod

            pre = metrics_mod.registry().counter("serve_preemptions")
            p0 = pre.total()
            fl, reqs = _fleet("fleet_preempt")
            with faults_lib.armed(("request.preempt:transient:1",),
                                  seed=cfg0.seed):
                fl.start()
                row = _finish(row, fl, reqs, want_alive=2)
            row["preemptions"] = pre.total() - p0
            row["ok"] = bool(row["ok"] and row["preemptions"] >= 1)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

    # Process-fleet cells (--procfleet, DESIGN.md §18): REAL subprocess
    # replicas under literal kill -9 / SIGSTOP / RLIMIT_AS blowups plus
    # the injected replica.spawn / replica.lease faults.  Contract: the
    # router never crashes, every submitted request reaches `done`, its
    # ledger verdict map (incl. counterexample bytes) is bit-equal to the
    # fault-free solo run, and each death is classified under the right
    # taxonomy kind (crash/hang/memout/spawn).
    if args.procfleet:
        import time as time_mod

        from fairify_tpu.obs import metrics as metrics_mod
        from fairify_tpu.resilience import faults as faults_lib
        from fairify_tpu.serve import ProcessFleet, ProcFleetConfig, \
            ServeConfig
        from fairify_tpu.serve import client as client_lib

        deaths_ctr = metrics_mod.registry().counter("replica_deaths")
        pf_over = {
            "soft_timeout_s": 30.0, "hard_timeout_s": 600.0, "sim_size": 64,
            "exact_certify_masks": False, "grid_chunk": args.grid_chunk,
            "launch_backoff_s": 1e-4}
        pf_sizes = [len(cfg0.query().columns), 8, 1]

        def _pf_base(seed):
            rep = sweep.verify_model(
                init_mlp(tuple(pf_sizes), seed=seed),
                cfg0.with_(result_dir=os.path.join(args.out,
                                                   f"pf_base{seed}")),
                model_name="m", resume=False, partition_span=span)
            out = {}
            for o in rep.outcomes:
                ce = None if o.counterexample is None else \
                    json.dumps([[int(v) for v in x]
                                for x in o.counterexample])
                out[o.partition_id] = (o.verdict, ce)
            return out

        pf_want = {3: _pf_base(3), 5: _pf_base(5)}

        def _pf_fleet(tag, **kw):
            kw.setdefault("poll_s", 0.03)
            kw.setdefault("pulse_s", 0.0)
            kw.setdefault("backoff_s", 0.05)
            kw.setdefault("replica", ServeConfig(
                batch_window_s=0.1, max_batch=4, poll_s=0.05, span_chunks=1))
            return ProcessFleet(ProcFleetConfig(
                n_replicas=2, spool=os.path.join(args.out, tag), **kw))

        def _pf_submit(fl, seed):
            return client_lib.submit(fl.cfg.spool, client_lib.build_payload(
                args.preset, init={"sizes": pf_sizes, "seed": seed},
                overrides=dict(pf_over), span=span))

        def _pf_map(fl, rid):
            out = {}
            for path in client_lib.ledger_paths(fl.cfg.spool, rid):
                for pid, rec in sweep._load_ledger(path).items():
                    ce = rec.get("ce")
                    out[pid] = (rec["verdict"],
                                None if ce is None else json.dumps(ce))
            return out

        def _pf_wait_running(fl, rid, timeout=90.0):
            t0 = time_mod.monotonic()
            while time_mod.monotonic() - t0 < timeout:
                if fl.status_of(rid) == "running":
                    owner = fl.owner_of(rid)
                    if owner is not None:
                        return owner
                time_mod.sleep(0.01)
            return None

        def _pf_finish(row, fl, rids, want_kind=None, d0=None):
            ok = True
            for seed, rid in rids.items():
                rec = fl.wait(rid, timeout=600)
                done = rec is not None and rec.get("status") == "done"
                bit_equal = done and _pf_map(fl, rid) == pf_want[seed]
                row[f"status_{seed}"] = None if rec is None \
                    else rec.get("status")
                row[f"bit_equal_{seed}"] = bit_equal
                ok = ok and done and bit_equal
            if want_kind is not None:
                fired = deaths_ctr.value(kind=want_kind) - (d0 or 0)
                row["deaths_" + want_kind] = fired
                ok = ok and fired >= 1
            row["replicas_alive"] = fl.replicas_alive()
            fl.drain()
            row["ok"] = bool(ok)
            return row

        # Literal kill -9 MID-BATCH: the owning replica dies with no
        # cleanup; failover re-homes, resume replays, bit-equal.
        import signal as signal_mod

        row = {"cell": "procfleet/sigkill-mid-batch"}
        try:
            d0 = deaths_ctr.value(kind="crash")
            fl = _pf_fleet("pf_kill").start()
            fl.wait_ready(timeout=180)
            rids = {3: _pf_submit(fl, 3), 5: _pf_submit(fl, 5)}
            owner = _pf_wait_running(fl, rids[3])
            os.kill(fl.pids()[owner], signal_mod.SIGKILL)
            row = _pf_finish(row, fl, rids, want_kind="crash", d0=d0)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # SIGSTOP wedge: alive to waitpid, dead to the lease — the router
        # must escalate SIGTERM -> SIGKILL (only the SIGKILL lands on a
        # stopped process) and fail over.  The lease must clear the
        # worst-case healthy inter-beat gap (a whole granule on a loaded
        # single-core host), or the router kills the SURVIVOR too and
        # flaps the fleet dead — 5 s is the reviewed margin here.
        row = {"cell": "procfleet/sigstop-lease-wedge"}
        try:
            d0 = deaths_ctr.value(kind="hang")
            fl = _pf_fleet("pf_stop", lease_s=5.0, term_grace_s=0.5).start()
            fl.wait_ready(timeout=180)
            rids = {3: _pf_submit(fl, 3), 5: _pf_submit(fl, 5)}
            owner = _pf_wait_running(fl, rids[3])
            os.kill(fl.pids()[owner], signal_mod.SIGSTOP)
            row = _pf_finish(row, fl, rids, want_kind="hang", d0=d0)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # replica.lease:fatal — the injected fault FORCES a healthy
        # replica's lease expired, so the real hang-containment (and the
        # failover behind it) runs without a wedge to wait for.
        row = {"cell": "procfleet/replica.lease/fatal",
               "spec": "replica.lease:fatal:1"}
        try:
            d0 = deaths_ctr.value(kind="hang")
            fl = _pf_fleet("pf_lease", lease_s=30.0, term_grace_s=0.5)
            with faults_lib.armed(("replica.lease:fatal:1",),
                                  seed=cfg0.seed):
                fl.start()
                fl.wait_ready(timeout=180)
                rids = {3: _pf_submit(fl, 3)}
                t0 = time_mod.monotonic()
                while deaths_ctr.value(kind="hang") == d0 \
                        and time_mod.monotonic() - t0 < 60:
                    time_mod.sleep(0.02)
                row = _pf_finish(row, fl, rids, want_kind="hang", d0=d0)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # replica.lease:transient — a stat blip: absorbed for one tick,
        # nothing dies, nothing restarts.
        row = {"cell": "procfleet/replica.lease/transient",
               "spec": "replica.lease:transient:1"}
        try:
            d0 = deaths_ctr.total()
            fl = _pf_fleet("pf_lease_t", lease_s=30.0)
            with faults_lib.armed(("replica.lease:transient:1",),
                                  seed=cfg0.seed):
                fl.start()
                fl.wait_ready(timeout=180)
                rids = {3: _pf_submit(fl, 3)}
                row = _pf_finish(row, fl, rids)
                row["deaths_total"] = deaths_ctr.total() - d0
                row["ok"] = bool(row["ok"] and row["deaths_total"] == 0
                                 and row["replicas_alive"] == 2)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # replica.spawn:transient — slot 0's FIRST fork fails; the
        # bounded-backoff respawn brings the fleet to full strength.
        row = {"cell": "procfleet/replica.spawn/transient",
               "spec": "replica.spawn:transient:1"}
        try:
            fl = _pf_fleet("pf_spawn_t")
            with faults_lib.armed(("replica.spawn:transient:1",),
                                  seed=cfg0.seed):
                fl.start()
                t0 = time_mod.monotonic()
                while fl.replicas_alive() < 2 \
                        and time_mod.monotonic() - t0 < 120:
                    time_mod.sleep(0.05)
            fl.wait_ready(timeout=180)
            rids = {3: _pf_submit(fl, 3)}
            row = _pf_finish(row, fl, rids, want_kind=None)
            row["recovered"] = row["replicas_alive"] == 2
            row["ok"] = bool(row["ok"] and row["recovered"])
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # replica.spawn exhausted — slot 0's fork AND both respawn
        # attempts fail (arrivals 1, 3, 4; arrival 2 is slot 1): the slot
        # is abandoned, the survivor serves everything.
        row = {"cell": "procfleet/replica.spawn/exhausted",
               "spec": "replica.spawn:transient:1 + 3-4"}
        try:
            fl = _pf_fleet("pf_spawn_x", max_restarts=2)
            with faults_lib.armed(("replica.spawn:transient:1",
                                   "replica.spawn:transient:3-4"),
                                  seed=cfg0.seed):
                fl.start()
                fl.wait_ready(timeout=180)
                rids = {3: _pf_submit(fl, 3)}
                t0 = time_mod.monotonic()
                while fl.restarts()[0] < 2 \
                        and time_mod.monotonic() - t0 < 120:
                    time_mod.sleep(0.05)
                row = _pf_finish(row, fl, rids)
                row["slot0_restarts"] = fl.restarts()[0]
                row["ok"] = bool(row["ok"] and row["slot0_restarts"] == 2)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # memout transient — one allocation past RLIMIT_AS kills exactly
        # that replica with the distinct exit code; the restart absorbs
        # it and requests stay bit-equal.
        row = {"cell": "procfleet/memout/transient"}
        try:
            d0 = deaths_ctr.value(kind="memout")
            fl = _pf_fleet("pf_mem_t", memory_cap_mb=2048).start()
            fl.wait_ready(timeout=240)
            assert fl.inject_memout(0)
            t0 = time_mod.monotonic()
            while deaths_ctr.value(kind="memout") == d0 \
                    and time_mod.monotonic() - t0 < 60:
                time_mod.sleep(0.02)
            rids = {3: _pf_submit(fl, 3)}
            row = _pf_finish(row, fl, rids, want_kind="memout", d0=d0)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

        # memout exhausted — every comeback of slot 0 memouts again until
        # its restart budget is spent; the slot is abandoned and the
        # survivor still serves.
        row = {"cell": "procfleet/memout/exhausted"}
        try:
            d0 = deaths_ctr.value(kind="memout")
            fl = _pf_fleet("pf_mem_x", memory_cap_mb=2048,
                           max_restarts=1).start()
            fl.wait_ready(timeout=240)
            killed = 0
            t0 = time_mod.monotonic()
            while killed < 2 and time_mod.monotonic() - t0 < 240:
                if 0 in fl.pids() and fl.inject_memout(0):
                    before = deaths_ctr.value(kind="memout")
                    while deaths_ctr.value(kind="memout") == before \
                            and time_mod.monotonic() - t0 < 240:
                        time_mod.sleep(0.02)
                    killed += 1
                else:
                    time_mod.sleep(0.05)
            rids = {3: _pf_submit(fl, 3)}
            row = _pf_finish(row, fl, rids)
            row["memouts"] = deaths_ctr.value(kind="memout") - d0
            row["slot0_restarts"] = fl.restarts()[0]
            row["ok"] = bool(row["ok"] and row["memouts"] >= 2
                             and row["slot0_restarts"] == 1)
        except BaseException as exc:
            row["crashed"] = f"{type(exc).__name__}: {exc}"
            row["ok"] = False
        failures += 0 if row["ok"] else 1
        print(json.dumps(row), flush=True)

    # SMT worker-pool cells: see module docstring.  workers=1 keeps the
    # dispatch arrival order (and therefore nth-based schedules)
    # deterministic; memory_cap enables the memout higher-cap retry tier.
    if not args.no_smt:
        from fairify_tpu.data.domains import get_domain
        from fairify_tpu.verify import engine as engine_mod
        from fairify_tpu.verify import sweep as sweep_mod
        from fairify_tpu.verify.engine import EngineConfig
        from fairify_tpu.verify.sweep import _ledger_path

        def _dull_decode(host, ctx, stats=None):
            import numpy as np

            n = ctx["n"]
            return np.zeros(n, bool), np.zeros(n, bool), {}

        def _unknown_many(net_, enc_, rlo, rhi, ecfg, **kw):
            return [engine_mod.Decision("unknown")
                    for _ in range(rlo.shape[0])]

        ov = {c: (0, 0) for c in get_domain("german").columns}
        ov.update(age=(0, 1), month=(0, 5), purpose=(0, 5),
                  credit_amount=(0, 2))
        smt_cfg0 = presets.get("GC").with_(
            soft_timeout_s=1.0, hard_timeout_s=600.0, sim_size=16,
            exact_certify_masks=False, grid_chunk=8, launch_backoff_s=1e-4,
            max_launch_retries=1, domain_overrides=ov, partition_threshold=2,
            smt_retry_timeouts_s=(5.0,), smt_workers=1,
            smt_memory_cap_mb=128, engine=EngineConfig(pgd_phase=False))
        smt_net = init_mlp((len(smt_cfg0.query().columns), 4, 1), seed=3)
        smt_span = (0, 8)
        saved = (sweep_mod._stage0_block_decode, engine_mod.decide_many,
                 engine_mod.decide_box)
        sweep_mod._stage0_block_decode = _dull_decode
        engine_mod.decide_many = _unknown_many
        engine_mod.decide_box = \
            lambda *a, **k: engine_mod.Decision("unknown")
        try:
            smt_base = sweep_mod.verify_model(
                smt_net, smt_cfg0.with_(
                    result_dir=os.path.join(args.out, "smt_base")),
                model_name="m", resume=False, partition_span=smt_span)
            smt_want = _vmap(smt_base)
            row = {"cell": "smt/fault-free",
                   "all_decided": all(v != "unknown"
                                      for v in smt_want.values())}
            failures += 0 if row["all_decided"] else 1
            print(json.dumps(row), flush=True)

            SMT_CELLS = [(site, label,
                          f"{site}:transient:{'2' if label == 'transient' else '2+'}",
                          label == "transient")
                         for site in ("smt.worker.crash", "smt.worker.hang",
                                      "smt.worker.memout")
                         for label in ("transient", "exhausted")]
            # spawn cells use nth 1/1+ — the pool spawns lazily at first
            # checkout, so unlike dispatch sites the arrival count stays
            # at one per spawn attempt (idle workers are reused).
            SMT_CELLS += [
                ("smt.worker.spawn", "transient",
                 "smt.worker.spawn:transient:1", True),
                ("smt.worker.spawn", "exhausted",
                 "smt.worker.spawn:transient:1+", False),
            ]
            for site, label, spec, absorbed in SMT_CELLS:
                rdir = os.path.join(
                    args.out, f"{site}-{label}".replace(".", "_"))
                cfg = smt_cfg0.with_(result_dir=rdir, inject_faults=(spec,))
                row = {"cell": f"{site}/{label}", "spec": spec}
                try:
                    rep = sweep_mod.verify_model(
                        smt_net, cfg, model_name="m", resume=False,
                        partition_span=smt_span)
                except BaseException as exc:  # clause 1: must not crash
                    row["crashed"] = f"{type(exc).__name__}: {exc}"
                    row["ok"] = False
                    failures += 1
                    print(json.dumps(row), flush=True)
                    continue
                got = _vmap(rep)
                decided_match = all(got[k] == smt_want[k] for k in got
                                    if got[k] != "unknown")
                row.update(degraded=rep.degraded, **rep.counts,
                           decided_match=decided_match)
                if absorbed:
                    # One worker death: the fresh-worker retry absorbs it.
                    row["ok"] = bool(got == smt_want and rep.degraded == 0)
                else:
                    # Exhaustion: the faulted queries' partitions degrade
                    # with the site's machine-readable reason, and a
                    # disarmed resume converges to the fault-free map.
                    recs, _sk = sweep_mod._read_ledger(
                        _ledger_path(cfg, rep.sink_name))
                    reasons = {r["failure"]["reason"] for r in recs
                               if r.get("failure")}
                    want_reason = f"smt.worker:{site.rsplit('.', 1)[-1]}"
                    resumed = sweep_mod.verify_model(
                        smt_net, cfg.with_(inject_faults=()), model_name="m",
                        resume=True, partition_span=smt_span)
                    row["reasons"] = sorted(reasons)
                    row["resume_converged"] = _vmap(resumed) == smt_want
                    row["ok"] = bool(decided_match and rep.degraded > 0
                                     and reasons == {want_reason}
                                     and row["resume_converged"])
                failures += 0 if row["ok"] else 1
                print(json.dumps(row), flush=True)

            # smt.query:corrupt (--integrity): a solver counterexample
            # comes back with a flipped bit.  The witness replay
            # (validate_pair) must refuse it — the partition degrades to
            # unknown:failure:integrity.smt.query, never a wrong sat.
            if args.integrity:
                import numpy as np

                int_viol = metrics_mod.registry().counter(
                    "integrity_violations")
                # Seed 11 is the sat-bearing world: the solver refutes 4
                # of the 8 partitions, so there ARE witnesses to corrupt
                # (seed 3's all-unsat map would make this cell vacuous).
                # Two extra knobs make the sats actually reach the SMT
                # tier: mega_chunks=0 routes stage0 through the dulled
                # chunk decode (the mega path runs the REAL stage0
                # kernels), and pgd_attack_decode is stubbed to find
                # nothing — the batched stage0 PGD pass would otherwise
                # settle every sat in-process, bypassing the solver
                # (near_abs > 50 also skips the slab refinement).
                int_smt_cfg0 = smt_cfg0.with_(mega_chunks=0)
                _saved_pgd = engine_mod.pgd_attack_decode
                engine_mod.pgd_attack_decode = (
                    lambda host, ctx, return_points=False:
                    ({}, None, np.full(4096, 1e9)))
                try:
                    int_smt_net = init_mlp(
                        (len(int_smt_cfg0.query().columns), 4, 1), seed=11)
                    int_smt_base = sweep_mod.verify_model(
                        int_smt_net, int_smt_cfg0.with_(
                            result_dir=os.path.join(
                                args.out, "int_smt_base")),
                        model_name="m", resume=False,
                        partition_span=smt_span)
                    int_smt_want = _vmap(int_smt_base)
                    spec = "smt.query:corrupt:1+"
                    cfg = int_smt_cfg0.with_(
                        result_dir=os.path.join(args.out, "int_smt"),
                        inject_faults=(spec,))
                    row = {"cell": "integrity/smt.query/run", "spec": spec,
                           "sat_in_base": sum(
                               1 for v in int_smt_want.values()
                               if v == "sat")}
                    v0 = int_viol.value(site="smt.query")
                    try:
                        rep = sweep_mod.verify_model(
                            int_smt_net, cfg, model_name="m", resume=False,
                            partition_span=smt_span)
                        got = _vmap(rep)
                        row["sdc_escaped"] = sum(
                            1 for k in got
                            if got[k] != "unknown"
                            and got[k] != int_smt_want[k])
                        row["detected"] = bool(
                            int_viol.value(site="smt.query") > v0)
                        recs, _sk = sweep_mod._read_ledger(
                            _ledger_path(cfg, rep.sink_name))
                        reasons = {r["failure"]["reason"] for r in recs
                                   if r.get("failure")}
                        row["reasons"] = sorted(reasons)
                        row["degraded"] = rep.degraded
                        resumed = sweep_mod.verify_model(
                            int_smt_net, cfg.with_(inject_faults=()),
                            model_name="m", resume=True,
                            partition_span=smt_span)
                        row["resume_converged"] = \
                            _vmap(resumed) == int_smt_want
                        row["ok"] = bool(
                            row["sat_in_base"] >= 1
                            and row["detected"]
                            and row["sdc_escaped"] == 0
                            and rep.degraded >= 1
                            and reasons == {"integrity.smt.query:fatal"}
                            and row["resume_converged"])
                    except BaseException as exc:
                        row["crashed"] = f"{type(exc).__name__}: {exc}"
                        row["ok"] = False
                    failures += 0 if row["ok"] else 1
                    print(json.dumps(row), flush=True)

                    # The same corruption inside the persistent server:
                    # the invalid witness surfaces in the deferred SMT
                    # drain, the replica goes suspect, and a disarmed
                    # resubmit converges.
                    if args.serve:
                        from fairify_tpu.resilience import \
                            faults as faults_lib
                        from fairify_tpu.serve import ServeConfig, \
                            VerificationServer

                        row = {"cell": "integrity/smt.query/serve",
                               "spec": spec}
                        rdir = os.path.join(args.out, "int_smt_serve")
                        try:
                            with faults_lib.armed((spec,),
                                                  seed=smt_cfg0.seed):
                                srv = VerificationServer(ServeConfig(
                                    batch_window_s=0.2, max_batch=2,
                                    smt_workers=1))
                                r1 = srv.submit(
                                    int_smt_cfg0.with_(result_dir=rdir),
                                    int_smt_net, "ma",
                                    partition_span=smt_span)
                                srv.start()
                                f1 = srv.wait(r1.id, timeout=900.0)
                                suspect = srv.suspect()
                                srv.drain()
                            got1 = {} if f1.report is None \
                                else _vmap(f1.report)
                            row["sdc_escaped"] = sum(
                                1 for p, v in got1.items()
                                if v != "unknown" and v != int_smt_want[p])
                            row["suspect"] = suspect
                            srv2 = VerificationServer(ServeConfig(
                                batch_window_s=0.2, max_batch=2,
                                smt_workers=1))
                            r2 = srv2.submit(
                                int_smt_cfg0.with_(result_dir=rdir),
                                int_smt_net, "ma", partition_span=smt_span)
                            srv2.start()
                            f2 = srv2.wait(r2.id, timeout=900.0)
                            srv2.drain()
                            row["resume_converged"] = bool(
                                f2.status == "done"
                                and _vmap(f2.report) == int_smt_want)
                            row["ok"] = bool(
                                f1.status == "done" and suspect
                                and row["sdc_escaped"] == 0
                                and row["resume_converged"])
                        except BaseException as exc:
                            row["crashed"] = f"{type(exc).__name__}: {exc}"
                            row["ok"] = False
                        failures += 0 if row["ok"] else 1
                        print(json.dumps(row), flush=True)
                finally:
                    engine_mod.pgd_attack_decode = _saved_pgd

            # Serve-mode smt cells: the same faults inside the persistent
            # server, two clients sharing the server-wide pool.
            if args.serve:
                from fairify_tpu.resilience import faults as faults_lib
                from fairify_tpu.serve import ServeConfig, VerificationServer

                for label, spec, absorbed in [
                        ("transient", "smt.worker.crash:transient:2", True),
                        ("exhausted", "smt.worker.crash:transient:2+", False)]:
                    row = {"cell": f"serve/smt.worker.crash/{label}",
                           "spec": spec}
                    rdir = os.path.join(args.out, f"serve_smt_{label}")
                    dirs = {"ma": os.path.join(rdir, "a"),
                            "mb": os.path.join(rdir, "b")}
                    try:
                        with faults_lib.armed((spec,), seed=smt_cfg0.seed):
                            srv = VerificationServer(ServeConfig(
                                batch_window_s=0.2, max_batch=4,
                                smt_workers=1))
                            ra = srv.submit(
                                smt_cfg0.with_(result_dir=dirs["ma"]),
                                smt_net, "ma", partition_span=smt_span)
                            rb = srv.submit(
                                smt_cfg0.with_(result_dir=dirs["mb"]),
                                smt_net, "mb", partition_span=smt_span)
                            srv.start()
                            fa = srv.wait(ra.id, timeout=900.0)
                            fb = srv.wait(rb.id, timeout=900.0)
                            srv.drain()
                    except BaseException as exc:  # the loop never crashes
                        row["crashed"] = f"{type(exc).__name__}: {exc}"
                        row["ok"] = False
                        failures += 1
                        print(json.dumps(row), flush=True)
                        continue
                    row["status"] = {"ma": fa.status, "mb": fb.status}
                    maps = {n_: ({} if r.report is None else _vmap(r.report))
                            for r, n_ in ((fa, "ma"), (fb, "mb"))}
                    decided_match = all(
                        maps[n_].get(p) == smt_want[p]
                        for n_ in maps for p in maps[n_]
                        if maps[n_][p] != "unknown")
                    row["decided_match"] = decided_match
                    if absorbed:
                        row["ok"] = bool(fa.status == fb.status == "done"
                                         and maps["ma"] == smt_want
                                         and maps["mb"] == smt_want)
                    else:
                        srv2 = VerificationServer(ServeConfig(
                            batch_window_s=0.2, max_batch=4, smt_workers=1))
                        r2a = srv2.submit(
                            smt_cfg0.with_(result_dir=dirs["ma"]), smt_net,
                            "ma", partition_span=smt_span)
                        r2b = srv2.submit(
                            smt_cfg0.with_(result_dir=dirs["mb"]), smt_net,
                            "mb", partition_span=smt_span)
                        srv2.start()
                        f2a = srv2.wait(r2a.id, timeout=900.0)
                        f2b = srv2.wait(r2b.id, timeout=900.0)
                        srv2.drain()
                        row["resume_converged"] = bool(
                            f2a.status == f2b.status == "done"
                            and _vmap(f2a.report) == smt_want
                            and _vmap(f2b.report) == smt_want)
                        row["ok"] = bool(decided_match
                                         and row["resume_converged"])
                    failures += 0 if row["ok"] else 1
                    print(json.dumps(row), flush=True)

            # Fleet cell: replica.lost:fatal MID-SMT-DRAIN.  A hang fault
            # wedges the first solver query for ~its hard deadline, which
            # parks the request on the owning replica's SMT drainer
            # (non-blocking smt_defer, ledger rows WITHHELD); killing that
            # replica while parked must lose nothing — failover re-homes
            # the request and the survivor's own pool re-solves on resume.
            if args.fleet:
                import time as time_mod

                from fairify_tpu.resilience import faults as faults_lib
                from fairify_tpu.serve import FleetConfig, ServeConfig, \
                    ServerFleet

                row = {"cell": "fleet/replica.lost/fatal/mid-smt-drain"}
                try:
                    fl = ServerFleet(FleetConfig(
                        n_replicas=2, poll_s=0.02,
                        replica=ServeConfig(batch_window_s=0.1, max_batch=4,
                                            smt_workers=1)))
                    rdir = os.path.join(args.out, "fleet_smtdrain")
                    with faults_lib.armed(("smt.worker.hang:transient:1",),
                                          seed=smt_cfg0.seed):
                        ra = fl.submit(
                            smt_cfg0.with_(result_dir=os.path.join(rdir,
                                                                   "a")),
                            smt_net, "ma", partition_span=smt_span)
                        fl.start()
                        parked = False
                        t0 = time_mod.monotonic()
                        while time_mod.monotonic() - t0 < 60.0:
                            cur = fl.get(ra.id)
                            if cur is not None and cur.status == "running" \
                                    and cur.report is not None:
                                parked = True
                                break
                            if cur is not None and cur.status in (
                                    "done", "failed", "rejected"):
                                break
                            time_mod.sleep(0.005)
                        owner = fl.owner_of(ra.id)
                    row["parked"] = parked
                    spec = f"replica.lost:fatal:{(owner or 0) + 1}"
                    row["spec"] = spec
                    with faults_lib.armed((spec,), seed=smt_cfg0.seed):
                        t0 = time_mod.monotonic()
                        while fl.replicas_alive() == 2 \
                                and time_mod.monotonic() - t0 < 30.0:
                            time_mod.sleep(0.005)
                    final = fl.wait(ra.id, timeout=900.0)
                    fl.drain()
                    got = {} if final is None or final.report is None \
                        else _vmap(final.report)
                    row["status"] = final.status if final else "?"
                    row["replicas_alive"] = fl.replicas_alive()
                    row["ok"] = bool(parked and final is not None
                                     and final.status == "done"
                                     and got == smt_want)
                except BaseException as exc:
                    row["crashed"] = f"{type(exc).__name__}: {exc}"
                    row["ok"] = False
                failures += 0 if row["ok"] else 1
                print(json.dumps(row), flush=True)
        finally:
            (sweep_mod._stage0_block_decode, engine_mod.decide_many,
             engine_mod.decide_box) = saved

    if args.lockprof:
        # The dynamic cross-check cell: every acquisition-order edge the
        # matrix actually exercised must be modeled by the static graph
        # (an unmodeled edge is a bug in analysis/locks.py), and no
        # static lock-order cycle may have fully manifested.
        from fairify_tpu.obs import lockprof

        lockprof.flush_events()
        rep = lockprof.check_against_static()
        row = {"cell": "lockprof", **rep.as_dict()}
        failures += 0 if rep.ok else 1
        print(json.dumps(row), flush=True)
        lockprof.uninstall()

    print(json.dumps({"cells_failed": failures}), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/bash
# Round-5 hard-tier program — VERDICT r4 #5/#6 + ADVICE r4 fixes, staged to
# fit a single-chip wall-clock budget.
#
# Reality check on VERDICT r4 #6's "most close in minutes": under the
# reference's attempt-until-budget semantics a row only *closes* early when
# its grid EXHAUSTS — true for the german/targeted grids (hundreds to
# thousands of boxes), never true for the stress/relaxed AC/BM grids
# (1M-3.3M boxes), which burn their full hard budget by design.  The full
# 15-preset grid at 3600 s/model is therefore ~76 chip-HOURS, not "~40 rows
# x minutes".  This queue spends the available chip time where the
# reference budget is *meaningful*:
#   A. scaled stress zoos (wider/deeper nets, VERDICT r4 #5) at 900 s/model
#      — the criterion is UNK=0 on >=2x wider nets, not budget size;
#   B. every EXHAUSTIBLE preset (german + targeted + compact grids) at the
#      reference's own budget (hard 3600, preset soft) — these genuinely
#      close, giving the literal "full program at reference budget" for
#      every row where that program terminates;
#   C. the inexhaustible stress/relaxed AC/BM grids: VERDICT-named models
#      first (stress at their correct soft 200 — ADVICE r4 #1), each a full
#      3600 s attempt-until-budget row, as many as wall clock allows.
# Rows not reached keep their r4-tier entries; VARIANTS.md's Budget column
# makes the tiers explicit per row.
set -u
cd "$(dirname "$0")/.." || exit 1

TAG="r5-$(git rev-parse --short HEAD 2>/dev/null || echo untagged)"
echo "=== hard tier r5, tag $TAG ($(date -u +%H:%M:%S)) ==="

echo "=== A: scaled stress zoos (900 s/model) ==="
# make is idempotent (skips existing .h5); guarantees the zoo exists on a
# fresh checkout before the run stage, which fails loudly on an empty zoo.
PYTHONUNBUFFERED=1 python scripts/scaled_stress.py make \
  || echo "!! scaled_stress make exited $?"
FAIRIFY_TPU_MODEL_ROOT="$PWD/models_scaled" PYTHONUNBUFFERED=1 \
  python scripts/scaled_stress.py run --hard 900 --tag "$TAG" \
  || echo "!! scaled_stress exited $?"

echo "=== B: exhaustible presets at the reference budget (hard 3600) ==="
PYTHONUNBUFFERED=1 python scripts/variants.py run --out variants \
  --hard 3600 --tag "$TAG" \
  --presets stress-GC,relaxed-GC,targeted-GC,targeted-AC,targeted-BM,targeted2-GC,targeted2-AC,targeted2-BM,targeted-DF \
  || echo "!! variants B exited $?"

echo "=== C: inexhaustible grids, VERDICT-named rows first (hard 3600) ==="
for entry in \
  "stress-BM BM-4,BM-11" \
  "stress-AC AC-1,AC-12" \
  "relaxed-AC AC-1" \
  "relaxed-BM BM-4" \
  "relaxed2-BM BM-4" \
  "relaxed3-BM BM-4" \
  "stress-BM BM-1,BM-2,BM-3,BM-5,BM-6,BM-7,BM-8,BM-9,BM-10,BM-12,BM-13" \
  "stress-AC AC-2,AC-3,AC-4,AC-5,AC-6,AC-7,AC-8,AC-9,AC-10,AC-11" \
  "relaxed-AC AC-2,AC-3,AC-4,AC-5,AC-6,AC-7,AC-8,AC-9,AC-10,AC-11,AC-12" \
  "relaxed-BM BM-1,BM-2,BM-3,BM-5,BM-6,BM-7,BM-8,BM-9,BM-10,BM-11,BM-12,BM-13" \
  ; do
  preset=${entry%% *}
  models=${entry#* }
  echo "--- C: $preset $models ($(date -u +%H:%M:%S)) ---"
  PYTHONUNBUFFERED=1 python scripts/variants.py run --out variants \
    --hard 3600 --tag "$TAG" --presets "$preset" --models "$models" \
    || echo "!! $preset $models exited $?"
done
echo "=== hard tier r5 complete ($(date -u +%H:%M:%S)) ==="

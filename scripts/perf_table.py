"""Aggregate per-run ``*.throughput.json`` records into ``PERF.md``.

Every sweep writes a throughput record (decided counts, partitions/sec/chip,
and — since round 2 — per-phase wall-clock); this renders them into one
performance table so per-preset throughput and the fixed-cost outliers are
visible in the repo instead of buried in result dirs.

Usage: python scripts/perf_table.py [--dirs parity,variants] [--out PERF.md]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
from _sweeplib import model_natkey  # noqa: E402


def collect(dirs):
    rows = []
    for d in dirs:
        for path in glob.glob(os.path.join(d, "**", "*.throughput.json"),
                              recursive=True):
            try:
                rec = json.load(open(path))
            except json.JSONDecodeError:
                continue
            fname = os.path.basename(path)[: -len(".throughput.json")]
            # <preset>-<model>[@span]; the greedy prefix makes the LAST
            # family-pattern match the model (e.g. "targeted2-GC-GC-3" →
            # preset targeted2-GC, model GC-3).
            m = re.match(r"^(.*)-((?:a)?(?:GC|AC|BM|CP|DF|LSAC)-.+)$", fname)
            preset, model = (m.group(1), m.group(2)) if m else ("?", fname)
            if rec.get("decided", 0) + rec.get("unknown", 0) == 0:
                continue  # resume/bookkeeping pass: nothing newly decided
            rec["_preset"] = preset
            rec["_model"] = model
            rec["_dir"] = os.path.relpath(os.path.dirname(path), ROOT)
            rows.append(rec)
    return rows


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dirs", default="parity,variants")
    ap.add_argument("--out", default=os.path.join(ROOT, "PERF.md"))
    args = ap.parse_args()
    rows = collect([os.path.join(ROOT, d) for d in args.dirs.split(",")])
    rows.sort(key=lambda r: (r["_dir"], r["_preset"], model_natkey(r["_model"])))

    lines = [
        "# PERF — per-run throughput (one chip)",
        "",
        "Rendered by `scripts/perf_table.py` from the `*.throughput.json` "
        "records every sweep writes.  `s/part` is wall time over attempted "
        "partitions **including one-time XLA compile** for the first model "
        "of an architecture in a cold-cache process — the persistent "
        "compilation cache (`utils/cache.py`) makes subsequent models and "
        "runs pay ~0 compile (e.g. round-1 DF-1 48.8 s cold vs DF-2..11 "
        "≈3.5 s warm on identical 8-box grids).  `st0%` = share of decided "
        "partitions settled by the whole-grid stage-0 kernels (the rest "
        "went through branch-and-bound).",
        "",
        "| Run | Model | Decided | UNK | parts/s/chip | s/part | st0% | "
        "pipe (max/mean) | compile | slowest phase |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    worst = []
    for r in rows:
        n = r["decided"] + r["unknown"]
        spp = r["elapsed_s"] / max(n, 1)
        st0 = 100.0 * r["stage0_decided"] / max(r["decided"], 1)
        phases = r.get("phases_s") or {}
        slow = max(phases.items(), key=lambda kv: kv[1])[0] if phases else "—"
        if phases:
            slow = f"{slow} ({phases[slow]:.1f}s)"
        # Async-pipeline overlap: configured depth plus the max and
        # time-weighted-mean launches actually in flight (absent on
        # records written before the pipeline existed).
        if "pipeline_depth" in r:
            pipe = (f"d{r['pipeline_depth']} "
                    f"{r.get('launches_in_flight_max', 0)}/"
                    f"{r.get('launches_in_flight_mean', 0.0):.2f}")
        else:
            pipe = "—"
        # Per-run XLA compile record (obs.compile; absent on records
        # written before the compile registry existed).  A warm run shows
        # 0×/0.0s — nonzero n_compiles on a warm row is shape churn.
        if "n_compiles" in r:
            comp = f"{r['n_compiles']}x {r.get('compile_s', 0.0):.1f}s"
        else:
            comp = "—"
        lines.append(
            f"| {r['_dir']}/{r['_preset']} | {r['_model']} | {r['decided']} | "
            f"{r['unknown']} | {r['partitions_per_sec_per_chip']:.3f} | "
            f"{spp:.3f} | {st0:.0f} | {pipe} | {comp} | {slow} |")
        worst.append((spp, f"{r['_preset']}/{r['_model']}"))
    if not rows:
        lines.append("| *(no records yet)* | | | | | | | | | |")
    else:
        worst.sort(reverse=True)
        lines += [
            "",
            "Worst s/part rows: " + ", ".join(
                f"{name} ({spp:.2f}s)" for spp, name in worst[:5]) + ".",
            "",
            "Outlier s/part rows are artifacts of tiny denominators, not "
            "slow kernels: UNKNOWN-retry passes re-enter a model to decide "
            "a handful of leftover partitions (full stage-0 amortized over "
            "single-digit newly-decided counts), and the first model of an "
            "architecture in a cold process pays one-time XLA compile — "
            "now a recorded number (the `compile` column / PERF.md's "
            "cold-compile re-measurement: 61-81% of a cold run's wall is "
            "compile_s).  Whole-grid rows for the same architectures run "
            "orders of magnitude faster per partition (see the main table).",
        ]

    # Multi-device scaling record (audits/scaling_r4.json, scripts/scaling.py).
    sc_path = os.path.join(ROOT, "audits", "scaling_r4.json")
    if not os.path.isfile(sc_path):
        sc_path = os.path.join(ROOT, "audits", "scaling_r3.json")
    if os.path.isfile(sc_path):
        sc = json.load(open(sc_path))
        lines += [
            "",
            "## Multi-device sharding record (virtual CPU mesh)",
            "",
            f"Kernel: {sc['kernel']}; grid: {sc['grid']}.  " + sc["caveat"],
            "",
            "| Devices | Parts/device | Wall (s) | Overhead vs 1 dev | "
            "Decided (invariant) | Input MB/device | HLO collectives |",
            "|---|---|---|---|---|---|---|",
        ]
        for r in sc["rows"]:
            mb = r.get("input_mb_per_device")
            mb_cell = f"{mb:.3f}" if mb is not None else "—"
            colls = r.get("hlo_collectives")
            coll_cell = str(sum(colls.values())) if colls else "—"
            lines.append(
                f"| {r['devices']} | {r['parts_per_device']} | "
                f"{r['best_s']:.2f} | {r['overhead_vs_1dev']:.2f}× | "
                f"{r['decided']} | {mb_cell} | {coll_cell} |")
    with open(args.out, "w") as fp:
        fp.write("\n".join(lines) + "\n")
    print(f"wrote {args.out} ({len(rows)} rows)")


if __name__ == "__main__":
    main()

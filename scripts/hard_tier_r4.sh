#!/bin/bash
# Hard-tier (reference-budget) re-runs of the slow variant rows — VERDICT r3 #2.
#
# Each entry re-runs a (preset, models) group at the reference's own budget
# (soft 100 s / hard 3600 s, `INSTALL.md:45-71`) with the round-4 engine.
# The resume key in variants/results.jsonl carries the budget tier, so these
# append fresh 3600 s rows next to the existing 120 s rows instead of
# resuming past them.  Order: the rows VERDICT r3 named first, then the
# remaining dec/s < 5 rows.
set -u
cd "$(dirname "$0")/.."

QUEUE=(
  "stress-GC GC-5"
  "stress-BM BM-4,BM-11"
  "stress-AC AC-1,AC-12"
  "relaxed-GC GC-5"
  "relaxed-AC AC-1"
  "relaxed-BM BM-4,BM-11"
  "targeted-GC GC-5"
  "targeted-AC AC-8"
  "targeted2-AC AC-1,AC-8"
  "targeted2-BM BM-4,BM-7,BM-11"
)

for entry in "${QUEUE[@]}"; do
  preset=${entry%% *}
  models=${entry#* }
  echo "=== hard tier: $preset $models ($(date -u +%H:%M:%S)) ==="
  PYTHONUNBUFFERED=1 python scripts/variants.py run --out variants \
    --soft 100 --hard 3600 --presets "$preset" --models "$models" \
    || echo "!! $preset $models exited $?"
done
echo "=== hard tier queue complete ($(date -u +%H:%M:%S)) ==="

"""Per-phase attribution profile of the hard-root slow tail (VERDICT r3 #1).

The round-3 PERF table shows a few roots (AC-4 both PAs, BM-4, BM-9,
AC-2-sex, GC-5) running 15-31 s/partition — three to four orders of
magnitude above the grid norm — with nothing recording *where inside the
engine ladder* (Phase S sign-BaB / L sign-LP / input-split pair BaB /
P pair-LP / E lattice) those seconds land.  This harness samples each
model's stage-0 leftovers, runs :func:`engine.decide_many` with the
per-phase cost attribution added in round 4 (``Decision.stats``), and
writes ``audits/profile_r4.json``: per model, the phase-second totals,
verdict counts, and the slowest sampled roots with their phase split.

Usage: python scripts/profile_phases.py [--sample 48] [--deadline 240]
                                        [--targets AC-sex:AC-4,...]
                                        [--out audits/profile_r4.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# (run_id, preset, config overrides, model) — the round-3 slow-tail rows.
TARGETS = [
    ("AC-sex", "AC", {}, "AC-4"),
    ("AC-race", "AC", {"protected": ("race",)}, "AC-4"),
    ("AC-sex", "AC", {}, "AC-2"),
    ("BM-age", "BM", {}, "BM-4"),
    ("BM-age", "BM", {}, "BM-9"),
    ("GC-age", "GC", {}, "GC-5"),
]

PHASES = ("t_attack", "t_sign", "t_lp", "t_bab", "t_pair", "t_lattice")


def profile_target(run_id, preset_name, overrides, model, sample, deadline):
    from fairify_tpu.data import loaders
    from fairify_tpu.models import zoo
    from fairify_tpu.verify import engine, presets, sweep
    from fairify_tpu.verify.property import encode

    cfg = presets.get(preset_name).with_(**overrides)
    dataset = loaders.load(cfg.dataset)
    n_attrs = len(cfg.query().columns)
    nets, _ = zoo.load_matching(cfg.dataset, n_attrs, models=(model,))
    net = nets[model]
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)

    t0 = time.perf_counter()
    unsat0, sat0, _ = sweep._stage0_certify_and_attack(net, enc, lo, hi, cfg)
    stage0_s = time.perf_counter() - t0
    pending = np.where(~unsat0 & ~sat0)[0]
    sampled = pending[:sample]
    rec = {
        "run_id": run_id, "model": model,
        "grid": int(lo.shape[0]), "stage0_leftover": int(pending.size),
        "stage0_s": round(stage0_s, 2),
        "sampled": int(sampled.size), "deadline_s": deadline,
    }
    if not sampled.size:
        rec["note"] = "stage-0 decided everything; no hard roots to profile"
        return rec

    t1 = time.perf_counter()
    decisions = engine.decide_many(
        net, enc, lo[sampled], hi[sampled], cfg.engine, deadline_s=deadline)
    wall = time.perf_counter() - t1

    counts = {"sat": 0, "unsat": 0, "unknown": 0}
    totals = {p: 0.0 for p in PHASES}
    roots = []
    for r, d in enumerate(decisions):
        counts[d.verdict] += 1
        for p in PHASES:
            totals[p] += d.stats.get(p, 0.0)
        roots.append({
            "root": int(sampled[r]), "verdict": d.verdict,
            "elapsed_s": round(d.elapsed_s, 3), "nodes": d.nodes,
            **{p: round(d.stats.get(p, 0.0), 3) for p in PHASES}})
    roots.sort(key=lambda x: -x["elapsed_s"])
    dominant = max(totals, key=totals.get)
    rec.update({
        "wall_s": round(wall, 2), "verdicts": counts,
        "s_per_part": round(wall / sampled.size, 3),
        "phase_totals_s": {p: round(v, 2) for p, v in totals.items()},
        "dominant_phase": dominant,
        "slowest_roots": roots[:8],
    })
    return rec


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sample", type=int, default=48)
    ap.add_argument("--deadline", type=float, default=240.0)
    ap.add_argument("--targets", default="")
    ap.add_argument("--out", default=os.path.join(ROOT, "audits", "profile_r4.json"))
    args = ap.parse_args()

    wanted = None
    if args.targets:
        wanted = {tuple(t.split(":")) for t in args.targets.split(",")}
    out = {"what": ("Per-phase second attribution for the round-3 slow-tail "
                    "rows: engine.decide_many on a sample of each model's "
                    "stage-0 leftovers, with Decision.stats phase splits "
                    "(S=sign frontier, L=sign host LP, bab=input split, "
                    "P=pair LP, E=lattice)."),
           "script": "scripts/profile_phases.py",
           "records": []}
    for run_id, preset, overrides, model in TARGETS:
        if wanted is not None and (run_id, model) not in wanted:
            continue
        print(f"== profiling {run_id}/{model}", flush=True)
        rec = profile_target(run_id, preset, overrides, model,
                             args.sample, args.deadline)
        print(json.dumps(rec, indent=None), flush=True)
        out["records"].append(rec)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fp:
        json.dump(out, fp, indent=2)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()

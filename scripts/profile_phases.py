"""Per-phase attribution profile of the hard-root slow tail (VERDICT r3 #1).

Rebuilt on the obs event log.  The original harness predated
``fairify_tpu.obs`` and double-instrumented the engine ladder: hand-rolled
``time.perf_counter()`` timers in this script next to ``Decision.stats``
inside the engine, with no shared source of truth.  The engine now emits
spans on the active tracer (``engine.attack``, ``engine.sign_bab``,
``engine.bab``, ``engine.pair_lp``, ``engine.lattice`` /
``engine.lattice_first``), so this harness owns a tracer per target, runs
the same stage-0-leftover sample through :func:`engine.decide_many`, and
aggregates the phase seconds from the span records — the same records
``fairify_tpu report`` reads.  The raw per-target event logs are kept next
to ``--out`` for drill-down (Chrome-trace exports included).

For the sweep-wide "where do boxes die?" view prefer
``fairify_tpu report --funnel`` (DESIGN.md §20); this script remains for
targeted hard-root sampling on the known slow-tail rows.

Usage: python scripts/profile_phases.py [--sample 48] [--deadline 240]
                                        [--targets AC-sex:AC-4,...]
                                        [--out audits/profile_r4.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# (run_id, preset, config overrides, model) — the round-3 slow-tail rows.
TARGETS = [
    ("AC-sex", "AC", {}, "AC-4"),
    ("AC-race", "AC", {"protected": ("race",)}, "AC-4"),
    ("AC-sex", "AC", {}, "AC-2"),
    ("BM-age", "BM", {}, "BM-4"),
    ("BM-age", "BM", {}, "BM-9"),
    ("GC-age", "GC", {}, "GC-5"),
]

# Engine ladder span -> reported phase bucket.  ``engine.sign_bab`` covers
# Phase S including its host LP relaxations; the two lattice spans (first
# pass over cheap roots, full pass over survivors) fold into one bucket.
PHASE_SPANS = {
    "engine.attack": "attack",
    "engine.sign_bab": "sign_bab",
    "engine.bab": "bab",
    "engine.pair_lp": "pair_lp",
    "engine.lattice": "lattice",
    "engine.lattice_first": "lattice",
}
PHASES = tuple(dict.fromkeys(PHASE_SPANS.values()))


def _aggregate_spans(trace_path):
    """Phase-second totals + wall markers from one target's event log."""
    from fairify_tpu import obs

    totals = {p: 0.0 for p in PHASES}
    marks = {}
    for rec in obs.load_events(trace_path):
        if rec.get("type") != "span":
            continue
        dur = float(rec.get("dur_s") or 0.0)
        name = rec.get("name")
        if name in ("stage0_decide", "profile.decide_many"):
            marks[name] = marks.get(name, 0.0) + dur
        phase = PHASE_SPANS.get(name)
        if phase is not None:
            totals[phase] += dur
    return totals, marks


def profile_target(run_id, preset_name, overrides, model, sample, deadline,
                   trace_path):
    from fairify_tpu import obs
    from fairify_tpu.data import loaders
    from fairify_tpu.models import zoo
    from fairify_tpu.verify import engine, presets, sweep
    from fairify_tpu.verify.property import encode

    cfg = presets.get(preset_name).with_(**overrides)
    dataset = loaders.load(cfg.dataset)
    n_attrs = len(cfg.query().columns)
    nets, _ = zoo.load_matching(cfg.dataset, n_attrs, models=(model,))
    net = nets[model]
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)

    with obs.tracing(trace_path, run_id=f"profile-{run_id}-{model}"):
        with obs.span("stage0_decide", partitions=int(lo.shape[0])):
            unsat0, sat0, _ = sweep._stage0_certify_and_attack(
                net, enc, lo, hi, cfg)
        pending = np.where(~unsat0 & ~sat0)[0]
        sampled = pending[:sample]
        decisions = []
        if sampled.size:
            with obs.span("profile.decide_many", roots=int(sampled.size)):
                decisions = engine.decide_many(
                    net, enc, lo[sampled], hi[sampled], cfg.engine,
                    deadline_s=deadline)

    totals, marks = _aggregate_spans(trace_path)
    rec = {
        "run_id": run_id, "model": model,
        "grid": int(lo.shape[0]), "stage0_leftover": int(pending.size),
        "stage0_s": round(marks.get("stage0_decide", 0.0), 2),
        "sampled": int(sampled.size), "deadline_s": deadline,
        "trace": os.path.relpath(trace_path, ROOT),
    }
    if not sampled.size:
        rec["note"] = "stage-0 decided everything; no hard roots to profile"
        return rec

    counts = {"sat": 0, "unsat": 0, "unknown": 0}
    roots = []
    for r, d in enumerate(decisions):
        counts[d.verdict] += 1
        roots.append({
            "root": int(sampled[r]), "verdict": d.verdict,
            "reason": d.reason,
            "elapsed_s": round(d.elapsed_s, 3), "nodes": d.nodes})
    roots.sort(key=lambda x: -x["elapsed_s"])
    wall = marks.get("profile.decide_many", 0.0)
    dominant = max(totals, key=totals.get)
    rec.update({
        "wall_s": round(wall, 2), "verdicts": counts,
        "s_per_part": round(wall / sampled.size, 3),
        "phase_totals_s": {p: round(v, 2) for p, v in totals.items()},
        "dominant_phase": dominant,
        "slowest_roots": roots[:8],
    })
    return rec


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sample", type=int, default=48)
    ap.add_argument("--deadline", type=float, default=240.0)
    ap.add_argument("--targets", default="")
    ap.add_argument("--out", default=os.path.join(ROOT, "audits", "profile_r4.json"))
    args = ap.parse_args()

    wanted = None
    if args.targets:
        wanted = {tuple(t.split(":")) for t in args.targets.split(",")}
    trace_dir = os.path.join(os.path.dirname(os.path.abspath(args.out)),
                             "profile_phases_traces")
    os.makedirs(trace_dir, exist_ok=True)
    out = {"what": ("Per-phase second attribution for the round-3 slow-tail "
                    "rows: engine.decide_many on a sample of each model's "
                    "stage-0 leftovers, phase seconds aggregated from the "
                    "obs event-log spans (engine.attack / engine.sign_bab / "
                    "engine.bab / engine.pair_lp / engine.lattice*)."),
           "script": "scripts/profile_phases.py",
           "records": []}
    print("note: for sweep-wide attribution use `fairify_tpu report "
          "--funnel` on a --trace-out event log", file=sys.stderr)
    for run_id, preset, overrides, model in TARGETS:
        if wanted is not None and (run_id, model) not in wanted:
            continue
        print(f"== profiling {run_id}/{model}", flush=True)
        trace_path = os.path.join(trace_dir, f"{run_id}_{model}.jsonl")
        rec = profile_target(run_id, preset, overrides, model,
                             args.sample, args.deadline, trace_path)
        print(json.dumps(rec, indent=None), flush=True)
        out["records"].append(rec)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fp:
        json.dump(out, fp, indent=2)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()

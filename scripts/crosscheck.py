"""Adversarial audit of UNSAT verdicts in a sweep ledger.

For every partition a ledger records as UNSAT, mount an independent attack
(dense random sampling + multi-restart PGD, both exact-validated) and
report any counterexample found — which would disprove the certificate.

This is the cross-check used to adjudicate count differences against the
reference's published Table V rows: the reference's heuristic-prune path is
*unsound* (``utils/prune.py:862-939`` deletes unproven neurons before the
final Z3 query), so its SAT/UNSAT totals on rows with #HS > 0 are not
ground truth; this framework's UNSAT certificates are refutable by attack,
and SAT pairs are exact-replay-validated.

Usage:
    python scripts/crosscheck.py <preset> <model> <ledger.jsonl>
        [--samples 1024] [--restarts 8] [--pa attr]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("preset")
    ap.add_argument("model")
    ap.add_argument("ledger")
    ap.add_argument("--samples", type=int, default=1024)
    ap.add_argument("--restarts", type=int, default=8)
    ap.add_argument("--pa", default=None,
                    help="override the preset's protected attribute")
    args = ap.parse_args()

    import jax.numpy as jnp
    import numpy as np

    from fairify_tpu.models import zoo
    from fairify_tpu.verify import engine, presets, sweep
    from fairify_tpu.verify.property import encode, role_boxes

    cfg = presets.get(args.preset)
    if args.pa:
        cfg = cfg.with_(protected=(args.pa,))
    net = zoo.load(cfg.dataset, args.model)
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)

    ledger = sweep._load_ledger(args.ledger)
    unsat = np.array(sorted(pid - 1 for pid, rec in ledger.items()
                            if rec["verdict"] == "unsat"))
    print(f"auditing {len(unsat)} UNSAT partitions of {args.model} "
          f"({args.samples} samples + {args.restarts}x40 PGD each)",
          file=sys.stderr)

    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    rng = np.random.default_rng(12345)
    refuted = {}
    for start in range(0, len(unsat), 64):
        blk = unsat[start:start + 64]
        for k, ce in engine.pgd_attack(net, enc, lo[blk], hi[blk], rng,
                                       steps=40, restarts=args.restarts).items():
            refuted[int(blk[k])] = ce
        xr, pr = engine.build_attack_candidates(enc, rng, lo[blk], hi[blk],
                                                args.samples)
        lx, lp = engine._attack_logits(net, jnp.asarray(xr), jnp.asarray(pr))
        *_, valid = role_boxes(enc, lo[blk].astype(np.float32),
                               hi[blk].astype(np.float32))
        found, wit = engine.find_flips(enc, np.asarray(lx), np.asarray(lp), valid)
        for k, ce in engine.extract_witnesses(
                found, wit, xr, pr, weights, biases).items():
            refuted[int(blk[k])] = ce

    out = {"model": args.model, "preset": args.preset,
           "unsat_audited": int(len(unsat)), "refuted": len(refuted),
           "refuted_partitions": sorted(p + 1 for p in refuted)}
    print(json.dumps(out))
    return 1 if refuted else 0


if __name__ == "__main__":
    raise SystemExit(main())

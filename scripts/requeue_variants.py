"""Re-run variant rows whose attempted prefix still holds UNKNOWNs.

Round-2 rows were recorded before the round-3 engine (LP sign BaB) and
before the budget-truncation retry pass in ``_sweeplib``; their in-prefix
UNK counts are stale engine failures.  This driver removes exactly those
rows (results.jsonl entries + their per-config span ledgers) and re-runs
them at the same budget tier, so the re-rendered VARIANTS.md compares like
budgets with the current engine.

Usage: python scripts/requeue_variants.py [--out variants] [--exclude
       stress-AC:AC-3,...]  (excluded rows are left for a deeper tier)
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "scripts"))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="variants")
    ap.add_argument("--exclude", default="",
                    help="comma list of preset:model rows to leave alone")
    ap.add_argument("--presets", default="",
                    help="comma list restricting which presets to requeue "
                         "(parallel workers split the preset space)")
    ap.add_argument("--max-rows", type=int, default=10000)
    args = ap.parse_args()

    from _sweeplib import run_and_record_budgeted
    from fairify_tpu.verify import presets

    excl = set(tuple(x.split(":")) for x in args.exclude.split(",") if x)
    results_path = os.path.join(args.out, "results.jsonl")
    with open(results_path) as fp:
        recs = [json.loads(line) for line in fp]

    # Latest record per (run, model, budget) wins; requeue rows with UNK.
    latest = {}
    for r in recs:
        if "skipped" in r or "attempted" not in r:
            continue
        latest[(r["run_id"], r["model"], r["soft_s"], r["hard_s"])] = r
    wanted = set(args.presets.split(",")) if args.presets else None
    todo = [k for k, r in latest.items()
            if r["unknown"] > 0 and (k[0], k[1]) not in excl
            and (wanted is None or k[0] in wanted)]
    todo = todo[: args.max_rows]
    print(f"{len(todo)} rows to requeue", flush=True)

    keep = [r for r in recs
            if not (("attempted" in r) and "skipped" not in r
                    and (r["run_id"], r["model"], r["soft_s"], r["hard_s"]) in set(todo))]
    with open(results_path, "w") as fp:
        for r in keep:
            fp.write(json.dumps(r) + "\n")

    by_cfg: dict = {}
    for run_id, model, soft, hard in todo:
        # Remove the stale span artifacts so the re-run re-decides:
        # ledgers are "{cfg.name}-{model}@{span}.ledger.jsonl", CSVs are
        # span-qualified sink names "{model}@{span}[.csv|-counterexamples
        # .csv]" (sweep.verify_model with partition_span).
        led_dir = os.path.join(args.out, run_id, f"b{soft:g}-{hard:g}")
        for p in glob.glob(os.path.join(led_dir, f"*-{model}@*")):
            os.remove(p)
        for p in glob.glob(os.path.join(led_dir, f"{model}@*")):
            os.remove(p)
        by_cfg.setdefault((run_id, soft, hard), []).append(model)

    for (run_id, soft, hard), models in sorted(by_cfg.items()):
        cfg = presets.get(run_id).with_(
            soft_timeout_s=soft, hard_timeout_s=hard,
            result_dir=os.path.join(args.out, run_id))
        run_and_record_budgeted(cfg, run_id, results_path,
                                model_filter=set(models))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Observability lint: timing/progress in ``fairify_tpu/`` must use the obs layer.

Fast AST-based check (no imports of the package, runs in milliseconds; wired
into the tier-1 test run via ``tests/test_observability.py``).  Two rules:

* **No raw ``time.time()``** — wall-clock subtraction for timing belongs in
  ``PhaseTimer`` / obs spans (monotonic clocks, rounding only at
  serialization).  The one sanctioned caller is the obs layer's own clock
  shim (``obs/trace.py``, wall-clock span timestamps).
* **No bare ``print()``** for timing/progress — progress lines go through
  ``obs.heartbeat`` (throttled) and structured results through the event
  log.  Allowlisted: the CLI and report renderer (user-facing output is
  their job), the heartbeat itself, and two legacy shims that predate the
  obs layer (``verify/sweep.py``'s stderr skip warning,
  ``verify/exact_check.py``'s debug prints — shrink, don't grow, this list).

AST-based, so docstrings/comments mentioning the patterns don't trip it.
``scripts/`` and ``tests/`` are out of scope: the rule protects the
library's hot paths, not one-off harnesses.
"""
from __future__ import annotations

import ast
import os
import sys

# Paths are repo-relative, '/'-separated.
ALLOW_TIME_TIME = {
    "fairify_tpu/obs/trace.py",  # the obs layer's wall-clock shim
}
ALLOW_PRINT = {
    "fairify_tpu/cli.py",            # user-facing command output
    "fairify_tpu/obs/heartbeat.py",  # the sanctioned progress line
    "fairify_tpu/obs/report.py",     # report renderer (CLI body)
    "fairify_tpu/verify/sweep.py",   # legacy: stderr width-mismatch warning
    "fairify_tpu/verify/exact_check.py",  # legacy: gated debug prints
}


def _is_time_time(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "time"
            and isinstance(f.value, ast.Name) and f.value.id == "time")


def _is_print(node: ast.Call) -> bool:
    return isinstance(node.func, ast.Name) and node.func.id == "print"


def check_file(path: str, rel: str) -> list:
    with open(path) as fp:
        src = fp.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as exc:
        return [f"{rel}:{exc.lineno}: syntax error: {exc.msg}"]
    errors = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_time_time(node) and rel not in ALLOW_TIME_TIME:
            errors.append(
                f"{rel}:{node.lineno}: raw time.time() — use "
                f"time.perf_counter() via PhaseTimer/obs spans "
                f"(or extend ALLOW_TIME_TIME for a sanctioned shim)")
        elif _is_print(node) and rel not in ALLOW_PRINT:
            errors.append(
                f"{rel}:{node.lineno}: bare print() — progress goes through "
                f"fairify_tpu.obs.heartbeat, structured output through the "
                f"event log (or extend ALLOW_PRINT for user-facing output)")
    return errors


def main(argv=None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(root, "fairify_tpu")
    errors = []
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            errors.extend(check_file(path, rel))
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"lint_obs: {len(errors)} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

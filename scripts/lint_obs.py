#!/usr/bin/env python
"""Observability lint: timing/progress in ``fairify_tpu/`` must use the obs layer.

Fast AST-based check (no imports of the package, runs in milliseconds; wired
into the tier-1 test run via ``tests/test_observability.py``).  Two rules:

* **No raw ``time.time()``** — wall-clock subtraction for timing belongs in
  ``PhaseTimer`` / obs spans (monotonic clocks, rounding only at
  serialization).  The one sanctioned caller is the obs layer's own clock
  shim (``obs/trace.py``, wall-clock span timestamps).
* **No bare ``print()``** for timing/progress — progress lines go through
  ``obs.heartbeat`` (throttled) and structured results through the event
  log.  Allowlisted: the CLI and report renderer (user-facing output is
  their job), the heartbeat itself, and two legacy shims that predate the
  obs layer (``verify/sweep.py``'s stderr skip warning,
  ``verify/exact_check.py``'s debug prints — shrink, don't grow, this list).
* **No bare ``jax.jit`` in ``fairify_tpu/verify/`` or ``fairify_tpu/ops/``**
  — device kernels in the verification core must register through
  ``fairify_tpu.obs.compile.obs_jit`` so every compile is named, counted,
  timed, and cost/memory-analyzed.  An unregistered ``jax.jit`` (bare
  decorator, ``jax.jit(...)`` call, or ``partial(jax.jit, ...)``) is a
  blind spot: its recompiles from shape/static churn are exactly the
  ~110 ms-to-tens-of-seconds stalls the compile registry exists to
  attribute.  The allowlist (``ALLOW_RAW_JIT``, repo-relative file paths)
  names reviewed exceptions — currently empty; shrink, don't grow, it.
* **No silently-swallowed broad excepts in ``fairify_tpu/``** — a bare
  ``except:`` / ``except Exception`` / ``except BaseException`` whose body
  never re-raises swallows exactly the faults the resilience layer
  (``fairify_tpu/resilience``) exists to classify, retry, and degrade
  with a recorded reason.  Handlers that conditionally re-raise (after
  ``resilience.supervisor.classify``) pass; the reviewed swallow sites
  (compile fallback, import gates) live in ``ALLOW_BROAD_EXCEPT``.
* **No synchronous device fetch in ``fairify_tpu/verify/`` loops** —
  ``np.asarray(...)`` / ``jax.device_get(...)`` / ``.block_until_ready()``
  inside a ``for``/``while`` body stalls the launch queue exactly where
  the async pipeline (``parallel/pipeline.py``) exists to keep it full;
  chunk loops must submit through a :class:`LaunchPipeline` and convert
  only at dequeue.  The allowlist (``ALLOW_LOOP_FETCH``, keyed
  ``file::function``) names the remaining legitimate sync points — drain-
  API decode bodies, sequentially-dependent BaB iterations, single-
  partition retries — each with its reason.  Shrink, don't grow, it.
  Deliberately NOT matched: ``np.array`` (22 in-tree uses are host list
  construction; flagging them would bury the signal) — a reviewer must
  still catch ``np.array(device_array)``, as with any other blocking
  read (``float(x)``, ``int(x)``) the AST can't distinguish.

AST-based, so docstrings/comments mentioning the patterns don't trip it.
``scripts/`` and ``tests/`` are out of scope: the rule protects the
library's hot paths, not one-off harnesses.
"""
from __future__ import annotations

import ast
import os
import sys

# Paths are repo-relative, '/'-separated.
ALLOW_TIME_TIME = {
    "fairify_tpu/obs/trace.py",  # the obs layer's wall-clock shim
}
ALLOW_PRINT = {
    "fairify_tpu/cli.py",            # user-facing command output
    "fairify_tpu/obs/heartbeat.py",  # the sanctioned progress line
    "fairify_tpu/obs/report.py",     # report renderer (CLI body)
    "fairify_tpu/verify/sweep.py",   # legacy: stderr width-mismatch warning
    "fairify_tpu/verify/exact_check.py",  # legacy: gated debug prints
}

# Raw-jit rule scope: every device kernel of the verification core must go
# through obs.compile.obs_jit (named compile spans, recompile accounting).
RAW_JIT_SCOPE = ("fairify_tpu/verify/", "fairify_tpu/ops/")
# Repo-relative file paths reviewed as legitimate bare-jit users.  Empty:
# the whole core is migrated; a new entry needs a reason in review.
ALLOW_RAW_JIT: set = set()

# Hot-loop fetch rule scope: chunk/frontier loops of the verification core.
LOOP_FETCH_SCOPE = "fairify_tpu/verify/"
# ``file::function`` sync points reviewed as legitimate.  Everything else in
# a verify/ loop must route through parallel.pipeline.LaunchPipeline.
ALLOW_LOOP_FETCH = {
    # Drain-API decode bodies: the pipeline hands them HOST payloads; the
    # remaining np.asarray calls pull already-materialized model weights.
    "fairify_tpu/verify/sweep.py::_family_block_decode",
    # Per-partition heuristic-retry re-sim: one tiny launch whose result
    # this row's CSV needs immediately — scoped to its own helper so the
    # sweep's main loop body stays under the lint.
    "fairify_tpu/verify/sweep.py::_parity_resim",
    # BaB frontier iterations are sequentially dependent (each batch's
    # branching decides the next batch) — no independent work to overlap.
    "fairify_tpu/verify/engine.py::decide_many",
    "fairify_tpu/verify/engine.py::uniform_sign_bab",
    "fairify_tpu/verify/engine.py::_run_lp_phase",
    # Sound-prune chunk results feed the immediately-following host mask
    # assembly per chunk; candidate for pipelining, not yet converted.
    "fairify_tpu/verify/pruning.py::sound_prune_grid",
    "fairify_tpu/verify/exact_check.py::exact_certify_grid",
    # Pure-host numpy coercions of weights/points inside exact/LP/SMT
    # loops — ``np.asarray`` on data that never lived on device.
    "fairify_tpu/verify/engine.py::exact_logit_sign",
    "fairify_tpu/verify/engine.py::_leaf_sign_lp",
    "fairify_tpu/verify/engine.py::_eligible_lattice_roots",
    "fairify_tpu/verify/smt.py::_z3_net",
    # Per-root host phases (lattice enumeration / pair LP): independent
    # roots, so genuine pipelining candidates — not yet converted; the
    # fetched payloads feed immediately-following serial host solvers.
    "fairify_tpu/verify/engine.py::_lattice_phase",
    "fairify_tpu/verify/engine.py::_pair_lp_phase",
}
_FETCH_HINT = (
    "synchronous device fetch in a verify/ loop — submit through "
    "parallel.pipeline.LaunchPipeline and convert at dequeue "
    "(or extend ALLOW_LOOP_FETCH with file::function and a reason)")

# Broad-except rule: a bare ``except:`` / ``except Exception`` /
# ``except BaseException`` that never re-raises swallows exactly the
# faults the resilience layer exists to classify and surface (an injected
# ``crash`` fault, a KeyboardInterrupt under BaseException) — silent
# degradation with no counter, no event, no ledger reason.  Handlers that
# contain a ``raise`` (conditional re-raise after classification) pass.
# The allowlist (``file::function``) names reviewed swallow sites — each
# with its reason.  Shrink, don't grow, it.
ALLOW_BROAD_EXCEPT = {
    # Import gate: jax.api_util.shaped_abstractify rename degrades to
    # conservative fallback cache keys, never an import error.
    "fairify_tpu/obs/compile.py::<module>",
    # Compile fallbacks: an unusable AOT path serves the kernel via plain
    # jax.jit (counted in xla_compile_fallbacks) — observability must
    # never change results or availability.  (_compile's handler re-raises
    # propagate-class faults, so only __call__'s swallow sites need this.)
    "fairify_tpu/obs/compile.py::__call__",
    # Backend-optional executable analyses (cost/memory): absence degrades
    # to missing attrs.
    "fairify_tpu/obs/compile.py::_record_analysis",
}
_BROAD_HINT = (
    "broad except (bare/Exception/BaseException) that never re-raises — "
    "classify via fairify_tpu.resilience.supervisor.classify and degrade "
    "with a recorded reason, or extend ALLOW_BROAD_EXCEPT with a reviewed "
    "reason")


def _is_broad_type(node) -> bool:
    """Does the handler's type expression name Exception/BaseException?"""
    if node is None:
        return True  # bare except:
    if isinstance(node, ast.Tuple):
        return any(_is_broad_type(el) for el in node.elts)
    return isinstance(node, ast.Name) and node.id in ("Exception",
                                                      "BaseException")


def _broad_except_errors(tree: ast.AST, rel: str) -> list:
    """Flag broad exception handlers with no ``raise`` anywhere in the body."""
    errors = []

    def walk(node, fn_name):
        for child in ast.iter_child_nodes(node):
            c_fn = fn_name
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                c_fn = child.name
            elif isinstance(child, ast.ExceptHandler) \
                    and _is_broad_type(child.type) \
                    and not any(isinstance(n, ast.Raise)
                                for n in ast.walk(child)) \
                    and f"{rel}::{c_fn}" not in ALLOW_BROAD_EXCEPT:
                errors.append(f"{rel}:{child.lineno}: {_BROAD_HINT}")
            walk(child, c_fn)

    walk(tree, "<module>")
    return errors


def _is_time_time(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "time"
            and isinstance(f.value, ast.Name) and f.value.id == "time")


def _is_print(node: ast.Call) -> bool:
    return isinstance(node.func, ast.Name) and node.func.id == "print"


def _is_raw_jit(node: ast.AST) -> bool:
    """The ``jax.jit`` attribute itself: catches ``@jax.jit``,
    ``jax.jit(f, ...)`` and ``partial(jax.jit, ...)`` uniformly."""
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name) and node.value.id == "jax")


def _is_loop_fetch(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "block_until_ready":
            return True
        if isinstance(f.value, ast.Name):
            # np.asarray(...) / jax.device_get(...) on loop-carried arrays.
            if f.value.id in ("np", "numpy") and f.attr == "asarray":
                return True
            if f.value.id == "jax" and f.attr == "device_get":
                return True
    return False


def _loop_fetch_errors(tree: ast.AST, rel: str) -> list:
    """Flag sync fetches whose nearest enclosing loop is a for/while body.

    A nested ``def``/``lambda`` resets the context: a decode closure defined
    inside a function and *called* from a loop is the pipeline's drain path,
    not a loop-body fetch.
    """
    errors = []

    def walk(node, fn_name, in_loop):
        for child in ast.iter_child_nodes(node):
            c_fn, c_loop = fn_name, in_loop
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                c_fn, c_loop = child.name, False
            elif isinstance(child, ast.Lambda):
                c_loop = False
            elif isinstance(child, (ast.For, ast.While)):
                c_loop = True
            elif isinstance(child, ast.Call) and c_loop \
                    and _is_loop_fetch(child) \
                    and f"{rel}::{c_fn}" not in ALLOW_LOOP_FETCH:
                errors.append(f"{rel}:{child.lineno}: {_FETCH_HINT}")
            walk(child, c_fn, c_loop)

    walk(tree, "<module>", False)
    return errors


def check_file(path: str, rel: str) -> list:
    with open(path) as fp:
        src = fp.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as exc:
        return [f"{rel}:{exc.lineno}: syntax error: {exc.msg}"]
    errors = []
    if rel.startswith(RAW_JIT_SCOPE) and rel not in ALLOW_RAW_JIT:
        for node in ast.walk(tree):
            if _is_raw_jit(node):
                errors.append(
                    f"{rel}:{node.lineno}: bare jax.jit — register device "
                    f"kernels through fairify_tpu.obs.compile.obs_jit so "
                    f"compiles are named/counted/timed (or extend "
                    f"ALLOW_RAW_JIT with a reviewed reason)")
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_time_time(node) and rel not in ALLOW_TIME_TIME:
            errors.append(
                f"{rel}:{node.lineno}: raw time.time() — use "
                f"time.perf_counter() via PhaseTimer/obs spans "
                f"(or extend ALLOW_TIME_TIME for a sanctioned shim)")
        elif _is_print(node) and rel not in ALLOW_PRINT:
            errors.append(
                f"{rel}:{node.lineno}: bare print() — progress goes through "
                f"fairify_tpu.obs.heartbeat, structured output through the "
                f"event log (or extend ALLOW_PRINT for user-facing output)")
    if rel.startswith(LOOP_FETCH_SCOPE):
        errors.extend(_loop_fetch_errors(tree, rel))
    errors.extend(_broad_except_errors(tree, rel))
    return errors


def main(argv=None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(root, "fairify_tpu")
    errors = []
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            errors.extend(check_file(path, rel))
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"lint_obs: {len(errors)} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""DEPRECATED shim over ``fairify_tpu.lint`` — use ``fairify_tpu lint``.

The five observability rules this script used to implement inline
(raw ``time.time()``, bare ``print``, bare ``jax.jit`` in verify/+ops/,
silently-swallowed broad excepts, synchronous device fetches in verify/
loops) migrated unchanged into the rule engine at ``fairify_tpu/lint/``
(``rules_obs.py``), which added four more analyses (jit-purity,
recompile-hazard, lock-discipline, fault-site-coverage), per-rule
allowlists, ``# lint: disable=<rule-id>`` inline suppressions, a committed
baseline, and JSON output.  New call sites should run::

    python -m fairify_tpu lint          # all nine rules
    python scripts/lint.py --ratchet    # CI growth gate

This file keeps the old module surface — ``check_file(path, rel)``,
``main(argv)``, and the ``ALLOW_*`` constants — for existing callers
(``tests/test_observability.py`` / ``tests/test_resilience.py`` exercise
it as the legacy-rule regression surface).  It will be removed once
nothing imports it; do not add rules here.
"""
from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from fairify_tpu.lint.core import FileContext  # noqa: E402
from fairify_tpu.lint.rules import legacy_rules  # noqa: E402
from fairify_tpu.lint.rules_obs import (  # noqa: E402,F401  (legacy surface)
    ALLOW_BROAD_EXCEPT,
    ALLOW_LOOP_FETCH,
    ALLOW_PRINT,
    ALLOW_RAW_JIT,
    ALLOW_TIME_TIME,
    LOOP_FETCH_SCOPE,
    RAW_JIT_SCOPE,
)


def check_file(path: str, rel: str) -> list:
    """Legacy per-file entry: the five obs rules, old message format."""
    try:
        ctx = FileContext(path, rel)
    except SyntaxError as exc:
        return [f"{rel}:{exc.lineno}: syntax error: {exc.msg}"]
    findings = []
    for rule in legacy_rules():
        if rule.applies(rel):
            findings.extend(f for f in rule.check(ctx)
                            if not ctx.suppressed(f.line, f.rule))
    findings.sort(key=lambda f: (f.line, f.rule))
    return [f"{rel}:{f.line}: {f.message}" for f in findings]


def main(argv=None) -> int:
    pkg = os.path.join(_ROOT, "fairify_tpu")
    errors = []
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, _ROOT).replace(os.sep, "/")
            errors.extend(check_file(path, rel))
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"lint_obs: {len(errors)} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""SMT worker-pool throughput bench → ``audits/SMT_r*.json`` (perfdiff-gated).

Measures the SMT phase the sweep's UNKNOWN-retry ladder actually runs
(``verify.sweep._SmtTier``: serialize → fan out → consume), isolated from
device work so the number is the pool's own: Q identical-cost queries are
fanned out across 1 worker and then N workers, and the record carries
``queries_per_s`` per worker count, the 1→N ``speedup_x``, and the
containment health counters (``worker_crashes`` / ``memouts`` — a healthy
bench has ZERO of each; perfdiff fails any growth).

The solver is single-threaded, so before the pool the sweep's SMT phase
was serial no matter the host: speedup_x is the headline robustness win —
an UNKNOWN-heavy ladder's host-solving wall time divides by the worker
count (acceptance target: ≥ 2x at 4 workers).

Queries are UNSAT by construction (a constant-sign logit), forcing the
brute backend through its FULL enumeration — deterministic per-query cost,
no early-SAT shortcuts.  Where z3-solver is installed the worker backend
resolves to z3 automatically and the record's ``backend`` field says so.

Usage: python scripts/smt_bench.py [--queries 16] [--workers 4]
           [--out audits/SMT_r10.json] [--box 24]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _queries(n: int, box: int):
    """n serialized pair-property queries with identical enumeration cost."""
    import numpy as np

    from fairify_tpu.data.domains import DomainSpec
    from fairify_tpu.models import mlp
    from fairify_tpu.verify import property as prop
    from fairify_tpu.verify import smt as smt_mod

    ranges = {"a": (0, box), "b": (0, box), "c": (0, 3), "pa": (0, 1)}
    dom = DomainSpec(name="smtbench", columns=tuple(ranges),
                     ranges={k: tuple(v) for k, v in ranges.items()},
                     label="y")
    q = prop.FairnessQuery(domain=dom, protected=("pa",))
    enc = prop.encode(q)
    lo, hi = q.domain.lo_hi()
    out = []
    for i in range(n):
        rng = np.random.default_rng(1000 + i)
        ws = [rng.normal(size=(4, 6)).astype(np.float32) * 0.25,
              rng.normal(size=(6, 1)).astype(np.float32) * 0.25]
        # Large positive bias: the logit never crosses zero, so the
        # query is UNSAT and the backend must walk every pair.
        bs = [np.zeros(6, np.float32), np.array([50.0], np.float32)]
        net = mlp.from_numpy(ws, bs)
        out.append(smt_mod.build_query(net, enc, lo.astype(np.int64),
                                       hi.astype(np.int64), name=f"q{i}"))
    return out


def _run_level(queries, workers: int) -> dict:
    from fairify_tpu.smt.pool import PoolConfig, SmtPool

    with SmtPool(PoolConfig(workers=workers, backend="auto")) as pool:
        # Warm spawn outside the timed window (the sweep's pool lives for
        # the whole run; spawn cost is not per-query cost).
        warm = pool.solve_serialized(queries[0], soft_timeout_s=120.0)
        t0 = time.perf_counter()
        futs = [pool.submit_serialized(q, soft_timeout_s=120.0)
                for q in queries]
        results = [f.result() for f in futs]
        wall = time.perf_counter() - t0
    bad = [r.verdict for r in results + [warm] if r.verdict != "unsat"]
    return {
        "queries_per_s": round(len(queries) / wall, 3),
        "smt_wall_s": round(wall, 3),
        "unexpected_verdicts": len(bad),
        "backend": results[0].backend if results else "?",
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--queries", type=int, default=16)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--box", type=int, default=24,
                    help="per-attribute range width (enumeration cost knob)")
    ap.add_argument("--out", default=None,
                    help="write the SMT record JSON here (e.g. "
                         "audits/SMT_r10.json)")
    args = ap.parse_args()

    from fairify_tpu import obs

    queries = _queries(args.queries, args.box)
    reg = obs.registry()
    crashes0 = reg.counter("smt_worker_crashes").total()
    memouts0 = reg.counter("smt_memouts").total()
    levels = {}
    for w in sorted({1, max(args.workers, 1)}):
        levels[str(w)] = _run_level(queries, w)
        print(json.dumps({"workers": w, **levels[str(w)]}), flush=True)
    qps1 = levels["1"]["queries_per_s"]
    qpsn = levels[str(max(args.workers, 1))]["queries_per_s"]
    record = {
        "kind": "SMT",
        "queries": args.queries,
        "backend": levels["1"]["backend"],
        "workers": {k: {"queries_per_s": v["queries_per_s"],
                        "smt_wall_s": v["smt_wall_s"]}
                    for k, v in levels.items()},
        "speedup_x": round(qpsn / max(qps1, 1e-9), 2),
        "worker_crashes": int(reg.counter("smt_worker_crashes").total()
                              - crashes0),
        "memouts": int(reg.counter("smt_memouts").total() - memouts0),
        "ok": all(v["unexpected_verdicts"] == 0 for v in levels.values()),
    }
    print(json.dumps(record), flush=True)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as fp:
            json.dump(record, fp, indent=2)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

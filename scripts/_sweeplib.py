"""Shared scaffolding for the sweep-recording harnesses (parity, variants).

One resumable results ledger convention: ``<out>/results.jsonl`` holds one
JSON line per (run_id, model).  Models whose input width does not match the
verification domain produce a ``skipped`` record so resumption converges
instead of re-listing them forever (e.g. the 6-input CP-1/CP-11 under the
12-feature ``CP12`` preset).
"""
from __future__ import annotations

import json
import os
import re
import time


def done_set(results_path: str) -> set:
    done = set()
    if os.path.isfile(results_path):
        with open(results_path) as fp:
            for line in fp:
                rec = json.loads(line)
                done.add((rec["run_id"], rec["model"]))
    return done


def model_natkey(name: str):
    """Natural sort key robust to non-standard names like ``aCP-1-Old``."""
    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", name)]


def run_and_record(cfg, run_id: str, results_path: str, extra=None,
                   model_filter=None, done=None) -> list:
    """Sweep every not-yet-recorded zoo model under ``cfg``; append records.

    Returns the newly appended records (verified rows plus ``skipped``
    markers for width-mismatched models).
    """
    from fairify_tpu.models import zoo
    from fairify_tpu.verify import sweep

    if done is None:
        done = done_set(results_path)
    names = [p.stem for p in zoo.model_paths(cfg.dataset)]
    if cfg.models is not None:
        names = [n for n in names if n in cfg.models]
    if model_filter:
        names = [n for n in names if n in model_filter]
    todo = [n for n in names if (run_id, n) not in done]
    if not todo:
        return []
    print(f"== {run_id}: {todo}", flush=True)
    t0 = time.perf_counter()
    reports = sweep.run_sweep(cfg.with_(models=tuple(todo)))
    recs = []
    for rep in reports:
        counts = rep.counts
        decided = counts["sat"] + counts["unsat"]
        recs.append({
            "run_id": run_id, "model": rep.model, **(extra or {}),
            "partitions": rep.partitions_total, **counts,
            "total_time_s": round(rep.total_time_s, 2),
            "decided_per_sec": round(decided / max(rep.total_time_s, 1e-9), 3),
            "original_acc": round(rep.original_acc, 4),
            "soft_s": cfg.soft_timeout_s, "hard_s": cfg.hard_timeout_s,
        })
    reported = {r["model"] for r in recs}
    for name in todo:
        if name not in reported:
            recs.append({"run_id": run_id, "model": name, **(extra or {}),
                         "skipped": "input-width mismatch with domain"})
    with open(results_path, "a") as fp:
        for rec in recs:
            fp.write(json.dumps(rec) + "\n")
            print(json.dumps(rec), flush=True)
    print(f"== {run_id} done in {time.perf_counter() - t0:.1f}s", flush=True)
    return recs

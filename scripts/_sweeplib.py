"""Shared scaffolding for the sweep-recording harnesses (parity, variants).

One resumable results ledger convention: ``<out>/results.jsonl`` holds one
JSON line per (run_id, model).  Models whose input width does not match the
verification domain produce a ``skipped`` record so resumption converges
instead of re-listing them forever (e.g. the 6-input CP-1/CP-11 under the
12-feature ``CP12`` preset).
"""
from __future__ import annotations

import json
import os
import re
import time


def _config_key(rec: dict):
    """Binding-config part of a result key.

    Re-running a preset with different budgets or caps must *execute*, not
    silently resume past it, and a table mixing configs must be
    self-describing — so the resume key carries the knobs that change the
    experiment's semantics (soft/hard budgets, grid cap).  ``skipped``
    records (width mismatch) are config-independent: the mismatch holds for
    every budget.
    """
    if "skipped" in rec:
        return "skipped"
    if "cap" not in rec and "attempted" not in rec:
        # Rows written before the cap/attempted fields existed (round-1
        # capped runs): give them a sentinel key so a new uncapped full-grid
        # run never resumes past them.
        return ("legacy", rec.get("soft_s"), rec.get("hard_s"))
    # ``engine_tag`` (ADVICE r4 #2): rows recorded by an older engine carry
    # no tag (None); a harness passing a fresh tag re-EXECUTES instead of
    # silently resuming past stale-engine rows.
    return (rec.get("soft_s"), rec.get("hard_s"), rec.get("cap"),
            rec.get("engine_tag"))


def done_set(results_path: str) -> set:
    done = set()
    if os.path.isfile(results_path):
        with open(results_path) as fp:
            for line in fp:
                rec = json.loads(line)
                done.add((rec["run_id"], rec["model"], _config_key(rec)))
                if "skipped" in rec:
                    done.add((rec["run_id"], rec["model"], "skipped"))
    return done


def model_natkey(name: str):
    """Natural sort key robust to non-standard names like ``aCP-1-Old``."""
    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", name)]


def run_and_record(cfg, run_id: str, results_path: str, extra=None,
                   model_filter=None, done=None, n_shards=None) -> list:
    """Sweep every not-yet-recorded zoo model under ``cfg``; append records.

    Returns the newly appended records (verified rows plus ``skipped``
    markers for width-mismatched models).  Observability flows through the
    config: set ``cfg.trace_out`` / ``cfg.heartbeat_s`` and
    ``sweep.run_sweep`` owns the tracer scope.  ``n_shards`` routes the
    sweep through the fault-domain sharded runtime
    (``parallel.shards.sweep_sharded`` — per-shard journals merge with the
    same ``model@span`` ledger convention :func:`merge_span_ledgers`
    already unions, so resumable recording composes with sharding).
    """
    from fairify_tpu.models import zoo
    from fairify_tpu.verify import sweep

    if done is None:
        done = done_set(results_path)
    cfg_key = (cfg.soft_timeout_s, cfg.hard_timeout_s,
               cfg.max_partitions if cfg.capped_partitions else None,
               (extra or {}).get("engine_tag"))
    names = [p.stem for p in zoo.model_paths(cfg.dataset)]
    if cfg.models is not None:
        names = [n for n in names if n in cfg.models]
    if model_filter:
        names = [n for n in names if n in model_filter]
    todo = [n for n in names
            if (run_id, n, cfg_key) not in done
            and (run_id, n, "skipped") not in done]
    if not todo:
        return []
    print(f"== {run_id}: {todo}", flush=True)
    t0 = time.perf_counter()
    reports = sweep.run_sweep(cfg.with_(models=tuple(todo)),
                              n_shards=n_shards)
    recs = []
    for rep in reports:
        counts = rep.counts
        decided = counts["sat"] + counts["unsat"]
        recs.append({
            "run_id": run_id, "model": rep.model, **(extra or {}),
            "partitions": rep.partitions_total, **counts,
            "total_time_s": round(rep.total_time_s, 2),
            "decided_per_sec": round(decided / max(rep.total_time_s, 1e-9), 3),
            "original_acc": round(rep.original_acc, 4),
            "soft_s": cfg.soft_timeout_s, "hard_s": cfg.hard_timeout_s,
            "cap": cfg.max_partitions if cfg.capped_partitions else None,
        })
    reported = {r["model"] for r in recs}
    for name in todo:
        if name not in reported:
            recs.append({"run_id": run_id, "model": name, **(extra or {}),
                         "skipped": "input-width mismatch with domain"})
    with open(results_path, "a") as fp:
        for rec in recs:
            fp.write(json.dumps(rec) + "\n")
            print(json.dumps(rec), flush=True)
    print(f"== {run_id} done in {time.perf_counter() - t0:.1f}s", flush=True)
    return recs


def budgeted_model_sweep(cfg, net, model_name: str, dataset=None,
                         ledger_tag=None):
    """Attempt-until-hard-budget semantics over the full grid (one model).

    ``cfg.trace_out`` / ``cfg.heartbeat_s`` flow through: one obs tracer
    scope covers every span of the budgeted run (the per-span
    ``verify_model`` calls see the active tracer and nest under it).

    The reference's variant drivers iterate the shuffled partition list and
    break when cumulative time passes HARD_TIMEOUT, leaving the tail
    *unattempted* (``stress/GC/Verify-GC.py:31-35``; Table V's Cov%% column).
    The grid-batched sweep attempts everything at once, so this wrapper
    restores the reference semantics at grid scale: contiguous spans of the
    deterministically-shuffled grid are swept until ``cfg.hard_timeout_s``
    is spent; the remainder is recorded as unattempted coverage, never as
    UNKNOWN.  Span size adapts to measured throughput so most models finish
    in 1-3 spans.  Returns a result dict (counts, attempted, cov, timing).
    """
    from fairify_tpu.verify import sweep

    # Ledgers are per-config: a re-run with different budgets must re-decide,
    # not resume past, the old config's verdicts (the resume inside one
    # config still gives crash recovery).  ``ledger_tag`` (the engine tag)
    # namespaces the ledgers too — without it, a tagged re-run would
    # resume=True straight through the OLD engine's per-partition verdicts
    # and record bookkeeping-speed rows as fresh results.
    sub = f"b{cfg.soft_timeout_s:g}-{cfg.hard_timeout_s:g}"
    if ledger_tag:
        sub += f"-{ledger_tag}"
    cfg = cfg.with_(result_dir=os.path.join(cfg.result_dir, sub))
    from fairify_tpu import obs

    with obs.maybe_tracing(cfg.trace_out,
                           run_id=f"{cfg.name}-{model_name}-budgeted"):
        with obs.span("budgeted_model_sweep", preset=cfg.name,
                      model=model_name, budget_s=cfg.hard_timeout_s) as sp:
            row = _budgeted_model_sweep_impl(cfg, net, model_name, dataset)
            sp.set(attempted=row["attempted"], unknown=row["unknown"])
            return row


def _budgeted_model_sweep_impl(cfg, net, model_name, dataset):
    from fairify_tpu.verify import sweep

    _, lo, hi = sweep.build_partitions(cfg)
    P = lo.shape[0]
    t0 = time.perf_counter()
    counts = {"sat": 0, "unsat": 0, "unknown": 0}
    span = 0
    chunk = cfg.grid_chunk or 2048
    K = chunk  # first span: one stage-0 chunk (the throughput probe)
    rate = None
    while span < P:
        left = cfg.hard_timeout_s - (time.perf_counter() - t0)
        if left <= 0:
            break
        # Budget honesty (VERDICT r4 weak #2): once a rate is measured,
        # never START a span that cannot finish comfortably inside the
        # remaining budget — the reference's loop breaks BETWEEN partitions
        # when cumulative time passes the hard budget
        # (``stress/GC/Verify-GC.py:31-35``); a span is this harness's
        # partition-granule analog.  The predicate (and its safety factor
        # with the rate-misestimate rationale) lives in
        # ``fairify_tpu.serve.admission.span_admissible`` — the service's
        # SLA admission applies the same rule at request granularity, and
        # the two must not drift.  With the async launch pipeline, the
        # moment a span starts ``depth × chunk`` launches are committed
        # device work that must drain even if the budget trips mid-span, so
        # the minimum admissible cost of STARTING a span is the whole
        # in-flight backlog, not one chunk.
        from fairify_tpu.serve.admission import span_admissible

        depth = max(1, int(getattr(cfg, "pipeline_depth", 1)))
        if not span_admissible(rate, depth, chunk, left):
            break
        stop = min(P, span + K)
        t_block = time.perf_counter()
        rep = sweep.verify_model(
            net, cfg.with_(hard_timeout_s=left), model_name=model_name,
            dataset=dataset, partition_span=(span, stop), resume=True)
        for o in rep.outcomes:
            counts[o.verdict] += 1
        block_dt = time.perf_counter() - t_block
        n_block = stop - span
        span = stop
        left = cfg.hard_timeout_s - (time.perf_counter() - t0)
        if block_dt >= 1.0:
            # Measured-rate sizing: fill roughly half the remaining budget
            # per span, rounded DOWN to whole grid chunks so the stage-0
            # kernels keep their compiled shapes (a ragged span pads to a
            # new chunk size and re-compiles inside the budget).
            rate = n_block / block_dt
            K = int(min(rate * left * 0.5, 500_000)) // chunk * chunk
            K = max(chunk, K)
        else:
            # Ledger fast-forward (resumed span): the wall time measures
            # bookkeeping, not sweep throughput — grow geometrically instead.
            K = min(K * 4, 500_000)
    # In-prefix UNKNOWNs here are boxes the HARD budget cut mid-batch —
    # they never received their per-partition soft budget, unlike the
    # reference's loop which checks the cumulative break BETWEEN partitions
    # (each attempted partition gets its full Z3 query,
    # ``stress/GC/Verify-GC.py:31-35``).  Restore that semantics with a
    # retry pass that gives exactly those boxes a soft-timeout decision,
    # bounded by what is LEFT of the hard budget plus one soft-timeout
    # grace (the reference's in-flight partition finishes its full Z3
    # query past the cumulative break) — the old unconditional
    # ``max(120, hard/4)`` retry is how r4's "60 s" rows spent 280+ s.
    if counts["unknown"]:
        left = cfg.hard_timeout_s - (time.perf_counter() - t0)
        fixed = retry_span_unknowns(
            cfg, net, model_name,
            budget_s=max(left, 0.0) + min(cfg.soft_timeout_s,
                                          0.5 * cfg.hard_timeout_s),
            grid=(lo, hi))
        for verdict, n in fixed.items():
            counts[verdict] += n
            counts["unknown"] -= n
    elapsed = time.perf_counter() - t0
    decided = counts["sat"] + counts["unsat"]
    # Funnel accounting for the unattempted tail (obs.funnel): the budget
    # cut it before any attempt, so it is ``unknown:budget`` — mirrored
    # into the live ``funnel_states`` counter (heartbeat/metrics see it)
    # and counted against the row's decided fraction, which is over the
    # FULL grid (the reference's Cov% semantics, Table V).
    if P > span:
        from fairify_tpu.obs import funnel as funnel_lib

        funnel_lib.FunnelCounts().add("unknown:budget", int(P - span))
    return {
        "model": model_name,
        "partitions": int(P),
        "attempted": int(span),
        "cov": round(span / max(P, 1), 4),
        **counts,
        "total_time_s": round(elapsed, 2),  # the row's true wall time
        "budget_s": cfg.hard_timeout_s,
        "decided_per_sec": round(decided / max(elapsed, 1e-9), 3),
        "decided_fraction": round(decided / max(P, 1), 6),
    }


def merge_span_ledgers(cfg, model_name: str):
    """Decided-wins union of a model's span ledgers under this config.

    Crashed runs can leave OVERLAPPING span files (different adaptive span
    boundaries); a partition any file records as decided stays decided —
    a later file's budget-cut 'unknown' must never demote it.  This is the
    single merge semantics shared by :func:`retry_span_unknowns` and the
    deep-retry row recount (round-4 review: a file-order last-wins merge
    there could corrupt published counts).  Returns
    ``(paths, decided: {pid: rec}, unknown_pids: set)``.
    """
    import glob

    paths = sorted(glob.glob(os.path.join(
        cfg.result_dir, f"{cfg.name}-{model_name}@*.ledger.jsonl")))
    from fairify_tpu.verify import sweep as sweep_mod

    # The decided-wins merge now lives in the library (sweep.merge_ledgers,
    # this PR's promotion) — fault-degraded UNKNOWNs land in the retryable
    # bucket alongside budget UNKNOWNs, which is exactly what the retry
    # pass wants.
    done, degraded, _skipped = sweep_mod.merge_ledgers(paths)
    decided = {pid: rec for pid, rec in done.items()
               if rec["verdict"] != "unknown"}
    unknown = {pid for pid, rec in done.items()
               if rec["verdict"] == "unknown"} | set(degraded)
    return paths, decided, unknown


def retry_span_unknowns(cfg, net, model_name: str, budget_s: float,
                        grid=None, return_residual: bool = False):
    """Soft-timeout re-decision of a budgeted sweep's in-prefix UNKNOWNs.

    Merges every span ledger of the model under this config FIRST — a
    crashed earlier run can leave overlapping span files, and a partition
    any file records as decided must not be re-counted — then batches the
    still-unknown boxes straight through ``engine.decide_many`` (no stage-0
    recompute: masks/pruning only matter for the heuristic retry, which
    the native engine's LP/BaB phases supersede here), and appends the new
    verdicts to one ledger (last-wins merge on resume).  ``grid`` lets the
    caller pass its already-built (lo, hi) (the stress grids reach 3.3M
    boxes; rebuilding them here would double that cost).  Returns
    ``{"sat": n, "unsat": n}`` fixed counts, each pid counted once; with
    ``return_residual`` also the pre-retry residual-unknown count, so a
    caller can tell "nothing to retry / no ledgers found" (residual 0 —
    a no-op that must not be recorded as a deep pass) from a genuine
    attempt.
    """
    import numpy as np

    from fairify_tpu.verify import engine, sweep as sweep_mod
    from fairify_tpu.verify.property import encode

    if grid is None:
        _, lo, hi = sweep_mod.build_partitions(cfg)
    else:
        lo, hi = grid
    enc = encode(cfg.query())
    # The per-root LP/BaB deadlines inside decide_many run off the ENGINE
    # config's soft budget; sync it to the sweep-level soft budget exactly
    # like sweep.verify_model does, so an escalated cfg.soft_timeout_s
    # (deep_retry_variants.py) actually reaches the engine phases.
    from dataclasses import replace as _replace

    eng = _replace(cfg.engine, soft_timeout_s=cfg.soft_timeout_s)
    t0 = time.perf_counter()
    fixed = {"sat": 0, "unsat": 0}
    paths, decided, unknown = merge_span_ledgers(cfg, model_name)
    unk = sorted(unknown)
    if not unk or not paths:
        return (fixed, 0) if return_residual else fixed
    sink = paths[-1]
    for start in range(0, len(unk), 2048):
        blk = unk[start:start + 2048]
        left = budget_s - (time.perf_counter() - t0)
        if left <= 0:
            break
        idx = np.array([p - 1 for p in blk])
        decisions = engine.decide_many(
            net, enc, lo[idx], hi[idx], eng,
            deadline_s=min(left, cfg.soft_timeout_s * len(idx)))
        with open(sink, "a") as fp:
            for pid, dec in zip(blk, decisions):
                if dec.verdict == "unknown":
                    continue
                ce = dec.counterexample
                fixed[dec.verdict] += 1
                fp.write(json.dumps({
                    "partition_id": int(pid), "verdict": dec.verdict,
                    "ce": ([ce[0].tolist(), ce[1].tolist()] if ce else None),
                    "time_s": round(dec.elapsed_s, 4), "retry": "soft",
                    # Effective per-partition budget of THIS decision — a
                    # deep-tier re-decision must stay distinguishable from
                    # base-tier retries at the ledger level too.
                    "soft_s": cfg.soft_timeout_s,
                }) + "\n")
    return (fixed, len(unk)) if return_residual else fixed


def run_and_record_budgeted(cfg, run_id: str, results_path: str,
                            model_filter=None, extra=None) -> list:
    """Budgeted (attempt-until-hard-budget) sweep of a zoo under ``cfg``."""
    from fairify_tpu.data import loaders
    from fairify_tpu.models import zoo

    done = done_set(results_path)
    cfg_key = (cfg.soft_timeout_s, cfg.hard_timeout_s,
               cfg.max_partitions if cfg.capped_partitions else None,
               (extra or {}).get("engine_tag"))
    n_attrs = len(cfg.query().columns)
    names = [p.stem for p in zoo.model_paths(cfg.dataset)]
    if cfg.models is not None:
        names = [n for n in names if n in cfg.models]
    if model_filter:
        names = [n for n in names if n in model_filter]
    todo = [n for n in sorted(names, key=model_natkey)
            if (run_id, n, cfg_key) not in done
            and (run_id, n, "skipped") not in done]
    if not todo:
        return []
    nets, skipped = zoo.load_matching(cfg.dataset, n_attrs, models=tuple(todo))
    dataset = loaders.load(cfg.dataset)
    print(f"== {run_id} (budgeted {cfg.hard_timeout_s:.0f}s/model): {todo}",
          flush=True)
    recs = []
    for name in sorted(nets, key=model_natkey):
        import jax.numpy as jnp
        import numpy as np

        from fairify_tpu.models import mlp as mlp_mod

        import jax

        pred = np.asarray(mlp_mod.predict(
            nets[name], jnp.asarray(dataset.X_test, jnp.float32)))
        rec = {"run_id": run_id, **(extra or {}),
               **budgeted_model_sweep(cfg, nets[name], name, dataset=dataset,
                                      ledger_tag=(extra or {}).get("engine_tag")),
               "original_acc": round(float((pred.astype(int) == dataset.y_test).mean()), 4),
               "soft_s": cfg.soft_timeout_s, "hard_s": cfg.hard_timeout_s,
               "cap": cfg.max_partitions if cfg.capped_partitions else None,
               "platform": jax.devices()[0].platform}
        recs.append(rec)
        with open(results_path, "a") as fp:
            fp.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)
    for name in skipped:
        rec = {"run_id": run_id, "model": name,
               "skipped": "input-width mismatch with domain"}
        recs.append(rec)
        with open(results_path, "a") as fp:
            fp.write(json.dumps(rec) + "\n")
    return recs

"""Round-4 slow-tail parity re-runs (VERDICT r3 #1 'done' criterion).

Re-sweeps the round-3 slow-tail models — AC-4 (both PAs), AC-2, BM-4,
BM-9, GC-5 — on their FULL grids with the round-4 engine (Phase A deep
PGD, sign-frontier cap, multi-way splits), writing fresh throughput
records (with per-phase attribution) under ``parity/`` and appending to
``parity/results.jsonl``.  Done = every row ≥ 1 decided partition/sec.

Usage: python scripts/rerun_slow_parity.py [--out parity] [--targets ...]
"""
from __future__ import annotations

import argparse
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# (run_id, preset, overrides, model, hard_s) — cheap rows first so a crash
# late in the queue loses the least.
TARGETS = [
    ("GC-age", "GC", {}, "GC-5", 900.0),
    ("BM-age", "BM", {}, "BM-4", 1200.0),
    ("BM-age", "BM", {}, "BM-9", 1200.0),
    ("AC-race", "AC", {"protected": ("race",)}, "AC-4", 5400.0),
    ("AC-sex", "AC", {}, "AC-2", 5400.0),
    ("AC-sex", "AC", {}, "AC-4", 7200.0),
]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="parity")
    ap.add_argument("--soft", type=float, default=5.0)
    ap.add_argument("--targets", default="",
                    help="comma list run_id:model restricting the queue")
    args = ap.parse_args()

    sys.path.insert(0, os.path.join(ROOT, "scripts"))

    from _sweeplib import run_and_record
    from fairify_tpu.verify import presets

    os.makedirs(args.out, exist_ok=True)
    results_path = os.path.join(args.out, "results.jsonl")
    wanted = ({tuple(t.split(":")) for t in args.targets.split(",")}
              if args.targets else None)
    for run_id, preset, overrides, model, hard in TARGETS:
        if wanted is not None and (run_id, model) not in wanted:
            continue
        cfg = presets.get(preset).with_(
            soft_timeout_s=args.soft, hard_timeout_s=hard,
            result_dir=os.path.join(args.out, run_id), **overrides)
        # "Fresh ledgers" must mean fresh: verify_model resumes by default,
        # so a pre-existing ledger (an earlier round's run) would be
        # fast-forwarded and re-reported as a re-verification with
        # bookkeeping timings.  Move any prior sinks aside first.
        for suffix in (f"{cfg.name}-{model}.ledger.jsonl", f"{model}.csv",
                       f"{model}-counterexamples.csv",
                       f"{cfg.name}-{model}.throughput.json"):
            path = os.path.join(cfg.result_dir, suffix)
            if os.path.isfile(path):
                n = 1
                while os.path.isfile(f"{path}.prev{n}"):
                    n += 1
                os.rename(path, f"{path}.prev{n}")
                print(f"moved aside stale {path} -> .prev{n}", flush=True)
        run_and_record(cfg, run_id, results_path,
                       extra={"pa": overrides.get("protected", cfg.protected)[0]},
                       model_filter={model})
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Variant-experiment harness: stress / relaxed / targeted / targeted2 sweeps.

The reference ships these as 12 more copy-pasted driver scripts (Experiments
2-4, ``INSTALL.md:45-71``; config distinguishers in SURVEY.md §2.2) with no
published per-model table — only wall-clock budgets (1 h/model).  Here each
one is already a declarative preset (``fairify_tpu/verify/presets.py``);
this harness runs them all over their family zoos with one resumable
results file and renders ``VARIANTS.md``.

Usage:
    python scripts/variants.py run [--out variants] [--soft 100]
                                   [--hard 3600]   # = the reference's 1 h
                                   [--presets stress-GC,relaxed-AC,...]
                                   [--models GC-1,GC-2,...]
    python scripts/variants.py render [--out variants]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

VARIANT_PRESETS = [
    "stress-GC", "stress-AC", "stress-BM",
    "relaxed-GC", "relaxed-AC", "relaxed-BM", "relaxed2-BM", "relaxed3-BM",
    "targeted-GC", "targeted-AC", "targeted-BM",
    "targeted2-GC", "targeted2-AC", "targeted2-BM",
    "targeted-DF",
]

# What each variant changes vs the base driver — rendered into the report
# so the table is self-describing (sources in fairify_tpu/verify/presets.py).
DELTAS = {
    "stress-GC": "threshold 10, soft 200s", "stress-AC": "threshold 6, soft 200s",
    "stress-BM": "threshold 10, soft 200s",
    "relaxed-GC": "PA sex (+phantom marital-status)",
    "relaxed-AC": "PA race; RA age ε=5", "relaxed-BM": "PA age; RA duration ε=5",
    "relaxed2-BM": "PA age; RA duration+campaign ε=5 (two-RA, framework-native)",
    "relaxed3-BM": "PA age; RA duration+campaign+previous ε=5 (three-RA, "
                   "framework-native)",
    "targeted-GC": "PA sex; number_of_credits=2",
    "targeted-AC": "PA race; age∈[30,35]",
    "targeted-BM": "job=2, loan=1; RA duration ε=5",
    "targeted2-GC": "PA sex; purpose=7, foreign_worker=0",
    "targeted2-AC": "PA race; education∈[9,10]",
    "targeted2-BM": "poutcome=2; RA duration ε=5",
    "targeted-DF": "monetary dims pinned to an applicant profile",
    # Scaled stress zoos (round 5, VERDICT r4 #5): the reference's stress
    # drivers point at scaled-model dirs missing from its artifact; these
    # rows run the stress presets over wider/deeper nets trained by
    # scripts/scaled_stress.py (models_scaled/).
    "stress-AC-scaled": "stress-AC over 2x-wider/deeper scaled nets",
    "stress-BM-scaled": "stress-BM over 2x-wider/deeper scaled nets",
}


def cmd_run(args):
    from _sweeplib import run_and_record_budgeted
    from fairify_tpu.verify import presets

    os.makedirs(args.out, exist_ok=True)
    results_path = os.path.join(args.out, "results.jsonl")
    wanted = set(args.presets.split(",")) if args.presets else None
    model_filter = set(args.models.split(",")) if args.models else None
    for name in VARIANT_PRESETS:
        if wanted and name not in wanted:
            continue
        # --soft overrides the preset ONLY when explicitly passed (ADVICE r4
        # #1: a blanket --soft 100 silently halved the stress presets'
        # reference soft budget of 200 s, stress/GC/Verify-GC.py:33).
        cfg = presets.get(name).with_(
            hard_timeout_s=args.hard,
            result_dir=os.path.join(args.out, name))
        if args.soft is not None:
            cfg = cfg.with_(soft_timeout_s=args.soft)
        if args.max_partitions:
            cfg = cfg.with_(capped_partitions=True,
                            max_partitions=args.max_partitions)
        # Reference semantics: the FULL grid, attempted as a contiguous
        # prefix until the hard budget runs out (never grid subsampling);
        # the unattempted tail shows up as Cov% < 100, exactly like the
        # reference's cumulative-timeout break (stress/GC/Verify-GC.py:31-35).
        run_and_record_budgeted(cfg, name, results_path,
                                model_filter=model_filter,
                                extra={"engine_tag": args.tag} if args.tag
                                else None)


def cmd_render(args):
    recs = []
    for fname in ("results.jsonl", "results_scaled.jsonl"):
        path = os.path.join(args.out, fname)
        if os.path.isfile(path):
            with open(path) as fp:
                for line in fp:
                    recs.append(json.loads(line))
    # Only attempted-prefix rows render: legacy (round-1) records predate
    # the budgeted full-grid semantics — their grids were capped/subsampled,
    # so a Cov% column would misrepresent them (VERDICT.md round-1 item 2).
    recs = [r for r in recs if "skipped" not in r and "attempted" in r]
    order = {name: i for i, name in enumerate(VARIANT_PRESETS)}
    from _sweeplib import model_natkey
    recs.sort(key=lambda r: (order.get(r["run_id"], 99), model_natkey(r["model"]),
                             -r.get("hard_s", 0.0)))
    lines = [
        "# VARIANTS — stress / relaxed / targeted sweeps (Experiments 2-4)",
        "",
        "Generated by `scripts/variants.py` from `<out>/results.jsonl` and "
        "`<out>/results_scaled.jsonl` (scaled-zoo rows).  The "
        "reference runs these as 12 separate driver scripts with a "
        "**1 h/model** CPU budget and publishes no per-model table; this "
        "framework runs them as config presets over the same zoos with "
        "attempt-until-budget semantics: the FULL grid (stress/relaxed-AC "
        "reach 3.3M boxes), attempted as a contiguous prefix of the shuffled "
        "partition list until the hard budget runs out — Cov% is the "
        "attempted fraction, mirroring the reference's cumulative-timeout "
        "break (`stress/GC/Verify-GC.py:31-35`).  **Budget tiers are not "
        "the reference experiment repeated verbatim**: rows at 120 s/240 s "
        "hard budgets spend 1/30th–1/15th of the reference's hour (each "
        "still attempts more partitions than a reference CPU-hour would); "
        "rows at 3600 s are at the reference's own budget.  Boxes the hard "
        "budget cut mid-batch are re-decided in a bounded retry pass at "
        "their full per-partition soft budget (wall time counted into the "
        "row), so residual UNK is an engine failure unless that retry "
        "budget itself ran out — rows recorded before round 3 predate this "
        "pass.  Rows marked `(+drNs)` had residual UNKNOWNs re-decided at "
        "a deeper per-partition soft budget of up to N seconds "
        "(`scripts/deep_retry_variants.py`, the reference's larger-argv-"
        "timeout escalation); their wall time and dec/s include that pass.  "
        "SAT/UNSAT/UNK count attempted partitions only; per-row "
        "budgets are in the Budget column.  **Round-5 rows are "
        "budget-honest and engine-tagged**: spans never start unless they "
        "fit the remaining budget, every row records its true wall next to "
        "its label, and the `[r5-...]` tag in the Budget column names the "
        "engine commit (tagged re-runs re-execute instead of resuming "
        "through older engines' ledgers).  A scheduling note on the 3600 s "
        "tier: attempt-until-budget rows on the million-box stress/relaxed "
        "AC/BM grids spend their full hour by construction (the grid never "
        "exhausts), so the full 15-preset zoo at the reference budget is "
        "~76 chip-hours; round 5 ran every *exhaustible* preset at the "
        "full reference budget and the inexhaustible grids VERDICT-named-"
        "rows-first (scripts/hard_tier_r5.sh documents the schedule).  "
        "Scaled-zoo rows (`*-scaled`, VERDICT r4 #5) run the stress "
        "presets over 2x-wider/deeper nets from scripts/scaled_stress.py.",
        "",
        "| Preset | Delta vs base | Model | #P | Cov% | SAT | UNSAT | UNK "
        "| dec/s | Budget |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in recs:
        attempted = r.get("attempted", r["partitions"])
        cov = 100.0 * r.get("cov", attempted / max(r["partitions"], 1))
        budget = (f"{r['soft_s']:g}s/{r['hard_s']:g}s"
                  if "soft_s" in r and "hard_s" in r else "?")
        if "deep_retry" in r:
            # Boxes the base tier left UNKNOWN were re-decided at a deeper
            # per-partition soft budget (scripts/deep_retry_variants.py);
            # the row's wall time and dec/s include that pass.
            budget += f" (+dr{r['deep_retry']['soft_s']:g}s)"
        # Re-queued rows ran on the CPU host (faster than the tunnelled
        # single chip for this host-roundtrip-heavy workload); dec/s is
        # not chip throughput for those rows — marked explicitly.
        if r.get("platform") == "cpu":
            budget += " (cpu)"
        if r.get("engine_tag"):
            # Engine-tagged rows (round 5+) were produced by the named
            # engine; untagged rows predate the tag and may mix engines.
            budget += f" [{r['engine_tag']}]"
        lines.append(
            f"| {r['run_id']} | {DELTAS.get(r['run_id'], '')} | {r['model']} | "
            f"{r['partitions']} | {cov:.1f} | {r['sat']} | {r['unsat']} | "
            f"{r['unknown']} | {r['decided_per_sec']} | {budget} |")
    empty = not recs
    if empty:
        lines.append("| *(no runs recorded yet)* | | | | | | | | | |")
    out_md = os.path.join(ROOT, "VARIANTS.md")
    with open(out_md, "w") as fp:
        fp.write("\n".join(lines) + "\n")
    print(f"wrote {out_md} ({len(recs)} rows)")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    runp = sub.add_parser("run")
    runp.add_argument("--out", default="variants")
    runp.add_argument("--soft", type=float, default=None,
                      help="per-partition soft budget; default = the "
                           "preset's own (base 100 s, stress 200 s — the "
                           "reference drivers' SOFT_TIMEOUT values)")
    runp.add_argument("--tag", default=None,
                      help="engine tag carried into the resume key: rows "
                           "recorded under a different tag re-execute "
                           "instead of resuming (stale-engine guard)")
    runp.add_argument("--hard", type=float, default=3600.0,
                      help="per-model hard budget; 3600 = the reference's "
                           "1 h/model (INSTALL.md:45-71)")
    runp.add_argument("--presets", default=None)
    runp.add_argument("--models", default=None)
    runp.add_argument("--max-partitions", type=int, default=None,
                      help="cap each grid via the reference's DF-style capped "
                           "partitioning (PA-first priority, sampled combos) — "
                           "the stress grids reach 3.3M boxes uncapped")
    runp.set_defaults(fn=cmd_run)
    rend = sub.add_parser("render")
    rend.add_argument("--out", default="variants")
    rend.set_defaults(fn=cmd_render)
    args = ap.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()

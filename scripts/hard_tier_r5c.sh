#!/bin/bash
# Round-5 hard-tier finale — representative-model breadth.
#
# Second wall-clock correction: the r5b breadth loop ran whole zoos at the
# 600 s tier, but 5 presets x ~12 models x up to 600 s is another ~10 h.
# This finale records 2-3 representative models per remaining preset (the
# reference-named slow ones plus the first of each family), the easy-model
# relaxed3 companion row (BM-4's 62 residual unknowns deserve an easy-model
# UNK=0 counterpart), and the clean BM-S2 scaled re-run.
set -u
cd "$(dirname "$0")/.." || exit 1
TAG="r5-$(git rev-parse --short HEAD 2>/dev/null || echo untagged)"
echo "=== hard tier r5c, tag $TAG ($(date -u +%H:%M:%S)) ==="

for entry in \
  "relaxed3-BM BM-2,BM-10" \
  "targeted-BM BM-4,BM-11" \
  "targeted2-GC GC-3,GC-5" \
  "targeted2-AC AC-1,AC-8" \
  "targeted2-BM BM-4,BM-7,BM-11" \
  ; do
  preset=${entry%% *}
  models=${entry#* }
  echo "--- $preset $models (600s tier) ($(date -u +%H:%M:%S)) ---"
  PYTHONUNBUFFERED=1 python scripts/variants.py run --out variants \
    --hard 600 --tag "$TAG" --presets "$preset" --models "$models" \
    || echo "!! $preset exited $?"
done
echo "--- targeted-DF (tiny grids, whole zoo) ($(date -u +%H:%M:%S)) ---"
PYTHONUNBUFFERED=1 python scripts/variants.py run --out variants \
  --hard 600 --tag "$TAG" --presets targeted-DF \
  || echo "!! targeted-DF exited $?"

echo "--- BM-S2 scaled clean re-run ($(date -u +%H:%M:%S)) ---"
PYTHONUNBUFFERED=1 python scripts/scaled_stress.py make \
  || echo "!! scaled make exited $?"
FAIRIFY_TPU_MODEL_ROOT="$PWD/models_scaled" PYTHONUNBUFFERED=1 \
  python scripts/scaled_stress.py run --hard 900 --tag "$TAG-clean" \
  || echo "!! scaled rerun exited $?"
echo "=== r5c complete ($(date -u +%H:%M:%S)) ==="

"""Synthetic-data model pipeline (the reference's task1 analog).

Reproduces the capability of ``experimentData/task1``: synthesize rows of a
benchmark dataset (reference: CTGAN / distilgpt2 / gpt2; here: from-scratch
Gaussian-copula / autoregressive column model / bootstrap — see
``fairify_tpu/models/synth.py``), train a fresh MLP on the synthetic rows,
persist it as a Keras-compatible ``.h5`` (the reference's generated GC-6..8
slots, ``src/GC/Verify-GC-experiment.py:88-107``), verify it with the
dataset's preset, and compare against a real-data-trained twin.

Usage:
    python scripts/synthetic_models.py [--preset GC] [--generators copula,ar,bootstrap]
        [--n 2000] [--hidden 50] [--epochs 30] [--soft 5] [--hard 300]
        [--out res/synthetic]
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# The generated models keep the reference's naming convention: the first
# free slot per family (GC-6.., AC-17.., BM-14..).  Slots are keyed by
# generator *kind*, not by position in --generators, so a subset run (e.g.
# --generators ar) writes the same .h5 a full run would — never another
# generator's slot.
SLOT_BASE = {"GC": 6, "AC": 17, "BM": 14, "CP": 12, "DF": 12}
SLOT_OFFSET = {"copula": 0, "ar": 1, "bootstrap": 2}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", default="GC")
    ap.add_argument("--generators", default="copula,ar,bootstrap")
    ap.add_argument("--n", type=int, default=2000)
    ap.add_argument("--hidden", type=int, nargs="*", default=[50])
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--ar-epochs", type=int, default=200)
    ap.add_argument("--soft", type=float, default=5.0)
    ap.add_argument("--hard", type=float, default=300.0)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--out", default="res/synthetic")
    args = ap.parse_args()

    import numpy as np

    from fairify_tpu.data import loaders
    from fairify_tpu.models import export, synth, train
    from fairify_tpu.verify import presets, sweep

    cfg = presets.get(args.preset)
    cfg = dataclasses.replace(cfg, soft_timeout_s=args.soft,
                              hard_timeout_s=args.hard, result_dir=args.out)
    ds = loaders.load(cfg.dataset)
    query = cfg.query()
    lo, hi = query.domain.lo_hi()
    lo = np.concatenate([lo, [0.0]]).astype(np.int64)   # + label column
    hi = np.concatenate([hi, [1.0]]).astype(np.int64)

    # labelled real rows on the integer lattice (features then label)
    real = np.concatenate(
        [np.asarray(ds.X_train), np.asarray(ds.y_train)[:, None]], axis=1
    ).astype(np.int64)
    real = np.clip(real, lo[None, :], hi[None, :])

    os.makedirs(args.out, exist_ok=True)
    fam = args.preset.split("-")[-1]
    records = []

    def train_and_verify(tag: str, rows: np.ndarray, model_name: str):
        X, y = rows[:, :-1].astype(np.float32), rows[:, -1].astype(np.float32)
        if len(np.unique(y)) < 2:  # degenerate sample: nothing to verify
            return {"generator": tag, "model": model_name, "skipped": "single-class sample"}
        net = train.train_mlp(X, y, hidden=list(args.hidden),
                              epochs=args.epochs, seed=args.seed)
        h5 = os.path.join(args.out, f"{model_name}.h5")
        export.save_keras_h5(net, h5)
        report = sweep.verify_model(net, cfg, model_name=model_name,
                                    dataset=ds, resume=False)
        return {
            "generator": tag, "model": model_name, "h5": h5,
            "rows": int(len(rows)),
            "partitions": report.partitions_total, **report.counts,
            "test_acc": round(report.original_acc, 4),
            "total_time_s": round(report.total_time_s, 2),
        }

    # real-data twin first: the comparison anchor (reference compares the
    # synthetic models against the equivalently-shaped real-data model)
    records.append(train_and_verify("real", real, f"{fam}-real"))
    print(json.dumps(records[-1]), flush=True)

    for kind in [g for g in args.generators.split(",") if g]:
        rows = synth.synthesize(kind, real, lo, hi, args.n, seed=args.seed,
                                ar_epochs=args.ar_epochs)
        slot = SLOT_BASE.get(fam, 90) + SLOT_OFFSET.get(kind, len(SLOT_OFFSET))
        rec = train_and_verify(kind, rows, f"{fam}-{slot}")
        records.append(rec)
        print(json.dumps(rec), flush=True)

    with open(os.path.join(args.out, "summary.json"), "w") as fp:
        json.dump(records, fp, indent=1)


if __name__ == "__main__":
    main()

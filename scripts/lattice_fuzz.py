"""Phase E soundness fuzz: random boxes vs the exact per-point oracle.

Random tiny MLPs × random integer boxes × random queries — RA-free,
single-RA, two-RA, and (round 5, VERDICT r4 #8) three-RA — decided by
``ops.lattice.decide_box_exhaustive`` and cross-checked against
``engine.decide_leaf`` applied to every core shared point (the trusted
exact single-point semantics).  Any disagreement is a soundness bug in the
device scan / window dilation; SAT witnesses are additionally replayed in
exact arithmetic.  Writes ``audits/lattice_fuzz_r4.json``.

Usage: python scripts/lattice_fuzz.py [--trials 150] [--seed0 0]
"""
from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def oracle(net, enc, lo, hi):
    """decide_leaf at every core shared point — exact, lattice-independent."""
    import numpy as np

    from fairify_tpu.verify import engine

    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    dims = [k for k in range(len(lo)) if k not in enc.pa_idx]
    spaces = [range(int(lo[k]), int(hi[k]) + 1) for k in dims]
    for coord in itertools.product(*spaces):
        pt = np.array(lo, dtype=np.int64)
        pt[dims] = coord
        verdict, _ = engine.decide_leaf(enc, weights, biases, pt, lo, hi)
        if verdict == "sat":
            return "sat"
    return "unsat"


def one_trial(seed: int) -> dict:
    import numpy as np

    from fairify_tpu.ops import lattice as lattice_ops
    from fairify_tpu.verify import engine, property as prop
    from fairify_tpu.verify.oracle import random_net, tiny_domain

    rng = np.random.default_rng(seed)
    d = int(rng.integers(3, 6))
    names = [f"a{i}" for i in range(d)]
    ranges = {}
    for nm in names:
        lo0 = int(rng.integers(0, 2))
        ranges[nm] = (lo0, lo0 + int(rng.integers(1, 4)))
    pa = (names[int(rng.integers(0, d))],)
    rest = [nm for nm in names if nm not in pa]
    # Trial mix: ~1/4 each of RA-free, single-, two- and three-RA (when
    # the dimensionality allows).
    n_ra = int(rng.integers(0, 4))
    n_ra = min(n_ra, len(rest))
    ra = tuple(rng.choice(rest, size=n_ra, replace=False).tolist()) if n_ra else ()
    eps = int(rng.integers(1, 3)) if n_ra else 0
    dom = tiny_domain(ranges)
    query = prop.FairnessQuery(domain=dom, protected=pa, relaxed=ra,
                               relax_eps=eps)
    hidden = [int(rng.integers(2, 7)) for _ in range(int(rng.integers(1, 3)))]
    scale = float(rng.choice([0.3, 1.0, 3.0]))
    net = random_net(rng, (d, *hidden, 1), scale=scale)
    enc = prop.encode(query)
    lo, hi = dom.lo_hi()
    lo, hi = lo.astype(np.int64), hi.astype(np.int64)
    got, ce = lattice_ops.decide_box_exhaustive(
        net, enc, lo, hi, chunk=int(rng.choice([16, 64, 256])))
    want = oracle(net, enc, lo, hi)
    rec = {"seed": seed, "n_ra": n_ra, "eps": eps, "got": got, "want": want}
    if got == "sat":
        ws = [np.asarray(w) for w in net.weights]
        bs = [np.asarray(b) for b in net.biases]
        rec["witness_valid"] = bool(engine.validate_pair(ws, bs, *ce))
    return rec


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trials", type=int, default=150)
    ap.add_argument("--seed0", type=int, default=0)
    ap.add_argument("--out", default=os.path.join(ROOT, "audits",
                                                  "lattice_fuzz_r5.json"))
    args = ap.parse_args()
    import jax

    t0 = time.perf_counter()
    counts = {"sat": 0, "unsat": 0, "unknown": 0}
    ra_counts = {0: 0, 1: 0, 2: 0, 3: 0}
    mismatches, bad_witness = [], []
    for i in range(args.trials):
        if i and i % 10 == 0:
            jax.clear_caches()
        if i and i % 25 == 0:
            print(json.dumps({"progress": i,
                              "mismatches": len(mismatches)}), flush=True)
        rec = one_trial(args.seed0 + i)
        counts[rec["got"]] += 1
        ra_counts[rec["n_ra"]] += 1
        if rec["got"] != "unknown" and rec["got"] != rec["want"]:
            mismatches.append(rec)
        if rec.get("witness_valid") is False:
            bad_witness.append(rec)
    out = {
        "round": 5,
        "component": "ops/lattice.decide_box_exhaustive",
        "oracle": "engine.decide_leaf at every core shared point (exact)",
        "script": "scripts/lattice_fuzz.py",
        "trials": args.trials,
        "agree": args.trials - len(mismatches) - counts["unknown"],
        **counts,
        "trials_by_ra_count": {str(k): v for k, v in ra_counts.items()},
        "mismatches": len(mismatches),
        "invalid_witnesses": len(bad_witness),
        "elapsed_s": round(time.perf_counter() - t0, 1),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fp:
        json.dump(out, fp, indent=1)
    print(json.dumps(out))
    for rec in mismatches + bad_witness:
        print("FAIL " + json.dumps(rec), file=sys.stderr)
    return 1 if (mismatches or bad_witness) else 0


if __name__ == "__main__":
    sys.exit(main())

"""Deterministic keyed PRNG plumbing.

The reference's randomness is global and order-dependent: partition-list
shuffle (``src/GC/Verify-GC.py:73``), per-partition ``np.random.randint``
simulation (``utils/prune.py:216``), and Z3's internal seeds.  For a sharded
sweep to be reproducible regardless of device count or execution order, each
partition derives its own key from (run seed, partition index).
"""
from __future__ import annotations

import jax
import numpy as np


def run_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def partition_key(seed: int, partition_index: int) -> jax.Array:
    return jax.random.fold_in(jax.random.key(seed), partition_index)


def shuffled_order(n: int, seed: int) -> np.ndarray:
    """Deterministic sweep order (replaces the reference's global shuffle)."""
    return np.random.default_rng(seed).permutation(n)

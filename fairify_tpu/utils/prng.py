"""Deterministic keyed PRNG plumbing.

The reference's randomness is global and order-dependent: partition-list
shuffle (``src/GC/Verify-GC.py:73``), per-partition ``np.random.randint``
simulation (``utils/prune.py:216``), and Z3's internal seeds.  For a sharded
sweep to be reproducible regardless of device count or execution order, each
partition derives its own key from (run seed, partition index).
"""
from __future__ import annotations

import jax
import numpy as np


def run_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def grid_keys(seed: int, index_offset: int, n: int) -> jax.Array:
    """Per-partition keys for global indices [offset, offset+n), one call.

    The single key-derivation scheme of the framework: every consumer
    (pruning simulation, parity replay, heuristic-retry replay) regenerates
    identical streams from (seed, global partition index).
    """
    import jax.numpy as jnp

    base = jax.random.key(seed)
    return jax.vmap(lambda i: jax.random.fold_in(base, i))(
        jnp.arange(index_offset, index_offset + n))


def shuffled_order(n: int, seed: int) -> np.ndarray:
    """Deterministic sweep order (replaces the reference's global shuffle)."""
    return np.random.default_rng(seed).permutation(n)

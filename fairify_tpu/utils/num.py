"""Numeric policy: matmul precision for verification kernels.

On TPU the MXU's default matmul path accumulates in bfloat16-multiplied
passes; that is fine for training but not for *verification* arithmetic,
where bounds and counterexample replays must track the reference's float32
numpy semantics (and stay inside the exact-rational certification slack).
Every verification matmul therefore requests ``Precision.HIGHEST``
(6-pass f32 emulation on the MXU).  The matrices involved are tiny
(≤ a few hundred wide), so the cost is irrelevant next to HBM traffic;
training/repair kernels keep the default fast path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

PRECISION = jax.lax.Precision.HIGHEST


def matmul(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.matmul(a, b, precision=PRECISION)

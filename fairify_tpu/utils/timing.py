"""Per-phase wall-clock timing, keeping the reference's CSV timing schema.

The reference records five timing columns per partition — ``SV-time``
(solver), ``S-time`` (sound phase), ``HV-Time`` (heuristic solver),
``H-Time`` (heuristic phase), ``Total-Time`` (``src/GC/Verify-GC.py:272-292``)
— via ad-hoc ``time.time()`` subtraction (``compute_time``,
``utils/verif_utils.py:562-565``).  :class:`PhaseTimer` provides the same
numbers as named phases.
"""
from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict


class PhaseTimer:
    def __init__(self):
        self.t0 = time.perf_counter()
        self.phases: Dict[str, float] = {}

    @contextmanager
    def phase(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] = self.phases.get(name, 0.0) + (time.perf_counter() - start)

    def total(self) -> float:
        return time.perf_counter() - self.t0

    def get(self, name: str) -> float:
        # Raw float: rounding happens only at serialization (e.g.
        # ``ThroughputCounter.dump``) — ``get`` used to round to 2 decimals
        # while ``dump`` rounded to 3, so sums over phases disagreed with
        # the dumped per-phase values.
        return self.phases.get(name, 0.0)

"""Observability: XLA profiler hooks + partition-throughput counters.

The reference's only tracing is wall-clock subtraction per CSV row
(``utils/verif_utils.py:562-565``; SURVEY.md §5.1).  The rebuild keeps that
schema (:mod:`fairify_tpu.utils.timing`) and adds what a TPU deployment
actually needs: optional XLA device traces (viewable in TensorBoard/XProf)
around the hot kernels, and a throughput counter for the north-star metric
(verified partitions/sec/chip, BASELINE.json).
"""
from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field
from typing import Dict, Optional


# Nesting depth of open xla_trace scopes: device annotations below are
# emitted only while a trace is actually being captured, so the annotation
# helpers stay zero-cost (one int check, no jax import) on untraced runs.
_xprof_depth = 0


@contextlib.contextmanager
def xla_trace(trace_dir: Optional[str]):
    """Wrap a region in a jax profiler trace when ``trace_dir`` is set.

    The same switch feeds ``--xprof-dir`` on ``bench``, ``cli run`` and
    ``serve`` (via ``SweepConfig.profile_dir``): while a trace is open,
    :func:`annotation` / :func:`annotate_step` stamp the XLA timeline with
    the obs span names, so the XProf view joins the Perfetto merge story
    on shared names (DESIGN.md §20)."""
    global _xprof_depth
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        _xprof_depth += 1
        try:
            yield
        finally:
            _xprof_depth -= 1


def xprof_active() -> bool:
    """True while an :func:`xla_trace` capture is open."""
    return _xprof_depth > 0


@contextlib.contextmanager
def annotation(name: str):
    """``jax.profiler.TraceAnnotation`` named after an obs span.

    No-op (one int check) unless an :func:`xla_trace` capture is open —
    callers annotate unconditionally and only traced runs pay."""
    if _xprof_depth <= 0:
        yield
        return
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def annotate_step(name: str, step, fn):
    """Run ``fn()`` under a ``StepTraceAnnotation(name, step_num=step)``.

    The step-granular variant of :func:`annotation` for launch-loop bodies
    (one step per segment/chunk submit), callable from inside the launch
    pipeline's submit lambdas.  Same zero-cost-when-untraced contract."""
    if _xprof_depth <= 0:
        return fn()
    import jax

    with jax.profiler.StepTraceAnnotation(name, step_num=int(step)):
        return fn()


# Device-launch accounting.  On the tunnelled single-chip setup every
# kernel launch pays a ~110 ms relay round-trip regardless of batch size
# (audits/device_util_r4.json), so launch COUNT — not FLOPs — is the
# throughput governor; hot call sites bump this so each sweep can regress
# its launch economy (VERDICT r4 #3).  Host-side numpy/LP work is excluded.
#
# The counter lives in the obs metrics registry (``device_launches``) so it
# is resettable per run and lands in trace snapshots; ``bump_launch`` /
# ``launch_count`` stay as thin shims over it for the existing call sites.


def _launch_counter():
    from fairify_tpu.obs import metrics

    return metrics.registry().counter("device_launches")


def bump_launch(n: int = 1) -> None:
    _launch_counter().inc(n)


def launch_count() -> int:
    return int(_launch_counter().total())


def reset_launches() -> None:
    """Zero the process launch counter (per-run hygiene for absolute reads)."""
    _launch_counter().reset()


@dataclass
class ThroughputCounter:
    """Decided-partitions/sec accounting, per phase and per chip."""

    started_at: float = field(default_factory=time.perf_counter)
    decided: int = 0
    stage0_decided: int = 0
    bab_decided: int = 0
    unknown: int = 0
    n_devices: int = 1
    launches: int = 0  # device-launch delta over this sweep (bump_launch)

    def record(self, verdict: str, via_stage0: bool) -> None:
        if verdict in ("sat", "unsat"):
            self.decided += 1
            if via_stage0:
                self.stage0_decided += 1
            else:
                self.bab_decided += 1
        else:
            self.unknown += 1
        # Mirror into the registry so per-run instruments (resettable,
        # trace-snapshot-visible) absorb this counter's role.
        from fairify_tpu.obs import metrics

        metrics.registry().counter("decisions").inc(
            verdict=verdict, via="stage0" if via_stage0 else "bab")

    def summary(self) -> Dict[str, float]:
        elapsed = max(time.perf_counter() - self.started_at, 1e-9)
        pps = self.decided / elapsed
        return {
            "elapsed_s": round(elapsed, 3),
            "decided": self.decided,
            "stage0_decided": self.stage0_decided,
            "bab_decided": self.bab_decided,
            "unknown": self.unknown,
            "partitions_per_sec": round(pps, 4),
            "partitions_per_sec_per_chip": round(pps / max(self.n_devices, 1), 4),
            "device_launches": self.launches,
            # Launch economy per model (lower is better, perfdiff-gated):
            # O(segments) under the stage-0 mega-loop, O(chunks) before it.
            # A ThroughputCounter always covers ONE verify_model run, so
            # the per-model number IS the launch delta; multi-model
            # harnesses (bench.py's AC suite line) divide their own launch
            # delta by their stack width instead of dumping this counter.
            "launches_per_model": self.launches,
        }

    def dump(self, path: str, phases: Optional[Dict[str, float]] = None,
             pipeline: Optional[Dict[str, float]] = None,
             compile: Optional[Dict[str, float]] = None,
             resilience: Optional[Dict[str, float]] = None,
             funnel: Optional[dict] = None) -> None:
        out = self.summary()
        if funnel:
            # Verification-funnel block (obs.funnel, DESIGN.md §20):
            # terminal-state counts summing to the grid size, the decided
            # fraction (ROADMAP item-1's success metric — perfdiff gates it
            # higher-is-better), the fixed-bucket margin/gap histograms and
            # the prune pass's per-layer bound-looseness sums.
            out["decided_fraction"] = round(
                float(funnel.get("decided_fraction", 0.0)), 6)
            out["funnel"] = funnel.get("states", {})
            if funnel.get("margin_hist"):
                out["margin_hist"] = funnel["margin_hist"]
            if funnel.get("looseness") is not None:
                out["looseness"] = [round(float(v), 3)
                                    for v in funnel["looseness"]]
        if resilience and any(resilience.values()):
            # Fault record (resilience/): partitions degraded to UNKNOWN by
            # runtime faults, retries spent, torn resume-ledger lines — all
            # zero on a healthy run, so the key is omitted entirely then.
            out["resilience"] = {k: int(v) for k, v in resilience.items()}
        if phases:
            out["phases_s"] = {k: round(v, 3) for k, v in phases.items()}
        if pipeline:
            # Async-dispatch overlap record (parallel.pipeline): configured
            # depth plus the max / time-weighted-mean launches actually in
            # flight — the evidence the sweep hid its launch round-trips.
            out["pipeline_depth"] = int(pipeline.get("depth", 1))
            out["launches_in_flight_max"] = int(pipeline.get("max", 0))
            out["launches_in_flight_mean"] = float(pipeline.get("mean", 0.0))
        if compile:
            # Per-run XLA compile record (obs.compile.totals_delta): how
            # much of this sweep's wall time was trace/lower/compile, how
            # many compiles happened, and the largest per-executable
            # temp-buffer footprint among kernels compiled DURING this run
            # (the HBM number that bounds chunk sizing; a warm run reports
            # 0 compiles and 0 peak — its executables are attributed to
            # the run that compiled them).
            out["n_compiles"] = int(compile.get("n_compiles", 0))
            out["compile_s"] = round(float(compile.get("compile_s", 0.0)), 3)
            out["peak_temp_bytes"] = int(compile.get("peak_temp_bytes", 0))
        with open(path, "w") as fp:
            json.dump(out, fp, indent=2)

"""Persistent XLA compilation cache.

The sweep's kernels are shape-stable across runs (frontier and attack
batches are padded to fixed sizes), so every compile is reusable.  The
first TPU compile of the CROWN/attack kernels costs tens of seconds
(SURVEY.md §6 budget is 30 minutes *total* per model in the reference);
a persistent cache makes every run after the first pay ~0 compile time.
Disable with ``FAIRIFY_TPU_NO_CACHE=1``.
"""
from __future__ import annotations

import os

_ENABLED = False


def enable_persistent_cache(path: str | None = None) -> str | None:
    global _ENABLED
    if _ENABLED or os.environ.get("FAIRIFY_TPU_NO_CACHE"):
        return None
    import jax

    # Separate caches per platform selection: an axon/TPU-tunnel process may
    # AOT-compile host kernels with different machine features than a plain
    # JAX_PLATFORMS=cpu process, and loading the other's executables risks
    # SIGILL (XLA warns about exactly this).
    platform = os.environ.get("JAX_PLATFORMS") or "default"
    path = path or os.environ.get(
        "FAIRIFY_TPU_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "fairify_tpu",
                     f"xla-{platform}"),
    )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _ENABLED = True
    return path

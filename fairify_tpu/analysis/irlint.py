"""IR passes as ``fairify_tpu.lint`` rules: ``fairify_tpu lint --ir``.

Each pass module exposes ``PASS_ID`` + ``check_kernel(KernelIR) -> [msg]``;
this module wraps the four of them as :class:`fairify_tpu.lint.core.Rule`
plugins so findings ride the existing machinery unchanged — severities,
``# lint: disable=<id>`` inline suppressions (on the kernel's ``def``
line), ``audits/lint_baseline.json`` grandfathering, ``--ratchet``, text
and JSON rendering.  Findings are attributed to the kernel's real source
location (``path:def-line``, function = the wrapped function's name), so
baseline keys look like ``ir-buffers::fairify_tpu/verify/sweep.py::
_parity_grid_from_keys``.

All four rules share ONE :class:`fairify_tpu.analysis.ir.IRContext`
(process-cached): the registry is imported, specced, and lowered exactly
once per run — the passes are different views over the same cached
traversal.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from fairify_tpu.lint.core import Finding, Rule

IR_RULE_IDS = ("ir-host-transfer", "ir-soundness", "ir-recompile",
               "ir-buffers")


class IRRule(Rule):
    """Adapter: run one pass over the shared lowered registry.

    Per-file ``check`` is a no-op — kernels, not files, are the unit —
    and all findings come from ``finalize`` so the engine's suppression
    lookup (which needs the file contexts) applies normally.
    """

    scope = ("fairify_tpu/",)

    def __init__(self, pass_mod, ctx=None):
        self._pass = pass_mod
        self._ctx = ctx
        self.id = pass_mod.PASS_ID
        self.description = (pass_mod.__doc__ or "").strip().splitlines()[0]

    def _context(self):
        if self._ctx is None:
            from fairify_tpu.analysis import ir as ir_mod

            self._ctx = ir_mod.shared_context()
        return self._ctx

    def finalize(self, files: Dict[str, object]) -> Iterable[Finding]:
        ctx = self._context()
        for kir in ctx.kernels:
            for msg in self._pass.check_kernel(kir):
                yield Finding(rule=self.id, path=kir.path, line=kir.line,
                              function=kir.function, message=msg,
                              severity=self.severity)
        if self.id == "ir-recompile":
            # Registered-but-unspecced kernels dodge every pass — the
            # recompile rule owns visibility, so it reports them.
            for kernel in ctx.missing_specs:
                fn = getattr(kernel, "__wrapped__", kernel)
                code = getattr(fn, "__code__", None)
                from fairify_tpu.analysis.ir import _rel

                yield Finding(
                    rule=self.id,
                    path=_rel(code.co_filename) if code else "<unknown>",
                    line=code.co_firstlineno if code else 0,
                    function=getattr(fn, "__name__", kernel.name),
                    message=(
                        f"kernel '{kernel.name}' is registered in obs_jit "
                        f"but has no aval spec in analysis.avals — it is "
                        f"invisible to every IR pass; add a KernelSpec"),
                    severity=self.severity)


def ir_rules(ctx=None) -> List[Rule]:
    """Fresh rule instances for the four IR passes, sharing one context."""
    from fairify_tpu.analysis import (
        passes_buffers,
        passes_host,
        passes_recompile,
        passes_sound,
    )

    mods = (passes_host, passes_sound, passes_recompile, passes_buffers)
    return [IRRule(m, ctx=ctx) for m in mods]


def run_ir_lint(root: Optional[str] = None, baseline=None, ratchet=False,
                ctx=None):
    """One-call IR sweep: ``core.run_lint`` with the IR rule set."""
    from fairify_tpu.lint import core

    return core.run_lint(root=root, rules=ir_rules(ctx=ctx),
                         baseline=baseline, ratchet=ratchet)

"""IR pass ``ir-recompile``: compile-signature ground truth per kernel.

The AST rule ``recompile-hazard`` (PR 6) guesses from source patterns;
this pass asks the executable cache itself.  For every kernel the spec
declares the production call-shape variants, and the pass computes each
variant's REAL ``obs_jit`` cache key (``ObsJit.signature_key`` — the same
``(avals, treedef, statics)`` triple ``__call__`` dispatches on).  Checks:

* **declared-vs-actual executable sharing** — a variant declared
  ``same_exec=True`` (e.g. "a later ragged-but-padded chunk") whose key
  differs from the baseline is a predicted silent recompile, attributed
  to the exact component that diverged (a leaf aval — weak-typed scalar
  vs numpy scalar called out explicitly — or a static value); a variant
  declared ``same_exec=False`` whose key collapses into the baseline is a
  stale bucketing expectation.
* **signature budget** — the distinct-key count over baseline+variants
  must equal the spec's ``expected_signatures`` (the reviewed compile
  budget; ``engine.certify_attack``'s is 2 — PR 3's measured
  stage-0-vs-BaB bucketing).
* **unstable statics** — a float (or float-containing tuple) static
  value creates one executable per distinct value; statics must be
  ints/bools/shape tuples.
* **fallback-invisible kernels** — a kernel that failed the analysis
  lowering never registers a signature: it is invisible to IR analysis
  and to the compile registry's recompile attribution.  When a KernelIR
  carries LIVE process stats (``IRContext(include_stats=True)`` —
  interactive diagnosis, never the lint gate, whose input must be the
  repo alone), compiles served only by the plain-jit fallback
  (``n_compiles == 0``, ``fallbacks > 0``) are reported the same way.
  Registered kernels missing an aval spec are reported by the rule
  adapter.
"""
from __future__ import annotations

from typing import List

from fairify_tpu.analysis.ir import KernelIR

PASS_ID = "ir-recompile"


def _has_float(value) -> bool:
    if isinstance(value, float):
        return True
    if isinstance(value, (tuple, list)):
        return any(_has_float(v) for v in value)
    return False


def _diff_keys(base, other) -> str:
    """Human description of why two cache keys differ."""
    if base is None or other is None:
        return "variant key unavailable"
    b_avals, b_tree, b_statics = base
    o_avals, o_tree, o_statics = other
    if b_statics != o_statics:
        bd, od = dict(b_statics), dict(o_statics)
        names = sorted(k for k in set(bd) | set(od)
                       if bd.get(k) != od.get(k))
        return ("static arg(s) " +
                ", ".join(f"{n}: {bd.get(n)!r} != {od.get(n)!r}"
                          for n in names))
    if b_tree != o_tree:
        return "argument tree structure differs"
    for i, (ba, oa) in enumerate(zip(b_avals, o_avals)):
        if ba != oa:
            b_aval, o_aval = ba[0], oa[0]
            desc = f"leaf #{i}: {b_aval} != {o_aval}"
            if getattr(b_aval, "weak_type", False) != \
                    getattr(o_aval, "weak_type", False):
                desc += (" (weak-typed scalar on one side — a Python "
                         "number and a numpy scalar crossing the jit "
                         "boundary do not share an executable)")
            return desc
    if len(b_avals) != len(o_avals):
        return f"leaf count {len(b_avals)} != {len(o_avals)}"
    return "keys differ (component not attributable)"


def check_kernel(kir: KernelIR) -> List[str]:
    out: List[str] = []
    if kir.lower_error is not None:
        out.append(
            f"kernel '{kir.name}' failed AOT lowering under the analysis "
            f"avals ({kir.lower_error}) — it can only ever compile via "
            f"the plain-jit fallback, invisible to IR analysis and to "
            f"signature registration")
        return out
    st = kir.stats
    if st is not None and getattr(st, "n_compiles", 0) == 0 and \
            getattr(st, "fallbacks", 0) > 0:
        out.append(
            f"kernel '{kir.name}' compiled only via the plain-jit "
            f"fallback in this process ({st.fallbacks} fallback(s), 0 AOT "
            f"compiles) — its signatures were never registered; see "
            f"xla_compile_fallbacks for the attribution")
    for name, value in kir.statics:
        if _has_float(value):
            out.append(
                f"kernel '{kir.name}' static arg '{name}' carries a float "
                f"value ({value!r}) — every distinct value is a fresh "
                f"executable; pass floats as traced scalars")
    for c in kir.consts():
        aval = getattr(c, "aval", None)
        if getattr(aval, "weak_type", False):
            out.append(
                f"kernel '{kir.name}' captures a weak-typed constant — a "
                f"Python scalar closed over at trace time; bind it as an "
                f"explicit argument or a typed constant")
    keys = {repr(kir.signature_key)}
    for desc, (vkey, same_exec) in sorted(kir.variant_keys.items()):
        if vkey is None:
            out.append(
                f"kernel '{kir.name}' variant '{desc}' failed signature "
                f"derivation — its production call shape cannot be keyed")
            continue
        keys.add(repr(vkey))
        if same_exec and vkey != kir.signature_key:
            out.append(
                f"kernel '{kir.name}' variant '{desc}' predicts a SILENT "
                f"RECOMPILE: declared same-executable but the cache key "
                f"diverges — {_diff_keys(kir.signature_key, vkey)}")
        elif not same_exec and vkey == kir.signature_key:
            out.append(
                f"kernel '{kir.name}' variant '{desc}' declared a "
                f"separate compile bucket but keys to the SAME executable "
                f"— stale bucketing expectation in the spec")
    budget = kir.spec.expected_signatures if kir.spec else None
    if budget is not None and len(keys) != budget:
        out.append(
            f"kernel '{kir.name}' compiles {len(keys)} distinct "
            f"signature(s) over its declared production call shapes — "
            f"reviewed budget is {budget}")
    return out

"""Fairness repair: masked gradient repair and two-stage retraining.

Re-implements the reference's two repair pipelines TPU-first with optax:

* **Masked repair** (``src/AC/detect_bias.py:304-437``): freeze everything
  except the localized biased neurons — the reference builds per-layer
  kernel/bias masks (``create_neuron_masks:320-347``) and multiplies
  gradients inside a custom train step (``masked_train_step:350-378``).
  Here the mask lives in the optax chain, the step is one jitted update.
* **Two-stage retraining** (``src/AC/new_model.py:179-263``): stage 1
  fine-tunes on original data; stage 2 trains on counterexample batches at
  low LR with an accuracy floor (0.80) early stop.

Training math runs in f32 (these are 6-30-feature MLPs; bf16 would add
noise with no MXU payoff at this size), one jitted step per epoch loop.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fairify_tpu.models.mlp import MLP, forward


def bce_loss(net: MLP, x: jax.Array, y: jax.Array) -> jax.Array:
    """Binary cross-entropy on logits (the reference trains sigmoid+BCE)."""
    logits = forward(net, x)
    return optax.sigmoid_binary_cross_entropy(logits, y.astype(jnp.float32)).mean()


def neuron_gradient_masks(net: MLP, targets: Sequence[Tuple[int, int]]) -> MLP:
    """Masks selecting only the (layer, neuron) targets' incoming weights.

    Mirrors ``create_neuron_masks`` (``src/AC/detect_bias.py:320-347``): for a
    target neuron j of layer l, unfreeze column j of ``weights[l]`` and
    ``biases[l][j]``; everything else gets gradient 0.
    """
    wmasks = [np.zeros_like(np.asarray(w)) for w in net.weights]
    bmasks = [np.zeros_like(np.asarray(b)) for b in net.biases]
    for l, j in targets:
        wmasks[l][:, j] = 1.0
        bmasks[l][j] = 1.0
    return MLP(
        tuple(jnp.asarray(m) for m in wmasks),
        tuple(jnp.asarray(m) for m in bmasks),
        net.masks,
    )


@dataclass
class RepairResult:
    net: MLP
    history: List[dict]


def _fit(net: MLP, X, y, optimizer, epochs: int, batch_size: int, seed: int,
         grad_mask: MLP | None = None, trainable=None):
    X = jnp.asarray(np.asarray(X), jnp.float32)
    y = jnp.asarray(np.asarray(y), jnp.float32)
    params = (net.weights, net.biases)
    opt_state = optimizer.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            return bce_loss(MLP(p[0], p[1], net.masks), xb, yb)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        if grad_mask is not None:
            grads = (
                tuple(g * m for g, m in zip(grads[0], grad_mask.weights)),
                tuple(g * m for g, m in zip(grads[1], grad_mask.biases)),
            )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    rng = np.random.default_rng(seed)
    n = X.shape[0]
    history = []
    for epoch in range(epochs):
        order = rng.permutation(n)
        losses = []
        for s in range(0, n, batch_size):
            idx = order[s : s + batch_size]
            params, opt_state, loss = step(params, opt_state, X[idx], y[idx])
            losses.append(float(loss))
        history.append({"epoch": epoch, "loss": float(np.mean(losses))})
    return MLP(params[0], params[1], net.masks), history


def masked_repair(
    net: MLP,
    targets: Sequence[Tuple[int, int]],
    X, y,
    epochs: int = 5,
    lr: float = 1e-3,
    batch_size: int = 32,
    seed: int = 0,
) -> RepairResult:
    """Gradient-masked fine-tune updating only the biased neurons
    (``masked_train_step``, ``src/AC/detect_bias.py:350-405``)."""
    mask = neuron_gradient_masks(net, targets)
    repaired, history = _fit(
        net, X, y, optax.adam(lr), epochs, batch_size, seed, grad_mask=mask
    )
    return RepairResult(repaired, history)


def counterexample_retrain(
    net: MLP,
    X, y,
    ce_pairs: Sequence[Tuple[np.ndarray, np.ndarray]],
    X_val, y_val,
    stage1_epochs: int = 3,
    stage2_epochs: int = 10,
    stage1_lr: float = 1e-3,
    stage2_lr: float = 1e-4,
    accuracy_floor: float = 0.80,
    batch_size: int = 32,
    seed: int = 0,
) -> RepairResult:
    """Two-stage fairness retraining (``src/AC/new_model.py:179-263``).

    Counterexample pairs get the *same* target label (the original model's
    majority prediction for the pair), teaching the net to treat them alike;
    stage 2 stops early if validation accuracy drops below the floor.
    """
    stage1, hist1 = _fit(net, X, y, optax.adam(stage1_lr), stage1_epochs, batch_size, seed)

    # Build the counterexample batch: both points, shared label from the
    # current model's prediction on x (conservative same-label relabeling,
    # ``detect_bias.py:412-433`` / ``new_model.py:192-241``).
    if ce_pairs:
        xs = np.stack([p[0] for p in ce_pairs]).astype(np.float32)
        xps = np.stack([p[1] for p in ce_pairs]).astype(np.float32)
        labels = np.asarray(forward(stage1, jnp.asarray(xs)) > 0.0).astype(np.float32)
        ce_X = np.concatenate([xs, xps], axis=0)
        ce_y = np.concatenate([labels, labels], axis=0)
    else:
        ce_X = np.zeros((0, net.in_dim), np.float32)
        ce_y = np.zeros((0,), np.float32)

    current = stage1
    history = list(hist1)
    Xv = jnp.asarray(np.asarray(X_val), jnp.float32)
    for epoch in range(stage2_epochs):
        if ce_X.shape[0] == 0:
            break
        current, h = _fit(
            current, ce_X, ce_y, optax.adam(stage2_lr), 1, batch_size, seed + 1 + epoch
        )
        acc = float(
            (np.asarray(forward(current, Xv) > 0.0).astype(int) == np.asarray(y_val)).mean()
        )
        history.append({"epoch": f"stage2-{epoch}", "loss": h[0]["loss"], "val_acc": acc})
        if acc < accuracy_floor:  # accuracy floor early stop, new_model.py:233-241
            break
    return RepairResult(current, history)
